// Native host BLS12-381: the fast CPU crypto path of harmony-tpu.
//
// Role: the reference's L0 is herumi's hand-tuned C++ mcl library
// (reference: go.mod:27, Makefile:68-70) — every FBFT vote and block
// replay burns pairings inside it.  This library is the analogous
// native host path for THIS framework: the Python bigint twin
// (harmony_tpu/ref/) stays the auditable ground truth, the TPU ops
// (harmony_tpu/ops/) are the device path, and this file makes the host
// fallback fast enough to carry a live chain (ms-scale pairings vs the
// twin's ~240 ms).
//
// Conventions are EXACTLY the twin's, so GT elements, sqrt choices and
// hash-to-curve outputs are bitwise interchangeable:
//   Fp2  = Fp [u]/(u^2+1),  Fp6 = Fp2[v]/(v^3 - xi), xi = u+1,
//   Fp12 = Fp6[w]/(w^2 - v)
//   Miller loop: twist-Jacobian, sparse lines in {v^2, w, w v}
//     (ref/pairing.py::miller_loop_projective)
//   Final exp: CUBE of the reduced pairing via the x-addition chain
//     3λ = (x-1)^2 (x+p)(x^2+p^2-1) + 3  (ops/pairing.py chain)
//
// Arithmetic: 6x64-bit limbs, Montgomery form (R = 2^384), CIOS
// multiplication on unsigned __int128.  No assembly, no third-party
// code; every constant is derived at init from the prime and the BLS
// parameter x = -0xd201000000010000.
//
// ABI: flat byte buffers, big-endian 48-byte field elements.
//   G1 point: x||y (96 B) + explicit infinity flag.
//   G2 point: x.c0||x.c1||y.c0||y.c1 (192 B) + infinity flag.
//   GT:       12 x 48 B in ref-tuple order (c0.c0.c0, c0.c0.c1, ...).

#include <cstdint>
#include <cstring>

typedef unsigned __int128 u128;
typedef uint64_t u64;

// ---------------------------------------------------------------------------
// Fp: 6x64 Montgomery
// ---------------------------------------------------------------------------

static const u64 P_LIMBS[6] = {
    0xb9feffffffffaaabULL, 0x1eabfffeb153ffffULL, 0x6730d2a0f6b0f624ULL,
    0x64774b84f38512bfULL, 0x4b1ba7b6434bacd7ULL, 0x1a0111ea397fe69aULL,
};

static u64 NP;            // -p^-1 mod 2^64
static u64 R2_LIMBS[6];   // R^2 mod p (canonical limbs)
static u64 PM2[6];        // p - 2   (inversion exponent)
static u64 PP14[6];       // (p+1)/4 (sqrt exponent)
static u64 PM12[6];       // (p-1)/2 (is_neg threshold, canonical)

struct Fp {
    u64 v[6];
};

static inline bool fp_is_zero(const Fp &a) {
    u64 acc = 0;
    for (int i = 0; i < 6; i++) acc |= a.v[i];
    return acc == 0;
}

static inline bool fp_eq(const Fp &a, const Fp &b) {
    u64 acc = 0;
    for (int i = 0; i < 6; i++) acc |= a.v[i] ^ b.v[i];
    return acc == 0;
}

// canonical (non-Montgomery) limb compare: a >= b
static inline bool limbs_ge(const u64 *a, const u64 *b) {
    for (int i = 5; i >= 0; i--) {
        if (a[i] > b[i]) return true;
        if (a[i] < b[i]) return false;
    }
    return true;  // equal
}

static inline u64 limbs_sub(u64 *r, const u64 *a, const u64 *b) {
    u64 borrow = 0;
    for (int i = 0; i < 6; i++) {
        u128 d = (u128)a[i] - b[i] - borrow;
        r[i] = (u64)d;
        borrow = (u64)(d >> 64) & 1;
    }
    return borrow;
}

static inline u64 limbs_add(u64 *r, const u64 *a, const u64 *b) {
    u64 carry = 0;
    for (int i = 0; i < 6; i++) {
        u128 s = (u128)a[i] + b[i] + carry;
        r[i] = (u64)s;
        carry = (u64)(s >> 64);
    }
    return carry;
}

// branchless select: out = cond ? t : r  (cond in {0,1})
static inline void limbs_select(u64 *out, u64 cond, const u64 *t,
                                const u64 *r) {
    u64 mask = (u64)0 - cond;
    for (int i = 0; i < 6; i++) out[i] = (t[i] & mask) | (r[i] & ~mask);
}

static inline Fp fp_add(const Fp &a, const Fp &b) {
    Fp r;
    u64 carry = limbs_add(r.v, a.v, b.v);
    if (carry || limbs_ge(r.v, P_LIMBS)) limbs_sub(r.v, r.v, P_LIMBS);
    return r;
}

static inline Fp fp_sub(const Fp &a, const Fp &b) {
    Fp r;
    u64 borrow = limbs_sub(r.v, a.v, b.v);
    if (borrow) limbs_add(r.v, r.v, P_LIMBS);  // wraps mod 2^384: correct
    return r;
}

static inline Fp fp_neg(const Fp &a) {
    Fp r;
    if (fp_is_zero(a)) { memset(r.v, 0, sizeof r.v); return r; }
    limbs_sub(r.v, P_LIMBS, a.v);
    return r;
}

static inline Fp fp_dbl(const Fp &a) { return fp_add(a, a); }

// CIOS Montgomery multiplication: returns a*b*R^-1 mod p.
static Fp fp_mul(const Fp &a, const Fp &b) {
    u64 t[8];
    memset(t, 0, sizeof t);
    for (int i = 0; i < 6; i++) {
        u64 c = 0;
        for (int j = 0; j < 6; j++) {
            u128 s = (u128)a.v[i] * b.v[j] + t[j] + c;
            t[j] = (u64)s;
            c = (u64)(s >> 64);
        }
        u128 s = (u128)t[6] + c;
        t[6] = (u64)s;
        t[7] = (u64)(s >> 64);
        u64 m = t[0] * NP;
        s = (u128)m * P_LIMBS[0] + t[0];
        c = (u64)(s >> 64);
        for (int j = 1; j < 6; j++) {
            s = (u128)m * P_LIMBS[j] + t[j] + c;
            t[j - 1] = (u64)s;
            c = (u64)(s >> 64);
        }
        s = (u128)t[6] + c;
        t[5] = (u64)s;
        t[6] = t[7] + (u64)(s >> 64);
        t[7] = 0;
    }
    // result value = t[6]*2^384 + t[0..5] < 2p: at most one subtract
    Fp r;
    memcpy(r.v, t, sizeof r.v);
    if (t[6] || limbs_ge(r.v, P_LIMBS)) limbs_sub(r.v, r.v, P_LIMBS);
    return r;
}

static inline Fp fp_sqr(const Fp &a) { return fp_mul(a, a); }

static Fp FP_ZERO, FP_ONE, FP_R2, FP_INV2;  // ONE/INV2 in Montgomery form

// bytes (48, big-endian, canonical) <-> Montgomery limbs
static Fp fp_from_bytes(const uint8_t *b) {
    Fp r;
    for (int i = 0; i < 6; i++) {
        u64 x = 0;
        for (int j = 0; j < 8; j++) x = (x << 8) | b[(5 - i) * 8 + j];
        r.v[i] = x;
    }
    if (limbs_ge(r.v, P_LIMBS)) limbs_sub(r.v, r.v, P_LIMBS);
    return fp_mul(r, FP_R2);  // to Montgomery
}

static void fp_to_bytes(const Fp &a, uint8_t *out) {
    Fp one;
    memset(one.v, 0, sizeof one.v);
    one.v[0] = 1;
    Fp c = fp_mul(a, one);  // out of Montgomery
    for (int i = 0; i < 6; i++) {
        u64 x = c.v[5 - i];
        for (int j = 0; j < 8; j++) out[i * 8 + j] = (uint8_t)(x >> (56 - 8 * j));
    }
}

// generic pow by a canonical limb exponent (MSB-first scan)
static Fp fp_pow_limbs(const Fp &base, const u64 *e, int n) {
    Fp acc = FP_ONE;
    bool started = false;
    for (int i = n - 1; i >= 0; i--) {
        for (int b = 63; b >= 0; b--) {
            if (started) acc = fp_sqr(acc);
            if ((e[i] >> b) & 1) {
                if (!started) { acc = base; started = true; }
                else acc = fp_mul(acc, base);
            }
        }
    }
    return started ? acc : FP_ONE;
}

static inline Fp fp_inv(const Fp &a) { return fp_pow_limbs(a, PM2, 6); }

// principal sqrt a^((p+1)/4); ok=false if a is a non-residue.
static Fp fp_sqrt(const Fp &a, bool &ok) {
    Fp c = fp_pow_limbs(a, PP14, 6);
    ok = fp_eq(fp_sqr(c), a);
    return c;
}

// lexicographic 'sign' on the canonical value: a > (p-1)/2
static bool fp_is_neg(const Fp &a) {
    Fp one;
    memset(one.v, 0, sizeof one.v);
    one.v[0] = 1;
    Fp c = fp_mul(a, one);
    if (fp_is_zero(c)) return false;
    u64 t[6];
    // c > (p-1)/2  <=>  c >= (p-1)/2 + 1
    memcpy(t, PM12, sizeof t);
    u64 carry = 1;
    for (int i = 0; i < 6 && carry; i++) {
        u128 s = (u128)t[i] + carry;
        t[i] = (u64)s;
        carry = (u64)(s >> 64);
    }
    return limbs_ge(c.v, t);
}

// canonical compare for deterministic root choices: a < b (canonical ints)
static bool fp_canon_lt(const Fp &a, const Fp &b) {
    Fp one;
    memset(one.v, 0, sizeof one.v);
    one.v[0] = 1;
    Fp ca = fp_mul(a, one), cb = fp_mul(b, one);
    for (int i = 5; i >= 0; i--) {
        if (ca.v[i] < cb.v[i]) return true;
        if (ca.v[i] > cb.v[i]) return false;
    }
    return false;
}

// ---------------------------------------------------------------------------
// Fp2 = Fp[u]/(u^2+1)
// ---------------------------------------------------------------------------

struct Fp2 {
    Fp c0, c1;
};

static inline Fp2 fp2_add(const Fp2 &a, const Fp2 &b) {
    return {fp_add(a.c0, b.c0), fp_add(a.c1, b.c1)};
}
static inline Fp2 fp2_sub(const Fp2 &a, const Fp2 &b) {
    return {fp_sub(a.c0, b.c0), fp_sub(a.c1, b.c1)};
}
static inline Fp2 fp2_neg(const Fp2 &a) { return {fp_neg(a.c0), fp_neg(a.c1)}; }
static inline Fp2 fp2_dbl(const Fp2 &a) { return {fp_dbl(a.c0), fp_dbl(a.c1)}; }
static inline bool fp2_is_zero(const Fp2 &a) {
    return fp_is_zero(a.c0) && fp_is_zero(a.c1);
}
static inline bool fp2_eq(const Fp2 &a, const Fp2 &b) {
    return fp_eq(a.c0, b.c0) && fp_eq(a.c1, b.c1);
}

// Karatsuba: 3 Fp muls
static inline Fp2 fp2_mul(const Fp2 &a, const Fp2 &b) {
    Fp v0 = fp_mul(a.c0, b.c0);
    Fp v1 = fp_mul(a.c1, b.c1);
    Fp cross = fp_mul(fp_add(a.c0, a.c1), fp_add(b.c0, b.c1));
    return {fp_sub(v0, v1), fp_sub(cross, fp_add(v0, v1))};
}

// complex squaring: 2 Fp muls
static inline Fp2 fp2_sqr(const Fp2 &a) {
    Fp t0 = fp_mul(fp_add(a.c0, a.c1), fp_sub(a.c0, a.c1));
    Fp t1 = fp_mul(a.c0, a.c1);
    return {t0, fp_dbl(t1)};
}

static inline Fp2 fp2_scale(const Fp2 &a, const Fp &s) {
    return {fp_mul(a.c0, s), fp_mul(a.c1, s)};
}

static inline Fp2 fp2_conj(const Fp2 &a) { return {a.c0, fp_neg(a.c1)}; }

// xi = u + 1: (a0 - a1) + (a0 + a1) u
static inline Fp2 fp2_mul_xi(const Fp2 &a) {
    return {fp_sub(a.c0, a.c1), fp_add(a.c0, a.c1)};
}

static inline Fp2 fp2_inv(const Fp2 &a) {
    Fp norm = fp_add(fp_sqr(a.c0), fp_sqr(a.c1));
    Fp ninv = fp_inv(norm);
    return {fp_mul(a.c0, ninv), fp_neg(fp_mul(a.c1, ninv))};
}

static Fp2 FP2_ZERO_C, FP2_ONE_C;

// generic Fp2 pow by canonical limb exponent (for Frobenius gammas at init)
static Fp2 fp2_pow_limbs(const Fp2 &base, const u64 *e, int n) {
    Fp2 acc = FP2_ONE_C;
    bool started = false;
    for (int i = n - 1; i >= 0; i--) {
        for (int b = 63; b >= 0; b--) {
            if (started) acc = fp2_sqr(acc);
            if ((e[i] >> b) & 1) {
                if (!started) { acc = base; started = true; }
                else acc = fp2_mul(acc, base);
            }
        }
    }
    return started ? acc : FP2_ONE_C;
}

// Deterministic sqrt mirroring ref/fields.py::fp2_sqrt exactly (same
// branch structure, same principal-root convention), so decompress and
// hash-to-curve agree with the bigint twin bit for bit.
static bool fp2_sqrt(const Fp2 &a, Fp2 &out) {
    bool ok;
    if (fp_is_zero(a.c1)) {
        Fp s = fp_sqrt(a.c0, ok);
        if (ok) { out = {s, FP_ZERO}; return true; }
        s = fp_sqrt(fp_neg(a.c0), ok);
        if (!ok) return false;
        out = {FP_ZERO, s};
        return true;
    }
    Fp alpha = fp_sqrt(fp_add(fp_sqr(a.c0), fp_sqr(a.c1)), ok);
    if (!ok) return false;
    Fp delta = fp_mul(fp_add(a.c0, alpha), FP_INV2);
    Fp x0 = fp_sqrt(delta, ok);
    if (!ok) {
        delta = fp_mul(fp_sub(a.c0, alpha), FP_INV2);
        x0 = fp_sqrt(delta, ok);
        if (!ok) return false;
    }
    Fp x1 = fp_mul(a.c1, fp_inv(fp_dbl(x0)));
    Fp2 cand = {x0, x1};
    if (!fp2_eq(fp2_sqr(cand), a)) return false;
    out = cand;
    return true;
}

// lexicographic sign of Fp2: compare (c1, c0) — serialize.py convention
static bool fp2_is_neg(const Fp2 &a) {
    if (!fp_is_zero(a.c1)) return fp_is_neg(a.c1);
    return fp_is_neg(a.c0);
}

// (y1, y0) > (n1, n0) canonical lexicographic — hash_to_curve choice
static bool fp2_lex_gt(const Fp2 &y, const Fp2 &n) {
    if (!fp_eq(y.c1, n.c1)) return fp_canon_lt(n.c1, y.c1);
    if (!fp_eq(y.c0, n.c0)) return fp_canon_lt(n.c0, y.c0);
    return false;
}

// ---------------------------------------------------------------------------
// Fp6 = Fp2[v]/(v^3 - xi), Fp12 = Fp6[w]/(w^2 - v)
// ---------------------------------------------------------------------------

struct Fp6 {
    Fp2 c0, c1, c2;
};
struct Fp12 {
    Fp6 c0, c1;
};

static inline Fp6 fp6_add(const Fp6 &a, const Fp6 &b) {
    return {fp2_add(a.c0, b.c0), fp2_add(a.c1, b.c1), fp2_add(a.c2, b.c2)};
}
static inline Fp6 fp6_sub(const Fp6 &a, const Fp6 &b) {
    return {fp2_sub(a.c0, b.c0), fp2_sub(a.c1, b.c1), fp2_sub(a.c2, b.c2)};
}
static inline Fp6 fp6_neg(const Fp6 &a) {
    return {fp2_neg(a.c0), fp2_neg(a.c1), fp2_neg(a.c2)};
}

// Karatsuba-3: 6 Fp2 muls (same formulas as ops/towers.py::fp6_mul)
static Fp6 fp6_mul(const Fp6 &a, const Fp6 &b) {
    Fp2 v0 = fp2_mul(a.c0, b.c0);
    Fp2 v1 = fp2_mul(a.c1, b.c1);
    Fp2 v2 = fp2_mul(a.c2, b.c2);
    Fp2 v12 = fp2_mul(fp2_add(a.c1, a.c2), fp2_add(b.c1, b.c2));
    Fp2 v01 = fp2_mul(fp2_add(a.c0, a.c1), fp2_add(b.c0, b.c1));
    Fp2 v02 = fp2_mul(fp2_add(a.c0, a.c2), fp2_add(b.c0, b.c2));
    Fp6 r;
    r.c0 = fp2_add(v0, fp2_mul_xi(fp2_sub(v12, fp2_add(v1, v2))));
    r.c1 = fp2_add(fp2_sub(v01, fp2_add(v0, v1)), fp2_mul_xi(v2));
    r.c2 = fp2_add(fp2_sub(v02, fp2_add(v0, v2)), v1);
    return r;
}

// multiply by v: (c0, c1, c2) -> (xi c2, c0, c1)
static inline Fp6 fp6_mul_v(const Fp6 &a) {
    return {fp2_mul_xi(a.c2), a.c0, a.c1};
}

static Fp6 fp6_inv(const Fp6 &a) {
    Fp2 t0 = fp2_sub(fp2_sqr(a.c0), fp2_mul_xi(fp2_mul(a.c1, a.c2)));
    Fp2 t1 = fp2_sub(fp2_mul_xi(fp2_sqr(a.c2)), fp2_mul(a.c0, a.c1));
    Fp2 t2 = fp2_sub(fp2_sqr(a.c1), fp2_mul(a.c0, a.c2));
    Fp2 norm = fp2_add(
        fp2_mul(a.c0, t0),
        fp2_add(fp2_mul_xi(fp2_mul(a.c2, t1)), fp2_mul_xi(fp2_mul(a.c1, t2))));
    Fp2 ninv = fp2_inv(norm);
    return {fp2_mul(t0, ninv), fp2_mul(t1, ninv), fp2_mul(t2, ninv)};
}

static Fp6 FP6_ZERO_C, FP6_ONE_C;
static Fp12 FP12_ONE_C;

static inline Fp12 fp12_mul(const Fp12 &a, const Fp12 &b) {
    Fp6 v0 = fp6_mul(a.c0, b.c0);
    Fp6 v1 = fp6_mul(a.c1, b.c1);
    Fp6 cross = fp6_mul(fp6_add(a.c0, a.c1), fp6_add(b.c0, b.c1));
    Fp12 r;
    r.c0 = fp6_add(v0, fp6_mul_v(v1));
    r.c1 = fp6_sub(cross, fp6_add(v0, v1));
    return r;
}

// complex-method squaring: 2 Fp6 products
static inline Fp12 fp12_sqr(const Fp12 &a) {
    Fp6 v0 = fp6_mul(a.c0, a.c1);
    Fp6 cross = fp6_mul(fp6_add(a.c0, a.c1), fp6_add(a.c0, fp6_mul_v(a.c1)));
    Fp12 r;
    r.c0 = fp6_sub(fp6_sub(cross, v0), fp6_mul_v(v0));
    r.c1 = fp6_add(v0, v0);
    return r;
}

static inline Fp12 fp12_conj(const Fp12 &a) { return {a.c0, fp6_neg(a.c1)}; }

static Fp12 fp12_inv(const Fp12 &a) {
    Fp6 norm = fp6_sub(fp6_mul(a.c0, a.c0), fp6_mul_v(fp6_mul(a.c1, a.c1)));
    Fp6 ninv = fp6_inv(norm);
    return {fp6_mul(a.c0, ninv), fp6_neg(fp6_mul(a.c1, ninv))};
}

static bool fp12_eq(const Fp12 &a, const Fp12 &b) {
    return fp2_eq(a.c0.c0, b.c0.c0) && fp2_eq(a.c0.c1, b.c0.c1) &&
           fp2_eq(a.c0.c2, b.c0.c2) && fp2_eq(a.c1.c0, b.c1.c0) &&
           fp2_eq(a.c1.c1, b.c1.c1) && fp2_eq(a.c1.c2, b.c1.c2);
}

// Granger-Scott squaring for unitary elements (ops/towers.py
// ::fp12_cyclo_sqr formulas; valid after the easy part only).
static Fp12 fp12_cyclo_sqr(const Fp12 &a) {
    const Fp2 &c0 = a.c0.c0, &c1 = a.c0.c1, &c2 = a.c0.c2;
    const Fp2 &c3 = a.c1.c0, &c4 = a.c1.c1, &c5 = a.c1.c2;
    Fp2 s_c4 = fp2_sqr(c4), s_c0 = fp2_sqr(c0), s_40 = fp2_sqr(fp2_add(c4, c0));
    Fp2 s_c3 = fp2_sqr(c3), s_c2 = fp2_sqr(c2), s_32 = fp2_sqr(fp2_add(c3, c2));
    Fp2 s_c5 = fp2_sqr(c5), s_c1 = fp2_sqr(c1), s_51 = fp2_sqr(fp2_add(c5, c1));
    Fp2 t6 = fp2_sub(s_40, fp2_add(s_c4, s_c0));              // 2 c0 c4
    Fp2 t7 = fp2_sub(s_32, fp2_add(s_c3, s_c2));              // 2 c2 c3
    Fp2 t8 = fp2_mul_xi(fp2_sub(s_51, fp2_add(s_c5, s_c1)));  // 2 xi c1 c5
    Fp2 t0 = fp2_add(fp2_mul_xi(s_c4), s_c0);
    Fp2 t2 = fp2_add(fp2_mul_xi(s_c2), s_c3);
    Fp2 t4 = fp2_add(fp2_mul_xi(s_c5), s_c1);
    Fp12 r;
    r.c0.c0 = fp2_add(fp2_add(fp2_sub(t0, c0), fp2_sub(t0, c0)), t0);
    r.c0.c1 = fp2_add(fp2_add(fp2_sub(t2, c1), fp2_sub(t2, c1)), t2);
    r.c0.c2 = fp2_add(fp2_add(fp2_sub(t4, c2), fp2_sub(t4, c2)), t4);
    r.c1.c0 = fp2_add(fp2_add(fp2_add(t8, c3), fp2_add(t8, c3)), t8);
    r.c1.c1 = fp2_add(fp2_add(fp2_add(t6, c4), fp2_add(t6, c4)), t6);
    r.c1.c2 = fp2_add(fp2_add(fp2_add(t7, c5), fp2_add(t7, c5)), t7);
    return r;
}

// Frobenius: gamma_k[m] = xi^(m (p^k - 1)/6); coefficient of w^i v^j is
// multiplied by gamma_k[i + 2 j] after k-fold Fp2 conjugation.
static Fp2 GAMMA1[6], GAMMA2[6];

static Fp12 fp12_frobenius(const Fp12 &a, int k) {
    const Fp2 *g = (k == 1) ? GAMMA1 : GAMMA2;
    Fp12 r;
    Fp2 t[6] = {a.c0.c0, a.c0.c1, a.c0.c2, a.c1.c0, a.c1.c1, a.c1.c2};
    if (k & 1)
        for (int i = 0; i < 6; i++) t[i] = fp2_conj(t[i]);
    // (i_w, j_v): c0 part i=0 j=0,1,2 -> m=0,2,4 ; c1 part i=1 -> m=1,3,5
    r.c0.c0 = fp2_mul(t[0], g[0]);
    r.c0.c1 = fp2_mul(t[1], g[2]);
    r.c0.c2 = fp2_mul(t[2], g[4]);
    r.c1.c0 = fp2_mul(t[3], g[1]);
    r.c1.c1 = fp2_mul(t[4], g[3]);
    r.c1.c2 = fp2_mul(t[5], g[5]);
    return r;
}

// ---------------------------------------------------------------------------
// Curve: Jacobian points over a generic field (G1: Fp, G2: Fp2)
// ---------------------------------------------------------------------------

template <class F> struct FieldOps;  // trait

template <> struct FieldOps<Fp> {
    static Fp add(const Fp &a, const Fp &b) { return fp_add(a, b); }
    static Fp sub(const Fp &a, const Fp &b) { return fp_sub(a, b); }
    static Fp mul(const Fp &a, const Fp &b) { return fp_mul(a, b); }
    static Fp sqr(const Fp &a) { return fp_sqr(a); }
    static Fp neg(const Fp &a) { return fp_neg(a); }
    static Fp inv(const Fp &a) { return fp_inv(a); }
    static bool is_zero(const Fp &a) { return fp_is_zero(a); }
    static bool eq(const Fp &a, const Fp &b) { return fp_eq(a, b); }
    static Fp zero() { return FP_ZERO; }
    static Fp one() { return FP_ONE; }
};

template <> struct FieldOps<Fp2> {
    static Fp2 add(const Fp2 &a, const Fp2 &b) { return fp2_add(a, b); }
    static Fp2 sub(const Fp2 &a, const Fp2 &b) { return fp2_sub(a, b); }
    static Fp2 mul(const Fp2 &a, const Fp2 &b) { return fp2_mul(a, b); }
    static Fp2 sqr(const Fp2 &a) { return fp2_sqr(a); }
    static Fp2 neg(const Fp2 &a) { return fp2_neg(a); }
    static Fp2 inv(const Fp2 &a) { return fp2_inv(a); }
    static bool is_zero(const Fp2 &a) { return fp2_is_zero(a); }
    static bool eq(const Fp2 &a, const Fp2 &b) { return fp2_eq(a, b); }
    static Fp2 zero() { return FP2_ZERO_C; }
    static Fp2 one() { return FP2_ONE_C; }
};

template <class F> struct Jac {
    F X, Y, Z;
    bool inf() const { return FieldOps<F>::is_zero(Z); }
};

template <class F> static Jac<F> jac_infinity() {
    return {FieldOps<F>::zero(), FieldOps<F>::one(), FieldOps<F>::zero()};
}

// dbl-2009-l (a = 0); no 2-torsion on either curve so Y != 0 for finite pts.
template <class F> static Jac<F> jac_dbl(const Jac<F> &p) {
    typedef FieldOps<F> O;
    if (p.inf()) return p;
    F A = O::sqr(p.X);
    F B = O::sqr(p.Y);
    F C = O::sqr(B);
    F t = O::sqr(O::add(p.X, B));
    F D = O::add(O::sub(O::sub(t, A), C), O::sub(O::sub(t, A), C));
    F E = O::add(O::add(A, A), A);
    F Fv = O::sqr(E);
    Jac<F> r;
    r.X = O::sub(Fv, O::add(D, D));
    F C8 = O::add(O::add(O::add(C, C), O::add(C, C)),
                  O::add(O::add(C, C), O::add(C, C)));
    r.Y = O::sub(O::mul(E, O::sub(D, r.X)), C8);
    r.Z = O::add(O::mul(p.Y, p.Z), O::mul(p.Y, p.Z));
    return r;
}

// add-2007-bl with full edge handling
template <class F> static Jac<F> jac_add(const Jac<F> &p, const Jac<F> &q) {
    typedef FieldOps<F> O;
    if (p.inf()) return q;
    if (q.inf()) return p;
    F Z1Z1 = O::sqr(p.Z);
    F Z2Z2 = O::sqr(q.Z);
    F U1 = O::mul(p.X, Z2Z2);
    F U2 = O::mul(q.X, Z1Z1);
    F S1 = O::mul(O::mul(p.Y, q.Z), Z2Z2);
    F S2 = O::mul(O::mul(q.Y, p.Z), Z1Z1);
    F H = O::sub(U2, U1);
    F rr = O::sub(S2, S1);
    if (O::is_zero(H)) {
        if (O::is_zero(rr)) return jac_dbl(p);
        return jac_infinity<F>();
    }
    rr = O::add(rr, rr);
    F I = O::sqr(O::add(H, H));
    F J = O::mul(H, I);
    F V = O::mul(U1, I);
    Jac<F> r;
    r.X = O::sub(O::sub(O::sqr(rr), J), O::add(V, V));
    F SJ = O::mul(S1, J);
    r.Y = O::sub(O::mul(rr, O::sub(V, r.X)), O::add(SJ, SJ));
    F ZZ = O::sub(O::sub(O::sqr(O::add(p.Z, q.Z)), Z1Z1), Z2Z2);
    r.Z = O::mul(ZZ, H);
    return r;
}

template <class F>
static void jac_to_affine(const Jac<F> &p, F &x, F &y, bool &is_inf) {
    typedef FieldOps<F> O;
    if (p.inf()) { is_inf = true; return; }
    is_inf = false;
    F zi = O::inv(p.Z);
    F zi2 = O::sqr(zi);
    x = O::mul(p.X, zi2);
    y = O::mul(O::mul(p.Y, zi2), zi);
}

// double-and-add, MSB-first over an arbitrary-length big-endian scalar
// (scalars are NOT reduced — cofactor clearing passes huge ones;
// mirrors ref/curve.py::CurveOps.mul).
template <class F>
static Jac<F> jac_mul(const F &ax, const F &ay, bool a_inf, const uint8_t *sc,
                      int sclen) {
    Jac<F> acc = jac_infinity<F>();
    if (a_inf) return acc;
    Jac<F> base = {ax, ay, FieldOps<F>::one()};
    bool started = false;
    for (int i = 0; i < sclen; i++) {
        for (int b = 7; b >= 0; b--) {
            if (started) acc = jac_dbl(acc);
            if ((sc[i] >> b) & 1) {
                if (!started) { acc = base; started = true; }
                else acc = jac_add(acc, base);
            }
        }
    }
    return acc;
}

// ---------------------------------------------------------------------------
// Pairing: twist-Jacobian Miller loop + x-chain final exponentiation
// (same algorithm as ref/pairing.py::miller_loop_projective and
// ops/pairing.py::final_exponentiation — identical GT outputs)
// ---------------------------------------------------------------------------

static const u64 ABS_X = 0xd201000000010000ULL;  // |x|, x < 0

static Fp2 B_G2_MONT;  // 4(u+1)
static Fp B_G1_MONT;   // 4

// line = c_v2 v^2 + c_w w + c_wv (w v) as a dense Fp12
static inline Fp12 sparse_line(const Fp2 &c_v2, const Fp2 &c_w,
                               const Fp2 &c_wv) {
    Fp12 r;
    r.c0 = {FP2_ZERO_C, FP2_ZERO_C, c_v2};
    r.c1 = {c_w, c_wv, FP2_ZERO_C};
    return r;
}

// f * (c_v2 v^2 + c_w w + c_wv w v) exploiting the sparsity: the dense
// Karatsuba-2 runs 18 Fp2 muls, this runs 13.
static Fp12 fp12_mul_sparse(const Fp12 &f, const Fp2 &c_v2, const Fp2 &c_w,
                            const Fp2 &c_wv) {
    // s0 = (0, 0, c_v2): a*s0 = (xi(a1 c_v2), xi(a2 c_v2), a0 c_v2)
    const Fp6 &a0 = f.c0, &a1 = f.c1;
    Fp6 v0 = {fp2_mul_xi(fp2_mul(a0.c1, c_v2)),
              fp2_mul_xi(fp2_mul(a0.c2, c_v2)), fp2_mul(a0.c0, c_v2)};
    // s1 = (c_w, c_wv, 0): b2 = 0 term drops out of the schoolbook form
    Fp6 v1 = {fp2_add(fp2_mul(a1.c0, c_w),
                      fp2_mul_xi(fp2_mul(a1.c2, c_wv))),
              fp2_add(fp2_mul(a1.c0, c_wv), fp2_mul(a1.c1, c_w)),
              fp2_add(fp2_mul(a1.c1, c_wv), fp2_mul(a1.c2, c_w))};
    // cross = (a0 + a1) * (s0 + s1), s0+s1 = (c_w, c_wv, c_v2)
    Fp6 s = fp6_add(a0, a1);
    Fp6 cross = {
        fp2_add(fp2_mul(s.c0, c_w),
                fp2_mul_xi(fp2_add(fp2_mul(s.c1, c_v2), fp2_mul(s.c2, c_wv)))),
        fp2_add(fp2_add(fp2_mul(s.c0, c_wv), fp2_mul(s.c1, c_w)),
                fp2_mul_xi(fp2_mul(s.c2, c_v2))),
        fp2_add(fp2_add(fp2_mul(s.c0, c_v2), fp2_mul(s.c1, c_wv)),
                fp2_mul(s.c2, c_w))};
    Fp12 r;
    r.c0 = fp6_add(v0, fp6_mul_v(v1));
    r.c1 = fp6_sub(cross, fp6_add(v0, v1));
    return r;
}

struct G2Jac {
    Fp2 x, y, z;
};

// ref/pairing.py dbl: line coeffs then dbl-2009-l on the twist
static void miller_dbl(G2Jac &t, const Fp &xp, const Fp &yp, Fp2 &c_v2,
                       Fp2 &c_w, Fp2 &c_wv) {
    Fp2 zsq = fp2_sqr(t.z);
    Fp2 z3 = fp2_mul(zsq, t.z);
    Fp2 xsq = fp2_sqr(t.x);
    Fp2 ysq = fp2_sqr(t.y);
    c_v2 = fp2_scale(fp2_mul(t.y, z3), fp_dbl(yp));
    Fp2 x3p = fp2_mul(xsq, t.x);
    c_w = fp2_sub(fp2_add(fp2_add(x3p, x3p), x3p), fp2_dbl(ysq));
    Fp2 xz = fp2_mul(xsq, zsq);
    c_wv = fp2_neg(fp2_scale(fp2_add(fp2_add(xz, xz), xz), xp));
    // dbl-2009-l
    Fp2 a = xsq, b = ysq;
    Fp2 c = fp2_sqr(b);
    Fp2 d = fp2_dbl(fp2_sub(fp2_sub(fp2_sqr(fp2_add(t.x, b)), a), c));
    Fp2 e = fp2_add(fp2_add(a, a), a);
    Fp2 f = fp2_sqr(e);
    Fp2 x3 = fp2_sub(f, fp2_dbl(d));
    Fp2 c8 = fp2_dbl(fp2_dbl(fp2_dbl(c)));
    Fp2 y3 = fp2_sub(fp2_mul(e, fp2_sub(d, x3)), c8);
    Fp2 z3_ = fp2_dbl(fp2_mul(t.y, t.z));
    t = {x3, y3, z3_};
}

// ref/pairing.py add: chord line then madd-2007-bl (Z2 = 1)
static void miller_add(G2Jac &t, const Fp2 &xq, const Fp2 &yq, const Fp &xp,
                       const Fp &yp, Fp2 &c_v2, Fp2 &c_w, Fp2 &c_wv) {
    Fp2 zsq = fp2_sqr(t.z);
    Fp2 z3 = fp2_mul(zsq, t.z);
    Fp2 num = fp2_sub(t.y, fp2_mul(yq, z3));            // Y - yq Z^3
    Fp2 den = fp2_mul(t.z, fp2_sub(t.x, fp2_mul(xq, zsq)));  // Z(X - xq Z^2)
    c_v2 = fp2_scale(den, yp);
    c_wv = fp2_neg(fp2_scale(num, xp));
    c_w = fp2_sub(fp2_mul(xq, num), fp2_mul(yq, den));
    // madd-2007-bl
    Fp2 u2 = fp2_mul(xq, zsq);
    Fp2 s2 = fp2_mul(yq, z3);
    Fp2 h = fp2_sub(u2, t.x);
    Fp2 r = fp2_dbl(fp2_sub(s2, t.y));
    Fp2 i = fp2_sqr(fp2_dbl(h));
    Fp2 j = fp2_mul(h, i);
    Fp2 v = fp2_mul(t.x, i);
    Fp2 x3 = fp2_sub(fp2_sub(fp2_sqr(r), j), fp2_dbl(v));
    Fp2 y3 = fp2_sub(fp2_mul(r, fp2_sub(v, x3)), fp2_dbl(fp2_mul(t.y, j)));
    Fp2 z3_ = fp2_sub(fp2_sub(fp2_sqr(fp2_add(t.z, h)), zsq), fp2_sqr(h));
    t = {x3, y3, z3_};
}

// f_{|x|,Q}(P), conjugated for x < 0; affine finite inputs.
static Fp12 miller_loop(const Fp &xp, const Fp &yp, const Fp2 &xq,
                        const Fp2 &yq) {
    Fp12 f = FP12_ONE_C;
    G2Jac t = {xq, yq, FP2_ONE_C};
    Fp2 c_v2, c_w, c_wv;
    // MSB of |x| consumed by the initial T = Q; iterate remaining 63 bits
    for (int b = 62; b >= 0; b--) {
        miller_dbl(t, xp, yp, c_v2, c_w, c_wv);
        f = fp12_mul_sparse(fp12_sqr(f), c_v2, c_w, c_wv);
        if ((ABS_X >> b) & 1) {
            miller_add(t, xq, yq, xp, yp, c_v2, c_w, c_wv);
            f = fp12_mul_sparse(f, c_v2, c_w, c_wv);
        }
    }
    return fp12_conj(f);
}

// a^e (64-bit static exponent) with cyclotomic squarings; unitary a only.
static Fp12 cyclo_pow(const Fp12 &a, u64 e) {
    Fp12 acc = a;
    int top = 63;
    while (top >= 0 && !((e >> top) & 1)) top--;
    for (int b = top - 1; b >= 0; b--) {
        acc = fp12_cyclo_sqr(acc);
        if ((e >> b) & 1) acc = fp12_mul(acc, a);
    }
    return acc;
}

// f^(3 (p^12-1)/r): the framework's canonical (cubed) pairing power.
// Chain identical to ops/pairing.py::final_exponentiation.
static Fp12 final_exponentiation(const Fp12 &f) {
    Fp12 f1 = fp12_mul(fp12_conj(f), fp12_inv(f));       // ^(p^6 - 1)
    Fp12 f2 = fp12_mul(fp12_frobenius(f1, 2), f1);       // ^(p^2 + 1)
    Fp12 m1 = fp12_conj(cyclo_pow(f2, ABS_X + 1));       // f2^(x-1)
    Fp12 m2 = fp12_conj(cyclo_pow(m1, ABS_X + 1));       // ^(x-1)^2
    Fp12 m3 = fp12_mul(fp12_conj(cyclo_pow(m2, ABS_X)), fp12_frobenius(m2, 1));
    Fp12 m3x2 = cyclo_pow(cyclo_pow(m3, ABS_X), ABS_X);  // conj x2 cancels
    Fp12 m4 =
        fp12_mul(fp12_mul(m3x2, fp12_frobenius(m3, 2)), fp12_conj(m3));
    return fp12_mul(m4, fp12_mul(fp12_sqr(f2), f2));     // * f2^3
}

// ---------------------------------------------------------------------------
// init
// ---------------------------------------------------------------------------

static bool INIT_DONE = false;

static void init_constants() {
    if (INIT_DONE) return;
    // NP = -p^-1 mod 2^64 via Newton iteration
    u64 inv = P_LIMBS[0];
    for (int i = 0; i < 5; i++) inv *= 2 - P_LIMBS[0] * inv;
    NP = (u64)(0 - inv);
    memset(FP_ZERO.v, 0, sizeof FP_ZERO.v);
    // R mod p by doubling canonical 1, 384 times; then R^2 by 768
    u64 acc[6] = {1, 0, 0, 0, 0, 0};
    for (int i = 0; i < 768; i++) {
        u64 carry = limbs_add(acc, acc, acc);
        if (carry || limbs_ge(acc, P_LIMBS)) limbs_sub(acc, acc, P_LIMBS);
        if (i == 383) memcpy(FP_ONE.v, acc, sizeof FP_ONE.v);  // R mod p
    }
    memcpy(FP_R2.v, acc, sizeof FP_R2.v);
    memcpy(R2_LIMBS, acc, sizeof R2_LIMBS);
    // exponents: p-2, (p+1)/4, (p-1)/2
    u64 two[6] = {2, 0, 0, 0, 0, 0};
    limbs_sub(PM2, P_LIMBS, two);
    u64 pp1[6];
    u64 one1[6] = {1, 0, 0, 0, 0, 0};
    limbs_add(pp1, P_LIMBS, one1);  // p odd: no carry out of 381 bits
    for (int s = 0; s < 2; s++) {   // >> 2
        u64 carry = 0;
        for (int i = 5; i >= 0; i--) {
            u64 nc = pp1[i] & 1;
            pp1[i] = (pp1[i] >> 1) | (carry << 63);
            carry = nc;
        }
    }
    memcpy(PP14, pp1, sizeof PP14);
    u64 pm1[6];
    limbs_sub(pm1, P_LIMBS, one1);
    u64 carry = 0;
    for (int i = 5; i >= 0; i--) {
        u64 nc = pm1[i] & 1;
        pm1[i] = (pm1[i] >> 1) | (carry << 63);
        carry = nc;
    }
    memcpy(PM12, pm1, sizeof PM12);
    // tower constants
    FP2_ZERO_C = {FP_ZERO, FP_ZERO};
    FP2_ONE_C = {FP_ONE, FP_ZERO};
    FP6_ZERO_C = {FP2_ZERO_C, FP2_ZERO_C, FP2_ZERO_C};
    FP6_ONE_C = {FP2_ONE_C, FP2_ZERO_C, FP2_ZERO_C};
    FP12_ONE_C = {FP6_ONE_C, FP6_ZERO_C};
    FP_INV2 = fp_inv(fp_add(FP_ONE, FP_ONE));
    B_G1_MONT = fp_dbl(fp_dbl(FP_ONE));                    // 4
    B_G2_MONT = {B_G1_MONT, B_G1_MONT};                    // 4(u+1)
    // Frobenius gammas: gamma1[m] = xi^(m (p-1)/6)
    u64 e6[6];
    limbs_sub(e6, P_LIMBS, one1);  // p - 1
    u128 rem = 0;
    for (int i = 5; i >= 0; i--) {  // divide by 6
        u128 cur = (rem << 64) | e6[i];
        e6[i] = (u64)(cur / 6);
        rem = cur % 6;
    }
    Fp2 xi = {FP_ONE, FP_ONE};
    Fp2 g1 = fp2_pow_limbs(xi, e6, 6);
    GAMMA1[0] = FP2_ONE_C;
    for (int m = 1; m < 6; m++) GAMMA1[m] = fp2_mul(GAMMA1[m - 1], g1);
    for (int m = 0; m < 6; m++) GAMMA2[m] = fp2_mul(GAMMA1[m], fp2_conj(GAMMA1[m]));
    INIT_DONE = true;
}

// ---------------------------------------------------------------------------
// byte helpers for the ABI
// ---------------------------------------------------------------------------

static Fp2 fp2_from_bytes(const uint8_t *b) {
    return {fp_from_bytes(b), fp_from_bytes(b + 48)};
}

static void fp2_to_bytes(const Fp2 &a, uint8_t *out) {
    fp_to_bytes(a.c0, out);
    fp_to_bytes(a.c1, out + 48);
}

static void fp12_to_bytes(const Fp12 &a, uint8_t *out) {
    const Fp2 *cs[6] = {&a.c0.c0, &a.c0.c1, &a.c0.c2,
                        &a.c1.c0, &a.c1.c1, &a.c1.c2};
    for (int i = 0; i < 6; i++) fp2_to_bytes(*cs[i], out + 96 * i);
}

// ---------------------------------------------------------------------------
// exported ABI
// ---------------------------------------------------------------------------

extern "C" {

// init + algebraic selftest; returns 1 when healthy.
int hbls_ready() {
    init_constants();
    // deterministic element: a = (to_mont bytes of small ints)
    Fp12 a;
    Fp2 *cs[6] = {&a.c0.c0, &a.c0.c1, &a.c0.c2, &a.c1.c0, &a.c1.c1, &a.c1.c2};
    for (int i = 0; i < 6; i++) {
        Fp x = FP_ONE;
        for (int j = 0; j < i + 2; j++) x = fp_add(x, FP_ONE);
        *cs[i] = {x, fp_add(x, FP_ONE)};
    }
    // a * a^-1 == 1
    if (!fp12_eq(fp12_mul(a, fp12_inv(a)), FP12_ONE_C)) return -1;
    // frob(frob(a,1),1) == frob(a,2)
    if (!fp12_eq(fp12_frobenius(fp12_frobenius(a, 1), 1), fp12_frobenius(a, 2)))
        return -2;
    // cyclo_sqr == sqr in the cyclotomic subgroup (full easy part:
    // unitary alone is NOT enough for Granger-Scott)
    Fp12 u = fp12_mul(fp12_conj(a), fp12_inv(a));      // ^(p^6 - 1)
    u = fp12_mul(fp12_frobenius(u, 2), u);             // ^(p^2 + 1)
    if (!fp12_eq(fp12_cyclo_sqr(u), fp12_sqr(u))) return -3;
    return 1;
}

// scalar mul: out-affine; returns 1 if result is infinity.
int hbls_g1_mul(const uint8_t *xy, int inf, const uint8_t *sc, int sclen,
                uint8_t *out) {
    init_constants();
    Fp x = inf ? FP_ZERO : fp_from_bytes(xy);
    Fp y = inf ? FP_ZERO : fp_from_bytes(xy + 48);
    Jac<Fp> r = jac_mul<Fp>(x, y, inf != 0, sc, sclen);
    bool is_inf;
    Fp rx, ry;
    jac_to_affine(r, rx, ry, is_inf);
    if (is_inf) { memset(out, 0, 96); return 1; }
    fp_to_bytes(rx, out);
    fp_to_bytes(ry, out + 48);
    return 0;
}

int hbls_g2_mul(const uint8_t *xy, int inf, const uint8_t *sc, int sclen,
                uint8_t *out) {
    init_constants();
    Fp2 x = inf ? FP2_ZERO_C : fp2_from_bytes(xy);
    Fp2 y = inf ? FP2_ZERO_C : fp2_from_bytes(xy + 96);
    Jac<Fp2> r = jac_mul<Fp2>(x, y, inf != 0, sc, sclen);
    bool is_inf;
    Fp2 rx, ry;
    jac_to_affine(r, rx, ry, is_inf);
    if (is_inf) { memset(out, 0, 192); return 1; }
    fp2_to_bytes(rx, out);
    fp2_to_bytes(ry, out + 96);
    return 0;
}

// sum of n affine points (aggregation); returns 1 if infinity.
int hbls_g1_sum(const uint8_t *pts, const uint8_t *infs, int n, uint8_t *out) {
    init_constants();
    Jac<Fp> acc = jac_infinity<Fp>();
    for (int i = 0; i < n; i++) {
        if (infs[i]) continue;
        Jac<Fp> p = {fp_from_bytes(pts + 96 * i),
                     fp_from_bytes(pts + 96 * i + 48), FP_ONE};
        acc = jac_add(acc, p);
    }
    bool is_inf;
    Fp rx, ry;
    jac_to_affine(acc, rx, ry, is_inf);
    if (is_inf) { memset(out, 0, 96); return 1; }
    fp_to_bytes(rx, out);
    fp_to_bytes(ry, out + 48);
    return 0;
}

int hbls_g2_sum(const uint8_t *pts, const uint8_t *infs, int n, uint8_t *out) {
    init_constants();
    Jac<Fp2> acc = jac_infinity<Fp2>();
    for (int i = 0; i < n; i++) {
        if (infs[i]) continue;
        Jac<Fp2> p = {fp2_from_bytes(pts + 192 * i),
                      fp2_from_bytes(pts + 192 * i + 96), FP2_ONE_C};
        acc = jac_add(acc, p);
    }
    bool is_inf;
    Fp2 rx, ry;
    jac_to_affine(acc, rx, ry, is_inf);
    if (is_inf) { memset(out, 0, 192); return 1; }
    fp2_to_bytes(rx, out);
    fp2_to_bytes(ry, out + 96);
    return 0;
}

// subgroup membership: r * P == infinity (rogue-point defense used by
// decompress; the affine Python version costs ~40 ms, this ~0.3 ms).
int hbls_g1_in_subgroup(const uint8_t *xy, const uint8_t *r_be, int rlen) {
    init_constants();
    Fp x = fp_from_bytes(xy), y = fp_from_bytes(xy + 48);
    // must be on curve first: y^2 == x^3 + 4
    Fp lhs = fp_sqr(y);
    Fp rhs = fp_add(fp_mul(fp_sqr(x), x), B_G1_MONT);
    if (!fp_eq(lhs, rhs)) return 0;
    Jac<Fp> p = jac_mul<Fp>(x, y, false, r_be, rlen);
    return p.inf() ? 1 : 0;
}

int hbls_g2_in_subgroup(const uint8_t *xy, const uint8_t *r_be, int rlen) {
    init_constants();
    Fp2 x = fp2_from_bytes(xy), y = fp2_from_bytes(xy + 96);
    Fp2 lhs = fp2_sqr(y);
    Fp2 rhs = fp2_add(fp2_mul(fp2_sqr(x), x), B_G2_MONT);
    if (!fp2_eq(lhs, rhs)) return 0;
    Jac<Fp2> p = jac_mul<Fp2>(x, y, false, r_be, rlen);
    return p.inf() ? 1 : 0;
}

// try-and-increment map step (ref/hash_to_curve.py::map_to_twist body):
// given candidate x in Fp2, find y with y^2 = x^3 + 4(u+1), pick the
// lexicographically smaller of {y, -y}.  Returns 1 on success.
int hbls_g2_map_tai(const uint8_t *x96, uint8_t *out192) {
    init_constants();
    Fp2 x = fp2_from_bytes(x96);
    Fp2 rhs = fp2_add(fp2_mul(fp2_sqr(x), x), B_G2_MONT);
    Fp2 y;
    if (!fp2_sqrt(rhs, y)) return 0;
    Fp2 ny = fp2_neg(y);
    if (fp2_lex_gt(y, ny)) y = ny;
    fp2_to_bytes(x, out192);
    fp2_to_bytes(y, out192 + 96);
    return 1;
}

// deterministic Fp2 sqrt (decompress path); returns 1 on success.
int hbls_fp2_sqrt(const uint8_t *in96, uint8_t *out96) {
    init_constants();
    Fp2 a = fp2_from_bytes(in96);
    Fp2 r;
    if (!fp2_sqrt(a, r)) return 0;
    fp2_to_bytes(r, out96);
    return 1;
}

int hbls_fp_sqrt(const uint8_t *in48, uint8_t *out48) {
    init_constants();
    Fp a = fp_from_bytes(in48);
    bool ok;
    Fp r = fp_sqrt(a, ok);
    if (!ok) return 0;
    fp_to_bytes(r, out48);
    return 1;
}

// prod_i e(P_i, Q_i) as a full GT element (576 B, ref tuple order) —
// the parity surface the tests pin against ref/pairing.py.
void hbls_multi_pairing(const uint8_t *g1s, const uint8_t *g1infs,
                        const uint8_t *g2s, const uint8_t *g2infs, int n,
                        uint8_t *out576) {
    init_constants();
    Fp12 f = FP12_ONE_C;
    for (int i = 0; i < n; i++) {
        if (g1infs[i] || g2infs[i]) continue;  // e(O, Q) = 1
        Fp xp = fp_from_bytes(g1s + 96 * i);
        Fp yp = fp_from_bytes(g1s + 96 * i + 48);
        Fp2 xq = fp2_from_bytes(g2s + 192 * i);
        Fp2 yq = fp2_from_bytes(g2s + 192 * i + 96);
        f = fp12_mul(f, miller_loop(xp, yp, xq, yq));
    }
    fp12_to_bytes(final_exponentiation(f), out576);
}

// prod_i e(P_i, Q_i) == 1 — the verify decision (2 pairs for a single
// check, 2B for a replay batch with shared final exponentiation).
int hbls_pairing_check(const uint8_t *g1s, const uint8_t *g1infs,
                       const uint8_t *g2s, const uint8_t *g2infs, int n) {
    init_constants();
    Fp12 f = FP12_ONE_C;
    for (int i = 0; i < n; i++) {
        if (g1infs[i] || g2infs[i]) continue;
        Fp xp = fp_from_bytes(g1s + 96 * i);
        Fp yp = fp_from_bytes(g1s + 96 * i + 48);
        Fp2 xq = fp2_from_bytes(g2s + 192 * i);
        Fp2 yq = fp2_from_bytes(g2s + 192 * i + 96);
        f = fp12_mul(f, miller_loop(xp, yp, xq, yq));
    }
    return fp12_eq(final_exponentiation(f), FP12_ONE_C) ? 1 : 0;
}

}  // extern "C"
