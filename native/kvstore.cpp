// Native chain storage: a log-structured key/value store with the SAME
// on-disk format as harmony_tpu/core/kv.py FileKV — the two open each
// other's files.  This is the node's IO hot path done in native code
// (the role LevelDB's C++ plays under the reference's core/rawdb);
// Python binds via ctypes (harmony_tpu/core/kv_native.py), a Go node
// would bind via cgo exactly as the reference binds its storage.
//
// Record format (little-endian):
//   [klen u32][vlen u32 | 0xFFFFFFFF = tombstone][key][value]
//
// Atomic commit batches (same grammar as the Python twin): records
// bracketed by BEGIN = [0xFFFFFFFE][count] and COMMIT = [0xFFFFFFFD]
// [count] markers; replay applies a batch only when its COMMIT marker
// (with the matching count) is on disk — a crash anywhere inside the
// batch makes the whole batch invisible on reopen.
//
// C ABI: every function is kv_*; buffers returned by kv_get are owned
// by the store and valid until the next call on the same handle
// (single-threaded per handle, like the Python twin).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

#include <sys/types.h>
#include <unistd.h>

namespace {

constexpr uint32_t kTomb = 0xFFFFFFFFu;
constexpr uint32_t kBatchBegin = 0xFFFFFFFEu;
constexpr uint32_t kBatchCommit = 0xFFFFFFFDu;
constexpr uint32_t kKlenMax = 0xFFFFFFF0u;  // larger = corrupt header

struct Store {
  std::FILE* f = nullptr;
  std::string path;
  std::unordered_map<std::string, std::pair<uint64_t, uint32_t>> index;
  std::vector<uint8_t> last_value;  // buffer handed to callers
  int fsync_batch = 0;  // kv_config: fsync on every batch commit

  ~Store() {
    if (f) std::fclose(f);
  }
};

bool read_exact(std::FILE* f, void* buf, size_t n) {
  return std::fread(buf, 1, n, f) == n;
}

uint32_t load_u32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}

void store_u32(uint8_t* p, uint32_t v) {
  p[0] = v & 0xff;
  p[1] = (v >> 8) & 0xff;
  p[2] = (v >> 16) & 0xff;
  p[3] = (v >> 24) & 0xff;
}

// Replay the log into the index; truncate a torn tail record.  Every
// length field is bounds-checked against the REAL file size before any
// allocation or index insert: fseek happily passes EOF and POSIX
// truncate EXTENDS, so trusting lengths would turn a crash-torn value
// into silent zero-filled reads — and a corrupt klen into a
// multi-gigabyte allocation aborting the process through the C ABI.
bool replay(Store* s) {
  std::fseek(s->f, 0, SEEK_END);
  const uint64_t file_size = static_cast<uint64_t>(std::ftell(s->f));
  std::fseek(s->f, 0, SEEK_SET);
  uint64_t pos = 0;
  bool in_batch = false;
  uint64_t batch_start = 0;   // offset of the open batch's BEGIN marker
  uint32_t batch_count = 0;
  // records staged inside the open batch: (key, voff, vlen);
  // voff == UINT64_MAX marks a tombstone
  std::vector<std::tuple<std::string, uint64_t, uint32_t>> pending;
  std::vector<char> keybuf;
  for (;;) {
    pos = static_cast<uint64_t>(std::ftell(s->f));
    uint8_t hdr[8];
    if (!read_exact(s->f, hdr, 8)) break;
    const uint32_t klen = load_u32(hdr);
    const uint32_t vlen = load_u32(hdr + 4);
    if (klen == kBatchBegin) {
      if (in_batch) break;  // nested BEGIN: corrupt
      in_batch = true;
      batch_start = pos;
      batch_count = vlen;
      pending.clear();
      continue;
    }
    if (klen == kBatchCommit) {
      if (!in_batch || vlen != pending.size() ||
          batch_count != pending.size()) {
        break;  // marker without its batch, or count mismatch
      }
      for (auto& [key, voff, vl] : pending) {
        if (voff == UINT64_MAX) {
          s->index.erase(key);
        } else {
          s->index[std::move(key)] = {voff, vl};
        }
      }
      in_batch = false;
      pending.clear();
      continue;
    }
    if (klen >= kKlenMax) break;  // implausible key length
    if (pos + 8 + klen > file_size) break;  // torn/corrupt key length
    keybuf.resize(klen);
    if (klen && !read_exact(s->f, keybuf.data(), klen)) break;
    std::string key(keybuf.data(), klen);
    if (vlen == kTomb) {
      if (in_batch) {
        pending.emplace_back(std::move(key), UINT64_MAX, 0);
      } else {
        s->index.erase(key);
      }
      continue;
    }
    const uint64_t voff = static_cast<uint64_t>(std::ftell(s->f));
    if (voff + vlen > file_size) break;  // torn value
    std::fseek(s->f, static_cast<long>(vlen), SEEK_CUR);
    if (in_batch) {
      pending.emplace_back(std::move(key), voff, vlen);
    } else {
      s->index[std::move(key)] = {voff, vlen};
    }
  }
  // drop everything from the failure point — from the BEGIN marker if
  // the failure is inside an open batch (the un-committed batch must
  // be invisible to appends too); never grows the file
  const uint64_t cut = in_batch ? batch_start : pos;
  std::fflush(s->f);
  if (cut < file_size &&
      truncate(s->path.c_str(), static_cast<off_t>(cut)) != 0) {
    // non-fatal: reads still consistent, appends go after the tear
  }
  std::freopen(s->path.c_str(), "r+b", s->f);
  std::fseek(s->f, 0, SEEK_END);
  return true;
}

bool append_record(Store* s, const uint8_t* key, uint32_t klen,
                   const uint8_t* val, uint32_t vlen, bool tomb) {
  std::fseek(s->f, 0, SEEK_END);
  uint8_t hdr[8];
  store_u32(hdr, klen);
  store_u32(hdr + 4, tomb ? kTomb : vlen);
  if (std::fwrite(hdr, 1, 8, s->f) != 8) return false;
  if (klen && std::fwrite(key, 1, klen, s->f) != klen) return false;
  const uint64_t voff = static_cast<uint64_t>(std::ftell(s->f));
  if (!tomb && vlen && std::fwrite(val, 1, vlen, s->f) != vlen) {
    return false;
  }
  std::string k(reinterpret_cast<const char*>(key), klen);
  if (tomb) {
    s->index.erase(k);
  } else {
    s->index[std::move(k)] = {voff, vlen};
  }
  return true;
}

}  // namespace

extern "C" {

void* kv_open(const char* path) {
  // no C++ exception may cross the C ABI: a corrupt file must yield
  // nullptr (the Python side falls back), never std::terminate
  try {
    auto* s = new Store();
    s->path = path;
    s->f = std::fopen(path, "r+b");
    if (s->f == nullptr) {
      s->f = std::fopen(path, "w+b");
      if (s->f == nullptr) {
        delete s;
        return nullptr;
      }
    }
    replay(s);
    return s;
  } catch (...) {
    return nullptr;
  }
}

int kv_put(void* h, const uint8_t* key, uint32_t klen, const uint8_t* val,
           uint32_t vlen) {
  if (vlen == kTomb) return -1;
  auto* s = static_cast<Store*>(h);
  return append_record(s, key, klen, val, vlen, false) ? 0 : -1;
}

// Returns pointer to the value (owned by the store, valid until the
// next call) and sets *vlen; nullptr when absent.
const uint8_t* kv_get(void* h, const uint8_t* key, uint32_t klen,
                      uint32_t* vlen) {
  auto* s = static_cast<Store*>(h);
  auto it = s->index.find(
      std::string(reinterpret_cast<const char*>(key), klen));
  if (it == s->index.end()) return nullptr;
  s->last_value.resize(it->second.second);
  std::fseek(s->f, static_cast<long>(it->second.first), SEEK_SET);
  if (!read_exact(s->f, s->last_value.data(), it->second.second)) {
    std::fseek(s->f, 0, SEEK_END);
    return nullptr;
  }
  std::fseek(s->f, 0, SEEK_END);
  *vlen = it->second.second;
  return s->last_value.data();
}

int kv_delete(void* h, const uint8_t* key, uint32_t klen) {
  auto* s = static_cast<Store*>(h);
  std::string k(reinterpret_cast<const char*>(key), klen);
  if (s->index.find(k) == s->index.end()) return 0;
  return append_record(s, key, klen, nullptr, 0, true) ? 0 : -1;
}

// Atomic batch commit.  `payload` is `count` concatenated records in
// the standard on-disk format (tombstones via vlen = 0xFFFFFFFF); the
// store brackets them with BEGIN/COMMIT markers, optionally fsyncs
// (kv_config), and applies them to the index only after the marker
// write succeeded.  On ANY failure the log is truncated back to the
// batch start — all-or-nothing on disk AND in memory.
int kv_write_batch(void* h, const uint8_t* payload, uint64_t payload_len,
                   uint32_t count) {
  auto* s = static_cast<Store*>(h);
  std::fseek(s->f, 0, SEEK_END);
  const uint64_t start = static_cast<uint64_t>(std::ftell(s->f));

  // parse + bounds-check the payload BEFORE writing anything
  std::vector<std::tuple<std::string, uint64_t, uint32_t>> staged;
  uint64_t off = 0;
  while (off < payload_len) {
    if (off + 8 > payload_len) return -1;
    const uint32_t klen = load_u32(payload + off);
    const uint32_t vlen = load_u32(payload + off + 4);
    if (klen >= kKlenMax) return -1;
    if (off + 8 + klen > payload_len) return -1;
    std::string key(reinterpret_cast<const char*>(payload + off + 8), klen);
    off += 8 + klen;
    if (vlen == kTomb) {
      staged.emplace_back(std::move(key), UINT64_MAX, 0);
      continue;
    }
    if (off + vlen > payload_len) return -1;
    // voff is relative for now; rebased after the BEGIN marker lands
    staged.emplace_back(std::move(key), off, vlen);
    off += vlen;
  }
  if (staged.size() != count) return -1;

  uint8_t hdr[8];
  store_u32(hdr, kBatchBegin);
  store_u32(hdr + 4, count);
  bool ok = std::fwrite(hdr, 1, 8, s->f) == 8;
  if (ok && payload_len) {
    ok = std::fwrite(payload, 1, payload_len, s->f) == payload_len;
  }
  if (ok) {
    store_u32(hdr, kBatchCommit);
    store_u32(hdr + 4, count);
    ok = std::fwrite(hdr, 1, 8, s->f) == 8;
  }
  if (!ok) {
    std::fflush(s->f);
    truncate(s->path.c_str(), static_cast<off_t>(start));
    std::freopen(s->path.c_str(), "r+b", s->f);
    std::fseek(s->f, 0, SEEK_END);
    return -1;
  }
  if (s->fsync_batch) {
    std::fflush(s->f);
    fsync(fileno(s->f));
  }
  const uint64_t base = start + 8;  // payload begins after BEGIN marker
  for (auto& [key, voff, vlen] : staged) {
    if (voff == UINT64_MAX) {
      s->index.erase(key);
    } else {
      s->index[std::move(key)] = {base + voff, vlen};
    }
  }
  return 0;
}

// Store configuration: currently one knob, fsync-on-batch-commit
// (0 = OS-buffered, 1 = durable batch commits).
int kv_config(void* h, int fsync_batch) {
  static_cast<Store*>(h)->fsync_batch = fsync_batch ? 1 : 0;
  return 0;
}

int kv_has(void* h, const uint8_t* key, uint32_t klen) {
  auto* s = static_cast<Store*>(h);
  return s->index.count(
             std::string(reinterpret_cast<const char*>(key), klen))
             ? 1
             : 0;
}

uint64_t kv_len(void* h) {
  return static_cast<Store*>(h)->index.size();
}

int kv_flush(void* h) {
  // flush() is the DURABILITY call (FileKV.flush os.fsync's): stdio
  // flush alone only reaches the page cache and would silently break
  // the SafetyStore's written-durably-before-broadcast guarantee on
  // the native (default) path
  auto* s = static_cast<Store*>(h);
  if (std::fflush(s->f) != 0) return -1;
  return fsync(fileno(s->f)) == 0 ? 0 : -1;
}

// Rewrite live records; reclaims tombstones and stale puts.
int kv_compact(void* h) {
  auto* s = static_cast<Store*>(h);
  const std::string tmp_path = s->path + ".compact";
  std::FILE* out = std::fopen(tmp_path.c_str(), "wb");
  if (out == nullptr) return -1;
  std::vector<uint8_t> val;
  for (const auto& [key, loc] : s->index) {
    val.resize(loc.second);
    std::fseek(s->f, static_cast<long>(loc.first), SEEK_SET);
    if (!read_exact(s->f, val.data(), loc.second)) {
      std::fclose(out);
      std::remove(tmp_path.c_str());
      return -1;
    }
    uint8_t hdr[8];
    store_u32(hdr, static_cast<uint32_t>(key.size()));
    store_u32(hdr + 4, loc.second);
    std::fwrite(hdr, 1, 8, out);
    std::fwrite(key.data(), 1, key.size(), out);
    std::fwrite(val.data(), 1, loc.second, out);
  }
  std::fflush(out);
  fsync(fileno(out));  // data must hit disk BEFORE the rename commits
  std::fclose(out);
  std::fclose(s->f);
  if (std::rename(tmp_path.c_str(), s->path.c_str()) != 0) {
    s->f = std::fopen(s->path.c_str(), "r+b");
    return -1;
  }
  s->f = std::fopen(s->path.c_str(), "r+b");
  s->index.clear();
  replay(s);
  return 0;
}

void kv_close(void* h) {
  delete static_cast<Store*>(h);
}

}  // extern "C"
