// Native sidecar client: the C++ half of the node <-> TPU-kernel-server
// boundary (harmony_tpu/sidecar/protocol.py defines the wire format).
//
// In deployment the chain node (Go, linking this via cgo the way the
// reference links herumi's libbls) calls these functions instead of an
// in-process pairing library; the heavy crypto happens in the persistent
// kernel server process.  Exposed as a C ABI so ctypes/cgo/FFI all work.
//
// Protocol v1 (little-endian):
//   frame  = [u32 len][u8 type][u32 req_id][body]; responses set type bit 7
//   bodies = see harmony_tpu/sidecar/protocol.py

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

namespace {

constexpr uint8_t kMsgPing = 0x01;
constexpr uint8_t kMsgSetCommittee = 0x02;
constexpr uint8_t kMsgAggVerify = 0x03;
constexpr uint8_t kRespFlag = 0x80;
constexpr uint32_t kMaxFrame = 2 * 1024 * 1024;
constexpr size_t kPubkeyBytes = 48;
constexpr size_t kSigBytes = 96;

void put_u16(std::vector<uint8_t>& out, uint16_t v) {
  out.push_back(v & 0xff);
  out.push_back((v >> 8) & 0xff);
}

void put_u32(std::vector<uint8_t>& out, uint32_t v) {
  for (int i = 0; i < 4; ++i) out.push_back((v >> (8 * i)) & 0xff);
}

void put_u64(std::vector<uint8_t>& out, uint64_t v) {
  for (int i = 0; i < 8; ++i) out.push_back((v >> (8 * i)) & 0xff);
}

bool write_all(int fd, const uint8_t* data, size_t len) {
  while (len > 0) {
    ssize_t n = ::write(fd, data, len);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
    data += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool read_all(int fd, uint8_t* data, size_t len) {
  while (len > 0) {
    ssize_t n = ::read(fd, data, len);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
    data += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

struct Client {
  int fd = -1;
  uint32_t next_req_id = 1;
};

// Sends one request frame and reads the matching response.  Returns the
// response status (>= 0) or a negative transport error.
int roundtrip(Client* c, uint8_t msg_type, const std::vector<uint8_t>& body,
              std::vector<uint8_t>* resp_body) {
  uint32_t req_id = c->next_req_id++;
  std::vector<uint8_t> frame;
  frame.reserve(9 + body.size());
  put_u32(frame, static_cast<uint32_t>(1 + 4 + body.size()));
  frame.push_back(msg_type);
  put_u32(frame, req_id);
  frame.insert(frame.end(), body.begin(), body.end());
  if (!write_all(c->fd, frame.data(), frame.size())) return -1;

  uint8_t hdr[4];
  if (!read_all(c->fd, hdr, 4)) return -2;
  uint32_t len = hdr[0] | (hdr[1] << 8) | (hdr[2] << 16)
      | (static_cast<uint32_t>(hdr[3]) << 24);
  if (len < 6 || len > kMaxFrame) return -3;
  std::vector<uint8_t> data(len);
  if (!read_all(c->fd, data.data(), len)) return -4;
  uint8_t rtype = data[0];
  uint32_t rid = data[1] | (data[2] << 8) | (data[3] << 16)
      | (static_cast<uint32_t>(data[4]) << 24);
  if (rtype != (msg_type | kRespFlag) || rid != req_id) return -5;
  uint8_t status = data[5];
  if (resp_body) resp_body->assign(data.begin() + 6, data.end());
  return status;
}

}  // namespace

extern "C" {

// Connect over TCP; returns an opaque handle or null.
void* harmony_sidecar_connect_tcp(const char* host, int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1 ||
      ::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return nullptr;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto* c = new Client();
  c->fd = fd;
  return c;
}

// Connect over a Unix socket; returns an opaque handle or null.
void* harmony_sidecar_connect_unix(const char* path) {
  int fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  std::strncpy(addr.sun_path, path, sizeof(addr.sun_path) - 1);
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return nullptr;
  }
  auto* c = new Client();
  c->fd = fd;
  return c;
}

void harmony_sidecar_close(void* handle) {
  auto* c = static_cast<Client*>(handle);
  if (!c) return;
  if (c->fd >= 0) ::close(c->fd);
  delete c;
}

// Returns the server protocol version (> 0) or a negative error.
int harmony_sidecar_ping(void* handle) {
  auto* c = static_cast<Client*>(handle);
  std::vector<uint8_t> resp;
  int status = roundtrip(c, kMsgPing, {}, &resp);
  if (status != 0) return status > 0 ? -100 - status : status;
  if (resp.size() < 2) return -6;
  return resp[0] | (resp[1] << 8);
}

// Upload a committee's pubkeys (n * 48 bytes).  Returns 0 on success.
int harmony_sidecar_set_committee(void* handle, uint64_t epoch, uint32_t shard,
                                  const uint8_t* pubkeys, uint32_t n) {
  auto* c = static_cast<Client*>(handle);
  std::vector<uint8_t> body;
  body.reserve(16 + n * kPubkeyBytes);
  put_u64(body, epoch);
  put_u32(body, shard);
  put_u32(body, n);
  body.insert(body.end(), pubkeys, pubkeys + n * kPubkeyBytes);
  int status = roundtrip(c, kMsgSetCommittee, body, nullptr);
  return status == 0 ? 0 : (status > 0 ? status : status);
}

// Aggregate-verify: bitmap-masked committee aggregate vs a 96-byte sig
// over `payload`.  Returns 1 valid, 0 invalid, negative on error.
int harmony_sidecar_agg_verify(void* handle, uint64_t epoch, uint32_t shard,
                               const uint8_t* payload, uint16_t payload_len,
                               const uint8_t* bitmap, uint16_t bitmap_len,
                               const uint8_t* sig96) {
  auto* c = static_cast<Client*>(handle);
  std::vector<uint8_t> body;
  body.reserve(14 + payload_len + 2 + bitmap_len + kSigBytes);
  put_u64(body, epoch);
  put_u32(body, shard);
  put_u16(body, payload_len);
  body.insert(body.end(), payload, payload + payload_len);
  put_u16(body, bitmap_len);
  body.insert(body.end(), bitmap, bitmap + bitmap_len);
  body.insert(body.end(), sig96, sig96 + kSigBytes);
  std::vector<uint8_t> resp;
  int status = roundtrip(c, kMsgAggVerify, body, &resp);
  if (status != 0) return status > 0 ? -100 - status : status;
  if (resp.empty()) return -6;
  return resp[0] ? 1 : 0;
}

}  // extern "C"
