"""Resilience layer: deadlines, retry/backoff, circuit breaker, the
fault-injection registry, and the sidecar client's failure contract
(fail closed on desync, reconnect with committee replay).

Every scenario here is DETERMINISTIC: jitter is hashed, faults are
counted, clocks are injected — a failure replays bit-for-bit.
"""

import socket
import threading
import time

import pytest

from harmony_tpu import faultinject as FI
from harmony_tpu.resilience import (
    TRANSITIONS,
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
)
from harmony_tpu.sidecar import protocol as P
from harmony_tpu.sidecar.client import SidecarClient, SidecarUnavailable


@pytest.fixture(autouse=True)
def _clean_faults():
    FI.reset()
    yield
    FI.reset()


# -- Deadline ----------------------------------------------------------------


def test_deadline_budget_and_bound():
    dl = Deadline.after(10.0)
    rem = dl.remaining()
    assert 9.0 < rem <= 10.0
    assert not dl.expired()
    assert dl.bound(3.0) == 3.0  # per-step timeout tighter
    assert dl.bound(None) == pytest.approx(rem, abs=0.5)
    dl.check("op")  # no raise

    gone = Deadline.after(0.0)
    assert gone.expired()
    assert gone.bound(3.0) == 0.0
    with pytest.raises(DeadlineExceeded):
        gone.check("op")


def test_deadline_none_is_unbounded():
    dl = Deadline.none()
    assert dl.remaining() is None
    assert not dl.expired()
    assert dl.bound(2.5) == 2.5
    assert dl.bound(None) is None
    dl.check()


def test_deadline_exceeded_is_oserror():
    # socket-style except blocks must catch budget exhaustion for free
    assert issubclass(DeadlineExceeded, TimeoutError)
    assert issubclass(DeadlineExceeded, OSError)


# -- RetryPolicy -------------------------------------------------------------


def test_retry_delays_are_deterministic_and_bounded():
    p = RetryPolicy(attempts=5, base_delay_s=0.1, multiplier=2.0,
                    max_delay_s=0.5, jitter=0.5, seed=7)
    a = [p.delay(i, key="x") for i in range(1, 5)]
    b = [p.delay(i, key="x") for i in range(1, 5)]
    assert a == b  # same seed/key/attempt -> same schedule
    assert a != [p.delay(i, key="y") for i in range(1, 5)]  # keyed
    for i, d in enumerate(a, start=1):
        cap = min(0.5, 0.1 * 2.0 ** (i - 1))
        assert 0.5 * cap <= d <= cap  # jitter shrinks, never grows


def test_retry_run_retries_then_raises():
    calls, slept = [], []
    p = RetryPolicy(attempts=3, base_delay_s=0.01)

    def fails():
        calls.append(1)
        raise ValueError("nope")

    with pytest.raises(ValueError):
        p.run(fails, retry_on=(ValueError,), sleep=slept.append)
    assert len(calls) == 3 and len(slept) == 2

    calls.clear()

    def flaky():
        calls.append(1)
        if len(calls) < 2:
            raise ValueError("once")
        return "ok"

    assert p.run(flaky, retry_on=(ValueError,),
                 sleep=slept.append) == "ok"
    assert len(calls) == 2


def test_retry_run_respects_deadline():
    """A backoff the budget cannot cover is skipped: the last error
    surfaces immediately instead of sleeping past the deadline."""
    p = RetryPolicy(attempts=10, base_delay_s=5.0, max_delay_s=5.0)
    calls, slept = [], []

    def fails():
        calls.append(1)
        raise ValueError("nope")

    t0 = time.monotonic()
    with pytest.raises(ValueError):
        p.run(fails, retry_on=(ValueError,),
              deadline=Deadline.after(0.2), sleep=slept.append)
    assert time.monotonic() - t0 < 1.0
    assert len(calls) == 1 and slept == []  # no 5 s sleep attempted

    # an already-dead budget never even tries
    with pytest.raises(DeadlineExceeded):
        p.run(fails, retry_on=(ValueError,),
              deadline=Deadline.after(0.0))


# -- CircuitBreaker ----------------------------------------------------------


def test_breaker_full_lifecycle_with_metrics():
    now = [0.0]
    brk = CircuitBreaker("t-lifecycle", failure_threshold=3,
                         reset_timeout_s=10.0, clock=lambda: now[0])
    base = {k: TRANSITIONS[f"t-lifecycle:{k}"]
            for k in ("open", "half_open", "close", "rejected")}

    def delta(k):
        return TRANSITIONS[f"t-lifecycle:{k}"] - base[k]

    assert brk.state == "closed" and brk.allow()
    brk.record_failure()
    brk.record_failure()
    assert brk.state == "closed"  # below threshold
    brk.record_success()  # success resets the consecutive count
    brk.record_failure()
    brk.record_failure()
    assert brk.state == "closed"
    brk.record_failure()  # third consecutive: trip
    assert brk.state == "open" and delta("open") == 1
    assert not brk.allow() and delta("rejected") >= 1

    now[0] = 10.1  # reset timeout elapses -> half-open
    assert brk.allow()  # the single probe
    assert delta("half_open") == 1
    assert not brk.allow()  # second concurrent probe rejected
    brk.record_failure()  # probe failed -> re-open
    assert brk.state == "open" and delta("open") == 2

    now[0] = 20.3
    assert brk.allow()
    brk.record_success()  # probe succeeded -> closed
    assert brk.state == "closed" and delta("close") == 1
    assert brk.allow()


# -- faultinject -------------------------------------------------------------


def test_faultinject_disarmed_is_noop():
    FI.fire("some.point")  # nothing armed: no raise
    assert FI.garble("some.point", b"abc") == b"abc"


def test_faultinject_counting_and_selectors():
    FI.arm("p", exc=RuntimeError, every=2, after=1, times=2)
    # hit 1 skipped (after=1); then every other: hits 2, 4 fire; times=2
    fired = []
    for i in range(1, 8):
        try:
            FI.fire("p")
        except RuntimeError:
            fired.append(i)
    assert fired == [2, 4]
    assert FI.hits("p") == 7


def test_faultinject_key_matching():
    FI.arm("peer", exc=ConnectionResetError, key="10.0.0.2:99")
    FI.fire("peer", key="10.0.0.1:99")  # other peer: clean
    with pytest.raises(ConnectionResetError):
        FI.fire("peer", key="10.0.0.2:99")


def test_faultinject_delay_and_garble_deterministic():
    FI.arm("slow", delay_s=0.05)
    t0 = time.monotonic()
    FI.fire("slow")
    assert time.monotonic() - t0 >= 0.05

    FI.arm("wire", garble=True)
    FI.set_seed(42)
    data = bytes(range(32))
    g1 = FI.garble("wire", data)
    assert g1 != data and len(g1) == len(data)
    FI.reset()
    FI.arm("wire", garble=True)
    FI.set_seed(42)
    assert FI.garble("wire", data) == g1  # seeded: replays exactly


# -- SidecarClient failure contract ------------------------------------------


class _HungServer:
    """Accepts connections, reads frames, never responds — the wedged
    sidecar the r5 client hung on forever."""

    def __init__(self):
        self.srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(4)
        self.address = self.srv.getsockname()
        self.conns = []
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            self.conns.append(conn)

    def kill_conns(self):
        for c in self.conns:
            try:
                c.close()
            except OSError:
                pass

    def close(self):
        self.kill_conns()
        try:
            self.srv.close()
        except OSError:
            pass


def _fast_client(address, call_timeout=0.4):
    return SidecarClient(
        address, connect_timeout=1.0, call_timeout=call_timeout,
        retry=RetryPolicy(attempts=2, base_delay_s=0.01,
                          max_delay_s=0.05),
    )


def test_sidecar_hung_server_times_out_within_deadline():
    srv = _HungServer()
    try:
        c = _fast_client(srv.address)
        t0 = time.monotonic()
        with pytest.raises(SidecarUnavailable):
            c.ping()
        assert time.monotonic() - t0 < 2.0  # bounded, not forever
        c.close()
    finally:
        srv.close()


def test_sidecar_killed_mid_request_fails_closed_fast():
    """A connection dying under an in-flight call surfaces the typed
    error IMMEDIATELY (EOF), long before the call timeout."""
    srv = _HungServer()
    try:
        c = SidecarClient(
            srv.address, connect_timeout=1.0, call_timeout=5.0,
            retry=RetryPolicy(attempts=1),  # surface the first EOF
        )
        errs = []

        def call():
            try:
                c.ping()
            except SidecarUnavailable as e:
                errs.append(e)

        t = threading.Thread(target=call)
        t0 = time.monotonic()
        t.start()
        time.sleep(0.15)  # let the request get in flight
        srv.kill_conns()  # sidecar dies mid-request
        t.join(timeout=3.0)
        assert not t.is_alive()
        assert errs and time.monotonic() - t0 < 3.0
        c.close()
    finally:
        srv.close()


class _DesyncServer:
    """Replies with a MISMATCHED request id — the stream-desync bug
    class that used to poison every later call."""

    def __init__(self):
        self.srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(4)
        self.address = self.srv.getsockname()
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _serve(self, conn):
        try:
            while True:
                frame = P.read_frame(conn)
                if frame is None:
                    return
                mtype, rid, _ = frame
                conn.sendall(P.pack_frame(
                    mtype | P.RESP_FLAG, rid + 1000,
                    bytes([P.STATUS_OK]) + b"\x01\x00",
                ))
        except (ValueError, OSError):
            pass
        finally:
            conn.close()

    def close(self):
        try:
            self.srv.close()
        except OSError:
            pass


def test_sidecar_desynced_reply_raises_typed_error():
    srv = _DesyncServer()
    try:
        c = _fast_client(srv.address)
        with pytest.raises(SidecarUnavailable):
            c.ping()
        c.close()
    finally:
        srv.close()


def test_sidecar_reconnects_and_replays_committee():
    """THE acceptance scenario: server dies after the committee upload;
    the next call fails typed and bounded; a replacement server on the
    same address serves agg_verify WITHOUT a fresh set_committee —
    the client replayed it on reconnect."""
    from harmony_tpu.consensus.mask import Mask
    from harmony_tpu.ref import bls as RB
    from harmony_tpu.sidecar.server import SidecarServer

    msg = b"0123456789abcdef0123456789abcdef"
    sks = [RB.keygen(bytes([40 + i])) for i in range(4)]
    pks = [RB.pubkey(sk) for sk in sks]
    sigs = [RB.sign(sk, msg) for sk in sks]
    agg = RB.aggregate_sigs([sigs[0], sigs[2], sigs[3]])
    mask = Mask(pks)
    for i in (0, 2, 3):
        mask.set_bit(i, True)

    srv = SidecarServer().start()
    host, port = srv.address
    c = _fast_client(srv.address, call_timeout=5.0)
    c.set_committee(9, 1, [RB.pubkey_to_bytes(p) for p in pks])
    assert c.agg_verify(9, 1, msg, mask.mask_bytes(),
                        RB.sig_to_bytes(agg))

    srv.stop()
    t0 = time.monotonic()
    with pytest.raises(SidecarUnavailable):
        c.ping()
    assert time.monotonic() - t0 < 4.0

    # replacement sidecar on the SAME address knows NO committees...
    srv2 = SidecarServer(host=host, port=port).start()
    try:
        # ...yet agg_verify succeeds: the client replays (9, 1) on
        # reconnect before letting the request through
        assert c.agg_verify(9, 1, msg, mask.mask_bytes(),
                            RB.sig_to_bytes(agg))
        # and a wrong bitmap still fails THROUGH the replayed state
        mask.set_bit(1, True)
        assert not c.agg_verify(9, 1, msg, mask.mask_bytes(),
                                RB.sig_to_bytes(agg))
        c.close()
    finally:
        srv2.stop()


def test_sidecar_injected_garbage_frame_drops_connection():
    """A garbage frame (via the sidecar.frame injection point) kills
    the connection — fail closed — and the next call heals by
    redialing."""
    from harmony_tpu.sidecar.server import SidecarServer

    srv = SidecarServer().start()
    try:
        c = _fast_client(srv.address, call_timeout=1.0)
        FI.arm("sidecar.frame", exc=ValueError, every=1, times=1)
        # the injected fault may land on this call (dropped + retried
        # on a fresh connection) — the call must still come back typed
        try:
            c.ping()
        except SidecarUnavailable:
            pass
        FI.reset()
        assert c.ping() == P.VERSION  # healed
        c.close()
    finally:
        srv.stop()


# -- webhooks bounded retry --------------------------------------------------


def test_webhook_retries_through_transient_failures():
    import http.server

    from harmony_tpu.webhooks import http_post_hook

    got = []

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers["Content-Length"])
            got.append(self.rfile.read(n))
            self.send_response(200)
            self.end_headers()

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_port}/hook"
    try:
        # two injected failures, three attempts: delivery must land
        FI.arm("webhook.post", exc=ConnectionResetError, times=2)
        hook = http_post_hook(
            url, timeout=2.0,
            retry=RetryPolicy(attempts=3, base_delay_s=0.01,
                              max_delay_s=0.05),
        )
        hook({"event": "double_sign"})
        deadline = time.monotonic() + 5.0
        while not got and time.monotonic() < deadline:
            time.sleep(0.01)
        assert got and b"double_sign" in got[0]

        # a permanently failing endpoint: logged drop, no delivery,
        # and the hook thread terminates
        FI.reset()
        FI.arm("webhook.post", exc=ConnectionResetError)
        before = len(got)
        hook({"event": "view_change"})
        time.sleep(0.3)
        assert len(got) == before
    finally:
        httpd.shutdown()
        httpd.server_close()
