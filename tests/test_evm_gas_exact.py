"""Gas-exact SSTORE net metering (EIP-2200) + access-list txs (EIP-2930).

The Istanbul matrix below is the EIP-2200 specification's transition
table (all 17 value sequences over original values 0/1), derived from
the spec rules the reference's go-ethereum fork implements in
core/vm/gas_table.go: no-op = SLOAD-like 800; clean set 20000; clean
reset 5000 (+15000 clear refund); dirty writes SLOAD-like with refund
bookkeeping (un-clear -15000, re-clear +15000, restore-to-original
+19200/+4200).  Each code is N x (PUSH1 v PUSH1 0 SSTORE) + STOP, so
expected totals include 6 gas of PUSHes per store.

The Berlin variants re-price: SLOAD-like 100, reset 2900, plus the
EIP-2929 cold-slot surcharge of 2100 on first touch unless the slot is
pre-warmed by an EIP-2930 access list.
"""

import pytest

from harmony_tpu.core.state import StateDB
from harmony_tpu.core.state_processor import (
    StateProcessor,
    intrinsic_gas,
)
from harmony_tpu.core.types import Transaction
from harmony_tpu.core.vm import EVM, Env, VMError

A = b"\xaa" * 20
C = b"\xcc" * 20
SLOT = b"\x00" * 32


def _sstore_code(seq):
    code = b""
    for v in seq:
        code += bytes([0x60, v, 0x60, 0x00, 0x55])
    return code + b"\x00"  # STOP


def _run(orig, seq, berlin, prewarm=True, gas=10**6):
    state = StateDB()
    state.add_balance(A, 10**18)
    if orig:
        state.storage_set(C, SLOT, orig)
    state.set_code(C, _sstore_code(seq))
    evm = EVM(state, Env(block_num=5, chain_id=2), origin=A,
              gas_price=1, berlin=berlin)
    evm.warm_addrs.add(C)
    if berlin and prewarm:
        evm.warm_slots.add((C, SLOT))
    ok, gas_left, _ = evm.call(A, C, 0, b"", gas)
    assert ok
    return gas - gas_left, evm.refund


# (original, value sequence, istanbul gas, istanbul refund) — the
# EIP-2200 spec matrix; gas includes 6/store of PUSH overhead
EIP2200_MATRIX = [
    (0, (0, 0), 1612, 0),
    (0, (0, 1), 20812, 0),
    (0, (1, 0), 20812, 19200),
    (0, (1, 2), 20812, 0),
    (0, (1, 1), 20812, 0),
    (1, (0, 0), 5812, 15000),
    (1, (0, 1), 5812, 4200),
    (1, (0, 2), 5812, 0),
    (1, (2, 0), 5812, 15000),
    (1, (2, 3), 5812, 0),
    (1, (2, 1), 5812, 4200),
    (1, (2, 2), 5812, 0),
    (1, (1, 0), 5812, 15000),
    (1, (1, 2), 5812, 0),
    (1, (1, 1), 1612, 0),
    # clean/dirty is judged per-store as current == original (not a
    # sticky flag): writing a slot back to its original re-cleans it,
    # so the third store below re-charges the full clean cost — the
    # official EIP-2200 vectors (usage 40818 / 10818)
    (0, (1, 0, 1), 40818, 19200),
    (1, (0, 1, 0), 10818, 19200),
]


@pytest.mark.parametrize("orig,seq,want_gas,want_refund", EIP2200_MATRIX)
def test_eip2200_istanbul_matrix(orig, seq, want_gas, want_refund):
    used, refund = _run(orig, seq, berlin=False)
    assert (used, refund) == (want_gas, want_refund)


def _berlin_expect(orig, seq):
    """Berlin re-pricing of the same rules (reference:
    core/vm/operations_acl.go): SLOAD-like 100, reset 2900,
    restore refunds 19900/2800; slot pre-warmed."""
    SLOAD_L, SET, RESET, CLEAR = 100, 20000, 2900, 15000
    gas, refund, cur = 0, 0, orig
    for v in seq:
        gas += 6  # two PUSH1
        if v == cur:
            gas += SLOAD_L
        elif cur == orig:
            if orig == 0:
                gas += SET
            else:
                gas += RESET
                if v == 0:
                    refund += CLEAR
        else:
            gas += SLOAD_L
            if orig != 0:
                if cur == 0:
                    refund -= CLEAR
                if v == 0:
                    refund += CLEAR
            if v == orig:
                refund += (SET - SLOAD_L) if orig == 0 else (RESET - SLOAD_L)
        cur = v
    return gas, refund


@pytest.mark.parametrize("orig,seq,_ig,_ir", EIP2200_MATRIX)
def test_eip2200_berlin_repricing(orig, seq, _ig, _ir):
    used, refund = _run(orig, seq, berlin=True, prewarm=True)
    assert (used, refund) == _berlin_expect(orig, seq)


def test_berlin_cold_slot_surcharge_on_sstore():
    warm_used, _ = _run(0, (1,), berlin=True, prewarm=True)
    cold_used, _ = _run(0, (1,), berlin=True, prewarm=False)
    assert cold_used - warm_used == 2100  # COLD_SLOAD exactly once


def test_sstore_stipend_sentry():
    """EIP-2200: SSTORE must fail if gas left <= 2300 so the call
    stipend can never write state."""
    state = StateDB()
    state.add_balance(A, 10**18)
    state.set_code(C, _sstore_code((1,)))
    evm = EVM(state, Env(block_num=5, chain_id=2), origin=A,
              gas_price=1, berlin=False)
    ok, gas_left, _ = evm.call(A, C, 0, b"", 2306)  # 6 for pushes
    assert not ok  # the SSTORE saw exactly 2300 left -> rejected
    assert state.storage_get(C, SLOT) == 0


# -- EIP-2930 access-list transactions ----------------------------------


def test_access_list_intrinsic_gas():
    tx = Transaction(
        nonce=0, gas_price=1, gas_limit=100_000, shard_id=0,
        to_shard=0, to=C, value=0, tx_type=1,
        access_list=[(C, [SLOT, b"\x01" * 32]), (A, [])],
    )
    assert intrinsic_gas(tx) == 21_000 + 2 * 2400 + 2 * 1900


def test_access_list_prewarms_storage():
    """The same contract call must cost exactly the cold-vs-warm slot
    difference less when the slot rides in the tx access list."""
    from harmony_tpu.crypto_ecdsa import ECDSAKey

    key = ECDSAKey.from_seed(b"gas-exact-seed")
    sender = key.address()

    def run(tx_type, access_list):
        state = StateDB()
        state.add_balance(sender, 10**18)
        state.set_code(C, _sstore_code((1,)))
        proc = StateProcessor(chain_id=2, shard_id=0)
        tx = Transaction(
            nonce=0, gas_price=1, gas_limit=200_000, shard_id=0,
            to_shard=0, to=C, value=0, tx_type=tx_type,
            access_list=access_list,
        ).sign(key, 2)
        receipt, _ = proc.apply_transaction(state, tx, 1, 0)
        assert receipt.status == 1
        return receipt.gas_used

    plain = run(0, [])
    listed = run(1, [(C, [SLOT])])
    # listed pays 2400+1900 intrinsic but saves the 2100 cold-slot
    # surcharge at execution time
    assert listed - plain == 2400 + 1900 - 2100


def test_typed_tx_roundtrips_and_legacy_hash_stable():
    from harmony_tpu.core import rawdb

    legacy = Transaction(
        nonce=1, gas_price=2, gas_limit=30_000, shard_id=0, to_shard=0,
        to=C, value=5,
    )
    typed = Transaction(
        nonce=1, gas_price=2, gas_limit=30_000, shard_id=0, to_shard=0,
        to=C, value=5, tx_type=1, access_list=[(A, [SLOT])],
    )
    assert legacy.signing_bytes(2) != typed.signing_bytes(2)
    for tx in (legacy, typed):
        back = rawdb.decode_tx(rawdb.encode_tx(tx, 2))
        assert back.signing_bytes(2) == tx.signing_bytes(2)
        assert back.tx_type == tx.tx_type
        assert back.access_list == tx.access_list
