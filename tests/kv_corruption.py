"""Shared KV corruption fixtures: every way a crash (or bad disk) can
mangle the log, with the recovery verdict each backend must reach.

Used by tests/test_kv_corruption.py (FileKV × NativeKV parametrized)
and tests/test_kv_native.py — the native store must pass the SAME
torn-tail / torn-value / torn-batch / implausible-header suite as the
Python twin, byte for byte (ISSUE 12 satellite)."""

from __future__ import annotations

import struct

_TOMB = 0xFFFFFFFF
_BEGIN = 0xFFFFFFFE
_COMMIT = 0xFFFFFFFD


def rec(key: bytes, value: bytes | None) -> bytes:
    """One on-disk record (None = tombstone)."""
    if value is None:
        return struct.pack("<II", len(key), _TOMB) + key
    return struct.pack("<II", len(key), len(value)) + key + value


def marker(kind: int, count: int) -> bytes:
    return struct.pack("<II", kind, count)


def seed_store(factory, path: str):
    """A healthy baseline: two plain records + one committed batch.
    Closed before returning — corruption cases append raw bytes."""
    db = factory(path)
    db.put(b"alpha", b"1")
    db.put(b"beta", b"22")
    from harmony_tpu.core.kv import WriteBatch

    batch = WriteBatch()
    batch.put(b"gamma", b"333")
    batch.delete(b"beta")
    db.write_batch(batch)
    db.flush()
    db.close()


# Each case: (name, raw bytes appended to the healthy log,
#             {key: expected value-or-None after reopen})
# The baseline keys alpha=1, gamma=333 must ALWAYS survive; beta was
# batch-deleted and must stay gone.
BASELINE = {b"alpha": b"1", b"beta": None, b"gamma": b"333"}

CASES = [
    (
        "torn_header_fragment",
        b"\x09\x00\x00\x00\x05",  # 5 bytes of an 8-byte header
        {b"torn": None},
    ),
    (
        "torn_key",
        struct.pack("<II", 8, 4) + b"tor",  # key cut short
        {b"tor": None, b"torn": None},
    ),
    (
        "torn_value",
        struct.pack("<II", 4, 100) + b"torn" + b"abc",  # 3/100 bytes
        {b"torn": None},
    ),
    (
        "implausible_klen",
        # klen 0xFFFFFFF0 == _KLEN_MAX: hits the implausible-header
        # rejection branch itself, not the generic EOF bounds check
        b"\xf0\xff\xff\xff" + b"\x01\x00\x00\x00" + b"xx",
        {b"xx": None},
    ),
    (
        "implausible_vlen_middle",
        # a record whose vlen points past EOF, FOLLOWED by a valid
        # record: the poisoned middle must not mis-frame the rest
        # (the tail record is unreachable — replay truncates at the
        # corruption — but the baseline must survive untouched)
        struct.pack("<II", 3, 0x7FFFFFFF) + b"bad"
        + rec(b"after", b"tail"),
        {b"bad": None, b"after": None},
    ),
    (
        "batch_without_commit",
        marker(_BEGIN, 2) + rec(b"half", b"1") + rec(b"way", b"2"),
        {b"half": None, b"way": None},
    ),
    (
        "batch_torn_inside",
        marker(_BEGIN, 2) + rec(b"half", b"1")
        + struct.pack("<II", 4, 50) + b"way",
        {b"half": None, b"way": None},
    ),
    (
        "batch_count_mismatch",
        marker(_BEGIN, 3) + rec(b"half", b"1") + marker(_COMMIT, 1),
        {b"half": None},
    ),
    (
        "commit_without_begin",
        marker(_COMMIT, 1) + rec(b"ghost", b"1"),
        {b"ghost": None},
    ),
    (
        "complete_batch_then_torn_batch",
        marker(_BEGIN, 2) + rec(b"good1", b"A") + rec(b"good2", b"B")
        + marker(_COMMIT, 2)
        + marker(_BEGIN, 1) + rec(b"lost", b"C"),
        {b"good1": b"A", b"good2": b"B", b"lost": None},
    ),
    (
        "batch_with_tombstone_commits",
        marker(_BEGIN, 2) + rec(b"alpha", None) + rec(b"neu", b"N")
        + marker(_COMMIT, 2),
        {b"alpha": None, b"neu": b"N"},
    ),
]


def run_case(factory, path: str, tail: bytes, expect: dict):
    """Append ``tail`` to a healthy log, reopen via ``factory``, check
    the verdict + that the store still accepts writes and survives
    another clean reopen."""
    seed_store(factory, path)
    with open(path, "ab") as f:
        f.write(tail)
    db = factory(path)
    try:
        want = dict(BASELINE)
        want.update(expect)
        for key, value in want.items():
            got = db.get(key)
            assert got == value, (
                f"{key!r}: got {got!r}, want {value!r}"
            )
        db.put(b"post", b"crash")
        assert db.get(b"post") == b"crash"
        db.flush()
    finally:
        db.close()
    db = factory(path)
    try:
        assert db.get(b"post") == b"crash"
        for key, value in want.items():
            assert db.get(key) == value
    finally:
        db.close()
