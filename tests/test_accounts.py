"""Accounts layer: HD derivation, ABI codec, KMS envelopes
(VERDICT r2 missing #10 — reference: accounts/, internal/blsgen/kms.go)."""

import pytest

from harmony_tpu.accounts import (
    abi_decode,
    abi_encode,
    derive_account,
    encode_call,
    function_selector,
    mnemonic_to_seed,
)
from harmony_tpu.accounts.hd import HARDENED, HDKey
from harmony_tpu.blsgen_kms import (
    AwsKMSProvider,
    KMSError,
    LocalKMSProvider,
    load_kms_key,
    save_kms_key,
)

# BIP-39 reference vector (Trezor test vectors, public):
# the all-"abandon" mnemonic with passphrase TREZOR
MNEMONIC = ("abandon abandon abandon abandon abandon abandon abandon "
            "abandon abandon abandon abandon about")
SEED_HEX = ("c55257c360c07c72029aebc1b53c05ed0362ada38ead3e3e9efa3708e534"
            "95531f09a6987599d18264c1e1c92f2cf141630c7a3c4ab7c81b2f00169"
            "8e7463b04")


def test_bip39_seed_matches_reference_vector():
    assert mnemonic_to_seed(MNEMONIC, "TREZOR").hex() == SEED_HEX


def test_bip32_master_and_children_deterministic():
    m = HDKey.master(mnemonic_to_seed(MNEMONIC))
    a = m.child(0 | HARDENED).child(5)
    b = m.child(0 | HARDENED).child(5)
    assert a.key == b.key and a.chain_code == b.chain_code
    assert a.key != m.child(0 | HARDENED).child(6).key
    # path parser agrees with manual derivation
    via_path = m.derive_path("m/0'/5")
    assert via_path.key == a.key


def test_harmony_account_derivation():
    k0 = derive_account(MNEMONIC, 0)
    k1 = derive_account(MNEMONIC, 1)
    assert k0.address() != k1.address()
    assert derive_account(MNEMONIC, 0).address() == k0.address()
    # a signature from the derived key recovers its address
    digest = b"\x11" * 32
    sig = k0.sign(digest)
    from harmony_tpu.crypto_ecdsa import verify

    assert verify(digest, sig, k0.address())


def test_abi_encode_static_and_selector():
    addr = b"\xaa" * 20
    data = encode_call(
        "Delegate(address,address,uint256)", [addr, b"\xbb" * 20, 500]
    )
    assert data[:4] == function_selector("Delegate(address,address,uint256)")
    assert len(data) == 4 + 96
    assert data[4:36] == addr.rjust(32, b"\x00")
    assert int.from_bytes(data[68:100], "big") == 500
    # matches the vm-side parser
    from harmony_tpu.core.vm import parse_stake_msg

    kind, delegator, validator, amount = parse_stake_msg(addr, data)
    assert (kind, delegator, amount) == ("delegate", addr, 500)
    assert validator == b"\xbb" * 20


def test_abi_dynamic_roundtrip():
    types = ["uint256", "string", "address[]", "bytes"]
    values = [
        7, "hello world", [b"\x01" * 20, b"\x02" * 20], b"\xde\xad",
    ]
    blob = abi_encode(types, values)
    assert abi_decode(types, blob) == values
    # int + bytes32 + bool + fixed array
    t2 = ["int256", "bytes32", "bool", "uint8[3]"]
    v2 = [-42, b"\x09" * 32, True, [1, 2, 3]]
    assert abi_decode(t2, abi_encode(t2, v2)) == v2


def test_abi_range_checks():
    with pytest.raises(ValueError):
        abi_encode(["uint8"], [256])
    with pytest.raises(ValueError):
        abi_encode(["address"], [b"\x01" * 19])


def test_kms_envelope_roundtrip(tmp_path):
    master = tmp_path / "master.key"
    LocalKMSProvider.generate_master(str(master))
    prov = LocalKMSProvider(str(master))
    sk = bytes(range(32))
    keyfile = tmp_path / "validator.bls"
    save_kms_key(str(keyfile), sk, prov)
    assert load_kms_key(str(keyfile), prov) == sk
    # a different master key cannot open it
    other = tmp_path / "other.key"
    LocalKMSProvider.generate_master(str(other))
    with pytest.raises(KMSError):
        load_kms_key(str(keyfile), LocalKMSProvider(str(other)))
    # tampered ciphertext rejected
    import json

    env = json.loads(keyfile.read_text())
    env["ciphertext"] = ("00" * 32)
    keyfile.write_text(json.dumps(env))
    with pytest.raises(KMSError):
        load_kms_key(str(keyfile), prov)


def test_aws_provider_states_unavailability():
    with pytest.raises(KMSError):
        AwsKMSProvider(region="us-east-1")
