"""Subprocess isolation for the XLA-heavy crypto parity tier.

test_ops_pairing_bls / test_ref_pairing_bls compile pairing-shaped XLA
programs that have segfaulted the CPU compiler on this image mid-suite
(conftest.py tail; VERDICT r2 weak #10 asked for a crash-free suite).
Each module runs here in its own interpreter: a segfault or timeout is
ONE red test naming the module, and every other suite result survives.
"""

import os
import pathlib
import subprocess
import sys

import pytest

ROOT = pathlib.Path(__file__).parent.parent
BUDGET_S = int(os.environ.get("OPS_HEAVY_BUDGET", "5400"))


def _run_module(name: str, attempts: int = 2):
    """One isolated run, retried ONCE if the interpreter crashes —
    the XLA:CPU fault is intermittent (same inputs pass on retry);
    a deterministic test FAILURE is never retried."""
    env = dict(os.environ)
    env["OPS_INPROC"] = "1"
    last_crash = ""
    for attempt in range(attempts):
        try:
            proc = subprocess.run(
                [sys.executable, "-m", "pytest", f"tests/{name}", "-q",
                 "--no-header", "-p", "no:cacheprovider"],
                cwd=ROOT,
                env=env,
                capture_output=True,
                text=True,
                timeout=BUDGET_S,
            )
        except subprocess.TimeoutExpired as e:
            pytest.fail(
                f"{name} exceeded {BUDGET_S}s in isolation "
                f"(cold XLA compiles; raise OPS_HEAVY_BUDGET to extend): "
                f"{(e.stdout or '')[-300:]}"
            )
        if proc.returncode < 0:
            last_crash = (
                f"{name} CRASHED the interpreter (signal "
                f"{-proc.returncode} — the known XLA:CPU compiler fault "
                f"on this image); tail: {proc.stderr[-500:]}"
            )
            continue
        assert proc.returncode == 0, (
            f"{name} failed in isolation:\n{proc.stdout[-1500:]}"
        )
        return
    pytest.fail(f"crashed {attempts}x: {last_crash}")


def test_ops_pairing_bls_isolated():
    _run_module("test_ops_pairing_bls.py")


def test_ref_pairing_bls_isolated():
    _run_module("test_ref_pairing_bls.py")
