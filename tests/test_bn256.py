"""alt_bn128 precompiles + blake2f (reference: core/vm/contracts.go
bn256Add/ScalarMul/Pairing via cgo — VERDICT r2 missing #6's bn256
hole; crypto_bn256.py is the bigint twin)."""

import hashlib
import struct

import pytest

from harmony_tpu import crypto_bn256 as BN
from harmony_tpu.core.vm import PRECOMPILES, VMError

# EIP-196's doubling vector: 2 * (1, 2)
TWO_G = (
    1368015179489954701390400359078579693043519447331113978918064868415326638035,
    9918110051302171585080402603319702774565515993150576347155970296011118125764,
)


def test_g1_double_matches_known_vector():
    assert BN.g1_mul(BN.G1_GEN, 2) == TWO_G
    assert BN.g1_add(BN.G1_GEN, BN.G1_GEN) == TWO_G


def test_pairing_bilinear_and_order():
    e1 = BN.pairing(BN.G1_GEN, BN.G2_GEN)
    assert e1 != BN.F12_ONE
    assert BN.f12_pow(e1, BN.N) == BN.F12_ONE
    assert BN.pairing(BN.g1_mul(BN.G1_GEN, 3), BN.G2_GEN) == \
        BN.pairing(BN.G1_GEN, BN.g2_mul(BN.G2_GEN, 3))


def _enc_g1(pt):
    x, y = pt if pt is not None else (0, 0)
    return x.to_bytes(32, "big") + y.to_bytes(32, "big")


def _enc_g2(pt):
    (xr, xi), (yr, yi) = pt
    return b"".join(v.to_bytes(32, "big") for v in (xi, xr, yi, yr))


def test_precompile_bn256_add_and_mul():
    add = PRECOMPILES[6]
    gas, out = add(_enc_g1(BN.G1_GEN) + _enc_g1(BN.G1_GEN), 10_000)
    assert out == _enc_g1(TWO_G)
    # infinity + P = P; short input right-padded with zeros
    gas, out = add(_enc_g1(BN.G1_GEN), 10_000)
    assert out == _enc_g1(BN.G1_GEN)
    mul = PRECOMPILES[7]
    gas, out = mul(
        _enc_g1(BN.G1_GEN) + (2).to_bytes(32, "big"), 10_000
    )
    assert out == _enc_g1(TWO_G)
    # off-curve input rejected
    bad = (1).to_bytes(32, "big") + (3).to_bytes(32, "big")
    with pytest.raises(VMError):
        add(bad + _enc_g1(BN.G1_GEN), 10_000)
    with pytest.raises(VMError):
        add(_enc_g1(BN.G1_GEN) + _enc_g1(BN.G1_GEN), 10)  # oog


def test_precompile_bn256_pairing():
    pairing = PRECOMPILES[8]
    neg = (BN.G1_GEN[0], (-BN.G1_GEN[1]) % BN.P)
    good = (
        _enc_g1(BN.G1_GEN) + _enc_g2(BN.G2_GEN)
        + _enc_g1(neg) + _enc_g2(BN.G2_GEN)
    )
    gas, out = pairing(good, 200_000)
    assert out == (1).to_bytes(32, "big")
    bad = _enc_g1(BN.G1_GEN) + _enc_g2(BN.G2_GEN)
    gas, out = pairing(bad, 200_000)
    assert out == (0).to_bytes(32, "big")
    # empty input: vacuous product == 1 (EIP-197)
    gas, out = pairing(b"", 50_000)
    assert out == (1).to_bytes(32, "big")
    with pytest.raises(VMError):
        pairing(good[:100], 200_000)  # not a multiple of 192
    # G2 point off the subgroup rejected: use a curve point that is
    # not order-n (double of an off-subgroup point construction is
    # expensive; tamper y to leave the curve instead)
    tampered = bytearray(good)
    tampered[64 + 127] ^= 1
    with pytest.raises(VMError):
        pairing(bytes(tampered), 200_000)


def test_precompile_blake2f_matches_hashlib():
    # one-block blake2b("abc") via the F precompile
    h = list(BN._BLAKE2B_IV)
    h[0] ^= 0x01010000 ^ 64
    block = b"abc".ljust(128, b"\x00")
    data = (
        (12).to_bytes(4, "big")
        + struct.pack("<8Q", *h)
        + block
        + struct.pack("<2Q", 3, 0)
        + b"\x01"
    )
    gas, out = PRECOMPILES[9](data, 1000)
    assert out == hashlib.blake2b(b"abc").digest()
    assert gas == 1000 - 12
    with pytest.raises(VMError):
        PRECOMPILES[9](data[:-1], 1000)  # wrong length
    with pytest.raises(VMError):
        PRECOMPILES[9](data[:-1] + b"\x02", 1000)  # bad flag
