"""Concurrency/race coverage (SURVEY §5's race-detector analog tier):
hammer the shared structures from threads the way the live node does —
consensus pump vs background downloader on the chain, RPC threads vs
the pump on the pool, gossip threads on the hosts."""

import threading
import time

from harmony_tpu.core.blockchain import Blockchain
from harmony_tpu.core.genesis import dev_genesis
from harmony_tpu.core.kv import MemKV
from harmony_tpu.core.tx_pool import TxPool
from harmony_tpu.core.types import Transaction
from harmony_tpu.crypto_ecdsa import ECDSAKey
from harmony_tpu.node.worker import Worker
from harmony_tpu.p2p.host import TCPHost

CHAIN_ID = 2


def test_concurrent_insert_chain_is_serialized_and_idempotent():
    """The consensus pump and the background downloader can both hold
    the same blocks (node._spin_up_sync); racing inserts must neither
    corrupt the head nor double-apply state."""
    genesis, keys, _ = dev_genesis()
    source = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    pool = TxPool(CHAIN_ID, 0, source.state)
    worker = Worker(source, pool)
    to = b"\x0c" * 20
    blocks = []
    for i in range(6):
        tx = Transaction(
            nonce=i, gas_price=1, gas_limit=25_000, shard_id=0,
            to_shard=0, to=to, value=100,
        ).sign(keys[0], CHAIN_ID)
        pool.add(tx)
        block = worker.propose_block(view_id=i + 1)
        source.insert_chain([block], verify_seals=False)
        pool.drop_applied()
        blocks.append(block)

    target = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    errors = []

    def racer():
        try:
            for b in blocks:
                target.insert_chain([b], verify_seals=False)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=racer) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errors, errors
    assert target.head_number == 6
    assert target.state().balance(to) == 600  # applied exactly once
    assert target.current_header().hash() == blocks[-1].hash()


def test_pool_concurrent_add_and_pending():
    """RPC threads add while the pump reads pending/drops — counts must
    stay consistent (the pool is lock-protected)."""
    genesis, keys, _ = dev_genesis(n_accounts=8)
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    pool = TxPool(CHAIN_ID, 0, chain.state)
    to = b"\x0d" * 20
    n_threads, per_thread = 4, 12
    errors = []

    def adder(ti):
        try:
            for i in range(per_thread):
                tx = Transaction(
                    nonce=i, gas_price=1 + ti, gas_limit=25_000,
                    shard_id=0, to_shard=0, to=to, value=1,
                ).sign(keys[ti], CHAIN_ID)
                pool.add(tx)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def reader():
        for _ in range(50):
            pool.pending(max_txs=16)
            pool.stats()
            time.sleep(0.001)

    threads = [
        threading.Thread(target=adder, args=(ti,))
        for ti in range(n_threads)
    ] + [threading.Thread(target=reader)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errors, errors
    assert len(pool) == n_threads * per_thread
    pending, queued = pool.stats()
    assert pending == n_threads * per_thread and queued == 0


def test_host_concurrent_publish_no_loss():
    """Gossip from many threads across a TCP link: the seen-cache and
    peer registry are hit concurrently; every distinct message must
    arrive exactly once."""
    a, b = TCPHost("ca"), TCPHost("cb")
    try:
        a.connect(b.port)
        assert a.wait_for_peers(1) and b.wait_for_peers(1)
        got = []
        lock = threading.Lock()

        def handler(topic, payload, frm):
            with lock:
                got.append(payload)

        b.subscribe("t", handler)

        def publisher(ti):
            for i in range(20):
                a.publish("t", f"m-{ti}-{i}".encode())

        threads = [
            threading.Thread(target=publisher, args=(ti,))
            for ti in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and len(got) < 80:
            time.sleep(0.02)
        assert sorted(got) == sorted(
            f"m-{ti}-{i}".encode() for ti in range(4) for i in range(20)
        )
    finally:
        a.close()
        b.close()


def test_tx_pool_journal_restores_local_txs(tmp_path):
    """reference: core/tx_journal.go — LOCAL (RPC-submitted) txs
    survive a restart via the journal; remote/gossip txs and applied
    txs do not come back."""
    from harmony_tpu.core.blockchain import Blockchain
    from harmony_tpu.core.genesis import dev_genesis
    from harmony_tpu.core.kv import MemKV
    from harmony_tpu.core.tx_pool import TxPool
    from harmony_tpu.core.types import Transaction

    CHAIN_ID = 2
    genesis, keys, _ = dev_genesis()
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    path = str(tmp_path / "pool.txjournal")

    pool = TxPool(CHAIN_ID, 0, chain.state)
    assert pool.open_journal(path) == 0
    to = b"\x0f" * 20
    local1 = Transaction(nonce=0, gas_price=1, gas_limit=25_000,
                         shard_id=0, to_shard=0, to=to,
                         value=11).sign(keys[0], CHAIN_ID)
    local2 = Transaction(nonce=1, gas_price=1, gas_limit=25_000,
                         shard_id=0, to_shard=0, to=to,
                         value=22).sign(keys[0], CHAIN_ID)
    remote = Transaction(nonce=0, gas_price=1, gas_limit=25_000,
                         shard_id=0, to_shard=0, to=to,
                         value=33).sign(keys[1], CHAIN_ID)
    pool.add(local1, local=True)
    pool.add(local2, local=True)
    pool.add(remote)  # gossip: not journaled

    # "restart": a new pool over the same journal file
    pool2 = TxPool(CHAIN_ID, 0, chain.state)
    assert pool2.open_journal(path) == 2
    hashes = {t.hash(CHAIN_ID) for t, _ in pool2.pending(10)}
    assert hashes == {local1.hash(CHAIN_ID), local2.hash(CHAIN_ID)}

    # once mined, drop_applied rotates them OUT of the journal
    from harmony_tpu.node.worker import Worker

    worker = Worker(chain, pool2)
    block = worker.propose_block(view_id=1)
    chain.insert_chain([block], verify_seals=False)
    pool2.drop_applied()
    pool3 = TxPool(CHAIN_ID, 0, chain.state)
    assert pool3.open_journal(path) == 0
