"""Mainnet-shaped data completeness (VERDICT r4 #8): full epoch-gate
table, real sharding schedule eras, foundational-account genesis."""

import pytest

from harmony_tpu.accounts.bech32 import (
    address_to_one, bech32_decode, one_to_address,
)
from harmony_tpu.config import genesis_accounts as GA
from harmony_tpu.config.chain import (
    EPOCH_TBD, mainnet_config, testnet_config,
)
from harmony_tpu.config.sharding import MAINNET


# --- bech32 ----------------------------------------------------------------

def test_bech32_bip173_vectors():
    # valid checksums from the BIP-173 test set
    for v in ("A12UEL5L", "an83characterlonghumanreadablepartthatcontains"
              "thenumber1andtheexcludedcharactersbio1tt5tgs"):
        hrp, _ = bech32_decode(v)
        assert hrp
    for bad in ("A12UEL5X", "one1y0xcf40fg65n2ehm8fx5vda4thrkymhpg45ecq",
                "split1cheo2y9e2w"):
        with pytest.raises(ValueError):
            bech32_decode(bad)


def test_one_address_roundtrip():
    # the first foundational account (reference: foundational.go:5)
    one = "one1y0xcf40fg65n2ehm8fx5vda4thrkymhpg45ecj"
    raw = one_to_address(one)
    assert len(raw) == 20
    assert address_to_one(raw) == one


# --- gate table ------------------------------------------------------------

def test_mainnet_gates_transcribed():
    c = mainnet_config()
    assert c.chain_id == 1 and c.eth_compatible_chain_id == 1666600000
    # spot checks across the table (reference MainnetChainConfig)
    assert c.staking_epoch == 186
    assert c.pre_staking_epoch == 185
    assert c.two_seconds_epoch == 366
    assert c.istanbul_epoch == 314
    assert c.receipt_log_epoch == 101
    assert c.staking_precompile_epoch == 871
    assert c.chain_id_fix_epoch == 1323
    assert c.hip30_epoch == 1673
    assert c.hip32_epoch == 2152
    assert c.one_second_epoch == EPOCH_TBD
    # at least the reference's ~40 gates are present as data
    assert len(c.gate_table()) >= 40


def test_generic_gate_lookup():
    c = mainnet_config()
    assert not c.is_active("istanbul", 313)
    assert c.is_active("istanbul", 314)
    assert not c.is_active("allowlist", 999_999)  # TBD gate far future
    assert c.is_active("sha3_epoch", 725)  # _epoch suffix accepted


def test_accepts_cross_tx_one_epoch_late():
    c = mainnet_config()
    assert c.cross_shard_epoch == 28
    assert not c.accepts_cross_tx(28)  # fields exist, txs not accepted
    assert c.accepts_cross_tx(29)  # reference: AcceptsCrossTx


def test_testnet_config_shape():
    t = testnet_config()
    assert t.chain_id == 2 and t.staking_epoch == 2


# --- schedule eras ---------------------------------------------------------

def test_mainnet_schedule_eras():
    cases = [
        (0, (4, 150, 112)),
        (1, (4, 152, 112)),
        (5, (4, 200, 148)),
        (12, (4, 250, 170)),
        (54, (4, 250, 170)),
        (208, (4, 250, 130)),
        (231, (4, 250, 90)),
        (530, (4, 250, 50)),
        (725, (4, 250, 25)),
        (1673, (2, 200, 20)),
        (2152, (2, 200, 2)),
    ]
    for epoch, (shards, slots, hmy) in cases:
        inst = MAINNET.instance_for_epoch(epoch)
        got = (inst.num_shards, inst.slots_per_shard,
               inst.harmony_nodes_per_shard)
        assert got == (shards, slots, hmy), f"epoch {epoch}: {got}"


def test_hip16_slots_limit():
    assert MAINNET.instance_for_epoch(998).slots_limit() == 0
    inst = MAINNET.instance_for_epoch(999)
    # 0.06 * (250 - 25) external slots = 13 (int floor)
    assert inst.slots_limit() == 13


def test_vote_share_trajectory():
    assert str(
        MAINNET.instance_for_epoch(0).harmony_vote_percent
    ).startswith("1.0")
    assert str(
        MAINNET.instance_for_epoch(185).harmony_vote_percent
    ).startswith("0.68")
    assert str(
        MAINNET.instance_for_epoch(2152).harmony_vote_percent
    ).startswith("0.01")


# --- foundational accounts + committee assembly ----------------------------

def test_tables_loaded_with_reference_counts():
    counts = {
        "FoundationalNodeAccounts": 152,
        "FoundationalNodeAccountsV1_5": 320,
        "HarmonyAccounts": 804,
        "HarmonyAccountsPostHIP30": 402,
    }
    for name, n in counts.items():
        assert len(GA.table(name)) == n, name


def test_round_robin_committee_assembly():
    inst = MAINNET.instance_for_epoch(0)
    shards = [GA.committee_slots(inst, s) for s in range(4)]
    for com in shards:
        assert len(com) == 150
        assert sum(1 for _, _, ext in com if not ext) == 112
    # round-robin: shard i, harmony slot j takes hmy[i + 4j]
    hmy = GA.table("HarmonyAccounts")
    assert shards[2][3][:2] == hmy[2 + 4 * 3]
    fn = GA.table("FoundationalNodeAccounts")
    assert shards[1][112][:2] == fn[1]  # first external slot
    # no key appears in two shards
    seen = set()
    for com in shards:
        for _, bls, _ in com:
            assert bls not in seen
            seen.add(bls)
    assert len(seen) == 4 * 150


def test_foundational_bls_keys_decode_as_herumi_points():
    from harmony_tpu.ref import herumi as HM

    inst = MAINNET.instance_for_epoch(0)
    com = GA.committee_slots(inst, 0)
    for _, bls, _ in com[:20]:  # sample; full set covered by genesis test
        assert HM.g1_deserialize(bls) is not None


def test_mainnet_genesis_boots():
    from harmony_tpu.core.blockchain import Blockchain
    from harmony_tpu.core.genesis import mainnet_genesis
    from harmony_tpu.core.kv import MemKV

    gen = mainnet_genesis(shard_id=0)
    assert len(gen.committee) == 150
    chain = Blockchain(MemKV(), gen, blocks_per_epoch=16384)
    assert chain.head_number == 0
    assert chain.current_header().shard_id == 0
    # committee surface serves the genesis keys
    assert chain.committee_for_epoch(0) == gen.committee


def test_mainnet_genesis_shard3():
    from harmony_tpu.core.genesis import mainnet_genesis

    g3 = mainnet_genesis(shard_id=3)
    g0 = mainnet_genesis(shard_id=0)
    assert len(g3.committee) == 150
    assert set(g3.committee).isdisjoint(g0.committee)
