"""Node binary wiring: build_node brings up chain + consensus + RPC +
metrics + sync server from config (the reference's cmd/harmony
setupNodeAndRun path — SURVEY.md §3.1 — in one process)."""

import http.client
import json
import time

from harmony_tpu.cli import DEFAULTS, build_node, load_config


def _rpc(port, method, params=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request(
        "POST", "/",
        json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                    "params": params or []}),
        {"Content-Type": "application/json"},
    )
    out = json.loads(conn.getresponse().read())
    conn.close()
    return out


def test_build_node_full_stack(tmp_path):
    cfg = load_config(None, {})
    cfg.update(
        datadir=str(tmp_path), in_memory=True, rpc_port=0,
        metrics_port=0, p2p_port=0, sync_port=0, blocks_per_epoch=16,
    )
    node, manager, reg, rpc, metrics = build_node(cfg)
    manager.start_services()
    try:
        # the dev node holds the whole committee: blocks flow solo.
        # Generous deadline: each block needs ~4 host pairings and this
        # box has one core that background compiles may contend for.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if node.chain.head_number >= 2:
                break
            time.sleep(0.05)
        assert node.chain.head_number >= 2

        head = _rpc(rpc.port, "hmyv2_blockNumber")["result"]
        assert head >= 2
        block1 = _rpc(rpc.port, "hmy_getBlockByNumber", ["0x1", False])
        assert block1["result"]["number"] == "0x1"

        conn = http.client.HTTPConnection(
            "127.0.0.1", metrics.port, timeout=10
        )
        conn.request("GET", "/metrics")
        assert conn.getresponse().status == 200
        conn.close()
    finally:
        manager.stop_services()


def test_load_config_toml_and_overrides(tmp_path):
    cfg_file = tmp_path / "node.toml"
    cfg_file.write_text(
        'network = "testnet"\nshard_id = 3\nrpc_port = 1234\n'
    )
    cfg = load_config(str(cfg_file), {"rpc_port": 4321})
    assert cfg["network"] == "testnet"
    assert cfg["shard_id"] == 3
    assert cfg["rpc_port"] == 4321  # flag beats file
    assert cfg["datadir"] == DEFAULTS["datadir"]


def test_foreign_shard_committee_fails_closed(tmp_path):
    """A foreign shard with no resolvable committee must yield a context
    that rejects every proof — NOT the local genesis committee (advisor
    r2: that verified cross-shard seals against the wrong key set)."""
    cfg = load_config(None, {})
    cfg.update(
        datadir=str(tmp_path), in_memory=True, rpc_port=0,
        metrics_port=0, p2p_port=0, sync_port=0, blocks_per_epoch=16,
    )
    node, manager, reg, rpc, metrics = build_node(cfg)
    try:
        engine = node.chain.engine
        local = engine.epoch_context(cfg["shard_id"], 0)
        assert len(local) > 0
        foreign = engine.epoch_context(cfg["shard_id"] + 7, 0)
        assert len(foreign) == 0  # empty context: fails closed
        # an empty context rejects any (sig, bitmap) pair
        from harmony_tpu.chain.header import Header

        hdr = Header(shard_id=cfg["shard_id"] + 7, epoch=0)
        assert not engine.verify_header_signature(
            hdr, b"\x01" * 96, b"\xff"
        )
    finally:
        manager.stop_services()
