"""Full-pairing GT parity: FP_BACKEND scan vs pallas (VERDICT r3 #2).

Opt-in (OPS_PALLAS_PAIRING=1): a full pairing program costs 20+ minutes
of XLA:CPU compile on the 1-core box (docs/NOTES_r3.md), and interpret-
mode Pallas multiplies that further.  The fast tier already proves the
two backends bit-identical at every composable tier (mont_mul incl.
lane padding, Fp2/Fp12 towers, the group law — tests/test_fp_backend.py);
since fp.mont_mul is the ONLY primitive the flag swaps, identical
mont_mul on all shapes implies identical GT elements.  This test checks
that implication end-to-end when the budget allows (always on a real
TPU, where compiles are seconds).
"""

import os

import numpy as np
import pytest

if not os.environ.get("OPS_PALLAS_PAIRING"):
    pytest.skip(
        "full-pairing backend parity is opt-in: OPS_PALLAS_PAIRING=1 "
        "(20+ min of XLA:CPU compile on this box)",
        allow_module_level=True,
    )


def test_pairing_gt_identical_across_backends():
    import jax

    from harmony_tpu.ops import fp
    from harmony_tpu.ops import interop as I
    from harmony_tpu.ops import pairing as OP
    from harmony_tpu.ref.curve import G1_GEN, G2_GEN, g1, g2

    ps = I.g1_batch_affine([G1_GEN, g1.dbl(G1_GEN)])
    qs = I.g2_batch_affine([G2_GEN, g2.dbl(G2_GEN)])

    fp.set_backend("scan")
    want = np.asarray(jax.jit(OP.pairing)(ps, qs))

    backend = (
        "pallas" if jax.default_backend() != "cpu" else "pallas-interpret"
    )
    fp.set_backend(backend)
    try:
        # fresh python callable => fresh trace under the new backend
        got = np.asarray(jax.jit(lambda p, q: OP.pairing(p, q))(ps, qs))
    finally:
        fp.set_backend("scan")
    np.testing.assert_array_equal(want, got)
