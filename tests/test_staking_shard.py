"""EPoS election + committee assignment tests."""

from harmony_tpu.numeric import Dec, new_dec
from harmony_tpu.shard import committee as SC
from harmony_tpu.staking import effective as E


def _orders():
    return {
        b"addr-a": E.SlotOrder(stake=1000, spread_among=[b"ka1", b"ka2"]),
        b"addr-b": E.SlotOrder(stake=900, spread_among=[b"kb1"]),
        b"addr-c": E.SlotOrder(stake=100, spread_among=[b"kc1"]),
        b"addr-d": E.SlotOrder(stake=50, spread_among=[b"kd1"]),
    }


def test_spread_and_ordering():
    med, picks = E.compute(_orders(), pull=10)
    # a spreads 1000 over 2 keys = 500 each; order: kb1(900), ka(500,500),
    # kc1(100), kd1(50)
    assert [p.key for p in picks] == [b"kb1", b"ka1", b"ka2", b"kc1", b"kd1"]
    assert picks[0].raw_stake.equal(new_dec(900))
    assert picks[1].raw_stake.equal(new_dec(500))
    assert med.equal(new_dec(500))  # odd count -> middle


def test_median_even_count():
    med, picks = E.compute(_orders(), pull=4)
    # picks: 900, 500, 500, 100 -> median (500+500)/2
    assert med.equal(new_dec(500))
    assert len(picks) == 4


def test_effective_stake_clamping():
    med, picks = E.apply(_orders(), pull=10)
    # median 500, c=0.15: bounds [425, 575]
    by_key = {p.key: p for p in picks}
    assert by_key[b"kb1"].epos_stake.equal(Dec.from_str("575"))  # capped
    assert by_key[b"ka1"].epos_stake.equal(new_dec(500))  # untouched
    assert by_key[b"kc1"].epos_stake.equal(Dec.from_str("425"))  # floored
    assert by_key[b"kd1"].epos_stake.equal(Dec.from_str("425"))


def test_extended_bound():
    _, picks = E.apply(_orders(), pull=10, extended_bound=True)
    by_key = {p.key: p for p in picks}
    # c=0.35: bounds [325, 675]
    assert by_key[b"kb1"].epos_stake.equal(Dec.from_str("675"))
    assert by_key[b"kd1"].epos_stake.equal(Dec.from_str("325"))


def test_pull_limits_winners():
    _, picks = E.apply(_orders(), pull=2)
    assert len(picks) == 2
    assert {p.key for p in picks} == {b"kb1", b"ka1"} or {
        p.key for p in picks
    } == {b"kb1", b"ka2"}


def test_committee_assignment_round_robin_and_shard_by_key():
    hmy = [(f"h{i}".encode(), f"hk{i}".encode()) for i in range(4)]
    state = SC.epos_staked_committee(
        epoch=10,
        shard_count=2,
        harmony_accounts=hmy,
        harmony_per_shard=2,
        orders=_orders(),
        external_slots_total=4,
    )
    assert len(state.shards) == 2
    # round robin: shard0 gets h0, h2; shard1 gets h1, h3
    assert [s.bls_pubkey for s in state.shards[0].slots[:2]] == [b"hk0", b"hk2"]
    assert [s.bls_pubkey for s in state.shards[1].slots[:2]] == [b"hk1", b"hk3"]
    # winners land on shard (key mod 2)
    for c in state.shards:
        for s in c.slots[2:]:
            assert int.from_bytes(s.bls_pubkey, "big") % 2 == c.shard_id
            assert s.effective_stake is not None
    # all 4 winners present across shards
    ext = [s for c in state.shards for s in c.slots if s.effective_stake]
    assert len(ext) == 4


def test_mainnet_200_slot_roster_election():
    """200-slot roster election at the reference's mainnet shape
    (ROADMAP item 2, mirroring one-node-staked-vote_test.go: elect at
    scale, then check the voting-power split): multi-key operators
    spread stakes over exactly 200 BLS slots, the auction fills every
    slot with the right ordering / spread / EPoS clamping, committee
    assignment shards the winners, and voting power sums to exactly
    one.  The roster's first four operators ARE the wan_committee
    chaos topology's live 64-key committee (dev_genesis keys, 4 nodes
    x 16 keys, via the same chaostest fixture) — the binding the live
    WAN scenario runs is the binding this election elects."""
    from harmony_tpu.chaostest import fixtures as FX
    from harmony_tpu.consensus import votepower as VP
    from harmony_tpu.core.genesis import dev_genesis
    from harmony_tpu.numeric import new_dec

    genesis, _, bls_keys = dev_genesis(n_accounts=4, n_keys=64)
    live = [k.pub.bytes for k in bls_keys]
    assert live == list(genesis.committee)  # the wan_committee keys

    orders, key_owner = FX.mainnet_roster(
        slots=200, seed=5, committee_keys=live
    )
    assert sum(len(o.spread_among) for o in orders.values()) == 200

    med, picks = E.apply(orders, pull=200)
    assert len(picks) == 200
    # slot ordering: raw stake non-increasing across the full roster
    stakes = [p.raw_stake.raw for p in picks]
    assert stakes == sorted(stakes, reverse=True)
    # multi-key operator binding: every winning key belongs to its
    # operator, and an operator's keys all carry the SAME truncated
    # spread (stake // n_keys semantics)
    per_op_spreads: dict = {}
    for p in picks:
        assert key_owner[p.key] == p.addr
        per_op_spreads.setdefault(p.addr, set()).add(p.raw_stake.raw)
    assert all(len(s) == 1 for s in per_op_spreads.values())
    assert any(
        len(o.spread_among) == 16 for o in orders.values()
    )  # the wan operators really are 16-key
    # the live 64-key committee out-stakes every synthetic operator:
    # it wins slots — and exactly the TOP 64 of them
    assert {p.key for p in picks[:64]} == set(live)
    # EPoS clamping: every effective stake inside [1-c, 1+c] * median
    hi = new_dec(1).add(E.C_BOUND).mul(med)
    lo = new_dec(1).sub(E.C_BOUND).mul(med)
    for p in picks:
        assert not p.epos_stake.gt(hi) and not lo.gt(p.epos_stake)

    # committee assignment at 4 shards (reference: 200 external slots
    # total, winners land on shard (key mod shard_count))
    hmy = [(f"h{i}".encode(), f"hk{i}".encode()) for i in range(8)]
    state = SC.epos_staked_committee(
        epoch=7,
        shard_count=4,
        harmony_accounts=hmy,
        harmony_per_shard=2,
        orders=orders,
        external_slots_total=200,
    )
    ext = [
        s for c in state.shards for s in c.slots
        if s.effective_stake is not None
    ]
    assert len(ext) == 200
    for c in state.shards:
        assert len(c.slots) >= 2  # harmony slots seated round-robin
        for s in c.slots[2:]:
            assert int.from_bytes(s.bls_pubkey, "big") % 4 == c.shard_id

    # voting power (the one-node-staked-vote_test.go assertion shape):
    # harmony slots split their configured 49% equally, the staked
    # slots split 51% pro-rata by effective stake, and the total is
    # forced to EXACTLY one
    shard0 = state.shards[0]
    roster = VP.compute_roster(
        [
            VP.Slot(
                address=s.ecdsa_address,
                bls_pubkey=s.bls_pubkey,
                effective_stake=s.effective_stake,
            )
            for s in shard0.slots
        ],
        harmony_percent=Dec.from_str("0.49"),
        external_percent=Dec.from_str("0.51"),
    )
    assert roster.harmony_slot_count == 2
    assert roster.our_voting_power.add(
        roster.their_voting_power
    ).equal(new_dec(1))
    hmy_voters = [
        v for v in roster.voters.values() if v.is_harmony
    ]
    assert all(
        v.overall_percent.equal(Dec.from_str("0.245"))
        for v in hmy_voters
    )


def test_committee_rotation_at_epoch_boundary():
    """Full rotation arc on a real chain, via the SAME chaostest
    fixtures the election-under-load scenario composes: a staked
    external key (with BLS proof-of-possession) wins an epoch-0 slot,
    the epoch-1 committee rotates to include it, and — because it keeps
    signing — the epoch-2 election keeps it seated."""
    from harmony_tpu.chaostest import fixtures as FX
    from harmony_tpu.core.blockchain import Blockchain
    from harmony_tpu.core.genesis import dev_genesis
    from harmony_tpu.core.kv import MemKV
    from harmony_tpu.core.tx_pool import TxPool

    genesis, ecdsa_keys, _ = dev_genesis(n_accounts=4, n_keys=4)
    chain = Blockchain(
        MemKV(), genesis, blocks_per_epoch=4,
        finalizer=FX.staking_finalizer(genesis, ecdsa_keys),
    )
    pool = TxPool(2, 0, chain.state)
    ext = FX.external_bls_key(99, 0)
    pool.add(
        FX.external_validator_stake(ecdsa_keys[0], ext),
        is_staking=True,
    )

    # epoch 0: blocks 1..3; block 3 is the election block
    FX.advance_with_full_bitmaps(chain, pool, 3)
    assert chain.is_election_block(3)
    com1 = chain.committee_for_epoch(1)
    assert len(com1) == 5 and ext.pub.bytes in com1
    assert com1 != list(genesis.committee)  # it ROTATED
    assert chain.committee_for_epoch(0) == list(genesis.committee)

    # the boundary crossing itself: the first epoch-1 blocks commit
    # under the rotated committee's full bitmaps
    FX.advance_with_full_bitmaps(chain, pool, 3)
    assert chain.head_number == 6
    assert chain.epoch_of(chain.head_number) == 1

    # the epoch-1 election (block 7) re-seats the signing validator
    FX.advance_with_full_bitmaps(chain, pool, 2)
    com2 = chain.committee_for_epoch(2)
    assert ext.pub.bytes in com2 and len(com2) == 5
