"""EPoS election + committee assignment tests."""

from harmony_tpu.numeric import Dec, new_dec
from harmony_tpu.shard import committee as SC
from harmony_tpu.staking import effective as E


def _orders():
    return {
        b"addr-a": E.SlotOrder(stake=1000, spread_among=[b"ka1", b"ka2"]),
        b"addr-b": E.SlotOrder(stake=900, spread_among=[b"kb1"]),
        b"addr-c": E.SlotOrder(stake=100, spread_among=[b"kc1"]),
        b"addr-d": E.SlotOrder(stake=50, spread_among=[b"kd1"]),
    }


def test_spread_and_ordering():
    med, picks = E.compute(_orders(), pull=10)
    # a spreads 1000 over 2 keys = 500 each; order: kb1(900), ka(500,500),
    # kc1(100), kd1(50)
    assert [p.key for p in picks] == [b"kb1", b"ka1", b"ka2", b"kc1", b"kd1"]
    assert picks[0].raw_stake.equal(new_dec(900))
    assert picks[1].raw_stake.equal(new_dec(500))
    assert med.equal(new_dec(500))  # odd count -> middle


def test_median_even_count():
    med, picks = E.compute(_orders(), pull=4)
    # picks: 900, 500, 500, 100 -> median (500+500)/2
    assert med.equal(new_dec(500))
    assert len(picks) == 4


def test_effective_stake_clamping():
    med, picks = E.apply(_orders(), pull=10)
    # median 500, c=0.15: bounds [425, 575]
    by_key = {p.key: p for p in picks}
    assert by_key[b"kb1"].epos_stake.equal(Dec.from_str("575"))  # capped
    assert by_key[b"ka1"].epos_stake.equal(new_dec(500))  # untouched
    assert by_key[b"kc1"].epos_stake.equal(Dec.from_str("425"))  # floored
    assert by_key[b"kd1"].epos_stake.equal(Dec.from_str("425"))


def test_extended_bound():
    _, picks = E.apply(_orders(), pull=10, extended_bound=True)
    by_key = {p.key: p for p in picks}
    # c=0.35: bounds [325, 675]
    assert by_key[b"kb1"].epos_stake.equal(Dec.from_str("675"))
    assert by_key[b"kd1"].epos_stake.equal(Dec.from_str("325"))


def test_pull_limits_winners():
    _, picks = E.apply(_orders(), pull=2)
    assert len(picks) == 2
    assert {p.key for p in picks} == {b"kb1", b"ka1"} or {
        p.key for p in picks
    } == {b"kb1", b"ka2"}


def test_committee_assignment_round_robin_and_shard_by_key():
    hmy = [(f"h{i}".encode(), f"hk{i}".encode()) for i in range(4)]
    state = SC.epos_staked_committee(
        epoch=10,
        shard_count=2,
        harmony_accounts=hmy,
        harmony_per_shard=2,
        orders=_orders(),
        external_slots_total=4,
    )
    assert len(state.shards) == 2
    # round robin: shard0 gets h0, h2; shard1 gets h1, h3
    assert [s.bls_pubkey for s in state.shards[0].slots[:2]] == [b"hk0", b"hk2"]
    assert [s.bls_pubkey for s in state.shards[1].slots[:2]] == [b"hk1", b"hk3"]
    # winners land on shard (key mod 2)
    for c in state.shards:
        for s in c.slots[2:]:
            assert int.from_bytes(s.bls_pubkey, "big") % 2 == c.shard_id
            assert s.effective_stake is not None
    # all 4 winners present across shards
    ext = [s for c in state.shards for s in c.slots if s.effective_stake]
    assert len(ext) == 4
