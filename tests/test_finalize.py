"""Epoch lifecycle: rewards, availability, EPoS election, committee
rotation (the reference's Finalize path — SURVEY.md §3.4 — end to end
on a real chain)."""

import pytest

from harmony_tpu import bls as B
from harmony_tpu.chain.finalize import FinalizeConfig, Finalizer
from harmony_tpu.core.blockchain import Blockchain
from harmony_tpu.core.genesis import dev_genesis
from harmony_tpu.core.kv import MemKV
from harmony_tpu.core.tx_pool import TxPool
from harmony_tpu.core.types import Directive, StakingTransaction
from harmony_tpu.node.worker import Worker

CHAIN_ID = 2
BPE = 4  # blocks per epoch


def _setup():
    genesis, ecdsa_keys, bls_keys = dev_genesis()
    harmony_accounts = [
        (k.address(), pub)
        for k, pub in zip(ecdsa_keys, genesis.committee)
    ]
    fin = Finalizer(FinalizeConfig(
        block_reward=28 * 10**18,
        shard_count=1,
        external_slots_per_shard=2,
        harmony_accounts=harmony_accounts,
    ))
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=BPE,
                       finalizer=fin)
    pool = TxPool(CHAIN_ID, 0, chain.state)
    return chain, pool, genesis, ecdsa_keys


def _advance(chain, pool, n=1, bitmap_bytes=None):
    """Commit n blocks; store a full-participation commit proof for
    each so the NEXT block's finalize sees its bitmap."""
    worker = Worker(chain, pool)
    for _ in range(n):
        block = worker.propose_block(view_id=chain.head_number + 1)
        assert chain.insert_chain([block], verify_seals=False) == 1
        committee = chain.committee_for_epoch(
            chain.epoch_of(block.block_num)
        )
        nbytes = (len(committee) + 7) >> 3
        bitmap = bitmap_bytes if bitmap_bytes is not None else (
            bytes([0xFF] * nbytes)
        )
        # trim overflow bits beyond committee size
        full = bytearray(bitmap[:nbytes])
        extra = nbytes * 8 - len(committee)
        if extra:
            full[-1] &= 0xFF >> extra
        chain.write_commit_sig(
            block.block_num, b"\x01" * 96 + bytes(full)
        )
        pool.drop_applied()


def test_election_rotates_committee_and_pays_rewards():
    chain, pool, genesis, ecdsa_keys = _setup()
    ext_bls = B.PrivateKey.generate(b"external-validator-key")
    staker = ecdsa_keys[0]

    stx = StakingTransaction(
        nonce=0, gas_price=1, gas_limit=50_000,
        directive=Directive.CREATE_VALIDATOR,
        fields={
            "amount": 10**20,
            "min_self_delegation": 10**18,
            "bls_keys": ext_bls.pub.bytes,
        },
    ).sign(staker, CHAIN_ID)
    pool.add(stx, is_staking=True)

    # epoch 0: blocks 1..3 (block 3 is the election block)
    _advance(chain, pool, 3)
    assert chain.is_election_block(3)
    elected = chain.shard_state_for_epoch(1)
    assert elected is not None
    com = elected.find_committee(0)
    keys = com.bls_pubkeys()
    # 4 harmony slots + the external winner
    assert len(keys) == 5
    assert ext_bls.pub.bytes in keys
    ext_slot = [s for s in com.slots if s.effective_stake is not None]
    assert len(ext_slot) == 1
    assert chain.committee_for_epoch(1) == keys
    assert chain.committee_for_epoch(0) == list(genesis.committee)

    # epoch 1: the external validator signs (full bitmaps) and earns
    w_before = chain.state().validator(staker.address())
    assert w_before.blocks_to_sign == 0
    _advance(chain, pool, 2)  # blocks 4, 5 (block 5 sees block 4's bitmap)
    w = chain.state().validator(staker.address())
    # block 5's finalize consumed block 4's 5-slot bitmap
    assert w.blocks_to_sign == 1 and w.blocks_signed == 1
    d = w.delegations[0]
    assert d.delegator == staker.address()
    assert d.reward == 28 * 10**18  # sole external signer gets it all


def test_missing_signer_goes_inactive_at_election():
    chain, pool, genesis, ecdsa_keys = _setup()
    ext_bls = B.PrivateKey.generate(b"lazy-validator-key")
    staker = ecdsa_keys[1]
    stx = StakingTransaction(
        nonce=0, gas_price=1, gas_limit=50_000,
        directive=Directive.CREATE_VALIDATOR,
        fields={
            "amount": 10**20,
            "min_self_delegation": 10**18,
            "bls_keys": ext_bls.pub.bytes,
        },
    ).sign(staker, CHAIN_ID)
    pool.add(stx, is_staking=True)
    _advance(chain, pool, 3)  # elected into epoch 1
    assert ext_bls.pub.bytes in chain.committee_for_epoch(1)

    # epoch 1: bitmaps mark only the 4 harmony slots; slot 5 never signs
    _advance(chain, pool, 4, bitmap_bytes=bytes([0x0F]))
    # the election block of epoch 1 (block 7) saw 0-of-N signing and
    # flipped the validator inactive; epoch 2's committee drops it
    w = chain.state().validator(staker.address())
    assert w.status == 1
    assert ext_bls.pub.bytes not in chain.committee_for_epoch(2)
    # harmony fallback committee still present
    assert len(chain.committee_for_epoch(2)) == 4


def test_bits_from_bytes_short_bitmap_raises_valueerror():
    """A truncated bitmap must raise ValueError (callers catch it on
    untrusted input), never IndexError."""
    from harmony_tpu.consensus.mask import bits_from_bytes

    with pytest.raises(ValueError):
        bits_from_bytes(b"\x01", 9)
    assert bits_from_bytes(b"\x01\x01", 9) == [1, 0, 0, 0, 0, 0, 0, 0, 1]


def test_fabricated_parent_proof_rejected_by_validator():
    """A proposal whose header carries a parent commit proof different
    from the locally committed one is rejected before voting (the
    bitmap drives reward state)."""
    from harmony_tpu.core.kv import MemKV
    from harmony_tpu.core.rawdb import encode_block, decode_block
    from harmony_tpu.multibls import PrivateKeys
    from harmony_tpu.node.node import Node
    from harmony_tpu.node.registry import Registry
    from harmony_tpu.p2p import InProcessNetwork

    genesis, ecdsa_keys, bls_keys = dev_genesis(n_keys=1)
    net = InProcessNetwork()
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    pool = TxPool(CHAIN_ID, 0, chain.state)
    reg = Registry(blockchain=chain, txpool=pool, host=net.host("solo"))
    node = Node(reg, PrivateKeys.from_keys(bls_keys))
    node.start_round_if_leader()
    assert chain.head_number == 1

    good = Worker(chain, None).propose_block(view_id=2)
    assert node._validate_proposed_block(
        encode_block(good, CHAIN_ID)
    ) is not None
    forged = Worker(chain, None).propose_block(view_id=2)
    forged.header.last_commit_bitmap = bytes(
        [forged.header.last_commit_bitmap[0] ^ 0x02]
    ) + forged.header.last_commit_bitmap[1:]
    assert node._validate_proposed_block(
        encode_block(forged, CHAIN_ID)
    ) is None
