"""Fast-tier EXECUTION of the Miller-loop step kernels (VERDICT r3 #5).

The full pairing program cannot compile inside the fast tier on this
box (20+ min of XLA:CPU, docs/NOTES_r3.md), which left a hole: an edit
breaking ops/pairing.py math kept the quick suite green.  Three layers
now close it:

1. HERE — the factored Miller step kernels (_dbl_step, _add_step) are
   small programs that compile in seconds; their point halves are
   checked against the bigint ref group law (formula-independent: the
   jax kernels use twist-Jacobian dbl-2009-l / madd-2007-bl, the ref
   uses affine chord-tangent).
2. tests/test_fp_backend.py — mont_mul/towers/group-law executed and
   cross-checked on every run.
3. tests/test_multichip_artifact.py — the lowering digest of the FULL
   fused program (Miller loop, final exponentiation, line assembly
   included): any structural/math edit flips the artifact and fails CI,
   forcing the isolated heavy parity tier before re-pinning.

The line-coefficient VALUES and the final exponentiation stay covered
by the heavy tier (test_ops_pairing_bls via test_ops_heavy_isolated) —
they have no cheap independent oracle below a full pairing.
"""

import jax
import numpy as np
import pytest

from harmony_tpu.ops import fp
from harmony_tpu.ops import interop as I
from harmony_tpu.ops import pairing as OP
from harmony_tpu.ref.curve import G2_GEN, g2
from harmony_tpu.ref import fields as F


def _g2_jac_from_affine(pt):
    arr = I.g2_affine_to_arr(pt)  # (2, 2, 32) x/y affine
    one = I.fp2_to_arr((1, 0))
    return arr[0], arr[1], one


def _g2_affine_from_jac(x, y, z):
    xi = I.arr_to_fp2(np.asarray(x))
    yi = I.arr_to_fp2(np.asarray(y))
    zi = I.arr_to_fp2(np.asarray(z))
    z_inv = F.fp2_inv(zi)
    z2 = F.fp2_sqr(z_inv)
    return (
        F.fp2_mul(xi, z2),
        F.fp2_mul(yi, F.fp2_mul(z2, z_inv)),
    )


@pytest.fixture(scope="module")
def base_points():
    t = g2.mul(G2_GEN, 7)
    q = g2.mul(G2_GEN, 11)
    return t, q


def test_dbl_step_point_half_matches_group_law(base_points):
    t, _ = base_points
    x, y, z = _g2_jac_from_affine(t)
    xp3 = fp.to_mont(np.zeros(32, dtype=np.int32))  # line inputs: any
    yp2 = xp3  # valid Fp residues; the point half ignores them

    @jax.jit
    def step(x, y, z, a, b):
        (x3, y3, z3), _ = OP._dbl_step(x, y, z, a, b)
        return x3, y3, z3

    x3, y3, z3 = step(x, y, z, xp3, yp2)
    assert _g2_affine_from_jac(x3, y3, z3) == g2.dbl(t)


def test_add_step_point_half_matches_group_law(base_points):
    t, q = base_points
    x, y, z = _g2_jac_from_affine(t)
    qx = I.fp2_to_arr(q[0])
    qy = I.fp2_to_arr(q[1])
    dummy = fp.to_mont(np.zeros(32, dtype=np.int32))

    @jax.jit
    def step(x, y, z, qx, qy, a, b):
        (x3, y3, z3), _ = OP._add_step(x, y, z, qx, qy, a, b)
        return x3, y3, z3

    x3, y3, z3 = step(x, y, z, qx, qy, dummy, dummy)
    assert _g2_affine_from_jac(x3, y3, z3) == g2.add(t, q)


def test_dbl_chain_stays_on_curve_and_consistent(base_points):
    """Three chained doublings through the jitted kernel must track the
    bigint group law exactly (catches accumulated coordinate-scaling
    errors a single step could mask)."""
    t, _ = base_points
    x, y, z = _g2_jac_from_affine(t)
    dummy = fp.to_mont(np.zeros(32, dtype=np.int32))

    @jax.jit
    def chain(x, y, z, a, b):
        for _ in range(3):
            (x, y, z), _ = OP._dbl_step(x, y, z, a, b)
        return x, y, z

    x3, y3, z3 = chain(x, y, z, dummy, dummy)
    want = g2.dbl(g2.dbl(g2.dbl(t)))
    assert _g2_affine_from_jac(x3, y3, z3) == want
