"""Chain core tests: ECDSA, VDF, KV stores, state DB, tx pool, state
processor, worker assembly, and Blockchain insert/replay (the
reference's core/ test tier — SURVEY.md §4 in-memory chain fixtures)."""

import os

import pytest

from harmony_tpu import crypto_ecdsa as E
from harmony_tpu.chain.engine import Engine, EpochContext
from harmony_tpu.core import rawdb
from harmony_tpu.core.blockchain import Blockchain, ChainError
from harmony_tpu.core.genesis import dev_genesis
from harmony_tpu.core.kv import FileKV, MemKV
from harmony_tpu.core.state import StateDB, ValidatorWrapper
from harmony_tpu.core.state_processor import (
    ExecutionError,
    StateProcessor,
)
from harmony_tpu.core.tx_pool import PoolError, TxPool
from harmony_tpu.core.types import Directive, StakingTransaction, Transaction
from harmony_tpu.node.worker import Worker
from harmony_tpu.vdf import VDF

CHAIN_ID = 2


# -- ecdsa ------------------------------------------------------------------

def test_ecdsa_sign_recover_roundtrip():
    key = E.ECDSAKey.from_seed(b"alice")
    digest = bytes(range(32))
    sig = key.sign(digest)
    assert len(sig) == 65
    assert E.pub_to_address(E.recover(digest, sig)) == key.address()
    assert E.verify(digest, sig, key.address())
    # deterministic (RFC 6979)
    assert key.sign(digest) == sig
    # tampered digest fails
    assert not E.verify(bytes(32), sig, key.address())
    # low-S enforced
    s = int.from_bytes(sig[32:64], "big")
    assert s <= E.N // 2


def test_ecdsa_rejects_high_s():
    key = E.ECDSAKey.from_seed(b"bob")
    digest = os.urandom(32)
    sig = bytearray(key.sign(digest))
    s = int.from_bytes(sig[32:64], "big")
    sig[32:64] = (E.N - s).to_bytes(32, "big")  # malleate to high-S
    sig[64] ^= 1
    with pytest.raises(ValueError):
        E.recover(digest, bytes(sig))


# -- vdf --------------------------------------------------------------------

def test_vdf_evaluate_verify():
    vdf = VDF(100)
    out = vdf.evaluate(b"seed")
    assert vdf.verify(b"seed", out)
    assert not vdf.verify(b"seed2", out)
    assert VDF(101).evaluate(b"seed") != out


# -- kv ---------------------------------------------------------------------

def test_filekv_roundtrip_reopen_compact(tmp_path):
    path = str(tmp_path / "db.log")
    db = FileKV(path)
    db.put(b"a", b"1")
    db.put(b"b", b"2")
    db.put(b"a", b"3")  # overwrite
    db.delete(b"b")
    assert db.get(b"a") == b"3" and db.get(b"b") is None
    db.close()
    db = FileKV(path)  # replay
    assert db.get(b"a") == b"3" and not db.has(b"b")
    size_before = os.path.getsize(path)
    db.compact()
    assert os.path.getsize(path) < size_before
    assert db.get(b"a") == b"3"
    # torn tail: partial record is dropped on reopen
    db.put(b"c", b"4")
    db.flush()
    db.close()
    with open(path, "ab") as f:
        f.write(b"\x05\x00\x00\x00")  # header fragment
    db = FileKV(path)
    assert db.get(b"c") == b"4"
    db.put(b"d", b"5")  # writable after truncation
    assert db.get(b"d") == b"5"
    db.close()


# -- state ------------------------------------------------------------------

def test_state_root_and_serialization():
    s = StateDB()
    a, b = b"\x01" * 20, b"\x02" * 20
    s.add_balance(a, 100)
    s.add_balance(b, 50)
    s.set_nonce(a, 7)
    w = ValidatorWrapper(address=b, bls_keys=[b"\x0b" * 48])
    from harmony_tpu.core.state import Delegation

    w.delegations.append(Delegation(b, 1000, [(5, 3)], reward=9))
    s.set_validator(w)
    root = s.root()
    # insertion order must not matter
    s2 = StateDB()
    s2.set_validator(w)
    s2.add_balance(b, 50)
    s2.set_nonce(a, 7)
    s2.add_balance(a, 100)
    assert s2.root() == root
    # round-trip through bytes
    s3 = StateDB.deserialize(s.serialize())
    assert s3.root() == root
    assert s3.balance(a) == 100 and s3.nonce(a) == 7
    w3 = s3.validator(b)
    assert w3.bls_keys == [b"\x0b" * 48]
    assert w3.delegations[0].undelegations == [(5, 3)]
    assert w3.delegations[0].reward == 9
    # empty accounts don't perturb the root
    s.balance(b"\x03" * 20)
    s.account(b"\x04" * 20)
    assert s.root() == root


# -- transactions + pool ----------------------------------------------------

def _transfer(key, nonce, to, value, gas_price=1, shard=0, to_shard=None):
    tx = Transaction(
        nonce=nonce, gas_price=gas_price, gas_limit=25_000,
        shard_id=shard, to_shard=shard if to_shard is None else to_shard,
        to=to, value=value,
    )
    return tx.sign(key, CHAIN_ID)


def test_transaction_sender_recovery():
    key = E.ECDSAKey.from_seed(b"carol")
    tx = _transfer(key, 0, b"\x09" * 20, 5)
    assert tx.sender(CHAIN_ID) == key.address()
    tx.value = 6  # tamper -> recovered sender changes or raises
    try:
        assert tx.sender(CHAIN_ID) != key.address()
    except ValueError:
        pass


def test_tx_pool_ordering_and_replacement():
    key1 = E.ECDSAKey.from_seed(b"p1")
    key2 = E.ECDSAKey.from_seed(b"p2")
    state = StateDB()
    state.add_balance(key1.address(), 10**9)
    state.add_balance(key2.address(), 10**9)
    pool = TxPool(CHAIN_ID, 0, lambda: state)
    to = b"\x08" * 20
    pool.add(_transfer(key1, 0, to, 1, gas_price=5))
    pool.add(_transfer(key1, 1, to, 1, gas_price=5))
    pool.add(_transfer(key2, 0, to, 1, gas_price=9))
    # nonce-gapped tx is admitted but not pending
    pool.add(_transfer(key2, 2, to, 1, gas_price=9))
    pend = pool.pending()
    assert [t.sender(CHAIN_ID) for t, _ in pend][:1] == [key2.address()]
    assert len(pend) == 3  # gapped nonce-2 excluded
    nonces = [t.nonce for t, _ in pend if t.sender(CHAIN_ID) == key1.address()]
    assert nonces == [0, 1]
    # replacement needs a >=10% bump
    with pytest.raises(PoolError):
        pool.add(_transfer(key1, 0, to, 2, gas_price=5))
    pool.add(_transfer(key1, 0, to, 2, gas_price=6))
    # stale nonce rejected
    state.set_nonce(key1.address(), 1)
    with pytest.raises(PoolError):
        pool.add(_transfer(key1, 0, to, 1, gas_price=50))
    pool.drop_applied()
    assert len(pool) == 3  # key1 nonce-0 pruned


# -- processor --------------------------------------------------------------

def test_processor_transfer_and_cx():
    key = E.ECDSAKey.from_seed(b"proc")
    state = StateDB()
    state.add_balance(key.address(), 10**9)
    proc = StateProcessor(CHAIN_ID, 0)
    to = b"\x07" * 20
    r, cx = proc.apply_transaction(
        state, _transfer(key, 0, to, 1000), block_num=1, cumulative_gas=0
    )
    assert r.status == 1 and cx is None
    assert state.balance(to) == 1000
    assert state.nonce(key.address()) == 1
    # cross-shard: debit here, receipt exported, no local credit
    r2, cx2 = proc.apply_transaction(
        state, _transfer(key, 1, to, 500, to_shard=1), 2, r.gas_used
    )
    assert cx2 is not None and cx2.to_shard == 1 and cx2.amount == 500
    assert state.balance(to) == 1000
    # destination shard credits it
    proc1 = StateProcessor(CHAIN_ID, 1)
    proc1.apply_incoming_receipt(state, cx2)  # same state obj for brevity
    assert state.balance(to) == 1500
    # bad nonce rejected
    with pytest.raises(ExecutionError):
        proc.apply_transaction(state, _transfer(key, 5, to, 1), 3, 0)


def _staking(key, nonce, directive, fields):
    tx = StakingTransaction(
        nonce=nonce, gas_price=1, gas_limit=50_000,
        directive=directive, fields=fields,
    )
    return tx.sign(key, CHAIN_ID)


def test_processor_staking_lifecycle():
    val = E.ECDSAKey.from_seed(b"val")
    del_ = E.ECDSAKey.from_seed(b"del")
    state = StateDB()
    state.add_balance(val.address(), 10**9)
    state.add_balance(del_.address(), 10**9)
    proc = StateProcessor(CHAIN_ID, 0)
    proc.apply_staking_transaction(
        state,
        _staking(val, 0, Directive.CREATE_VALIDATOR, {
            "amount": 10**6, "min_self_delegation": 10**5,
            "bls_keys": b"\x0c" * 48,
        }),
        epoch=0, cumulative_gas=0,
    )
    w = state.validator(val.address())
    assert w is not None and w.total_delegation() == 10**6
    proc.apply_staking_transaction(
        state,
        _staking(del_, 0, Directive.DELEGATE,
                 {"validator": val.address(), "amount": 5000}),
        epoch=0, cumulative_gas=0,
    )
    assert state.validator(val.address()).total_delegation() == 10**6 + 5000
    proc.apply_staking_transaction(
        state,
        _staking(del_, 1, Directive.UNDELEGATE,
                 {"validator": val.address(), "amount": 2000}),
        epoch=1, cumulative_gas=0,
    )
    w = state.validator(val.address())
    d = [d for d in w.delegations if d.delegator == del_.address()][0]
    assert d.amount == 3000 and d.undelegations == [(2000, 1)]
    # maturity payout
    bal_before = state.balance(del_.address())
    proc.payout_undelegations(state, epoch=1 + 7)
    assert state.balance(del_.address()) == bal_before + 2000
    # rewards
    d.reward = 777
    bal_before = state.balance(del_.address())
    proc.apply_staking_transaction(
        state, _staking(del_, 2, Directive.COLLECT_REWARDS, {}),
        epoch=8, cumulative_gas=0,
    )
    assert state.balance(del_.address()) == bal_before + 777 - 21_000
    # double create rejected
    with pytest.raises(ExecutionError):
        proc.apply_staking_transaction(
            state,
            _staking(val, 1, Directive.CREATE_VALIDATOR, {
                "amount": 10**6, "bls_keys": b"\x0d" * 48,
            }),
            epoch=2, cumulative_gas=0,
        )


# -- blockchain -------------------------------------------------------------

def _signed_tip_proof(chain, header, bls_keys, committee):
    """Build the [sig || bitmap] commit proof for a header."""
    from harmony_tpu import bls as B
    from harmony_tpu.consensus.mask import Mask
    from harmony_tpu.consensus.signature import construct_commit_payload

    payload = construct_commit_payload(
        header.hash(), header.block_num, header.view_id, True
    )
    sigs = [k.sign_hash(payload) for k in bls_keys]
    agg = B.aggregate_sigs(sigs)
    mask = Mask([k.pub.point for k in bls_keys])
    for i in range(len(bls_keys)):
        mask.set_bit(i, True)
    return agg.bytes + mask.mask_bytes()


def test_blockchain_insert_and_reload(tmp_path):
    genesis, ecdsa_keys, _ = dev_genesis()
    db = FileKV(str(tmp_path / "chain.log"))
    chain = Blockchain(db, genesis, blocks_per_epoch=16)
    assert chain.head_number == 0
    assert chain.state().balance(ecdsa_keys[0].address()) == 10**24

    pool = TxPool(CHAIN_ID, 0, chain.state)
    to = b"\x06" * 20
    pool.add(_transfer(ecdsa_keys[0], 0, to, 12345))
    worker = Worker(chain, pool)
    block = worker.propose_block(view_id=1, timestamp=1000)
    assert len(block.transactions) == 1
    assert chain.insert_chain([block], verify_seals=False) == 1
    assert chain.head_number == 1
    assert chain.state().balance(to) == 12345
    pool.drop_applied()
    assert len(pool) == 0

    # persistence: reopen from the same file
    db.flush()
    db.close()
    chain2 = Blockchain(FileKV(str(tmp_path / "chain.log")), genesis,
                        blocks_per_epoch=16)
    assert chain2.head_number == 1
    assert chain2.state().balance(to) == 12345
    assert chain2.block_by_number(1).transactions[0].value == 12345
    assert chain2.block_by_hash(block.hash()).block_num == 1

    # structural rejections
    bad = Worker(chain2, None).propose_block(view_id=2)
    bad.header.parent_hash = bytes(32)
    with pytest.raises(ChainError):
        chain2.insert_chain([bad], verify_seals=False)


def test_blockchain_insert_with_seal_verification():
    genesis, ecdsa_keys, bls_keys = dev_genesis()
    committee = genesis.committee
    engine = Engine(lambda shard, epoch: EpochContext(committee), device=False)
    chain = Blockchain(MemKV(), genesis, engine=engine,
                       blocks_per_epoch=16)
    worker = Worker(chain, None)

    b1 = worker.propose_block(view_id=1)
    p1 = _signed_tip_proof(chain, b1.header, bls_keys, committee)
    assert chain.insert_chain([b1], commit_sigs=[p1]) == 1
    assert chain.read_commit_sig(1) == p1

    # next block carries b1's proof; replay pattern resolves b2's own
    # proof from the explicit arg
    b2_worker = Worker(chain, None)
    b2 = b2_worker.propose_block(view_id=2)
    b2.header.last_commit_sig = p1[:96]
    b2.header.last_commit_bitmap = p1[96:]
    p2 = _signed_tip_proof(chain, b2.header, bls_keys, committee)
    assert chain.insert_chain([b2], commit_sigs=[p2]) == 1
    assert chain.head_number == 2

    # a forged proof is rejected
    b3 = worker.propose_block(view_id=3)
    forged = bytearray(_signed_tip_proof(chain, b3.header, bls_keys,
                                         committee))
    forged[10] ^= 0xFF
    with pytest.raises(ChainError):
        chain.insert_chain([b3], commit_sigs=[bytes(forged)])


def test_rawdb_codecs_roundtrip():
    key = E.ECDSAKey.from_seed(b"codec")
    tx = _transfer(key, 3, b"\x05" * 20, 42, to_shard=2)
    tx2 = rawdb.decode_tx(rawdb.encode_tx(tx, CHAIN_ID))
    assert tx2.hash(CHAIN_ID) == tx.hash(CHAIN_ID)
    assert tx2.sender(CHAIN_ID) == key.address()
    stx = _staking(key, 4, Directive.DELEGATE,
                   {"validator": b"\x01" * 20, "amount": 99})
    stx2 = rawdb.decode_staking_tx(rawdb.encode_staking_tx(stx, CHAIN_ID))
    assert stx2.hash(CHAIN_ID) == stx.hash(CHAIN_ID)
    assert stx2.fields == stx.fields


def test_pool_pending_queue_split_and_stats():
    key = E.ECDSAKey.from_seed(b"tier")
    state = StateDB()
    state.add_balance(key.address(), 10**9)
    pool = TxPool(CHAIN_ID, 0, lambda: state)
    to = b"\x08" * 20
    pool.add(_transfer(key, 0, to, 1))
    pool.add(_transfer(key, 1, to, 1))
    pool.add(_transfer(key, 5, to, 1))  # gapped: queued
    pending, queued = pool.stats()
    assert (pending, queued) == (2, 1)
    assert [t.nonce for t, _ in pool.queued()] == [5]
    # closing the gap promotes the queued tx
    pool.add(_transfer(key, 2, to, 1))
    pool.add(_transfer(key, 3, to, 1))
    pool.add(_transfer(key, 4, to, 1))
    pending, queued = pool.stats()
    assert (pending, queued) == (6, 0)


def test_pool_global_pressure_evicts_cheapest_queued():
    keys = [E.ECDSAKey.from_seed(bytes([i])) for i in range(3)]
    state = StateDB()
    for k in keys:
        state.add_balance(k.address(), 10**12)
    pool = TxPool(CHAIN_ID, 0, lambda: state, cap=3)
    to = b"\x08" * 20
    pool.add(_transfer(keys[0], 0, to, 1, gas_price=5))
    pool.add(_transfer(keys[1], 7, to, 1, gas_price=2))   # queued, cheap
    pool.add(_transfer(keys[2], 9, to, 1, gas_price=8))   # queued, rich
    assert len(pool) == 3
    # an underpriced newcomer cannot displace anything
    with pytest.raises(PoolError):
        pool.add(_transfer(keys[0], 1, to, 1, gas_price=1))
    # a better-paying one evicts the cheapest QUEUED tx (key1 nonce 7)
    pool.add(_transfer(keys[0], 1, to, 1, gas_price=6))
    assert len(pool) == 3
    assert pool.evicted == 1
    assert all(
        t.sender(CHAIN_ID) != keys[1].address() for t, _ in pool.queued()
    )


def test_pool_account_slot_caps():
    from harmony_tpu.core.tx_pool import ACCOUNT_QUEUE

    key = E.ECDSAKey.from_seed(b"caps")
    state = StateDB()
    state.add_balance(key.address(), 10**15)
    pool = TxPool(CHAIN_ID, 0, lambda: state)
    to = b"\x08" * 20
    # fill the queued tier for one sender (nonces far above state)
    for i in range(ACCOUNT_QUEUE):
        pool.add(_transfer(key, 1000 + i, to, 1))
    with pytest.raises(PoolError):
        pool.add(_transfer(key, 5000, to, 1))


def test_pool_lifetime_eviction():
    key = E.ECDSAKey.from_seed(b"stale")
    state = StateDB()
    state.add_balance(key.address(), 10**9)
    pool = TxPool(CHAIN_ID, 0, lambda: state, lifetime=10.0)
    to = b"\x08" * 20
    pool.add(_transfer(key, 0, to, 1))   # executable: survives
    pool.add(_transfer(key, 9, to, 1))   # queued: expires
    import time as _t

    pool.evict_stale(now=_t.monotonic() + 11.0)
    assert len(pool) == 1
    assert pool.stats() == (1, 0)


def test_revert_to_rolls_back_head_and_state():
    """Chain revert tooling (reference: cmd/harmony revert commands):
    head, live state, and canonical indices roll back; the chain can
    then advance again from the revert point."""
    from harmony_tpu.core import rawdb
    from harmony_tpu.core.genesis import dev_genesis
    from harmony_tpu.core.kv import MemKV
    from harmony_tpu.node.worker import Worker

    genesis, keys, _ = dev_genesis()
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    pool = TxPool(CHAIN_ID, 0, chain.state)
    worker = Worker(chain, pool)
    to = b"\x0e" * 20
    hashes = {}
    for i in range(4):
        tx = _transfer(keys[0], i, to, 10)
        pool.add(tx)
        block = worker.propose_block(view_id=i + 1)
        chain.insert_chain([block], verify_seals=False)
        pool.drop_applied()
        hashes[i + 1] = block.hash()
    assert chain.head_number == 4
    assert chain.state().balance(to) == 40

    assert chain.revert_to(2) == 2
    assert chain.head_number == 2
    assert chain.state().balance(to) == 20
    assert chain.current_header().hash() == hashes[2]
    assert chain.block_by_number(3) is None
    assert rawdb.read_canonical_hash(chain.db, 4) is None
    assert rawdb.read_block_number(chain.db, hashes[4]) is None
    # reverting to the head or future is a no-op
    assert chain.revert_to(2) == 0
    assert chain.revert_to(99) == 0

    # the chain advances again from block 2 (nonces follow state)
    tx = _transfer(keys[0], 2, to, 10)
    pool.add(tx)
    block = worker.propose_block(view_id=3)
    chain.insert_chain([block], verify_seals=False)
    assert chain.head_number == 3
    assert chain.state().balance(to) == 30
