"""EpochChain: beacon epoch-boundary light chain (reference:
core/epochchain.go — VERDICT r2 weak #9's missing EpochChain analog)."""

import pytest

from harmony_tpu import bls as B
from harmony_tpu.chain.engine import Engine, EpochContext
from harmony_tpu.chain.header import Header
from harmony_tpu.consensus.mask import Mask
from harmony_tpu.consensus.signature import construct_commit_payload
from harmony_tpu.core.epochchain import EpochChain, EpochChainError
from harmony_tpu.core.kv import MemKV
from harmony_tpu.shard.committee import Committee, Slot, State

N = 4


@pytest.fixture(scope="module")
def committee():
    keys = [B.PrivateKey.generate(bytes([70 + i])) for i in range(N)]
    serialized = [k.pub.bytes for k in keys]
    return keys, serialized


def _seal(header, keys, idx):
    payload = construct_commit_payload(
        header.hash(), header.block_num, header.view_id, True
    )
    sigs = [keys[i].sign_hash(payload) for i in idx]
    agg = B.aggregate_sigs(sigs)
    mask = Mask([k.pub.point for k in keys])
    for i in idx:
        mask.set_bit(i, True)
    return agg.bytes, mask.mask_bytes()


def _elected_state(serialized, shard_id=1):
    return State(epoch=1, shards=[Committee(
        shard_id=shard_id,
        slots=[Slot(ecdsa_address=bytes([i]) * 20, bls_pubkey=k)
               for i, k in enumerate(serialized)],
    )])


def test_epochchain_insert_and_committee_resolution(committee):
    keys, serialized = committee
    eng = Engine(lambda s, e: EpochContext(serialized), device=False)
    ec = EpochChain(MemKV(), lambda s: serialized, engine=eng)
    # genesis committee resolves at epoch 0 without any insert
    assert ec.committee_for(1, 0) == serialized
    assert ec.committee_for(1, 5) == []  # unseen epoch: fail closed

    h = Header(shard_id=0, block_num=16, epoch=0, view_id=16,
               shard_state=b"elected")
    sig, bitmap = _seal(h, keys, [0, 1, 2])
    ec.insert(h, _elected_state(serialized), sig, bitmap)
    assert ec.head_epoch() == 0
    got = ec.header_for_epoch(0)
    assert got is not None and got.hash() == h.hash()
    # next epoch's committee now resolves
    assert ec.committee_for(1, 1) == serialized


def test_epochchain_rejects_bad_seal_and_non_epoch_block(committee):
    keys, serialized = committee
    eng = Engine(lambda s, e: EpochContext(serialized), device=False)
    ec = EpochChain(MemKV(), lambda s: serialized, engine=eng)
    h = Header(shard_id=0, block_num=16, epoch=0, view_id=16)
    sig, bitmap = _seal(h, keys, [0, 1, 2])
    with pytest.raises(EpochChainError):
        ec.insert(h, None, sig, bitmap)  # no shard state: not epoch blk
    # under-quorum seal rejected before any write
    sig2, bitmap2 = _seal(h, keys, [0])
    with pytest.raises(EpochChainError):
        ec.insert(h, _elected_state(serialized), sig2, bitmap2)
    assert ec.head_epoch() is None


def test_epochchain_idempotent_reinsert(committee):
    keys, serialized = committee
    ec = EpochChain(MemKV(), lambda s: serialized)  # no engine: test tier
    h = Header(shard_id=0, block_num=16, epoch=0, view_id=16)
    ec.insert(h, _elected_state(serialized))
    h2 = Header(shard_id=0, block_num=17, epoch=0, view_id=17)
    ec.insert(h2, _elected_state(serialized))  # same epoch: no-op
    assert ec.header_for_epoch(0).hash() == h.hash()


def test_epoch_feed_follows_beacon(committee):
    """EpochFeed pulls boundary headers + elected states over the sync
    stream into the EpochChain (reference: the staged sync's
    epoch-block stage feeding core/epochchain.go)."""
    from harmony_tpu.core.blockchain import Blockchain
    from harmony_tpu.core.genesis import dev_genesis
    from harmony_tpu.core import rawdb
    from harmony_tpu.core.tx_pool import TxPool
    from harmony_tpu.node.worker import Worker
    from harmony_tpu.p2p.stream import SyncClient, SyncServer
    from harmony_tpu.sync.epoch_feed import EpochFeed

    _, serialized = committee
    bpe = 4
    genesis, keys, _bls = dev_genesis()
    beacon = Blockchain(MemKV(), genesis, blocks_per_epoch=bpe)
    pool = TxPool(2, 0, beacon.state)
    worker = Worker(beacon, pool)
    # two full epochs of empty blocks
    for i in range(2 * bpe):
        block = worker.propose_block(view_id=i + 1)
        beacon.insert_chain([block], verify_seals=False)
        beacon.write_commit_sig(
            block.block_num, b"\x01" * 96 + b"\x0f"
        )
    # elections recorded for epochs 1 and 2
    rawdb.write_shard_state(beacon.db, 1, _elected_state(serialized, 1))
    rawdb.write_shard_state(beacon.db, 2, _elected_state(serialized, 1))

    srv = SyncServer(beacon, listen_port=0)
    try:
        client = SyncClient(srv.port)
        ec = EpochChain(MemKV(), lambda s: serialized)  # engine-less
        feed = EpochFeed(ec, client, blocks_per_epoch=bpe)
        n = feed.feed_once()
        assert n == 2
        assert ec.head_epoch() == 1
        # committees for epochs 1 and 2 now resolve on the shard side
        assert ec.committee_for(1, 1) == serialized
        assert ec.committee_for(1, 2) == serialized
        # idempotent second pass
        assert feed.feed_once() == 0
    finally:
        srv.close()
