"""EpochChain: beacon epoch-boundary light chain (reference:
core/epochchain.go — VERDICT r2 weak #9's missing EpochChain analog)."""

import pytest

from harmony_tpu import bls as B
from harmony_tpu.chain.engine import Engine, EpochContext
from harmony_tpu.chain.header import Header
from harmony_tpu.consensus.mask import Mask
from harmony_tpu.consensus.signature import construct_commit_payload
from harmony_tpu.core.epochchain import EpochChain, EpochChainError
from harmony_tpu.core.kv import MemKV
from harmony_tpu.shard.committee import Committee, Slot, State

N = 4


@pytest.fixture(scope="module")
def committee():
    keys = [B.PrivateKey.generate(bytes([70 + i])) for i in range(N)]
    serialized = [k.pub.bytes for k in keys]
    return keys, serialized


def _seal(header, keys, idx):
    payload = construct_commit_payload(
        header.hash(), header.block_num, header.view_id, True
    )
    sigs = [keys[i].sign_hash(payload) for i in idx]
    agg = B.aggregate_sigs(sigs)
    mask = Mask([k.pub.point for k in keys])
    for i in idx:
        mask.set_bit(i, True)
    return agg.bytes, mask.mask_bytes()


def _elected_state(serialized, shard_id=1):
    return State(epoch=1, shards=[Committee(
        shard_id=shard_id,
        slots=[Slot(ecdsa_address=bytes([i]) * 20, bls_pubkey=k)
               for i, k in enumerate(serialized)],
    )])


def test_epochchain_insert_and_committee_resolution(committee):
    keys, serialized = committee
    eng = Engine(lambda s, e: EpochContext(serialized), device=False)
    ec = EpochChain(MemKV(), lambda s: serialized, engine=eng)
    # genesis committee resolves at epoch 0 without any insert
    assert ec.committee_for(1, 0) == serialized
    assert ec.committee_for(1, 5) == []  # unseen epoch: fail closed

    h = Header(shard_id=0, block_num=16, epoch=0, view_id=16,
               shard_state=b"elected")
    sig, bitmap = _seal(h, keys, [0, 1, 2])
    ec.insert(h, _elected_state(serialized), sig, bitmap)
    assert ec.head_epoch() == 0
    got = ec.header_for_epoch(0)
    assert got is not None and got.hash() == h.hash()
    # next epoch's committee now resolves
    assert ec.committee_for(1, 1) == serialized


def test_epochchain_rejects_bad_seal_and_non_epoch_block(committee):
    keys, serialized = committee
    eng = Engine(lambda s, e: EpochContext(serialized), device=False)
    ec = EpochChain(MemKV(), lambda s: serialized, engine=eng)
    h = Header(shard_id=0, block_num=16, epoch=0, view_id=16)
    sig, bitmap = _seal(h, keys, [0, 1, 2])
    with pytest.raises(EpochChainError):
        ec.insert(h, None, sig, bitmap)  # no shard state: not epoch blk
    # under-quorum seal rejected before any write
    sig2, bitmap2 = _seal(h, keys, [0])
    with pytest.raises(EpochChainError):
        ec.insert(h, _elected_state(serialized), sig2, bitmap2)
    assert ec.head_epoch() is None


def test_epochchain_idempotent_reinsert(committee):
    keys, serialized = committee
    ec = EpochChain(MemKV(), lambda s: serialized)  # no engine: test tier
    h = Header(shard_id=0, block_num=16, epoch=0, view_id=16)
    ec.insert(h, _elected_state(serialized))
    h2 = Header(shard_id=0, block_num=17, epoch=0, view_id=17)
    ec.insert(h2, _elected_state(serialized))  # same epoch: no-op
    assert ec.header_for_epoch(0).hash() == h.hash()
