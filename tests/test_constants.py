"""The committed _constants.py must match a fresh regeneration exactly —
the kernels can never drift from the bigint reference derivation."""

import pathlib

from harmony_tpu.ref import constants_gen


def test_generated_constants_up_to_date():
    target = (
        pathlib.Path(constants_gen.__file__).parent.parent
        / "ops"
        / "_constants.py"
    )
    assert target.read_text() == constants_gen.generate()
