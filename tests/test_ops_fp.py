"""Limb-arithmetic tests: JAX Fp ops vs Python bigints."""

import random

import jax.numpy as jnp
import numpy as np

from harmony_tpu.ops import fp
from harmony_tpu.ops.limbs import ints_to_limbs, limbs_to_int
from harmony_tpu.ref.params import P

rng = random.Random(0xF9)
R = 1 << 384

XS = [rng.randrange(P) for _ in range(16)]
YS = [rng.randrange(P) for _ in range(16)]
A = jnp.asarray(ints_to_limbs(XS))
B = jnp.asarray(ints_to_limbs(YS))


def _ints(arr):
    return [limbs_to_int(np.array(row)) for row in np.asarray(arr)]


def test_add_sub_neg():
    assert _ints(fp.add(A, B)) == [(x + y) % P for x, y in zip(XS, YS)]
    assert _ints(fp.sub(A, B)) == [(x - y) % P for x, y in zip(XS, YS)]
    assert _ints(fp.neg(A)) == [(-x) % P for x in XS]


def test_mont_mul_matches_bigint():
    am = jnp.asarray(ints_to_limbs([x * R % P for x in XS]))
    bm = jnp.asarray(ints_to_limbs([y * R % P for y in YS]))
    got = _ints(fp.mont_mul(am, bm))
    assert got == [x * y * R % P for x, y in zip(XS, YS)]


def test_mont_domain_roundtrip():
    assert _ints(fp.from_mont(fp.to_mont(A))) == XS


def test_inverse():
    am = jnp.asarray(ints_to_limbs([x * R % P for x in XS]))
    prod = fp.mont_mul(fp.inv(am), am)
    assert _ints(prod) == [R % P] * 16  # Montgomery form of 1


def test_edge_values():
    e = jnp.asarray(ints_to_limbs([0, 1, P - 1, P - 1]))
    f2 = jnp.asarray(ints_to_limbs([0, P - 1, P - 1, 1]))
    assert _ints(fp.add(e, f2)) == [0, 0, P - 2, 0]
    assert _ints(fp.neg(e)) == [0, P - 1, 1, 1]
    assert list(np.asarray(fp.is_zero(e))) == [True, False, False, False]


def test_mul_worst_case_carries():
    # p-1 squared exercises maximal limb magnitudes through the CIOS scan
    worst = [P - 1, P - 1, 1, 0] * 4
    wm = jnp.asarray(ints_to_limbs([x * R % P for x in worst]))
    got = _ints(fp.mont_mul(wm, wm))
    assert got == [x * x * R % P for x in worst]
