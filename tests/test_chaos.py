"""Chaos tier: consensus keeps finalizing while the TPU backend flaps
and a sync peer is black-holed (the ISSUE 3 acceptance scenario).

Device kernels are the bigint twins (same trick as test_device_path:
real verify decisions, no XLA pairing compiles on the CPU image) and
``device.use_device(True)`` forces the device branches, so every fault
injected at ``device.dispatch`` hits the REAL dispatch path — breaker,
fallback, counters — not a mock.  All faults are armed through
harmony_tpu.faultinject with fixed counting rules: deterministic,
replayable, seed-free.
"""

import socket
import threading
import time

import numpy as np
import pytest

from harmony_tpu import bls as B
from harmony_tpu import device as DV
from harmony_tpu import faultinject as FI
from harmony_tpu.chain.engine import Engine, EpochContext
from harmony_tpu.chain.header import Header
from harmony_tpu.consensus.mask import Mask
from harmony_tpu.consensus.signature import construct_commit_payload
from harmony_tpu.ops import bls as OB
from harmony_tpu.ops import interop as I
from harmony_tpu.ref import bls as RB
from harmony_tpu.ref.curve import g1
from harmony_tpu.resilience import TRANSITIONS, CircuitBreaker

N_KEYS = 4


def _aff_g1(arr):
    return (I.arr_to_fp(arr[0]), I.arr_to_fp(arr[1]))


def _aff_g2(arr):
    return (I.arr_to_fp2(arr[0]), I.arr_to_fp2(arr[1]))


def _twin_agg_verify(pk_affs, bitmap, h_aff, agg_sig_aff):
    tbl = np.asarray(pk_affs)
    bits = np.asarray(bitmap)
    agg = None
    for i, bit in enumerate(bits):
        if bit:
            agg = g1.add(agg, _aff_g1(tbl[i]))
    if agg is None:
        return np.asarray(False)
    return np.asarray(RB.verify_hashed(
        agg, _aff_g2(np.asarray(h_aff)), _aff_g2(np.asarray(agg_sig_aff))
    ))


def _twin_agg_verify_batch(pk_affs, bitmaps, h_affs, agg_sig_affs):
    return np.asarray([
        bool(_twin_agg_verify(pk_affs, bm, h, s))
        for bm, h, s in zip(
            np.asarray(bitmaps), np.asarray(h_affs),
            np.asarray(agg_sig_affs),
        )
    ])


def _twin_verify(pk_affs, h_affs, sig_affs):
    return np.asarray([
        RB.verify_hashed(_aff_g1(pk), _aff_g2(h), _aff_g2(s))
        for pk, h, s in zip(
            np.asarray(pk_affs), np.asarray(h_affs), np.asarray(sig_affs)
        )
    ])


@pytest.fixture(scope="module", autouse=True)
def force_device_with_twin_kernels():
    DV.use_device(True)
    saved = (OB.agg_verify, OB.agg_verify_batch, OB.verify)
    OB.agg_verify = _twin_agg_verify
    OB.agg_verify_batch = _twin_agg_verify_batch
    OB.verify = _twin_verify
    yield
    OB.agg_verify, OB.agg_verify_batch, OB.verify = saved
    DV.use_device(None)


@pytest.fixture(autouse=True)
def _clean_faults_and_breaker(monkeypatch, request):
    """Fresh faults and a per-test breaker (unique name -> isolated
    transition counters) so chaos state never leaks between tests."""
    FI.reset()
    brk = CircuitBreaker(f"chaos-{request.node.name}"[:60],
                         failure_threshold=3, reset_timeout_s=0.05)
    monkeypatch.setattr(DV, "BREAKER", brk)
    yield brk
    FI.reset()
    DV.set_dispatch_deadline(None)


@pytest.fixture(scope="module")
def committee():
    keys = [B.PrivateKey.generate(bytes([120 + i])) for i in range(N_KEYS)]
    return keys, [k.pub.bytes for k in keys]


def _provider(serialized):
    def provide(shard_id, epoch):
        return EpochContext(serialized)

    return provide


def _sign_header(header, keys, signer_idx):
    payload = construct_commit_payload(
        header.hash(), header.block_num, header.view_id, True
    )
    sigs = [keys[i].sign_hash(payload) for i in signer_idx]
    agg = B.aggregate_sigs(sigs)
    mask = Mask([k.pub.point for k in keys])
    for i in signer_idx:
        mask.set_bit(i, True)
    return agg.bytes, mask.mask_bytes()


def _tcount(brk, event):
    return TRANSITIONS[f"{brk.name}:{event}"]


# -- flapping backend: correctness through the fallback ----------------------


def test_flapping_backend_still_verifies_correctly(committee):
    """Backend raises on EVERY OTHER dispatch: every check still
    returns the host-path answer (accepts AND rejects) via the
    transparent reference fallback."""
    keys, serialized = committee
    FI.arm("device.dispatch", exc=RuntimeError, every=2)
    before = DV.COUNTERS["ref_fallback"]
    dev = Engine(_provider(serialized), device=True)
    host = Engine(_provider(serialized), device=False)
    h = Header(shard_id=0, block_num=77, epoch=5, view_id=77)
    good_sig, good_bm = _sign_header(h, keys, [0, 1, 2])
    bad_sig, _ = _sign_header(h, keys, [0, 1])
    cases = [(good_sig, good_bm), (bad_sig, good_bm)] * 4
    for sig, bm in cases:
        # fresh engines would cache; compare uncached decisions
        assert dev.verify_header_signature(h, sig, bm) == \
            host.verify_header_signature(h, sig, bm)
    assert DV.COUNTERS["ref_fallback"] > before  # fallback really ran
    assert FI.hits("device.dispatch") > 0


def test_flapping_backend_batch_replay_matches_host(committee):
    keys, serialized = committee
    FI.arm("device.dispatch", exc=ConnectionResetError, every=2)
    dev = Engine(_provider(serialized), device=True)
    host = Engine(_provider(serialized), device=False)
    items = []
    prev = bytes(32)
    for n in range(10):
        h = Header(shard_id=0, block_num=300 + n, epoch=6,
                   view_id=300 + n, parent_hash=prev)
        sig, bm = _sign_header(h, keys, [0, 1, 2, 3] if n % 2 else [0, 1, 2])
        items.append((h, sig, bm))
        prev = h.hash()
    items[3] = (items[3][0], items[2][1], items[3][2])  # corrupt one
    got = dev.verify_headers_batch(items)
    want = host.verify_headers_batch(items)
    assert got == want and got[3] is False


# -- breaker lifecycle under sustained failure -------------------------------


def test_breaker_opens_skips_device_then_recovers(committee, monkeypatch):
    """Sustained failures trip the breaker OPEN (observed in metrics);
    while open, dispatches skip the device entirely (fault hits stop
    climbing) yet answers stay correct; after the reset timeout a
    half-open probe re-admits the TPU and the breaker closes.  The
    breaker clock is injected: transitions happen exactly when this
    test advances time, never under it."""
    keys, serialized = committee
    now = [0.0]
    brk = CircuitBreaker("chaos-recovery", failure_threshold=3,
                         reset_timeout_s=10.0, clock=lambda: now[0])
    monkeypatch.setattr(DV, "BREAKER", brk)
    ctx = EpochContext(serialized)
    payload = b"chaos-breaker-payload-32-bytes!!"
    sigs = [keys[i].sign_hash(payload) for i in range(3)]
    agg = B.aggregate_sigs(sigs)
    bits = [1, 1, 1, 0]

    def check():
        return DV.agg_verify_on_device(
            ctx.committee_table(), bits, payload, agg.point
        )

    FI.arm("device.dispatch", exc=RuntimeError)  # hard down
    for _ in range(3):  # threshold=3 consecutive failures
        assert check()  # correct via fallback every time
    assert brk.state == "open"
    assert _tcount(brk, "open") == 1

    hits_when_open = FI.hits("device.dispatch")
    for _ in range(4):
        assert check()  # still correct, device never touched
    assert FI.hits("device.dispatch") == hits_when_open
    assert _tcount(brk, "rejected") >= 4

    FI.reset()  # backend heals
    # passive counting rule (times=0 never fires): keeps the registry
    # armed so hits() still observes device liveness
    FI.arm("device.dispatch", exc=RuntimeError, times=0)
    now[0] = 10.1  # reset timeout elapses
    assert check()  # half-open probe succeeds -> closed
    assert _tcount(brk, "half_open") == 1
    assert _tcount(brk, "close") == 1
    assert brk.state == "closed"
    hits_after = FI.hits("device.dispatch")
    assert check()
    assert FI.hits("device.dispatch") == hits_after + 1  # device live


def test_slow_backend_trips_breaker_via_deadline(committee, monkeypatch):
    """A backend that only STALLS (no exception) trips the breaker
    through the dispatch deadline; results stay correct throughout."""
    keys, serialized = committee
    brk = CircuitBreaker("chaos-slow", failure_threshold=3,
                         reset_timeout_s=60.0)
    monkeypatch.setattr(DV, "BREAKER", brk)
    DV.set_dispatch_deadline(0.01)
    FI.arm("device.dispatch", delay_s=0.05)  # 5x over budget
    ctx = EpochContext(serialized)
    payload = b"chaos-deadline-payload-32-bytes!"
    sigs = [keys[i].sign_hash(payload) for i in range(3)]
    agg = B.aggregate_sigs(sigs)
    for _ in range(3):
        assert DV.agg_verify_on_device(
            ctx.committee_table(), [1, 1, 1, 0], payload, agg.point
        )
    assert brk.state == "open"
    assert _tcount(brk, "open") == 1


def test_breaker_transitions_visible_in_prometheus_exposition(
        committee, _clean_faults_and_breaker):
    from harmony_tpu.metrics import Registry

    keys, serialized = committee
    brk = _clean_faults_and_breaker
    FI.arm("device.dispatch", exc=RuntimeError)
    ctx = EpochContext(serialized)
    payload = b"chaos-metrics-payload-32-bytes!!"
    sigs = [keys[i].sign_hash(payload) for i in range(3)]
    agg = B.aggregate_sigs(sigs)
    for _ in range(3):
        DV.agg_verify_on_device(
            ctx.committee_table(), [1, 1, 1, 0], payload, agg.point
        )
    text = Registry().expose()
    assert ("harmony_resilience_events_total"
            f'{{breaker="{brk.name}",event="open"}} 1') in text


# -- the acceptance scenario -------------------------------------------------


class _BlackHole:
    """A peer that accepts the TCP dial and then says nothing."""

    def __init__(self):
        self.srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(8)
        self.port = self.srv.getsockname()[1]
        self.conns = []
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            self.conns.append(conn)

    def close(self):
        for s in [self.srv] + self.conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


def _chain_with_blocks(n=3):
    from harmony_tpu.core.blockchain import Blockchain
    from harmony_tpu.core.genesis import dev_genesis
    from harmony_tpu.core.kv import MemKV
    from harmony_tpu.core.tx_pool import TxPool
    from harmony_tpu.core.types import Transaction
    from harmony_tpu.node.worker import Worker

    genesis, keys, _ = dev_genesis()
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    pool = TxPool(2, 0, chain.state)
    worker = Worker(chain, pool)
    to = b"\x07" * 20
    for i in range(n):
        tx = Transaction(
            nonce=i, gas_price=1, gas_limit=25_000, shard_id=0,
            to_shard=0, to=to, value=50 + i,
        ).sign(keys[0], 2)
        pool.add(tx)
        block = worker.propose_block(view_id=i + 1)
        chain.insert_chain([block], verify_seals=False)
        chain.write_commit_sig(block.block_num, b"\x01" * 96 + b"\x0f")
        pool.drop_applied()
    return chain, genesis


def test_sync_completes_with_blackholed_peer():
    """Satellite: a peer that times out mid-stage is excluded and the
    stage completes from the remaining peers — one dead peer costs one
    deadline, not a stall."""
    from harmony_tpu.core.blockchain import Blockchain
    from harmony_tpu.core.kv import MemKV
    from harmony_tpu.p2p.stream import SyncClient, SyncServer
    from harmony_tpu.sync import Downloader

    serving, genesis = _chain_with_blocks(4)
    srv = SyncServer(serving)
    hole = _BlackHole()
    try:
        bad = SyncClient(hole.port, timeout=5.0)  # deadline must win
        good = SyncClient(srv.port)
        fresh = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
        dl = Downloader(fresh, [bad, good], batch=2,
                        verify_seals=False, request_deadline_s=0.3)
        t0 = time.monotonic()
        res = dl.sync_once()
        elapsed = time.monotonic() - t0
        assert fresh.head_number == 4 and not res.errors
        assert id(bad) in dl._excluded  # black-holed peer benched
        # one deadline for the dead peer, not one per request/window
        assert elapsed < 5.0
        bad.close()
        good.close()
    finally:
        hole.close()
        srv.close()


def test_fbft_finalizes_block_while_backend_flaps_and_peer_blackholed(
        committee):
    """THE acceptance chaos scenario: device backend raising on every
    other dispatch AND a black-holed sync peer, simultaneously — the
    FBFT round still reaches a committed quorum proof that every
    validator accepts (via the reference fallback), the downloader
    still syncs the committed chain, and the degradation is visible in
    metrics (ref_fallback > 0)."""
    from harmony_tpu.consensus import fbft as FB
    from harmony_tpu.consensus import quorum as Q
    from harmony_tpu.core.blockchain import Blockchain
    from harmony_tpu.core.kv import MemKV
    from harmony_tpu.multibls import PrivateKeys
    from harmony_tpu.p2p.stream import SyncClient, SyncServer
    from harmony_tpu.ref.keccak import keccak256
    from harmony_tpu.sync import Downloader

    keys, serialized = committee
    FI.arm("device.dispatch", exc=RuntimeError, every=2)
    fallback_before = DV.COUNTERS["ref_fallback"]

    cfg = FB.RoundConfig(committee=serialized, block_num=9, view_id=1)
    leader = FB.Leader(
        PrivateKeys.from_keys([keys[0]]), cfg,
        Q.Decider(Q.Policy.UNIFORM, serialized),
    )
    validators = [
        FB.Validator(
            PrivateKeys.from_keys([k]), cfg,
            Q.Decider(Q.Policy.UNIFORM, serialized),
        )
        for k in keys[1:]
    ]
    block = b"chaos block body"
    block_hash = keccak256(block)

    announce = leader.announce(block_hash, block)
    prepares = [v.on_announce(announce) for v in validators]
    for p in prepares:
        assert leader.on_prepare(p)  # vote checks survive the flapping
    prepared = leader.try_prepared(block_hash)
    assert prepared is not None

    commits = [v.on_prepared(prepared) for v in validators]
    assert all(c is not None for c in commits)  # proofs verified
    for c in commits:
        assert leader.on_commit(c)
    committed = leader.try_committed(block_hash)
    assert committed is not None  # the block FINALIZED

    # every validator accepts the committed proof while flapping
    assert all(v.on_committed(committed) for v in validators)
    assert DV.COUNTERS["ref_fallback"] > fallback_before
    assert FI.hits("device.dispatch") > 0

    # ... and the sync layer rides out its black-holed peer in the
    # same chaotic process
    serving, genesis = _chain_with_blocks(3)
    srv = SyncServer(serving)
    hole = _BlackHole()
    try:
        bad = SyncClient(hole.port, timeout=5.0)
        good = SyncClient(srv.port)
        fresh = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
        dl = Downloader(fresh, [bad, good], batch=2,
                        verify_seals=False, request_deadline_s=0.3)
        res = dl.sync_once()
        assert fresh.head_number == 3 and not res.errors
        assert id(bad) in dl._excluded
        bad.close()
        good.close()
    finally:
        hole.close()
        srv.close()
