"""WAN netem tier (ISSUE 15): link-spec parsing edge cases, seed
determinism of the delivery schedule, match precedence, rate-cap
queuing, both transport integrations (in-process hub chokepoint +
TCPHost publish path), the zero-cost-disarmed claim, the sync
downloader's EWMA peer ordering, and the vc_timeout ladder pinned
against a fixed netem delay."""

from __future__ import annotations

import time

import pytest

from harmony_tpu.chaostest import netem as NE
from harmony_tpu.chaostest.netem import Decision, LinkRule, NetEm


# -- link-spec parsing -------------------------------------------------------


def test_parse_full_string_grammar():
    r = NE.parse_link(
        "a->b delay=300ms jitter=50ms loss=5% dup=1% reorder=10% "
        "rate=1mbps"
    )
    assert (r.src, r.dst) == ("a", "b")
    assert r.delay_ms == 300.0 and r.jitter_ms == 50.0
    assert r.loss == pytest.approx(0.05)
    assert r.dup == pytest.approx(0.01)
    assert r.reorder == pytest.approx(0.10)
    assert r.rate_bytes_per_s == 1e6


def test_parse_units_and_defaults():
    assert NE.parse_link("a->b delay=1.5s").delay_ms == 1500.0
    assert NE.parse_link("a->b delay=40").delay_ms == 40.0  # bare = ms
    assert NE.parse_link("a->b loss=0.25").loss == 0.25
    assert NE.parse_link("a->b rate=64k").rate_bytes_per_s == 64000.0
    assert NE.parse_link("a->b rate=512").rate_bytes_per_s == 512.0
    r = NE.parse_link("a->b")
    assert r.loss == 0.0 and r.delay_ms == 0.0 and r.dup == 0.0


def test_parse_wildcards_and_rtt_range():
    r = NE.parse_link("*->* rtt=50..150ms jitter=10ms loss=0.5%")
    assert r.src == "*" and r.dst == "*"
    assert r.rtt_ms == (50.0, 150.0)
    assert r.loss == pytest.approx(0.005)
    # one-sided wildcard via empty endpoint
    r2 = NE.parse_link("a-> loss=1")
    assert (r2.src, r2.dst) == ("a", "*") and r2.loss == 1.0


def test_parse_dict_spec_and_tagging():
    r = NE.parse_link(
        {"src": "x", "dst": "*", "delay_ms": 10, "rtt_ms": [20, 40]},
        tag="phase:p",
    )
    assert r.rtt_ms == (20.0, 40.0) and r.tag == "phase:p"


@pytest.mark.parametrize("bad", [
    "a->b loss=1.5",            # probability above 1
    "a->b loss=-0.1",           # negative probability
    "a->b delay=-5ms",          # negative delay
    "a->b speed=3",             # unknown key
    "a->b delay",               # bare token, no =
    "delay=3ms",                # missing src->dst
    "a->b rtt=50ms",            # rtt without a range
    "a->b rtt=150..50ms",       # inverted range
    "a->b rate=fast",           # unparseable rate
    "a->b delay=xms",           # unparseable duration
    {"src": "a", "dst": "b", "bogus": 1},  # unknown dict field
    42,                         # not a spec at all
])
def test_parse_rejects_malformed_specs(bad):
    with pytest.raises(ValueError):
        NE.parse_link(bad)


def test_partition_rules_are_total_loss_both_ways():
    rules = NE.partition_rules("s0n2", tag="phase:x")
    assert len(rules) == 2
    assert {(r.src, r.dst) for r in rules} == {
        ("s0n2", "*"), ("*", "s0n2"),
    }
    assert all(r.loss == 1.0 and r.tag == "phase:x" for r in rules)
    nm = NetEm(seed=1)
    nm.add(*rules)
    assert nm.decide("s0n2", "s0n1", 10).drop
    assert nm.decide("s0n1", "s0n2", 10).drop
    assert nm.decide("s0n0", "s0n1", 10) is None  # third parties clean


# -- determinism -------------------------------------------------------------


def _script(seed: int):
    """One scripted event sequence -> its full conditioning schedule
    (drop set, per-copy delays, duplicate count, reorder flags, and
    the delivery ORDER by due time)."""
    nm = NetEm(seed=seed)
    nm.add({"src": "*", "dst": "*", "delay_ms": 40.0,
            "jitter_ms": 20.0, "loss": 0.2, "dup": 0.15,
            "reorder": 0.1})
    events, order = [], []
    for i in range(400):
        src, dst = f"n{i % 4}", f"n{(i + 1 + i // 7) % 4}"
        d = nm.decide(src, dst, 100 + i)
        events.append((src, dst, d.drop, d.delays, d.reordered))
        if not d.drop:
            for c, dl in enumerate(d.delays):
                order.append((dl, i, c))
    order.sort()
    return repr(events), repr(order)


def test_same_seed_identical_delivery_schedule():
    assert _script(9) == _script(9)


def test_different_seed_different_schedule():
    assert _script(9) != _script(10)


def test_schedule_exercises_every_event_class():
    nm = NetEm(seed=9)
    nm.add({"src": "*", "dst": "*", "delay_ms": 40.0,
            "jitter_ms": 20.0, "loss": 0.2, "dup": 0.15,
            "reorder": 0.1})
    drops = dups = reorders = 0
    for i in range(400):
        d = nm.decide("a", "b", i)
        drops += d.drop
        dups += (not d.drop and len(d.delays) == 2)
        reorders += d.reordered
    # probabilistic but SEEDED: these are exact, repeatable counts
    assert drops and dups and reorders
    assert 0.1 < drops / 400 < 0.3


def test_pair_rtt_stable_and_asymmetric():
    nm = NetEm(seed=3)
    (rule,) = nm.add("*->* rtt=50..150ms")
    ab = nm.pair_rtt_ms(rule, "a", "b")
    assert 50.0 <= ab <= 150.0
    assert nm.pair_rtt_ms(rule, "a", "b") == ab  # stable per pair
    # the directed pairs draw independently: A->B and B->A condition
    # independently (first-class asymmetry)
    assert nm.pair_rtt_ms(rule, "b", "a") != ab
    # and the one-way delay is RTT/2
    d = nm.decide("a", "b", 10)
    assert d.delays[0] == pytest.approx(ab / 2e3)


# -- matching + rate cap -----------------------------------------------------


def test_match_most_specific_wins_then_last_installed():
    nm = NetEm(seed=1)
    nm.add("*->* delay=10ms")
    nm.add("a->* delay=20ms")
    nm.add("*->b delay=30ms")
    nm.add("a->b delay=40ms")
    assert nm.decide("a", "b", 1).delays[0] == pytest.approx(0.040)
    assert nm.decide("a", "c", 1).delays[0] == pytest.approx(0.020)
    assert nm.decide("c", "b", 1).delays[0] == pytest.approx(0.030)
    assert nm.decide("c", "d", 1).delays[0] == pytest.approx(0.010)
    nm.add("a->b delay=50ms")  # same specificity: later wins
    assert nm.decide("a", "b", 1).delays[0] == pytest.approx(0.050)


def test_remove_tag_heals_only_that_phase():
    nm = NetEm(seed=1)
    nm.add("a->b loss=1", tag="phase:one")
    nm.add("c->d loss=1", tag="phase:two")
    assert nm.remove_tag("phase:one") == 1
    assert nm.decide("a", "b", 1) is None
    assert nm.decide("c", "d", 1).drop
    nm.clear()
    assert not nm.armed


def test_rate_cap_store_and_forward_queuing():
    clk = [0.0]
    nm = NetEm(seed=1, clock=lambda: clk[0])
    nm.add("a->b rate=1000")  # 1000 bytes/s
    assert nm.decide("a", "b", 500).delays[0] == pytest.approx(0.5)
    # second message queues behind the first's transmission
    assert nm.decide("a", "b", 500).delays[0] == pytest.approx(1.0)
    clk[0] = 10.0  # link long idle: no queue, only its own tx time
    assert nm.decide("a", "b", 250).delays[0] == pytest.approx(0.25)


# -- in-process hub integration ----------------------------------------------


def _hub(names=("a", "b", "c")):
    from harmony_tpu.p2p import InProcessNetwork

    net = InProcessNetwork()
    hosts = {n: net.host(n) for n in names}
    inbox: dict = {n: [] for n in names}
    for n, h in hosts.items():
        h.subscribe("t", lambda _t, p, frm, n=n: inbox[n].append(
            (frm, p)
        ))
    return net, hosts, inbox


def test_hub_disarmed_is_synchronous_and_threadless():
    net, hosts, inbox = _hub()
    assert net.netem is None
    hosts["a"].publish("t", b"x")
    # no conditioner: delivery happened INLINE, before publish returned
    assert inbox["b"] == [("a", b"x")] and inbox["c"] == [("a", b"x")]


def test_hub_armed_nonmatching_stays_inline():
    net, hosts, inbox = _hub()
    net.netem = NetEm(seed=1)
    net.netem.add("x->y delay=500ms")  # matches nobody here
    hosts["a"].publish("t", b"x")
    assert inbox["b"] == [("a", b"x")]
    assert net.netem._thread is None  # scheduler never spawned
    net.netem.close()


def test_hub_loss_is_asymmetric():
    net, hosts, inbox = _hub()
    net.netem = NetEm(seed=1)
    net.netem.add("a->b loss=1")
    hosts["a"].publish("t", b"ping")
    hosts["b"].publish("t", b"pong")
    time.sleep(0.05)
    assert inbox["b"] == []                    # a->b black-holed
    assert ("a", b"ping") in inbox["c"]        # a->c untouched
    assert ("b", b"pong") in inbox["a"]        # b->a untouched
    assert net.netem.totals()["dropped"] == 1
    net.netem.close()


def test_hub_delay_defers_then_delivers():
    net, hosts, inbox = _hub()
    net.netem = NetEm(seed=1)
    net.netem.add("a->* delay=120ms")
    hosts["a"].publish("t", b"slow")
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and (
        not inbox["b"] or not inbox["c"]
    ):
        time.sleep(0.01)
    assert inbox["b"] == [("a", b"slow")]
    assert inbox["c"] == [("a", b"slow")]
    assert net.netem.totals()["delayed"] == 2
    net.netem.close()


def test_hub_duplication_delivers_both_copies():
    net, hosts, inbox = _hub(("a", "b"))
    net.netem = NetEm(seed=1)
    net.netem.add("a->b delay=20ms dup=100%")
    hosts["a"].publish("t", b"twice")
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and len(inbox["b"]) < 2:
        time.sleep(0.01)
    assert inbox["b"] == [("a", b"twice")] * 2
    assert net.netem.totals()["duplicated"] == 1
    net.netem.close()


def test_hub_delayed_delivery_skips_late_partition():
    """A message in flight when its destination is partitioned must
    NOT arrive: the chokepoint re-checks partition state at delivery
    time."""
    net, hosts, inbox = _hub(("a", "b"))
    net.netem = NetEm(seed=1)
    net.netem.add("a->b delay=150ms")
    hosts["a"].publish("t", b"late")
    net.partitioned.add("b")
    time.sleep(0.4)
    assert inbox["b"] == []
    net.partitioned.clear()
    net.netem.close()


def test_netem_metrics_exposition():
    net, hosts, _ = _hub(("a", "b"))
    net.netem = NetEm(seed=1)
    net.netem.add("a->b loss=1")
    hosts["a"].publish("t", b"x")
    text = NE.expose()
    assert "# TYPE harmony_netem_events_total counter" in text
    assert 'harmony_netem_events_total{event="dropped",rule="a->b"}' \
        in text
    # and the process registry carries the family (module imported)
    from harmony_tpu.metrics import Registry

    assert "harmony_netem_events_total" in Registry().expose()
    net.netem.close()


# -- TCPHost publish path ----------------------------------------------------


def test_tcphost_publish_path_conditioned():
    from harmony_tpu.p2p.host import TCPHost

    a = TCPHost(name="wan-a")
    b = TCPHost(name="wan-b")
    got = []
    b.subscribe("t", lambda _t, p, frm: got.append((frm, p)))
    try:
        a.connect(b.port)
        assert a.wait_for_peers(1) and b.wait_for_peers(1)
        a.netem = NetEm(seed=2)
        a.netem.add("wan-a->wan-b delay=100ms")
        a.publish("t", b"over-the-wan")
        deadline = time.monotonic() + 8.0
        while time.monotonic() < deadline and not got:
            time.sleep(0.02)
        assert got and got[0][1] == b"over-the-wan"
        assert a.netem.totals()["delayed"] >= 1
    finally:
        if a.netem is not None:
            a.netem.close()
        a.close()
        b.close()


# -- sync downloader: EWMA peer ordering (ISSUE 15 satellite) ---------------


class _StubClient:
    pass


def test_downloader_ewma_orders_slow_peers_last():
    from harmony_tpu.sync.staged import Downloader

    a, b, c = _StubClient(), _StubClient(), _StubClient()
    dl = Downloader(chain=None, clients=[a, b, c], verify_seals=False)
    # unmeasured: configured order (stable sort at EWMA 0)
    assert dl._peers() == [a, b, c]
    # the drip-feeder: answers just under the deadline every window —
    # before the EWMA ordering it won every _fetch_window race forever
    for _ in range(4):
        dl._note_latency(a, 1.9)
        dl._note_latency(b, 0.05)
        dl._note_latency(c, 0.2)
    assert dl._peers() == [b, c, a]
    # exclusion still per-pass, on top of the ordering
    dl._excluded.add(id(b))
    assert dl._peers() == [c, a]
    dl._excluded.clear()
    # one fast answer does not erase a slow history (EWMA, not last)
    dl._note_latency(a, 0.01)
    assert dl._peers()[0] is b and dl._peers()[-1] is a


def test_downloader_call_feeds_ewma():
    from harmony_tpu.sync.staged import Downloader

    c1 = _StubClient()
    dl = Downloader(chain=None, clients=[c1], verify_seals=False)
    assert dl._call(c1, lambda x: x + 1, 41) == 42
    assert id(c1) in dl._lat
    # a raising call leaves the EWMA untouched (exclusion handles it)
    before = dict(dl._lat)
    with pytest.raises(ConnectionError):
        dl._call(c1, _raise)
    assert dl._lat == before


def _raise():
    raise ConnectionError("peer gone")


# -- vc_timeout ladder vs a fixed netem delay (ISSUE 15 satellite) ----------


def test_vc_timeout_ladder_outpaces_fixed_netem_delay():
    """The de-sync class PR 8 fixed, pinned against LATENCY rather
    than loss: under a fixed netem one-way delay D, one full
    view-change exchange needs ~2 hops (VC vote out, NEWVIEW back).
    A CONSTANT timeout below 2D times out every view forever and the
    committee never converges; the escalating vc_timeout ladder
    (base * min(1+vc, 8)) must cross 2D at a predictable escalation —
    and its 8x cap keeps a truly dead network bounded."""
    from harmony_tpu.node.node import Node

    nm = NetEm(seed=3)
    nm.add("*->* delay=450ms")
    d = nm.decide("v0", "v1", 256)
    one_way = d.delays[0]
    assert one_way == pytest.approx(0.45)  # fixed: no jitter armed
    # the netem schedule is deterministic: every hop costs exactly D
    assert nm.decide("v1", "v0", 256).delays[0] == one_way
    exchange = 2 * one_way

    node = Node.__new__(Node)  # vc_timeout reads only these two
    node.phase_timeout = 0.2
    # constant timeout (the bug class): base < exchange, every rung
    # identical, never outpaces the wire
    node._vc = 0
    assert all(node.vc_timeout() < exchange for _ in range(16))
    # the ladder: grows linearly until a window fits the exchange
    converged_at = None
    for k in range(16):
        node._vc = k
        if node.vc_timeout() > exchange:
            converged_at = k
            break
    # 0.2 * (1+4) = 1.0 > 0.9: escalation 4, deterministically
    assert converged_at == 4
    # and the reference's 8x cap bounds the ladder: past-cap latency
    # is a dead network, not a slow one
    node._vc = 100
    assert node.vc_timeout() == pytest.approx(0.2 * 8)


# -- scenario vocabulary ----------------------------------------------------


def test_new_scenarios_registered_and_buildable():
    from harmony_tpu.chaostest.scenarios import SCENARIOS

    for name in ("gray_leader", "asymmetric_partition",
                 "minority_partition_heal", "wan_committee"):
        s = SCENARIOS[name](quick=True)
        assert s.name == name and s.phases
    wan = SCENARIOS["wan_committee"](quick=True)
    assert wan.topology.committee_size >= 64
    # the WAN matrix spec parses through the production grammar
    rule = NE.parse_link(wan.phases[0].links[0])
    assert rule.rtt_ms == (50.0, 150.0)
    heal = SCENARIOS["minority_partition_heal"](quick=True)
    assert heal.phases[0].cut_sync and heal.phases[0].measure_heal
