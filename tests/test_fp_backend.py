"""FP_BACKEND=pallas wiring parity (VERDICT r3 #2).

ops/fp.py::mont_mul is the single chokepoint every Fp product in the
framework flows through — tower muls, curve adds, the Miller loop, the
final exponentiation.  These tests flip the backend to the Pallas
kernel (interpret mode on CPU) and assert bit-identical results against
the scan path at each tier the fast suite can afford on this box:
raw mont_mul (incl. the lane-padding path), the Fp2/Fp12 towers, and a
G1 point-double.  The full-pairing GT comparison lives in the isolated
heavy tier (test_ops_heavy_isolated.py) because any pairing-shaped
program costs 20+ min of XLA:CPU compile here (docs/NOTES_r3.md).
"""

import numpy as np
import pytest

from harmony_tpu.ops import fp
from harmony_tpu.ops import _constants as C
from harmony_tpu.ops.limbs import int_to_limbs, limbs_to_int

P = C.P_INT
rng = np.random.default_rng(42)


def _rand_fp(shape=()):
    flat = [rng.integers(0, 2**63, size=7) for _ in range(int(np.prod(shape)) or 1)]
    vals = [int.from_bytes(np.asarray(f, dtype=np.uint64).tobytes(), "little") % P
            for f in flat]
    arr = np.stack([int_to_limbs(v) for v in vals])
    return arr.reshape(*shape, arr.shape[-1]) if shape else arr[0], vals


@pytest.fixture
def pallas_backend():
    fp.set_backend("pallas-interpret")
    yield
    fp.set_backend("scan")


def _both_backends(fn):
    fp.set_backend("scan")
    want = np.asarray(fn())
    fp.set_backend("pallas-interpret")
    try:
        got = np.asarray(fn())
    finally:
        fp.set_backend("scan")
    return want, got


def test_mont_mul_parity_small_batch():
    a, _ = _rand_fp((5,))
    b, _ = _rand_fp((5,))
    want, got = _both_backends(lambda: fp.mont_mul(a, b))
    np.testing.assert_array_equal(want, got)


def test_mont_mul_parity_lane_padding():
    # 131 rows: exercises the pad-to-128 path and a 2-tile grid
    a, _ = _rand_fp((131,))
    b, _ = _rand_fp((131,))
    want, got = _both_backends(lambda: fp.mont_mul(a, b))
    np.testing.assert_array_equal(want, got)


def test_mont_mul_pallas_is_correct_vs_bigint(pallas_backend):
    a, av = _rand_fp((3,))
    b, bv = _rand_fp((3,))
    out = np.asarray(fp.mont_mul(a, b))
    r_inv = pow(1 << 384, P - 2, P)
    for row, x, y in zip(out, av, bv):
        assert limbs_to_int(row) == x * y * r_inv % P


def test_tower_mul_parity():
    from harmony_tpu.ops import towers as T

    a, _ = _rand_fp((2, 2))  # one Fp2 element batch of 2: (2, 2, 32)
    b, _ = _rand_fp((2, 2))
    want, got = _both_backends(lambda: T.fp2_mul(a, b))
    np.testing.assert_array_equal(want, got)


def test_fp12_mul_parity():
    from harmony_tpu.ops import towers as T

    a, _ = _rand_fp((2, 3, 2))  # one Fp12 element (2, 3, 2, 32)
    b, _ = _rand_fp((2, 3, 2))
    want, got = _both_backends(lambda: T.fp12_mul(a, b))
    np.testing.assert_array_equal(want, got)


def test_g1_double_parity():
    from harmony_tpu.ops import curve as CV
    from harmony_tpu.ops import interop as I
    from harmony_tpu.ref.curve import G1_GEN

    pt = I.g1_affine_to_jacobian_arr(G1_GEN)

    def run():
        x, y, z = CV.dbl(pt, CV.FP_OPS)
        return np.stack([np.asarray(x), np.asarray(y), np.asarray(z)])

    want, got = _both_backends(run)
    np.testing.assert_array_equal(want, got)
