"""Health subsystem tier (ISSUE 14): heartbeat states, watchdog
detection + flight-recorder evidence + supervised restart, the
/healthz + /readyz endpoints, and the metrics families."""

import json
import threading
import time
import urllib.request

import pytest

from harmony_tpu import health as HL
from harmony_tpu import trace


@pytest.fixture(autouse=True)
def _clean():
    HL.reset()
    trace.reset()
    yield
    HL.reset()
    trace.reset()


# -- heartbeat states ---------------------------------------------------------


def test_states_ok_stale_idle_closed():
    HL.configure(enabled=False)  # pure bookkeeping: no watchdog thread
    hb = HL.register("a", max_age_s=0.05)
    assert hb.state() == "ok"
    time.sleep(0.08)
    assert hb.state() == "stale"  # busy + silent past max_age
    hb.beat()
    assert hb.state() == "ok"
    hb.idle()
    time.sleep(0.08)
    assert hb.state() == "idle"  # declared-healthy parking never stales
    hb.close()
    assert hb.state() == "closed"
    assert all(p.name != "a" for p in HL.participants())


def test_dead_thread_state():
    HL.configure(enabled=False)
    t = threading.Thread(target=lambda: None)
    t.start()
    t.join()
    hb = HL.register("gone", thread=t, max_age_s=100.0)
    assert hb.state() == "dead"  # thread liveness beats beat age


def test_close_is_identity_guarded():
    """A moribund participant closing late must not deregister the
    successor that took its name."""
    HL.configure(enabled=False)
    old = HL.register("reader")
    new = HL.register("reader")  # replacement (redial path)
    old.close()
    assert HL.participants() == [new]


# -- the watchdog -------------------------------------------------------------


def test_watchdog_detects_stale_dumps_once_and_sees_recovery(tmp_path):
    HL.configure(enabled=False)  # drive check_once deterministically
    trace.configure(enabled=True, dump_dir=str(tmp_path),
                    dump_cooldown_s=0)
    hb = HL.register("wedgy", max_age_s=0.05)
    time.sleep(0.08)
    assert HL.check_once()["wedgy"] == "stale"
    assert HL.EVENTS["stale"] == 1
    dumps = [p for p in trace.dumps()]
    assert len(dumps) == 1
    assert json.load(open(dumps[0]))["kind"] == "watchdog.wedgy"
    # still stale next sweep: no double count, no second dump
    assert HL.check_once()["wedgy"] == "stale"
    assert HL.EVENTS["stale"] == 1
    assert len(trace.dumps()) == 1
    # the thread beats again: recovery observed exactly once
    hb.beat()
    assert HL.check_once()["wedgy"] == "ok"
    assert HL.EVENTS["recovered"] == 1


def test_watchdog_restarts_dead_participant():
    HL.configure(enabled=False)
    t = threading.Thread(target=lambda: None)
    t.start()
    t.join()
    revived = []

    def restart():
        live = threading.Thread(target=time.sleep, args=(5.0,),
                                daemon=True)
        live.start()
        hb.bind(live)
        revived.append(live)

    hb = HL.register("svc", thread=t, restart=restart)
    states = HL.check_once()
    assert states["svc"] == "dead"
    assert HL.EVENTS["dead"] == 1
    assert HL.EVENTS["restart"] == 1
    assert revived and hb.state() == "ok"


def test_watchdog_restart_failure_is_counted_not_fatal():
    HL.configure(enabled=False)
    t = threading.Thread(target=lambda: None)
    t.start()
    t.join()

    def broken():
        raise RuntimeError("no resurrection today")

    HL.register("doomed", thread=t, restart=broken)
    HL.check_once()  # must not raise
    assert HL.EVENTS["restart_failed"] == 1


def test_close_while_flagged_counts_recovery():
    """A wedged participant exiting through its own fail-closed path
    (reader drops the connection, client redials) IS a recovery."""
    HL.configure(enabled=False)
    hb = HL.register("reader", max_age_s=0.05)
    time.sleep(0.08)
    HL.check_once()
    assert HL.EVENTS["stale"] == 1
    hb.close(reason="desync")
    assert HL.EVENTS["recovered"] == 1


def test_registry_cardinality_bound():
    HL.configure(enabled=False)
    keeper = HL.register("keeper", critical=True)
    for i in range(HL._MAX_PARTICIPANTS + 8):
        HL.register(f"transient{i}")
    names = {p.name for p in HL.participants()}
    assert len(names) <= HL._MAX_PARTICIPANTS
    assert keeper.name in names  # critical entries outlive the purge


# -- verdict surfaces ---------------------------------------------------------


def test_verdicts_and_critical_gating():
    HL.configure(enabled=False)
    HL.register("fine")
    sick = HL.register("sick", max_age_s=0.01)
    time.sleep(0.03)
    v = HL.verdicts()
    assert v["ok"] is True  # degraded but not critical
    assert v["degraded"] == ["sick"]
    assert v["participants"]["sick"]["state"] == "stale"
    sick.critical = True
    assert HL.verdicts()["ok"] is False
    assert HL.healthy() is False


def test_readiness_reflects_governor_tier():
    from harmony_tpu import governor as GV

    HL.configure(enabled=False)
    HL.register("pump", critical=True)
    assert HL.readiness()["ready"] is True
    gov = GV.ResourceGovernor(sample_fn=lambda: {})
    gov._state = GV.Tier.CRITICAL
    GV.install(gov)
    try:
        r = HL.readiness()
        assert r["ready"] is False
        assert r["governor"] == "critical"
        assert r["health_ok"] is True  # alive, just shedding
    finally:
        GV.uninstall()


def test_healthz_readyz_http(tmp_path):
    """The MetricsServer serves both probes with 200/503 semantics."""
    from harmony_tpu.metrics import MetricsServer, Registry

    HL.configure(enabled=False)
    pump = HL.register("pump", critical=True, max_age_s=0.2)
    srv = MetricsServer(Registry(), port=0).start()
    try:
        def get(path):
            try:
                resp = urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}{path}", timeout=10
                )
                return resp.status, json.load(resp)
            except urllib.error.HTTPError as e:
                return e.code, json.load(e)

        status, body = get("/healthz")
        assert status == 200 and body["ok"] is True
        assert "pump" in body["participants"]
        status, body = get("/readyz")
        assert status == 200 and body["ready"] is True
        time.sleep(0.3)  # the critical pump goes silent -> stale
        status, body = get("/healthz")
        assert status == 503 and body["ok"] is False
        assert body["participants"]["pump"]["state"] == "stale"
        status, body = get("/readyz")
        assert status == 503 and body["ready"] is False
        pump.beat()
        status, _ = get("/healthz")
        assert status == 200
    finally:
        srv.stop()


# -- metrics ------------------------------------------------------------------


def test_exposition_families(tmp_path):
    from harmony_tpu.metrics import Registry

    HL.configure(enabled=False)
    hb = HL.register("pump", max_age_s=0.05)
    time.sleep(0.08)
    HL.check_once()
    hb.beat()
    HL.check_once()
    hb.max_age_s = 60.0  # the scrape below must see it healthy
    hb.beat()
    text = Registry().expose()
    assert 'harmony_health_up{participant="pump"} 1' in text
    assert "harmony_health_beat_age_seconds" in text
    assert 'harmony_health_watchdog_total{event="stale"} 1' in text
    assert 'harmony_health_watchdog_total{event="recovered"} 1' in text
    # the process gauges (ISSUE 14 satellite) ride the same exposition
    assert "harmony_process_threads" in text
    from harmony_tpu.metrics import process_sample

    s = process_sample()
    if s["rss_bytes"] is not None:
        assert "harmony_process_rss_bytes" in text
    if s["open_fds"] is not None:
        assert "harmony_process_open_fds" in text


def test_process_sample_shape():
    from harmony_tpu.metrics import process_sample

    s = process_sample()
    assert set(s) == {"rss_bytes", "open_fds", "threads"}
    assert s["threads"] >= 1
    if s["rss_bytes"] is not None:
        assert s["rss_bytes"] > 1 << 20  # a Python process holds >1MiB
    if s["open_fds"] is not None:
        assert s["open_fds"] >= 3  # stdio at minimum


# -- the live watchdog thread -------------------------------------------------


def test_live_watchdog_end_to_end(tmp_path):
    """Real watchdog thread: a busy participant goes silent, the
    watchdog flags it within its check interval, then sees recovery."""
    trace.configure(enabled=True, dump_dir=str(tmp_path),
                    dump_cooldown_s=0)
    HL.configure(check_interval_s=0.05)
    hb = HL.register("slow", max_age_s=0.1)
    deadline = time.monotonic() + 5.0
    while HL.EVENTS["stale"] < 1 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert HL.EVENTS["stale"] == 1
    hb.beat()
    deadline = time.monotonic() + 5.0
    while HL.EVENTS["recovered"] < 1 and time.monotonic() < deadline:
        hb.beat()
        time.sleep(0.02)
    assert HL.EVENTS["recovered"] == 1
    assert any(
        json.load(open(p))["kind"] == "watchdog.slow"
        for p in trace.dumps()
    )
