"""herumi/mcl interop ciphersuite vectors (ref/herumi.py).

All vectors are data vendored from the reference repo — the outputs of
the herumi library the real chain runs, not its code:

* SK_HEX / PK_HEX: the (secret, public) pair hardcoded in reference
  core/tx_pool_test.go:52-53 (same pair in test/chain/reward/main.go).
* MAINNET_PUBKEYS: the first 16 foundational-committee BLS public keys
  from reference internal/genesis/foundational.go:5-20 — real mainnet
  wire bytes.
"""

import pytest

from harmony_tpu.ref import herumi as H
from harmony_tpu.ref.curve import g1, g2
from harmony_tpu.ref.params import G1_X, G1_Y, R_ORDER

SK_HEX = "c6d7603520311f7a4e6aac0b26701fc433b75b38df504cd416ef2b900cd66205"
PK_HEX = (
    "30b2c38b1316da91e068ac3bd8751c0901ef6c02a1d58bc712104918302c6ed0"
    "3d5894671d0c816dad2b4d303320f202"
)

MAINNET_PUBKEYS = [
    "9e70e8d76851f6e8dc648255acdd57bb5c49cdae7571aed43f86e9f140a6343caed2ffa860919d03e0912411fee4850a",
    "fce3097d9fc234d34d6eaef3eecd0365d435d1118f69f2da1ed2a69ba725270771572e40347c222aca784cb973307b11",
    "edb61007e99af30191098f2cd6f787e2f53fb595bf63fcb4d31a386e7070f7a4fdcefd3e896080a665dc19fecbafc306",
    "475b5c3bbbda60cd92951e44bbea2aac63f1b774652d6bbec86aaed0dabd10a46717e98763d559b63bc4f1bfbde66908",
    "f7af1b02f35cdfb3ef2ac7cdccb87cf20f5411922170e4e191d57d6d1f52901a7c6e363d266a1c86bb1aef651bd1ae96",
    "f400d1caa1f40a14d870640c50d895205014f5b54c3aa9661579b937ea5bcc2f159b9bbb8075b516628f545af822180f",
    "bfa025fd7799315e528be8a985d1ab4a90506fca94db7e1f88d29d0f8e8221af742a0f8e9f7f9fbe71c1beca2a6c9690",
    "eb4d1c141fc6319f32710212b78b88a045ce95437025bfca56ec399cdcd469d1c49081025f859e09b35249cf2cc6bf06",
    "bbd0b173ace9f35c22eb80fe4673497f55c7039f089a3444a329f760f0d4a335927bb7d94a70b817c405351570f3d411",
    "714fb47f27b4d300320e06e37e973e0a9cfa647f7bdb915262d7fe500252a777f37d8d358dc07b27c7eef88a7521ad06",
    "663f82d48ff61d09bb215836f853e838df7da62aa90344dcf7950c18378dae909895c0c179c2dd71ea77fa747af53106",
    "1e9f5f68845634efca8a64e8ffcf90d63ec196f28fb64f688fb88b868728ab562b702af8414f48c5d045e94433ec5a87",
    "43b1376eff41dfdccaeb601edc09b4353e5abd343a90740ecb3f9aac882321361e01267ffd2a0e2115755b5148b1f115",
    "43f5ed2b60cb88c64dc16c4c3527943eb92a15f75967cf37ef3a9a8171da5a59685c198c981a9fd471ffc299fe699887",
    "b01f1752fdbe3d21cc9cf9dc3d1a781b216fae48d34a4c3866e36cc686c4d955f66d9bd0bd608ccb3b54565c9125fc12",
    "23ab4b6415a53e3ac398b53e9df5376f28c024e3d300fa9a6ed8c3c867929c43e81f978f8ba02bacd5f956dc2d3a6399",
]


def test_reference_keypair_roundtrips_exactly():
    """sk -> pk must reproduce the reference's bytes bit-for-bit: this
    pins the Fr endianness, the BLS_SWAP_G base point, and the G1
    serialization (LE + odd-y MSB flag) all at once."""
    sk = H.fr_from_bytes(bytes.fromhex(SK_HEX))
    pk = H.pubkey(sk)
    assert H.g1_serialize(pk).hex() == PK_HEX
    assert H.g1_deserialize(bytes.fromhex(PK_HEX)) == pk
    assert H.fr_to_bytes(sk).hex() == SK_HEX


def test_base_point_is_in_subgroup_and_nonstandard():
    assert g1.mul(H.HERUMI_G1, R_ORDER) is None  # r-torsion
    assert H.HERUMI_G1 != (G1_X, G1_Y)  # NOT the IETF generator


@pytest.mark.parametrize("hexkey", MAINNET_PUBKEYS)
def test_mainnet_genesis_pubkeys_roundtrip(hexkey):
    """Every real mainnet committee key must deserialize to a valid
    r-torsion G1 point and re-serialize byte-identically."""
    data = bytes.fromhex(hexkey)
    pt = H.g1_deserialize(data)
    assert pt is not None
    assert H.g1_serialize(pt) == data


def test_g1_rejects_out_of_range_and_bad_points():
    from harmony_tpu.ref.params import P

    bad = bytearray(P.to_bytes(48, "little"))
    with pytest.raises(ValueError):
        H.g1_deserialize(bytes(bad))
    with pytest.raises(ValueError):
        H.g1_deserialize(b"\x01" + bytes(46))  # wrong length
    assert H.g1_deserialize(bytes(48)) is None  # infinity
    assert H.g1_serialize(None) == bytes(48)


def test_g2_signature_roundtrip():
    sk = H.fr_from_bytes(bytes.fromhex(SK_HEX))
    sig = H.sign_hash(sk, b"\x11" * 32)
    data = H.g2_serialize(sig)
    assert len(data) == 96
    assert H.g2_deserialize(data) == sig
    assert H.g2_deserialize(bytes(96)) is None
    assert H.g2_serialize(None) == bytes(96)


def test_sign_hash_verify_and_reject():
    sk = H.fr_from_bytes(bytes.fromhex(SK_HEX))
    pk = H.pubkey(sk)
    msg = b"\x22" * 32
    sig = H.sign_hash(sk, msg)
    assert H.verify_hash(pk, msg, sig)
    assert not H.verify_hash(pk, b"\x23" * 32, sig)
    assert not H.verify_hash(pk, msg, g2.neg(sig))


def test_aggregate_over_herumi_suite():
    sks = [H.fr_from_bytes(bytes([i + 1] * 32)) % R_ORDER for i in range(3)]
    sks = [sk if sk else 1 for sk in sks]
    msg = b"\x33" * 32
    pks = [H.pubkey(sk) for sk in sks]
    sigs = [H.sign_hash(sk, msg) for sk in sks]
    agg_sig = None
    agg_pk = None
    for s, p in zip(sigs, pks):
        agg_sig = g2.add(agg_sig, s)
        agg_pk = g1.add(agg_pk, p)
    assert H.verify_hash(agg_pk, msg, agg_sig)


def test_map_to_g2_is_deterministic_and_torsion():
    h1 = H.map_to_g2_herumi(b"\x44" * 32)
    h2_ = H.map_to_g2_herumi(b"\x44" * 32)
    assert h1 == h2_
    assert g2.mul(h1, R_ORDER) is None
    assert H.map_to_g2_herumi(b"\x45" * 32) != h1


def test_localnet_keyfile_vectors_pin_base_point():
    """26 more herumi-PRODUCED (sk -> pk) pairs, mined from the
    reference's encrypted localnet key files (see
    vectors_herumi_localnet.py): each must reproduce the reference's
    pubkey bytes exactly, independently re-pinning the BLS_SWAP_G base
    point and the LE + parity-flag serialization."""
    from vectors_herumi_localnet import SK_PK_VECTORS

    assert len(SK_PK_VECTORS) == 26
    for sk_hex, pk_hex in SK_PK_VECTORS:
        sk = H.fr_from_bytes(bytes.fromhex(sk_hex))
        assert H.g1_serialize(H.pubkey(sk)).hex() == pk_hex


@pytest.mark.parametrize("root", ["algorithmic", "even", "odd"])
@pytest.mark.parametrize("cofactor", ["h2", "heff"])
def test_map_conventions_all_self_consistent(root, cofactor):
    """Every carried (root, cofactor) convention must yield a working
    ciphersuite: deterministic r-torsion map, sign/verify roundtrip.
    Pinning the real mcl convention is then a config flip, not code
    (VERDICT r3 #3a)."""
    saved = dict(H.MAP_CONVENTION)
    try:
        H.set_map_convention(root=root, cofactor=cofactor)
        msg = b"\x55" * 32
        h = H.map_to_g2_herumi(msg)
        assert g2.mul(h, R_ORDER) is None
        assert H.map_to_g2_herumi(msg) == h
        sk = H.fr_from_bytes(bytes.fromhex(SK_HEX))
        sig = H.sign_hash(sk, msg)
        assert H.verify_hash(H.pubkey(sk), msg, sig)
    finally:
        H.MAP_CONVENTION.update(saved)


def test_map_conventions_are_distinguishable():
    """The conventions must produce DIFFERENT signatures for at least
    some message, so one herumi-produced vector disambiguates all of
    them.  (A message whose map hits a y with even parity under the
    algorithmic root makes 'algorithmic' and 'even' coincide — scan a
    few messages so each pair is separated somewhere.)"""
    import itertools

    saved = dict(H.MAP_CONVENTION)
    sk = H.fr_from_bytes(bytes.fromhex(SK_HEX))
    convs = list(
        itertools.product(["algorithmic", "even", "odd"], ["h2", "heff"])
    )
    separated = {}
    try:
        for i in range(8):
            msg = bytes([0x60 + i]) * 32
            sigs = {}
            for root, cof in convs:
                H.set_map_convention(root=root, cofactor=cof)
                sigs[(root, cof)] = H.sign_hash(sk, msg)
            for a, b in itertools.combinations(convs, 2):
                if sigs[a] != sigs[b]:
                    separated[(a, b)] = True
            if len(separated) == len(convs) * (len(convs) - 1) // 2:
                break
    finally:
        H.MAP_CONVENTION.update(saved)
    # every pair of distinct conventions must be separated by some msg
    assert len(separated) == len(convs) * (len(convs) - 1) // 2
