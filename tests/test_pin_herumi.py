"""tools/pin_herumi.py: convention pinning from signature vectors."""

import json
import pathlib
import sys

import pytest

sys.path.insert(0, "tools")

from pin_herumi import pin_from_vectors  # noqa: E402

from harmony_tpu.ref import herumi as HM  # noqa: E402


def _vector(sk: int, msg: bytes):
    pk = HM.pubkey(sk)
    sig = HM.sign_hash(sk, msg)
    return (HM.g1_serialize(pk), msg, HM.g2_serialize(sig))


@pytest.mark.parametrize("root,cof", [
    ("algorithmic", "h2"), ("even", "h2"), ("odd", "heff"),
])
def test_recovers_the_signing_convention(root, cof):
    saved = dict(HM.MAP_CONVENTION)
    try:
        HM.set_map_convention(root=root, cofactor=cof)
        vectors = [
            _vector(1234567 + i, bytes([i]) * 32) for i in range(3)
        ]
    finally:
        HM.set_map_convention(**saved)
    res = pin_from_vectors(vectors)
    assert (root, cof) in res["matches"]
    # three distinct messages pin it uniquely in practice
    if res["pin"] is not None:
        assert res["pin"] == {"root": root, "cofactor": cof}
    # and the process convention was restored
    assert HM.MAP_CONVENTION == saved


def test_corrupt_vector_matches_nothing():
    # a VALID signature over a different message: decodes fine,
    # verifies under no convention
    pk, msg, _ = _vector(99991, b"q" * 32)
    _, _, other_sig = _vector(99991, b"z" * 32)
    res = pin_from_vectors([(pk, msg, other_sig)])
    assert res["matches"] == [] and res["pin"] is None


def test_default_convention_is_mcl_best_guess():
    """The shipped default is the documented mcl-source best guess;
    flipping it is an env/config action, never a code edit."""
    assert HM.MAP_CONVENTION == {"root": "algorithmic", "cofactor": "h2"}


# -- the committed self-parity pin (VERDICT Missing #5) ----------------------

_PIN_FILE = pathlib.Path(__file__).parent / "vectors" / (
    "herumi_signhash_pin.json"
)


def _committed_vectors():
    with open(_PIN_FILE) as f:
        return json.load(f)


def test_committed_vectors_pin_the_default_convention():
    """The committed vector file pins SignHash to exactly the shipped
    default — the pin tools/pin_herumi.py would emit for it."""
    vecs = [
        (bytes.fromhex(v["pk"]), bytes.fromhex(v["msg"]),
         bytes.fromhex(v["sig"]))
        for v in _committed_vectors()
    ]
    res = pin_from_vectors(vecs)
    assert res["pin"] == {"root": "algorithmic", "cofactor": "h2"}, (
        f"committed vectors no longer pin uniquely: {res['matches']}"
    )


def test_committed_vectors_reproduce_byte_for_byte():
    """Regenerating each committed vector from its sk must reproduce
    the committed bytes EXACTLY: any drift in the sqrt-root choice,
    cofactor clearing, or the G1/G2 serialization (the conventions a
    device round would silently inherit) breaks here first."""
    from pin_herumi import emit_vectors

    committed = _committed_vectors()
    regenerated = emit_vectors(len(committed))
    assert regenerated == committed
