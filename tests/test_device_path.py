"""CI coverage for the DEVICE verification path (VERDICT r2 weak #3).

Forces ``device.use_device(True)`` so the engine's and FBFT's device
branches — CommitteeTable padding, the fused agg_verify route, the
batched replay grouping, COUNTERS — execute in CI and are
bitwise-compared against the host bigint path.

The innermost jitted kernels (ops/bls.agg_verify + friends) are
swapped for BIGINT-BACKED TWINS here: on this 1-core CI box ANY
execution of the pairing through XLA — jit compile OR eager — costs
8+ minutes (measured 2026-07-29; docs/NOTES_r2.md's minefield), so the
kernel math is covered by the ops parity tier while THIS module covers
every layer above it: the twins receive exactly the padded device
arrays the real kernels would, convert them back, and make REAL
verify decisions in bigint — wrong padding, bitmap routing, table
layout, or result slicing fails loudly.
"""

import numpy as np
import pytest

from harmony_tpu import bls as B
from harmony_tpu import device as DV
from harmony_tpu.chain.engine import Engine, EpochContext
from harmony_tpu.chain.header import Header
from harmony_tpu.consensus.mask import Mask
from harmony_tpu.consensus.signature import construct_commit_payload
from harmony_tpu.ops import bls as OB
from harmony_tpu.ops import interop as I
from harmony_tpu.ref import bls as RB
from harmony_tpu.ref.curve import g1

N_KEYS = 4

KERNEL_CALLS = {"agg_verify": 0, "agg_verify_batch": 0, "verify": 0}


def _aff_g1(arr):
    return (I.arr_to_fp(arr[0]), I.arr_to_fp(arr[1]))


def _aff_g2(arr):
    return (I.arr_to_fp2(arr[0]), I.arr_to_fp2(arr[1]))


def _twin_agg_verify(pk_affs, bitmap, h_aff, agg_sig_aff):
    """Bigint twin of ops/bls.agg_verify: same signature, same padded
    array layout, decisions from the reference implementation."""
    KERNEL_CALLS["agg_verify"] += 1
    tbl = np.asarray(pk_affs)
    bits = np.asarray(bitmap)
    assert tbl.shape[0] == bits.shape[0], "table/bitmap width mismatch"
    agg = None
    for i, bit in enumerate(bits):
        if bit:
            agg = g1.add(agg, _aff_g1(tbl[i]))
    if agg is None:
        return np.asarray(False)
    h_pt = _aff_g2(np.asarray(h_aff))
    sig_pt = _aff_g2(np.asarray(agg_sig_aff))
    return np.asarray(RB.verify_hashed(agg, h_pt, sig_pt))


def _twin_agg_verify_batch(pk_affs, bitmaps, h_affs, agg_sig_affs):
    KERNEL_CALLS["agg_verify_batch"] += 1
    out = [
        bool(_twin_agg_verify(pk_affs, bm, h, s))
        for bm, h, s in zip(
            np.asarray(bitmaps), np.asarray(h_affs),
            np.asarray(agg_sig_affs),
        )
    ]
    KERNEL_CALLS["agg_verify"] -= len(out)  # inner calls don't count
    return np.asarray(out)


def _twin_verify(pk_affs, h_affs, sig_affs):
    KERNEL_CALLS["verify"] += 1
    out = []
    for pk, h, s in zip(
        np.asarray(pk_affs), np.asarray(h_affs), np.asarray(sig_affs)
    ):
        out.append(RB.verify_hashed(_aff_g1(pk), _aff_g2(h), _aff_g2(s)))
    return np.asarray(out)


@pytest.fixture(scope="module", autouse=True)
def force_device_with_twin_kernels():
    DV.use_device(True)
    saved = (OB.agg_verify, OB.agg_verify_batch, OB.verify)
    OB.agg_verify = _twin_agg_verify
    OB.agg_verify_batch = _twin_agg_verify_batch
    OB.verify = _twin_verify
    yield
    OB.agg_verify, OB.agg_verify_batch, OB.verify = saved
    DV.use_device(None)


@pytest.fixture(scope="module")
def committee():
    keys = [B.PrivateKey.generate(bytes([60 + i])) for i in range(N_KEYS)]
    serialized = [k.pub.bytes for k in keys]
    return keys, serialized


def _provider(serialized):
    def provide(shard_id, epoch):
        return EpochContext(serialized)

    return provide


def _sign_header(header, keys, signer_idx):
    payload = construct_commit_payload(
        header.hash(), header.block_num, header.view_id, True
    )
    sigs = [keys[i].sign_hash(payload) for i in signer_idx]
    agg = B.aggregate_sigs(sigs)
    mask = Mask([k.pub.point for k in keys])
    for i in signer_idx:
        mask.set_bit(i, True)
    return agg.bytes, mask.mask_bytes()


def test_device_enabled_is_forced():
    assert DV.device_enabled()


def test_committee_table_padding():
    keys = [B.PrivateKey.generate(bytes([80 + i])) for i in range(3)]
    tbl = DV.CommitteeTable([k.pub.point for k in keys])
    assert tbl.n == 3 and tbl.size == 8  # padded to the smallest bucket
    bits = tbl.pad_bits([1, 0, 1])
    assert list(bits) == [1, 0, 1, 0, 0, 0, 0, 0]


def test_engine_device_verify_matches_host(committee):
    """The fused device quorum check and the host bigint check must
    agree bitwise on accept AND reject (VERDICT r2 next-steps #3)."""
    keys, serialized = committee
    before = DV.COUNTERS["agg_verify"]
    dev = Engine(_provider(serialized), device=True)
    host = Engine(_provider(serialized), device=False)
    h = Header(shard_id=0, block_num=10, epoch=2, view_id=10)
    cases = []
    sig, bitmap = _sign_header(h, keys, [0, 1, 2, 3])
    cases.append((h, sig, bitmap))
    sig2, bitmap2 = _sign_header(h, keys, [0, 1, 2])
    cases.append((h, sig2, bitmap2))
    # mismatched: 3-signer sig against the full bitmap
    cases.append((h, sig2, bitmap))
    # insufficient quorum (2 of 4)
    sig3, bitmap3 = _sign_header(h, keys, [0, 3])
    cases.append((h, sig3, bitmap3))
    for hdr, s, bm in cases:
        assert dev.verify_header_signature(hdr, s, bm) == \
            host.verify_header_signature(hdr, s, bm)
    assert DV.COUNTERS["agg_verify"] > before  # device branch really ran


def test_engine_device_batch_replay_matches_host(committee):
    keys, serialized = committee
    before = DV.COUNTERS["batch_verify"]
    dev = Engine(_provider(serialized), device=True)
    host = Engine(_provider(serialized), device=False)
    headers = []
    prev_hash = bytes(32)
    for n in range(12):
        h = Header(
            shard_id=0, block_num=200 + n, epoch=3, view_id=200 + n,
            parent_hash=prev_hash,
        )
        signers = [0, 1, 2, 3] if n % 3 else [0, 1, 2]
        sig, bitmap = _sign_header(h, keys, signers)
        headers.append((h, sig, bitmap))
        prev_hash = h.hash()
    items = list(headers)
    # corrupt two entries: swapped sig, truncated quorum
    items[4] = (items[4][0], items[3][1], items[4][2])
    bad_sig, bad_bm = _sign_header(items[7][0], keys, [1])
    items[7] = (items[7][0], bad_sig, bad_bm)
    got = dev.verify_headers_batch(items)
    want = host.verify_headers_batch(items)
    assert got == want
    assert got[4] is False and got[7] is False
    assert sum(got) == 10
    assert DV.COUNTERS["batch_verify"] > before


def test_fbft_validator_device_branch(committee):
    """Validator._verify_proof device branch: committee table built
    lazily, fused agg_verify consulted, decision matches host."""
    from harmony_tpu.consensus import fbft as FB
    from harmony_tpu.consensus import quorum as Q
    from harmony_tpu.multibls import PrivateKeys

    keys, serialized = committee
    payload = b"fbft-device-branch-payload-32byt"
    sigs = [k.sign_hash(payload) for k in keys[:3]]
    agg = B.aggregate_sigs(sigs)
    mask = Mask([k.pub.point for k in keys])
    for i in range(3):
        mask.set_bit(i, True)
    proof = agg.bytes + mask.mask_bytes()
    cfg = FB.RoundConfig(committee=serialized, block_num=1, view_id=0)

    def mk_validator():
        return FB.Validator(
            PrivateKeys.from_keys([keys[0]]), cfg,
            Q.Decider(Q.Policy.UNIFORM, serialized),
        )

    def mk_msg(pl):
        return FB.FBFTMessage(
            msg_type=FB.MsgType.PREPARED, view_id=0, block_num=1,
            block_hash=b"\xab" * 32, sender_pubkeys=[serialized[0]],
            payload=pl,
        )

    before = DV.COUNTERS["agg_verify"]
    v = mk_validator()
    assert v._verify_proof(mk_msg(proof), payload)
    assert DV.COUNTERS["agg_verify"] > before
    # flipped bitmap bit -> aggregate mismatch -> reject
    bad = bytearray(proof)
    bad[-1] ^= 0x08
    assert not v._verify_proof(mk_msg(bytes(bad)), payload)
    DV.use_device(False)
    try:
        v2 = mk_validator()
        assert v2._verify_proof(mk_msg(proof), payload)
        assert not v2._verify_proof(mk_msg(bytes(bad)), payload)
    finally:
        DV.use_device(True)
