"""Dedicated RateLimiter tier (ISSUE 14 satellite: the token bucket
gates RPC ingress, p2p per-peer flood defense, sync-stream serving and
now the governor's PRESSURED admission — and had zero tests of its
own): refill, burst, per-key isolation, drop, concurrent allow."""

import threading
import time

from harmony_tpu.ratelimit import RateLimiter


def test_burst_then_refill():
    """A fresh key gets exactly ``burst`` immediate tokens; further
    allows wait on the refill rate."""
    rl = RateLimiter(per_second=5.0, burst=3)
    assert [rl.allow("k") for _ in range(3)] == [True, True, True]
    assert rl.allow("k") is False  # burst spent, refill is 5/s
    time.sleep(0.30)  # ~1.5 tokens back
    assert rl.allow("k") is True
    assert rl.allow("k") is False  # the fraction is not a full token


def test_tokens_capped_at_burst():
    """Idle time must not bank more than ``burst`` tokens."""
    rl = RateLimiter(per_second=1000.0, burst=2)
    assert rl.allow("k") and rl.allow("k")
    time.sleep(0.05)  # would refill ~50 tokens uncapped
    allowed = sum(1 for _ in range(10) if rl.allow("k"))
    assert allowed <= 3  # the cap (2) plus at most ~1 token of refill
    #                      during the loop itself


def test_per_key_isolation():
    """One chatty key must not drain another's bucket."""
    rl = RateLimiter(per_second=0.001, burst=2)
    assert rl.allow("chatty") and rl.allow("chatty")
    assert rl.allow("chatty") is False
    # a different key still holds its full burst
    assert rl.allow("quiet") and rl.allow("quiet")


def test_drop_resets_key_state():
    """drop() forgets a key's (exhausted) bucket: a peer reconnecting
    after churn starts from a fresh burst, and state does not
    accumulate across disconnects."""
    rl = RateLimiter(per_second=0.001, burst=1)
    assert rl.allow("peer") is True
    assert rl.allow("peer") is False
    rl.drop("peer")
    assert "peer" not in rl._state
    assert rl.allow("peer") is True  # fresh burst after the drop
    rl.drop("never-seen")  # dropping an unknown key is a no-op


def test_max_keys_evicts_stalest_not_hot():
    """At the key cap, a new key evicts the least-recently-touched
    bucket — not a hot one — and the table never exceeds max_keys
    (an attacker cycling source addresses must not grow the limiter's
    own memory: the exact failure the governor exists to prevent)."""
    rl = RateLimiter(per_second=0.001, burst=1, max_keys=3)
    assert rl.allow("a") and rl.allow("b") and rl.allow("c")
    rl.allow("a")  # touch: "a" is now the HOTTEST, "b" the stalest
    assert rl.allow("d") is True  # new key at cap -> evict "b"
    assert len(rl._state) == 3
    assert "b" not in rl._state
    # "a" survived its touch with an exhausted bucket (no fresh burst)
    assert rl.allow("a") is False
    # a cycling-key flood stays bounded
    for i in range(100):
        rl.allow(f"cycle-{i}")
    assert len(rl._state) == 3


def test_concurrent_allow_exact_accounting():
    """N racing threads on ONE key must win exactly ``burst`` tokens
    between them (plus at most the sliver refilled during the race) —
    the read-modify-write under the lock must never double-spend."""
    rl = RateLimiter(per_second=0.001, burst=100)
    wins = []
    lock = threading.Lock()
    start = threading.Event()

    def worker():
        start.wait()
        mine = sum(1 for _ in range(50) if rl.allow("hot"))
        with lock:
            wins.append(mine)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    start.set()
    for t in threads:
        t.join()
    assert sum(wins) == 100  # exactly the burst: no lost or double
    #                          spends under contention


def test_wait_blocks_until_token():
    """wait() consumes a token, blocking no longer than the refill
    interval requires."""
    rl = RateLimiter(per_second=50.0, burst=1)
    assert rl.allow("k") is True  # burst spent
    t0 = time.monotonic()
    rl.wait("k")  # must block ~20ms for one refill
    waited = time.monotonic() - t0
    assert waited < 2.0  # bounded (generous for a loaded box)
    assert rl.allow("k") is False  # wait() consumed the token it got
