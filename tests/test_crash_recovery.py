"""Crash-consistent storage + restart recovery (ISSUE 12 tentpole):
atomic per-block commit batches, recovery-on-open head rollback, the
kv.commit crash-point matrix, and the durable last-signed-view safety
store that keeps a restarted validator from double-signing."""

import pytest

from harmony_tpu import faultinject as FI
from harmony_tpu.consensus.safety import (
    PHASE_COMMIT,
    PHASE_PREPARE,
    PHASE_VIEWCHANGE,
    SafetyStore,
)
from harmony_tpu.core import rawdb
from harmony_tpu.core.blockchain import Blockchain, ChainError
from harmony_tpu.core.genesis import dev_genesis
from harmony_tpu.core.kv import FileKV, MemKV, WriteBatch
from harmony_tpu.node.worker import Worker

CHAIN_ID = 2


@pytest.fixture(autouse=True)
def _fi_clean():
    FI.reset()
    yield
    FI.reset()


def _proof(chain, num):
    committee = chain.committee_for_epoch(chain.epoch_of(num))
    return b"\x01" * 96 + b"\xff" * ((len(committee) + 7) >> 3)


def _grow(chain, n, with_proofs=True):
    worker = Worker(chain, None)
    blocks = []
    for _ in range(n):
        block = worker.propose_block(view_id=chain.head_number + 1)
        sigs = [_proof(chain, block.block_num)] if with_proofs else None
        assert chain.insert_chain(
            [block], commit_sigs=sigs, verify_seals=False
        ) == 1
        blocks.append(block)
    return blocks


def _open(path, genesis, **kw):
    kw.setdefault("blocks_per_epoch", 16)
    return Blockchain(FileKV(path), genesis, **kw)


# -- atomic block commits ----------------------------------------------------


def test_block_insert_is_one_atomic_batch(tmp_path):
    """A crash at ANY kv.commit point of an insert leaves the previous
    head fully intact on reopen — never a block without its state,
    proof, or head pointer."""
    path = str(tmp_path / "chain.kv")
    genesis, _, _ = dev_genesis()
    chain = _open(path, genesis)
    _grow(chain, 2)
    chain.db.close()

    # enumerate this insert's crash points with a counting-only rule
    FI.arm("kv.commit", key="__none__", after=10**9)
    chain = _open(path, genesis)
    block = Worker(chain, None).propose_block(view_id=3)
    before = FI.hits("kv.commit")
    chain.insert_chain([block], commit_sigs=[_proof(chain, 3)],
                       verify_seals=False)
    points = FI.hits("kv.commit") - before
    assert points >= 3  # BEGIN + records + COMMIT at minimum
    chain.db.close()

    for k in range(points):
        p = str(tmp_path / f"fp{k}.kv")
        import shutil

        shutil.copyfile(path, p)
        c = Blockchain(FileKV(p), genesis, blocks_per_epoch=16)
        c.revert_to(2)
        blk = Worker(c, None).propose_block(view_id=3)
        FI.reset()
        FI.arm("kv.commit", key=p, after=k, times=1)
        with pytest.raises(FI.FaultInjected):
            c.insert_chain([blk], commit_sigs=[_proof(c, 3)],
                           verify_seals=False)
        FI.reset()
        # abandon without close (unbuffered writes = SIGKILL state)
        r = Blockchain(FileKV(p), genesis, blocks_per_epoch=16,
                       require_commit_sigs=True)
        assert r.head_number == 2
        assert r.current_header() is not None
        assert r.read_commit_sig(2) is not None
        # zero manual repair: the block inserts cleanly after recovery
        assert r.insert_chain([blk], commit_sigs=[_proof(r, 3)],
                              verify_seals=False) == 1
        r.db.close()


def test_reopen_after_clean_insert(tmp_path):
    path = str(tmp_path / "chain.kv")
    genesis, _, _ = dev_genesis()
    chain = _open(path, genesis)
    blocks = _grow(chain, 3)
    chain.db.close()
    re = _open(path, genesis, require_commit_sigs=True)
    assert re.head_number == 3
    assert re.current_header().hash() == blocks[-1].hash()
    assert re.recovered_blocks == 0
    re.db.close()


# -- recovery-on-open --------------------------------------------------------


def test_torn_head_rolls_back_on_open(tmp_path):
    """A pre-batch-era torn commit (head pointer advanced, block
    records missing) must roll back to the last whole block instead of
    crashing or serving the torn head."""
    path = str(tmp_path / "chain.kv")
    genesis, _, _ = dev_genesis()
    chain = _open(path, genesis)
    _grow(chain, 3)
    # simulate the legacy tear: head says 4, but only a header made it
    hdr = chain.current_header()
    fake = rawdb.encode_header(hdr)
    chain.db.put(b"h" + (4).to_bytes(8, "little"), fake)
    rawdb.write_head_number(chain.db, 4)
    chain.db.close()

    re = _open(path, genesis, require_commit_sigs=True)
    assert re.head_number == 3
    assert re.recovered_blocks == 1
    # the rollback is durable: a second reopen is clean
    re.db.close()
    re2 = _open(path, genesis, require_commit_sigs=True)
    assert re2.head_number == 3
    assert re2.recovered_blocks == 0
    re2.db.close()


def test_missing_commit_sig_rolls_back_when_required(tmp_path):
    path = str(tmp_path / "chain.kv")
    genesis, _, _ = dev_genesis()
    chain = _open(path, genesis)
    _grow(chain, 3)
    chain.db.delete(b"s" + (3).to_bytes(8, "little"))
    chain.db.close()
    # consensus-shaped chains require the proof: roll back
    re = _open(path, genesis, require_commit_sigs=True)
    assert re.head_number == 2
    re.db.close()
    # proof-less test chains do not (engine=None default)
    p2 = str(tmp_path / "chain2.kv")
    c2 = _open(p2, genesis)
    _grow(c2, 2, with_proofs=False)
    c2.db.close()
    re2 = _open(p2, genesis)
    assert re2.head_number == 2
    re2.db.close()


def test_pruned_state_still_raises_missing_state(tmp_path):
    """A WHOLE block whose state blob is absent is a pruned/snapshot
    store, not a tear: reopen must raise the classic error, never
    destroy block records by rolling back through them."""
    path = str(tmp_path / "chain.kv")
    genesis, _, _ = dev_genesis()
    chain = _open(path, genesis)
    _grow(chain, 2)
    rawdb.delete_state(chain.db, chain.current_header().root)
    chain.db.close()
    with pytest.raises(ChainError, match="missing state"):
        _open(path, genesis)


def test_corrupt_state_blob_rolls_back(tmp_path):
    from harmony_tpu.core.tx_pool import TxPool
    from harmony_tpu.core.types import Transaction

    path = str(tmp_path / "chain.kv")
    genesis, ecdsa_keys, _ = dev_genesis()
    chain = _open(path, genesis)
    # empty dev blocks share one state root — the blocks need distinct
    # roots so corrupting the HEAD's blob damages only the head
    for n in range(2):
        pool = TxPool(CHAIN_ID, 0, chain.state)
        pool.add(Transaction(
            nonce=n, gas_price=1, gas_limit=21_000, shard_id=0,
            to_shard=0, to=b"\x2d" * 20, value=1 + n,
        ).sign(ecdsa_keys[0], CHAIN_ID))
        block = Worker(chain, pool).propose_block(
            view_id=chain.head_number + 1
        )
        assert chain.insert_chain(
            [block], commit_sigs=[_proof(chain, block.block_num)],
            verify_seals=False,
        ) == 1
    h1, h2 = chain.header_by_number(1), chain.header_by_number(2)
    assert h1.root != h2.root
    chain.db.put(b"S" + h2.root, b"\xff\xff\xff\xffgarbage")
    chain.db.close()
    re = _open(path, genesis, require_commit_sigs=True)
    assert re.head_number == 1
    re.db.close()


def test_revert_is_atomic_and_unspends_cx(tmp_path):
    """revert_to stages ALL deletes + the head move into one batch —
    and un-marks consumed cx batches so a re-synced block's proofs
    are not misread as double spends (the rawdb revert tooling)."""
    from harmony_tpu.core.genesis import Genesis
    from harmony_tpu.core.tx_pool import TxPool
    from harmony_tpu.core.types import Transaction
    from harmony_tpu.node.cross_shard import export_receipts

    g0, ecdsa_keys, _ = dev_genesis(shard_id=0)
    g1 = Genesis(config=g0.config, shard_id=1, alloc=dict(g0.alloc),
                 committee=list(g0.committee))
    c0 = Blockchain(MemKV(), g0, blocks_per_epoch=16)
    c1 = Blockchain(MemKV(), g1, blocks_per_epoch=16)
    pool = TxPool(CHAIN_ID, 0, c0.state)
    pool.add(Transaction(
        nonce=0, gas_price=1, gas_limit=25_000, shard_id=0,
        to_shard=1, to=b"\x0c" * 20, value=777,
    ).sign(ecdsa_keys[0], CHAIN_ID))
    b0 = Worker(c0, pool).propose_block(view_id=1)
    assert c0.insert_chain([b0], verify_seals=False) == 1
    proofs = export_receipts(c0, 1, shard_count=2)
    b1 = Worker(c1, None).propose_block(
        view_id=1, incoming_receipts=[proofs[1]]
    )
    assert c1.insert_chain([b1], verify_seals=False) == 1
    assert rawdb.is_cx_spent(c1.db, 0, 1)
    assert rawdb.cx_spender(c1.db, 0, 1) == 1
    assert c1.state().balance(b"\x0c" * 20) == 777

    assert c1.revert_to(0) == 1
    assert not rawdb.is_cx_spent(c1.db, 0, 1)  # un-spent on revert
    assert c1.head_number == 0
    assert rawdb.read_header(c1.db, 1) is None
    # the revert is the whole point: the SAME block re-inserts
    assert c1.insert_chain([b1], verify_seals=False) == 1
    assert rawdb.cx_spender(c1.db, 0, 1) == 1
    assert c1.state().balance(b"\x0c" * 20) == 777


# -- the durable safety store ------------------------------------------------


def test_safety_store_rules():
    db = MemKV()
    s = SafetyStore(db)
    pk = b"\x11" * 48
    h_a, h_b = b"\xaa" * 32, b"\xbb" * 32

    assert s.record([pk], 5, 6, PHASE_PREPARE, h_a)
    # same (height, view), same hash: idempotent re-sign
    assert s.may_sign(pk, 5, 6, PHASE_PREPARE, h_a)
    # same (height, view), DIFFERENT hash: the double sign
    assert not s.may_sign(pk, 5, 6, PHASE_PREPARE, h_b)
    assert not s.record([pk], 5, 6, PHASE_COMMIT, h_b)
    assert s.refused == 1
    # commit on the SAME hash advances fine
    assert s.record([pk], 5, 6, PHASE_COMMIT, h_a)
    # OTHER views at the same height are ordinary FBFT view churn,
    # not equivocation — a NEWVIEW quorum can form below this key's
    # last view and its vote there must not be withheld
    assert s.may_sign(pk, 5, 5, PHASE_PREPARE, h_b)
    assert s.may_sign(pk, 5, 9, PHASE_PREPARE, h_b)
    # stale height: refused; higher height: fine
    assert not s.may_sign(pk, 4, 9, PHASE_PREPARE, h_b)
    assert s.may_sign(pk, 6, 7, PHASE_PREPARE, h_b)
    # a view-change FOR view 8 never conflicts with votes, raises the
    # restart watermark, and never overwrites the vote record
    assert s.record([pk], 5, 8, PHASE_VIEWCHANGE, bytes(32))
    assert s.may_sign(pk, 5, 8, PHASE_PREPARE, h_b)
    assert s.last(pk)[3] == h_a  # vote memory intact
    assert s.watermark(pk) == (5, 8)
    # live floor (view monotonicity) tracks VOTES only; the restart
    # floor is strictly above the last vote and honors the watermark
    assert s.min_view(5) == 6
    assert s.restart_floor(5) == 8  # max(voted 6 + 1, watermark 8)
    assert s.min_view(99) == 0


def test_safety_store_survives_reopen(tmp_path):
    path = str(tmp_path / "safety.kv")
    db = FileKV(path)
    s = SafetyStore(db)
    pk = b"\x22" * 48
    assert s.record([pk], 3, 4, PHASE_PREPARE, b"\xcc" * 32)
    db.close()  # hard kill would be equivalent: puts are unbuffered

    db2 = FileKV(path)
    s2 = SafetyStore(db2)
    s2.load_keys([pk])
    assert s2.last(pk) == (3, 4, PHASE_PREPARE, b"\xcc" * 32)
    assert not s2.may_sign(pk, 3, 4, PHASE_PREPARE, b"\xdd" * 32)
    assert s2.min_view(3) == 4
    db2.close()


def test_restarted_validator_cannot_double_sign(tmp_path, monkeypatch):
    """Node-level: a validator votes PREPARE for block A, is hard-
    killed, restarts from the same data dir, and receives an
    equivocating announce for block B at the SAME (height, view) — the
    durable record must withhold the second vote."""
    monkeypatch.setenv("HARMONY_KERNEL_TWIN", "1")
    from harmony_tpu.consensus.messages import MsgType
    from harmony_tpu.core.tx_pool import TxPool
    from harmony_tpu.multibls import PrivateKeys
    from harmony_tpu.node.node import Node
    from harmony_tpu.node.registry import Registry
    from harmony_tpu.p2p import InProcessNetwork

    genesis, _, bls_keys = dev_genesis(n_keys=4)
    path = str(tmp_path / "val.kv")
    net = InProcessNetwork()

    def build(host_name):
        chain = Blockchain(FileKV(path), genesis, blocks_per_epoch=16)
        pool = TxPool(CHAIN_ID, 0, chain.state)
        reg = Registry(blockchain=chain, txpool=pool,
                       host=net.host(host_name))
        # a validator key that is NOT the view-1 leader slot
        # (view 1 -> committee[1 % 4] = key 1 leads)
        return Node(reg, PrivateKeys.from_keys([bls_keys[2]]))

    # the view-1 leader proposes block A on ITS OWN chain replica
    leader_chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    leader_pool = TxPool(CHAIN_ID, 0, leader_chain.state)
    leader_reg = Registry(blockchain=leader_chain, txpool=leader_pool,
                          host=net.host("leader"))
    leader = Node(leader_reg, PrivateKeys.from_keys([bls_keys[1]]))

    val = build("val")
    block_a = leader.start_round_if_leader()
    assert block_a is not None
    assert val.process_pending() >= 1  # announce consumed
    rec = val.safety.last(bls_keys[2].pub.bytes)
    assert rec is not None
    assert rec[:2] == (1, 1) and rec[3] == block_a.hash()

    # hard kill: abandon the node, reopen the SAME file
    val.stop()
    val2 = build("val2")
    assert val2.safety.last(bls_keys[2].pub.bytes)[3] == block_a.hash()

    # an equivocating announce: different block, same (height, view).
    # worker proposals differ by timestamp/coinbase ordering — force a
    # distinct hash via leader_extra
    from harmony_tpu.consensus.messages import (
        FBFTMessage, encode_message, sign_message,
    )
    from harmony_tpu.node.ingress import MessageCategory, pack_envelope

    block_b = Worker(leader_chain, None).propose_block(
        view_id=1, leader_extra=b"equivocate"
    )
    assert block_b.hash() != block_a.hash()
    announce_b = sign_message(FBFTMessage(
        msg_type=MsgType.ANNOUNCE, view_id=1, block_num=1,
        block_hash=block_b.hash(),
        sender_pubkeys=[bls_keys[1].pub.bytes],
        payload=b"", block=rawdb.encode_block(block_b, CHAIN_ID),
    ), PrivateKeys.from_keys([bls_keys[1]]))
    env = pack_envelope(
        MessageCategory.CONSENSUS, int(MsgType.ANNOUNCE),
        encode_message(announce_b),
    )
    # strict view monotonicity: the restarted node rejoined ABOVE the
    # view it already voted in, so the equivocating view-1 announce is
    # dropped at the view-mismatch gate — the double sign is prevented
    # one layer before the record check even runs
    assert val2.view_id == 2
    val2._handle(env)
    assert val2._announce_voted is None  # no vote left the node
    # and the durable record still names block A at (1, view 1)
    assert val2.safety.last(bls_keys[2].pub.bytes)[3] == block_a.hash()
    # the record check itself also refuses (belt and braces): a forged
    # same-view different-hash sign attempt is withheld
    assert not val2.safety.may_sign(
        bls_keys[2].pub.bytes, 1, 1, PHASE_PREPARE, block_b.hash()
    )
    val2.stop()
    leader.stop()
    val2.chain.db.close()


def test_snapshot_import_crash_matrix(tmp_path):
    """ISSUE 18: SIGKILL at EVERY kv.commit fault point of a snapshot
    import leaves a store that reopens to either the pre-import head or
    the complete snapshot — never a half-imported state (a header
    without its accounts, a head pointer past its state)."""
    import shutil

    from harmony_tpu.core import snapshot as SN

    genesis, _, _ = dev_genesis()
    src = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    _grow(src, 5)
    snap = str(tmp_path / "head.snap")
    assert SN.export_snapshot(src, snap) == 5
    src_root = src.state().root()

    # the importer has its own 2-block history on disk
    path = str(tmp_path / "import.kv")
    chain = _open(path, genesis)
    _grow(chain, 2)
    pre_root = chain.state().root()
    chain.db.close()

    # enumerate this import's crash points with a counting-only rule
    probe = str(tmp_path / "probe.kv")
    shutil.copyfile(path, probe)
    FI.arm("kv.commit", key="__none__", after=10**9)
    c = Blockchain(FileKV(probe), genesis, blocks_per_epoch=16)
    before = FI.hits("kv.commit")
    assert SN.import_snapshot(c, snap, trust=True) == 5
    points = FI.hits("kv.commit") - before
    assert points >= 3  # BEGIN + records + COMMIT at minimum
    c.db.close()
    FI.reset()

    outcomes = set()
    for k in range(points):
        p = str(tmp_path / f"snapfp{k}.kv")
        shutil.copyfile(path, p)
        c = Blockchain(FileKV(p), genesis, blocks_per_epoch=16)
        FI.reset()
        FI.arm("kv.commit", key=p, after=k, times=1)
        with pytest.raises(FI.FaultInjected):
            SN.import_snapshot(c, snap, trust=True)
        FI.reset()
        # abandon without close (unbuffered writes = SIGKILL state)
        r = Blockchain(FileKV(p), genesis, blocks_per_epoch=16)
        head = r.head_number
        assert head in (2, 5), (
            f"fault point {k}: half-imported head {head}"
        )
        if head == 5:
            # the import went fully durable before the kill
            assert r.state().root() == src_root
            assert r.read_commit_sig(5) is not None
        else:
            # the import vanished whole: the old chain still extends
            assert r.state().root() == pre_root
            assert r.insert_chain(
                [Worker(r, None).propose_block(view_id=3)],
                commit_sigs=[_proof(r, 3)], verify_seals=False,
            ) == 1
        outcomes.add(head)
        r.db.close()
    # the matrix exercised the pre-commit side at minimum
    assert 2 in outcomes


def test_adopt_state_moves_head_and_state_together(tmp_path):
    """Fast-sync completion: a crash between the state write and the
    head move must never strand a head without state — they commit in
    one batch."""
    path = str(tmp_path / "fast.kv")
    genesis, _, _ = dev_genesis()
    src = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    blocks = _grow(src, 3)

    dst = _open(path, genesis)
    assert dst.insert_headers_fast(
        blocks, commit_sigs=[_proof(src, b.block_num) for b in blocks],
        verify_seals=False,
    ) == 3
    assert dst.head_number == 0  # head does not move on fast insert

    FI.arm("kv.commit", key=path, after=1, times=1)
    with pytest.raises(FI.FaultInjected):
        dst.adopt_state(3, src.state_at(3))
    FI.reset()
    r = _open(path, genesis, require_commit_sigs=True)
    assert r.head_number == 0  # neither state nor head moved
    r.adopt_state(3, src.state_at(3))
    assert r.head_number == 3
    r.db.close()
    dst.db.close()
