"""End-to-end double-sign slashing pipeline (ISSUE 13): record codec,
verification edge cases, economic application through the chain, node
detection -> gossip -> inclusion, and the bounded evidence queues."""

import pytest

from harmony_tpu import bls as B
from harmony_tpu.chaostest import fixtures as FX
from harmony_tpu.consensus.signature import (
    construct_commit_payload,
    prepare_payload,
)
from harmony_tpu.core.blockchain import Blockchain, ChainError
from harmony_tpu.core.genesis import dev_genesis
from harmony_tpu.core.kv import MemKV
from harmony_tpu.core.tx_pool import TxPool
from harmony_tpu.node.worker import Worker
from harmony_tpu.staking import slash as SL

CHAIN_ID = 2


def _record(key, height=100, view=7, epoch=3, shard=0,
            offender=b"\x0f" * 20, reporter=b"\x1e" * 20,
            h1=bytes([1]) * 32, h2=bytes([2]) * 32,
            second_payload=None):
    votes = []
    for i, h in enumerate((h1, h2)):
        payload = construct_commit_payload(h, height, view)
        if i == 1 and second_payload is not None:
            payload = second_payload
        votes.append(SL.Vote(
            signer_pubkeys=[key.pub.bytes],
            block_header_hash=h,
            signature=key.sign_hash(payload).bytes,
        ))
    return SL.Record(
        evidence=SL.Evidence(
            moment=SL.Moment(epoch, shard, height, view),
            first_vote=votes[0], second_vote=votes[1],
            offender=offender,
        ),
        reporter=reporter,
    )


@pytest.fixture(scope="module")
def key():
    return B.PrivateKey.generate(b"\x77")


# -- codec -------------------------------------------------------------------


def test_record_codec_roundtrip(key):
    rec = _record(key)
    blob = SL.encode_record(rec)
    back = SL.decode_record(blob)
    assert back == rec
    many = SL.encode_records([rec, _record(key, height=101)])
    assert SL.decode_records(many) == [rec, _record(key, height=101)]


def test_record_fingerprint_ignores_reporter(key):
    a = _record(key, reporter=b"\x1e" * 20)
    b = _record(key, reporter=b"\x2f" * 20)
    assert SL.record_fingerprint(a) == SL.record_fingerprint(b)
    c = _record(key, height=101)
    assert SL.record_fingerprint(a) != SL.record_fingerprint(c)


def test_decode_rejects_inflated_key_count(key):
    """A forged vote key count must be rejected BEFORE allocation."""
    import struct

    blob = bytearray(SL.encode_record(_record(key)))
    # the first vote's u16 key count sits right after the 28B moment
    struct.pack_into("<H", blob, 28, 0xFFFF)
    with pytest.raises(ValueError, match="implausible"):
        SL.decode_record(bytes(blob))


def test_decode_rejects_truncation_and_trailing(key):
    blob = SL.encode_record(_record(key))
    for cut in (1, 10, 27, 30, len(blob) - 1):
        with pytest.raises(ValueError):
            SL.decode_record(blob[:cut])
    with pytest.raises(ValueError, match="trailing"):
        SL.decode_record(blob + b"\x00")


def test_decode_records_caps_count(key):
    import struct

    blob = struct.pack("<H", SL.MAX_SLASHES_PER_BLOCK + 1)
    with pytest.raises(ValueError, match="cap"):
        SL.decode_records(blob + b"\x00" * 64)


# -- verification edge cases (satellite: distinct errors) --------------------


def test_verify_rejects_non_committee_signer(key):
    other = B.PrivateKey.generate(b"\x78")
    with pytest.raises(SL.SlashVerifyError,
                       match="not in committee"):
        SL.verify_record(_record(key), [other.pub.bytes])


def test_verify_rejects_same_hash_votes(key):
    rec = _record(key, h1=bytes([3]) * 32, h2=bytes([3]) * 32)
    with pytest.raises(SL.SlashVerifyError, match="do not conflict"):
        SL.verify_record(rec, [key.pub.bytes])


def test_verify_rejects_invalid_ballot_signature(key):
    rec = _record(key)
    rec.evidence.second_vote.signature = bytes(96)
    with pytest.raises(SL.SlashVerifyError,
                       match="signature invalid"):
        SL.verify_record(rec, [key.pub.bytes])


def test_verify_rejects_wrong_phase_payload(key):
    """A ballot signed over the PREPARE payload (bare hash) is its own
    distinct rejection — only commit ballots are slashable."""
    h2 = bytes([2]) * 32
    rec = _record(key)
    rec.evidence.second_vote.signature = key.sign_hash(
        prepare_payload(h2)
    ).bytes
    with pytest.raises(SL.SlashVerifyError, match="wrong phase"):
        SL.verify_record(rec, [key.pub.bytes])


def test_verify_rejects_self_report(key):
    rec = _record(key, offender=b"\x1e" * 20, reporter=b"\x1e" * 20)
    with pytest.raises(SL.SlashVerifyError, match="same"):
        SL.verify_record(rec, [key.pub.bytes])


def test_verify_rejects_disjoint_keys(key):
    other = B.PrivateKey.generate(b"\x79")
    rec = _record(key)
    rec.evidence.second_vote = _record(other).evidence.second_vote
    with pytest.raises(SL.SlashVerifyError, match="no matching"):
        SL.verify_record(rec, [key.pub.bytes, other.pub.bytes])


# -- chain application -------------------------------------------------------


@pytest.fixture()
def staked_chain():
    """A staking chain past its first election with one staked
    external validator seated in the epoch-1 committee."""
    genesis, ecdsa_keys, bls_keys = dev_genesis(n_accounts=5, n_keys=5)
    fin = FX.staking_finalizer(genesis, ecdsa_keys)
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=4,
                       finalizer=fin)
    pool = TxPool(CHAIN_ID, 0, chain.state)
    ext = FX.external_bls_key(99)
    pool.add(FX.external_validator_stake(ecdsa_keys[0], ext,
                                         chain_id=CHAIN_ID),
             is_staking=True)
    FX.advance_with_full_bitmaps(chain, pool, 4)
    assert ext.pub.bytes in chain.committee_for_epoch(1)
    return chain, pool, ecdsa_keys, ext


def _staked_record(chain, ecdsa_keys, ext, height=4, view=9):
    return _record(
        ext, height=height, view=view, epoch=chain.epoch_of(height),
        offender=ecdsa_keys[0].address(),
        reporter=ecdsa_keys[1].address(),
    )


def test_slash_applied_through_block(staked_chain):
    """Propose-with-record -> header.slashes sealed -> insert replays
    the verification + application: offender slashed at the reference
    rate and banned, reporter rewarded half, next election excludes."""
    chain, pool, ecdsa_keys, ext = staked_chain
    rec = _staked_record(chain, ecdsa_keys, ext)
    offender, reporter = rec.evidence.offender, rec.reporter
    stake0 = chain.state().validator(offender).total_delegation()
    rep0 = chain.state().balance(reporter)

    worker = Worker(chain, pool)
    block = worker.propose_block(view_id=chain.head_number + 1,
                                 slashes=[rec])
    assert block.header.slashes
    assert SL.decode_records(block.header.slashes) == [rec]
    assert chain.insert_chain([block], verify_seals=False) == 1

    w = chain.state().validator(offender)
    expect = SL.apply_slash(stake0)
    assert w.status == 2  # banned
    assert stake0 - w.total_delegation() == expect.total_slashed
    assert chain.state().balance(reporter) - rep0 == (
        expect.total_beneficiary_reward
    )
    # the election AFTER the ban must drop the offender's key
    FX.advance_with_full_bitmaps(chain, pool, 8 - chain.head_number)
    assert ext.pub.bytes not in chain.committee_for_epoch(2)


def test_duplicate_slash_rejected_and_proposer_drops_it(staked_chain):
    chain, pool, ecdsa_keys, ext = staked_chain
    rec = _staked_record(chain, ecdsa_keys, ext)
    worker = Worker(chain, pool)
    b1 = worker.propose_block(view_id=chain.head_number + 1,
                              slashes=[rec])
    assert chain.insert_chain([b1], verify_seals=False) == 1
    # the proposer dry-applies and silently DROPS the consumed record
    b2 = worker.propose_block(view_id=chain.head_number + 1,
                              slashes=[rec])
    assert b2.header.slashes == b""
    # a forged header carrying it anyway is rejected on insert
    b2.header.slashes = SL.encode_records([rec])
    with pytest.raises(ChainError, match="already banned"):
        chain.insert_chain([b2], verify_seals=False)


def test_forged_slash_payload_rejects_block(staked_chain):
    chain, pool, ecdsa_keys, ext = staked_chain
    worker = Worker(chain, pool)
    block = worker.propose_block(view_id=chain.head_number + 1)
    block.header.slashes = b"\xff" * 40  # undecodable
    with pytest.raises(ChainError, match="bad slash payload"):
        chain.insert_chain([block], verify_seals=False)
    # structurally valid but cryptographically bogus record
    bogus = _staked_record(chain, ecdsa_keys, ext)
    bogus.evidence.second_vote.signature = bytes(96)
    block2 = worker.propose_block(view_id=chain.head_number + 1)
    block2.header.slashes = SL.encode_records([bogus])
    with pytest.raises(ChainError, match="invalid slash record"):
        chain.insert_chain([block2], verify_seals=False)


def test_future_evidence_rejected(staked_chain):
    chain, pool, ecdsa_keys, ext = staked_chain
    rec = _staked_record(chain, ecdsa_keys, ext,
                         height=chain.head_number + 5)
    with pytest.raises(ChainError, match="future"):
        chain.apply_slash_records(
            chain.state().copy(), [rec], chain.head_number + 1
        )


# -- node detection / gossip / queue ----------------------------------------


def _leader_node(bls_keys, finalizer_keys=None):
    from harmony_tpu.multibls import PrivateKeys
    from harmony_tpu.node.node import Node
    from harmony_tpu.node.registry import Registry
    from harmony_tpu.p2p import InProcessNetwork

    genesis, ecdsa_keys, keys = dev_genesis(n_keys=4)
    net = InProcessNetwork()
    fin = FX.staking_finalizer(genesis, ecdsa_keys)
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16,
                       finalizer=fin)
    pool = TxPool(CHAIN_ID, 0, chain.state)
    reg = Registry(blockchain=chain, txpool=pool,
                   host=net.host("leader"))
    node = Node(reg, PrivateKeys.from_keys([keys[1]]))
    return node, net, keys, ecdsa_keys


def _double_commit(node, rogue, fake_hash=None):
    """Feed the leader a legit commit vote then a conflicting one."""
    from harmony_tpu.consensus.messages import FBFTMessage, MsgType

    announced = node.leader.current_block_hash
    legit_payload = node.leader._commit_payload(announced)
    node._on_commit(FBFTMessage(
        msg_type=MsgType.COMMIT, view_id=node.view_id,
        block_num=node.block_num, block_hash=announced,
        sender_pubkeys=[rogue.pub.bytes],
        payload=rogue.sign_hash(legit_payload).bytes,
    ))
    fake = fake_hash or bytes([0xAB]) * 32
    node._on_commit(FBFTMessage(
        msg_type=MsgType.COMMIT, view_id=node.view_id,
        block_num=node.block_num, block_hash=fake,
        sender_pubkeys=[rogue.pub.bytes],
        payload=rogue.sign_hash(
            node.leader._commit_payload(fake)
        ).bytes,
    ))


def test_commit_conflict_builds_record_and_gossips():
    """A commit-phase double vote at the leader becomes a verifiable
    Record, is queued for proposal, and floods the slash topic."""
    node, net, keys, ecdsa_keys = _leader_node(None)
    assert node.is_leader
    node.start_round_if_leader()

    heard = []
    probe = net.host("probe")
    probe.subscribe(node._slash_topic, lambda t, p, f: heard.append(p))

    _double_commit(node, keys[2])
    assert node.double_sign_events == 1
    assert len(node.pending_slash_records) == 1
    rec = node.pending_slash_records[0]
    SL.verify_record(rec, node.committee())  # re-verifies clean
    # offender resolved via the finalizer's harmony account table
    assert rec.evidence.offender == ecdsa_keys[2].address()
    assert rec.reporter == ecdsa_keys[1].address()
    assert heard, "record was not published on the slash topic"
    # includable only when the offender has slashable on-chain stake
    assert node._includable_slashes() == []


def test_gossiped_record_queued_with_dedup():
    from harmony_tpu.node.ingress import (
        NODE_MSG_SLASH, MessageCategory, pack_envelope,
    )

    node, net, keys, ecdsa_keys = _leader_node(None)
    rogue = keys[2]
    rec = _record(
        rogue, height=node.block_num - 0, view=node.view_id,
        epoch=0, offender=ecdsa_keys[2].address(),
        reporter=ecdsa_keys[3].address(),
    )
    # moment height must be in the past for chain-side checks, but the
    # node-side gossip handler only verifies the evidence crypto
    env = pack_envelope(MessageCategory.NODE, NODE_MSG_SLASH,
                        SL.encode_record(rec))
    node._handle(env)
    assert len(node.pending_slash_records) == 1
    node._handle(env)  # duplicate: deduped by fingerprint
    assert len(node.pending_slash_records) == 1
    # garbage on the slash topic is rejected by the validator
    from harmony_tpu.p2p.host import REJECT

    assert node._slash_validator(b"\x01\x10garbage", "x") == REJECT


def test_forensic_queue_evicts_duplicates_then_counts_drops():
    node, net, keys, _ = _leader_node(None)
    mk = lambda i: {  # noqa: E731
        "height": i, "view_id": i, "keys": [f"{i:02x}"],
        "shard_id": 0, "first_hash": "", "first_keys": [],
        "first_signature": "", "second_hash": "",
        "second_signature": "",
    }
    for i in range(64):
        node._queue_forensic_evidence(mk(i))
    assert len(node.pending_double_signs) == 64
    # a duplicate of an existing entry evicts the old copy, no drop
    node._queue_forensic_evidence(mk(3))
    assert len(node.pending_double_signs) == 64
    assert node.double_signs_dropped == 0
    # a FRESH offender at the cap is dropped — logged once + counted
    node._queue_forensic_evidence(mk(99))
    assert node.double_signs_dropped == 1
    assert node._ds_drop_logged
