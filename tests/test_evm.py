"""EVM interpreter: deploy, call, storage, revert, precompiles, and the
state_processor contract path (reference: core/vm)."""

import pytest

from harmony_tpu.core.state import StateDB
from harmony_tpu.core.state_processor import ExecutionError, StateProcessor
from harmony_tpu.core.types import Transaction
from harmony_tpu.core.vm import (
    EVM,
    Env,
    create_address,
    create2_address,
)
from harmony_tpu.crypto_ecdsa import ECDSAKey
from harmony_tpu.ref.keccak import keccak256

# runtime: no calldata -> return sload(0); calldata -> sstore(0, word0)
RUNTIME = bytes([
    0x36, 0x15, 0x60, 0x0C, 0x57,            # calldatasize iszero jumpi
    0x60, 0x00, 0x35, 0x60, 0x00, 0x55,      # sstore(0, calldataload(0))
    0x00,                                    # stop
    0x5B, 0x60, 0x00, 0x54,                  # jumpdest; sload(0)
    0x60, 0x00, 0x52,                        # mstore(0, val)
    0x60, 0x20, 0x60, 0x00, 0xF3,            # return(0, 32)
])

# init: codecopy(0, 12, len(RUNTIME)); return(0, len(RUNTIME))
INIT = bytes([
    0x60, len(RUNTIME), 0x60, 0x0C, 0x60, 0x00, 0x39,
    0x60, len(RUNTIME), 0x60, 0x00, 0xF3,
]) + RUNTIME

REVERTER = bytes([0x60, 0x00, 0x60, 0x00, 0xFD])  # revert(0, 0)

A = b"\xaa" * 20


def _evm(state):
    return EVM(state, Env(block_num=5, chain_id=2), origin=A, gas_price=1)


def test_deploy_and_call_roundtrip():
    state = StateDB()
    state.add_balance(A, 10**18)
    evm = _evm(state)
    ok, gas_left, addr = evm.create(A, 0, INIT, 1_000_000)
    assert ok and gas_left > 0
    assert state.code(addr) == RUNTIME
    assert addr == create_address(A, 0)

    # write 0x2a via calldata
    ok, _, out = evm.call(A, addr, 0, (42).to_bytes(32, "big"), 500_000)
    assert ok
    assert state.storage_get(addr, b"\x00" * 32) == 42
    # read it back
    ok, _, out = evm.call(A, addr, 0, b"", 500_000)
    assert ok and int.from_bytes(out, "big") == 42


def test_revert_unwinds_state_and_reports():
    state = StateDB()
    state.add_balance(A, 10**18)
    evm = _evm(state)
    ok, _, addr = evm.create(A, 0, INIT + b"", 1_000_000)
    ok, _, raddr = evm.create(
        A, 0, bytes([0x60, len(REVERTER), 0x60, 0x0C, 0x60, 0x00, 0x39,
                     0x60, len(REVERTER), 0x60, 0x00, 0xF3]) + REVERTER,
        1_000_000,
    )
    assert ok
    ok, gas_left, out = evm.call(A, raddr, 0, b"", 100_000)
    assert not ok and gas_left > 0  # revert refunds remaining gas


def test_value_transfer_through_call():
    state = StateDB()
    state.add_balance(A, 1000)
    evm = _evm(state)
    to = b"\xbb" * 20
    ok, _, _ = evm.call(A, to, 250, b"", 100_000)
    assert ok
    assert state.balance(to) == 250 and state.balance(A) == 750


def test_create2_address():
    state = StateDB()
    state.add_balance(A, 10**18)
    evm = _evm(state)
    salt = b"\x07" * 32
    ok, _, addr = evm.create(A, 0, INIT, 1_000_000, salt=salt)
    assert ok
    assert addr == create2_address(A, salt, INIT)


def test_precompiles():
    state = StateDB()
    state.add_balance(A, 10**18)
    evm = _evm(state)
    # identity (0x04)
    ok, _, out = evm.call(A, b"\x00" * 19 + b"\x04", 0, b"hello", 100_000)
    assert ok and out == b"hello"
    # sha256 (0x02)
    import hashlib

    ok, _, out = evm.call(A, b"\x00" * 19 + b"\x02", 0, b"x", 100_000)
    assert ok and out == hashlib.sha256(b"x").digest()
    # modexp (0x05): 3^4 mod 5 = 1
    data = (
        (1).to_bytes(32, "big") + (1).to_bytes(32, "big")
        + (1).to_bytes(32, "big") + b"\x03\x04\x05"
    )
    ok, _, out = evm.call(A, b"\x00" * 19 + b"\x05", 0, data, 100_000)
    assert ok and out == b"\x01"
    # ecrecover (0x01) against our own signer
    key = ECDSAKey.from_seed(b"\x11")
    h = keccak256(b"message")
    sig = key.sign(h)  # [R||S||V(0/1)]
    data = h + (27 + sig[64]).to_bytes(32, "big") + sig[:64]
    ok, _, out = evm.call(A, b"\x00" * 19 + b"\x01", 0, data, 100_000)
    assert ok and out[12:] == key.address()
    # bn256 pairing (0x08) fails by design
    ok, _, _ = evm.call(A, b"\x00" * 19 + b"\x08", 0, b"", 100_000)
    assert not ok


def test_processor_contract_path():
    """Deploy + interact through real signed transactions."""
    key = ECDSAKey.from_seed(b"\x22")
    sender = key.address()
    state = StateDB()
    state.add_balance(sender, 10**18)
    proc = StateProcessor(chain_id=2, shard_id=0)

    deploy = Transaction(
        nonce=0, gas_price=1, gas_limit=1_000_000, shard_id=0,
        to_shard=0, to=None, value=0, data=INIT,
    ).sign(key, 2)
    receipt, cx = proc.apply_transaction(state, deploy, 1, 0)
    assert receipt.status == 1 and cx is None
    addr = create_address(sender, 0)
    assert state.code(addr) == RUNTIME
    assert state.nonce(sender) == 1
    assert receipt.gas_used > 21_000  # intrinsic + create + execution

    call = Transaction(
        nonce=1, gas_price=1, gas_limit=200_000, shard_id=0,
        to_shard=0, to=addr, value=0, data=(7).to_bytes(32, "big"),
    ).sign(key, 2)
    receipt, _ = proc.apply_transaction(state, call, 2, 0)
    assert receipt.status == 1
    assert state.storage_get(addr, b"\x00" * 32) == 7

    # plain transfer to the contract-free address still works
    xfer = Transaction(
        nonce=2, gas_price=1, gas_limit=25_000, shard_id=0,
        to_shard=0, to=b"\x0c" * 20, value=5,
    ).sign(key, 2)
    receipt, _ = proc.apply_transaction(state, xfer, 3, 0)
    assert receipt.status == 1 and state.balance(b"\x0c" * 20) == 5

    # out-of-gas contract call: included with status 0, fee charged,
    # nonce advanced, storage untouched
    bal_before = state.balance(sender)
    oog = Transaction(
        nonce=3, gas_price=1, gas_limit=21_200, shard_id=0,
        to_shard=0, to=addr, value=0, data=(9).to_bytes(32, "big"),
    ).sign(key, 2)
    receipt, _ = proc.apply_transaction(state, oog, 4, 0)
    assert receipt.status == 0
    assert state.nonce(sender) == 4
    assert state.storage_get(addr, b"\x00" * 32) == 7  # unchanged
    assert state.balance(sender) == bal_before - receipt.gas_used

    # deterministic root across an independent replay
    state2 = StateDB()
    state2.add_balance(sender, 10**18)
    proc2 = StateProcessor(chain_id=2, shard_id=0)
    for i, tx in enumerate((deploy, call, xfer, oog)):
        proc2.apply_transaction(state2, tx, i + 1, 0)
    assert state2.root() == state.root()
    assert state2.mpt_root() == state.mpt_root()
