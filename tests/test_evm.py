"""EVM interpreter: deploy, call, storage, revert, precompiles, and the
state_processor contract path (reference: core/vm)."""

import pytest

from harmony_tpu.core.state import StateDB
from harmony_tpu.core.state_processor import ExecutionError, StateProcessor
from harmony_tpu.core.types import Transaction
from harmony_tpu.core.vm import (
    EVM,
    Env,
    create_address,
    create2_address,
)
from harmony_tpu.crypto_ecdsa import ECDSAKey
from harmony_tpu.ref.keccak import keccak256

# runtime: no calldata -> return sload(0); calldata -> sstore(0, word0)
RUNTIME = bytes([
    0x36, 0x15, 0x60, 0x0C, 0x57,            # calldatasize iszero jumpi
    0x60, 0x00, 0x35, 0x60, 0x00, 0x55,      # sstore(0, calldataload(0))
    0x00,                                    # stop
    0x5B, 0x60, 0x00, 0x54,                  # jumpdest; sload(0)
    0x60, 0x00, 0x52,                        # mstore(0, val)
    0x60, 0x20, 0x60, 0x00, 0xF3,            # return(0, 32)
])

# init: codecopy(0, 12, len(RUNTIME)); return(0, len(RUNTIME))
INIT = bytes([
    0x60, len(RUNTIME), 0x60, 0x0C, 0x60, 0x00, 0x39,
    0x60, len(RUNTIME), 0x60, 0x00, 0xF3,
]) + RUNTIME

REVERTER = bytes([0x60, 0x00, 0x60, 0x00, 0xFD])  # revert(0, 0)

A = b"\xaa" * 20


def _evm(state):
    return EVM(state, Env(block_num=5, chain_id=2), origin=A, gas_price=1)


def test_deploy_and_call_roundtrip():
    state = StateDB()
    state.add_balance(A, 10**18)
    evm = _evm(state)
    ok, gas_left, addr = evm.create(A, 0, INIT, 1_000_000)
    assert ok and gas_left > 0
    assert state.code(addr) == RUNTIME
    assert addr == create_address(A, 0)

    # write 0x2a via calldata
    ok, _, out = evm.call(A, addr, 0, (42).to_bytes(32, "big"), 500_000)
    assert ok
    assert state.storage_get(addr, b"\x00" * 32) == 42
    # read it back
    ok, _, out = evm.call(A, addr, 0, b"", 500_000)
    assert ok and int.from_bytes(out, "big") == 42


def test_revert_unwinds_state_and_reports():
    state = StateDB()
    state.add_balance(A, 10**18)
    evm = _evm(state)
    ok, _, addr = evm.create(A, 0, INIT + b"", 1_000_000)
    ok, _, raddr = evm.create(
        A, 0, bytes([0x60, len(REVERTER), 0x60, 0x0C, 0x60, 0x00, 0x39,
                     0x60, len(REVERTER), 0x60, 0x00, 0xF3]) + REVERTER,
        1_000_000,
    )
    assert ok
    ok, gas_left, out = evm.call(A, raddr, 0, b"", 100_000)
    assert not ok and gas_left > 0  # revert refunds remaining gas


def test_value_transfer_through_call():
    state = StateDB()
    state.add_balance(A, 1000)
    evm = _evm(state)
    to = b"\xbb" * 20
    ok, _, _ = evm.call(A, to, 250, b"", 100_000)
    assert ok
    assert state.balance(to) == 250 and state.balance(A) == 750


def test_create2_address():
    state = StateDB()
    state.add_balance(A, 10**18)
    evm = _evm(state)
    salt = b"\x07" * 32
    ok, _, addr = evm.create(A, 0, INIT, 1_000_000, salt=salt)
    assert ok
    assert addr == create2_address(A, salt, INIT)


def test_precompiles():
    state = StateDB()
    state.add_balance(A, 10**18)
    evm = _evm(state)
    # identity (0x04)
    ok, _, out = evm.call(A, b"\x00" * 19 + b"\x04", 0, b"hello", 100_000)
    assert ok and out == b"hello"
    # sha256 (0x02)
    import hashlib

    ok, _, out = evm.call(A, b"\x00" * 19 + b"\x02", 0, b"x", 100_000)
    assert ok and out == hashlib.sha256(b"x").digest()
    # modexp (0x05): 3^4 mod 5 = 1
    data = (
        (1).to_bytes(32, "big") + (1).to_bytes(32, "big")
        + (1).to_bytes(32, "big") + b"\x03\x04\x05"
    )
    ok, _, out = evm.call(A, b"\x00" * 19 + b"\x05", 0, data, 100_000)
    assert ok and out == b"\x01"
    # ecrecover (0x01) against our own signer
    key = ECDSAKey.from_seed(b"\x11")
    h = keccak256(b"message")
    sig = key.sign(h)  # [R||S||V(0/1)]
    data = h + (27 + sig[64]).to_bytes(32, "big") + sig[:64]
    ok, _, out = evm.call(A, b"\x00" * 19 + b"\x01", 0, data, 100_000)
    assert ok and out[12:] == key.address()
    # bn256 pairing (0x08): empty input is the vacuous product == 1
    # (EIP-197; full coverage in tests/test_bn256.py)
    ok, _, out = evm.call(A, b"\x00" * 19 + b"\x08", 0, b"", 100_000)
    assert ok and out == (1).to_bytes(32, "big")


def test_processor_contract_path():
    """Deploy + interact through real signed transactions."""
    key = ECDSAKey.from_seed(b"\x22")
    sender = key.address()
    state = StateDB()
    state.add_balance(sender, 10**18)
    proc = StateProcessor(chain_id=2, shard_id=0)

    deploy = Transaction(
        nonce=0, gas_price=1, gas_limit=1_000_000, shard_id=0,
        to_shard=0, to=None, value=0, data=INIT,
    ).sign(key, 2)
    receipt, cx = proc.apply_transaction(state, deploy, 1, 0)
    assert receipt.status == 1 and cx is None
    addr = create_address(sender, 0)
    assert state.code(addr) == RUNTIME
    assert state.nonce(sender) == 1
    assert receipt.gas_used > 21_000  # intrinsic + create + execution

    call = Transaction(
        nonce=1, gas_price=1, gas_limit=200_000, shard_id=0,
        to_shard=0, to=addr, value=0, data=(7).to_bytes(32, "big"),
    ).sign(key, 2)
    receipt, _ = proc.apply_transaction(state, call, 2, 0)
    assert receipt.status == 1
    assert state.storage_get(addr, b"\x00" * 32) == 7

    # plain transfer to the contract-free address still works
    xfer = Transaction(
        nonce=2, gas_price=1, gas_limit=25_000, shard_id=0,
        to_shard=0, to=b"\x0c" * 20, value=5,
    ).sign(key, 2)
    receipt, _ = proc.apply_transaction(state, xfer, 3, 0)
    assert receipt.status == 1 and state.balance(b"\x0c" * 20) == 5

    # out-of-gas contract call: included with status 0, fee charged,
    # nonce advanced, storage untouched
    bal_before = state.balance(sender)
    oog = Transaction(
        nonce=3, gas_price=1, gas_limit=21_200, shard_id=0,
        to_shard=0, to=addr, value=0, data=(9).to_bytes(32, "big"),
    ).sign(key, 2)
    receipt, _ = proc.apply_transaction(state, oog, 4, 0)
    assert receipt.status == 0
    assert state.nonce(sender) == 4
    assert state.storage_get(addr, b"\x00" * 32) == 7  # unchanged
    assert state.balance(sender) == bal_before - receipt.gas_used

    # deterministic root across an independent replay
    state2 = StateDB()
    state2.add_balance(sender, 10**18)
    proc2 = StateProcessor(chain_id=2, shard_id=0)
    for i, tx in enumerate((deploy, call, xfer, oog)):
        proc2.apply_transaction(state2, tx, i + 1, 0)
    assert state2.root() == state.root()
    assert state2.mpt_root() == state.mpt_root()


def test_failed_precompile_call_reverts_value_transfer():
    """A precompile call that runs out of gas must leave NO state effect
    (advisor r2: the value transfer used to survive the failure)."""
    state = StateDB()
    state.add_balance(A, 10**18)
    evm = _evm(state)
    sha = (2).to_bytes(20, "big")
    # gas 10 is below the sha256 base cost of 60 -> precompile fails
    ok, gas_left, out = evm.call(A, sha, 777, b"x", 10)
    assert not ok
    assert state.balance(A) == 10**18
    assert state.balance(sha) == 0


def test_zero_size_memory_op_with_huge_offset_is_free():
    """RETURN(huge_offset, 0) must not fail the offset bound check
    (advisor r2: zero-size ops are free no-ops in the EVM)."""
    state = StateDB()
    evm = _evm(state)
    # PUSH1 0; PUSH8 2^60; RETURN  -> return(huge, 0)
    code = bytes([0x60, 0x00, 0x67]) + (1 << 60).to_bytes(8, "big") + bytes([0xF3])
    out, gas = evm._run(code, A, A, 0, b"", 100_000, False)
    assert out == b""


def test_delegatecall_reaches_precompile():
    """DELEGATECALL to sha256 must execute the precompile, not succeed
    with empty output (advisor r2)."""
    state = StateDB()
    state.add_balance(A, 10**18)
    evm = _evm(state)
    # contract: calldatacopy(0,0,calldatasize);
    #   delegatecall(gas, 0x2, 0, calldatasize, 0x20, 0x20); pop
    #   return(0x20, 0x20)
    code = bytes([
        0x36, 0x60, 0x00, 0x60, 0x00, 0x37,        # calldatacopy(0,0,size)
        0x60, 0x20, 0x60, 0x20, 0x36, 0x60, 0x00,  # out 0x20/0x20, in 0/size
        0x60, 0x02, 0x5A, 0xF4,                    # delegatecall(gas, 2, ...)
        0x50,                                      # pop ok flag
        0x60, 0x20, 0x60, 0x20, 0xF3,              # return(0x20, 0x20)
    ])
    import hashlib
    ca = b"\xcc" * 20
    state.set_code(ca, code)
    ok, _, out = evm.call(A, ca, 0, b"abc", 500_000)
    assert ok
    assert out == hashlib.sha256(b"abc").digest()


def test_journal_nested_revert_restores_exact_state():
    """Nested CALL reverting must roll back only the inner frame's
    mutations (journal replaces full-state deepcopy; advisor r2)."""
    state = StateDB()
    state.add_balance(A, 10**18)
    evm = _evm(state)
    # inner contract: sstore(0, 7); revert(0,0)
    inner = bytes([0x60, 0x07, 0x60, 0x00, 0x55, 0x60, 0x00, 0x60, 0x00, 0xFD])
    ia = b"\xdd" * 20
    state.set_code(ia, inner)
    # outer: sstore(0, 5); call(gas, inner, 0, 0,0,0,0); sstore(1, 9); stop
    outer = bytes([
        0x60, 0x05, 0x60, 0x00, 0x55,              # sstore(0, 5)
        0x60, 0x00, 0x60, 0x00, 0x60, 0x00, 0x60, 0x00,
        0x60, 0x00, 0x73]) + ia + bytes([          # push addr
        0x5A, 0xF1, 0x50,                          # call, pop
        0x60, 0x09, 0x60, 0x01, 0x55,              # sstore(1, 9)
        0x00,
    ])
    oa = b"\xee" * 20
    state.set_code(oa, outer)
    ok, _, _ = evm.call(A, oa, 0, b"", 500_000)
    assert ok
    assert state.storage_get(oa, b"\x00" * 32) == 5     # outer write kept
    assert state.storage_get(oa, (1).to_bytes(32, "big")) == 9
    assert state.storage_get(ia, b"\x00" * 32) == 0     # inner write undone
    state.end_tx()


def test_journal_end_tx_disables_journaling():
    state = StateDB()
    mark = state.snapshot()
    state.add_balance(A, 5)
    state.end_tx()
    state.add_balance(A, 5)       # not journaled
    assert state.balance(A) == 10


# -- staking precompile (address 252), EIP-2929, call tracer ------------


def _mk_validator(state, vaddr):
    from harmony_tpu.core.state import Delegation, ValidatorWrapper

    state.set_validator(ValidatorWrapper(
        address=vaddr, bls_keys=[b"\x01" * 48],
        delegations=[Delegation(vaddr, 100)],
    ))


def _stake_calldata(selector_sig, *args32):
    sel = keccak256(selector_sig)[:4]
    return sel + b"".join(args32)


def test_staking_precompile_delegate_from_contract():
    from harmony_tpu.core.vm import STAKING_PRECOMPILE_ADDR

    state = StateDB()
    vaddr = b"\x56" * 20
    _mk_validator(state, vaddr)
    ca = b"\xcb" * 20  # the delegating contract
    state.add_balance(ca, 10_000)
    evm = _evm(state)
    data = _stake_calldata(
        b"Delegate(address,address,uint256)",
        ca.rjust(32, b"\x00"), vaddr.rjust(32, b"\x00"),
        (500).to_bytes(32, "big"),
    )
    ok, gas_left, out = evm.call(ca, STAKING_PRECOMPILE_ADDR, 0, data,
                                 200_000)
    assert ok
    assert state.balance(ca) == 9_500
    w = state.validator(vaddr)
    assert any(d.delegator == ca and d.amount == 500
               for d in w.delegations)
    assert evm.stake_msgs == [("delegate", ca, vaddr, 500)]


def test_staking_precompile_rejects_other_delegator():
    from harmony_tpu.core.vm import STAKING_PRECOMPILE_ADDR

    state = StateDB()
    vaddr = b"\x56" * 20
    _mk_validator(state, vaddr)
    ca = b"\xcb" * 20
    other = b"\xcc" * 20
    state.add_balance(ca, 10_000)
    evm = _evm(state)
    data = _stake_calldata(
        b"Delegate(address,address,uint256)",
        other.rjust(32, b"\x00"), vaddr.rjust(32, b"\x00"),
        (500).to_bytes(32, "big"),
    )
    ok, _, _ = evm.call(ca, STAKING_PRECOMPILE_ADDR, 0, data, 200_000)
    assert not ok
    assert state.balance(ca) == 10_000  # nothing moved


def test_staking_precompile_undelegate_and_collect():
    from harmony_tpu.core.state import Delegation, ValidatorWrapper
    from harmony_tpu.core.vm import STAKING_PRECOMPILE_ADDR

    state = StateDB()
    vaddr = b"\x56" * 20
    ca = b"\xcb" * 20
    state.set_validator(ValidatorWrapper(
        address=vaddr, bls_keys=[b"\x01" * 48],
        delegations=[Delegation(vaddr, 100),
                     Delegation(ca, 300, reward=44)],
    ))
    evm = _evm(state)
    data = _stake_calldata(
        b"Undelegate(address,address,uint256)",
        ca.rjust(32, b"\x00"), vaddr.rjust(32, b"\x00"),
        (200).to_bytes(32, "big"),
    )
    ok, _, _ = evm.call(ca, STAKING_PRECOMPILE_ADDR, 0, data, 200_000)
    assert ok
    w = state.validator(vaddr)
    d = next(d for d in w.delegations if d.delegator == ca)
    assert d.amount == 100 and d.undelegations == [(200, 0)]
    ok, _, _ = evm.call(
        ca, STAKING_PRECOMPILE_ADDR, 0,
        _stake_calldata(b"CollectRewards(address)", ca.rjust(32, b"\x00")),
        200_000,
    )
    assert ok
    assert state.balance(ca) == 44


def test_staking_precompile_reverts_with_outer_frame():
    """A contract that delegates then REVERTs must leave staking state
    untouched (journaled set_validator)."""
    from harmony_tpu.core.vm import STAKING_PRECOMPILE_ADDR

    state = StateDB()
    vaddr = b"\x56" * 20
    _mk_validator(state, vaddr)
    ca = b"\xcd" * 20
    state.add_balance(ca, 10_000)
    evm = _evm(state)
    data = _stake_calldata(
        b"Delegate(address,address,uint256)",
        ca.rjust(32, b"\x00"), vaddr.rjust(32, b"\x00"),
        (500).to_bytes(32, "big"),
    )
    # contract: calldatacopy(0,0,size); call(gas, 0xfc, 0, 0, size, 0, 0); revert(0,0)
    code = bytes([
        0x36, 0x60, 0x00, 0x60, 0x00, 0x37,
        0x60, 0x00, 0x60, 0x00, 0x36, 0x60, 0x00, 0x60, 0x00,
        0x73]) + STAKING_PRECOMPILE_ADDR + bytes([
        0x5A, 0xF1, 0x50,
        0x60, 0x00, 0x60, 0x00, 0xFD,
    ])
    state.set_code(ca, code)
    ok, _, _ = evm.call(A, ca, 0, data, 500_000)
    assert not ok
    assert state.balance(ca) == 10_000
    w = state.validator(vaddr)
    assert all(d.delegator != ca for d in w.delegations)
    state.end_tx()


def test_staking_precompile_wrong_shard_fails():
    from harmony_tpu.core.vm import STAKING_PRECOMPILE_ADDR

    state = StateDB()
    vaddr = b"\x56" * 20
    _mk_validator(state, vaddr)
    ca = b"\xcb" * 20
    state.add_balance(ca, 10_000)
    evm = EVM(state, Env(block_num=5, chain_id=2, shard_id=1),
              origin=A, gas_price=1)
    data = _stake_calldata(
        b"Delegate(address,address,uint256)",
        ca.rjust(32, b"\x00"), vaddr.rjust(32, b"\x00"),
        (500).to_bytes(32, "big"),
    )
    ok, _, _ = evm.call(ca, STAKING_PRECOMPILE_ADDR, 0, data, 200_000)
    assert not ok


def test_eip2929_cold_then_warm_sload():
    """First SLOAD of a slot is cold (2100), repeat is warm (100)."""
    state = StateDB()
    ca = b"\xce" * 20
    # sload(7); pop; sload(7); pop; stop
    code = bytes([0x60, 0x07, 0x54, 0x50, 0x60, 0x07, 0x54, 0x50, 0x00])
    state.set_code(ca, code)
    evm = _evm(state)
    ok, gas_left, _ = evm.call(A, ca, 0, b"", 100_000)
    assert ok
    used = 100_000 - gas_left
    # 2 pushes(3) + 2 pops(2) + cold 2100 + warm 100
    assert used == 3 + 3 + 2 + 2 + 2100 + 100
    # legacy mode: flat SLOAD_GAS
    evm2 = EVM(StateDB(), Env(), origin=A, gas_price=1, berlin=False)
    evm2.state.set_code(ca, code)
    ok, gas_left2, _ = evm2.call(A, ca, 0, b"", 100_000)
    assert ok
    assert 100_000 - gas_left2 == 3 + 3 + 2 + 2 + 800 + 800


def test_eip2929_access_list_reverts_with_frame():
    """EIP-2929: an inner frame's warmed slots revert with it."""
    state = StateDB()
    evm = _evm(state)
    inner = b"\xd1" * 20
    # inner: sload(3); pop; revert(0,0)
    state.set_code(inner, bytes([0x60, 0x03, 0x54, 0x50,
                                 0x60, 0x00, 0x60, 0x00, 0xFD]))
    ok, _, _ = evm.call(A, inner, 0, b"", 100_000)
    assert not ok
    assert (inner, (3).to_bytes(32, "big")) not in evm.warm_slots
    state.end_tx()


def test_call_tracer_captures_nested_calls():
    from harmony_tpu.core.vm import CallTracer

    state = StateDB()
    tracer = CallTracer()
    evm = EVM(state, Env(block_num=5, chain_id=2), origin=A,
              gas_price=1, tracer=tracer)
    inner = b"\xd2" * 20
    state.set_code(inner, bytes([0x00]))  # stop
    outer = b"\xd3" * 20
    # call(gas, inner, 0, 0,0,0,0); stop
    code = bytes([
        0x60, 0x00, 0x60, 0x00, 0x60, 0x00, 0x60, 0x00, 0x60, 0x00,
        0x73]) + inner + bytes([0x5A, 0xF1, 0x50, 0x00])
    state.set_code(outer, code)
    ok, _, _ = evm.call(A, outer, 0, b"\x99", 200_000)
    assert ok
    assert tracer.root["type"] == "CALL"
    assert tracer.root["to"] == outer.hex()
    assert tracer.root["input"] == "99"
    assert len(tracer.root["calls"]) == 1
    assert tracer.root["calls"][0]["to"] == inner.hex()
    assert "gasUsed" in tracer.root
