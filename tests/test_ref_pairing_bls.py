"""Pairing bilinearity and BLS end-to-end tests for the reference layer.

Marked-slow cases are the bigint pairing computations (~0.3 s each); the
suite keeps the count small — the TPU tests get their ground truth from
fixture values computed here once.
"""

import random

import pytest

from harmony_tpu.ref import bls
from harmony_tpu.ref import fields as F
from harmony_tpu.ref import pairing as PR
from harmony_tpu.ref.curve import G1_GEN, G2_GEN, g1, g2
from harmony_tpu.ref.hash_to_curve import hash_to_g2, map_to_twist
from harmony_tpu.ref.params import R_ORDER

rng = random.Random(0x9A1)


@pytest.fixture(scope="module")
def e_gen():
    return PR.pairing(G1_GEN, G2_GEN)


def test_pairing_nondegenerate_order_r(e_gen):
    assert e_gen != F.FP12_ONE
    assert F.fp12_pow(e_gen, R_ORDER) == F.FP12_ONE


def test_hard_part_x_chain_identity():
    # the TPU final exponentiation runs this addition chain; the cubed
    # pairing convention rests on this identity (see ref/pairing.py)
    from harmony_tpu.ref.params import P, X

    lam = (P**4 - P**2 + 1) // R_ORDER
    assert (X - 1) ** 2 * (X + P) * (X**2 + P**2 - 1) + 3 == 3 * lam


def test_bilinearity(e_gen):
    a = rng.randrange(1, 1 << 64)
    b = rng.randrange(1, 1 << 64)
    eab = PR.pairing(g1.mul(G1_GEN, a), g2.mul(G2_GEN, b))
    assert eab == F.fp12_pow(e_gen, a * b)


def test_multi_pairing_matches_product(e_gen):
    # e(-G1, 2 G2) * e(2 G1, G2) == 1
    gt = PR.multi_pairing(
        [(g1.neg(G1_GEN), g2.dbl(G2_GEN)), (g1.dbl(G1_GEN), G2_GEN)]
    )
    assert gt == F.FP12_ONE


def test_hash_to_g2_deterministic_subgroup():
    h1 = hash_to_g2(b"m" * 32)
    h2 = hash_to_g2(b"m" * 32)
    assert h1 == h2
    assert g2.is_on_curve(h1)
    assert g2.mul(h1, R_ORDER) is None
    assert hash_to_g2(b"n" * 32) != h1


def test_map_to_twist_off_subgroup_is_handled():
    pt = map_to_twist(b"x" * 32)
    assert g2.is_on_curve(pt)


def test_bls_sign_verify():
    sk = bls.keygen(b"\x01")
    pk = bls.pubkey(sk)
    msg = b"0123456789abcdef0123456789abcdef"
    sig = bls.sign(sk, msg)
    assert bls.verify(pk, msg, sig)
    assert not bls.verify(pk, b"y" * 32, sig)
    assert not bls.verify(bls.pubkey(sk + 1), msg, sig)


def test_bls_aggregate_verify():
    sks = [bls.keygen(bytes([i])) for i in range(3)]
    pks = [bls.pubkey(sk) for sk in sks]
    msg = b"0123456789abcdef0123456789abcdef"
    sigs = [bls.sign(sk, msg) for sk in sks]
    agg = bls.aggregate_sigs(sigs)
    assert bls.verify_aggregate(pks, msg, agg)
    assert not bls.verify_aggregate(pks[:2], msg, agg)


def test_serialization_roundtrip_and_sizes():
    sk = bls.keygen(b"\x07")
    pk = bls.pubkey(sk)
    msg = b"0123456789abcdef0123456789abcdef"
    sig = bls.sign(sk, msg)
    pkb, sigb = bls.pubkey_to_bytes(pk), bls.sig_to_bytes(sig)
    assert len(pkb) == 48 and len(sigb) == 96
    assert bls.pubkey_from_bytes(pkb) == pk
    assert bls.sig_from_bytes(sigb) == sig
    # infinity encodings
    assert bls.pubkey_from_bytes(bytes([0xC0]) + bytes(47)) is None
    assert bls.sig_from_bytes(bytes([0xC0]) + bytes(95)) is None
    # negated point flips the sign bit
    negb = bls.pubkey_to_bytes(g1.neg(pk))
    assert negb[0] ^ pkb[0] == 0x20


def test_keccak_vectors():
    from harmony_tpu.ref.keccak import keccak256

    assert (
        keccak256(b"").hex()
        == "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470"
    )
    assert (
        keccak256(b"abc").hex()
        == "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45"
    )
    assert len(keccak256(b"x" * 1000)) == 32
    # rate-1 input length exercises the single-byte 0x81 padding branch
    assert (
        keccak256(b"z" * 135).hex()
        == "796f5184228df590c13bfb8992d2c10b6562903362103899249736357eb573fd"
    )
