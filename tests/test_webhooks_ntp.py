"""Double-sign detection + webhooks + NTP parsing."""

import pytest

from harmony_tpu import bls as B
from harmony_tpu.consensus.messages import FBFTMessage, MsgType
from harmony_tpu.consensus.signature import prepare_payload
from harmony_tpu.core.blockchain import Blockchain
from harmony_tpu.core.genesis import dev_genesis
from harmony_tpu.core.kv import MemKV
from harmony_tpu.core.tx_pool import TxPool
from harmony_tpu.multibls import PrivateKeys
from harmony_tpu.node.node import Node
from harmony_tpu.node.registry import Registry
from harmony_tpu.p2p import InProcessNetwork
from harmony_tpu.staking.slash import (
    Evidence,
    Moment,
    Record,
    SlashVerifyError,
    Vote,
    detect_double_sign,
    verify_record,
)
from harmony_tpu.webhooks import Hooks

CHAIN_ID = 2


def test_leader_detects_double_sign_and_fires_webhook():
    genesis, _, bls_keys = dev_genesis(n_keys=4)
    net = InProcessNetwork()
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    pool = TxPool(CHAIN_ID, 0, chain.state)
    hooks = Hooks()
    fired = []
    hooks.register("double_sign", fired.append)
    # the round-robin leader for view 1 holds committee key 1
    reg = Registry(blockchain=chain, txpool=pool,
                   host=net.host("leader"), webhooks=hooks)
    node = Node(reg, PrivateKeys.from_keys([bls_keys[1]]))
    assert node.is_leader
    node.start_round_if_leader()

    # the equivocating validator (key 2) first votes for the announced
    # block, then for a DIFFERENT hash — both properly signed
    rogue = bls_keys[2]
    announced = node.leader.current_block_hash
    legit = FBFTMessage(
        msg_type=MsgType.PREPARE,
        view_id=node.view_id,
        block_num=node.block_num,
        block_hash=announced,
        sender_pubkeys=[rogue.pub.bytes],
        payload=rogue.sign_hash(prepare_payload(announced)).bytes,
    )
    node._on_prepare(legit)
    other_hash = b"\x66" * 32
    vote = FBFTMessage(
        msg_type=MsgType.PREPARE,
        view_id=node.view_id,
        block_num=node.block_num,
        block_hash=other_hash,
        sender_pubkeys=[rogue.pub.bytes],
        payload=rogue.sign_hash(prepare_payload(other_hash)).bytes,
    )
    node._on_prepare(vote)
    assert len(node.pending_double_signs) == 1
    assert fired and fired[0]["second_hash"] == other_hash.hex()
    assert fired[0]["keys"] == [rogue.pub.bytes.hex()]
    # BOTH signed votes are in the evidence (a valid slash record needs
    # the pair) and the queue drains for the slash pipeline
    assert fired[0]["first_hash"] == announced.hex()
    assert fired[0]["first_signature"]
    # a vote from a key that never voted this round is NOT equivocation
    delayed = FBFTMessage(
        msg_type=MsgType.PREPARE,
        view_id=node.view_id,
        block_num=node.block_num,
        block_hash=b"\x55" * 32,
        sender_pubkeys=[bls_keys[0].pub.bytes],
        payload=bls_keys[0].sign_hash(
            prepare_payload(b"\x55" * 32)
        ).bytes,
    )
    node._on_prepare(delayed)
    assert len(node.pending_double_signs) == 1

    # unsigned junk for a different hash must NOT frame anyone — even
    # from a key that DID vote this round (rogue), the conflicting
    # signature must verify before evidence is recorded
    junk = FBFTMessage(
        msg_type=MsgType.PREPARE,
        view_id=node.view_id,
        block_num=node.block_num,
        block_hash=b"\x77" * 32,
        sender_pubkeys=[rogue.pub.bytes],
        payload=b"\x01" * 96,
    )
    node._on_prepare(junk)
    assert len(node.pending_double_signs) == 1
    assert node.drain_double_signs() and not node.pending_double_signs


def test_slash_record_verify():
    keys = [B.PrivateKey.generate(bytes([90 + i])) for i in range(3)]
    committee = [k.pub.bytes for k in keys]
    h1, h2 = b"\x01" * 32, b"\x02" * 32
    moment = Moment(epoch=1, shard_id=0, height=5, view_id=6)
    from harmony_tpu.consensus.signature import construct_commit_payload

    def vote_for(h):
        payload = construct_commit_payload(h, 5, 6, True)
        return Vote(
            signer_pubkeys=[keys[0].pub.bytes],
            block_header_hash=h,
            signature=keys[0].sign_hash(payload).bytes,
        )

    record = Record(
        evidence=Evidence(
            moment=moment, first_vote=vote_for(h1),
            second_vote=vote_for(h2), offender=b"\x0a" * 20,
        ),
        reporter=b"\x0b" * 20,
    )
    verify_record(record, committee)  # no raise
    # tampered signature fails
    bad = Record(
        evidence=Evidence(
            moment=moment, first_vote=vote_for(h1),
            second_vote=Vote(
                signer_pubkeys=[keys[0].pub.bytes],
                block_header_hash=h2,
                signature=b"\x03" * 96,
            ),
            offender=b"\x0a" * 20,
        ),
        reporter=b"\x0b" * 20,
    )
    with pytest.raises(SlashVerifyError):
        verify_record(bad, committee)
    assert detect_double_sign({b"k": h1}, b"k", h2) == h1
    assert detect_double_sign({b"k": h1}, b"k", h1) is None


def test_hooks_never_raise_and_http_hook_shape():
    hooks = Hooks()
    hooks.register("view_change", lambda p: 1 / 0)  # broken hook
    hooks.fire("view_change", {"view": 5})  # must not raise
    assert list(hooks.fired) == [("view_change", {"view": 5})]
    # the event log is bounded
    for i in range(1000):
        hooks.fire("view_change", {"view": i})
    assert len(hooks.fired) == 256
    with pytest.raises(ValueError):
        hooks.register("nonsense", lambda p: None)


def test_ntp_parse_and_offline_tolerance():
    from harmony_tpu import ntp

    # unreachable server: check passes with offset None
    ok, offset = ntp.check_clock(server="127.0.0.1", max_drift=1.0)
    assert ok and offset is None
