"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Real TPU hardware (single chip) is only used by bench.py; all tests —
including the multi-chip sharding tests under tests/test_parallel*.py —
run on CPU with 8 virtual XLA devices so CI needs no accelerator.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
