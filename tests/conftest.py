"""Test configuration: CPU-only JAX with a persistent compile cache.

The axon sitecustomize force-selects jax_platforms="axon,cpu" via
jax.config.update at interpreter start, which silently overrides the
JAX_PLATFORMS env var — so the env var alone is NOT enough; we must
counter-update the config before any backend initializes.

Multi-chip sharding is validated in a SEPARATE process
(tests/test_parallel.py subprocesses __graft_entry__.dryrun_multichip
with xla_force_host_platform_device_count): executables compiled under
forced multi-device CPU topologies segfault XLA's persistent-cache
serializer on this image (observed twice in put_executable_and_time), so
the in-process suite stays single-device where cache writes are stable
and warm across runs.
"""

import os

import jax

jax.config.update("jax_platforms", "cpu")

# Big-integer field arithmetic compiles slowly on XLA:CPU (~7 ms/HLO line);
# cache compiled executables across test runs and sessions.
_CACHE = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
jax.config.update("jax_compilation_cache_dir", os.path.abspath(_CACHE))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
