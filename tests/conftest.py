"""Test configuration: CPU-only JAX with a READ-ONLY compile cache.

The axon sitecustomize force-selects jax_platforms="axon,cpu" via
jax.config.update at interpreter start, which silently overrides the
JAX_PLATFORMS env var — so the env var alone is NOT enough; we must
counter-update the config before any backend initializes.

The persistent cache is READ-only here: XLA's cache serializer
(put_executable_and_time) segfaults intermittently on this image —
first observed under forced multi-device CPU topologies, then
(2026-07-29 02:16) on a plain single-device suite run.  Reads are safe
and serve the warm cache built by bench/entry runs; writes are gated
off by an unreachable min-compile-time.  Multi-chip sharding is
validated in a SEPARATE process (tests/test_parallel.py subprocesses
__graft_entry__.dryrun_multichip).
"""

import os

# XLA:CPU's parallel LLVM codegen (default split 32 threads) has
# intermittently segfaulted backend_compile_and_load on this 1-core
# image (2026-07-29, twice); serialize codegen before jax initializes.
_flags = os.environ.get("XLA_FLAGS", "")
if "parallel_codegen" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_cpu_parallel_codegen_split_count=1"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

# Big-integer field arithmetic compiles slowly on XLA:CPU (~7 ms/HLO line);
# reuse executables cached by bench/entry runs (reads only — see above).
_CACHE = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
jax.config.update("jax_compilation_cache_dir", os.path.abspath(_CACHE))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 10**9)

# The XLA-heavy crypto tier (pairing-shaped programs) has segfaulted
# XLA's CPU compiler on this image more than once, killing whole suite
# runs (VERDICT r2 weak #10; observed again 2026-07-30).  Those modules
# run SUBPROCESS-ISOLATED through test_ops_heavy_isolated.py — a
# compiler crash there becomes one failing test with a clear message
# instead of aborting the suite.  Set OPS_INPROC=1 to collect them
# in-process (fast iteration on a box with a warm cache).
if os.environ.get("OPS_INPROC") != "1":
    collect_ignore = [
        "test_ops_pairing_bls.py",
        "test_ref_pairing_bls.py",
    ]

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 `-m 'not slow'` budget "
        "(10^5-account profiling runs; check.sh runs them in a "
        "dedicated stage)",
    )


_EXIT_STATUS = [0]


@pytest.hookimpl(trylast=True)
def pytest_sessionfinish(session, exitstatus):
    _EXIT_STATUS[0] = int(exitstatus)


@pytest.hookimpl(trylast=True)
def pytest_unconfigure(config):
    """Hard-exit with pytest's REAL verdict: jaxlib's atexit teardown
    segfaults/aborts nondeterministically on this image after
    thread-heavy suites (observed 2026-08-04 with the chaos localnet
    tier: "terminate called without an active exception" / SIGSEGV
    with no Python frame, AFTER all tests passed and the summary
    printed).  unconfigure runs after the terminal summary, so
    os._exit skips only the crashing interpreter teardown — never a
    test outcome or a report line.  Timeout kills (the tier-1 870 s
    budget) bypass this hook unchanged."""
    import sys

    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(_EXIT_STATUS[0])
