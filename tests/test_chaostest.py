"""Chaos-scenario framework tier: faultinject phase windows, flight-
recorder dump capping, the scenario registry, violation evidence, the
view-change quorum-mid-drain regression, and THE acceptance scenario
(leader black-holed under flood -> view change -> recovery)."""

import json
import os
import time

import pytest

from harmony_tpu import faultinject as FI
from harmony_tpu import trace


@pytest.fixture(autouse=True)
def _clean():
    FI.reset()
    trace.reset()
    yield
    FI.reset()
    trace.reset()


# -- faultinject: timed/phased arm mode --------------------------------------


def test_fault_window_t0_t1():
    """A rule with a [t0, t1) window fires only inside it, and hits
    outside the window don't consume its counting budget.  Margins are
    wide on the SIDE a scheduler stall could flip: pre-t0 fires happen
    microseconds after arm (t0=0.3s away), the in-window fire happens
    with ~10s of t1 headroom, and the closed-window case uses its own
    already-expired rule."""
    FI.arm("w.point", exc=RuntimeError, t0=0.3, t1=10.0, times=1)
    FI.fire("w.point")  # before t0: invisible (would have fired times=1)
    time.sleep(0.35)
    with pytest.raises(RuntimeError):
        FI.fire("w.point")
    FI.reset()
    FI.arm("w.closed", exc=RuntimeError, t1=0.05)
    time.sleep(0.1)
    FI.fire("w.closed")  # window closed: no fault


def test_fault_window_budget_not_consumed_outside():
    """after= counts only live hits: pre-window traffic must not eat
    the skip budget."""
    FI.arm("w.budget", exc=ValueError, t0=0.3, after=1)
    for _ in range(5):
        FI.fire("w.budget")  # pre-window: not counted
    time.sleep(0.35)
    FI.fire("w.budget")  # first LIVE hit: skipped by after=1
    with pytest.raises(ValueError):
        FI.fire("w.budget")


def test_fault_when_predicate_round_window():
    """when= gates liveness on a cheap predicate — the 'between round
    k and k+m' scripting mode."""
    head = {"n": 0}
    FI.arm("w.round", exc=ConnectionError,
           when=lambda: 3 <= head["n"] < 5)
    for n in (0, 1, 2):
        head["n"] = n
        FI.fire("w.round")
    head["n"] = 3
    with pytest.raises(ConnectionError):
        FI.fire("w.round")
    head["n"] = 5
    FI.fire("w.round")  # window closed


def test_fault_when_predicate_error_is_safe():
    """A broken predicate must never fault the production call site."""
    FI.arm("w.broken", exc=RuntimeError,
           when=lambda: (_ for _ in ()).throw(ValueError))
    FI.fire("w.broken")  # predicate raised -> rule invisible


# -- trace: flight-recorder dump capping -------------------------------------


def test_anomaly_dedup_by_kind_and_trace(tmp_path):
    """One (kind, trace_id) pair dumps at most once; a different trace
    id of the same kind still dumps (cooldown disabled)."""
    trace.configure(enabled=True, dump_dir=str(tmp_path),
                    dump_cooldown_s=0)
    p1 = trace.anomaly("storm", trace_id="a" * 32)
    assert p1 is not None and os.path.exists(p1)
    assert trace.anomaly("storm", trace_id="a" * 32) is None  # dedup
    p2 = trace.anomaly("storm", trace_id="b" * 32)
    assert p2 is not None and p2 != p1
    # a different kind on the already-dumped trace is fresh evidence
    assert trace.anomaly("desync", trace_id="a" * 32) is not None


def test_anomaly_disk_budget(tmp_path):
    """Once the byte budget is spent no further dumps are written;
    reset() restores the default budget."""
    trace.configure(enabled=True, dump_dir=str(tmp_path),
                    dump_cooldown_s=0, dump_max_bytes=1)
    p1 = trace.anomaly("k1", trace_id="c" * 32)
    assert p1 is not None  # budget checked before the first write
    assert trace.anomaly("k2", trace_id="d" * 32) is None  # spent
    assert trace.anomaly("k3", trace_id="e" * 32) is None
    trace.reset()
    trace.configure(enabled=True, dump_dir=str(tmp_path),
                    dump_cooldown_s=0)
    assert trace.anomaly("k4", trace_id="f" * 32) is not None


def test_anomaly_failed_write_does_not_burn_dedup(tmp_path):
    """A dump that never reached disk (unwritable dir) must not mark
    its (kind, trace_id) seen: the next trigger after the disk
    recovers still writes the evidence."""
    blocker = tmp_path / "not-a-dir"
    blocker.write_text("file where a directory must go")
    trace.configure(enabled=True, dump_dir=str(blocker),
                    dump_cooldown_s=0)
    assert trace.anomaly("diskfail", trace_id="a" * 32) is None
    trace.configure(dump_dir=str(tmp_path))
    p = trace.anomaly("diskfail", trace_id="a" * 32)
    assert p is not None and os.path.exists(p)


def test_anomaly_cooldown_still_applies(tmp_path):
    trace.configure(enabled=True, dump_dir=str(tmp_path),
                    dump_cooldown_s=60.0)
    assert trace.anomaly("cool", trace_id="1" * 32) is not None
    # new trace id, same kind, inside the cooldown: suppressed
    assert trace.anomaly("cool", trace_id="2" * 32) is None


# -- scenario registry -------------------------------------------------------


def test_scenario_registry_names_and_shape():
    from harmony_tpu.chaostest import SCENARIOS

    assert set(SCENARIOS) == {
        "view_change_storm", "epoch_election_rotation",
        "cross_shard_partition", "validator_churn", "sidecar_flap",
        "leader_kill_restart", "rolling_restart",
        "byz_equivocating_leader", "byz_double_voter_slashed",
        "byz_invalid_proposal_flood",
        "overload_storm", "wedged_thread_recovery",
        "gray_leader", "asymmetric_partition",
        "minority_partition_heal", "wan_committee",
        "mainnet_rehearsal",
        "wan_committee_200", "gray_aggregator",
    }
    for name, builder in SCENARIOS.items():
        for quick in (False, True):
            s = builder(quick=quick)
            assert s.name == name
            assert s.invariants.min_blocks >= 1
            assert s.invariants.round_p99_s > 0
            assert s.topology.nodes >= 3
            assert s.window_s > 0
        # quick runs must genuinely be scaled down
        assert (builder(quick=True).window_s
                <= builder(quick=False).window_s)


# -- load-relative phase windows (ISSUE 14 deflake) --------------------------


def _hold_env(phase):
    """Minimal RunEnv stand-in for driving _timeline directly: one
    literal-partition phase, no kills, an empty committee."""
    import types

    return types.SimpleNamespace(
        scenario=types.SimpleNamespace(phases=(phase,)),
        handles=[],
        net=types.SimpleNamespace(partitioned=set()),
        shard_head=lambda shard: 0,
        by_shard=lambda shard: [],
        data={},
    )


def _drive_timeline(env, stop):
    import threading

    from harmony_tpu.chaostest import runner as R

    t = threading.Thread(
        target=R._timeline, args=(env, stop, time.monotonic(), []),
        daemon=True,
    )
    t.start()
    return t


def test_phase_hold_until_outlasts_duration():
    """A phase with hold_until stays armed past duration_s until the
    predicate proves the fault did its job — the view_change_storm
    heal must not race a loaded box's VC ladder."""
    import threading

    from harmony_tpu.chaostest.scenario import Phase

    done = threading.Event()
    phase = Phase(
        "hold", at_s=0.0, duration_s=0.1, partition=("n0",),
        hold_until=lambda env: done.is_set(), hold_max_s=30.0,
    )
    env = _hold_env(phase)
    stop = threading.Event()
    t = _drive_timeline(env, stop)
    try:
        deadline = time.monotonic() + 5.0
        while "n0" not in env.net.partitioned:
            assert time.monotonic() < deadline, "phase never armed"
            time.sleep(0.01)
        time.sleep(0.4)  # well past duration_s
        assert "n0" in env.net.partitioned, (
            "healed on wall clock despite an unsatisfied hold_until"
        )
        done.set()
        deadline = time.monotonic() + 5.0
        while "n0" in env.net.partitioned:
            assert time.monotonic() < deadline, "never healed"
            time.sleep(0.01)
        t.join(5.0)
        assert not t.is_alive()
    finally:
        stop.set()


def test_phase_hold_max_caps_a_never_true_predicate():
    """hold_max_s bounds the hold: a fault whose observable never
    materializes heals anyway (and lets the invariant fail the run)
    instead of wedging the timeline."""
    import threading

    from harmony_tpu.chaostest.scenario import Phase

    phase = Phase(
        "cap", at_s=0.0, duration_s=0.05, partition=("n0",),
        hold_until=lambda env: False, hold_max_s=0.4,
    )
    env = _hold_env(phase)
    stop = threading.Event()
    t = _drive_timeline(env, stop)
    try:
        deadline = time.monotonic() + 5.0
        while "n0" not in env.net.partitioned:
            assert time.monotonic() < deadline, "phase never armed"
            time.sleep(0.01)
        deadline = time.monotonic() + 5.0
        while "n0" in env.net.partitioned:
            assert time.monotonic() < deadline, (
                "hold_max_s did not cap a never-true predicate"
            )
            time.sleep(0.01)
        t.join(5.0)
        assert not t.is_alive()
    finally:
        stop.set()


# -- the view-change quorum-mid-drain regression -----------------------------


def test_view_change_quorum_mid_drain_does_not_crash(monkeypatch):
    """Regression (found by the election scenario): a multi-key next
    leader draining early-buffered VC votes reaches M3 quorum mid-loop;
    adoption clears the collector, and the trailing try_new_view used
    to crash the consensus pump with AttributeError on None."""
    monkeypatch.setenv("HARMONY_KERNEL_TWIN", "1")
    from harmony_tpu import bls as B
    from harmony_tpu.core.blockchain import Blockchain
    from harmony_tpu.core.genesis import Genesis
    from harmony_tpu.core.kv import MemKV
    from harmony_tpu.core.tx_pool import TxPool
    from harmony_tpu.multibls import PrivateKeys
    from harmony_tpu.node.node import Node
    from harmony_tpu.node.registry import Registry
    from harmony_tpu.p2p import InProcessNetwork

    from harmony_tpu.core.genesis import dev_genesis

    keys = [B.PrivateKey.generate(bytes([140 + i])) for i in range(5)]
    committee = [k.pub.bytes for k in keys]
    base, _, _ = dev_genesis(n_keys=5)
    genesis = Genesis(
        config=base.config, shard_id=0, alloc=dict(base.alloc),
        committee=committee,
    )
    net = InProcessNetwork()
    nodes = []
    key_sets = [[keys[0], keys[4]], [keys[1]], [keys[2]], [keys[3]]]
    for i, ks in enumerate(key_sets):
        chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
        pool = TxPool(2, 0, chain.state)
        reg = Registry(blockchain=chain, txpool=pool,
                       host=net.host(f"n{i}"))
        nodes.append(Node(reg, PrivateKeys.from_keys(ks)))

    # next view's leader: view 2 -> committee[2 % 5] ... force the
    # multi-key node to be the collector by picking the view whose
    # slot is one of ITS keys.  view 5 -> committee[0] (node 0), and
    # node 0 also holds committee[4]: quorum 4-of-5 is reachable from
    # 3 early votes + its own 2 keys DURING the drain.
    for n in nodes:
        n._vc = 3  # next start_view_change votes for view 5
    # validators time out first: their votes buffer at node 0
    for n in nodes[1:]:
        n.start_view_change()
    for _ in range(50):
        if not any(n.process_pending() for n in nodes):
            break
    # node 0's own timeout: drain hits quorum mid-loop.  Before the
    # fix this raised AttributeError and killed the pump thread.
    nodes[0]._vc = 3
    nodes[0].start_view_change()
    for _ in range(50):
        if not any(n.process_pending() for n in nodes):
            break
    assert nodes[0].new_views_adopted >= 1
    # every node that saw the NEWVIEW adopted the view (block_num 1)
    adopted = sum(n.new_views_adopted for n in nodes)
    assert adopted >= 3


# -- violation evidence: exactly one dump per violation ----------------------


def test_violation_produces_exactly_one_dump(tmp_path, monkeypatch):
    """A scenario that cannot meet liveness must report the violation
    AND exactly one correlated flight-recorder dump for it."""
    monkeypatch.setenv("HARMONY_TPU_TRACE_DIR", str(tmp_path))
    from harmony_tpu.chaostest import (
        Invariants, Scenario, Topology, Traffic, run,
    )

    scenario = Scenario(
        name="impossible_liveness",
        seed=7,
        topology=Topology(nodes=4, block_time_s=0.2,
                          phase_timeout_s=30.0),
        traffic=Traffic(),
        invariants=Invariants(min_blocks=10_000, round_p99_s=60.0),
        window_s=6.0,
    )
    r = run(scenario)
    assert not r.passed
    assert [v["invariant"] for v in r.violations] == ["liveness"]
    assert len(r.violation_dumps) == 1
    dump = json.load(open(r.violation_dumps[0]))
    assert dump["kind"] == "chaos.impossible_liveness.liveness"
    assert "min_blocks=10000" in dump["info"]["detail"]


# -- THE acceptance scenario -------------------------------------------------


def test_view_change_storm_scenario_passes(tmp_path, monkeypatch):
    """Leader black-holed mid-round under flood: the committee view-
    changes to a live leader, keeps committing with ZERO consensus
    sheds, and the healed ex-leader resyncs — the chaos stack's
    acceptance gate, tier-1 resident so regressions surface before the
    full sweep stage."""
    monkeypatch.setenv("HARMONY_TPU_TRACE_DIR", str(tmp_path))
    from harmony_tpu.chaostest import run, scenarios

    r = run(scenarios.view_change_storm(quick=True))
    assert r.passed, f"violations: {r.violations}"
    assert r.metrics["consensus_sheds"]["value"] == 0
    assert r.metrics["new_views_adopted"]["value"] >= 1
    assert r.metrics["blocks_min"]["value"] >= 4
    assert not r.violation_dumps
