"""Explorer index/HTTP service + Rosetta Data API (reference:
api/service/explorer, rosetta/ — VERDICT r2 missing #8)."""

import http.client
import json

import pytest

from harmony_tpu.core.blockchain import Blockchain
from harmony_tpu.core.genesis import dev_genesis
from harmony_tpu.core.kv import MemKV
from harmony_tpu.core.types import Transaction
from harmony_tpu.core.tx_pool import TxPool
from harmony_tpu.explorer import ExplorerServer
from harmony_tpu.hmy.facade import Harmony
from harmony_tpu.node.worker import Worker
from harmony_tpu.rosetta import RosettaServer

CHAIN_ID = 2


@pytest.fixture(scope="module")
def stack():
    genesis, keys, _ = dev_genesis()
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    pool = TxPool(CHAIN_ID, 0, chain.state)
    worker = Worker(chain, pool)
    to = b"\x0b" * 20
    tx = Transaction(
        nonce=0, gas_price=1, gas_limit=25_000, shard_id=0, to_shard=0,
        to=to, value=4242,
    ).sign(keys[0], CHAIN_ID)
    pool.add(tx)
    block = worker.propose_block(view_id=1)
    chain.insert_chain([block], verify_seals=False)
    pool.drop_applied()
    return chain, keys, to, tx


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    resp = conn.getresponse()
    out = (resp.status, json.loads(resp.read()))
    conn.close()
    return out


def _post(port, path, body):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("POST", path, json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    out = (resp.status, json.loads(resp.read()))
    conn.close()
    return out


def test_explorer_blocks_tx_address(stack):
    chain, keys, to, tx = stack
    ex = ExplorerServer(chain).start()
    try:
        status, height = _get(ex.port, "/height")
        assert status == 200 and height["height"] == 1
        status, blocks = _get(ex.port, "/blocks?from=0&to=1")
        assert [b["number"] for b in blocks] == [0, 1]
        txh = "0x" + tx.hash(CHAIN_ID).hex()
        status, got = _get(ex.port, f"/tx?id={txh}")
        assert got["value"] == 4242 and got["blockNumber"] == 1
        sender_hex = "0x" + keys[0].address().hex()
        status, addr = _get(ex.port, f"/address?id={sender_hex}")
        assert addr["txCount"] == 1
        assert addr["txs"][0]["type"] == "SENT"
        status, recv = _get(ex.port, "/address?id=0x" + to.hex())
        assert recv["balance"] == 4242
        assert recv["txs"][0]["type"] == "RECEIVED"
        status, _ = _get(ex.port, "/tx?id=0x" + "00" * 32)
        assert status == 404
    finally:
        ex.stop()


def test_explorer_pagination_and_bech32():
    """VERDICT r4 weak #7: pageIndex/pageSize paging (newest-first) +
    one1 address form acceptance.  Own chain: the shared fixture's
    height is pinned by the Rosetta tests."""
    from harmony_tpu.accounts.bech32 import address_to_one

    genesis, keys, _ = dev_genesis()
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    to = b"\x0b" * 20
    pool = TxPool(CHAIN_ID, 0, chain.state)
    worker = Worker(chain, pool)
    for i in range(6):
        t = Transaction(
            nonce=i, gas_price=1, gas_limit=25_000, shard_id=0,
            to_shard=0, to=to, value=10 + i,
        ).sign(keys[0], CHAIN_ID)
        pool.add(t)
        block = worker.propose_block(view_id=chain.head_number + 1)
        chain.insert_chain([block], verify_seals=False)
        pool.drop_applied()
    ex = ExplorerServer(chain).start()
    try:
        one = address_to_one(keys[0].address())
        status, page0 = _get(
            ex.port, f"/address?id={one}&pageIndex=0&pageSize=2"
        )
        assert status == 200 and page0["txCount"] == 6
        assert page0["one"] == one
        assert len(page0["txs"]) == 2
        # newest first: the last send (value 14, block 6) leads
        assert page0["txs"][0]["blockNumber"] == 6
        status, page2 = _get(
            ex.port, f"/address?id={one}&pageIndex=2&pageSize=2"
        )
        assert [t["blockNumber"] for t in page2["txs"]] == [2, 1]
        status, err = _get(ex.port, f"/address?id={one}&pageSize=0")
        assert status == 400
    finally:
        ex.stop()


def test_explorer_index_persists_across_restart(stack):
    """The index lives in the chain's KV store: a new server instance
    over the same db resumes at the indexed height with full history
    (reference: the LevelDB-backed explorer storage)."""
    chain, keys, to, tx = stack
    ex1 = ExplorerServer(chain)
    ex1.index.index_through()
    h = ex1.index.height
    assert h >= 1
    ex2 = ExplorerServer(chain)  # fresh instance, same db
    assert ex2.index.height == h  # resumed, not rescanned
    assert ex2.index.address_count(keys[0].address()) >= 1
    loc = ex2.index.tx_location(tx.hash(CHAIN_ID))
    assert loc is not None and loc[0] == 1


def test_rosetta_data_api(stack):
    chain, keys, to, tx = stack
    rs = RosettaServer(Harmony(chain)).start()
    try:
        status, nets = _post(rs.port, "/network/list", {})
        assert nets["network_identifiers"][0]["network"] == "shard-0"
        status, st = _post(rs.port, "/network/status", {})
        assert st["current_block_identifier"]["index"] == 1
        assert st["genesis_block_identifier"]["index"] == 0
        status, opts = _post(rs.port, "/network/options", {})
        assert "NativeTransfer" in opts["allow"]["operation_types"]
        status, blk = _post(rs.port, "/block",
                            {"block_identifier": {"index": 1}})
        ops = blk["block"]["transactions"][0]["operations"]
        assert ops[0]["amount"]["value"] == "-4242"
        assert ops[1]["amount"]["value"] == "4242"
        assert ops[1]["account"]["address"] == "0x" + to.hex()
        status, bal = _post(rs.port, "/account/balance", {
            "account_identifier": {"address": "0x" + to.hex()},
        })
        assert bal["balances"][0]["value"] == "4242"
        status, err = _post(rs.port, "/nope", {})
        assert status == 404
    finally:
        rs.stop()


def test_rosetta_construction_end_to_end(stack):
    """The full Construction flow (reference:
    rosetta/services/construction*.go): derive -> preprocess ->
    metadata -> payloads -> [external ECDSA sign] -> combine -> parse
    -> hash -> submit, landing the tx in the live pool."""
    chain, keys, to, _ = stack
    pool = TxPool(CHAIN_ID, 0, chain.state)
    hmy = Harmony(chain, pool)
    rs = RosettaServer(hmy).start()
    sender = keys[0]
    try:
        # derive: pubkey -> address
        pub_hex = "04" + sender.pub[0].to_bytes(32, "big").hex() + (
            sender.pub[1].to_bytes(32, "big").hex()
        )
        status, got = _post(rs.port, "/construction/derive",
                            {"public_key": {"hex_bytes": pub_hex}})
        assert status == 200
        assert got["account_identifier"]["address"] == (
            "0x" + sender.address().hex()
        )
        # SEC1 compressed (the standard Rosetta wire form) derives the
        # same address
        comp = bytes([2 + (sender.pub[1] & 1)]) + (
            sender.pub[0].to_bytes(32, "big")
        )
        status, got2 = _post(rs.port, "/construction/derive",
                             {"public_key": {"hex_bytes": comp.hex()}})
        assert status == 200 and got2 == got

        ops = [
            {"operation_identifier": {"index": 0},
             "type": "NativeTransfer",
             "account": {"address": "0x" + sender.address().hex()},
             "amount": {"value": "-777",
                        "currency": {"symbol": "ONE", "decimals": 18}}},
            {"operation_identifier": {"index": 1},
             "type": "NativeTransfer",
             "account": {"address": "0x" + to.hex()},
             "amount": {"value": "777",
                        "currency": {"symbol": "ONE", "decimals": 18}}},
        ]
        status, pre = _post(rs.port, "/construction/preprocess",
                            {"operations": ops})
        assert status == 200
        assert pre["required_public_keys"][0]["address"] == (
            "0x" + sender.address().hex()
        )
        status, meta = _post(rs.port, "/construction/metadata",
                             {"options": pre["options"]})
        assert status == 200
        assert meta["metadata"]["nonce"] == 1  # one tx already applied
        status, pay = _post(rs.port, "/construction/payloads",
                            {"operations": ops,
                             "metadata": meta["metadata"]})
        assert status == 200
        payload = pay["payloads"][0]
        assert payload["signature_type"] == "ecdsa_recovery"

        # rosetta-cli style intent check: parse(unsigned) must round-
        # trip BOTH operations, with no signers yet
        status, up = _post(rs.port, "/construction/parse", {
            "transaction": pay["unsigned_transaction"], "signed": False,
        })
        assert status == 200 and up["account_identifier_signers"] == []
        assert sorted(
            int(op["amount"]["value"]) for op in up["operations"]
        ) == [-777, 777]
        assert {op["account"]["address"] for op in up["operations"]} == {
            "0x" + sender.address().hex(), "0x" + to.hex()
        }

        # degenerate combine input is a Rosetta error, not a hang/reset
        status, _ = _post(rs.port, "/construction/combine", {
            "unsigned_transaction": pay["unsigned_transaction"],
            "signatures": [],
        })
        assert status == 500

        # the signer is EXTERNAL to the server: sign the payload bytes
        sig = sender.sign(bytes.fromhex(payload["hex_bytes"]))
        status, comb = _post(rs.port, "/construction/combine", {
            "unsigned_transaction": pay["unsigned_transaction"],
            "signatures": [{"hex_bytes": sig.hex()}],
        })
        assert status == 200

        status, parsed = _post(rs.port, "/construction/parse", {
            "transaction": comb["signed_transaction"], "signed": True,
        })
        assert status == 200
        assert parsed["account_identifier_signers"] == [
            {"address": "0x" + sender.address().hex()}
        ]
        amounts = sorted(
            int(op["amount"]["value"]) for op in parsed["operations"]
        )
        assert amounts == [-777, 777]

        status, hsh = _post(rs.port, "/construction/hash", {
            "signed_transaction": comb["signed_transaction"],
        })
        assert status == 200

        status, sub = _post(rs.port, "/construction/submit", {
            "signed_transaction": comb["signed_transaction"],
        })
        assert status == 200
        assert sub["transaction_identifier"] == (
            hsh["transaction_identifier"]
        )
        assert len(pool) == 1  # landed in the live mempool

        # a corrupted signature recovers to a DIFFERENT address (that's
        # the nature of ecdsa_recovery) — the pool's sender checks must
        # then reject the submit
        bad = bytearray(sig)
        bad[40] ^= 0x01
        status, comb2 = _post(rs.port, "/construction/combine", {
            "unsigned_transaction": pay["unsigned_transaction"],
            "signatures": [{"hex_bytes": bytes(bad).hex()}],
        })
        if status == 200:  # recovery happened to succeed
            status, _ = _post(rs.port, "/construction/submit", {
                "signed_transaction": comb2["signed_transaction"],
            })
        assert status == 500
        assert len(pool) == 1  # nothing new landed
    finally:
        rs.stop()


def test_rosetta_construction_staking_delegate(stack):
    """Staking intents through the construction flow (reference:
    rosetta construction_create.go staking operations): a Delegate
    op becomes a signed StakingTransaction landing in the pool's
    staking lane; parse round-trips the intent."""
    chain, keys, to, _ = stack
    pool = TxPool(CHAIN_ID, 0, chain.state)
    hmy = Harmony(chain, pool)
    rs = RosettaServer(hmy).start()
    delegator = keys[0]
    validator = b"\x1a" * 20
    try:
        ops = [{
            "operation_identifier": {"index": 0},
            "type": "Delegate",
            "account": {"address": "0x" + delegator.address().hex()},
            "amount": {"value": "-100000000000000000000",
                       "currency": {"symbol": "ONE", "decimals": 18}},
            "metadata": {"validatorAddress": "0x" + validator.hex()},
        }]
        status, pre = _post(rs.port, "/construction/preprocess",
                            {"operations": ops})
        assert status == 200 and pre["options"]["kind"] == "delegate"
        status, meta = _post(rs.port, "/construction/metadata",
                             {"options": pre["options"]})
        assert status == 200
        status, pay = _post(rs.port, "/construction/payloads",
                            {"operations": ops,
                             "metadata": meta["metadata"]})
        assert status == 200

        # unsigned parse round-trips the staking intent
        status, up = _post(rs.port, "/construction/parse", {
            "transaction": pay["unsigned_transaction"], "signed": False,
        })
        assert status == 200
        op = up["operations"][0]
        assert op["type"] == "Delegate"
        assert op["metadata"]["validatorAddress"] == "0x" + validator.hex()
        assert int(op["amount"]["value"]) == -(10**20)

        sig = delegator.sign(bytes.fromhex(pay["payloads"][0]["hex_bytes"]))
        status, comb = _post(rs.port, "/construction/combine", {
            "unsigned_transaction": pay["unsigned_transaction"],
            "signatures": [{"hex_bytes": sig.hex()}],
        })
        assert status == 200
        status, parsed = _post(rs.port, "/construction/parse", {
            "transaction": comb["signed_transaction"], "signed": True,
        })
        assert status == 200
        assert parsed["account_identifier_signers"] == [
            {"address": "0x" + delegator.address().hex()}
        ]
        status, hsh = _post(rs.port, "/construction/hash", {
            "signed_transaction": comb["signed_transaction"],
        })
        assert status == 200
        status, sub = _post(rs.port, "/construction/submit", {
            "signed_transaction": comb["signed_transaction"],
        })
        assert status == 200
        assert sub == hsh
        pending = pool.pending(10)
        assert len(pending) == 1 and pending[0][1] is True  # staking lane
        assert pending[0][0].fields["amount"] == 10**20

        # a POSITIVE Delegate amount is a mis-signed intent: rejected
        bad_ops = [dict(ops[0], amount={
            "value": "100", "currency": {"symbol": "ONE", "decimals": 18},
        })]
        status, _ = _post(rs.port, "/construction/preprocess",
                          {"operations": bad_ops})
        assert status == 500

        # a MINED staking tx surfaces in the Data API /block response
        # (reconcilers must see the delegator's debit): store a block
        # carrying it and read it back
        from harmony_tpu.chain.header import Header
        from harmony_tpu.core import rawdb
        from harmony_tpu.core.types import Block

        stx = rawdb.decode_staking_tx(
            bytes.fromhex(comb["signed_transaction"][2:])[1:]
        )
        blk = Block(None, transactions=[],
                    staking_transactions=[stx], execution_order=[1])
        blk.header = Header(shard_id=0, block_num=2, epoch=0, view_id=2,
                            parent_hash=chain.current_header().hash(),
                            timestamp=1000)
        rawdb.write_block(chain.db, blk, CHAIN_ID)
        status, got_blk = _post(rs.port, "/block",
                                {"block_identifier": {"index": 2}})
        assert status == 200
        ops_out = got_blk["block"]["transactions"][-1]["operations"]
        assert ops_out[0]["type"] == "Delegate"
        assert int(ops_out[0]["amount"]["value"]) == -(10**20)
        assert ops_out[0]["account"]["address"] == (
            "0x" + delegator.address().hex()
        )
    finally:
        rs.stop()
