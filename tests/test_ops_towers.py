"""Tower-field tests: batched JAX Fp2/Fp6/Fp12 vs the bigint reference."""

import random

import jax.numpy as jnp
import numpy as np

from harmony_tpu.ops import interop as I
from harmony_tpu.ops import towers as T
from harmony_tpu.ref import fields as F
from harmony_tpu.ref.params import P

rng = random.Random(0x70)


def rfp2():
    return (rng.randrange(P), rng.randrange(P))


def rfp6():
    return (rfp2(), rfp2(), rfp2())


def rfp12():
    return (rfp6(), rfp6())


A2_REF = [rfp2() for _ in range(4)]
B2_REF = [rfp2() for _ in range(4)]
A2 = jnp.asarray(I.batch(I.fp2_to_arr, A2_REF))
B2 = jnp.asarray(I.batch(I.fp2_to_arr, B2_REF))

A12_REF = [rfp12() for _ in range(2)]
B12_REF = [rfp12() for _ in range(2)]
A12 = jnp.asarray(I.batch(I.fp12_to_arr, A12_REF))
B12 = jnp.asarray(I.batch(I.fp12_to_arr, B12_REF))


def test_fp2_ops():
    out = T.fp2_mul(A2, B2)
    for i in range(4):
        assert I.arr_to_fp2(np.array(out[i])) == F.fp2_mul(A2_REF[i], B2_REF[i])
    out = T.fp2_sqr(A2)
    for i in range(4):
        assert I.arr_to_fp2(np.array(out[i])) == F.fp2_sqr(A2_REF[i])
    out = T.fp2_inv(A2)
    for i in range(4):
        assert I.arr_to_fp2(np.array(out[i])) == F.fp2_inv(A2_REF[i])
    out = T.fp2_mul_xi(A2)
    for i in range(4):
        assert I.arr_to_fp2(np.array(out[i])) == F.fp2_mul_xi(A2_REF[i])


def test_fp6_ops():
    a6 = [rfp6() for _ in range(2)]
    b6 = [rfp6() for _ in range(2)]
    a = jnp.asarray(I.batch(I.fp6_to_arr, a6))
    b = jnp.asarray(I.batch(I.fp6_to_arr, b6))
    out = T.fp6_mul(a, b)
    for i in range(2):
        assert I.arr_to_fp6(np.array(out[i])) == F.fp6_mul(a6[i], b6[i])
    out = T.fp6_inv(a)
    for i in range(2):
        assert I.arr_to_fp6(np.array(out[i])) == F.fp6_inv(a6[i])
    out = T.fp6_mul_v(a)
    for i in range(2):
        assert I.arr_to_fp6(np.array(out[i])) == F.fp6_mul_v(a6[i])


def test_fp12_ops():
    out = T.fp12_mul(A12, B12)
    for i in range(2):
        assert I.arr_to_fp12(np.array(out[i])) == F.fp12_mul(
            A12_REF[i], B12_REF[i]
        )
    out = T.fp12_inv(A12)
    for i in range(2):
        assert I.arr_to_fp12(np.array(out[i])) == F.fp12_inv(A12_REF[i])
    out = T.fp12_conj(A12)
    for i in range(2):
        assert I.arr_to_fp12(np.array(out[i])) == F.fp12_conj(A12_REF[i])


def test_frobenius_against_generic_pow():
    for k in (1, 2, 3):
        out = T.fp12_frobenius(A12, k)
        for i in range(2):
            assert I.arr_to_fp12(np.array(out[i])) == F.fp12_pow(
                A12_REF[i], P**k
            ), f"frobenius^{k}"


def test_fp12_pow_small():
    out = T.fp12_pow(A12, [1, 0, 1, 1])
    for i in range(2):
        assert I.arr_to_fp12(np.array(out[i])) == F.fp12_pow(A12_REF[i], 11)
