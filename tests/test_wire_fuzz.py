"""Seed-deterministic structured fuzz for the wire decoders (ISSUE 13):
mutated / truncated / length-inflated inputs must raise TYPED errors
(ValueError family), never crash with an untyped exception, hang, or
allocate unbounded buffers.  Every case derives from random.Random(seed)
so a failure reproduces exactly."""

import json
import random
import socket
import struct
import time

import pytest

from harmony_tpu import bls as B
from harmony_tpu.consensus.messages import (
    FBFTMessage,
    MsgType,
    decode_message,
    encode_message,
    sign_message,
)
from harmony_tpu.multibls import PrivateKeys
from harmony_tpu.sidecar import protocol as SP
from harmony_tpu.staking import slash as SL

SEED = 0xF0221
N_MUTATIONS = 300

# the decode contract: these (all ValueError subclasses included) are
# the ONLY acceptable rejections — anything else is a crash
TYPED = (ValueError, IndexError, KeyError)


def _mutations(rng, base: bytes):
    """Classic structured mutations: byte flips, truncations, random
    splices, and length-field inflation at random offsets."""
    for _ in range(N_MUTATIONS):
        kind = rng.randrange(4)
        buf = bytearray(base)
        if kind == 0 and buf:  # flip a few bytes
            for _ in range(rng.randrange(1, 4)):
                buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
        elif kind == 1:  # truncate
            buf = buf[:rng.randrange(len(buf) + 1)]
        elif kind == 2 and len(buf) >= 4:  # inflate a 4-byte field
            struct.pack_into(
                "<I", buf, rng.randrange(len(buf) - 3),
                rng.choice([0xFFFFFFFF, 2**31, len(buf) * 1000]),
            )
        else:  # random splice
            at = rng.randrange(len(buf) + 1)
            buf[at:at] = rng.randbytes(rng.randrange(1, 32))
        yield bytes(buf)


def _fuzz(decoder, base: bytes, budget_s: float = 20.0):
    rng = random.Random(SEED)
    t0 = time.monotonic()
    for mutant in _mutations(rng, base):
        try:
            decoder(mutant)
        except TYPED:
            pass  # the contract: typed rejection
        # any OTHER exception propagates and fails the test
    took = time.monotonic() - t0
    assert took < budget_s, (
        f"{N_MUTATIONS} mutants took {took:.1f}s — some decode path "
        "is not bounded"
    )


def test_fuzz_consensus_message_decoder():
    keys = PrivateKeys.from_keys(
        [B.PrivateKey.generate(bytes([i])) for i in (1, 2)]
    )
    msg = sign_message(FBFTMessage(
        msg_type=MsgType.PREPARED, view_id=7, block_num=42,
        block_hash=bytes(range(32)), sender_pubkeys=[
            k.pub.bytes for k in keys
        ],
        payload=b"\x05" * 97, block=b"\x06" * 200,
        trace_ctx=b"\x07" * 26,
    ), keys)
    _fuzz(decode_message, encode_message(msg))


def test_consensus_message_length_inflation_rejected_fast():
    """The worst case explicitly: a tiny frame claiming 2^31-sized
    fields must be rejected in microseconds, before any allocation."""
    base = bytearray(encode_message(FBFTMessage(
        msg_type=MsgType.COMMIT, view_id=1, block_num=1,
        block_hash=bytes(32), sender_pubkeys=[], payload=b"x" * 8,
    )))
    # payload length prefix sits after type+view+block+hash+keycount
    struct.pack_into("<I", base, 1 + 8 + 8 + 32 + 4, 2**31)
    t0 = time.monotonic()
    with pytest.raises(ValueError):
        decode_message(bytes(base))
    assert time.monotonic() - t0 < 0.1


def test_fuzz_sidecar_parsers():
    committee = SP.build_set_committee(3, 0, [b"\x01" * 48] * 4)
    agg = SP.build_agg_verify(3, 0, b"payload", b"\x0f", b"\x02" * 96)
    batch = SP.build_verify_batch(
        [(b"\x01" * 48, b"p%d" % i, b"\x02" * 96) for i in range(3)]
    )
    _fuzz(SP.parse_set_committee, committee)
    _fuzz(SP.parse_agg_verify, agg)
    _fuzz(SP.parse_verify_batch, batch)


def test_sidecar_batch_count_inflation_rejected_before_allocation():
    buf = bytearray(SP.build_verify_batch(
        [(b"\x01" * 48, b"p", b"\x02" * 96)]
    ))
    struct.pack_into("<I", buf, 0, 0xFFFFFFF0)
    t0 = time.monotonic()
    with pytest.raises(ValueError, match="implausible"):
        SP.parse_verify_batch(bytes(buf))
    assert time.monotonic() - t0 < 0.1


def test_fuzz_slash_record_decoder():
    key = B.PrivateKey.generate(b"\x55")
    payload = b"\x01" * 96
    vote = SL.Vote([key.pub.bytes], bytes([1]) * 32, payload)
    vote2 = SL.Vote([key.pub.bytes], bytes([2]) * 32, payload)
    rec = SL.Record(
        evidence=SL.Evidence(
            moment=SL.Moment(1, 0, 9, 9), first_vote=vote,
            second_vote=vote2, offender=b"\x0f" * 20,
        ),
        reporter=b"\x1e" * 20,
    )
    _fuzz(SL.decode_record, SL.encode_record(rec))
    _fuzz(SL.decode_records, SL.encode_records([rec]))


def test_fuzz_block_decoder():
    from harmony_tpu.chain.header import Header
    from harmony_tpu.core import rawdb
    from harmony_tpu.core.types import Block, Transaction

    tx = Transaction(nonce=0, gas_price=1, gas_limit=21000, shard_id=0,
                     to_shard=0, to=b"\x2d" * 20, value=5,
                     sig=b"\x01" * 65)
    block = Block(Header(shard_id=0, block_num=3), [tx], [], [], [0])
    _fuzz(rawdb.decode_block, rawdb.encode_block(block, 2))


def test_fuzz_viewchange_decoders():
    from harmony_tpu.consensus import view_change as VC

    vc = VC.ViewChangeMsg(
        view_id=9, block_num=4, sender_pubkeys=[b"\x01" * 48],
        m3_sig=b"\x02" * 96, m2_sig=b"\x03" * 96, m1_sig=b"",
        m1_payload=b"\x04" * 40,
    )
    _fuzz(VC.decode_viewchange, VC.encode_viewchange(vc))


def test_sync_server_survives_garbage_frames():
    """Raw garbage at a SyncServer: oversized length prefixes and junk
    frames drop the CONNECTION, never the server — an honest client
    still gets served afterwards."""
    from harmony_tpu.core.blockchain import Blockchain
    from harmony_tpu.core.genesis import dev_genesis
    from harmony_tpu.core.kv import MemKV
    from harmony_tpu.p2p.stream import SyncClient, SyncServer

    genesis, _, _ = dev_genesis(n_keys=4)
    chain = Blockchain(MemKV(), genesis)
    server = SyncServer(chain)
    try:
        rng = random.Random(SEED)
        for _ in range(20):
            s = socket.create_connection(("127.0.0.1", server.port),
                                         timeout=5)
            try:
                kind = rng.randrange(3)
                if kind == 0:  # absurd frame length
                    s.sendall(struct.pack("<IBQ", 0x7FFFFFFF, 1, 1))
                elif kind == 1:  # random junk
                    s.sendall(rng.randbytes(rng.randrange(1, 64)))
                else:  # well-framed junk body
                    body = rng.randbytes(rng.randrange(1, 32))
                    s.sendall(
                        struct.pack("<IBQ", len(body), 1, 7) + body
                    )
                s.settimeout(2.0)
                try:
                    s.recv(64)  # server may answer junk or just close
                except OSError:
                    pass
            finally:
                s.close()
        # the server is still alive for honest clients
        client = SyncClient(server.port, timeout=5.0)
        head, head_hash = client.get_head()
        assert head == 0 and len(head_hash) == 32
        client.close()
    finally:
        server.close()


def test_sync_client_rejects_forged_response_counts():
    """A malicious sync peer forging a huge element count in a
    response body must get a typed rejection, not a 4-billion-iteration
    decode loop."""
    from harmony_tpu.p2p import stream as ST

    forged = (0xFFFFFFFE).to_bytes(4, "little") + b"\x00" * 16
    r = ST._Reader(forged)
    with pytest.raises(ValueError, match="implausible"):
        ST._checked_count(r)


# -- ISSUE 16 (GL13 burn-down): decoders the taint pass newly flagged --------
#
# GL13 flagged the typed-tx access-list tail of rawdb.decode_tx,
# Receipt.decode's log/topic counts, and the read_receipts /
# read_outgoing_cx batch counts as unchecked wire/disk counts; the fix
# routed each through checked_count.  These mutants pin the same code
# paths dynamically, so a regression trips both the static and the
# fuzz tier.


class _MemDB(dict):
    def put(self, k, v):
        self[k] = v


def _typed_tx(access_list):
    from harmony_tpu.core.types import Transaction

    return Transaction(
        nonce=0, gas_price=1, gas_limit=21000, shard_id=0, to_shard=0,
        to=b"\x2d" * 20, value=5, sig=b"", tx_type=1,
        access_list=access_list,
    )


def test_fuzz_typed_tx_decoder():
    from harmony_tpu.core import rawdb

    tx = _typed_tx([(b"\xaa" * 20, [b"\x01" * 32, b"\x02" * 32])])
    _fuzz(rawdb.decode_tx, rawdb.encode_tx(tx, 2))


def test_fuzz_receipt_decoder():
    from harmony_tpu.core.types import Reader, Receipt

    rcpt = Receipt(
        tx_hash=b"\x11" * 32, status=1, gas_used=21000,
        cumulative_gas=21000,
        logs=[(b"\xaa" * 20, [b"\x01" * 32], b"payload")],
    )
    _fuzz(lambda blob: Receipt.decode(Reader(blob)), rcpt.encode())


def test_tx_access_list_count_inflation_rejected_fast():
    from harmony_tpu.core import rawdb

    # outer access-list count, then inner slots count: each is the
    # last field of the signing section, trailed only by the empty
    # sig's 4-byte length prefix
    for tx in (_typed_tx([]), _typed_tx([(b"\xaa" * 20, [])])):
        buf = bytearray(rawdb.encode_tx(tx, 2))
        struct.pack_into("<H", buf, len(buf) - 6, 0xFFFF)
        t0 = time.monotonic()
        with pytest.raises(ValueError, match="implausible"):
            rawdb.decode_tx(bytes(buf))
        assert time.monotonic() - t0 < 0.1


def test_receipt_log_and_topic_count_inflation_rejected_fast():
    from harmony_tpu.core.types import Reader, Receipt

    no_logs = Receipt(tx_hash=b"\x11" * 32, status=1, gas_used=1,
                      cumulative_gas=1)
    buf = bytearray(no_logs.encode())
    struct.pack_into("<I", buf, len(buf) - 4, 0xFFFFFFF0)  # log count
    with pytest.raises(ValueError, match="implausible"):
        Receipt.decode(Reader(bytes(buf)))

    one_log = Receipt(tx_hash=b"\x11" * 32, status=1, gas_used=1,
                      cumulative_gas=1,
                      logs=[(b"\xaa" * 20, [], b"")])
    buf = bytearray(one_log.encode())
    # topic count rides before the empty data's 4-byte length prefix
    struct.pack_into("<H", buf, len(buf) - 6, 0xFFFF)
    t0 = time.monotonic()
    with pytest.raises(ValueError, match="implausible"):
        Receipt.decode(Reader(bytes(buf)))
    assert time.monotonic() - t0 < 0.1


# -- ISSUE 18: the snapshot-serving codec (late-join bootstrap path) ---------
#
# The meta frame is the root of the download budget (a hostile peer's
# forged n_pages/state_len must die before any allocation); page frames
# carry raw pair bytes whose count is bounded by what the peer actually
# paid to send; paginate_state walks operator/peer state blobs with
# length arithmetic only.


def _snapshot_state_blob(n_accounts: int = 20) -> bytes:
    from harmony_tpu.core.state import Account, StateDB

    return StateDB({
        bytes([i]) * 20: Account(balance=10**18 + i, nonce=i)
        for i in range(n_accounts)
    }).serialize()


def test_fuzz_snapshot_meta_decoder():
    from harmony_tpu.p2p import stream as ST

    base = (
        (42).to_bytes(8, "little")          # block num
        + (3).to_bytes(4, "little")         # n_pages
        + (4096).to_bytes(8, "little")      # state_len
        + (80).to_bytes(4, "little") + b"\x07" * 80   # header blob
        + (108).to_bytes(4, "little") + b"\x08" * 108  # commit proof
    )
    assert ST.decode_snapshot_meta(base) is not None
    _fuzz(ST.decode_snapshot_meta, base)


def test_fuzz_snapshot_page_decoder():
    from harmony_tpu.p2p import stream as ST

    blob = _snapshot_state_blob()
    base = (20).to_bytes(4, "little") + blob[4:]
    assert ST.decode_snapshot_page(base)[0] == 20

    def decode(buf: bytes):
        try:
            ST.decode_snapshot_page(buf)
        except ConnectionError:
            pass  # empty body = the typed not-serving signal

    _fuzz(decode, base)


def test_fuzz_paginate_state():
    from harmony_tpu.core.snapshot import SnapshotError, paginate_state

    blob = _snapshot_state_blob()
    pages = paginate_state(blob, max_accounts=4)
    assert sum(c for _, _, c in pages) == 20
    assert issubclass(SnapshotError, ValueError)
    _fuzz(lambda b: paginate_state(b, max_accounts=4), blob)


def test_snapshot_meta_count_inflation_rejected_fast():
    """A peer forging a 4-billion page count (or a 2^60 state size)
    must get a typed rejection in microseconds — before the downloader
    sizes ANY structure against it."""
    from harmony_tpu.p2p import stream as ST

    base = bytearray(
        (42).to_bytes(8, "little") + (3).to_bytes(4, "little")
        + (4096).to_bytes(8, "little")
        + (4).to_bytes(4, "little") + b"\x07" * 4
        + (0).to_bytes(4, "little")
    )
    struct.pack_into("<I", base, 8, 0xFFFFFFF0)  # n_pages
    t0 = time.monotonic()
    with pytest.raises(ValueError, match="implausible"):
        ST.decode_snapshot_meta(bytes(base))
    assert time.monotonic() - t0 < 0.1

    base = bytearray(base)
    struct.pack_into("<I", base, 8, 3)           # restore n_pages
    struct.pack_into("<Q", base, 12, 1 << 60)    # state_len
    with pytest.raises(ValueError, match="implausible"):
        ST.decode_snapshot_meta(bytes(base))


def test_snapshot_page_count_inflation_rejected_fast():
    from harmony_tpu.p2p import stream as ST

    base = bytearray((2).to_bytes(4, "little") + b"\x01" * 64)
    struct.pack_into("<I", base, 0, 0xFFFFFFF0)
    t0 = time.monotonic()
    with pytest.raises(ValueError, match="implausible"):
        ST.decode_snapshot_page(bytes(base))
    assert time.monotonic() - t0 < 0.1


def test_paginate_state_count_inflation_rejected_fast():
    """A corrupted state blob forging the leading account count walks
    ZERO accounts before the typed rejection (the walk is length
    arithmetic, no allocation)."""
    from harmony_tpu.core.snapshot import SnapshotError, paginate_state

    blob = bytearray(_snapshot_state_blob())
    struct.pack_into("<I", blob, 0, 0xFFFFFFF0)
    t0 = time.monotonic()
    with pytest.raises(SnapshotError, match="implausible"):
        paginate_state(bytes(blob))
    assert time.monotonic() - t0 < 0.1


# -- ISSUE 19: trace context + span-sink reader (forensics inputs) -----------
#
# Two taint surfaces the forensics arc adds: the 26-byte trace_ctx a
# consensus message carries (transport metadata a hostile peer fully
# controls), and the JSONL sink files round_forensics.py merges (they
# travel from other machines and are truncated by the very crash under
# investigation).  Neither may crash the node or pollute the span
# store.


def test_fuzz_trace_ctx_never_crashes_or_pollutes_store():
    from harmony_tpu import trace

    trace.reset()
    trace.configure(enabled=True)
    try:
        with trace.span("legit", component="consensus") as sp:
            good = trace.traceparent()
        assert trace.parse_traceparent(good) == (sp.trace_id, sp.span_id)
        before = len(trace.spans())
        rng = random.Random(SEED)
        well_formed = 0
        for mutant in _mutations(rng, good):
            # parse is total: bytes in, (ids | None) out, NEVER a raise
            parsed = trace.parse_traceparent(mutant)
            if parsed is None:
                # malformed context: resume is the shared no-op and
                # plants NOTHING in the store
                n0 = len(trace.spans())
                with trace.resume(mutant, "consensus.prepare"):
                    pass
                assert len(trace.spans()) == n0
            else:
                # a flipped-but-well-formed context is indistinguishable
                # from a legit remote trace: it may resume, but only
                # with structurally valid hex ids
                tid, sid = parsed
                int(tid, 16), int(sid, 16)
                assert len(tid) == 32 and len(sid) == 16
                well_formed += 1
                with trace.resume(mutant, "consensus.prepare"):
                    pass
        # the store grew by exactly the well-formed resumes — garbled
        # contexts contributed zero entries
        assert len(trace.spans()) == before + well_formed
    finally:
        trace.reset()


def test_fuzz_consensus_trace_ctx_through_the_codec():
    """The full path a hostile peer reaches: mutated trace_ctx bytes
    ride a VALID message through decode, then the receiver resumes on
    whatever arrived.  Typed rejection or clean resume — no third
    outcome, and the store stays unpolluted."""
    from harmony_tpu import trace

    keys = PrivateKeys.from_keys([B.PrivateKey.generate(b"\x31")])
    trace.reset()
    trace.configure(enabled=True)
    try:
        rng = random.Random(SEED)
        t0 = time.monotonic()
        for junk in (b"", b"\x00", rng.randbytes(25), rng.randbytes(26),
                     rng.randbytes(27), b"\xff" * 26, b"\x00" * 26,
                     rng.randbytes(200)):
            msg = sign_message(FBFTMessage(
                msg_type=MsgType.PREPARE, view_id=1, block_num=2,
                block_hash=bytes(32),
                sender_pubkeys=[keys[0].pub.bytes],
                payload=b"\x05" * 97, trace_ctx=junk,
            ), keys)
            wired = decode_message(encode_message(msg))
            assert wired.trace_ctx == junk  # transport metadata survives
            with trace.resume(wired.trace_ctx, "consensus.prepare"):
                pass
        # resumes on junk recorded nothing; resumes on a valid-length
        # random context recorded AT MOST orphan spans with well-formed
        # ids — never an exception, never a malformed store entry
        for s in trace.spans():
            assert len(s.trace_id) == 32 and len(s.span_id) == 16
        assert time.monotonic() - t0 < 20.0
    finally:
        trace.reset()


def test_fuzz_span_sink_reader(tmp_path):
    """read_spans over mutated sink files: mutants of a valid JSONL
    file (flips, truncations, splices, inflations) must never raise
    and never emit a record missing the span schema — the reader
    budget-checks each line before json.loads allocates on it."""
    from harmony_tpu.obs import read_spans

    base_records = [
        {"trace_id": "ab" * 16, "span_id": f"{i:02x}" * 8,
         "name": "consensus.round", "ts": 100.0 + i, "dur_s": 0.5,
         "pid": 1, "tid": 2, "attrs": {"node": f"node{i}", "block": i}}
        for i in range(4)
    ]
    base = ("\n".join(
        json.dumps(r) for r in base_records
    ) + "\n").encode()
    p = tmp_path / "spans_fuzz.jsonl"
    rng = random.Random(SEED)
    t0 = time.monotonic()
    for mutant in _mutations(rng, base):
        p.write_bytes(mutant)
        for rec in read_spans(str(p)):  # must not raise
            # schema holds on every surviving record
            assert isinstance(rec["trace_id"], str)
            assert isinstance(rec["span_id"], str)
            assert isinstance(rec["name"], str)
            assert isinstance(rec["ts"], (int, float))
    took = time.monotonic() - t0
    assert took < 20.0, f"sink-reader fuzz took {took:.1f}s"


def test_span_sink_reader_oversize_line_budget(tmp_path):
    """A multi-megabyte single line costs bounded chunk reads, never a
    whole-line buffer: the 64 KiB record budget is enforced BEFORE
    allocation, and parsing stays fast."""
    from harmony_tpu.obs import read_spans

    p = tmp_path / "spans_big.jsonl"
    good = json.dumps(
        {"trace_id": "cd" * 16, "span_id": "ef" * 8, "name": "x",
         "ts": 1.0, "dur_s": 0.1, "pid": 1, "tid": 1, "attrs": {}}
    )
    with open(p, "w") as f:
        f.write('{"pad": "' + "y" * (8 * 1024 * 1024) + '"}\n')
        f.write(good + "\n")
    t0 = time.monotonic()
    out = read_spans(str(p))
    assert time.monotonic() - t0 < 2.0
    assert len(out) == 1 and out[0]["span_id"] == "ef" * 8


# -- ISSUE 20: the aggregation-overlay wire message --------------------------
#
# AggContribution is NODE-category gossip a hostile peer fully
# controls, decoded by every slot-topic owner before any pairing work:
# the decoder must reject flips/truncations/bitmap-length inflation
# with typed errors, never allocate against a forged length, and hold
# the AGG_BITMAP_MAX budget (GL13 discipline).


def _agg_contribution_base() -> bytes:
    from harmony_tpu.consensus.messages import (
        AggContribution, encode_aggregation,
    )

    return encode_aggregation(AggContribution(
        phase=1, view_id=7, block_num=42, block_hash=bytes(range(32)),
        level=3, bitmap=b"\x0f" * 25, sig=b"\x02" * 96, sender_slot=5,
    ))


def test_fuzz_aggregation_decoder():
    from harmony_tpu.consensus.messages import decode_aggregation

    _fuzz(decode_aggregation, _agg_contribution_base())


def test_aggregation_bitmap_inflation_rejected_fast():
    """A contribution claiming a 64 KiB bitmap (or one past
    AGG_BITMAP_MAX) dies on the length check before the decoder sizes
    anything against it."""
    from harmony_tpu.consensus.messages import (
        AGG_BITMAP_MAX, decode_aggregation,
    )

    base = bytearray(_agg_contribution_base())
    # bitmap_len u16 rides after [phase u8][view u64][block u64]
    # [hash 32][level u8]
    off = 1 + 8 + 8 + 32 + 1
    for forged in (0xFFFF, AGG_BITMAP_MAX + 1):
        buf = bytearray(base)
        struct.pack_into("<H", buf, off, forged)
        t0 = time.monotonic()
        with pytest.raises(ValueError):
            decode_aggregation(bytes(buf))
        assert time.monotonic() - t0 < 0.1


def test_aggregation_truncation_and_trailer_rejected():
    """Every truncation of a valid frame — and any frame with trailing
    bytes past the declared bitmap — is a typed rejection: the decoder
    demands the exact length it computed."""
    from harmony_tpu.consensus.messages import decode_aggregation

    base = _agg_contribution_base()
    for cut in range(len(base)):
        with pytest.raises(TYPED):
            decode_aggregation(base[:cut])
    with pytest.raises(TYPED):
        decode_aggregation(base + b"\x00")
    for bad_phase in (0, 3, 255):
        with pytest.raises(TYPED):
            decode_aggregation(bytes([bad_phase]) + base[1:])


def test_stored_batch_count_inflation_rejected_fast():
    """A corrupted (or crash-torn) store blob forging the leading
    batch count must raise, not spin garbage-object loops."""
    from harmony_tpu.core import rawdb
    from harmony_tpu.core.types import CXReceipt, Receipt

    db = _MemDB()
    rawdb.write_receipts(db, 7, [Receipt(
        tx_hash=b"\x11" * 32, status=1, gas_used=1, cumulative_gas=1,
    )])
    rawdb.write_outgoing_cx(db, 1, 7, [CXReceipt(
        tx_hash=b"\x22" * 32, sender=b"\x01" * 20, to=b"\x02" * 20,
        amount=9, from_shard=0, to_shard=1,
    )])
    for key in list(db):
        buf = bytearray(db[key])
        if len(buf) >= 4:
            struct.pack_into("<I", buf, 0, 0xFFFFFFF0)
        db.put(key, bytes(buf))
    t0 = time.monotonic()
    with pytest.raises(ValueError, match="implausible"):
        rawdb.read_receipts(db, 7)
    with pytest.raises(ValueError, match="implausible"):
        rawdb.read_outgoing_cx(db, 1, 7)
    assert time.monotonic() - t0 < 0.1
