"""Ingress envelope + pre-validation filter tests."""

import pytest

from harmony_tpu.consensus.messages import FBFTMessage, MsgType
from harmony_tpu.node.ingress import (
    IngressContext,
    MessageCategory,
    pack_envelope,
    parse_envelope,
    validate_consensus_message,
)

KEYS = [bytes([i + 1]) * 48 for i in range(8)]


def _ctx(**kw):
    base = dict(
        shard_id=2,
        current_view_id=100,
        committee_keys=set(KEYS),
        is_leader=True,
    )
    base.update(kw)
    return IngressContext(**base)


def _msg(**kw):
    base = dict(
        msg_type=MsgType.PREPARE,
        view_id=100,
        block_num=7,
        block_hash=bytes(32),
        sender_pubkeys=[KEYS[0]],
        payload=bytes(96),
    )
    base.update(kw)
    return FBFTMessage(**base)


def test_envelope_roundtrip():
    env = pack_envelope(MessageCategory.CONSENSUS, 3, b"payload")
    assert parse_envelope(env) == (MessageCategory.CONSENSUS, 3, b"payload")
    with pytest.raises(ValueError):
        parse_envelope(b"\x00")


def test_shard_and_view_window():
    assert validate_consensus_message(_msg(), _ctx(), shard_id=2).accepted
    assert not validate_consensus_message(_msg(), _ctx(), shard_id=3).accepted
    # viewID + 5 < current -> drop; boundary passes
    old = _msg(view_id=94)
    assert not validate_consensus_message(old, _ctx(), 2).accepted
    edge = _msg(view_id=95)
    assert validate_consensus_message(edge, _ctx(), 2).accepted


def test_role_filtering():
    vote = _msg()  # PREPARE is leader-bound
    assert not validate_consensus_message(
        vote, _ctx(is_leader=False), 2
    ).accepted
    proof = _msg(
        msg_type=MsgType.PREPARED, payload=bytes(96 + 1)
    )  # 8 keys -> 1 bitmap byte
    assert not validate_consensus_message(proof, _ctx(is_leader=True), 2).accepted
    assert validate_consensus_message(
        proof, _ctx(is_leader=False), 2
    ).accepted


def test_sender_and_bitmap_checks():
    stranger = _msg(sender_pubkeys=[bytes(48)])
    assert not validate_consensus_message(stranger, _ctx(), 2).accepted
    short_key = _msg(sender_pubkeys=[b"short"])
    assert not validate_consensus_message(short_key, _ctx(), 2).accepted
    empty = _msg(sender_pubkeys=[])
    assert not validate_consensus_message(empty, _ctx(), 2).accepted
    bad_bitmap = _msg(
        msg_type=MsgType.PREPARED,
        payload=bytes(96 + 2),  # expected 1 byte for 8 keys
    )
    assert not validate_consensus_message(
        bad_bitmap, _ctx(is_leader=False), 2
    ).accepted


def test_viewchange_gating():
    # a FUTURE view's VC traffic is admissible even before this node's
    # own timeout (peers' clocks lead ours — the node buffers it);
    # stale views are dropped unless already in view change
    future = _msg(msg_type=MsgType.VIEWCHANGE, view_id=101)
    assert validate_consensus_message(future, _ctx(), 2).accepted
    stale = _msg(msg_type=MsgType.VIEWCHANGE, view_id=100)
    assert not validate_consensus_message(stale, _ctx(), 2).accepted
    assert validate_consensus_message(
        stale, _ctx(in_view_change=True, is_leader=False), 2
    ).accepted
