"""State pruning + snapshot export/import (core/snapshot.py — the
reference's core/state/snapshot + blockchain_pruner roles)."""

import pytest

from harmony_tpu.core import rawdb
from harmony_tpu.core import snapshot as SN
from harmony_tpu.core.blockchain import Blockchain, ChainError
from harmony_tpu.core.genesis import dev_genesis
from harmony_tpu.core.kv import MemKV
from harmony_tpu.core.tx_pool import TxPool
from harmony_tpu.core.types import Transaction
from harmony_tpu.node.worker import Worker

CHAIN_ID = 2

_GENESIS = dev_genesis()


def _grow(chain, keys, n, start_nonce=0):
    pool = TxPool(CHAIN_ID, 0, chain.state)
    worker = Worker(chain, pool)
    for i in range(n):
        tx = Transaction(
            nonce=start_nonce + i, gas_price=1, gas_limit=25_000,
            shard_id=0, to_shard=0, to=b"\x07" * 20, value=50 + i,
        ).sign(keys[0], CHAIN_ID)
        pool.add(tx)
        block = worker.propose_block(view_id=chain.head_number + 1)
        chain.insert_chain([block], verify_seals=False)
        pool.drop_applied()


def _fresh_chain(db=None, **kw):
    genesis, keys, _ = _GENESIS
    return Blockchain(db or MemKV(), genesis, blocks_per_epoch=16,
                      **kw), keys


def test_bulk_prune_drops_old_states_keeps_window():
    chain, keys = _fresh_chain()
    _grow(chain, keys, 8)
    assert SN.prune_states(chain, retain=3) > 0
    # window intact: head-2..head load fine
    for num in range(6, 9):
        assert chain.state_at(num) is not None
    # pruned history raises the clear chain error
    with pytest.raises(ChainError, match="missing state"):
        chain.state_at(2)
    # headers/bodies/receipts are NOT pruned: the header chain is whole
    for num in range(0, 9):
        assert chain.header_by_number(num) is not None
    # genesis state is never pruned
    assert chain.state_at(0) is not None


def test_incremental_retention_on_insert():
    chain, keys = _fresh_chain(state_retention=2)
    _grow(chain, keys, 6)
    assert chain.state_at(6) is not None
    assert chain.state_at(5) is not None
    with pytest.raises(ChainError, match="missing state"):
        chain.state_at(3)


def test_shared_root_never_lost(tmp_path):
    """Empty blocks share a state root only if NOTHING changes; with
    rewards off in the dev chain an empty proposal still bumps nothing
    — simulate the shared-root case directly."""
    chain, keys = _fresh_chain()
    _grow(chain, keys, 2)
    h1 = chain.header_by_number(1)
    h2 = chain.header_by_number(2)
    if h1.root != h2.root:
        # roots differ on this chain shape: deletion of 1 must not
        # touch 2
        assert SN.prune_state_at(chain, 1)
        assert chain.state_at(2) is not None
    else:
        # shared: pruning 1 defers (state 2 would die with it)
        assert not SN.prune_state_at(chain, 1)
        assert chain.state_at(2) is not None


def test_snapshot_roundtrip_restores_pruned_node(tmp_path):
    chain, keys = _fresh_chain()
    _grow(chain, keys, 5)
    path = str(tmp_path / "head.snap")
    assert SN.export_snapshot(chain, path) == 5

    # prune EVERYTHING but head, then kill the head state too (the
    # worst restart: no usable state at all below head)
    SN.prune_states(chain, retain=1)
    head_root = chain.current_header().root
    rawdb.delete_state(chain.db, head_root)
    db = chain.db

    # restart on the same db fails to load head state...
    with pytest.raises(ChainError, match="missing state"):
        _fresh_chain(db=db)

    # ...until the snapshot is imported — via a maintenance-shaped
    # minimal object (the damaged store cannot construct a Blockchain)
    import threading

    class _M:
        pass

    m = _M()
    m.db = db
    m.config = chain.config
    m._insert_lock = threading.RLock()
    m.head_number = 5
    m._committee_cache = {}
    num = SN.import_snapshot(m, path)
    assert num == 5
    # now a real restart works and the chain extends
    chain3, keys3 = _fresh_chain(db=db)
    assert chain3.head_number == 5
    _grow(chain3, keys3, 1, start_nonce=5)
    assert chain3.head_number == 6


def test_snapshot_import_rejects_forged_accounts(tmp_path):
    chain, keys = _fresh_chain()
    _grow(chain, keys, 3)
    path = str(tmp_path / "head.snap")
    SN.export_snapshot(chain, path)
    # tamper with the account payload
    raw = bytearray(open(path, "rb").read())
    raw[-3] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    with pytest.raises(SN.SnapshotError):
        SN.import_snapshot(chain, path)


def test_snapshot_import_fresh_node_requires_trust(tmp_path):
    chain, keys = _fresh_chain()
    _grow(chain, keys, 3)
    path = str(tmp_path / "head.snap")
    SN.export_snapshot(chain, path)

    fresh, _ = _fresh_chain()
    with pytest.raises(SN.SnapshotError, match="trust"):
        SN.import_snapshot(fresh, path)
    num = SN.import_snapshot(fresh, path, trust=True)
    assert num == 3 and fresh.head_number == 3
    assert fresh.state().root() == chain.state().root()


# -- ISSUE 18: the serve -> late-join import path at mainnet-ish size --------


def test_snapshot_serve_import_roundtrip_10k():
    """Export -> serve -> import at 10^4 accounts: the late joiner's
    snapshot bootstrap lands on the exact sealed state, tail replay
    re-derives cross-shard receipts, and the genesis build time guards
    the de-quadratic'd allocation/root paths."""
    import time

    from harmony_tpu.core import rawdb as RD
    from harmony_tpu.node.cross_shard import export_receipts
    from harmony_tpu.p2p.stream import SyncClient, SyncServer
    from harmony_tpu.sync.staged import Downloader

    t0 = time.monotonic()
    genesis, keys, _ = dev_genesis(n_accounts=10_000, flat_root=True)
    build_s = time.monotonic() - t0
    # regression guard: the pre-PR-18 O(N^2) root/alloc paths took
    # minutes here; the linear paths take ~2s on a loaded box
    assert build_s < 15.0, f"dev_genesis(10k) took {build_s:.1f}s"

    serving = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    _grow(serving, keys, 3)

    srv = SyncServer(serving)
    try:
        joiner = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
        dl = Downloader(joiner, [SyncClient(srv.port)], batch=2,
                        verify_seals=False, snapshot_threshold=2)
        dl.sync_once()
        assert dl.snapshot_bootstraps == 1
        assert dl.last_snapshot_bootstrap_s is not None
        assert joiner.head_number == 3
        assert (joiner.current_header().hash()
                == serving.current_header().hash())
        assert joiner.state().root() == serving.state().root()

        # the tail above the snapshot: a cross-shard tx whose receipts
        # the joiner must re-derive during replay
        pool = TxPool(CHAIN_ID, 0, serving.state)
        worker = Worker(serving, pool)
        pool.add(Transaction(
            nonce=3, gas_price=1, gas_limit=25_000, shard_id=0,
            to_shard=1, to=b"\x0c" * 20, value=777,
        ).sign(keys[0], CHAIN_ID))
        block = worker.propose_block(view_id=4)
        serving.insert_chain([block], verify_seals=False)
        dl.sync_once()
        assert joiner.head_number == 4
        assert joiner.state().root() == serving.state().root()
        want = RD.read_receipts(serving.db, 4)
        assert want  # the cx tx produced a receipt
        assert RD.read_receipts(joiner.db, 4) == want
        assert (export_receipts(joiner, 4, shard_count=2)
                == export_receipts(serving, 4, shard_count=2))
    finally:
        srv.close()


def test_snapshot_import_preserves_cx_marks(tmp_path):
    """An import on a store with history must not clobber its
    cross-shard spent marks or receipts — the destination shard's
    double-spend ledger survives a snapshot restore."""
    from harmony_tpu.core import rawdb as RD
    from harmony_tpu.core.genesis import Genesis
    from harmony_tpu.node.cross_shard import export_receipts

    g0, keys, _ = _GENESIS
    g1 = Genesis(config=g0.config, shard_id=1, alloc=dict(g0.alloc),
                 committee=list(g0.committee))
    c0 = Blockchain(MemKV(), g0, blocks_per_epoch=16)
    c1 = Blockchain(MemKV(), g1, blocks_per_epoch=16)

    pool = TxPool(CHAIN_ID, 0, c0.state)
    pool.add(Transaction(
        nonce=0, gas_price=1, gas_limit=25_000, shard_id=0,
        to_shard=1, to=b"\x0c" * 20, value=555,
    ).sign(keys[0], CHAIN_ID))
    b0 = Worker(c0, pool).propose_block(view_id=1)
    assert c0.insert_chain([b0], verify_seals=False) == 1
    proofs = export_receipts(c0, 1, shard_count=2)
    b1 = Worker(c1, None).propose_block(
        view_id=1, incoming_receipts=[proofs[1]]
    )
    assert c1.insert_chain([b1], verify_seals=False) == 1
    assert RD.is_cx_spent(c1.db, 0, 1)

    path = str(tmp_path / "s1.snap")
    assert SN.export_snapshot(c1, path) == 1
    # damage: head state pruned away (the restore-after-prune shape)
    rawdb.delete_state(c1.db, c1.current_header().root)
    assert SN.import_snapshot(c1, path) == 1
    assert c1.state().balance(b"\x0c" * 20) == 555
    # the spent marks were never part of the batch: intact
    assert RD.is_cx_spent(c1.db, 0, 1)
    assert RD.cx_spender(c1.db, 0, 1) == 1

    # same restore on the SOURCE shard: its outgoing receipts (the
    # proof material other shards may still request) survive too
    path0 = str(tmp_path / "s0.snap")
    assert SN.export_snapshot(c0, path0) == 1
    rawdb.delete_state(c0.db, c0.current_header().root)
    assert SN.import_snapshot(c0, path0) == 1
    assert RD.read_receipts(c0.db, 1)
    assert export_receipts(c0, 1, shard_count=2) == proofs


@pytest.mark.slow
def test_snapshot_budget_100k_profiled():
    """ISSUE 18 acceptance: the 10^5-account genesis builds and
    snapshot-imports inside the scenario budget, with prof.stage()
    histograms over the build/root/export/install paths (the numbers
    quoted in docs/ANALYSIS.md § Dress rehearsal)."""
    import time

    from harmony_tpu import prof

    prof.reset()
    prof.configure(enabled=True)
    try:
        t0 = time.monotonic()
        genesis, keys, _ = dev_genesis(n_accounts=100_000,
                                       flat_root=True)
        build_s = time.monotonic() - t0
        assert build_s < 120.0, f"dev_genesis(100k) {build_s:.1f}s"

        chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
        _grow(chain, keys, 1)

        import tempfile

        with tempfile.TemporaryDirectory() as d:
            path = d + "/big.snap"
            t0 = time.monotonic()
            assert SN.export_snapshot(chain, path) == 1
            fresh = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
            assert SN.import_snapshot(fresh, path, trust=True) == 1
            roundtrip_s = time.monotonic() - t0
        assert roundtrip_s < 120.0, f"roundtrip {roundtrip_s:.1f}s"
        assert fresh.state().root() == chain.state().root()

        summary = prof.stage_summary()
        for stage in ("genesis.build_state", "state.root",
                      "snapshot.export", "snapshot.install"):
            assert stage in summary, f"stage {stage} not recorded"
        # surfaced for the ANALYSIS.md table (pytest -s)
        for name, s in sorted(summary.items()):
            print(f"  {name}: n={s['count']} sum={s['sum_s']:.3f}s "
                  f"p50={s['p50_s']:.3f}s p99={s['p99_s']:.3f}s")
    finally:
        prof.reset()


def test_pruned_node_resyncs_history_state(tmp_path):
    """prune -> restart -> resync (VERDICT r4 #7 done-criterion): a
    pruned node re-acquires a historical state through the fast-sync
    states machinery (account-range download bound to the sealed
    root)."""
    from harmony_tpu.p2p.stream import SyncClient, SyncServer
    from harmony_tpu.sync.staged import Downloader

    serving, keys = _fresh_chain()
    _grow(serving, keys, 4)

    pruned, _ = _fresh_chain(db=None)
    # sync the chain fully first
    srv = SyncServer(serving)
    try:
        dl = Downloader(pruned, [SyncClient(srv.port)], batch=2,
                        verify_seals=False)
        dl.sync_once()
        assert pruned.head_number == 4
        SN.prune_states(pruned, retain=1)
        with pytest.raises(ChainError):
            pruned.state_at(2)
        # head state is still bound + more blocks keep flowing
        _grow(serving, keys, 1, start_nonce=4)
        dl.sync_once()
        assert pruned.head_number == 5
        assert (pruned.current_header().hash()
                == serving.current_header().hash())
    finally:
        srv.close()
