"""State pruning + snapshot export/import (core/snapshot.py — the
reference's core/state/snapshot + blockchain_pruner roles)."""

import pytest

from harmony_tpu.core import rawdb
from harmony_tpu.core import snapshot as SN
from harmony_tpu.core.blockchain import Blockchain, ChainError
from harmony_tpu.core.genesis import dev_genesis
from harmony_tpu.core.kv import MemKV
from harmony_tpu.core.tx_pool import TxPool
from harmony_tpu.core.types import Transaction
from harmony_tpu.node.worker import Worker

CHAIN_ID = 2

_GENESIS = dev_genesis()


def _grow(chain, keys, n, start_nonce=0):
    pool = TxPool(CHAIN_ID, 0, chain.state)
    worker = Worker(chain, pool)
    for i in range(n):
        tx = Transaction(
            nonce=start_nonce + i, gas_price=1, gas_limit=25_000,
            shard_id=0, to_shard=0, to=b"\x07" * 20, value=50 + i,
        ).sign(keys[0], CHAIN_ID)
        pool.add(tx)
        block = worker.propose_block(view_id=chain.head_number + 1)
        chain.insert_chain([block], verify_seals=False)
        pool.drop_applied()


def _fresh_chain(db=None, **kw):
    genesis, keys, _ = _GENESIS
    return Blockchain(db or MemKV(), genesis, blocks_per_epoch=16,
                      **kw), keys


def test_bulk_prune_drops_old_states_keeps_window():
    chain, keys = _fresh_chain()
    _grow(chain, keys, 8)
    assert SN.prune_states(chain, retain=3) > 0
    # window intact: head-2..head load fine
    for num in range(6, 9):
        assert chain.state_at(num) is not None
    # pruned history raises the clear chain error
    with pytest.raises(ChainError, match="missing state"):
        chain.state_at(2)
    # headers/bodies/receipts are NOT pruned: the header chain is whole
    for num in range(0, 9):
        assert chain.header_by_number(num) is not None
    # genesis state is never pruned
    assert chain.state_at(0) is not None


def test_incremental_retention_on_insert():
    chain, keys = _fresh_chain(state_retention=2)
    _grow(chain, keys, 6)
    assert chain.state_at(6) is not None
    assert chain.state_at(5) is not None
    with pytest.raises(ChainError, match="missing state"):
        chain.state_at(3)


def test_shared_root_never_lost(tmp_path):
    """Empty blocks share a state root only if NOTHING changes; with
    rewards off in the dev chain an empty proposal still bumps nothing
    — simulate the shared-root case directly."""
    chain, keys = _fresh_chain()
    _grow(chain, keys, 2)
    h1 = chain.header_by_number(1)
    h2 = chain.header_by_number(2)
    if h1.root != h2.root:
        # roots differ on this chain shape: deletion of 1 must not
        # touch 2
        assert SN.prune_state_at(chain, 1)
        assert chain.state_at(2) is not None
    else:
        # shared: pruning 1 defers (state 2 would die with it)
        assert not SN.prune_state_at(chain, 1)
        assert chain.state_at(2) is not None


def test_snapshot_roundtrip_restores_pruned_node(tmp_path):
    chain, keys = _fresh_chain()
    _grow(chain, keys, 5)
    path = str(tmp_path / "head.snap")
    assert SN.export_snapshot(chain, path) == 5

    # prune EVERYTHING but head, then kill the head state too (the
    # worst restart: no usable state at all below head)
    SN.prune_states(chain, retain=1)
    head_root = chain.current_header().root
    rawdb.delete_state(chain.db, head_root)
    db = chain.db

    # restart on the same db fails to load head state...
    with pytest.raises(ChainError, match="missing state"):
        _fresh_chain(db=db)

    # ...until the snapshot is imported — via a maintenance-shaped
    # minimal object (the damaged store cannot construct a Blockchain)
    import threading

    class _M:
        pass

    m = _M()
    m.db = db
    m.config = chain.config
    m._insert_lock = threading.RLock()
    m.head_number = 5
    m._committee_cache = {}
    num = SN.import_snapshot(m, path)
    assert num == 5
    # now a real restart works and the chain extends
    chain3, keys3 = _fresh_chain(db=db)
    assert chain3.head_number == 5
    _grow(chain3, keys3, 1, start_nonce=5)
    assert chain3.head_number == 6


def test_snapshot_import_rejects_forged_accounts(tmp_path):
    chain, keys = _fresh_chain()
    _grow(chain, keys, 3)
    path = str(tmp_path / "head.snap")
    SN.export_snapshot(chain, path)
    # tamper with the account payload
    raw = bytearray(open(path, "rb").read())
    raw[-3] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    with pytest.raises(SN.SnapshotError):
        SN.import_snapshot(chain, path)


def test_snapshot_import_fresh_node_requires_trust(tmp_path):
    chain, keys = _fresh_chain()
    _grow(chain, keys, 3)
    path = str(tmp_path / "head.snap")
    SN.export_snapshot(chain, path)

    fresh, _ = _fresh_chain()
    with pytest.raises(SN.SnapshotError, match="trust"):
        SN.import_snapshot(fresh, path)
    num = SN.import_snapshot(fresh, path, trust=True)
    assert num == 3 and fresh.head_number == 3
    assert fresh.state().root() == chain.state().root()


def test_pruned_node_resyncs_history_state(tmp_path):
    """prune -> restart -> resync (VERDICT r4 #7 done-criterion): a
    pruned node re-acquires a historical state through the fast-sync
    states machinery (account-range download bound to the sealed
    root)."""
    from harmony_tpu.p2p.stream import SyncClient, SyncServer
    from harmony_tpu.sync.staged import Downloader

    serving, keys = _fresh_chain()
    _grow(serving, keys, 4)

    pruned, _ = _fresh_chain(db=None)
    # sync the chain fully first
    srv = SyncServer(serving)
    try:
        dl = Downloader(pruned, [SyncClient(srv.port)], batch=2,
                        verify_seals=False)
        dl.sync_once()
        assert pruned.head_number == 4
        SN.prune_states(pruned, retain=1)
        with pytest.raises(ChainError):
            pruned.state_at(2)
        # head state is still bound + more blocks keep flowing
        _grow(serving, keys, 1, start_nonce=4)
        dl.sync_once()
        assert pruned.head_number == 5
        assert (pruned.current_header().hash()
                == serving.current_header().hash())
    finally:
        srv.close()
