"""Scheduler invariants (ISSUE 5): the continuous-batching
verification scheduler in front of device.py.

Covered here, deterministically where the invariant allows it (manual
schedulers driven by ``_flush_once``; fake dispatchers for pure queue
logic; the bigint twin kernels for real-crypto paths):

- per-lane FIFO and same-group coalescing,
- priority preemption (consensus first) with lower-lane backfill and
  the starvation bound,
- deadline fail-fast at admission AND in-queue expiry — no dispatch is
  ever issued for an already-expired request,
- breaker-open shed path bitwise-matches the CPU reference,
- bounded-queue overflow sheds to the CPU reference,
- batch fill ratio >= 2x the unscheduled baseline under coalescing,
- chaos: an injected device.dispatch delay backs the sync lane up
  while consensus-lane latency stays bounded,
- tx-pool BLS proof-of-possession on the ingress lane,
- the engine's sidecar per-header remainder pipelined through the
  scheduler (cross-epoch batch, result parity with the direct path).
"""

from __future__ import annotations

import threading
import time

import pytest

from harmony_tpu import bls as B
from harmony_tpu import device as DV
from harmony_tpu import faultinject as FI
from harmony_tpu import sched
from harmony_tpu.ops import twin as TWIN
from harmony_tpu.ref.hash_to_curve import hash_to_g2
from harmony_tpu.resilience import CircuitBreaker, Deadline, DeadlineExceeded
from harmony_tpu.sched.scheduler import FILL, Lane, VerifyScheduler

N_KEYS = 4


@pytest.fixture(autouse=True)
def _forced_device_twins(monkeypatch):
    """Twin kernels + forced device path (the test-image convention for
    exercising the device layers), fresh global scheduler per test."""
    monkeypatch.setenv("HARMONY_KERNEL_TWIN", "1")
    DV.use_device(True)
    sched.reset()
    yield
    sched.reset()
    FI.reset()
    DV.use_device(None)


@pytest.fixture(scope="module")
def committee():
    keys = [B.PrivateKey.generate(bytes([40 + i])) for i in range(N_KEYS)]
    table = DV.CommitteeTable([k.pub.point for k in keys])
    payload = b"sched-quorum-payload-32-bytes!!!"
    agg = B.aggregate_sigs([k.sign_hash(payload) for k in keys[:3]])
    bits = [1, 1, 1, 0]
    return keys, table, payload, agg, bits


def _recording(scheduler):
    """Replace the instance's device dispatchers with recorders."""
    flushes = []

    def run(kind):
        def _run(batch):
            flushes.append(
                (kind, [(id(r.table), r.lane, r.bits) for r in batch])
            )
            return [True] * len(batch), len(batch)

        return _run

    scheduler._run_single = run("single")
    scheduler._run_agg = run("agg")
    return flushes


class _FakeTable:
    pass


def _submit_agg(s, table, lane, tag):
    return s.submit_agg(table, tag, None, None, lane=lane)


# -- queue-logic invariants (fake dispatch, fully deterministic) -------------


def test_per_lane_fifo_and_group_prefix():
    s = VerifyScheduler(manual=True)
    flushes = _recording(s)
    t1, t2 = _FakeTable(), _FakeTable()
    _submit_agg(s, t1, Lane.SYNC, "a1")
    _submit_agg(s, t1, Lane.SYNC, "a2")
    _submit_agg(s, t2, Lane.SYNC, "b1")
    _submit_agg(s, t1, Lane.SYNC, "a3")
    while s._flush_once():
        pass
    # FIFO within the lane: only the t1-PREFIX fuses; a3 must not jump
    # over b1 even though it shares a1/a2's group
    assert [[tag for _, _, tag in batch] for _, batch in flushes] == [
        ["a1", "a2"], ["b1"], ["a3"],
    ]


def test_priority_preemption_with_backfill():
    s = VerifyScheduler(manual=True)
    flushes = _recording(s)
    t1 = _FakeTable()
    _submit_agg(s, t1, Lane.SYNC, "s1")
    _submit_agg(s, t1, Lane.SYNC, "s2")
    _submit_agg(s, t1, Lane.INGRESS, "i1")
    _submit_agg(s, t1, Lane.CONSENSUS, "c1")
    s._flush_once()
    # one fused flush: the consensus request leads, same-group traffic
    # from the lower lanes backfills the bucket (priority order)
    assert len(flushes) == 1
    assert [tag for _, _, tag in flushes[0][1]] == ["c1", "s1", "s2", "i1"]


def test_starvation_bound():
    s = VerifyScheduler(manual=True, starvation_limit=2)
    flushes = _recording(s)
    tc, ts = _FakeTable(), _FakeTable()  # distinct groups: no backfill
    _submit_agg(s, ts, Lane.SYNC, "s1")
    served_sync_at = None
    for i in range(6):
        _submit_agg(s, tc, Lane.CONSENSUS, f"c{i}")
        s._flush_once()
        lanes = {lane for _, batch in flushes[-1:] for _, lane, _ in batch}
        if Lane.SYNC in lanes:
            served_sync_at = i
            break
    # the sync request may be passed over at most starvation_limit
    # consecutive flushes before it MUST be served
    assert served_sync_at is not None and served_sync_at <= 2


def test_deadline_failfast_at_admission():
    s = VerifyScheduler(manual=True)
    flushes = _recording(s)
    fut = s.submit_agg(_FakeTable(), "x", None, None,
                       lane=Lane.CONSENSUS, deadline=Deadline.after(0.0))
    with pytest.raises(DeadlineExceeded):
        fut.result(1.0)
    assert not any(s._lanes.values())  # never enqueued
    s._flush_once()
    assert flushes == []  # and never dispatched


def test_expired_in_queue_never_dispatched():
    s = VerifyScheduler(manual=True)
    flushes = _recording(s)
    fut = s.submit_agg(_FakeTable(), "x", None, None,
                       lane=Lane.SYNC, deadline=Deadline.after(0.02))
    assert s._lanes[Lane.SYNC]  # admitted (budget covered the queue)
    time.sleep(0.04)
    s._flush_once()
    assert flushes == []  # expired: dropped, no dispatch ever issued
    with pytest.raises(DeadlineExceeded):
        fut.result(1.0)


def test_queue_full_sheds_to_cpu_ref(committee):
    _, table, payload, agg, bits = committee
    h = hash_to_g2(payload)
    s = VerifyScheduler(manual=True, max_queue_per_lane=2)
    f1 = s.submit_agg(table, bits, h, agg.point, lane=Lane.SYNC)
    f2 = s.submit_agg(table, bits, h, agg.point, lane=Lane.SYNC)
    f3 = s.submit_agg(table, bits, h, agg.point, lane=Lane.SYNC)
    # the overflow request resolved INLINE on the reference path
    assert f3.done() and f3.result() is True
    assert not f1.done() and not f2.done()
    while s._flush_once():
        pass
    assert f1.result(5) is True and f2.result(5) is True


# -- real-crypto paths (twin kernels) ----------------------------------------


def test_breaker_open_shed_bitwise_matches_cpu_ref(committee, monkeypatch):
    keys, table, payload, agg, bits = committee
    brk = CircuitBreaker("device", failure_threshold=1,
                         reset_timeout_s=3600.0)
    brk.record_failure()  # OPEN, and stays open for the test
    monkeypatch.setattr(DV, "BREAKER", brk)
    calls_before = dict(TWIN.CALLS)
    h = hash_to_g2(payload)
    got_good = sched.agg_verify(table, bits, payload, agg.point,
                                lane=sched.Lane.CONSENSUS)
    bad_sig = B.aggregate_sigs(
        [k.sign_hash(payload) for k in keys[:2]]
    )
    got_bad = sched.agg_verify(table, bits, payload, bad_sig.point,
                               lane=sched.Lane.CONSENSUS)
    # bitwise: the shed path IS the reference path
    assert got_good == DV._ref_agg_verify(table, bits, h, agg.point)
    assert got_bad == DV._ref_agg_verify(table, bits, h, bad_sig.point)
    assert (got_good, got_bad) == (True, False)
    # the device was never touched
    assert dict(TWIN.CALLS) == calls_before


def test_fill_ratio_coalescing_beats_unscheduled_baseline():
    """8 coalesced single checks fill one 8-wide bucket completely —
    >= 2x the 1/8 fill each check would get dispatched alone."""
    keys = [B.PrivateKey.generate(bytes([90 + i])) for i in range(8)]
    msgs = [b"fill-%d" % i for i in range(8)]
    sigs = [k.sign_hash(m) for k, m in zip(keys, msgs)]
    s = VerifyScheduler(manual=True)
    items0, slots0 = FILL["items"], FILL["slots"]
    futs = [
        s.submit_single(k.pub.point, hash_to_g2(m), sig.point,
                        lane=Lane.INGRESS)
        for k, m, sig in zip(keys, msgs, sigs)
    ]
    while s._flush_once():
        pass
    assert [f.result(10) for f in futs] == [True] * 8
    d_items = FILL["items"] - items0
    d_slots = FILL["slots"] - slots0
    assert d_items == 8
    assert d_items / d_slots >= 2 * (1 / 8)
    assert d_items / d_slots == 1.0  # one full bucket, zero pad waste


def test_chaos_consensus_p50_bounded_while_sync_backs_up(committee):
    """faultinject a device.dispatch delay: the sync lane queues up
    behind slow flushes while consensus-lane requests keep jumping the
    queue — their p50 stays bounded (the ISSUE 5 chaos invariant)."""
    _, table, payload, agg, bits = committee
    h = hash_to_g2(payload)
    FI.arm("device.dispatch", delay_s=0.05)
    s = sched.scheduler()
    stop = threading.Event()
    sync_depth_seen = []

    def flood():
        while not stop.is_set():
            futs = [
                s.submit_agg(table, bits, h, agg.point, lane=Lane.SYNC)
                for _ in range(6)
            ]
            sync_depth_seen.append(len(s._lanes[Lane.SYNC]))
            for f in futs:
                try:
                    f.result(30)
                except RuntimeError:
                    return  # scheduler stopped at teardown

    t = threading.Thread(target=flood, daemon=True)
    t.start()
    time.sleep(0.1)  # let the sync lane saturate
    lat = []
    for _ in range(7):
        t0 = time.monotonic()
        ok = sched.agg_verify(table, bits, payload, agg.point,
                              lane=sched.Lane.CONSENSUS)
        lat.append(time.monotonic() - t0)
        assert ok is True
    stop.set()
    t.join(timeout=30)
    p50 = sorted(lat)[len(lat) // 2]
    # bounded: ~one in-flight flush (50 ms fault + pairing work), not
    # the sync backlog.  The bound is generous for slow CI boxes.
    assert p50 < 1.0, f"consensus p50 {p50:.3f}s under sync backlog"
    assert max(sync_depth_seen, default=0) > 0  # sync really backed up


def test_txpool_staking_pop_on_ingress_lane():
    from harmony_tpu.core.tx_pool import PoolError, TxPool
    from harmony_tpu.core.types import Directive, StakingTransaction
    from harmony_tpu.crypto_ecdsa import ECDSAKey

    class _State:
        def nonce(self, sender):
            return 0

        def balance(self, sender):
            return 10**30

    pool = TxPool(2, 0, lambda: _State())
    staker = ECDSAKey.from_seed(b"sched-pop-staker")
    bls_key = B.PrivateKey.generate(b"sched-pop-bls")
    pop = B.proof_of_possession(bls_key)

    def mk(nonce, pop_bytes):
        return StakingTransaction(
            nonce=nonce, gas_price=1, gas_limit=50_000,
            directive=Directive.CREATE_VALIDATOR,
            fields={
                "amount": 10**20, "min_self_delegation": 10**18,
                "bls_keys": bls_key.pub.bytes,
                "bls_key_sigs": pop_bytes,
            },
        ).sign(staker, 2)

    pool.add(mk(0, pop), is_staking=True)  # valid proof admits
    bad = bytearray(pop)
    bad[5] ^= 0x40
    with pytest.raises(PoolError, match="proof of possession"):
        pool.add(mk(1, bytes(bad)), is_staking=True)
    with pytest.raises(PoolError, match="length mismatch"):
        pool.add(mk(1, pop + pop), is_staking=True)
    # legacy tx without proof fields still admits (opt-in wire field)
    legacy = StakingTransaction(
        nonce=1, gas_price=1, gas_limit=50_000,
        directive=Directive.CREATE_VALIDATOR,
        fields={
            "amount": 10**20, "min_self_delegation": 10**18,
            "bls_keys": bls_key.pub.bytes,
        },
    ).sign(staker, 2)
    pool.add(legacy, is_staking=True)


def test_ingress_sender_sig_gate_through_scheduler():
    from harmony_tpu.consensus.messages import FBFTMessage, MsgType, \
        sign_message
    from harmony_tpu.multibls import PrivateKeys
    from harmony_tpu.node.ingress import verify_sender

    keys = PrivateKeys.from_keys([B.PrivateKey.generate(b"ingress-k")])
    msg = sign_message(FBFTMessage(
        msg_type=MsgType.ANNOUNCE, view_id=1, block_num=1,
        block_hash=b"\x11" * 32,
        sender_pubkeys=[keys[0].pub.bytes],
    ), keys)
    before = DV.COUNTERS["verify"]
    assert verify_sender(msg)
    assert DV.COUNTERS["verify"] > before  # went through the device path
    msg.block_num = 2  # breaks the signed encoding
    assert not verify_sender(msg)


def test_engine_backend_remainder_pipelined_cross_epoch():
    """The sidecar path of verify_headers_batch: a cross-epoch batch
    pipelines through the scheduler's backend worker instead of
    serializing one round-trip per header; results match the direct
    (scheduler-disabled) per-header path."""
    from harmony_tpu.chain.engine import Engine, EpochContext
    from harmony_tpu.chain.header import Header
    from harmony_tpu.consensus.mask import Mask
    from harmony_tpu.consensus.signature import construct_commit_payload
    from harmony_tpu.sidecar.client import SidecarClient
    from harmony_tpu.sidecar.server import SidecarServer

    committees = {
        2: [B.PrivateKey.generate(bytes([10 + i])) for i in range(3)],
        3: [B.PrivateKey.generate(bytes([20 + i])) for i in range(3)],
    }

    def provider(shard_id, epoch):
        return EpochContext([k.pub.bytes for k in committees[epoch]])

    def sign(header, epoch, signer_idx):
        keys = committees[epoch]
        payload = construct_commit_payload(
            header.hash(), header.block_num, header.view_id, True
        )
        agg = B.aggregate_sigs([keys[i].sign_hash(payload)
                                for i in signer_idx])
        mask = Mask([k.pub.point for k in keys])
        for i in signer_idx:
            mask.set_bit(i, True)
        return agg.bytes, mask.mask_bytes()

    items = []
    for n in range(6):
        epoch = 2 if n < 3 else 3
        h = Header(shard_id=0, block_num=300 + n, epoch=epoch,
                   view_id=300 + n)
        sig, bm = sign(h, epoch, [0, 1, 2])
        items.append((h, sig, bm))
    # corrupt one: epoch-2 sig against an epoch-3 header
    items[4] = (items[4][0], items[1][1], items[4][2])

    server = SidecarServer().start()
    client = SidecarClient(server.address)
    try:
        engine = Engine(provider, device=False, backend=client)
        got = engine.verify_headers_batch(items)
        sched.configure(enabled=False)
        direct = Engine(provider, device=False, backend=client)
        want = direct.verify_headers_batch(items)
        assert got == want
        assert got[4] is False and sum(got) == 5
        # cached now: a repeat is free and still correct
        assert engine.verify_headers_batch(items) == got
    finally:
        client.close()
        server.stop()


def test_sched_metrics_exposed():
    text = sched.expose_metrics()
    for fam in ("harmony_sched_queue_depth", "harmony_sched_shed_total",
                "harmony_sched_flushes_total", "harmony_sched_items_total",
                "harmony_sched_wait_seconds",
                "harmony_sched_batch_fill_ratio"):
        assert fam in text
    assert 'lane="consensus"' in text
