"""Handel-style vote aggregation (ISSUE 20): the level-ladder unit
tier (topology, merge rules, forged-partial rejection, timeout
escalation) plus deterministic localnet arcs — a 64-slot committee
assembling quorum through the overlay with bounded leader inbound,
and the direct-mode bit-parity guarantee (aggregation off produces
byte-identical wire traffic)."""

import time

from harmony_tpu import bls as B
from harmony_tpu.consensus import aggregation as AGG
from harmony_tpu.consensus.mask import Mask
from harmony_tpu.core.blockchain import Blockchain
from harmony_tpu.core.genesis import dev_genesis
from harmony_tpu.core.kv import MemKV
from harmony_tpu.core.tx_pool import TxPool
from harmony_tpu.multibls import PrivateKeys
from harmony_tpu.node.node import Node
from harmony_tpu.node.registry import Registry
from harmony_tpu.p2p import InProcessNetwork
from harmony_tpu.ref import bls as RB

CHAIN_ID = 2


# -- level topology ----------------------------------------------------------

def test_num_levels():
    assert AGG.num_levels(1) == 1
    assert AGG.num_levels(2) == 1
    assert AGG.num_levels(3) == 2
    assert AGG.num_levels(4) == 2
    assert AGG.num_levels(64) == 6
    assert AGG.num_levels(200) == 8


def test_level_peers_partition_power_of_two():
    """For every slot the union of peers over all levels is exactly
    the rest of the committee, each level's peers live in the OTHER
    half of the slot's 2**level block, and no level self-includes."""
    n = 16
    for slot in range(n):
        seen: set = set()
        for level in range(1, AGG.num_levels(n) + 1):
            peers = AGG.level_peers(slot, level, n)
            assert slot not in peers
            half = 1 << (level - 1)
            base = (slot >> level) << level
            own_half = range(base, base + half) if not (slot & half) \
                else range(base + half, base + 2 * half)
            assert not set(peers) & set(own_half)
            assert not set(peers) & seen  # levels are disjoint
            seen |= set(peers)
        assert seen == set(range(n)) - {slot}


def test_level_peers_clipped_committee():
    """A non-power-of-two committee clips the top block: the union
    still covers every other live slot, never a phantom one."""
    n = 13
    for slot in range(n):
        seen: set = set()
        for level in range(1, AGG.num_levels(n) + 1):
            peers = AGG.level_peers(slot, level, n)
            assert all(0 <= p < n for p in peers)
            seen |= set(peers)
        assert seen == set(range(n)) - {slot}


def test_level_span_doubles_and_clips():
    assert AGG.level_span(0, 1, 64) == (0, 2)
    assert AGG.level_span(0, 6, 64) == (0, 64)
    assert AGG.level_span(5, 2, 64) == (4, 8)
    # clipped committee: the top block's span never exceeds n
    assert AGG.level_span(12, 3, 13) == (8, 13)


# -- the aggregator: merge rules, forgery, ladder ----------------------------

def _mk_agg(n=8, home=0, leader_slot=0, **kw):
    keys = [B.PrivateKey.generate(b"agg-unit-%d" % i) for i in range(n)]
    committee = [k.pub.bytes for k in keys]
    bar = (2 * n) // 3 + 1
    emitted = []
    agg = AGG.Aggregator(
        committee, [home],
        quorum_check=lambda bv: int(bv.sum()) >= bar,
        emit=lambda t, ph, lv, bm, sg: emitted.append((t, ph, lv)),
        leader_slot=leader_slot,
        **kw,
    )
    return agg, keys, emitted


def _contrib(keys, payload, slots):
    """A genuine partial: aggregate sig + bitmap over ``slots``."""
    sigs = [keys[s].sign_hash(payload) for s in slots]
    bits = 0
    for s in slots:
        bits |= 1 << s
    return bits, B.aggregate_sigs(sigs).bytes


def test_merge_disjoint_adds_and_dedups():
    agg, keys, _ = _mk_agg()
    payload = b"\x11" * 32
    agg.seed(AGG.PHASE_PREPARE, payload, 1, keys[0].sign_hash(payload))
    bits, sig_b = _contrib(keys, payload, [1, 2])
    bm = bits.to_bytes(agg.mask_len, "little")
    assert agg.on_contribution(AGG.PHASE_PREPARE, 1, bm, sig_b) == "queued"
    # byte-identical replay dedups for free, before any pairing work
    assert agg.on_contribution(AGG.PHASE_PREPARE, 1, bm, sig_b) == "dup"
    work = agg.tick(AGG.PHASE_PREPARE, now=0.0)
    assert work["merged"] == 1 and work["forged"] == 0
    assert agg.signed_count(AGG.PHASE_PREPARE) == 3
    # the merged aggregate genuinely verifies against the mask
    mask = Mask(agg.committee_points)
    mask.set_mask((0b111).to_bytes(agg.mask_len, "little"))
    st = agg.phases[AGG.PHASE_PREPARE]
    assert RB.verify(mask.aggregate_public(device=False), payload,
                     st.sig.point)
    # a subset contribution carries zero new weight: dropped pre-verify
    sub_bits, sub_sig = _contrib(keys, payload, [2])
    assert agg.on_contribution(
        AGG.PHASE_PREPARE, 1,
        sub_bits.to_bytes(agg.mask_len, "little"), sub_sig,
    ) == "stale"
    assert agg.merged == 1 and agg.dup_dropped == 1


def test_merge_overlapping_keeps_heavier():
    """Overlapping verified aggregates cannot add (the overlap would
    double-count); the heavier one wins wholesale."""
    agg, keys, _ = _mk_agg()
    payload = b"\x22" * 32
    agg.seed(AGG.PHASE_PREPARE, payload, 0b11,
             B.aggregate_sigs([keys[0].sign_hash(payload),
                               keys[1].sign_hash(payload)]))
    bits, sig_b = _contrib(keys, payload, [1, 2, 3])
    agg.on_contribution(AGG.PHASE_PREPARE, 1,
                        bits.to_bytes(agg.mask_len, "little"), sig_b)
    work = agg.tick(AGG.PHASE_PREPARE, now=0.0)
    assert work["merged"] == 1
    st = agg.phases[AGG.PHASE_PREPARE]
    assert st.bits == 0b1110  # replaced, not OR-ed
    mask = Mask(agg.committee_points)
    mask.set_mask(st.bits.to_bytes(agg.mask_len, "little"))
    assert RB.verify(mask.aggregate_public(device=False), payload,
                     st.sig.point)


def test_forged_partial_rejected_never_merged():
    agg, keys, _ = _mk_agg()
    payload = b"\x33" * 32
    agg.seed(AGG.PHASE_PREPARE, payload, 1, keys[0].sign_hash(payload))
    # a REAL signature over a different payload: parses fine, fails
    # the aggregate pairing check — the Byzantine forgery shape
    bits, sig_b = _contrib(keys, b"\x44" * 32, [1, 2])
    agg.on_contribution(AGG.PHASE_PREPARE, 1,
                        bits.to_bytes(agg.mask_len, "little"), sig_b,
                        frm="evil")
    work = agg.tick(AGG.PHASE_PREPARE, now=0.0)
    assert work["forged"] == 1 and work["merged"] == 0
    assert work["forged_from"] == ["evil"]
    assert agg.signed_count(AGG.PHASE_PREPARE) == 1  # untouched
    # malformed shapes are verdicts, not exceptions
    assert agg.on_contribution(
        AGG.PHASE_PREPARE, 1, bytes(agg.mask_len + 1), sig_b,
    ) == "malformed"
    assert agg.on_contribution(
        AGG.PHASE_PREPARE, 1, bytes(agg.mask_len), sig_b) == "malformed"


def test_timeout_escalation_reaches_leader():
    """With no inbound help, per-level timeouts walk the ladder to the
    final rung and the best (lone) contribution ships direct to the
    leader slot — Handel's loss tolerance."""
    agg, keys, emitted = _mk_agg(
        home=3, leader_slot=5,
        level_timeout_s=0.1, reemit_s=0.05,
    )
    payload = b"\x55" * 32
    agg.seed(AGG.PHASE_PREPARE, payload, 1 << 3,
             keys[3].sign_hash(payload), now=0.0)
    agg.tick(AGG.PHASE_PREPARE, now=0.0)
    assert emitted, "first tick must emit to level-1 peers"
    assert all(t != 5 for t, _, _ in emitted)  # not the leader yet
    # stride past every level timeout (respecting the reemit cadence)
    now = 0.0
    for _ in range(agg.n_levels + 2):
        now += 0.15
        agg.tick(AGG.PHASE_PREPARE, now=now)
    assert emitted[-1][0] == 5  # final rung: direct to the leader
    assert agg.phases[AGG.PHASE_PREPARE].final_sent >= 1


def test_quorum_and_proof_shape():
    agg, keys, _ = _mk_agg()
    payload = b"\x66" * 32
    agg.seed(AGG.PHASE_COMMIT, payload, 1, keys[0].sign_hash(payload))
    assert not agg.quorum(AGG.PHASE_COMMIT)
    bits, sig_b = _contrib(keys, payload, [1, 2, 3, 4, 5, 6])
    agg.on_contribution(AGG.PHASE_COMMIT, 2,
                        bits.to_bytes(agg.mask_len, "little"), sig_b)
    agg.tick(AGG.PHASE_COMMIT, now=0.0)
    assert agg.quorum(AGG.PHASE_COMMIT)  # 7 of 8 >= 2n/3+1
    proof = agg.proof(AGG.PHASE_COMMIT)
    assert len(proof) == 96 + agg.mask_len
    mask = Mask(agg.committee_points)
    mask.set_mask(proof[96:])
    assert RB.verify(mask.aggregate_public(device=False), payload,
                     B.Signature.from_bytes(proof[:96]).point)


def test_fallback_is_one_shot():
    agg, keys, _ = _mk_agg(stall_timeout_s=0.2)
    payload = b"\x77" * 32
    agg.seed(AGG.PHASE_PREPARE, payload, 1, keys[0].sign_hash(payload),
             fallback="direct-vote", now=0.0)
    assert agg.stalled(0.1) == []
    assert agg.stalled(0.5) == [AGG.PHASE_PREPARE]
    assert agg.take_fallback(AGG.PHASE_PREPARE) == "direct-vote"
    assert agg.take_fallback(AGG.PHASE_PREPARE) is None
    assert agg.stalled(1.0) == []  # taken: never offered again
    assert agg.fallbacks == 1


# -- localnet arcs -----------------------------------------------------------

def _make_localnet(n_nodes=4, keys_per_node=1, aggregation=None):
    genesis, ecdsa_keys, bls_keys = dev_genesis(
        n_keys=n_nodes * keys_per_node
    )
    net = InProcessNetwork()
    nodes = []
    for i in range(n_nodes):
        chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
        pool = TxPool(CHAIN_ID, 0, chain.state)
        reg = Registry(
            blockchain=chain, txpool=pool, host=net.host(f"node{i}")
        )
        if aggregation is not None:
            reg.set("aggregation", aggregation)
        ks = bls_keys[i * keys_per_node:(i + 1) * keys_per_node]
        nodes.append(Node(reg, PrivateKeys.from_keys(ks)))
    return nodes, net


def _pump_agg(nodes, done, budget_s=30.0):
    """Drive pumps + overlay ticks until ``done()`` or the budget."""
    deadline = time.monotonic() + budget_s
    while time.monotonic() < deadline:
        busy = any([n.process_pending() for n in nodes])
        now = time.monotonic()
        for n in nodes:
            n._aggregation_tick(now)
        if done():
            return True
        if not busy:
            time.sleep(0.005)
    return done()


def test_handel_localnet_commits():
    """4 single-key nodes, overlay on: two rounds commit, quorum was
    assembled from merged contributions, zero forged partials and zero
    stall fallbacks on a clean network."""
    nodes, net = _make_localnet(4, aggregation="handel")
    assert all(n.aggregator is not None for n in nodes)
    for target in (1, 2):
        leader = next(n for n in nodes if n.is_leader)
        leader.start_round_if_leader()
        assert _pump_agg(
            nodes,
            lambda: all(n.chain.head_number == target for n in nodes),
        ), f"round {target} never committed through the overlay"
    stats = [n.aggregation_stats() for n in nodes]
    assert sum(s["merged"] for s in stats) > 0
    assert sum(s["forged"] for s in stats) == 0
    assert sum(s["fallbacks"] for s in stats) == 0
    assert all(n.chain.read_commit_sig(2) is not None for n in nodes)


def test_handel_64_slot_assembly_bounded_inbound():
    """The ISSUE 20 shape: a 64-slot committee (16-key operators, the
    wan_committee topology) assembles prepare AND commit quorums
    through the ladder; the leader ingests at most committee_size/4
    vote-bearing messages for the round — O(log N) assembly, not N."""
    nodes, net = _make_localnet(4, keys_per_node=16,
                                aggregation="handel")
    leader = next(n for n in nodes if n.is_leader)
    assert all(len(n.aggregator.home_slots) == 16 for n in nodes)
    assert nodes[0].aggregator.n == 64
    leader.start_round_if_leader()
    assert _pump_agg(
        nodes,
        lambda: all(n.chain.head_number == 1 for n in nodes),
    ), "the 64-slot round never committed through the overlay"
    inbound = sum(
        v for (_ph, kind), v in leader.host.inbound_votes.items()
        if kind in ("ballot", "aggregate")
    )
    assert inbound <= 64 // 4, (
        f"leader ingested {inbound} vote msgs (> 16 = slots/4)"
    )
    stats = [n.aggregation_stats() for n in nodes]
    assert sum(s["forged"] for s in stats) == 0


def _record_wire(nodes):
    rec = []
    for n in nodes:
        orig = n.host.publish

        def pub(topic, payload, _orig=orig, _name=n.host.name):
            rec.append((_name, topic, payload))
            return _orig(topic, payload)

        n.host.publish = pub
    return rec


def _one_recorded_round(aggregation):
    nodes, net = _make_localnet(4, aggregation=aggregation)
    rec = _record_wire(nodes)
    leader = next(n for n in nodes if n.is_leader)
    leader.start_round_if_leader()
    assert _pump_agg(
        nodes, lambda: all(n.chain.head_number == 1 for n in nodes)
    )
    return rec


def test_direct_mode_bit_parity():
    """aggregation = "direct" must restore the exact pre-overlay wire
    behavior: byte-identical message sequences to an unconfigured
    node, and not a single aggregation-topic publish."""
    base = _one_recorded_round(aggregation=None)
    direct = _one_recorded_round(aggregation="direct")
    assert base == direct  # byte-for-byte, including ballot sigs
    assert all("/aggregation/" not in topic for _, topic, _p in base)
    # and the overlay mode really is what moves votes off the topic
    handel = _one_recorded_round(aggregation="handel")
    assert any("/aggregation/" in topic for _, topic, _p in handel)
