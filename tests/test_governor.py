"""Resource-governor tier (ISSUE 14): tier transitions with
hysteresis + dwell, every knob it drives (tx-pool overload floor,
ingress admission, scheduler sheds, sync window), and the maintenance
tick that finally calls evict_stale on a running node."""

import time

import pytest

from harmony_tpu import governor as GV
from harmony_tpu import health as HL
from harmony_tpu.governor import Limits, ResourceGovernor, Tier


@pytest.fixture(autouse=True)
def _clean():
    HL.configure(enabled=False)
    yield
    GV.uninstall()
    HL.reset()


def _gov(sample, clock=None, **kw):
    kw.setdefault("limits", Limits(
        queue_pressured=100, queue_critical=200,
        pool_pressured=0.5, pool_critical=0.9,
        threads_pressured=500, threads_critical=1000,
        hysteresis=0.8, dwell_s=1.0,
    ))
    return ResourceGovernor(
        sample_fn=lambda: dict(sample),
        clock=clock or time.monotonic, **kw,
    )


# -- the tier state machine ---------------------------------------------------


def test_escalation_is_immediate_worst_signal_wins():
    sample = {"queue_depth": 0}
    gov = _gov(sample)
    assert gov.sample_once() is Tier.NORMAL
    sample["queue_depth"] = 150
    assert gov.sample_once() is Tier.PRESSURED
    sample["queue_depth"] = 0
    sample["pool_fill"] = 0.95  # a DIFFERENT signal goes critical
    assert gov.sample_once() is Tier.CRITICAL
    assert gov.peak is Tier.CRITICAL


def test_deescalation_needs_dwell_and_hysteresis():
    now = [0.0]
    sample = {"queue_depth": 150}
    gov = _gov(sample, clock=lambda: now[0])
    assert gov.sample_once() is Tier.PRESSURED
    # clear drop, but the dwell (1 s since the transition) not served
    sample["queue_depth"] = 10
    now[0] += 0.5
    assert gov.sample_once() is Tier.PRESSURED
    # BELOW the enter threshold but above exit (enter 100 * hysteresis
    # 0.8 = 80): the tier holds no matter how long
    sample["queue_depth"] = 90
    now[0] += 10.0
    assert gov.sample_once() is Tier.PRESSURED
    # clear headroom + dwell served -> steps down
    sample["queue_depth"] = 10
    now[0] += 1.0
    assert gov.sample_once() is Tier.NORMAL


def test_deescalation_steps_one_tier_per_dwell():
    now = [0.0]
    sample = {"queue_depth": 500}
    gov = _gov(sample, clock=lambda: now[0])
    assert gov.sample_once() is Tier.CRITICAL
    sample["queue_depth"] = 0
    now[0] += 2.0
    assert gov.sample_once() is Tier.PRESSURED  # one step, not a jump
    now[0] += 2.0
    assert gov.sample_once() is Tier.NORMAL


def test_missing_signals_are_not_judged():
    gov = _gov({"rss_bytes": None, "pool_fill": None})
    assert gov.sample_once() is Tier.NORMAL


def test_transition_metrics_and_state_gauge():
    before = GV.TRANSITIONS.value(**{"from": "normal", "to": "pressured"})
    sample = {"queue_depth": 150}
    gov = _gov(sample)
    gov.sample_once()
    assert GV.TRANSITIONS.value(
        **{"from": "normal", "to": "pressured"}
    ) == before + 1
    assert GV.STATE.value() == 1.0
    text = GV.expose()
    assert "harmony_governor_state" in text
    assert "harmony_governor_transitions_total" in text


# -- knob: tx-pool overload floor --------------------------------------------


def _mk_pool(**kw):
    from harmony_tpu.core.tx_pool import TxPool

    class _Stub:
        def nonce(self, addr):
            return 0

        def balance(self, addr):
            return 10**30

    return TxPool(2, 0, _Stub, **kw)


def _tx(nonce=0, gas_price=1):
    from harmony_tpu.core.types import Transaction

    return Transaction(nonce=nonce, gas_price=gas_price,
                       gas_limit=21_000, shard_id=0, to_shard=0,
                       to=b"\x2d" * 20, value=1)


def test_pool_floor_follows_tiers():
    from harmony_tpu.core.tx_pool import PoolError

    pool = _mk_pool()
    sample = {"queue_depth": 0}
    gov = _gov(sample)
    gov.attach_pool(pool)
    sender = b"\x41" * 20
    pool.add(_tx(nonce=0), sender=sender)  # floor 1 admits price 1
    sample["queue_depth"] = 150
    gov.sample_once()  # PRESSURED: floor x4
    before = GV.rejections_total()
    with pytest.raises(PoolError, match="overload floor"):
        pool.add(_tx(nonce=1), sender=sender)
    assert GV.rejections_total() == before + 1
    pool.add(_tx(nonce=1, gas_price=4), sender=sender)  # pays the floor
    # recovery restores the configured floor
    sample["queue_depth"] = 0
    time.sleep(0)  # dwell is against the real clock here
    gov.limits = Limits(dwell_s=0.0, queue_pressured=100,
                        queue_critical=200)
    gov.sample_once()
    assert gov.state() is Tier.NORMAL
    pool.add(_tx(nonce=2), sender=sender)


def test_pool_fill_ratio():
    pool = _mk_pool(cap=10)
    sender = b"\x42" * 20
    assert pool.fill_ratio() == 0.0
    for n in range(5):
        pool.add(_tx(nonce=n), sender=sender)
    assert pool.fill_ratio() == 0.5


def test_ordinary_floor_rejection_is_not_counted_as_governed():
    from harmony_tpu.core.tx_pool import PoolError

    pool = _mk_pool(price_floor=10)
    before = GV.rejections_total()
    with pytest.raises(PoolError, match="below floor"):
        pool.add(_tx(gas_price=5), sender=b"\x43" * 20)
    assert GV.rejections_total() == before


# -- knob: ingress admission --------------------------------------------------


def test_admit_ingress_tiers():
    sample = {"queue_depth": 0}
    gov = _gov(sample, pressured_ingress_rate=1.0)
    GV.install(gov)
    assert GV.admit_ingress("1.2.3.4") is True  # NORMAL: open
    sample["queue_depth"] = 150
    gov.sample_once()
    # PRESSURED: token-bucket limited per key (burst 2 at rate 1/s)
    allowed = [gov.admit_ingress("1.2.3.4") for _ in range(4)]
    assert allowed[:2] == [True, True] and allowed[-1] is False
    assert gov.admit_ingress("5.6.7.8") is True  # per-key isolation
    sample["queue_depth"] = 500
    gov.sample_once()
    before = GV.rejections_total()
    assert gov.admit_ingress("1.2.3.4") is False  # CRITICAL: refused
    assert GV.rejections_total() == before + 1


def test_uninstalled_helpers_are_open():
    from harmony_tpu.sched.scheduler import Lane

    GV.uninstall()
    assert GV.admit_ingress("x") is True
    assert GV.should_shed(Lane.INGRESS) is False
    assert GV.sync_window_scale() == 1.0


# -- knob: scheduler sheds ----------------------------------------------------


def test_should_shed_matrix():
    from harmony_tpu.sched.scheduler import Lane

    sample = {"queue_depth": 0}
    gov = _gov(sample)
    for lane in Lane:
        assert gov.should_shed(lane) is False
    gov._state = Tier.PRESSURED
    assert gov.should_shed(Lane.INGRESS) is True
    assert gov.should_shed(Lane.SYNC) is False
    assert gov.should_shed(Lane.CONSENSUS) is False
    gov._state = Tier.CRITICAL
    assert gov.should_shed(Lane.INGRESS) is True
    assert gov.should_shed(Lane.SYNC) is True
    assert gov.should_shed(Lane.CONSENSUS) is False  # NEVER


def test_scheduler_sheds_governed_lanes_to_fallback():
    """A CRITICAL governor sheds INGRESS/SYNC submissions to the
    caller-thread fallback (counted, correct), while CONSENSUS still
    queues for the device."""
    from harmony_tpu.sched.scheduler import (
        SHED, Lane, VerifyScheduler,
    )

    class _StubClient:
        def agg_verify(self, *args, deadline=None):
            return True

    gov = _gov({"queue_depth": 0})
    gov._state = Tier.CRITICAL
    GV.install(gov)
    sched = VerifyScheduler(manual=True)
    before = SHED.value(lane="ingress", reason="governor")
    fut = sched.submit_backend(
        _StubClient(), 0, 0, b"p", b"\xff", b"s", lane=Lane.INGRESS,
    )
    assert fut.result(1.0) is True  # the fallback ran the stub call
    assert SHED.value(
        lane="ingress", reason="governor"
    ) == before + 1
    # consensus traffic is untouched: it queues instead of shedding
    fut2 = sched.submit_backend(
        _StubClient(), 0, 0, b"p", b"\xff", b"s", lane=Lane.CONSENSUS,
    )
    assert not fut2.done()
    assert len(sched._lanes[Lane.CONSENSUS]) == 1


# -- knob: sync window --------------------------------------------------------


def test_sync_window_shrinks_with_tier():
    from harmony_tpu.sync.staged import Downloader

    dl = Downloader(chain=None, clients=[], batch=64)
    assert dl._window() == 64
    gov = _gov({"queue_depth": 0})
    GV.install(gov)
    gov._state = Tier.PRESSURED
    assert dl._window() == 32
    gov._state = Tier.CRITICAL
    assert dl._window() == 16
    gov._state = Tier.NORMAL
    assert dl._window() == 64


# -- the maintenance tick -----------------------------------------------------


def test_running_node_ticks_evict_stale(monkeypatch):
    """The live pump must periodically evict stale queued txs — the
    ISSUE 14 satellite: evict_stale existed, nothing ever called it."""
    monkeypatch.setenv("HARMONY_KERNEL_TWIN", "1")
    from harmony_tpu.core.blockchain import Blockchain
    from harmony_tpu.core.genesis import dev_genesis
    from harmony_tpu.core.kv import MemKV
    from harmony_tpu.core.tx_pool import TxPool
    from harmony_tpu.multibls import PrivateKeys
    from harmony_tpu.node.node import Node
    from harmony_tpu.node.registry import Registry
    from harmony_tpu.p2p import InProcessNetwork

    genesis, ecdsa_keys, bls_keys = dev_genesis(n_keys=1)
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    pool = TxPool(2, 0, chain.state, lifetime=0.05)
    reg = Registry(blockchain=chain, txpool=pool,
                   host=InProcessNetwork().host("n0"))
    node = Node(reg, PrivateKeys.from_keys(bls_keys))
    node.maintenance_interval_s = 0.05
    # a FUTURE-nonce tx parks in the queued tier and can only leave
    # via lifetime eviction
    sender = ecdsa_keys[0].address()
    pool.add(_tx(nonce=7), sender=sender)
    assert len(pool) == 1
    pump = node.run_forever(poll_interval=0.01, block_time=60.0)
    try:
        deadline = time.monotonic() + 5.0
        while len(pool) and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(pool) == 0
        assert pool.evicted == 1
    finally:
        node.stop()
        pump.join(timeout=5.0)


def test_evict_stale_returns_count():
    pool = _mk_pool(lifetime=0.01)
    sender = b"\x44" * 20
    pool.add(_tx(nonce=5), sender=sender)  # queued (future nonce)
    time.sleep(0.03)
    assert pool.evict_stale() == 1
    assert pool.evict_stale() == 0
