"""LIVE consensus through the device path (VERDICT r4 #3).

A real Node commits real blocks with device.py FORCED ON: every quorum
proof runs through CommitteeTable + agg_verify_on_device and the
COUNTERS observably increment.  Kernels are the host-backed twins
(HARMONY_KERNEL_TWIN=1, ops/twin.py) — the layer split of
test_device_path.py, but carried by actual FBFT rounds instead of
hand-fed arrays.  tools/localnet.py --device-path is the subprocess
variant of this scenario (counters asserted over /metrics)."""

import pytest

from harmony_tpu import device as DV
from harmony_tpu.core.blockchain import Blockchain
from harmony_tpu.core.genesis import dev_genesis
from harmony_tpu.core.kv import MemKV
from harmony_tpu.core.tx_pool import TxPool
from harmony_tpu.multibls import PrivateKeys
from harmony_tpu.node.node import Node
from harmony_tpu.node.registry import Registry
from harmony_tpu.ops import twin
from harmony_tpu.p2p import InProcessNetwork

CHAIN_ID = 2


@pytest.fixture
def device_forced(monkeypatch):
    monkeypatch.setenv("HARMONY_KERNEL_TWIN", "1")
    DV.use_device(True)
    yield
    DV.use_device(None)


def test_live_rounds_traverse_device_path(device_forced):
    genesis, ecdsa_keys, bls_keys = dev_genesis(n_keys=4)
    net = InProcessNetwork()
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    pool = TxPool(CHAIN_ID, 0, chain.state)
    reg = Registry(blockchain=chain, txpool=pool, host=net.host("solo"))
    node = Node(reg, PrivateKeys.from_keys(bls_keys))

    before = dict(DV.COUNTERS)
    twin_before = dict(twin.CALLS)
    for _ in range(3):
        node.start_round_if_leader()
    assert chain.head_number == 3, "device-path rounds must commit"
    grew = DV.COUNTERS["agg_verify"] - before["agg_verify"]
    assert grew > 0, (before, DV.COUNTERS)
    # the counters were backed by real twin-kernel invocations (the
    # device arrays actually flowed, not just the counter line)
    assert twin.CALLS["agg_verify"] - twin_before["agg_verify"] >= grew
    # committee bucket 8: the 4-key committee pads to the first bucket
    tbl = DV.get_committee_table(
        tuple(k.pub.bytes for k in bls_keys),
        [k.pub.point for k in bls_keys],
    )
    assert tbl.size == 8 and tbl.n == 4


def test_device_metrics_exposition(device_forced):
    from harmony_tpu.metrics import Registry as MetricsRegistry

    base = DV.COUNTERS["agg_verify"]
    DV.COUNTERS["agg_verify"] = base + 1
    try:
        text = MetricsRegistry().expose()
    finally:
        DV.COUNTERS["agg_verify"] = base
    assert 'harmony_device_checks_total{kind="agg_verify"}' in text
    assert "harmony_device_kernel_twin 1" in text
