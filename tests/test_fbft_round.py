"""Full in-process FBFT prepare+commit round over the framework's crypto
path — the executable model of the reference's hot loop (SURVEY.md §3.2)
and the small-scale version of BASELINE config #3."""

import pytest

from harmony_tpu.consensus import fbft as FB
from harmony_tpu.consensus import quorum as Q
from harmony_tpu.consensus.messages import MsgType, decode_sig_and_bitmap
from harmony_tpu.multibls import PrivateKeys
from harmony_tpu.ref.keccak import keccak256


@pytest.fixture(scope="module")
def network():
    """7 nodes, one multi-key (2 slots): 8 committee slots total."""
    keysets = [
        PrivateKeys.from_keys(
            [
                __import__("harmony_tpu.bls", fromlist=["PrivateKey"])
                .PrivateKey.generate(bytes([10 * n + j]))
                for j in range(2 if n == 0 else 1)
            ]
        )
        for n in range(7)
    ]
    committee = [k.pub.bytes for ks in keysets for k in ks]
    cfg = FB.RoundConfig(committee=committee, block_num=42, view_id=3)

    def decider():
        return Q.Decider(Q.Policy.UNIFORM, committee)

    leader = FB.Leader(keysets[0], cfg, decider())
    validators = [FB.Validator(ks, cfg, decider()) for ks in keysets[1:]]
    return leader, validators, cfg


def test_full_round(network):
    leader, validators, cfg = network
    block = b"block body bytes"
    block_hash = keccak256(block)

    announce = leader.announce(block_hash, block)
    assert announce.msg_type == MsgType.ANNOUNCE

    # announce itself cast the leader's own prepare vote (leader.go:20)
    assert leader.decider.count(FB.Phase.PREPARE) == len(leader.keys)
    # a re-sent self-vote is a duplicate and must be rejected
    self_prep = FB.Validator(leader.keys, cfg, leader.decider).on_announce(
        announce
    )
    assert not leader.on_prepare(self_prep)

    # validators sign prepare votes; leader verifies each (hot loop)
    prepares = [v.on_announce(announce) for v in validators]
    for p in prepares:
        assert leader.on_prepare(p)

    # duplicate vote rejected
    assert not leader.on_prepare(prepares[0])

    prepared = leader.try_prepared(block_hash)
    assert prepared is not None and prepared.msg_type == MsgType.PREPARED
    sig, bitmap = decode_sig_and_bitmap(prepared.payload, 1)
    assert len(sig) == 96 and len(bitmap) == 1
    assert bitmap == b"\xff"  # all 8 slots voted

    # validators verify the prepare proof and emit commit votes
    commits = [v.on_prepared(prepared) for v in validators]
    assert all(c is not None for c in commits)
    self_commit = FB.Validator(leader.keys, cfg, leader.decider).on_prepared(
        prepared
    )
    assert leader.on_commit(self_commit)
    for c in commits:
        assert leader.on_commit(c)

    committed = leader.try_committed(block_hash)
    assert committed is not None and committed.msg_type == MsgType.COMMITTED

    # every validator accepts the committed proof
    for v in validators:
        assert v.on_committed(committed)


def test_tampered_proof_rejected(network):
    leader, validators, cfg = network
    block_hash = keccak256(b"other block")
    # reuse the committed proof for a different block hash: must fail
    committed = leader.try_committed(keccak256(b"block body bytes"))
    tampered = FB.FBFTMessage(
        msg_type=MsgType.COMMITTED,
        view_id=cfg.view_id,
        block_num=cfg.block_num,
        block_hash=block_hash,
        sender_pubkeys=committed.sender_pubkeys,
        payload=committed.payload,
    )
    assert not validators[0].on_committed(tampered)


def test_overlapping_keyset_vote_rejected(network):
    """A key-set overlapping an earlier vote would double a signature in
    the aggregate while the bitmap marks it once — must be dropped."""
    _, validators, cfg = network
    from harmony_tpu.consensus.quorum import Decider, Policy
    from harmony_tpu.multibls import PrivateKeys

    leader = FB.Leader(
        validators[0].keys, cfg, Decider(Policy.UNIFORM, cfg.committee)
    )
    block = b"overlap test block"
    h = keccak256(block)
    announce = leader.announce(h, block)
    v1 = validators[1]
    assert leader.on_prepare(v1.on_announce(announce))
    # combined key-set containing v1's already-voted key
    combined = PrivateKeys.from_keys(list(v1.keys) + list(validators[2].keys))
    overlap_vote = FB.Validator(combined, cfg, leader.decider).on_announce(
        announce
    )
    assert not leader.on_prepare(overlap_vote)


def test_malformed_proof_rejected_not_raised(network):
    _, validators, cfg = network
    for bad_payload in (b"short", bytes(96), bytes(96) + b"\x00\x01"):
        msg = FB.FBFTMessage(
            msg_type=MsgType.PREPARED,
            view_id=cfg.view_id,
            block_num=cfg.block_num,
            block_hash=keccak256(b"x"),
            sender_pubkeys=[cfg.committee[0]],
            payload=bad_payload,
        )
        assert validators[0].on_prepared(msg) is None  # no exception


def test_insufficient_quorum_no_prepared(network):
    _, validators, cfg = network
    # a fresh leader with only 2 of 8 votes must not produce PREPARED
    from harmony_tpu.consensus.quorum import Decider, Policy

    leader2 = FB.Leader(
        validators[0].keys, cfg, Decider(Policy.UNIFORM, cfg.committee)
    )
    block = b"b2"
    h = keccak256(block)
    leader2.announce(h, block)
    vote = validators[1].on_announce(leader2.announce(h, block))
    assert leader2.on_prepare(vote)
    assert leader2.try_prepared(h) is None
