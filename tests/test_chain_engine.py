"""Header model + engine verification tests, including a mini chain
replay through the batched device path (BASELINE config #5 in miniature).

Engine runs host-mode (device=False) here: this image's XLA persistent cache aborts deserializing the big pairing executables (see tests/conftest.py); the device path's correctness is covered by the ops parity suite and runs on real TPU via bench/__graft_entry__."""

import pytest

from harmony_tpu import bls as B
from harmony_tpu.chain.engine import Engine, EpochContext
from harmony_tpu.chain.header import Header
from harmony_tpu.consensus.mask import Mask
from harmony_tpu.consensus.signature import construct_commit_payload
from harmony_tpu.multibls import PrivateKeys

N_KEYS = 4


@pytest.fixture(scope="module")
def committee():
    keys = [B.PrivateKey.generate(bytes([30 + i])) for i in range(N_KEYS)]
    serialized = [k.pub.bytes for k in keys]
    return keys, serialized


def _provider(serialized):
    def provide(shard_id, epoch):
        return EpochContext(serialized)

    return provide


def _sign_header(header, keys, signer_idx):
    payload = construct_commit_payload(
        header.hash(), header.block_num, header.view_id, True
    )
    sigs = [keys[i].sign_hash(payload) for i in signer_idx]
    agg = B.aggregate_sigs(sigs)
    mask = Mask([k.pub.point for k in keys])
    for i in signer_idx:
        mask.set_bit(i, True)
    return agg.bytes, mask.mask_bytes()


def test_header_hash_includes_carried_commit_proof():
    """Reference semantics (block/v3/header.go:67-68): the PARENT's
    commit sig/bitmap are ordinary header fields, fixed at proposal —
    the signed hash commits to them."""
    h = Header(shard_id=0, block_num=5, epoch=1, view_id=5)
    base = h.hash()
    h.last_commit_sig = b"x" * 96
    h.last_commit_bitmap = b"\x0f"
    assert h.hash() != base  # proof is part of the hashed fields
    h2 = Header(shard_id=0, block_num=6, epoch=1, view_id=5)
    assert h2.hash() != base


def test_header_versions_hash_distinctly():
    kw = dict(shard_id=1, block_num=7, epoch=2, view_id=7)
    hashes = {Header(version=v, **kw).hash() for v in ("v0", "v1", "v2", "v3")}
    assert len(hashes) == 4  # tagged envelope separates versions
    import pytest

    with pytest.raises(ValueError):
        Header(version="v9", **kw).hash()


def test_header_rawdb_roundtrip_all_versions():
    from harmony_tpu.core import rawdb

    for v in ("v0", "v1", "v2", "v3"):
        h = Header(
            shard_id=2, block_num=9, epoch=1, view_id=9,
            parent_hash=b"\x01" * 32, root=b"\x02" * 32,
            last_commit_sig=b"s" * 96, last_commit_bitmap=b"\x0f",
            vrf=b"vrf-bytes", shard_state=b"ss", cross_links=b"cl",
            slashes=b"sl", version=v,
        )
        back = rawdb.decode_header(rawdb.encode_header(h))
        assert back == h
        assert back.hash() == h.hash()


def test_verify_header_signature_and_cache(committee):
    keys, serialized = committee
    eng = Engine(_provider(serialized), device=False)
    h = Header(shard_id=0, block_num=10, epoch=2, view_id=10)
    sig, bitmap = _sign_header(h, keys, [0, 1, 2, 3])
    assert eng.verify_header_signature(h, sig, bitmap)
    # cached second call (host-only fast path)
    assert eng.verify_header_signature(h, sig, bitmap)
    # insufficient quorum: only 2 of 4 (threshold 2*4//3+1 = 3)
    sig2, bitmap2 = _sign_header(h, keys, [0, 1])
    assert not eng.verify_header_signature(h, sig2, bitmap2)
    # signature/bitmap mismatch
    sig3, _ = _sign_header(h, keys, [0, 1, 2])
    assert not eng.verify_header_signature(h, sig3, bitmap)


def test_verify_seal_via_child(committee):
    keys, serialized = committee
    eng = Engine(_provider(serialized), device=False)
    parent = Header(shard_id=0, block_num=20, epoch=2, view_id=20)
    sig, bitmap = _sign_header(parent, keys, [0, 1, 2])
    child = Header(
        shard_id=0,
        block_num=21,
        epoch=2,
        view_id=21,
        parent_hash=parent.hash(),
        last_commit_sig=sig,
        last_commit_bitmap=bitmap,
    )
    assert eng.verify_seal(parent, child)
    assert not eng.verify_seal(child, child)  # proof is for the parent


def test_batched_replay(committee):
    keys, serialized = committee
    eng = Engine(_provider(serialized), device=False)
    headers = []
    prev_hash = bytes(32)
    for n in range(5):
        h = Header(
            shard_id=0, block_num=100 + n, epoch=3, view_id=100 + n,
            parent_hash=prev_hash,
        )
        sig, bitmap = _sign_header(h, keys, [0, 1, 2, 3])
        headers.append((h, sig, bitmap))
        prev_hash = h.hash()
    # corrupt one: replace block 102's sig with block 101's
    items = list(headers)
    items[2] = (items[2][0], items[1][1], items[2][2])
    results = eng.verify_headers_batch(items)
    assert results == [True, True, False, True, True]
    # second replay: everything good is cache-hit (no device work needed)
    results2 = eng.verify_headers_batch(
        [headers[0], headers[1], headers[3], headers[4]]
    )
    assert results2 == [True] * 4
