"""Cross-shard transfers end to end: debit on the source shard,
authenticated proof export, destination verification + inclusion,
credit (the reference's CXReceiptsProof flow — SURVEY.md §2.7;
core/block_validator.go ValidateCXReceiptsProof)."""

import pytest

from harmony_tpu.core.blockchain import Blockchain, ChainError, verify_cx_proof
from harmony_tpu.core.genesis import Genesis, dev_genesis
from harmony_tpu.core.kv import MemKV
from harmony_tpu.core.tx_pool import TxPool
from harmony_tpu.core.types import CXReceipt, Transaction
from harmony_tpu.node.cross_shard import (
    CXPool,
    cx_topic,
    decode_cx_batch,
    encode_cx_batch,
    export_receipts,
    make_cx_proof,
)
from harmony_tpu.node.worker import Worker

CHAIN_ID = 2


def _two_shards():
    g0, ecdsa_keys, bls = dev_genesis(shard_id=0)
    g1 = Genesis(
        config=g0.config, shard_id=1, alloc=dict(g0.alloc),
        committee=list(g0.committee),
    )
    c0 = Blockchain(MemKV(), g0, blocks_per_epoch=16)
    c1 = Blockchain(MemKV(), g1, blocks_per_epoch=16)
    return c0, c1, ecdsa_keys


def _send_cross_shard(c0, sender, to, value):
    pool0 = TxPool(CHAIN_ID, 0, c0.state)
    tx = Transaction(
        nonce=0, gas_price=1, gas_limit=25_000, shard_id=0, to_shard=1,
        to=to, value=value,
    ).sign(sender, CHAIN_ID)
    pool0.add(tx)
    block0 = Worker(c0, pool0).propose_block(view_id=1)
    assert c0.insert_chain([block0], verify_seals=False) == 1
    return block0


def test_cross_shard_transfer_end_to_end():
    c0, c1, keys = _two_shards()
    sender = keys[0]
    to = b"\x0c" * 20
    _send_cross_shard(c0, sender, to, 9999)
    assert c0.state().balance(to) == 0  # no local credit

    proofs = export_receipts(c0, 1, shard_count=2)
    assert list(proofs) == [1]
    assert proofs[1].receipts[0].amount == 9999
    # proof self-consistency (merkle chain up to the source header)
    assert verify_cx_proof(proofs[1], 1, None, c1.config)

    # transport: encode -> (gossip topic) -> decode at destination
    blob = encode_cx_batch(proofs[1])
    assert decode_cx_batch(blob).receipts[0].amount == 9999
    assert cx_topic("localnet", 1).endswith("/1/cx")
    cx_pool = CXPool(shard_id=1, config=c1.config)
    assert cx_pool.add_batch(blob) == 1
    assert cx_pool.add_batch(blob) == 0  # duplicate batch dropped

    # destination proposer includes the proof; credit lands
    incoming = cx_pool.drain()
    block1 = Worker(c1, None).propose_block(
        view_id=1, incoming_receipts=incoming
    )
    assert block1.incoming_receipts
    assert c1.insert_chain([block1], verify_seals=False) == 1
    assert c1.state().balance(to) == 9999
    assert len(cx_pool) == 0

    # double spend: the same source batch cannot enter a later block
    block2 = Worker(c1, None).propose_block(
        view_id=2, incoming_receipts=incoming
    )
    with pytest.raises(ChainError):
        c1.insert_chain([block2], verify_seals=False)

    # replay integrity: tampering with an included receipt breaks both
    # the merkle chain and the body commitment
    c1b = Blockchain(MemKV(), Genesis(
        config=c1.config, shard_id=1,
        alloc=dict(c1.genesis.alloc), committee=list(c1.genesis.committee),
    ), blocks_per_epoch=16)
    tampered = Worker(c1b, None).propose_block(
        view_id=1, incoming_receipts=incoming
    )
    tampered.incoming_receipts[0].receipts[0].amount = 10**18
    with pytest.raises(ChainError):
        c1b.insert_chain([tampered], verify_seals=False)


def test_fabricated_receipts_rejected():
    """ADVICE r1 (high): unauthenticated CX batches must not mint
    balance — a fabricated batch fails the merkle/header chain."""
    c0, c1, keys = _two_shards()
    _send_cross_shard(c0, keys[0], b"\x0c" * 20, 50)
    proof = make_cx_proof(c0, 1, 1, shard_count=2)

    # fabricate: bump the amount (group root no longer matches)
    evil = decode_cx_batch(proof.encode())
    evil.receipts[0].amount = 10**18
    cx_pool = CXPool(shard_id=1, config=c1.config)
    assert cx_pool.add_batch(evil.encode()) == 0

    # fabricate: rebuild roots over the forged receipts — now the
    # header's out_cx_root no longer matches
    from harmony_tpu.core.types import cx_group_root

    evil.shard_hashes = [cx_group_root(evil.receipts)]
    evil.shard_ids = [1]
    assert cx_pool.add_batch(evil.encode()) == 0

    # fabricate: forge the header too — the engine-wired pool rejects
    # it for having no valid committee seal
    from harmony_tpu.chain.engine import Engine, EpochContext

    def provider(shard_id, epoch):
        return EpochContext(c0.committee_for_epoch(epoch))

    engine = Engine(provider, device=False)
    from harmony_tpu.core import rawdb

    hdr = rawdb.decode_header(evil.header_bytes)
    hdr.out_cx_root = __import__(
        "harmony_tpu.ref.keccak", fromlist=["keccak256"]
    ).keccak256(
        (1).to_bytes(4, "little") + cx_group_root(evil.receipts)
    )
    evil.header_bytes = rawdb.encode_header(hdr)
    sealed_pool = CXPool(shard_id=1, engine=engine, config=c1.config)
    assert sealed_pool.add_batch(evil.encode()) == 0

    # the honest proof (no seal stored on an engine-less source chain)
    # is also rejected by a seal-enforcing pool — receipts from an
    # unsealed block are not final
    assert sealed_pool.add_batch(proof.encode()) == 0


def test_cx_pool_caps_and_filtering():
    c0, c1, keys = _two_shards()
    cx_pool = CXPool(shard_id=1, cap=2, config=c1.config)

    # wrong destination: a batch claiming shard 3 receipts never enters
    # a shard-1 pool
    _send_cross_shard(c0, keys[0], b"\x0d" * 20, 7)
    proof = make_cx_proof(c0, 1, 1, shard_count=2)
    wrong = decode_cx_batch(proof.encode())
    for cx in wrong.receipts:
        cx.to_shard = 3
    assert cx_pool.add_batch(wrong.encode()) == 0

    assert cx_pool.add_batch(proof.encode()) == 1
    assert len(cx_pool.drain()) == 1

    # spent tracking: a pool wired to the chain's spent set refuses a
    # batch the chain already consumed
    tracked = CXPool(
        shard_id=1, config=c1.config,
        spent=lambda fs, num: (fs, num) == (0, 1),
    )
    assert tracked.add_batch(proof.encode()) == 0


def test_cx_receipt_by_hash_rpc():
    """hmyv2_getCXReceiptByHash (reference: rpc/transaction.go) — the
    re-export handle any validator can serve when the leader's cx
    broadcast was lost."""
    import http.client
    import json

    from harmony_tpu.hmy.facade import Harmony
    from harmony_tpu.rpc import RPCServer

    c0, c1, keys = _two_shards()
    sender = keys[0]
    to = b"\x0c" * 20
    block0 = _send_cross_shard(c0, sender, to, 4321)
    tx = block0.transactions[0]
    hmy = Harmony(c0)
    assert hmy.get_cx_receipt_by_hash(tx.hash(CHAIN_ID)).amount == 4321
    assert hmy.get_cx_receipt_by_hash(b"\x00" * 32) is None
    srv = RPCServer(hmy, port=0).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=10)
        conn.request("POST", "/", json.dumps({
            "jsonrpc": "2.0", "id": 1,
            "method": "hmyv2_getCXReceiptByHash",
            "params": ["0x" + tx.hash(CHAIN_ID).hex()],
        }), {"Content-Type": "application/json"})
        got = json.loads(conn.getresponse().read())["result"]
        conn.close()
        # reference json tags: rpc/harmony/v2/types.go CxReceipt
        assert got["value"] == 4321 and got["toShardID"] == 1
        assert got["shardID"] == 0
        assert got["hash"] == "0x" + tx.hash(CHAIN_ID).hex()
        assert got["to"] == "0x" + to.hex()
        assert got["blockHash"] == "0x" + (
            c0.header_by_number(1).hash().hex()
        )
    finally:
        srv.stop()


def test_fast_sync_reconstructs_cx_spent_set():
    """A fast-synced destination node must know which source batches
    its skipped range already credited (the downloaded blocks carry
    them, seal-verified) — otherwise it could later lead a
    double-credit proposal the network rejects."""
    from harmony_tpu.core import rawdb
    from harmony_tpu.p2p.stream import SyncClient, SyncServer
    from harmony_tpu.sync import Downloader

    c0, c1, keys = _two_shards()
    to = b"\x0c" * 20
    _send_cross_shard(c0, keys[0], to, 777)
    proof = make_cx_proof(c0, 1, 1, shard_count=2)
    block1 = Worker(c1, None).propose_block(
        view_id=1, incoming_receipts=[proof]
    )
    assert c1.insert_chain([block1], verify_seals=False) == 1
    c1.write_commit_sig(1, b"\x01" * 96 + b"\x0f")

    srv = SyncServer(c1)
    try:
        fresh = Blockchain(MemKV(), Genesis(
            config=c1.config, shard_id=1, alloc=dict(c1.genesis.alloc),
            committee=list(c1.genesis.committee),
        ), blocks_per_epoch=16)
        dl = Downloader(fresh, [SyncClient(srv.port)], batch=4,
                        verify_seals=False)
        res = dl.fast_sync()
        assert res.inserted == 1 and not res.errors
        assert fresh.state().balance(to) == 777
        # the spent-set survived the skip: (shard 0, block 1) is spent
        assert rawdb.is_cx_spent(fresh.db, 0, 1)
        # and a replayed batch cannot enter a new block here
        replay = Worker(fresh, None).propose_block(
            view_id=2, incoming_receipts=[proof]
        )
        with pytest.raises(ChainError):
            fresh.insert_chain([replay], verify_seals=False)

        # an ABORTED fast sync (bodies persisted + spent-marked, states
        # stage never completed) must not wedge the full-replay
        # fallback: the same block re-consuming its own batches is
        # idempotent, only a DIFFERENT block is a double spend
        fresh2 = Blockchain(MemKV(), Genesis(
            config=c1.config, shard_id=1, alloc=dict(c1.genesis.alloc),
            committee=list(c1.genesis.committee),
        ), blocks_per_epoch=16)
        blk1 = c1.block_by_number(1)
        fresh2.insert_headers_fast([blk1], verify_seals=False)
        assert rawdb.is_cx_spent(fresh2.db, 0, 1)
        assert fresh2.head_number == 0  # head never moved
        assert fresh2.insert_chain([blk1], verify_seals=False) == 1
        assert fresh2.state().balance(to) == 777
    finally:
        srv.close()
