"""Cross-shard transfers end to end: debit on the source shard, receipt
export, destination inclusion, credit (the reference's CXReceipt flow
— SURVEY.md §2.7 cross-shard traffic)."""

from harmony_tpu.core.blockchain import Blockchain
from harmony_tpu.core.genesis import Genesis, dev_genesis
from harmony_tpu.core.kv import MemKV
from harmony_tpu.core.tx_pool import TxPool
from harmony_tpu.core.types import Transaction
from harmony_tpu.node.cross_shard import (
    CXPool,
    cx_topic,
    decode_cx_batch,
    encode_cx_batch,
    export_receipts,
)
from harmony_tpu.node.worker import Worker

CHAIN_ID = 2


def _two_shards():
    g0, ecdsa_keys, bls = dev_genesis(shard_id=0)
    g1 = Genesis(
        config=g0.config, shard_id=1, alloc=dict(g0.alloc),
        committee=list(g0.committee),
    )
    c0 = Blockchain(MemKV(), g0, blocks_per_epoch=16)
    c1 = Blockchain(MemKV(), g1, blocks_per_epoch=16)
    return c0, c1, ecdsa_keys


def test_cross_shard_transfer_end_to_end():
    c0, c1, keys = _two_shards()
    sender = keys[0]
    to = b"\x0c" * 20
    pool0 = TxPool(CHAIN_ID, 0, c0.state)
    tx = Transaction(
        nonce=0, gas_price=1, gas_limit=25_000, shard_id=0, to_shard=1,
        to=to, value=9999,
    ).sign(sender, CHAIN_ID)
    pool0.add(tx)

    # source shard commits the debit and exports the receipt
    block0 = Worker(c0, pool0).propose_block(view_id=1)
    assert c0.insert_chain([block0], verify_seals=False) == 1
    sender_bal = c0.state().balance(sender.address())
    assert c0.state().balance(to) == 0  # no local credit
    groups = export_receipts(c0, 1, shard_count=2)
    assert list(groups) == [1] and groups[1][0].amount == 9999

    # transport: encode -> (gossip topic) -> decode at destination
    blob = encode_cx_batch(0, 1, groups[1])
    assert cx_topic("localnet", 1).endswith("/1/cx")
    cx_pool = CXPool(shard_id=1)
    assert cx_pool.add_batch(blob) == 1
    assert cx_pool.add_batch(blob) == 0  # duplicate batch dropped

    # destination proposer includes the receipts; credit lands
    incoming = cx_pool.drain()
    block1 = Worker(c1, None).propose_block(
        view_id=1, incoming_receipts=incoming
    )
    assert block1.incoming_receipts
    assert c1.insert_chain([block1], verify_seals=False) == 1
    assert c1.state().balance(to) == 9999
    assert len(cx_pool) == 0

    # replay integrity: tampering with an included receipt breaks the
    # body commitment (tx_root covers incoming receipts)
    import pytest

    from harmony_tpu.core.blockchain import ChainError

    c1b = Blockchain(MemKV(), Genesis(
        config=c1.config, shard_id=1,
        alloc=dict(c1.genesis.alloc), committee=list(c1.genesis.committee),
    ), blocks_per_epoch=16)
    tampered = Worker(c1b, None).propose_block(
        view_id=1, incoming_receipts=incoming
    )
    tampered.incoming_receipts[0].amount = 10**18
    with pytest.raises(ChainError):
        c1b.insert_chain([tampered], verify_seals=False)


def test_cx_pool_caps_and_filtering():
    cx_pool = CXPool(shard_id=1, cap=2)
    from harmony_tpu.core.types import CXReceipt

    def batch(from_shard, num, n, to_shard=1):
        cxs = [
            CXReceipt(
                tx_hash=bytes([i]) * 32, sender=b"\x01" * 20,
                to=b"\x02" * 20, amount=i + 1, from_shard=from_shard,
                to_shard=to_shard, block_num=num,
            )
            for i in range(n)
        ]
        return encode_cx_batch(from_shard, num, cxs)

    # wrong destination filtered out entirely
    assert cx_pool.add_batch(batch(0, 1, 1, to_shard=3)) == 0
    assert cx_pool.add_batch(batch(0, 2, 2)) == 2
    # cap reached
    assert cx_pool.add_batch(batch(2, 3, 1)) == 0
    assert len(cx_pool.drain()) == 2
    assert cx_pool.add_batch(batch(2, 3, 1)) == 1
