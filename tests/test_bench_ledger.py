"""tools/bench_ledger.py: measured-vs-modeled round comparison flags."""

import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent / "tools"))

from bench_ledger import (  # noqa: E402
    diff,
    direction,
    load_rounds,
    main,
    normalize,
)

ROOT = pathlib.Path(__file__).parent.parent


def _tagged(v, unit="x/s", source="measured", **kw):
    return dict({"value": v, "unit": unit, "source": source}, **kw)


# -- normalize ---------------------------------------------------------------


def test_normalize_handles_tagged_legacy_and_missing():
    tagged = normalize({
        "metric": "p", "value": 1.5, "source": "measured",
        "extra": {"a_per_sec": _tagged(10.0),
                  "note": "not-a-metric",
                  "legacy_ms": 7.0},
    })
    assert tagged["p"]["value"] == 1.5
    assert tagged["a_per_sec"]["source"] == "measured"
    assert tagged["legacy_ms"]["source"] is None
    assert "note" not in tagged
    assert normalize(None) == {}


def test_normalize_attaches_legacy_mode_sibling():
    out = normalize({"extra": {
        "agg_verify_p50_ms_host_1k": 10.9,
        "agg_verify_1k_mode": "sched_mixed_lane_twin",
    }})
    assert out["agg_verify_p50_ms_host_1k"]["mode"] == (
        "sched_mixed_lane_twin"
    )


def test_ambiguous_legacy_mode_sibling_attaches_to_none():
    """Two metrics matching the stem: stamping either could launder a
    real regression into 'redefined' — so neither gets the mode and
    both stay comparable."""
    out = normalize({"extra": {
        "agg_verify_p50_ms_host_1k": 10.9,
        "agg_verify_p50_ms_1k_keys": 0.4,
        "agg_verify_1k_mode": "sched_mixed_lane_twin",
    }})
    assert out["agg_verify_p50_ms_host_1k"]["mode"] is None
    assert out["agg_verify_p50_ms_1k_keys"]["mode"] is None


# -- direction ---------------------------------------------------------------


def test_direction_map():
    assert direction("replay_headers_per_sec_host") == 1
    assert direction("agg_verify_p50_ms_host") == -1
    assert direction("round_p99_s_latency") == -1
    assert direction("sched_batch_fill_ratio") == 1
    assert direction("agg_verify_n_keys") == 0  # parameter, never flagged
    assert direction("some_mystery_number") == 0


# -- diff / flags ------------------------------------------------------------


def _pair(ma, mb, threshold=0.30):
    return diff([(5, "a", ma), (6, "b", mb)], threshold)


def test_throughput_drop_flags_regression():
    flags = _pair({"x_per_sec": _tagged(100.0)},
                  {"x_per_sec": _tagged(50.0)})
    assert [f["kind"] for f in flags] == ["regression"]
    assert flags[0]["change_pct"] == -50.0


def test_latency_drop_is_an_improvement():
    flags = _pair({"x_p50_ms": _tagged(100.0, "ms")},
                  {"x_p50_ms": _tagged(10.0, "ms")})
    assert [f["kind"] for f in flags] == ["improvement"]


def test_latency_rise_flags_regression():
    flags = _pair({"x_p50_ms": _tagged(10.0, "ms")},
                  {"x_p50_ms": _tagged(100.0, "ms")})
    assert [f["kind"] for f in flags] == ["regression"]


def test_within_threshold_is_silent():
    flags = _pair({"x_per_sec": _tagged(100.0)},
                  {"x_per_sec": _tagged(80.0)})  # -20% < 30%
    assert flags == []


def test_mode_change_is_redefinition_not_regression():
    """r06's replay redefinition: the measured number fell 8x because
    the MEASUREMENT changed (1/p50 kernel derivation -> end-to-end
    pipeline) — the ledger must say so instead of crying regression."""
    flags = _pair(
        {"replay_headers_per_sec_host": {
            "value": 200.35, "unit": None, "source": None}},
        {"replay_headers_per_sec_host": _tagged(
            23.9, "headers/s", mode="staged_sync_e2e")},
    )
    assert [f["kind"] for f in flags] == ["redefined"]


def test_param_change_is_redefinition():
    """Same source+mode but a different measurement parameter (e.g.
    BENCH_REPLAY_COMMITTEE) is a redefinition, not a speedup."""
    flags = _pair(
        {"replay_per_sec": dict(_tagged(24.0), mode="e2e",
                                params={"committee_keys": 64})},
        {"replay_per_sec": dict(_tagged(90.0), mode="e2e",
                                params={"committee_keys": 16})},
    )
    assert [f["kind"] for f in flags] == ["redefined"]


def test_source_backfill_alone_stays_comparable():
    """The r05->r06 untagged->tagged migration must NOT blind the
    gate: source None -> 'measured' with unchanged mode/params is
    still a comparison, so a genuine r06 regression flags."""
    flags = _pair(
        {"agg_p50_ms": {"value": 10.0, "source": None, "mode": None,
                        "params": {}}},
        {"agg_p50_ms": dict(_tagged(100.0, "ms"))},
    )
    assert [f["kind"] for f in flags] == ["regression"]


def test_unknown_direction_never_flags():
    flags = _pair({"mystery": _tagged(100.0)},
                  {"mystery": _tagged(1.0)})
    assert flags == []


def test_new_and_dropped_are_informational():
    flags = _pair({"old_per_sec": _tagged(1.0)},
                  {"new_per_sec": _tagged(1.0)})
    kinds = sorted(f["kind"] for f in flags)
    assert kinds == ["dropped", "new"]


# -- the committed history + CLI gate ----------------------------------------


def test_committed_bench_rounds_pass_the_check(capsys):
    """check.sh stage 6 runs --check over the committed BENCH files;
    this pins that the committed history stays regression-free under
    the default threshold."""
    rc = main(["--check"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert report["ok"] is True


def test_check_exits_nonzero_on_regression(tmp_path, capsys):
    a = tmp_path / "BENCH_r90.json"
    b = tmp_path / "BENCH_r91.json"
    a.write_text(json.dumps({"n": 90, "parsed": {
        "metric": "x_per_sec", "value": 100.0, "source": "measured"}}))
    b.write_text(json.dumps({"n": 91, "parsed": {
        "metric": "x_per_sec", "value": 10.0, "source": "measured"}}))
    rc = main([str(a), str(b), "--check"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert report["ok"] is False
    assert any(f["kind"] == "regression" for f in report["flags"])


def test_load_rounds_orders_by_round_number():
    paths = sorted(str(p) for p in ROOT.glob("BENCH_r*.json"))
    rounds = load_rounds(paths)
    assert [r[0] for r in rounds] == sorted(r[0] for r in rounds)
    assert len(rounds) >= 5
