"""hmy facade + JSON-RPC server + metrics exposition (the reference's
L7 API surface — SURVEY.md §2.6 rpc/harmony + prometheus)."""

import http.client
import json

import pytest

from harmony_tpu.core import rawdb
from harmony_tpu.core.blockchain import Blockchain
from harmony_tpu.core.genesis import dev_genesis
from harmony_tpu.core.kv import MemKV
from harmony_tpu.core.tx_pool import TxPool
from harmony_tpu.core.types import Transaction
from harmony_tpu.hmy import Harmony
from harmony_tpu.metrics import MetricsServer, Registry
from harmony_tpu.node.worker import Worker
from harmony_tpu.rpc import RPCServer

CHAIN_ID = 2


@pytest.fixture(scope="module")
def stack():
    genesis, keys, _ = dev_genesis()
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    pool = TxPool(CHAIN_ID, 0, chain.state)
    worker = Worker(chain, pool)
    to = b"\x09" * 20
    tx = Transaction(
        nonce=0, gas_price=1, gas_limit=25_000, shard_id=0, to_shard=0,
        to=to, value=5555,
    ).sign(keys[0], CHAIN_ID)
    pool.add(tx)
    block = worker.propose_block(view_id=1)
    chain.insert_chain([block], verify_seals=False)
    pool.drop_applied()
    hmy = Harmony(chain, pool)
    srv = RPCServer(hmy, port=0).start()
    yield srv, hmy, keys, to, tx
    srv.stop()


def _call(port, method, params=None, req_id=1):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request(
        "POST", "/",
        json.dumps({"jsonrpc": "2.0", "id": req_id, "method": method,
                    "params": params or []}),
        {"Content-Type": "application/json"},
    )
    resp = json.loads(conn.getresponse().read())
    conn.close()
    return resp


def test_rpc_block_and_balance(stack):
    srv, hmy, keys, to, tx = stack
    assert _call(srv.port, "hmy_blockNumber")["result"] == "0x1"
    assert _call(srv.port, "hmyv2_blockNumber")["result"] == 1
    bal = _call(srv.port, "hmyv2_getBalance", ["0x" + to.hex()])
    assert bal["result"] == 5555
    block = _call(srv.port, "hmy_getBlockByNumber", ["0x1", True])["result"]
    assert block["number"] == "0x1"
    assert len(block["transactions"]) == 1
    assert block["transactions"][0]["value"] == hex(5555)
    assert block["transactions"][0]["from"] == "0x" + keys[0].address().hex()
    by_hash = _call(srv.port, "hmy_getBlockByHash", [block["hash"]])
    assert by_hash["result"]["number"] == "0x1"
    found = _call(srv.port, "hmy_getTransactionByHash",
                  ["0x" + tx.hash(CHAIN_ID).hex()])["result"]
    assert found["blockNumber"] == "0x1"
    assert _call(srv.port, "net_version")["result"] == str(CHAIN_ID)


def test_rpc_send_raw_transaction(stack):
    srv, hmy, keys, to, _ = stack
    tx2 = Transaction(
        nonce=1, gas_price=1, gas_limit=25_000, shard_id=0, to_shard=0,
        to=to, value=1,
    ).sign(keys[0], CHAIN_ID)
    blob = rawdb.encode_tx(tx2, CHAIN_ID)
    resp = _call(srv.port, "hmy_sendRawTransaction", ["0x" + blob.hex()])
    assert resp["result"] == "0x" + tx2.hash(CHAIN_ID).hex()
    assert len(hmy.tx_pool) == 1
    # a bad signature is an error, not a silent accept
    bad = bytearray(blob)
    bad[-10] ^= 0xFF
    resp = _call(srv.port, "hmy_sendRawTransaction", ["0x" + bad.hex()])
    assert "error" in resp


def test_rpc_errors_and_committee(stack):
    srv, hmy, keys, _, _ = stack
    assert "error" in _call(srv.port, "hmy_noSuchMethod")
    assert "error" in _call(srv.port, "nonsense")
    committee = _call(srv.port, "hmy_getCommittee")["result"]
    assert len(committee) == 4
    # batch requests
    conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
    batch = [
        {"jsonrpc": "2.0", "id": i, "method": "hmy_blockNumber",
         "params": []}
        for i in range(3)
    ]
    conn.request("POST", "/", json.dumps(batch),
                 {"Content-Type": "application/json"})
    out = json.loads(conn.getresponse().read())
    conn.close()
    assert [r["result"] for r in out] == ["0x1"] * 3


def test_method_allowlist():
    genesis, _, _ = dev_genesis()
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    srv = RPCServer(Harmony(chain), port=0,
                    method_allowlist=["hmy_blockNumber"]).start()
    try:
        assert _call(srv.port, "hmy_blockNumber")["result"] == "0x0"
        assert "error" in _call(srv.port, "hmy_getCommittee")
    finally:
        srv.stop()


def test_metrics_registry_and_server():
    reg = Registry()
    c = reg.counter("consensus_rounds_total", "rounds")
    c.inc(phase="prepare")
    c.inc(phase="prepare")
    c.inc(phase="commit")
    g = reg.gauge("chain_head", "head")
    g.set(42)
    h = reg.histogram("verify_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.expose()
    assert 'consensus_rounds_total{phase="prepare"} 2' in text
    assert "chain_head 42" in text
    assert 'verify_seconds_bucket{le="0.1"} 1' in text
    assert 'verify_seconds_bucket{le="+Inf"} 3' in text
    assert "verify_seconds_count 3" in text

    srv = MetricsServer(reg, port=0).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
        conn.request("GET", "/metrics")
        body = conn.getresponse().read().decode()
        conn.close()
        assert "chain_head 42" in body
    finally:
        srv.stop()


def test_rpc_receipt_logs_filters_and_call(stack):
    """The round-3 RPC surface: receipts, getLogs, polling filters,
    eth_call/estimateGas, code/storage, debug_traceTransaction
    (reference: rpc transaction.go/contract.go + eth/filters)."""
    srv, hmy, keys, to, tx = stack
    chain = hmy.chain
    worker = Worker(chain, hmy.tx_pool)
    if len(hmy.tx_pool):  # flush txs parked by earlier tests
        block = worker.propose_block(view_id=chain.head_number + 1)
        chain.insert_chain([block], verify_seals=False)
        hmy.tx_pool.drop_applied()
    txh = "0x" + tx.hash(CHAIN_ID).hex()

    # receipt for the mined transfer (indexed lookup)
    rc = _call(srv.port, "eth_getTransactionReceipt", [txh])["result"]
    assert rc["status"] == "0x1" and rc["blockNumber"] == "0x1"
    assert rc["logs"] == []
    assert _call(srv.port, "eth_getTransactionReceipt",
                 ["0x" + "ab" * 32])["result"] is None

    # deploy a log-emitting contract through the processor
    # runtime: log1(0, 0, topic=0x77); stop
    runtime = bytes([0x60, 0x77, 0x60, 0x00, 0x60, 0x00, 0xA1, 0x00])
    init = bytes([
        0x60, len(runtime), 0x60, 0x0C, 0x60, 0x00, 0x39,
        0x60, len(runtime), 0x60, 0x00, 0xF3,
    ]) + runtime
    sender_nonce = chain.state().nonce(keys[0].address())
    deploy = Transaction(
        nonce=sender_nonce, gas_price=1, gas_limit=500_000, shard_id=0,
        to_shard=0, to=None, value=0, data=init,
    ).sign(keys[0], CHAIN_ID)
    hmy.tx_pool.add(deploy)
    block = worker.propose_block(view_id=chain.head_number + 1)
    chain.insert_chain([block], verify_seals=False)
    hmy.tx_pool.drop_applied()
    drc = _call(
        srv.port, "eth_getTransactionReceipt",
        ["0x" + deploy.hash(CHAIN_ID).hex()],
    )["result"]
    ca = drc["contractAddress"]
    assert ca is not None

    # call the contract: the log shows in the receipt AND eth_getLogs
    invoke = Transaction(
        nonce=chain.state().nonce(keys[0].address()), gas_price=1,
        gas_limit=200_000, shard_id=0, to_shard=0,
        to=bytes.fromhex(ca[2:]), value=0, data=b"",
    ).sign(keys[0], CHAIN_ID)
    hmy.tx_pool.add(invoke)
    block = worker.propose_block(view_id=chain.head_number + 1)
    chain.insert_chain([block], verify_seals=False)
    hmy.tx_pool.drop_applied()
    topic = "0x" + (0x77).to_bytes(32, "big").hex()
    logs = _call(srv.port, "eth_getLogs", [{
        "fromBlock": "0x1", "toBlock": "latest", "address": ca,
    }])["result"]
    assert len(logs) == 1 and logs[0]["topics"] == [topic]

    # polling filter sees only NEW blocks
    fid = _call(srv.port, "eth_newBlockFilter")["result"]
    assert _call(srv.port, "eth_getFilterChanges", [fid])["result"] == []
    block = worker.propose_block(view_id=chain.head_number + 1)
    chain.insert_chain([block], verify_seals=False)
    changes = _call(srv.port, "eth_getFilterChanges", [fid])["result"]
    assert changes == ["0x" + block.hash().hex()]
    assert _call(srv.port, "eth_uninstallFilter", [fid])["result"] is True

    # eth_call reads state without mutating it; estimateGas bounds it
    out = _call(srv.port, "eth_call", [{
        "from": "0x" + keys[0].address().hex(), "to": ca, "data": "0x",
    }])["result"]
    assert out == "0x"
    est = _call(srv.port, "eth_estimateGas", [{
        "from": "0x" + keys[0].address().hex(), "to": ca,
    }])["result"]
    assert 21000 <= int(est, 16) < 60_000

    # code/storage reads + call tracer (geth semantics: callTracer is
    # an explicit option; the bare call returns structLogs)
    code = _call(srv.port, "eth_getCode", [ca])["result"]
    assert code == "0x" + runtime.hex()
    trace = _call(
        srv.port, "debug_traceTransaction",
        ["0x" + invoke.hash(CHAIN_ID).hex(), {"tracer": "callTracer"}],
    )["result"]
    assert trace["type"] == "CALL" and trace["to"] == ca[2:].lower()


def test_rpc_staking_reads(stack):
    """Delegation/election/median-stake reads (reference: rpc
    staking.go GetDelegationsBy*/GetElectedValidatorAddresses/
    GetMedianRawStakeSnapshot)."""
    from harmony_tpu.core.state import Delegation, ValidatorWrapper

    srv, hmy, keys, to, tx = stack
    state = hmy.chain.state()
    vaddr = b"\x61" * 20
    delegator = b"\x62" * 20
    state.set_validator(ValidatorWrapper(
        address=vaddr, bls_keys=[b"\x07" * 48],
        delegations=[Delegation(vaddr, 1000),
                     Delegation(delegator, 250, reward=9)],
    ))
    out = _call(
        srv.port, "hmy_getDelegationsByDelegator",
        ["0x" + delegator.hex()],
    )["result"]
    assert len(out) == 1
    assert out[0]["validator_address"] == "0x" + vaddr.hex()
    assert out[0]["amount"] == 250 and out[0]["reward"] == 9
    out = _call(
        srv.port, "hmy_getDelegationsByValidator", ["0x" + vaddr.hex()],
    )["result"]
    assert {d["delegator_address"] for d in out} == {
        "0x" + vaddr.hex(), "0x" + delegator.hex(),
    }
    snap = _call(srv.port, "hmy_getMedianRawStakeSnapshot")["result"]
    assert snap["slot_count"] == 1
    assert int(float(snap["median_raw_stake"])) > 0
    # no election recorded yet in this dev chain
    assert _call(
        srv.port, "hmy_getElectedValidatorAddresses"
    )["result"] == []


def test_pprof_service_profiles():
    """reference: api/service/pprof — live profiling endpoint
    (goroutine==thread dump, sampling CPU profile, heap, threadz)."""
    import http.client
    import threading
    import time

    from harmony_tpu.pprof import PprofServer

    stop = threading.Event()

    def busy():
        while not stop.is_set():
            sum(i * i for i in range(2000))

    t = threading.Thread(target=busy, name="busy-loop", daemon=True)
    t.start()
    srv = PprofServer().start()
    try:
        def get(path):
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=30)
            conn.request("GET", path)
            resp = conn.getresponse()
            out = (resp.status, resp.read().decode())
            conn.close()
            return out

        status, idx = get("/debug/pprof/")
        assert status == 200 and "goroutine" in idx
        status, dump = get("/debug/pprof/goroutine")
        assert status == 200 and "busy" in dump
        status, prof = get("/debug/pprof/profile?seconds=0.5")
        assert status == 200
        assert "busy@" in prof  # the hot loop dominates the samples
        status, tz = get("/debug/pprof/threadz")
        assert status == 200 and "busy-loop" in tz
        status, heap1 = get("/debug/pprof/heap")
        assert status == 200  # first call arms tracemalloc
        blobs = [bytearray(3000) for _ in range(50)]
        status, heap2 = get("/debug/pprof/heap")
        assert status == 200 and "size=" in heap2
        del blobs
        import tracemalloc

        tracemalloc.stop()
    finally:
        stop.set()
        srv.stop()


def test_eth_get_proof(stack):
    """eth_getProof: the returned account + storage proofs verify
    against the returned state root with core/trie.verify_proof."""
    from harmony_tpu import rlp
    from harmony_tpu.core.trie import verify_proof
    from harmony_tpu.ref.keccak import keccak256

    srv, hmy, keys, to, _ = stack
    addr_hex = "0x" + to.hex()
    resp = _call(srv.port, "eth_getProof", [addr_hex, [], "latest"])
    got = resp["result"]
    # the module fixture accumulates transfers to `to` across tests:
    # pin to the LIVE balance, and require the proof leaf to match it
    live = hmy.get_balance(to)
    assert live >= 5555 and int(got["balance"], 16) == live
    root = bytes.fromhex(got["stateRoot"][2:])
    proof = [bytes.fromhex(n[2:]) for n in got["accountProof"]]
    leaf = verify_proof(root, keccak256(to), proof)
    fields = rlp.decode(leaf)
    assert rlp.decode_int(fields[1]) == live
    # absent account: exclusion proof against the same root
    resp = _call(srv.port, "eth_getProof", ["0x" + "ef" * 20, []])
    got = resp["result"]
    assert int(got["balance"], 16) == 0
    proof = [bytes.fromhex(n[2:]) for n in got["accountProof"]]
    assert verify_proof(
        bytes.fromhex(got["stateRoot"][2:]),
        keccak256(b"\xef" * 20), proof,
    ) == b""


def test_debug_tracers_structlog_and_prestate(stack):
    """debug_traceTransaction tracer options (reference: eth/tracers):
    default = geth structLogs; prestateTracer = touched accounts and
    slots as they were before the tx; callTracer unchanged."""
    srv, hmy, keys, to, _ = stack
    chain = hmy.chain
    worker = Worker(chain, hmy.tx_pool)
    # a contract that writes storage: sstore(key=5, value=7); stop
    runtime = bytes([0x60, 0x07, 0x60, 0x05, 0x55, 0x00])
    init = bytes([
        0x60, len(runtime), 0x60, 0x0C, 0x60, 0x00, 0x39,
        0x60, len(runtime), 0x60, 0x00, 0xF3,
    ]) + runtime
    deploy = Transaction(
        nonce=chain.state().nonce(keys[0].address()), gas_price=1,
        gas_limit=500_000, shard_id=0, to_shard=0, to=None, value=0,
        data=init,
    ).sign(keys[0], CHAIN_ID)
    hmy.tx_pool.add(deploy)
    block = worker.propose_block(view_id=chain.head_number + 1)
    chain.insert_chain([block], verify_seals=False)
    hmy.tx_pool.drop_applied()
    rc = _call(srv.port, "eth_getTransactionReceipt",
               ["0x" + deploy.hash(CHAIN_ID).hex()])["result"]
    ca = rc["contractAddress"]
    invoke = Transaction(
        nonce=chain.state().nonce(keys[0].address()), gas_price=1,
        gas_limit=200_000, shard_id=0, to_shard=0,
        to=bytes.fromhex(ca[2:]), value=0,
    ).sign(keys[0], CHAIN_ID)
    hmy.tx_pool.add(invoke)
    block = worker.propose_block(view_id=chain.head_number + 1)
    chain.insert_chain([block], verify_seals=False)
    hmy.tx_pool.drop_applied()
    txh = "0x" + invoke.hash(CHAIN_ID).hex()

    # default: geth-shaped structLogs, opcode names + 1-based depth;
    # the traced gas must AGREE with the mined receipt
    rc2 = _call(srv.port, "eth_getTransactionReceipt", [txh])["result"]
    got = _call(srv.port, "debug_traceTransaction", [txh])["result"]
    assert not got["failed"]
    assert got["gas"] == int(rc2["gasUsed"], 16)
    ops = [l["op"] for l in got["structLogs"]]
    assert ops == ["PUSH1", "PUSH1", "SSTORE", "STOP"]
    assert got["structLogs"][0]["depth"] == 1
    assert got["structLogs"][2]["stack"] == ["0x7", "0x5"]

    # prestateTracer: the slot's PRE value (0) and the sender's
    # PRE-transaction nonce (not the replay's bumped one)
    pre = _call(srv.port, "debug_traceTransaction",
                [txh, {"tracer": "prestateTracer"}])["result"]
    slot_key = "0x" + (5).to_bytes(32, "big").hex()
    assert pre[ca]["storage"][slot_key] == "0x0"
    sender_pre = pre["0x" + keys[0].address().hex()]
    assert int(sender_pre["balance"], 16) > 0
    assert sender_pre["nonce"] == invoke.nonce

    # callTracer still answers
    ct = _call(srv.port, "debug_traceTransaction",
               [txh, {"tracer": "callTracer"}])["result"]
    assert ct["type"] == "CALL"
    assert ct["to"] in (ca[2:].lower(), ca[2:])

    # the named profiling tracers the reference serves through its JS
    # engine (hmy/tracers), implemented natively (VERDICT r4 missing
    # #6): opcount, unigram/bigram, noop, 4byte
    oc = _call(srv.port, "debug_traceTransaction",
               [txh, {"tracer": "opcountTracer"}])["result"]
    assert oc == 4  # PUSH1 PUSH1 SSTORE STOP
    uni = _call(srv.port, "debug_traceTransaction",
                [txh, {"tracer": "unigramTracer"}])["result"]
    assert uni == {"PUSH1": 2, "SSTORE": 1, "STOP": 1}
    bi = _call(srv.port, "debug_traceTransaction",
               [txh, {"tracer": "bigramTracer"}])["result"]
    assert bi["PUSH1-PUSH1"] == 1 and bi["SSTORE-STOP"] == 1
    assert _call(srv.port, "debug_traceTransaction",
                 [txh, {"tracer": "noopTracer"}])["result"] == {}
    # 4byteTracer keys selector-argsize over call inputs; a call with
    # >=4 bytes of calldata registers
    probe = Transaction(
        nonce=chain.state().nonce(keys[0].address()), gas_price=1,
        gas_limit=200_000, shard_id=0, to_shard=0,
        to=bytes.fromhex(ca[2:]), value=0,
        data=bytes.fromhex("a9059cbb") + bytes(64),
    ).sign(keys[0], CHAIN_ID)
    hmy.tx_pool.add(probe)
    block = worker.propose_block(view_id=chain.head_number + 1)
    chain.insert_chain([block], verify_seals=False)
    hmy.tx_pool.drop_applied()
    fb = _call(srv.port, "debug_traceTransaction",
               ["0x" + probe.hash(CHAIN_ID).hex(),
                {"tracer": "4byteTracer"}])["result"]
    assert fb == {"0xa9059cbb-64": 1}
    # unknown tracer is an error
    assert "error" in _call(srv.port, "debug_traceTransaction",
                            [txh, {"tracer": "bogusTracer"}])


def test_pending_transactions_and_trace_block(stack):
    """hmy_pendingTransactions + debug_traceBlockByNumber (reference:
    rpc/transaction.go PendingTransactions, eth/tracers block API)."""
    srv, hmy, keys, to, _ = stack
    nonce = hmy.chain.state().nonce(keys[0].address())
    tx = Transaction(
        nonce=nonce, gas_price=1, gas_limit=25_000, shard_id=0,
        to_shard=0, to=to, value=9,
    ).sign(keys[0], CHAIN_ID)
    hmy.send_raw_transaction(rawdb.encode_tx(tx, CHAIN_ID))
    pend = _call(srv.port, "hmy_pendingTransactions")["result"]
    mine = [p for p in pend
            if p["hash"] == "0x" + tx.hash(CHAIN_ID).hex()]
    assert mine and mine[0]["blockNumber"] is None  # unmined = null
    assert _call(srv.port,
                 "hmy_pendingStakingTransactions")["result"] == []
    # drain so later fixture users see a clean pool
    block = Worker(hmy.chain, hmy.tx_pool).propose_block(
        view_id=hmy.chain.head_number + 1
    )
    hmy.chain.insert_chain([block], verify_seals=False)
    hmy.tx_pool.drop_applied()

    traced = _call(srv.port, "debug_traceBlockByNumber",
                   ["0x1", {"tracer": "callTracer"}])["result"]
    assert len(traced) == 1
    assert traced[0]["result"]["type"] == "CALL"
    assert _call(srv.port, "debug_traceBlockByNumber",
                 ["0x7f"])["result"] is None
