"""TPU pairing + BLS op-surface tests vs the bigint reference.

These carry the heaviest one-time XLA:CPU compiles in the suite (cached in
.jax_cache; shapes here deliberately match across tests to share cache
entries).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from harmony_tpu.ops import bls as OB
from harmony_tpu.ops import interop as I
from harmony_tpu.ops import pairing as OP
from harmony_tpu.ref import bls as RB
from harmony_tpu.ref import pairing as RP
from harmony_tpu.ref.curve import G1_GEN, G2_GEN, g1, g2
from harmony_tpu.ref.hash_to_curve import hash_to_g2, map_to_twist

MSG = b"0123456789abcdef0123456789abcdef"


def _g1_aff(p):
    return np.stack([I.fp_to_arr(p[0]), I.fp_to_arr(p[1])])


def _g2_aff(q):
    return np.stack([I.fp2_to_arr(q[0]), I.fp2_to_arr(q[1])])


@pytest.fixture(scope="module")
def keys():
    sks = [RB.keygen(bytes([i])) for i in range(4)]
    pks = [RB.pubkey(sk) for sk in sks]
    sigs = [RB.sign(sk, MSG) for sk in sks]
    return sks, pks, sigs


@pytest.fixture(scope="module")
def h_point():
    return hash_to_g2(MSG)


def test_miller_loop_matches_bigint_twin():
    ps = [G1_GEN, g1.mul(G1_GEN, 123456789)]
    qs = [G2_GEN, g2.mul(G2_GEN, 987654321)]
    p_arr = jnp.asarray(np.stack([_g1_aff(p) for p in ps]))
    q_arr = jnp.asarray(np.stack([_g2_aff(q) for q in qs]))
    f = OP.miller_loop(p_arr, q_arr)
    for i in range(2):
        assert I.arr_to_fp12(np.array(f[i])) == RP.miller_loop_projective(
            ps[i], qs[i]
        )


def test_pairing_matches_reference_gt():
    ps = [G1_GEN, g1.mul(G1_GEN, 123456789)]
    qs = [G2_GEN, g2.mul(G2_GEN, 987654321)]
    p_arr = jnp.asarray(np.stack([_g1_aff(p) for p in ps]))
    q_arr = jnp.asarray(np.stack([_g2_aff(q) for q in qs]))
    e = OP.pairing(p_arr, q_arr)
    for i in range(2):
        assert I.arr_to_fp12(np.array(e[i])) == RP.pairing(ps[i], qs[i])


def test_pairing_product_cancellation():
    # e(-G1, 2 G2) * e(2 G1, G2) == 1
    pp = [g1.neg(G1_GEN), g1.dbl(G1_GEN)]
    qq = [g2.dbl(G2_GEN), G2_GEN]
    p_arr = jnp.asarray(np.stack([_g1_aff(p) for p in pp]))
    q_arr = jnp.asarray(np.stack([_g2_aff(q) for q in qq]))
    assert bool(OP.is_one(OP.pairing_product(p_arr, q_arr)))


def test_bls_verify_batch(keys, h_point):
    _, pks, sigs = keys
    pk = jnp.asarray(np.stack([_g1_aff(p) for p in pks]))
    sg = jnp.asarray(np.stack([_g2_aff(s) for s in sigs]))
    hh = jnp.broadcast_to(jnp.asarray(_g2_aff(h_point)), (4, 2, 2, 32))
    ok = OB.verify(pk, hh, sg)
    assert all(np.array(ok))
    bad = OB.verify(pk, hh, jnp.roll(sg, 1, axis=0))
    assert not any(np.array(bad))


def test_bls_agg_verify_bitmap(keys, h_point):
    _, pks, sigs = keys
    pk = jnp.asarray(np.stack([_g1_aff(p) for p in pks]))
    h_arr = jnp.asarray(_g2_aff(h_point))
    agg = RB.aggregate_sigs([sigs[0], sigs[2], sigs[3]])
    ag = jnp.asarray(_g2_aff(agg))
    assert bool(OB.agg_verify(pk, jnp.asarray([1, 0, 1, 1]), h_arr, ag))
    assert not bool(OB.agg_verify(pk, jnp.asarray([1, 1, 1, 1]), h_arr, ag))


def test_device_sign_matches_reference(keys, h_point):
    sks, _, sigs = keys
    skb = jnp.asarray(OB.sk_to_bits(sks[:2]))
    h_jac = jnp.asarray(
        np.stack([I.g2_affine_to_jacobian_arr(h_point)] * 2)
    )
    out = OB.sign(h_jac, skb)
    for i in range(2):
        assert I.arr_to_g2_affine(np.array(out[i])) == sigs[i]


def test_device_pubkey_derivation(keys):
    sks, pks, _ = keys
    skb = jnp.asarray(OB.sk_to_bits(sks[:2]))
    out = OB.derive_pubkeys(skb)
    for i in range(2):
        assert I.arr_to_g1_affine(np.array(out[i])) == pks[i]


def test_device_cofactor_clearing(h_point):
    tw = map_to_twist(MSG)
    arr = jnp.asarray(np.stack([I.g2_affine_to_jacobian_arr(tw)]))
    out = OB.clear_cofactor_g2(arr)
    assert I.arr_to_g2_affine(np.array(out[0])) == h_point
