"""Pallas Montgomery-kernel tests (interpret mode on CPU; the same kernel
runs compiled on TPU)."""

import random

import jax.numpy as jnp
import numpy as np

from harmony_tpu.ops.fp_pallas import mont_mul_pallas
from harmony_tpu.ops.limbs import ints_to_limbs, limbs_to_int
from harmony_tpu.ref.params import P

rng = random.Random(0x9A)
R = 1 << 384


def test_matches_bigint_with_padding():
    xs = [rng.randrange(P) for _ in range(150)]  # not a multiple of 128
    ys = [rng.randrange(P) for _ in range(150)]
    a = jnp.asarray(ints_to_limbs([x * R % P for x in xs]))
    b = jnp.asarray(ints_to_limbs([y * R % P for y in ys]))
    out = mont_mul_pallas(a, b, interpret=True)
    for i in range(150):
        assert limbs_to_int(np.array(out[i])) == xs[i] * ys[i] * R % P


def test_worst_case_carries():
    w = jnp.asarray(ints_to_limbs([(P - 1) * R % P] * 4))
    out = mont_mul_pallas(w, w, interpret=True)
    for i in range(4):
        assert limbs_to_int(np.array(out[i])) == (P - 1) * (P - 1) * R % P


def test_nd_leading_shape():
    xs = [rng.randrange(P) for _ in range(72)]
    a = jnp.asarray(ints_to_limbs([x * R % P for x in xs])).reshape(2, 36, 32)
    out = mont_mul_pallas(a, a, interpret=True)
    flat = out.reshape(72, 32)
    for i in range(72):
        assert limbs_to_int(np.array(flat[i])) == xs[i] * xs[i] * R % P
