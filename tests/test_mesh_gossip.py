"""Degree-bounded mesh gossip + routed discovery (VERDICT r4 #5).

The TCP transport now runs a gossipsub-shaped protocol — per-topic
meshes capped at MESH_D_HI with lazy IHAVE/IWANT pull for everyone
else — so per-node egress stays bounded as the peer set grows, and
discovery keeps a Kademlia k-bucket table with routed closest-first
lookups (reference: p2p/host.go:73-99 gossipsub,
p2p/discovery/discovery.go:41-79 DHT)."""

import time

import pytest

from harmony_tpu.p2p.discovery import Discovery, RoutingTable
from harmony_tpu.p2p.gating import Gater
from harmony_tpu.p2p.host import TCPHost
from harmony_tpu.ref.keccak import keccak256


def _host(name):
    """Every peer shares 127.0.0.1 in these topologies: lift the
    per-IP gate (production keeps the default 8)."""
    return TCPHost(name, gater=Gater(max_peers=128, max_per_ip=128))


def _close_all(hosts):
    for h in hosts:
        h.close()


def _wait(pred, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


def test_mesh_bounded_egress_16_nodes():
    """16 fully-connected nodes, one topic: every node receives every
    message, but no node's eager egress exceeds the mesh bound —
    the flood transport sent to ALL 15 peers, the mesh sends to at
    most MESH_D_HI."""
    n = 16
    hosts = [_host(f"m{i}") for i in range(n)]
    try:
        got = [[] for _ in range(n)]
        for i, h in enumerate(hosts):
            h.subscribe("t", lambda t, p, f, i=i: got[i].append(p))
        # full clique so every mesh has plenty of candidates
        for i in range(n):
            for j in range(i + 1, n):
                hosts[i].connect(hosts[j].port)
        assert all(h.wait_for_peers(n - 1, timeout=30) for h in hosts)
        msgs = 6
        for k in range(msgs):
            hosts[0].publish("t", b"msg-%d" % k)
            time.sleep(0.1)
        assert _wait(
            lambda: all(len(g) == msgs for g in got[1:]), timeout=30
        ), [len(g) for g in got]
        cap = hosts[0].MESH_D_HI
        for h in hosts:
            # eager pushes + IWANT serves, per message relayed
            assert h.sent_publish_frames <= msgs * (cap + 4), (
                h.name, h.sent_publish_frames
            )
        total = sum(h.sent_publish_frames for h in hosts)
        flood_total = msgs * n * (n - 1)  # what the flood hub would send
        assert total < flood_total / 2, (total, flood_total)
    finally:
        _close_all(hosts)


def test_mesh_partition_heal():
    """A message published while two islands are disconnected reaches
    the other side after ONE bridge link appears: the bridge peer
    learns the id from the heartbeat's IHAVE digest and pulls the full
    message (gossipsub's healing property — floods only ever pushed)."""
    a = [_host(f"a{i}") for i in range(3)]
    b = [_host(f"b{i}") for i in range(3)]
    try:
        got_b = [[] for _ in b]
        for h in a:
            h.subscribe("t", lambda t, p, f: None)
        for i, h in enumerate(b):
            h.subscribe("t", lambda t, p, f, i=i: got_b[i].append(p))
        for grp in (a, b):
            for i in range(len(grp)):
                for j in range(i + 1, len(grp)):
                    grp[i].connect(grp[j].port)
        assert all(h.wait_for_peers(2) for h in a + b)
        # published while partitioned: island B sees nothing
        a[0].publish("t", b"island-msg")
        time.sleep(1.0)
        assert all(not g for g in got_b)
        # ONE bridge link heals the partition
        a[1].connect(b[1].port)
        assert _wait(
            lambda: all(g == [b"island-msg"] for g in got_b), timeout=25
        ), got_b
    finally:
        _close_all(a + b)


def test_late_subscriber_joins_mesh():
    """A peer that subscribes AFTER connecting is grafted in by the
    heartbeat and receives subsequent messages."""
    h1, h2 = _host("h1"), _host("h2")
    try:
        h1.subscribe("t", lambda t, p, f: None)
        h2.connect(h1.port)
        assert h1.wait_for_peers(1) and h2.wait_for_peers(1)
        got = []
        h2.subscribe("t", lambda t, p, f: got.append(p))
        time.sleep(0.2)
        h1.publish("t", b"late")
        assert _wait(lambda: got == [b"late"]), got
    finally:
        _close_all([h1, h2])


def test_iwant_service_is_capped():
    """An IWANT flood cannot amplify: at most IWANT_MAX messages are
    served per request frame."""
    h = _host("s")
    try:
        mids = []
        for k in range(h.IWANT_MAX + 20):
            body = h._pack_publish("t", b"m%d" % k)
            mid = keccak256(body)
            h._mcache.put(mid, "t", body)
            mids.append(mid)

        sent = []

        class _Sock:
            pass

        h._send_frame = lambda sock, kind, payload: sent.append(kind)
        h._on_iwant(_Sock(), b"".join(mids))
        assert len(sent) == h.IWANT_MAX
    finally:
        h.close()


# --- routed discovery ------------------------------------------------------

def test_routing_table_buckets_and_eviction():
    rt = RoutingTable("127.0.0.1:1000")
    addrs = [f"10.0.0.{i}:9{i:03d}" for i in range(1, 200)]
    for a in addrs:
        rt.add(a)
    assert len(rt) <= 256 * RoutingTable.K
    # closest() really sorts by XOR distance to the target
    target = keccak256(b"somewhere")
    out = rt.closest(target, k=10)
    t = int.from_bytes(target, "big")

    def dist(a):
        return int.from_bytes(keccak256(a.encode()), "big") ^ t

    assert out == sorted(out, key=dist)
    assert len(out) == 10
    # re-adding moves to bucket tail, remove() drops
    rt.add(addrs[0])
    rt.remove(addrs[0])
    assert addrs[0] not in rt.closest(keccak256(addrs[0].encode()), k=500)


def test_targeted_peers_req_returns_closest():
    """The PEERS_REQ routing contract: with a 32-byte target the
    responder serves its closest-K known addresses."""
    serving, client = _host("srv"), _host("cli")
    try:
        now = time.monotonic()
        with serving._peer_lock:
            for i in range(60):
                serving._remember_addr(f"10.1.0.{i}:7000", now)
        client.connect(serving.port)
        assert client.wait_for_peers(1) and serving.wait_for_peers(1)
        target = keccak256(b"lookup-target")
        client.request_peers(target)
        assert _wait(lambda: len(client.known_addrs) >= 16)
        t = int.from_bytes(target, "big")
        candidates = [f"10.1.0.{i}:7000" for i in range(60)]
        candidates.sort(
            key=lambda a: int.from_bytes(keccak256(a.encode()), "big") ^ t
        )
        learned = set(client.known_addrs)
        # the 10 globally-closest candidates must all have been served
        assert all(c in learned for c in candidates[:10])
    finally:
        _close_all([serving, client])


def test_discovery_converges_via_routing():
    """A newcomer reaches its peer target through routed lookups from
    one bootnode in a 10-node network."""
    hosts = [_host(f"d{i}") for i in range(10)]
    try:
        # everyone knows the bootnode (hosts[0])
        for h in hosts[1:9]:
            h.connect(hosts[0].port)
        assert hosts[0].wait_for_peers(8)
        discos = [
            Discovery(h, bootnodes=[f"127.0.0.1:{hosts[0].port}"],
                      target_peers=4)
            for h in hosts[1:]
        ]
        # drive rounds synchronously (no background threads in tests)
        for _ in range(6):
            for d in discos:
                d.step()
            time.sleep(0.3)
        newcomer = discos[-1]
        assert newcomer.host.peer_count() >= 4
        assert len(newcomer.table) >= 4
    finally:
        _close_all(hosts)
