"""BLS-VRF tests."""

import pytest

from harmony_tpu import crypto_vrf as VRF
from harmony_tpu.bls import PrivateKey


def test_evaluate_verify_roundtrip():
    sk = PrivateKey.generate(b"\x42")
    msg = b"epoch randomness seed...........x"
    out, proof = VRF.evaluate(sk, msg)
    assert len(out) == VRF.VRF_OUTPUT_BYTES and len(proof) == 96
    assert VRF.verify(sk.pub, msg, proof) == out
    # deterministic
    out2, proof2 = VRF.evaluate(sk, msg)
    assert (out2, proof2) == (out, proof)


def test_verify_rejects_wrong_inputs():
    sk = PrivateKey.generate(b"\x42")
    other = PrivateKey.generate(b"\x43")
    msg = b"epoch randomness seed...........x"
    _, proof = VRF.evaluate(sk, msg)
    with pytest.raises(ValueError):
        VRF.verify(other.pub, msg, proof)
    with pytest.raises(ValueError):
        VRF.verify(sk.pub, b"different message...............", proof)
    with pytest.raises(ValueError):
        VRF.proof_to_hash(b"short")
    # distinct keys -> distinct outputs for the same message
    out_a, _ = VRF.evaluate(sk, msg)
    out_b, _ = VRF.evaluate(other, msg)
    assert out_a != out_b
