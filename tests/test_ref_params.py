"""Parameter-derivation and curve-structure tests for the reference layer.

Everything in harmony_tpu.ref.params is derived from the BLS parameter x;
these tests re-check the derivations and the published-constant
cross-checks that anchor them.
"""

import math

from harmony_tpu.ref import fields as F
from harmony_tpu.ref import params
from harmony_tpu.ref.curve import (
    G1_GEN,
    G2_GEN,
    clear_cofactor_g1,
    clear_cofactor_g2,
    e12,
    g1,
    g1_embed,
    g2,
    untwist,
)


def test_field_sizes():
    assert params.P.bit_length() == 381
    assert params.R_ORDER.bit_length() == 255
    assert params.P % 4 == 3


def test_r_divides_curve_order():
    assert (params.P + 1 - params.TRACE) % params.R_ORDER == 0
    assert (params.P + 1 - params.TRACE) // params.R_ORDER == params.H1


def test_cm_discriminant():
    # D = -3: t^2 - 4p = -3 f^2 for integer f
    d = 4 * params.P - params.TRACE**2
    assert d % 3 == 0
    f = math.isqrt(d // 3)
    assert f * f == d // 3


def test_known_cofactors():
    # independently published values (sanity anchor for the derivation)
    assert params.H1 == 0x396C8C005555E1568C00AAAB0000AAAB
    assert params.H2 % 2 == 1
    assert params.H2.bit_length() == 507


def test_generators_on_curve_and_order():
    assert g1.is_on_curve(G1_GEN)
    assert g2.is_on_curve(G2_GEN)
    assert g1.mul(G1_GEN, params.R_ORDER) is None
    assert g2.mul(G2_GEN, params.R_ORDER) is None


def test_cofactor_clearing_lands_in_subgroup():
    # a twist point NOT in the r-torsion: x from a fixed non-hash search
    x = (5, 0)
    while True:
        rhs = F.fp2_add(F.fp2_mul(F.fp2_sqr(x), x), g2.b)
        y = F.fp2_sqrt(rhs)
        if y is not None:
            break
        x = (x[0] + 1, 0)
    pt = (x, y)
    assert g2.is_on_curve(pt)
    cleared = clear_cofactor_g2(pt)
    assert cleared is not None
    assert g2.mul(cleared, params.R_ORDER) is None

    x1 = 7
    while True:
        y1 = F.fp_sqrt((x1 * x1 % params.P * x1 + 4) % params.P)
        if y1 is not None:
            break
        x1 += 1
    p1 = (x1, y1)
    cleared1 = clear_cofactor_g1(p1)
    assert cleared1 is not None
    assert g1.mul(cleared1, params.R_ORDER) is None


def test_untwist_embed_land_on_e12():
    assert e12.is_on_curve(untwist(G2_GEN))
    assert e12.is_on_curve(g1_embed(G1_GEN))


def test_group_law_basics():
    p2 = g1.dbl(G1_GEN)
    assert g1.add(G1_GEN, G1_GEN) == p2
    assert g1.add(p2, g1.neg(G1_GEN)) == G1_GEN
    assert g1.add(G1_GEN, g1.neg(G1_GEN)) is None
    assert g1.add(None, G1_GEN) == G1_GEN
    assert g1.mul(G1_GEN, 6) == g1.dbl(g1.add(p2, G1_GEN))
