"""Multi-chip sharding validation: run dryrun_multichip in a subprocess
with 8 virtual CPU devices (see conftest.py for why not in-process).

CPU-virtualized dryruns compile the SHARDED collective half and decide
the pairing with the bigint reference (see __graft_entry__ docstring —
measured 253 s from scratch on the 1-core CI box), so the budget here
is the driver-shaped 600 s, not the old 3600 s.
"""

import os
import pathlib
import subprocess
import sys


def test_dryrun_multichip_8_devices():
    root = pathlib.Path(__file__).parent.parent
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    # drop the axon sitecustomize so jax_platforms isn't forced back
    env["PYTHONPATH"] = str(root)
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8)"],
        cwd=root,
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dryrun_multichip OK" in proc.stdout
