"""Multi-chip sharding validation: run dryrun_multichip in a subprocess
with 8 virtual CPU devices (see conftest.py for why not in-process).

This compiles the full sharded quorum-check step (shard_map masked
aggregation with an all_gather + data-parallel verify) from scratch each
run, so it is the slowest test in the suite; skip with
-k 'not multichip' when iterating elsewhere.
"""

import os
import pathlib
import subprocess
import sys


def test_dryrun_multichip_8_devices():
    root = pathlib.Path(__file__).parent.parent
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    # drop the axon sitecustomize so jax_platforms isn't forced back
    env["PYTHONPATH"] = str(root)
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8)"],
        cwd=root,
        env=env,
        capture_output=True,
        text=True,
        timeout=3600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dryrun_multichip OK" in proc.stdout
