"""Peer discovery: PEX over the TCP host + bootnode bootstrap
(reference: p2p/discovery/discovery.go Advertise/FindPeers,
cmd/bootnode/main.go — VERDICT r2 missing #4)."""

import time

from harmony_tpu.p2p.discovery import Discovery, run_bootnode
from harmony_tpu.p2p.host import TCPHost


def _wait(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


def test_advert_and_pex_pull():
    a = TCPHost(name="a")
    b = TCPHost(name="b")
    try:
        a.connect(b.port)
        assert _wait(lambda: a.peer_count() == 1 and b.peer_count() == 1)
        # both ends ADVERT their dialable address on connect
        assert _wait(lambda: f"127.0.0.1:{b.port}" in a.known_addrs)
        assert _wait(lambda: f"127.0.0.1:{a.port}" in b.known_addrs)
        # a third host tells b about itself, then a PEX pull spreads it
        c = TCPHost(name="c")
        try:
            c.connect(b.port)
            assert _wait(lambda: f"127.0.0.1:{c.port}" in b.known_addrs)
            a.request_peers()
            assert _wait(lambda: f"127.0.0.1:{c.port}" in a.known_addrs)
        finally:
            c.close()
    finally:
        a.close()
        b.close()


def test_localnet_bootstraps_from_one_bootnode():
    """Three hosts, ZERO static peers: everyone finds everyone through
    the bootnode + PEX (the VERDICT r2 'done' criterion)."""
    boot = run_bootnode(port=0)
    baddr = f"127.0.0.1:{boot.port}"
    hosts = [TCPHost(name=f"n{i}") for i in range(3)]
    discos = [
        Discovery(h, bootnodes=[baddr], target_peers=3, interval=0.2)
        for h in hosts
    ]
    try:
        for d in discos:
            d.start()
        # each node should reach the bootnode + both siblings
        ok = _wait(
            lambda: all(h.peer_count() >= 3 for h in hosts), timeout=20
        )
        assert ok, [h.peer_count() for h in hosts]
        # gossip actually flows across discovered links: n0 publishes,
        # n1/n2 deliver
        got = []
        for h in hosts[1:]:
            h.subscribe("t", lambda t, p, f: got.append(p))
        hosts[0].publish("t", b"hello-pex")
        assert _wait(lambda: got.count(b"hello-pex") >= 2)
    finally:
        for d in discos:
            d.stop()
        for h in hosts:
            h.close()
        boot.close()


def test_discovery_stops_dialing_at_target():
    boot = run_bootnode(port=0)
    h = TCPHost(name="solo")
    d = Discovery(h, bootnodes=[f"127.0.0.1:{boot.port}"],
                  target_peers=1, interval=0.2)
    try:
        d.step()
        assert _wait(lambda: h.peer_count() >= 1)
        dials_after_connect = d.dials
        d.step()
        d.step()
        assert d.dials == dials_after_connect  # target met: no more dials
    finally:
        d.stop()
        h.close()
        boot.close()
