"""eth-keystore V3 + extended ABI (tuples/events/errors) interop
(VERDICT r4 missing #5)."""

import json

import pytest

from harmony_tpu.accounts import abi
from harmony_tpu.accounts import keystore_v3 as KS

# The Web3 Secret Storage Definition's canonical test vectors
# (password "testpassword", secret 7a28...fe9d) — cross-implementation
# ground truth for the V3 format.
_SECRET = bytes.fromhex(
    "7a28b5ba57c53603b0b07b56bba752f7784bf506fa95edc395f5cf6c7514fe9d"
)

_PBKDF2_VECTOR = {
    "crypto": {
        "cipher": "aes-128-ctr",
        "cipherparams": {"iv": "6087dab2f9fdbbfaddc31a909735c1e6"},
        "ciphertext": (
            "5318b4d5bcd28de64ee5559e671353e16f075ecae9f99c7a79a38af5f869aa46"
        ),
        "kdf": "pbkdf2",
        "kdfparams": {
            "c": 262144, "dklen": 32, "prf": "hmac-sha256",
            "salt": (
                "ae3cd4e7013836a3df6bd7241b12db061dbe2c6785853cce422d148a62"
                "4ce0bd"
            ),
        },
        "mac": (
            "517ead924a9d0dc3124507e3393d175ce3ff7c1e96529c6c555ce9e51205e9b2"
        ),
    },
    "id": "3198bc9c-6672-5ab3-d995-4942343ae5b6",
    "version": 3,
}

_SCRYPT_VECTOR = {
    "crypto": {
        "cipher": "aes-128-ctr",
        "cipherparams": {"iv": "83dbcc02d8ccb40e466191a123791e0e"},
        "ciphertext": (
            "d172bf743a674da9cdad04534d56926ef8358534d458fffccd4e6ad2fbde479c"
        ),
        "kdf": "scrypt",
        "kdfparams": {
            "dklen": 32, "n": 262144, "r": 1, "p": 8,
            "salt": (
                "ab0c7876052600dd703518d6fc3fe8984592145b591fc8fb5c6d43190334"
                "ba19"
            ),
        },
        "mac": (
            "2103ac29920d71da29f15d75b4a16dbe95cfd7ff8faea1056c33131d846e3097"
        ),
    },
    "id": "3198bc9c-6672-5ab3-d995-4942343ae5b6",
    "version": 3,
}


def test_pbkdf2_spec_vector():
    assert KS.decrypt(_PBKDF2_VECTOR, "testpassword") == _SECRET


def test_scrypt_spec_vector():
    """The spec vector's UNUSUAL shape (r=1, p=8) trips OpenSSL 3.0's
    broken scrypt memory accounting (requirement computed ~16384*n*p,
    hard-capped, maxmem ignored — measured on this image's 3.0.18).
    Real-world keystores (geth defaults r=8, p=1) are unaffected; the
    vector stays as the canary for a fixed OpenSSL."""
    try:
        got = KS.decrypt(_SCRYPT_VECTOR, "testpassword")
    except KS.KeystoreError as e:
        if "OpenSSL" in str(e):
            pytest.xfail(f"OpenSSL scrypt cap: {e}")
        raise
    assert got == _SECRET


def test_scrypt_geth_default_shape_roundtrip():
    """The parameter shape every real keyfile uses (geth scrypt
    defaults, n scaled down for test time) round-trips through
    hashlib's scrypt."""
    blob = KS.encrypt(_SECRET, "pw", kdf="scrypt", light=True)
    assert blob["crypto"]["kdfparams"]["r"] == 8
    assert blob["crypto"]["kdfparams"]["p"] == 1
    assert KS.decrypt(blob, "pw") == _SECRET


def test_wrong_password_rejected():
    with pytest.raises(KS.KeystoreError, match="MAC"):
        KS.decrypt(_PBKDF2_VECTOR, "nottestpassword")


def test_roundtrip_and_file_io(tmp_path):
    blob = KS.encrypt(_SECRET, "hunter2", light=True)
    assert KS.decrypt(json.dumps(blob), "hunter2") == _SECRET
    # address field matches our ECDSA derivation
    from harmony_tpu.crypto_ecdsa import ECDSAKey

    assert blob["address"] == ECDSAKey.from_bytes(_SECRET).address().hex()
    path = str(tmp_path / "key.json")
    KS.save(path, _SECRET, "pw", light=True)
    assert KS.load(path, "pw") == _SECRET
    blob2 = KS.encrypt(_SECRET, "pw", kdf="pbkdf2", light=True)
    assert KS.decrypt(blob2, "pw") == _SECRET


# --- ABI: the Solidity-spec example ---------------------------------------

def test_spec_example_dynamic_encoding():
    """The contract-ABI spec's canonical f(uint,uint32[],bytes10,bytes)
    example — byte-exact against the published encoding."""
    data = abi.abi_encode(
        ["uint256", "uint32[]", "bytes10", "bytes"],
        [0x123, [0x456, 0x789], b"1234567890", b"Hello, world!"],
    )
    expect = (
        "0000000000000000000000000000000000000000000000000000000000000123"
        "0000000000000000000000000000000000000000000000000000000000000080"
        "3132333435363738393000000000000000000000000000000000000000000000"
        "00000000000000000000000000000000000000000000000000000000000000e0"
        "0000000000000000000000000000000000000000000000000000000000000002"
        "0000000000000000000000000000000000000000000000000000000000000456"
        "0000000000000000000000000000000000000000000000000000000000000789"
        "000000000000000000000000000000000000000000000000000000000000000d"
        "48656c6c6f2c20776f726c642100000000000000000000000000000000000000"
    )
    assert data.hex() == expect


def test_tuple_static_roundtrip():
    types = ["(uint256,bool)", "address"]
    vals = [(7, True), b"\xaa" * 20]
    out = abi.abi_decode(types, abi.abi_encode(types, vals))
    assert out == [(7, True), b"\xaa" * 20]


def test_tuple_dynamic_nested_roundtrip():
    types = ["(uint256,bytes)", "(uint8,(string,uint256[]))[]"]
    vals = [
        (42, b"\x01\x02\x03"),
        [(1, ("hi", [5, 6])), (2, ("there", []))],
    ]
    out = abi.abi_decode(types, abi.abi_encode(types, vals))
    assert out[0] == (42, b"\x01\x02\x03")
    assert out[1] == [(1, ("hi", [5, 6])), (2, ("there", []))]


def test_split_types_respects_tuples():
    assert abi.split_types("uint256,(address,bytes)[],bool") == [
        "uint256", "(address,bytes)[]", "bool",
    ]


def test_event_encode_decode():
    sig = "Transfer(address,address,uint256)"
    frm, to = b"\x11" * 20, b"\x22" * 20
    topics, data = abi.encode_log(sig, [True, True, False],
                                  [frm, to, 1000])
    assert topics[0] == abi.event_topic(sig)
    assert len(topics) == 3 and len(data) == 32
    vals = abi.decode_log(sig, [True, True, False], topics, data)
    assert vals == [frm, to, 1000]


def test_event_indexed_dynamic_is_hashed():
    sig = "Named(string,uint256)"
    topics, data = abi.encode_log(sig, [True, False], ["alice", 5])
    vals = abi.decode_log(sig, [True, False], topics, data)
    assert vals[0] == topics[1] and len(vals[0]) == 32  # hash only
    assert vals[1] == 5


def test_error_decoding():
    msg = abi.abi_encode(["string"], ["nope"])
    kind, got = abi.decode_error(abi.ERROR_STRING_SELECTOR + msg)
    assert (kind, got) == ("Error", "nope")
    panic = abi.abi_encode(["uint256"], [0x11])
    assert abi.decode_error(abi.PANIC_SELECTOR + panic) == ("Panic", 0x11)
    custom_sel = abi.function_selector("NotEnough(uint256,uint256)")
    kind, args = abi.decode_error(
        custom_sel + abi.abi_encode(["uint256", "uint256"], [1, 2]),
        custom={custom_sel: ("NotEnough(uint256,uint256)",
                             ["uint256", "uint256"])},
    )
    assert kind.startswith("NotEnough") and args == [1, 2]
    assert abi.decode_error(b"\xde\xad\xbe\xef")[0] == "unknown"
