"""Parity: native host BLS12-381 (native/bls381.cpp) vs the bigint twin.

The native library must be bitwise interchangeable with ref/ — same GT
elements (the framework's cubed pairing), same deterministic sqrt
choices, same hash-to-curve outputs — so the chain can hot-swap between
them per HOST_BLS without any consensus-visible difference.
"""

import os

import pytest

from harmony_tpu.ref import bls as RB
from harmony_tpu.ref import fields as F
from harmony_tpu.ref import native as NB
from harmony_tpu.ref import pairing as RP
from harmony_tpu.ref.curve import G1_GEN, G2_GEN, g1, g2
from harmony_tpu.ref.params import H2, R_ORDER

pytestmark = pytest.mark.skipif(
    not NB.available(), reason="native bls381 library unavailable"
)


@pytest.fixture
def bigint_mode(monkeypatch):
    """Force the pure-twin path inside the fixture's scope."""
    monkeypatch.setenv("HOST_BLS", "bigint")


def test_pairing_gt_parity_generators():
    assert NB.multi_pairing([(G1_GEN, G2_GEN)]) == RP.pairing(G1_GEN, G2_GEN)


def test_pairing_gt_parity_scaled():
    p = g1.mul(G1_GEN, 7)
    q = g2.mul(G2_GEN, 11)
    assert NB.multi_pairing([(p, q)]) == RP.pairing(p, q)


def test_multi_pairing_product_parity():
    pairs = [
        (g1.mul(G1_GEN, 3), G2_GEN),
        (g1.neg(G1_GEN), g2.mul(G2_GEN, 3)),
    ]
    assert NB.multi_pairing(pairs) == RP.multi_pairing(pairs)
    # e(3P, Q) * e(-P, 3Q) == 1 by bilinearity
    assert NB.pairing_check(pairs)


def test_pairing_infinity_pairs():
    assert NB.multi_pairing([(None, G2_GEN)]) == F.FP12_ONE
    assert NB.multi_pairing([(G1_GEN, None)]) == F.FP12_ONE
    assert NB.pairing_check([])


def test_pairing_check_rejects():
    assert not NB.pairing_check([(G1_GEN, G2_GEN)])


def test_scalar_mul_parity():
    for k in (1, 2, 3, R_ORDER - 1, R_ORDER, R_ORDER + 5, H2):
        assert NB.g1_mul(G1_GEN, k) == g1.mul(G1_GEN, k)
        assert NB.g2_mul(G2_GEN, k) == g2.mul(G2_GEN, k)


def test_scalar_mul_edges():
    assert NB.g1_mul(G1_GEN, 0) is None
    assert NB.g1_mul(None, 5) is None
    assert NB.g1_mul(G1_GEN, R_ORDER) is None  # order annihilates
    assert NB.g1_mul(G1_GEN, -3) == g1.mul(G1_GEN, -3)
    assert NB.g2_mul(G2_GEN, -7) == g2.mul(G2_GEN, -7)


def test_sums_parity():
    pts1 = [g1.mul(G1_GEN, k) for k in (1, 5, 9, 13)]
    pts2 = [g2.mul(G2_GEN, k) for k in (2, 4, 8)]
    assert NB.g1_sum(pts1) == g1.mul(G1_GEN, 28)
    assert NB.g2_sum(pts2) == g2.mul(G2_GEN, 14)
    assert NB.g1_sum([]) is None
    assert NB.g1_sum([None, G1_GEN, None]) == G1_GEN
    # cancellation to infinity
    assert NB.g1_sum([G1_GEN, g1.neg(G1_GEN)]) is None


def test_subgroup_checks():
    assert NB.g1_in_subgroup(G1_GEN)
    assert NB.g2_in_subgroup(G2_GEN)
    assert NB.g1_in_subgroup(None)
    # find an E(Fp) point outside the r-torsion (cofactor h1 = 3 * 11^2)
    from harmony_tpu.ref.params import P

    x = 1
    while True:
        y = F.fp_sqrt((x * x * x + 4) % P)
        if y is not None and g1.mul((x, y), R_ORDER) is not None:
            break
        x += 1
    assert not NB.g1_in_subgroup((x, y))
    # off-curve point must fail too
    assert not NB.g1_in_subgroup((G1_GEN[0], (G1_GEN[1] + 1) % P))


def test_hash_to_g2_native_vs_bigint(monkeypatch):
    from harmony_tpu.ref import hash_to_curve as H

    msgs = [b"\x00" * 32, b"parity-vector-1", b"\xff" * 32]
    native = [H.hash_to_g2(m) for m in msgs]
    monkeypatch.setenv("HOST_BLS", "bigint")
    twin = [H.hash_to_g2(m) for m in msgs]
    assert native == twin


def test_sign_verify_cross_paths(monkeypatch):
    sk = RB.keygen(b"native-parity-seed")
    msg = b"m" * 32
    pk_n = RB.pubkey(sk)
    sig_n = RB.sign(sk, msg)
    assert RB.verify(pk_n, msg, sig_n)
    monkeypatch.setenv("HOST_BLS", "bigint")
    # twin verifies the natively-produced signature, and vice versa
    assert RB.pubkey(sk) == pk_n
    assert RB.sign(sk, msg) == sig_n
    assert RB.verify(pk_n, msg, sig_n)
    monkeypatch.delenv("HOST_BLS")
    assert not RB.verify(pk_n, b"x" * 32, sig_n)


def test_fp2_sqrt_parity():
    from harmony_tpu.ref.params import P

    for seed in range(8):
        a = (pow(3, seed + 2, P), pow(5, seed + 3, P))
        sq = F.fp2_sqr(a)
        n = NB.fp2_sqrt(sq)
        t = F.fp2_sqrt(sq)
        assert n == t
    # non-residue: both refuse (x^3+b roots cover both branches already;
    # pick a known non-square by trial)
    probe = (2, 0)
    while F.fp2_sqrt(probe) is not None:
        probe = (probe[0] + 1, 1)
    assert NB.fp2_sqrt(probe) is None


def test_decompress_roundtrip_uses_native():
    from harmony_tpu.ref.serialize import (
        g1_compress, g1_decompress, g2_compress, g2_decompress,
    )

    pt1 = g1.mul(G1_GEN, 31337)
    pt2 = g2.mul(G2_GEN, 31337)
    assert g1_decompress(g1_compress(pt1)) == pt1
    assert g2_decompress(g2_compress(pt2)) == pt2


def test_herumi_cross_paths(monkeypatch):
    from harmony_tpu.ref import herumi as HM

    sk = 0x1EF1125F9AB49686B6E6D17D8EAA1EF2C7C71FBB683A4AB8AC4FC6BFF9
    msg = b"h" * 32
    pk_n = HM.pubkey(sk)
    sig_n = HM.sign_hash(sk, msg)
    assert HM.verify_hash(pk_n, msg, sig_n)
    monkeypatch.setenv("HOST_BLS", "bigint")
    assert HM.pubkey(sk) == pk_n
    assert HM.sign_hash(sk, msg) == sig_n
    assert HM.verify_hash(pk_n, msg, sig_n)
