"""View-change protocol tests: M1/M2/M3 collection, NEWVIEW, next
leader — including the adversarial-delivery tier (message loss,
duplicates, stale views, deterministic wire garbling) the chaos
scenarios exercise at network scale."""

import pytest

from harmony_tpu import bls as B
from harmony_tpu import faultinject as FI
from harmony_tpu.consensus import view_change as VC
from harmony_tpu.consensus.messages import encode_sig_and_bitmap
from harmony_tpu.consensus.quorum import Decider, Phase, Policy
from harmony_tpu.multibls import PrivateKeys


@pytest.fixture(scope="module")
def committee():
    keysets = [
        PrivateKeys.from_keys([B.PrivateKey.generate(bytes([60 + i]))])
        for i in range(4)
    ]
    keys = [ks[0].pub.bytes for ks in keysets]
    return keysets, keys


def test_next_leader_rotation(committee):
    _, keys = committee
    assert VC.next_leader_key(keys, keys[1], 1) == keys[2]
    assert VC.next_leader_key(keys, keys[3], 1) == keys[0]  # wraps
    assert VC.next_leader_key(keys, keys[0], 2) == keys[2]
    # unknown last leader: gap from start
    assert VC.next_leader_key(keys, b"nope", 1) == keys[0]


def test_view_change_nil_quorum_and_new_view(committee):
    keysets, keys = committee
    view_id = 9
    coll = VC.ViewChangeCollector(
        keys, Decider(Policy.UNIFORM, keys), view_id
    )
    msgs = [
        VC.construct_viewchange(ks, view_id, block_num=5) for ks in keysets
    ]
    for m in msgs:
        assert coll.on_viewchange(m)
    # duplicate rejected
    assert not coll.on_viewchange(msgs[0])
    # wrong view id rejected
    assert not coll.on_viewchange(
        VC.construct_viewchange(keysets[0], view_id + 1, 5)
    )
    nv = coll.try_new_view(block_num=5, leader_keys=keysets[0])
    assert nv is not None
    assert VC.verify_new_view(nv, keys, Decider(Policy.UNIFORM, keys))


def _real_prepared_proof(keysets, keys, block_hash):
    """A genuine PREPARED quorum proof: every committee member's prepare
    signature aggregated, full bitmap."""
    sigs = [ks.sign_hash_aggregated(block_hash) for ks in keysets]
    agg = B.aggregate_sigs(sigs)
    n = len(keys)
    bitmap = bytearray((n + 7) >> 3)
    for i in range(n):
        bitmap[i >> 3] |= 1 << (i & 7)
    return encode_sig_and_bitmap(agg.bytes, bytes(bitmap))


def test_view_change_with_prepared_block(committee):
    keysets, keys = committee
    view_id = 11
    coll = VC.ViewChangeCollector(
        keys, Decider(Policy.UNIFORM, keys), view_id
    )
    block_hash = bytes(range(32))
    proof = _real_prepared_proof(keysets, keys, block_hash)
    # two voters saw the prepared block, two did not
    for ks in keysets[:2]:
        assert coll.on_viewchange(
            VC.construct_viewchange(ks, view_id, 6, block_hash, proof)
        )
    for ks in keysets[2:]:
        assert coll.on_viewchange(VC.construct_viewchange(ks, view_id, 6))
    nv = coll.try_new_view(block_num=6, leader_keys=keysets[1])
    assert nv is not None
    assert nv.m1_payload == VC.m1_payload(block_hash, proof)
    assert VC.verify_new_view(nv, keys, Decider(Policy.UNIFORM, keys))


def test_new_view_missing_m1_rejected(committee):
    keysets, keys = committee
    view_id = 13
    coll = VC.ViewChangeCollector(
        keys, Decider(Policy.UNIFORM, keys), view_id
    )
    block_hash = bytes(range(32))
    proof = _real_prepared_proof(keysets, keys, block_hash)
    for ks in keysets[:3]:
        coll.on_viewchange(
            VC.construct_viewchange(ks, view_id, 7, block_hash, proof)
        )
    coll.on_viewchange(VC.construct_viewchange(keysets[3], view_id, 7))
    nv = coll.try_new_view(block_num=7, leader_keys=keysets[0])
    assert nv is not None
    nv.m1_payload = b""  # strip the prepared payload: must now fail
    assert not VC.verify_new_view(nv, keys, Decider(Policy.UNIFORM, keys))


def test_fabricated_m1_proof_rejected(committee):
    """A NEWVIEW carrying a made-up prepared block (garbage aggregate)
    must be rejected — the embedded PREPARED proof is verified."""
    keysets, keys = committee
    view_id = 17
    coll = VC.ViewChangeCollector(
        keys, Decider(Policy.UNIFORM, keys), view_id
    )
    for ks in keysets:
        coll.on_viewchange(VC.construct_viewchange(ks, view_id, 9))
    nv = coll.try_new_view(block_num=9, leader_keys=keysets[0])
    # malicious leader grafts a fabricated prepared payload
    fake_hash = bytes(range(32))
    fake_proof = encode_sig_and_bitmap(
        keysets[0].sign_hash_aggregated(b"x" * 32).bytes, b"\x0f"
    )
    nv.m1_payload = VC.m1_payload(fake_hash, fake_proof)
    assert not VC.verify_new_view(nv, keys, Decider(Policy.UNIFORM, keys))


def test_outsider_and_overlapping_votes_rejected(committee):
    keysets, keys = committee
    view_id = 19
    coll = VC.ViewChangeCollector(
        keys, Decider(Policy.UNIFORM, keys), view_id
    )
    outsider = PrivateKeys.from_keys([B.PrivateKey.generate(b"\x77")])
    # non-committee voter: rejected, no crash, no store pollution
    assert not coll.on_viewchange(
        VC.construct_viewchange(outsider, view_id, 9)
    )
    assert not coll.m3_sigs and not coll.m2_sigs
    # overlapping key-set: second vote containing an already-voted key
    assert coll.on_viewchange(VC.construct_viewchange(keysets[0], view_id, 9))
    both = PrivateKeys.from_keys(list(keysets[0]) + list(keysets[1]))
    assert not coll.on_viewchange(VC.construct_viewchange(both, view_id, 9))


def test_tampered_m3_rejected(committee):
    keysets, keys = committee
    view_id = 15
    coll = VC.ViewChangeCollector(
        keys, Decider(Policy.UNIFORM, keys), view_id
    )
    for ks in keysets:
        coll.on_viewchange(VC.construct_viewchange(ks, view_id, 8))
    nv = coll.try_new_view(block_num=8, leader_keys=keysets[0])
    nv.view_id += 1  # signature no longer matches the claimed view
    assert not VC.verify_new_view(nv, keys, Decider(Policy.UNIFORM, keys))


# -- adversarial delivery: loss, duplication, staleness, garbling ------------


def test_message_loss_below_quorum_no_new_view(committee):
    """Only 2 of 4 view-change votes arrive (uniform quorum needs 3):
    no NEWVIEW may form, and the collector stays consistent for the
    votes that DID land."""
    keysets, keys = committee
    view_id = 21
    coll = VC.ViewChangeCollector(
        keys, Decider(Policy.UNIFORM, keys), view_id
    )
    for ks in keysets[:2]:
        assert coll.on_viewchange(
            VC.construct_viewchange(ks, view_id, 10)
        )
    assert coll.try_new_view(block_num=10, leader_keys=keysets[0]) is None
    assert len(coll.m3_sigs) == 2


def test_message_loss_at_quorum_still_forms_new_view(committee):
    """3 of 4 votes (one lost forever) is exactly quorum: the NEWVIEW
    must form and verify — a single silent validator cannot stall the
    view change."""
    keysets, keys = committee
    view_id = 23
    coll = VC.ViewChangeCollector(
        keys, Decider(Policy.UNIFORM, keys), view_id
    )
    for ks in keysets[:3]:
        assert coll.on_viewchange(
            VC.construct_viewchange(ks, view_id, 11)
        )
    nv = coll.try_new_view(block_num=11, leader_keys=keysets[1])
    assert nv is not None
    assert VC.verify_new_view(nv, keys, Decider(Policy.UNIFORM, keys))


def test_duplicate_votes_are_idempotent(committee):
    """Gossip redelivers the same vote (retry paths re-publish): the
    second copy must change NOTHING — no double-counted quorum power,
    no double-aggregated signature."""
    keysets, keys = committee
    view_id = 25
    decider = Decider(Policy.UNIFORM, keys)
    coll = VC.ViewChangeCollector(keys, decider, view_id)
    msg = VC.construct_viewchange(keysets[0], view_id, 12)
    assert coll.on_viewchange(msg)
    before = (dict(coll.m3_sigs), decider.count(Phase.VIEWCHANGE))
    for _ in range(3):
        assert not coll.on_viewchange(msg)  # duplicate rejected
    assert coll.m3_sigs == before[0]
    assert decider.count(Phase.VIEWCHANGE) == before[1]


def test_stale_and_future_view_votes_rejected(committee):
    """Votes for any view other than the collector's (older rounds
    replayed, or a peer that escalated further) leave no trace."""
    keysets, keys = committee
    view_id = 27
    coll = VC.ViewChangeCollector(
        keys, Decider(Policy.UNIFORM, keys), view_id
    )
    assert not coll.on_viewchange(
        VC.construct_viewchange(keysets[0], view_id - 1, 13)
    )
    assert not coll.on_viewchange(
        VC.construct_viewchange(keysets[0], view_id + 3, 13)
    )
    assert not coll.m3_sigs and not coll.m2_sigs


def test_garbled_wire_bytes_never_crash_or_pollute(committee):
    """Seed-deterministic wire corruption (the faultinject garble
    engine) over encoded view-change messages: every corrupted variant
    must either fail decode with ValueError or be rejected by the
    collector — never crash, never leave partial state."""
    keysets, keys = committee
    view_id = 29
    coll = VC.ViewChangeCollector(
        keys, Decider(Policy.UNIFORM, keys), view_id
    )
    wire = VC.encode_viewchange(
        VC.construct_viewchange(keysets[0], view_id, 14)
    )
    FI.reset()
    try:
        for seed in range(8):
            FI.set_seed(seed)
            FI.arm("vc.wire", garble=True)
            bad = FI.garble("vc.wire", wire)
            FI.reset()
            assert bad != wire  # the garble engine really corrupted it
            try:
                msg = VC.decode_viewchange(bad)
            except ValueError:
                continue  # truncation/length forgery: failed fast
            coll.on_viewchange(msg)  # must not raise
        assert not coll.m3_sigs and not coll.m2_sigs  # nothing leaked
        # the pristine original still lands afterwards
        assert coll.on_viewchange(VC.decode_viewchange(wire))
    finally:
        FI.reset()


def test_garbled_newview_rejected_by_verify(committee):
    """A garbled NEWVIEW that still decodes must fail verification —
    validators must not adopt a corrupted quorum proof."""
    keysets, keys = committee
    view_id = 31
    coll = VC.ViewChangeCollector(
        keys, Decider(Policy.UNIFORM, keys), view_id
    )
    for ks in keysets:
        coll.on_viewchange(VC.construct_viewchange(ks, view_id, 15))
    nv = coll.try_new_view(block_num=15, leader_keys=keysets[0])
    wire = VC.encode_newview(nv)
    FI.reset()
    try:
        rejected = 0
        for seed in range(8):
            FI.set_seed(seed)
            FI.arm("nv.wire", garble=True)
            bad = FI.garble("nv.wire", wire)
            FI.reset()
            try:
                got = VC.decode_newview(bad)
            except ValueError:
                rejected += 1
                continue
            if not VC.verify_new_view(
                got, keys, Decider(Policy.UNIFORM, keys)
            ):
                rejected += 1
        assert rejected == 8  # every corruption caught
    finally:
        FI.reset()


def test_conflicting_prepared_payloads_rejected(committee):
    """Two voters claiming DIFFERENT prepared blocks: the second
    conflicting claim is rejected outright (one round can only have
    prepared one block)."""
    keysets, keys = committee
    view_id = 33
    coll = VC.ViewChangeCollector(
        keys, Decider(Policy.UNIFORM, keys), view_id
    )
    hash_a = bytes(range(32))
    hash_b = bytes(reversed(range(32)))
    proof_a = _real_prepared_proof(keysets, keys, hash_a)
    proof_b = _real_prepared_proof(keysets, keys, hash_b)
    assert coll.on_viewchange(
        VC.construct_viewchange(keysets[0], view_id, 16, hash_a, proof_a)
    )
    assert not coll.on_viewchange(
        VC.construct_viewchange(keysets[1], view_id, 16, hash_b, proof_b)
    )
    assert coll.m1_payload == VC.m1_payload(hash_a, proof_a)


def test_aggregate_public_honors_twin_mode(monkeypatch, committee):
    """Twin-mode regression (found by minority_partition_heal): the
    NEWVIEW verify path asks for the device tree-sum, but twins keep
    jax UNLOADED by contract — aggregate_public must fall back to the
    host path instead of compiling a fresh XLA masked-sum on the
    consensus pump thread (the first NEWVIEW at a new committee width
    used to wedge every validator's pump for a full XLA:CPU compile,
    ~90 s at width 7)."""
    from harmony_tpu.consensus.mask import Mask
    from harmony_tpu.ref import bls as RB

    _, keys = committee
    points = [RB.pubkey_from_bytes(k) for k in keys]
    mask = Mask(points)
    for i in range(len(points)):
        mask.set_bit(i, True)
    monkeypatch.setenv("HARMONY_KERNEL_TWIN", "1")
    # the device kernels must never be touched under twins — make any
    # excursion into ops.curve a loud failure
    import harmony_tpu.ops.curve as CV

    def _boom(*a, **k):
        raise AssertionError(
            "aggregate_public compiled a device masked-sum under twins"
        )

    monkeypatch.setattr(CV, "masked_sum", _boom)
    got = mask.aggregate_public(device=True)
    want = RB.aggregate_pubkeys(points)
    assert got == want
