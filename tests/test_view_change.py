"""View-change protocol tests: M1/M2/M3 collection, NEWVIEW, next leader."""

import pytest

from harmony_tpu import bls as B
from harmony_tpu.consensus import view_change as VC
from harmony_tpu.consensus.messages import encode_sig_and_bitmap
from harmony_tpu.consensus.quorum import Decider, Phase, Policy
from harmony_tpu.multibls import PrivateKeys


@pytest.fixture(scope="module")
def committee():
    keysets = [
        PrivateKeys.from_keys([B.PrivateKey.generate(bytes([60 + i]))])
        for i in range(4)
    ]
    keys = [ks[0].pub.bytes for ks in keysets]
    return keysets, keys


def test_next_leader_rotation(committee):
    _, keys = committee
    assert VC.next_leader_key(keys, keys[1], 1) == keys[2]
    assert VC.next_leader_key(keys, keys[3], 1) == keys[0]  # wraps
    assert VC.next_leader_key(keys, keys[0], 2) == keys[2]
    # unknown last leader: gap from start
    assert VC.next_leader_key(keys, b"nope", 1) == keys[0]


def test_view_change_nil_quorum_and_new_view(committee):
    keysets, keys = committee
    view_id = 9
    coll = VC.ViewChangeCollector(
        keys, Decider(Policy.UNIFORM, keys), view_id
    )
    msgs = [
        VC.construct_viewchange(ks, view_id, block_num=5) for ks in keysets
    ]
    for m in msgs:
        assert coll.on_viewchange(m)
    # duplicate rejected
    assert not coll.on_viewchange(msgs[0])
    # wrong view id rejected
    assert not coll.on_viewchange(
        VC.construct_viewchange(keysets[0], view_id + 1, 5)
    )
    nv = coll.try_new_view(block_num=5, leader_keys=keysets[0])
    assert nv is not None
    assert VC.verify_new_view(nv, keys, Decider(Policy.UNIFORM, keys))


def _real_prepared_proof(keysets, keys, block_hash):
    """A genuine PREPARED quorum proof: every committee member's prepare
    signature aggregated, full bitmap."""
    sigs = [ks.sign_hash_aggregated(block_hash) for ks in keysets]
    agg = B.aggregate_sigs(sigs)
    n = len(keys)
    bitmap = bytearray((n + 7) >> 3)
    for i in range(n):
        bitmap[i >> 3] |= 1 << (i & 7)
    return encode_sig_and_bitmap(agg.bytes, bytes(bitmap))


def test_view_change_with_prepared_block(committee):
    keysets, keys = committee
    view_id = 11
    coll = VC.ViewChangeCollector(
        keys, Decider(Policy.UNIFORM, keys), view_id
    )
    block_hash = bytes(range(32))
    proof = _real_prepared_proof(keysets, keys, block_hash)
    # two voters saw the prepared block, two did not
    for ks in keysets[:2]:
        assert coll.on_viewchange(
            VC.construct_viewchange(ks, view_id, 6, block_hash, proof)
        )
    for ks in keysets[2:]:
        assert coll.on_viewchange(VC.construct_viewchange(ks, view_id, 6))
    nv = coll.try_new_view(block_num=6, leader_keys=keysets[1])
    assert nv is not None
    assert nv.m1_payload == VC.m1_payload(block_hash, proof)
    assert VC.verify_new_view(nv, keys, Decider(Policy.UNIFORM, keys))


def test_new_view_missing_m1_rejected(committee):
    keysets, keys = committee
    view_id = 13
    coll = VC.ViewChangeCollector(
        keys, Decider(Policy.UNIFORM, keys), view_id
    )
    block_hash = bytes(range(32))
    proof = _real_prepared_proof(keysets, keys, block_hash)
    for ks in keysets[:3]:
        coll.on_viewchange(
            VC.construct_viewchange(ks, view_id, 7, block_hash, proof)
        )
    coll.on_viewchange(VC.construct_viewchange(keysets[3], view_id, 7))
    nv = coll.try_new_view(block_num=7, leader_keys=keysets[0])
    assert nv is not None
    nv.m1_payload = b""  # strip the prepared payload: must now fail
    assert not VC.verify_new_view(nv, keys, Decider(Policy.UNIFORM, keys))


def test_fabricated_m1_proof_rejected(committee):
    """A NEWVIEW carrying a made-up prepared block (garbage aggregate)
    must be rejected — the embedded PREPARED proof is verified."""
    keysets, keys = committee
    view_id = 17
    coll = VC.ViewChangeCollector(
        keys, Decider(Policy.UNIFORM, keys), view_id
    )
    for ks in keysets:
        coll.on_viewchange(VC.construct_viewchange(ks, view_id, 9))
    nv = coll.try_new_view(block_num=9, leader_keys=keysets[0])
    # malicious leader grafts a fabricated prepared payload
    fake_hash = bytes(range(32))
    fake_proof = encode_sig_and_bitmap(
        keysets[0].sign_hash_aggregated(b"x" * 32).bytes, b"\x0f"
    )
    nv.m1_payload = VC.m1_payload(fake_hash, fake_proof)
    assert not VC.verify_new_view(nv, keys, Decider(Policy.UNIFORM, keys))


def test_outsider_and_overlapping_votes_rejected(committee):
    keysets, keys = committee
    view_id = 19
    coll = VC.ViewChangeCollector(
        keys, Decider(Policy.UNIFORM, keys), view_id
    )
    outsider = PrivateKeys.from_keys([B.PrivateKey.generate(b"\x77")])
    # non-committee voter: rejected, no crash, no store pollution
    assert not coll.on_viewchange(
        VC.construct_viewchange(outsider, view_id, 9)
    )
    assert not coll.m3_sigs and not coll.m2_sigs
    # overlapping key-set: second vote containing an already-voted key
    assert coll.on_viewchange(VC.construct_viewchange(keysets[0], view_id, 9))
    both = PrivateKeys.from_keys(list(keysets[0]) + list(keysets[1]))
    assert not coll.on_viewchange(VC.construct_viewchange(both, view_id, 9))


def test_tampered_m3_rejected(committee):
    keysets, keys = committee
    view_id = 15
    coll = VC.ViewChangeCollector(
        keys, Decider(Policy.UNIFORM, keys), view_id
    )
    for ks in keysets:
        coll.on_viewchange(VC.construct_viewchange(ks, view_id, 8))
    nv = coll.try_new_view(block_num=8, leader_keys=keysets[0])
    nv.view_id += 1  # signature no longer matches the claimed view
    assert not VC.verify_new_view(nv, keys, Decider(Policy.UNIFORM, keys))
