"""View-change protocol tests: M1/M2/M3 collection, NEWVIEW, next leader."""

import pytest

from harmony_tpu import bls as B
from harmony_tpu.consensus import view_change as VC
from harmony_tpu.consensus.messages import encode_sig_and_bitmap
from harmony_tpu.consensus.quorum import Decider, Phase, Policy
from harmony_tpu.multibls import PrivateKeys


@pytest.fixture(scope="module")
def committee():
    keysets = [
        PrivateKeys.from_keys([B.PrivateKey.generate(bytes([60 + i]))])
        for i in range(4)
    ]
    keys = [ks[0].pub.bytes for ks in keysets]
    return keysets, keys


def test_next_leader_rotation(committee):
    _, keys = committee
    assert VC.next_leader_key(keys, keys[1], 1) == keys[2]
    assert VC.next_leader_key(keys, keys[3], 1) == keys[0]  # wraps
    assert VC.next_leader_key(keys, keys[0], 2) == keys[2]
    # unknown last leader: gap from start
    assert VC.next_leader_key(keys, b"nope", 1) == keys[0]


def test_view_change_nil_quorum_and_new_view(committee):
    keysets, keys = committee
    view_id = 9
    coll = VC.ViewChangeCollector(
        keys, Decider(Policy.UNIFORM, keys), view_id
    )
    msgs = [
        VC.construct_viewchange(ks, view_id, block_num=5) for ks in keysets
    ]
    for m in msgs:
        assert coll.on_viewchange(m)
    # duplicate rejected
    assert not coll.on_viewchange(msgs[0])
    # wrong view id rejected
    assert not coll.on_viewchange(
        VC.construct_viewchange(keysets[0], view_id + 1, 5)
    )
    nv = coll.try_new_view(block_num=5, leader_keys=keysets[0])
    assert nv is not None
    assert VC.verify_new_view(nv, keys, Decider(Policy.UNIFORM, keys))


def test_view_change_with_prepared_block(committee):
    keysets, keys = committee
    view_id = 11
    coll = VC.ViewChangeCollector(
        keys, Decider(Policy.UNIFORM, keys), view_id
    )
    block_hash = bytes(range(32))
    proof = encode_sig_and_bitmap(bytes(96), b"\x0f")
    # two voters saw the prepared block, two did not
    for ks in keysets[:2]:
        assert coll.on_viewchange(
            VC.construct_viewchange(ks, view_id, 6, block_hash, proof)
        )
    for ks in keysets[2:]:
        assert coll.on_viewchange(VC.construct_viewchange(ks, view_id, 6))
    nv = coll.try_new_view(block_num=6, leader_keys=keysets[1])
    assert nv is not None
    assert nv.m1_payload == VC.m1_payload(block_hash, proof)
    assert VC.verify_new_view(nv, keys, Decider(Policy.UNIFORM, keys))


def test_new_view_missing_m1_rejected(committee):
    keysets, keys = committee
    view_id = 13
    coll = VC.ViewChangeCollector(
        keys, Decider(Policy.UNIFORM, keys), view_id
    )
    block_hash = bytes(32)
    proof = encode_sig_and_bitmap(bytes(96), b"\x0f")
    for ks in keysets[:3]:
        coll.on_viewchange(
            VC.construct_viewchange(ks, view_id, 7, block_hash, proof)
        )
    coll.on_viewchange(VC.construct_viewchange(keysets[3], view_id, 7))
    nv = coll.try_new_view(block_num=7, leader_keys=keysets[0])
    assert nv is not None
    nv.m1_payload = b""  # strip the prepared payload: must now fail
    assert not VC.verify_new_view(nv, keys, Decider(Policy.UNIFORM, keys))


def test_tampered_m3_rejected(committee):
    keysets, keys = committee
    view_id = 15
    coll = VC.ViewChangeCollector(
        keys, Decider(Policy.UNIFORM, keys), view_id
    )
    for ks in keysets:
        coll.on_viewchange(VC.construct_viewchange(ks, view_id, 8))
    nv = coll.try_new_view(block_num=8, leader_keys=keysets[0])
    nv.view_id += 1  # signature no longer matches the claimed view
    assert not VC.verify_new_view(nv, keys, Decider(Policy.UNIFORM, keys))
