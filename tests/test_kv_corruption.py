"""KV corruption/replay suite, parametrized over BOTH stores: every
torn-tail, torn-value, torn-batch and implausible-header shape in
tests/kv_corruption.py must recover identically on FileKV and the
native C++ store (same on-disk format, same replay verdicts)."""

import os

import pytest

from harmony_tpu.core.kv import FileKV, WriteBatch
from harmony_tpu.core.kv_native import available

import kv_corruption as KC


def _native(path):
    from harmony_tpu.core.kv_native import NativeKV

    return NativeKV(path)


BACKENDS = [
    pytest.param(FileKV, id="filekv"),
    pytest.param(
        _native, id="native",
        marks=pytest.mark.skipif(
            not available(), reason="native toolchain unavailable"
        ),
    ),
]


@pytest.mark.parametrize("factory", BACKENDS)
@pytest.mark.parametrize(
    "name,tail,expect", KC.CASES, ids=[c[0] for c in KC.CASES]
)
def test_corruption_case(tmp_path, factory, name, tail, expect):
    KC.run_case(factory, str(tmp_path / f"{name}.kv"), tail, expect)


@pytest.mark.parametrize("factory", BACKENDS)
def test_batch_atomic_and_cross_readable(tmp_path, factory):
    """A committed batch is all-there; the OTHER backend reads it (the
    two stores share the marker grammar on disk)."""
    path = str(tmp_path / "x.kv")
    db = factory(path)
    batch = WriteBatch()
    batch.put(b"k1", b"v1")
    batch.put(b"k2", b"v2" * 100)
    batch.delete(b"k1")
    db.write_batch(batch)
    assert db.get(b"k1") is None and db.get(b"k2") == b"v2" * 100
    db.flush()
    db.close()
    other = FileKV(path) if factory is not FileKV else (
        _native(path) if available() else FileKV(path)
    )
    try:
        assert other.get(b"k2") == b"v2" * 100
        assert other.get(b"k1") is None
    finally:
        other.close()


@pytest.mark.parametrize("factory", BACKENDS)
def test_empty_batch_is_noop(tmp_path, factory):
    path = str(tmp_path / "e.kv")
    db = factory(path)
    db.put(b"a", b"1")
    db.write_batch(WriteBatch())
    db.flush()
    size = os.path.getsize(path)
    db.close()
    # no markers were written for the empty batch
    assert size == 8 + 1 + 1


@pytest.mark.parametrize("factory", BACKENDS)
def test_fsync_policy_knob(tmp_path, factory):
    for policy in ("none", "batch", "always"):
        path = str(tmp_path / f"f_{policy}.kv")
        db = (FileKV(path, fsync=policy) if factory is FileKV
              else __import__(
                  "harmony_tpu.core.kv_native", fromlist=["NativeKV"]
              ).NativeKV(path, fsync=policy))
        db.put(b"k", b"v")
        batch = WriteBatch()
        batch.put(b"b", b"bb")
        db.write_batch(batch)
        assert db.get(b"b") == b"bb"
        db.close()
    with pytest.raises(ValueError):
        FileKV(str(tmp_path / "bad.kv"), fsync="sometimes")


def test_filekv_context_manager(tmp_path):
    path = str(tmp_path / "cm.kv")
    with FileKV(path) as db:
        db.put(b"k", b"v")
        assert not db.closed
    assert db.closed
    with FileKV(path) as db:
        assert db.get(b"k") == b"v"


@pytest.mark.skipif(not available(), reason="native unavailable")
def test_native_context_manager(tmp_path):
    from harmony_tpu.core.kv_native import NativeKV

    path = str(tmp_path / "cm.kv")
    with NativeKV(path) as db:
        db.put(b"k", b"v")
        assert not db.closed
    assert db.closed
