"""ISSUE 19 round forensics tier: per-node span attribution, the
durable JSONL span sink, RoundTimeline phase stitching, clock-skew
alignment for multi-process merges, and histogram exemplars.

The attribution tests drive a REAL pump-driven localnet round (the
test_trace recipe: forced device path via the numpy/bigint twins,
sidecar-backed verification) — the timelines asserted here are built
from the same spans a live deployment exports.
"""

import json
import os

import numpy as np
import pytest

from harmony_tpu import bls as B
from harmony_tpu import device as DV
from harmony_tpu import health
from harmony_tpu import trace
from harmony_tpu.obs import (
    PHASES, RoundTimeline, SpanSink, align_clocks, build_timelines,
    observe_timelines, read_spans,
)
from harmony_tpu.ops import bls as OB
from harmony_tpu.ref import bls as RB
from harmony_tpu.ref.curve import g1

CHAIN_ID = 2


@pytest.fixture(autouse=True)
def _clean(tmp_path):
    trace.reset()
    health.reset()
    trace.configure(dump_dir=str(tmp_path))
    yield
    trace.reset()
    health.reset()


# -- the forced-device twins (test_trace recipe) -----------------------------


def _twin_agg_verify(pk_affs, bitmap, h_aff, agg_sig_aff):
    from harmony_tpu.ops import interop as I

    tbl = np.asarray(pk_affs)
    agg = None
    for i, bit in enumerate(np.asarray(bitmap)):
        if bit:
            agg = g1.add(agg, (I.arr_to_fp(tbl[i][0]),
                               I.arr_to_fp(tbl[i][1])))
    if agg is None:
        return np.asarray(False)
    h = (I.arr_to_fp2(np.asarray(h_aff)[0]),
         I.arr_to_fp2(np.asarray(h_aff)[1]))
    s = (I.arr_to_fp2(np.asarray(agg_sig_aff)[0]),
         I.arr_to_fp2(np.asarray(agg_sig_aff)[1]))
    return np.asarray(RB.verify_hashed(agg, h, s))


def _twin_verify(pk_affs, h_affs, sig_affs):
    from harmony_tpu.ops import interop as I

    out = []
    for pk, h, s in zip(np.asarray(pk_affs), np.asarray(h_affs),
                        np.asarray(sig_affs)):
        out.append(RB.verify_hashed(
            (I.arr_to_fp(pk[0]), I.arr_to_fp(pk[1])),
            (I.arr_to_fp2(h[0]), I.arr_to_fp2(h[1])),
            (I.arr_to_fp2(s[0]), I.arr_to_fp2(s[1])),
        ))
    return np.asarray(out)


@pytest.fixture
def forced_device(monkeypatch):
    DV.use_device(True)
    monkeypatch.setattr(OB, "agg_verify", _twin_agg_verify)
    monkeypatch.setattr(OB, "verify", _twin_verify)
    monkeypatch.setattr(DV, "_SEEN_PROGRAMS", set())
    monkeypatch.setenv("HARMONY_KERNEL_TWIN", "1")
    monkeypatch.setattr(
        "harmony_tpu.ops.twin.agg_verify", _twin_agg_verify
    )
    monkeypatch.setattr("harmony_tpu.ops.twin.verify", _twin_verify)
    yield
    DV.use_device(None)


def _traced_localnet(n_nodes, sidecar_address):
    from harmony_tpu.chain.engine import Engine, EpochContext
    from harmony_tpu.core.blockchain import Blockchain
    from harmony_tpu.core.genesis import dev_genesis
    from harmony_tpu.core.kv import MemKV
    from harmony_tpu.core.tx_pool import TxPool
    from harmony_tpu.multibls import PrivateKeys
    from harmony_tpu.node.node import Node
    from harmony_tpu.node.registry import Registry
    from harmony_tpu.p2p import InProcessNetwork
    from harmony_tpu.sidecar.client import SidecarClient

    genesis, _, bls_keys = dev_genesis(n_keys=n_nodes)
    committee = [k.pub.bytes for k in bls_keys]
    net = InProcessNetwork()
    nodes, clients = [], []
    for i in range(n_nodes):
        client = SidecarClient(sidecar_address)
        clients.append(client)
        engine = Engine(
            lambda s, e, c=committee: EpochContext(c),
            device=False, backend=client,
        )
        chain = Blockchain(MemKV(), genesis, engine=engine,
                           blocks_per_epoch=16)
        pool = TxPool(CHAIN_ID, 0, chain.state)
        reg = Registry(
            blockchain=chain, txpool=pool, host=net.host(f"node{i}")
        )
        nodes.append(Node(reg, PrivateKeys.from_keys([bls_keys[i]])))
    return nodes, clients


def _pump(nodes, rounds=50):
    for _ in range(rounds):
        if not any(n.process_pending() for n in nodes):
            break


def _run_round():
    """One committed round across 4 in-process nodes; spans stay in
    the live store."""
    from harmony_tpu.sidecar.server import SidecarServer

    sidecar = SidecarServer().start()
    nodes, clients = _traced_localnet(4, sidecar.address)
    try:
        leader = next(n for n in nodes if n.is_leader)
        leader.start_round_if_leader()
        _pump(nodes)
        assert all(n.chain.head_number == 1 for n in nodes)
    finally:
        for c in clients:
            c.close()
        for n in nodes:
            n.stop()
        sidecar.stop()


# -- THE acceptance criterion: >= 95% of round wall time attributed ----------


def test_round_attribution_covers_wall_time(forced_device):
    """A deterministic pump-driven round attributes >= 95% of its wall
    time to named phases, every span carries a node identity, and the
    dominating phase is named."""
    trace.configure(enabled=True)
    _run_round()
    timelines = build_timelines(trace.spans())
    assert len(timelines) == 1
    tl = timelines[0]
    assert tl.committed and not tl.partial
    assert tl.attributed_fraction() >= 0.95, tl.to_dict()
    assert tl.dominant_phase() in PHASES
    assert set(tl.phases) <= set(PHASES)
    # the in-process localnet binds a node per pump: the consensus
    # spans are attributable, node0..node3 all appear
    assert {"node0", "node1", "node2", "node3"} <= set(tl.nodes), tl.nodes
    # leader identity comes from the round root's node attr
    assert tl.leader in {"node0", "node1", "node2", "node3"}
    # feeding the histograms: one observation per populated phase
    summary = observe_timelines(timelines)
    assert summary["rounds"] == 1
    assert summary["phase_seconds"]
    assert set(summary["phase_seconds"]) <= set(PHASES)


def test_abandoned_round_degrades_to_partial_timeline():
    """A torn trace (abandoned round: no quorum spans, no duration on
    the root) yields partial=True with whatever evidence exists — and
    never a crash."""
    rnd = {"trace_id": "ab" * 16, "span_id": "01" * 8, "name":
           "consensus.round", "ts": 100.0, "dur_s": 0.8, "pid": 1,
           "attrs": {"node": "node0", "block": 7, "abandoned": True}}
    ann = {"trace_id": "ab" * 16, "span_id": "02" * 8, "name":
           "consensus.phase.announce", "ts": 100.0, "dur_s": 0.01,
           "pid": 1, "attrs": {"node": "node0"}}
    # committed_only (the default) excludes it entirely
    assert build_timelines([rnd, ann]) == []
    tls = build_timelines([rnd, ann], committed_only=False)
    assert len(tls) == 1
    tl = tls[0]
    assert not tl.committed and tl.partial
    assert tl.attributed_fraction() < 0.95  # partial evidence only
    # abandoned rounds never feed the committed-round histograms
    assert observe_timelines(tls)["rounds"] == 0
    # a root with NO duration at all (process died mid-round)
    del rnd["dur_s"]
    rnd["attrs"] = {"node": "node0"}
    tls = build_timelines([rnd, ann], committed_only=False)
    assert len(tls) == 1 and tls[0].partial
    # an empty span set is simply no timelines
    assert build_timelines([]) == []


def test_round_timeline_to_dict_is_json_ready(forced_device):
    trace.configure(enabled=True)
    _run_round()
    tl = build_timelines(trace.spans())[0]
    d = json.loads(json.dumps(tl.to_dict()))
    assert d["trace_id"] == tl.trace_id
    assert d["attributed_fraction"] >= 0.95
    assert d["dominant_phase"] in PHASES
    assert d["committed"] is True and d["partial"] is False


# -- clock-skew guard (multi-process merges) ---------------------------------


def _skewed_trace(skew_s: float):
    """Synthetic two-process round: validator clock off by ``skew_s``.
    On the leader clock: announce sent [0, 0.01], validator received
    at 0.05 (span [0.05, 0.15]), leader got the prepare vote at 0.3,
    prepare_quorum [0.01, 0.35]."""
    tid, mk = "cd" * 16, lambda i: f"{i:02x}" * 8

    def sp(i, name, ts, dur, pid, node, **attrs):
        attrs["node"] = node
        return {"trace_id": tid, "span_id": mk(i), "name": name,
                "ts": ts, "dur_s": dur, "pid": pid, "attrs": attrs}

    return [
        sp(1, "consensus.round", 0.0, 1.0, 1, "L", block=3),
        sp(2, "consensus.phase.announce", 0.0, 0.01, 1, "L"),
        sp(3, "consensus.phase.prepare_quorum", 0.01, 0.34, 1, "L"),
        sp(4, "consensus.phase.commit_quorum", 0.4, 0.4, 1, "L"),
        sp(5, "consensus.prepare", 0.3, 0.001, 1, "L"),
        sp(6, "chain.finalize", 0.85, 0.1, 1, "L"),
        # the validator's receive span, stamped by ITS skewed clock
        sp(7, "consensus.announce", 0.05 + skew_s, 0.1, 2, "V"),
    ]


def test_align_clocks_restores_causality():
    """A validator whose exported timestamps precede the leader's send
    is shifted by the minimum offset restoring receive-after-send;
    already-causal nodes are left untouched."""
    # no skew: every causal edge holds, nothing shifts
    assert align_clocks(_skewed_trace(0.0)) == {}
    # the validator clock runs 2s behind: its receive (leader-time
    # 0.05) exports as -1.95, before the 0.0 send
    offs = align_clocks(_skewed_trace(-2.0))
    assert set(offs) == {"V"}
    # minimum restoring offset: receive lands exactly at the send
    assert offs["V"] == pytest.approx(2.0 - 0.05, abs=1e-9)
    # the builder applies it: the skewed merge still yields a full
    # timeline (the minimal offset puts the receive exactly at the
    # send, so the announce leg collapses to zero — the vote-return
    # leg survives and total attribution holds)
    tls = build_timelines(_skewed_trace(-2.0))
    assert len(tls) == 1
    assert "vote_return" in tls[0].phases
    assert tls[0].attributed_fraction() >= 0.95
    # the unskewed merge keeps the announce leg distinct
    assert "announce_wire" in build_timelines(_skewed_trace(0.0))[0].phases
    # skew_align=False shows why it matters: the receive falls outside
    # [t0, t1] and evidence degrades
    raw = build_timelines(_skewed_trace(-2.0), skew_align=False)
    assert len(raw) == 1
    # a validator clock running AHEAD is bounded by the vote edge: its
    # receive span would END after the leader already counted the vote
    offs = align_clocks(_skewed_trace(+3.0))
    assert set(offs) == {"V"} and offs["V"] < 0
    # monotonic-within-node: one offset per node, never per span
    shifted = build_timelines(_skewed_trace(-2.0))[0]
    assert shifted.wall_s == pytest.approx(1.0)


# -- durable span sink --------------------------------------------------------


def test_sink_roundtrip_rotation_and_heartbeat(tmp_path):
    trace.configure(enabled=True)
    trace.set_node("nodeA")  # process identity -> span attrs AND the
    sink = SpanSink(str(tmp_path), max_bytes=4096,  # sink's file tag
                    keep=2).arm()
    try:
        # the writer is watchdog-registered (GL14)
        assert any(p.name == "obs.sink[nodeA]"
                   for p in health.participants())
        for i in range(200):
            with trace.span("consensus.round", component="consensus",
                            block=i):
                pass
    finally:
        sink.close()
    # close() drained the queue: everything written, nothing dropped
    assert sink.written == 200 and sink.dropped == 0
    # 200 records * ~150B >> 4096: rotation produced generations, and
    # keep=2 bounds them
    files = sink.files()
    assert os.path.basename(sink.path()) == "spans_nodeA.jsonl"
    assert 1 < len(files) <= 3
    # the reader stitches active + rotated back together (newest first;
    # rotation may drop the oldest generations — bounded disk is the
    # contract, not totality)
    spans = read_spans(files)
    assert spans and all(s["name"] == "consensus.round" for s in spans)
    assert all(s["attrs"]["node"] == "nodeA" for s in spans)
    # close() deregistered the heartbeat
    assert not any(p.name.startswith("obs.sink")
                   for p in health.participants())


def test_sink_reader_survives_garbage(tmp_path):
    """GL13 on the read side: oversize records are skipped without
    buffering, garbled JSON and schema-less records are dropped, a
    missing file is an empty result — never a raise."""
    p = tmp_path / "spans_evil.jsonl"
    good = json.dumps({"trace_id": "aa" * 16, "span_id": "bb" * 8,
                       "name": "consensus.round", "ts": 1.0,
                       "dur_s": 0.5, "pid": 9, "attrs": {}})
    with open(p, "w") as f:
        f.write('{"trace_id": 12, "span_id": "x"}\n')  # wrong types
        f.write("{not json at all\n")
        f.write('{"a": "' + "x" * (128 * 1024) + '"}\n')  # oversize
        f.write(good + "\n")
        f.write('{"trace_id": "cc"}')  # truncated mid-record, no \n
    spans = read_spans(str(p))
    assert len(spans) == 1
    assert spans[0]["span_id"] == "bb" * 8
    assert read_spans(str(tmp_path / "missing.jsonl")) == []
    # binary garbage file
    evil2 = tmp_path / "spans_bin.jsonl"
    evil2.write_bytes(os.urandom(4096))
    assert read_spans(str(evil2)) == []


def test_sink_hook_drops_on_full_queue_never_blocks(tmp_path):
    trace.configure(enabled=True)
    sink = SpanSink(str(tmp_path), node="nodeB", queue_cap=4)
    # NOT armed: no writer drains, so the 5th span must drop, not block
    for i in range(8):
        sink._hook(_fake_span(i))
    assert sink.dropped == 4
    sink.close()  # close on a never-armed sink is a no-op


def _fake_span(i):
    class _S:
        def to_dict(self):
            return {"trace_id": "ee" * 16, "span_id": f"{i:02x}" * 8,
                    "name": "x", "ts": float(i), "dur_s": 0.0,
                    "pid": os.getpid(), "tid": 0, "attrs": {}}
    return _S()


# -- node identity ------------------------------------------------------------


def test_node_scope_and_bind_stamp_spans():
    trace.configure(enabled=True)
    with trace.node_scope("alpha"):
        with trace.span("a") as s1:
            with trace.node_scope("beta"):
                with trace.span("b") as s2:
                    pass
            with trace.span("c") as s3:
                pass
    assert s1.attrs["node"] == "alpha"
    assert s2.attrs["node"] == "beta"
    assert s3.attrs["node"] == "alpha"  # scope nested AND restored
    trace.set_node("proc-default")
    with trace.span("d") as s4:
        pass
    assert s4.attrs["node"] == "proc-default"
    # explicit attr wins over ambient identity
    with trace.span("e", node="forced") as s5:
        pass
    assert s5.attrs["node"] == "forced"


def test_node_scope_disabled_is_shared_noop():
    """One-bool discipline: with tracing disarmed, node_scope returns
    the shared no-op singleton — no allocation, no contextvar churn."""
    assert not trace.enabled()
    assert trace.node_scope("a") is trace.node_scope("b")
    assert trace.node_scope("a") is trace.span("x")


# -- histogram exemplars ------------------------------------------------------


def test_histogram_exemplars_bounded_and_gated():
    from harmony_tpu.metrics import Histogram

    h = Histogram("test_obs_exemplar_seconds", "t",
                  buckets=(0.1, 1.0), labels={"k": "v"})
    trace.configure(enabled=True)
    tids = []
    for i in range(50):  # 50 observations, only 3 buckets -> bounded
        with trace.span("r") as sp:
            h.observe(0.05 if i % 2 else 5.0)
        tids.append(sp.trace_id)
    assert len(h._exemplars) <= len(h.buckets) + 1
    # last-exemplar-per-bucket: the retained ids are recent ones
    for idx, (tid, _val) in h._exemplars.items():
        assert tid in tids
    plain = h.expose()
    assert "# {" not in plain  # default scrape stays exemplar-free
    ex = h.expose(exemplars=True)
    assert ' # {trace_id="' in ex
    # every line with a suffix is a _bucket line
    for line in ex.splitlines():
        if "# {" in line and not line.startswith("#"):
            assert "_bucket" in line
    # untraced observations leave no exemplar
    h2 = Histogram("test_obs_exemplar2_seconds", "t", buckets=(1.0,))
    trace.configure(enabled=False)
    h2.observe(0.5)
    assert h2._exemplars == {}
    assert "# {" not in h2.expose(exemplars=True)


# -- replay stages ------------------------------------------------------------


def test_replay_stage_histogram_and_quantiles():
    from harmony_tpu.obs import REPLAY_STAGE_SECONDS, REPLAY_STAGES
    from harmony_tpu.obs import replay

    base = replay.snapshot()
    with replay.stage("execute", block=5):
        pass
    with replay.stage("kv_commit", block=5):
        pass
    q = replay.quantiles_since(base)
    assert set(q) == {"execute", "kv_commit"}
    for stage_q in q.values():
        assert stage_q["count"] == 1
        assert stage_q["sum_s"] >= 0
        assert "p50_s" in stage_q and "p99_s" in stage_q
    assert set(REPLAY_STAGE_SECONDS) == set(REPLAY_STAGES)


def test_replay_stage_spans_join_ambient_trace():
    from harmony_tpu.obs import replay

    trace.configure(enabled=True)
    with trace.span("consensus.round", component="consensus") as root:
        with replay.stage("seal_verify", blocks=2):
            pass
    spans = trace.spans(root.trace_id)
    st = next(s for s in spans if s.name == "replay.seal_verify")
    assert st.parent_id == root.span_id
    assert st.attrs["blocks"] == 2
