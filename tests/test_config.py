"""Chain-config gates and sharding-schedule tests."""

import pytest

from harmony_tpu.config import ChainConfig, Instance, Schedule
from harmony_tpu.config.sharding import LOCALNET, MAINNET_LIKE
from harmony_tpu.numeric import Dec


def test_epoch_gates():
    cfg = ChainConfig(
        staking_epoch=10, two_seconds_epoch=None, extra={"hip30": 50}
    )
    assert not cfg.is_staking(9)
    assert cfg.is_staking(10)
    assert cfg.is_staking(11)
    assert not cfg.is_two_seconds(10**9)  # None never activates
    assert not cfg.is_active("hip30", 49)
    assert cfg.is_active("hip30", 50)
    assert not cfg.is_active("unknown", 50)


def test_schedule_lookup():
    s = MAINNET_LIKE  # alias of the exact MAINNET schedule since r5
    assert s.instance_for_epoch(0).num_shards == 4
    assert s.instance_for_epoch(207).harmony_nodes_per_shard == 170
    assert s.instance_for_epoch(208).harmony_nodes_per_shard == 130
    assert s.instance_for_epoch(1673).num_shards == 2
    v5 = s.instance_for_epoch(10**6)
    assert v5.harmony_vote_percent.equal(Dec.from_str("0.01"))
    assert v5.external_vote_percent().equal(Dec.from_str("0.99"))
    assert v5.external_slots_per_shard() == 198
    assert v5.total_slots() == 400


def test_schedule_validation():
    inst = LOCALNET.instance_for_epoch(0)
    assert inst.num_shards == 2
    with pytest.raises(ValueError):
        Schedule([])
    with pytest.raises(ValueError):
        Schedule([(5, inst)])  # must start at 0
    with pytest.raises(ValueError):
        Schedule([(0, inst), (10, inst), (5, inst)])  # not ascending
