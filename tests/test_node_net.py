"""Networking + node integration: gossip hosts, sync streams, staged
download, and a full in-process FBFT localnet committing blocks (the
reference's localnet test tier — SURVEY.md §4 — in one process)."""

import threading
import time

import pytest

from harmony_tpu import bls as B
from harmony_tpu.core.blockchain import Blockchain
from harmony_tpu.core.genesis import dev_genesis
from harmony_tpu.core.kv import MemKV
from harmony_tpu.core.tx_pool import TxPool
from harmony_tpu.core.types import Transaction
from harmony_tpu.crypto_ecdsa import ECDSAKey
from harmony_tpu.multibls import PrivateKeys
from harmony_tpu.node.node import Node
from harmony_tpu.node.registry import Registry
from harmony_tpu.node.services import Manager, Service, ServiceType
from harmony_tpu.node.worker import Worker
from harmony_tpu.p2p import InProcessNetwork, TCPHost, consensus_topic
from harmony_tpu.p2p.gating import Gater
from harmony_tpu.p2p.host import ACCEPT, IGNORE
from harmony_tpu.p2p.stream import SyncClient, SyncServer
from harmony_tpu.sync import Downloader

CHAIN_ID = 2


# -- hosts ------------------------------------------------------------------

def test_inprocess_gossip_validate_and_deliver():
    net = InProcessNetwork()
    a, b, c = net.host("a"), net.host("b"), net.host("c")
    got = []
    b.subscribe("t", lambda t, p, f: got.append((t, p, f)))
    c.add_validator("t", lambda p, f: ACCEPT if p != b"bad" else IGNORE)
    got_c = []
    c.subscribe("t", lambda t, p, f: got_c.append(p))
    a.publish("t", b"hello")
    a.publish("t", b"bad")
    assert got == [("t", b"hello", "a"), ("t", b"bad", "a")]
    assert got_c == [b"hello"]  # validator filtered "bad"


def test_tcp_gossip_relay_and_dedup():
    h1 = TCPHost("n1")
    h2 = TCPHost("n2")
    h3 = TCPHost("n3")
    try:
        # line topology: n1 - n2 - n3; the message must transit n2.
        # Mesh semantics (gossipsub, like the reference): only peers
        # participating in a topic relay it — n2 registers a validator
        # (the relay posture every shard node has for its topics)
        from harmony_tpu.p2p.host import ACCEPT as _A

        h2.add_validator("x", lambda p, f: _A)
        h2.connect(h1.port)
        h3.connect(h2.port)
        assert h1.wait_for_peers(1) and h3.wait_for_peers(1)
        assert h2.wait_for_peers(2)
        got1, got3 = [], []
        h1.subscribe("x", lambda t, p, f: got1.append(p))
        h3.subscribe("x", lambda t, p, f: got3.append(p))
        h1.publish("x", b"m1")
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not got3:
            time.sleep(0.01)
        assert got3 == [b"m1"]
        assert got1 == []  # no self-delivery, no echo back
    finally:
        h1.close(), h2.close(), h3.close()


def test_gater_limits():
    g = Gater(max_peers=2, max_per_ip=1)
    assert g.allow("10.0.0.1")
    assert not g.allow("10.0.0.1")  # per-ip
    assert g.allow("10.0.0.2")
    assert not g.allow("10.0.0.3")  # total
    g.release("10.0.0.1")
    g.ban("10.0.0.3")
    assert not g.allow("10.0.0.3")  # banned even though slot free
    assert g.allow("10.0.0.1")


# -- sync streams -----------------------------------------------------------

def _chain_with_blocks(n=3):
    genesis, keys, bls_keys = dev_genesis()
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    pool = TxPool(CHAIN_ID, 0, chain.state)
    worker = Worker(chain, pool)
    to = b"\x05" * 20
    for i in range(n):
        tx = Transaction(
            nonce=i, gas_price=1, gas_limit=25_000, shard_id=0,
            to_shard=0, to=to, value=100 + i,
        ).sign(keys[0], CHAIN_ID)
        pool.add(tx)
        block = worker.propose_block(view_id=i + 1)
        chain.insert_chain([block], verify_seals=False)
        chain.write_commit_sig(block.block_num, b"\x01" * 96 + b"\x0f")
        pool.drop_applied()
    return chain, genesis


def test_sync_stream_and_staged_download():
    serving, genesis = _chain_with_blocks(5)
    srv = SyncServer(serving)
    try:
        cli = SyncClient(srv.port)
        head, head_hash = cli.get_head()
        assert head == 5
        assert head_hash == serving.current_header().hash()
        hashes = cli.get_block_hashes(1, 5)
        assert len(hashes) == 5
        blocks = cli.get_blocks_by_number(1, 2)
        assert [b.block_num for b, _ in blocks] == [1, 2]
        assert blocks[0][0].hash() == hashes[0]
        assert blocks[0][1] is not None  # commit sig travels along

        # fresh chain catches up via the staged downloader
        fresh = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
        dl = Downloader(fresh, [SyncClient(srv.port)], batch=2,
                        verify_seals=False)
        res = dl.sync_once()
        assert res.inserted == 5 and not res.errors
        assert fresh.head_number == 5
        assert fresh.current_header().hash() == head_hash
        assert fresh.state().root() == serving.state().root()
    finally:
        srv.close()


def test_fast_sync_joins_head_without_replay():
    """VERDICT r3 #6: a node with EMPTY state reaches the head through
    the states stage (account-range download bound to the sealed state
    root) instead of replaying every block; receipts for the recent
    tail arrive via METHOD_RECEIPTS."""
    serving, genesis = _chain_with_blocks(5)
    srv = SyncServer(serving)
    try:
        fresh = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
        dl = Downloader(fresh, [SyncClient(srv.port)], batch=2,
                        verify_seals=False)
        # canary: fast_sync must NOT execute transactions on the fresh
        # chain — make replay impossible by poisoning the processor
        dl.chain.processor = None
        res = dl.fast_sync(receipts_tail=2)
        assert res.inserted == 5 and not res.errors
        assert fresh.head_number == 5
        assert fresh.current_header().hash() == (
            serving.current_header().hash()
        )
        assert fresh.state().root() == serving.state().root()
        # the receipts tail (blocks 4-5) was fetched and indexed
        from harmony_tpu.core import rawdb

        assert rawdb.read_receipts(fresh.db, 5)
        assert [r.tx_hash for r in rawdb.read_receipts(fresh.db, 5)] == [
            r.tx_hash for r in rawdb.read_receipts(serving.db, 5)
        ]
        # a fast-synced node keeps extending normally (processor back)
        from harmony_tpu.core.state_processor import StateProcessor

        fresh.processor = StateProcessor(CHAIN_ID, 0)
    finally:
        srv.close()


def test_fast_sync_rejects_forged_receipts():
    """ADVICE r4: the receipts stage verifies every downloaded list
    against the sealed header's receipt_root — a peer serving forged
    statuses/logs is rotated away instead of poisoning
    eth_getTransactionReceipt."""
    serving, genesis = _chain_with_blocks(3)
    srv = SyncServer(serving)

    class ForgingClient(SyncClient):
        def get_receipts(self, start, count, deadline=None):
            per_block = super().get_receipts(start, count, deadline)
            for receipts in per_block:
                for r in receipts:
                    r.status = 0  # flip success -> failure
            return per_block

    try:
        fresh = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
        dl = Downloader(fresh, [ForgingClient(srv.port)], batch=2,
                        verify_seals=False)
        res = dl.fast_sync(receipts_tail=2)
        # chain still syncs; the forged receipts were refused
        assert fresh.head_number == 3
        assert any("receipts commitment mismatch" in e for e in res.errors)
        from harmony_tpu.core import rawdb

        assert not rawdb.read_receipts(fresh.db, 3)
        # an honest second peer heals the tail
        dl2 = Downloader(fresh, [SyncClient(srv.port)], batch=2,
                         verify_seals=False)
        dl2.fast_sync(receipts_tail=2)
    finally:
        srv.close()


def test_fast_sync_rotates_on_non_advancing_account_pages():
    """ADVICE r4: a peer repeating account-range pages must not wedge
    the states stage in an infinite loop — the downloader breaks and
    rotates to the next peer."""
    serving, genesis = _chain_with_blocks(3)
    srv = SyncServer(serving)

    class LoopingClient(SyncClient):
        def get_account_range(self, num, start, deadline=None):
            page = super().get_account_range(num, b"")
            return page  # always the FIRST page: start never advances

    try:
        fresh = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
        dl = Downloader(
            fresh, [LoopingClient(srv.port), SyncClient(srv.port)],
            batch=2, verify_seals=False,
        )
        res = dl.fast_sync(receipts_tail=1)
        assert res.inserted == 3 and not res.errors  # healed via peer 2
        assert fresh.state().root() == serving.state().root()
    finally:
        srv.close()


def test_fast_sync_harvests_committees_from_sealed_headers():
    """The fast-sync trust chain across an election (VERDICT r3 #6 +
    review hardening): the next epoch's committee is read from the
    seal-verified election HEADER (header.shard_state, written by the
    proposer and replay-verified), never from a peer's epoch-state
    blob — a peer serving forged epoch states cannot influence seal
    verification.  Reference: block header ShardState + epochchain.go;
    stagedstreamsync."""
    from harmony_tpu.chain.engine import Engine, EpochContext
    from harmony_tpu.chain.finalize import FinalizeConfig, Finalizer
    from harmony_tpu.consensus.mask import Mask
    from harmony_tpu.consensus.signature import construct_commit_payload

    genesis, ecdsa_keys, bls_keys = dev_genesis()

    def _mk_chain():
        fin = Finalizer(FinalizeConfig(
            block_reward=28 * 10**18,
            shard_count=1,
            external_slots_per_shard=2,
            harmony_accounts=[
                (k.address(), pub)
                for k, pub in zip(ecdsa_keys, genesis.committee)
            ],
        ))
        chain = Blockchain(MemKV(), genesis, blocks_per_epoch=4,
                           finalizer=fin)
        chain.engine = Engine(
            lambda shard, epoch: EpochContext(
                chain.committee_for_epoch(epoch)
            ),
            device=False,
        )
        return chain

    def _proof(header):
        payload = construct_commit_payload(
            header.hash(), header.block_num, header.view_id, True
        )
        sigs = [k.sign_hash(payload) for k in bls_keys]
        agg = B.aggregate_sigs(sigs)
        mask = Mask([k.pub.point for k in bls_keys])
        for i in range(len(bls_keys)):
            mask.set_bit(i, True)
        return agg.bytes + mask.mask_bytes()

    serving = _mk_chain()
    worker = Worker(serving, None)
    for i in range(5):  # block 3 is the election block (BPE=4)
        block = worker.propose_block(view_id=i + 1)
        serving.insert_chain([block], verify_seals=False)
        serving.write_commit_sig(block.block_num, _proof(block.header))
    assert serving.header_by_number(3).shard_state  # committee carried

    srv = SyncServer(serving)
    try:
        fresh = _mk_chain()
        client = SyncClient(srv.port)
        # poison the epoch-state RPC: the trustless path must not ask
        client.get_epoch_state = None
        dl = Downloader(fresh, [client], batch=2, verify_seals=True)
        res = dl.fast_sync(receipts_tail=1)
        assert res.inserted == 5 and not res.errors, res.errors
        assert fresh.head_number == 5
        assert fresh.state().root() == serving.state().root()
        # the epoch-1 committee came from the sealed election header
        assert fresh.committee_for_epoch(1) == (
            serving.committee_for_epoch(1)
        )
        # a corrupted seal in the window is rejected outright
        fresh2 = _mk_chain()
        import harmony_tpu.core.rawdb as rawdb_mod

        blob = serving.read_commit_sig(2)
        serving.write_commit_sig(2, blob[:10] + b"\x00" * 86 + blob[96:])
        dl2 = Downloader(fresh2, [SyncClient(srv.port)], batch=5,
                         verify_seals=True)
        res2 = dl2.fast_sync()
        assert res2.errors and fresh2.head_number == 0
        serving.write_commit_sig(2, blob)  # restore
    finally:
        srv.close()


def test_adopt_state_rejects_forged_accounts():
    """adopt_state is the trust boundary of the states stage: accounts
    that do not hash to the sealed state root must be rejected."""
    from harmony_tpu.core.blockchain import ChainError
    from harmony_tpu.core.state import StateDB

    serving, genesis = _chain_with_blocks(2)
    forged = StateDB({b"\x07" * 20: serving.state().account(b"\x07" * 20)})
    forged.add_balance(b"\x07" * 20, 10**18)
    with pytest.raises(ChainError):
        serving.adopt_state(2, forged)


def test_account_range_pagination_covers_state():
    serving, _ = _chain_with_blocks(3)
    srv = SyncServer(serving)
    try:
        cli = SyncClient(srv.port)
        # page size 2 forces multiple round trips
        start, got = b"", []
        while True:
            page = cli.get_account_range(3, start, limit=2)
            got.extend(page)
            if not page:
                break
            start = page[-1][0]
        addrs = [a for a, _ in got]
        assert addrs == sorted(addrs)
        assert len(addrs) == len(set(addrs))
        live = dict(serving.state_at(3)._live_accounts())
        assert set(addrs) == set(live)
        for addr, blob in got:
            assert blob == live[addr].encode()
    finally:
        srv.close()


# -- service manager --------------------------------------------------------

class _SpySvc(Service):
    def __init__(self, log, name, fail=False):
        self.log, self.name, self.fail = log, name, fail

    def start(self):
        if self.fail:
            raise RuntimeError("boom")
        self.log.append(("start", self.name))

    def stop(self):
        self.log.append(("stop", self.name))


def test_service_manager_order_and_rollback():
    log = []
    m = Manager()
    m.register(ServiceType.CONSENSUS, _SpySvc(log, "consensus"))
    m.register(ServiceType.SYNCHRONIZE, _SpySvc(log, "sync"))
    m.start_services()
    m.stop_services()
    assert log == [
        ("start", "consensus"), ("start", "sync"),
        ("stop", "sync"), ("stop", "consensus"),
    ]
    log.clear()
    m2 = Manager()
    m2.register(ServiceType.CONSENSUS, _SpySvc(log, "a"))
    m2.register(ServiceType.PROMETHEUS, _SpySvc(log, "b", fail=True))
    with pytest.raises(RuntimeError):
        m2.start_services()
    assert log == [("start", "a"), ("stop", "a")]  # rollback


# -- the localnet: N nodes committing blocks over gossip --------------------

def _make_localnet(n_nodes=4):
    genesis, ecdsa_keys, bls_keys = dev_genesis(n_keys=n_nodes)
    net = InProcessNetwork()
    nodes = []
    for i in range(n_nodes):
        chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
        pool = TxPool(CHAIN_ID, 0, chain.state)
        reg = Registry(
            blockchain=chain, txpool=pool, host=net.host(f"node{i}")
        )
        node = Node(reg, PrivateKeys.from_keys([bls_keys[i]]))
        nodes.append(node)
    return nodes, ecdsa_keys, net


def _pump(nodes, rounds=50):
    for _ in range(rounds):
        if not any(n.process_pending() for n in nodes):
            break


def test_localnet_commits_blocks_over_gossip():
    nodes, ecdsa_keys, net = _make_localnet(4)
    leaders = [n for n in nodes if n.is_leader]
    assert len(leaders) == 1

    # round 1: empty block
    leaders[0].start_round_if_leader()
    _pump(nodes)
    assert all(n.chain.head_number == 1 for n in nodes)
    assert all(n.committed_blocks == 1 for n in nodes)

    # round 2: a transfer reaches every replica's state
    to = b"\x0a" * 20
    tx = Transaction(
        nonce=0, gas_price=1, gas_limit=25_000, shard_id=0, to_shard=0,
        to=to, value=777,
    ).sign(ecdsa_keys[0], CHAIN_ID)
    leaders2 = [n for n in nodes if n.is_leader]
    assert len(leaders2) == 1
    # leader rotated (round-robin by view id)
    leaders2[0].pool.add(tx)
    leaders2[0].start_round_if_leader()
    _pump(nodes)
    assert all(n.chain.head_number == 2 for n in nodes)
    assert all(n.chain.state().balance(to) == 777 for n in nodes)
    # every replica stored the quorum proof for the committed block
    assert all(n.chain.read_commit_sig(2) is not None for n in nodes)


def test_localnet_tolerates_partitioned_validator():
    nodes, _, net = _make_localnet(4)
    # cut one NON-leader node off; 3 of 4 still exceeds 2/3+1 quorum
    victim = next(n for n in nodes if not n.is_leader)
    net.partitioned.add(victim.host.name)
    leader = next(n for n in nodes if n.is_leader)
    leader.start_round_if_leader()
    _pump(nodes)
    live = [n for n in nodes if n is not victim]
    assert all(n.chain.head_number == 1 for n in live)
    assert victim.chain.head_number == 0


def test_single_node_committee_self_quorum():
    """A committee whose leader alone meets quorum must produce blocks
    without any external votes (the announce-time self-vote plus
    leader self-commit drain through _leader_advance)."""
    genesis, ecdsa_keys, bls_keys = dev_genesis(n_keys=1)
    net = InProcessNetwork()
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    pool = TxPool(CHAIN_ID, 0, chain.state)
    reg = Registry(blockchain=chain, txpool=pool, host=net.host("solo"))
    node = Node(reg, PrivateKeys.from_keys(bls_keys))
    assert node.is_leader
    node.start_round_if_leader()
    assert node.chain.head_number == 1
    node.start_round_if_leader()
    assert node.chain.head_number == 2
    assert node.chain.read_commit_sig(1) is not None


# -- view change ------------------------------------------------------------

def test_view_change_replaces_failed_leader():
    """Leader partitioned before proposing: validators time out, view-
    change to the next leader, and commit a fresh block (M2/NIL path)."""
    nodes, _, net = _make_localnet(4)
    leader = next(n for n in nodes if n.is_leader)
    net.partitioned.add(leader.host.name)
    live = [n for n in nodes if n is not leader]
    for n in live:
        n.start_view_change()
    _pump(nodes)
    new_leader = next(n for n in live if n.is_leader)
    assert new_leader is not leader
    new_leader.start_round_if_leader()
    _pump(nodes)
    assert all(n.chain.head_number == 1 for n in live)
    assert leader.chain.head_number == 0
    assert all(not n.in_view_change for n in live)


def test_view_change_carries_prepared_block():
    """Leader dies AFTER broadcasting PREPARED: the view change carries
    the prepared block (M1) and the new leader re-proposes THE SAME
    block — same hash, original header view — which then commits."""
    nodes, _, net = _make_localnet(4)
    leader = next(n for n in nodes if n.is_leader)
    validators = [n for n in nodes if n is not leader]

    proposed = leader.start_round_if_leader()
    # validators vote prepare
    for v in validators:
        v.process_pending()
    # leader reaches prepare quorum and broadcasts PREPARED...
    leader.process_pending(max_msgs=2)
    # ...validators receive it and send commit votes...
    for v in validators:
        v.process_pending()
    # ...then the leader vanishes before COMMITTED
    net.partitioned.add(leader.host.name)
    assert all(v._prepared_proof is not None for v in validators)

    for v in validators:
        v.start_view_change()
    _pump(nodes)
    new_leader = next(v for v in validators if v.is_leader)
    assert new_leader._reproposal is not None or new_leader._proposed
    new_leader.start_round_if_leader()
    _pump(nodes)
    assert all(v.chain.head_number == 1 for v in validators)
    committed = validators[0].chain.block_by_number(1)
    # the SAME block survived: same hash, original proposal view
    assert committed.hash() == proposed.hash()
    assert committed.header.view_id == proposed.header.view_id


def test_precommit_and_propose_pipelining():
    """With pipelining armed (live mode), the leader's commit is
    immediately followed by the next proposal — no pacing-tick wait
    (reference: consensus_v2.go:559-635 preCommitAndPropose)."""
    nodes, _, net = _make_localnet(4)
    leader = next(n for n in nodes if n.is_leader)
    for n in nodes:
        n.pipelining = True
        n.block_time = 0.0  # block period elapsed: propose eagerly
    leader.start_round_if_leader()
    # pump until the pipelined follow-up round lands (round 2 proposes
    # itself off the back of round 1's COMMITTED — nobody calls
    # start_round_if_leader again); stop as soon as it has
    for _ in range(200):
        if all(n.chain.head_number >= 2 for n in nodes):
            break
        if not any(n.process_pending(max_msgs=4) for n in nodes):
            break
    assert all(n.chain.head_number >= 2 for n in nodes)


def test_behind_node_spins_up_sync():
    """A node that sees a run of future-round messages must trigger the
    downloader and rejoin at the synced head (reference:
    consensus/downloader.go:13-107 spinUpStateSync)."""
    nodes, _, net = _make_localnet(4)
    # run two rounds normally
    for _ in range(2):
        next(n for n in nodes if n.is_leader).start_round_if_leader()
        _pump(nodes)
    assert all(n.chain.head_number == 2 for n in nodes)

    # a fresh node joins late with a sync path to node0
    genesis = nodes[0].chain.genesis
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    pool = TxPool(CHAIN_ID, 0, chain.state)
    srv = SyncServer(nodes[0].chain, listen_port=0)
    reg = Registry(blockchain=chain, txpool=pool, host=net.host("late"))
    late = Node(reg, PrivateKeys.from_keys([]))
    reg.set("downloader", Downloader(chain, [SyncClient(srv.port)],
                                     verify_seals=False))
    try:
        assert late.chain.head_number == 0
        # future-round gossip: fabricate announce-shaped envelopes for
        # round 3 (late node is at round 1) — after the threshold run,
        # the downloader spins up
        from harmony_tpu.consensus.messages import (
            FBFTMessage, MsgType, encode_message, sign_message,
        )
        from harmony_tpu.node.ingress import (
            MessageCategory, pack_envelope,
        )

        keys = PrivateKeys.from_keys(
            [B.PrivateKey.generate(bytes([7]))]
        )
        msg = sign_message(FBFTMessage(
            msg_type=MsgType.ANNOUNCE, view_id=3, block_num=3,
            block_hash=b"\x01" * 32,
            sender_pubkeys=[k.pub.bytes for k in keys],
        ), keys)
        env = pack_envelope(
            MessageCategory.CONSENSUS, int(msg.msg_type),
            encode_message(msg),
        )
        for _ in range(late.ahead_threshold):
            late._handle(env)
        assert late._syncing or late._sync_done.is_set()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            late.process_pending()
            if late.chain.head_number == 2 and not late._syncing:
                break
            time.sleep(0.05)
        assert late.chain.head_number == 2
        assert late.sync_spinups == 1
        assert late.block_num == 3  # rejoined at the network's round
    finally:
        srv.close()


def test_vrf_gated_proposal_carries_verifiable_proof():
    """With the 'vrf' epoch gate active, proposals carry the leader's
    BLS-VRF proof over the parent hash and replicas verify it
    (reference: consensus_v2.go VRF in gated headers)."""
    genesis, ecdsa_keys, bls_keys = dev_genesis(n_keys=1)
    genesis.config.extra["vrf"] = 0  # active from epoch 0
    net = InProcessNetwork()
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    pool = TxPool(CHAIN_ID, 0, chain.state)
    reg = Registry(blockchain=chain, txpool=pool, host=net.host("v"))
    node = Node(reg, PrivateKeys.from_keys(bls_keys))
    parent_hash = chain.current_header().hash()
    block = node.start_round_if_leader()
    assert block is not None and block.header.vrf != b""
    from harmony_tpu import crypto_vrf

    out = crypto_vrf.verify(
        bls_keys[0].pub, parent_hash, block.header.vrf
    )
    assert len(out) == 32
    # a stranger's proof would be rejected
    other = B.PrivateKey.generate(b"\x99")
    _, bad_proof = crypto_vrf.evaluate(other, parent_hash)
    with pytest.raises(ValueError):
        crypto_vrf.verify(bls_keys[0].pub, parent_hash, bad_proof)


def test_operator_distinct_leader_rotation():
    """With the LeaderRotation gate active, a multi-key operator gets
    ONE leadership turn per cycle (quorum.go NthNextValidator
    semantics)."""
    from harmony_tpu.core import rawdb
    from harmony_tpu.shard.committee import Committee, Slot, State

    genesis, ecdsa_keys, bls_keys = dev_genesis(n_keys=4)
    genesis.config.leader_rotation_epoch = 0
    net = InProcessNetwork()
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    # operator A runs slots 0-2 (3 keys), operator B runs slot 3
    serialized = [k.pub.bytes for k in bls_keys]
    state = State(epoch=0, shards=[Committee(shard_id=0, slots=[
        Slot(ecdsa_address=b"\xaa" * 20, bls_pubkey=serialized[0]),
        Slot(ecdsa_address=b"\xaa" * 20, bls_pubkey=serialized[1]),
        Slot(ecdsa_address=b"\xaa" * 20, bls_pubkey=serialized[2]),
        Slot(ecdsa_address=b"\xbb" * 20, bls_pubkey=serialized[3]),
    ])])
    rawdb.write_shard_state(chain.db, 0, state)
    chain._committee_cache.clear()
    pool = TxPool(CHAIN_ID, 0, chain.state)
    reg = Registry(blockchain=chain, txpool=pool, host=net.host("r"))
    node = Node(reg, PrivateKeys.from_keys([bls_keys[0]]))
    # cycle length = number of DISTINCT operators (2), not slots (4):
    # view v -> operator (v % 2)'s first slot key
    assert node.leader_key(0) == serialized[0]  # operator A
    assert node.leader_key(1) == serialized[3]  # operator B
    assert node.leader_key(2) == serialized[0]  # back to A — one turn
    assert node.leader_key(3) == serialized[3]
    # without the gate: uniform over all 4 slots
    genesis.config.leader_rotation_epoch = None
    assert [node.leader_key(v) for v in range(4)] == serialized


def test_tcp_validation_pool_and_peer_scoring():
    """reference: p2p/host.go's bounded validate pool + gossipsub
    scoring's role: spam that fails validation drives the sender's
    score to the floor, dropping that CONNECTION; the shared loopback
    address stays un-banned (ADVICE r4: no collateral IP bans), and
    the reader thread never blocks on a slow validator."""
    h1 = TCPHost("spammer")
    h2 = TCPHost("victim")
    h2.SCORE_FLOOR = -5.0  # fail fast for the test
    try:
        h1.connect(h2.port)
        assert h2.wait_for_peers(1) and h1.wait_for_peers(1)
        from harmony_tpu.p2p.host import REJECT

        good = []

        def verdict(p, f):
            if p.startswith(b"ok"):
                return ACCEPT
            if p.startswith(b"meh"):
                return IGNORE  # routine filtering: NOT punishable
            return REJECT

        h2.add_validator("t", verdict)
        h2.subscribe("t", lambda t, p, f: good.append(p))
        h1.publish("t", b"ok-1")
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not good:
            time.sleep(0.01)
        assert good == [b"ok-1"]
        # IGNOREd traffic accrues no score: the peer must survive it
        # (gossipsub semantics — role filtering is free)
        for i in range(10):
            h1.publish("t", b"meh-%d" % i)
        time.sleep(1.0)
        assert h2.peer_count() == 1
        # REJECTed junk: the victim bans the spammer.  Scores decay
        # toward zero between hits (SCORE_DECAY_PER_S), so under a
        # loaded 1-core box the first volley may land too slowly to
        # reach the floor — keep publishing until the drop
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and h2.peer_count():
            for i in range(10):
                h1.publish("t", b"junk-%d" % i)
            time.sleep(0.2)
        assert h2.peer_count() == 0  # the offending connection dropped
        # loopback is NEVER IP-banned: honest peers sharing the address
        # must stay connectable (the ban was per-connection)
        assert h2.gater.allow("127.0.0.1")
        assert good == [b"ok-1"]  # junk never delivered
        # repeated floor hits from distinct NON-loopback connections DO
        # escalate to the gater (driven directly: loopback sockets are
        # all this test topology has)
        class _Sock:
            def close(self):
                pass

        for _ in range(h2.IP_BAN_STRIKES):
            sock = _Sock()
            for _ in range(10):
                h2._punish("10.9.8.7", sock)
        assert not h2.gater.allow("10.9.8.7")
    finally:
        h1.close(), h2.close()


def test_tcp_per_peer_ingress_rate_limit():
    """One chatty peer is throttled ahead of the validation pool; a
    quiet peer on the same IP keeps flowing (buckets key on the
    CONNECTION, so neither a shared address nor a spoofed HELLO name
    pools or drains another peer's budget)."""
    chatty = TCPHost("chatty")
    quiet = TCPHost("chatty")  # same (spoofed) name, same 127.0.0.1
    h2 = TCPHost("victim", msg_rate=5.0, msg_burst=10)
    try:
        chatty.connect(h2.port)
        quiet.connect(h2.port)
        assert h2.wait_for_peers(2)
        assert chatty.wait_for_peers(1) and quiet.wait_for_peers(1)
        got = []
        h2.subscribe("t", lambda t, p, f: got.append(p))
        for i in range(50):
            chatty.publish("t", b"m%d" % i)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and h2.dropped_rate_limited == 0:
            time.sleep(0.02)
        assert h2.dropped_rate_limited > 0  # excess shed
        time.sleep(0.3)
        flood_got = len(got)
        assert 0 < flood_got <= 12  # burst-bounded delivery, no flood
        # the quiet peer's own bucket is untouched by the flood
        quiet.publish("t", b"quiet-1")
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and b"quiet-1" not in got:
            time.sleep(0.02)
        assert b"quiet-1" in got
    finally:
        chatty.close(), quiet.close(), h2.close()
