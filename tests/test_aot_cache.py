"""AOT content-addressed executable cache + fallback accounting.

Covers ISSUE 17's satellite contract: cache hit / miss / corrupt-
artifact / version-skew behavior of harmony_tpu.aot, the once-per-
artifact fallback logging with ``harmony_aot_fallback_total{reason}``,
resolve() precedence, twin-mode warmup marking, and the committed
compile manifest's shape.  The one real executable these tests
serialize is a scalar add — nothing pairing-shaped ever compiles.
"""

import json
import os
import pathlib
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from harmony_tpu import aot  # noqa: E402


@pytest.fixture(autouse=True)
def _isolated_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("HARMONY_AOT_CACHE", str(tmp_path / "aotc"))
    aot._reset_for_tests()
    yield
    aot._reset_for_tests()


def _tiny_compiled():
    import jax
    import jax.numpy as jnp

    return jax.jit(lambda x: x + 1).lower(
        jax.ShapeDtypeStruct((), jnp.int32)).compile()


def _counts(counter, **labels):
    return counter.value(**labels)


def test_cache_store_then_load_hits():
    compiled = _tiny_compiled()
    key = aot.cache_key("sha-tiny", (8,), "cpu")
    hits0 = _counts(aot.CACHE_EVENTS, event="hit")
    stores0 = _counts(aot.CACHE_EVENTS, event="store")
    assert aot.cache_store(key, compiled, {
        "program": "tiny_b8", "bucket": [8],
        "jaxlib": aot.jaxlib_version(), "backend": "cpu",
    })
    assert _counts(aot.CACHE_EVENTS, event="store") == stores0 + 1
    loaded = aot.cache_load(key, "tiny_b8")
    assert loaded is not None
    assert _counts(aot.CACHE_EVENTS, event="hit") == hits0 + 1
    import numpy as np

    assert int(np.asarray(loaded(np.int32(41)))) == 42
    meta = aot.cache_meta(key)
    assert meta["program"] == "tiny_b8" and meta["bucket"] == [8]


def test_cache_miss_counts():
    miss0 = _counts(aot.CACHE_EVENTS, event="miss")
    assert aot.cache_load("0" * 64, "absent_b8") is None
    assert _counts(aot.CACHE_EVENTS, event="miss") == miss0 + 1


def test_corrupt_artifact_unlinked_and_counted():
    key = aot.cache_key("sha-corrupt", (8,), "cpu")
    d = aot.cache_dir()
    os.makedirs(d, exist_ok=True)
    art = os.path.join(d, key + ".aotx")
    with open(art, "wb") as f:
        f.write(b"not a pickled executable")
    corrupt0 = _counts(aot.CACHE_EVENTS, event="corrupt")
    fb0 = _counts(aot.FALLBACKS, reason="corrupt")
    assert aot.cache_load(key, "corrupt_b8") is None
    assert _counts(aot.CACHE_EVENTS, event="corrupt") == corrupt0 + 1
    assert _counts(aot.FALLBACKS, reason="corrupt") == fb0 + 1
    assert not os.path.exists(art), "corrupt artifact must be unlinked"


def test_version_skew_detected_on_miss(monkeypatch):
    """An artifact for the same program under a different jaxlib keys
    differently; the miss sweep must still name the cause."""
    compiled = _tiny_compiled()
    key = aot.cache_key("sha-skew", (8,), "cpu")
    assert aot.cache_store(key, compiled, {
        "program": "skew_b8", "bucket": [8],
        "jaxlib": aot.jaxlib_version(), "backend": "cpu",
    })
    monkeypatch.setattr(aot, "jaxlib_version", lambda: "9.9.9-future")
    new_key = aot.cache_key("sha-skew", (8,), "cpu")
    assert new_key != key, "key must change with jaxlib version"
    skew0 = _counts(aot.CACHE_EVENTS, event="skew")
    fb0 = _counts(aot.FALLBACKS, reason="skew")
    assert aot.cache_load(new_key, "skew_b8") is None
    assert _counts(aot.CACHE_EVENTS, event="skew") == skew0 + 1
    assert _counts(aot.FALLBACKS, reason="skew") == fb0 + 1


def test_load_corrupt_export_counts_and_warns_once(tmp_path,
                                                  monkeypatch):
    """The old load() swallowed every exception into silent jit
    fallback; now a corrupt shipped artifact counts a reason and the
    warn fires once per artifact."""
    monkeypatch.setattr(aot, "_EXPORT_DIR", str(tmp_path))
    name = "broken_b8"
    with open(tmp_path / f"{name}.jaxexport", "wb") as f:
        f.write(b"\x00garbage")
    fb0 = _counts(aot.FALLBACKS, reason="corrupt")
    assert aot.load(name) is None
    assert _counts(aot.FALLBACKS, reason="corrupt") == fb0 + 1
    assert (name, "corrupt") in aot._warned
    # cached negative result: second call doesn't re-read or re-count
    assert aot.load(name) is None
    assert _counts(aot.FALLBACKS, reason="corrupt") == fb0 + 1


def test_resolve_prefers_warmed_executable(monkeypatch):
    sentinel = object()
    with aot._lock:
        aot._compiled["warm_b8"] = sentinel
    assert aot.resolve("warm_b8") is sentinel
    # unknown name falls through to the export layer (absent -> None)
    assert aot.resolve("nonexistent_b8") is None


def test_warmup_twin_marks_manifest(monkeypatch):
    from harmony_tpu import device as DV

    monkeypatch.setenv("HARMONY_KERNEL_TWIN", "1")
    manifest = {"programs": [
        {"family": "t_b{}", "names": ["t_b8", "t_b16"]},
    ]}
    before = set(DV._SEEN_PROGRAMS)
    stats = aot.warmup(manifest)
    assert stats["mode"] == "twin"
    assert stats["warmed"] == 3  # two names + the verify_w1 hot path
    marked = set(DV._SEEN_PROGRAMS) - before
    assert {"t_b8", "t_b16"} <= set(DV._SEEN_PROGRAMS)
    assert "verify_w1" in DV._SEEN_PROGRAMS
    # warmup marking must not move the JIT first-use counters
    assert marked <= {"t_b8", "t_b16", "verify_w1"}


def test_warmup_without_manifest_degrades():
    stats = aot.warmup(None) if aot.load_manifest() is None else \
        aot.warmup(aot.load_manifest())
    assert stats["programs"] >= 0  # never raises


def test_committed_manifest_shape():
    """The committed manifest is the machine-checked artifact GL16
    diffs against — pin its gross shape so a hand edit stands out."""
    manifest = aot.load_manifest()
    assert manifest is not None, "compile manifest must be committed"
    names = aot.manifest_names(manifest)
    assert len(names) == len(set(names))
    fams = {f["family"] for f in manifest["programs"]}
    assert fams == {"agg_verify_b{}", "agg_verify_batch_b{}x{}",
                    "verify_w{}", "masked_sum_w{}"}
    assert "agg_verify_b8" in names and "agg_verify_b1024" in names
    assert "verify_w8" in names and "masked_sum_w8" in names
    for name in names:
        assert aot.program_spec(name) is not None, (
            f"manifest name {name} matches no warmup program family")


def test_program_spec_shapes():
    fam, dims, specs = aot.program_spec("agg_verify_b8")
    assert fam == "agg_verify" and dims == (8,)
    assert [tuple(s.shape) for s in specs] == [
        (8, 2, 32), (8,), (2, 2, 32), (2, 2, 32)]
    fam, dims, specs = aot.program_spec("agg_verify_batch_b16x64")
    assert fam == "agg_verify_batch" and dims == (16, 64)
    assert [tuple(s.shape) for s in specs] == [
        (16, 2, 32), (64, 16), (64, 2, 2, 32), (64, 2, 2, 32)]
    fam, dims, specs = aot.program_spec("masked_sum_w32")
    assert fam == "masked_sum" and dims == (32,)
    assert [tuple(s.shape) for s in specs] == [(32, 3, 32), (32,)]
    assert aot.program_spec("mystery_b8") is None
