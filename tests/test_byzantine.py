"""Byzantine-validator behaviors against the production node (ISSUE 13):
the ByzantineNode policy layer (equivocation, double votes, invalid
proposals, vote withholding) driven through deterministic pump-mode
localnets, plus the hostile-wire peer-scoring ladder on both transports."""

import time

import pytest

from harmony_tpu.chaostest import fixtures as FX
from harmony_tpu.chaostest.byzantine import ByzantineNode
from harmony_tpu.core.blockchain import Blockchain
from harmony_tpu.core.genesis import dev_genesis
from harmony_tpu.core.kv import MemKV
from harmony_tpu.core.tx_pool import TxPool
from harmony_tpu.multibls import PrivateKeys
from harmony_tpu.node.node import Node
from harmony_tpu.node.registry import Registry
from harmony_tpu.p2p import InProcessNetwork, TCPHost
from harmony_tpu.p2p.host import ACCEPT, REJECT
from harmony_tpu.staking import slash as SL

CHAIN_ID = 2


def _localnet(n_nodes=4, byz_index=None, behaviors=(),
              staking=False, blocks_per_epoch=16, ext_on=None):
    """Pump-driven localnet; node ``byz_index`` is a ByzantineNode.
    ``ext_on`` additionally rides a staked external BLS key on that
    node index (registered via a staking tx in every pool)."""
    genesis, ecdsa_keys, bls_keys = dev_genesis(
        n_accounts=n_nodes, n_keys=n_nodes
    )
    net = InProcessNetwork()
    ext = FX.external_bls_key(7) if ext_on is not None else None
    nodes = []
    for i in range(n_nodes):
        chain = Blockchain(
            MemKV(), genesis, blocks_per_epoch=blocks_per_epoch,
            finalizer=(FX.staking_finalizer(genesis, ecdsa_keys)
                       if staking else None),
        )
        pool = TxPool(CHAIN_ID, 0, chain.state)
        if ext is not None:
            pool.add(
                FX.external_validator_stake(ecdsa_keys[0], ext,
                                            chain_id=CHAIN_ID),
                is_staking=True,
            )
        reg = Registry(blockchain=chain, txpool=pool,
                       host=net.host(f"node{i}"))
        keys = [bls_keys[i]]
        if ext_on == i:
            keys.append(ext)
        if i == byz_index:
            node = ByzantineNode(
                reg, PrivateKeys.from_keys(keys),
                behaviors=behaviors,
                adversary_keys=({ext.pub.bytes} if ext is not None
                                else None),
                seed=5,
            )
        else:
            node = Node(reg, PrivateKeys.from_keys(keys))
        nodes.append(node)
    return nodes, ecdsa_keys, (ext, net)


def _pump(nodes, rounds=80):
    for _ in range(rounds):
        if not any(n.process_pending() for n in nodes):
            break


def _run_round(nodes):
    leaders = [n for n in nodes if n.is_leader]
    assert len(leaders) == 1
    leaders[0].start_round_if_leader()
    _pump(nodes)
    return leaders[0]


def test_double_voter_detected_included_applied():
    """The acceptance arc, deterministic: a staked external key on the
    byzantine node double-votes once elected; an honest leader detects
    it (late-ballot window included), the record gossips, the next
    honest leader INCLUDES it, every validator re-verifies, and the
    finalized state shows the offender slashed+banned, the reporter
    rewarded, and the key excluded from the next election."""
    nodes, ecdsa_keys, (ext, net) = _localnet(
        4, byz_index=2, behaviors=("double_vote",), staking=True,
        blocks_per_epoch=4, ext_on=2,
    )
    byz = nodes[2]
    offender = ecdsa_keys[0].address()  # the ext validator's staker
    stake0 = 10**20
    honest = [n for n in nodes if n is not byz]

    for _ in range(8):
        _run_round(nodes)

    chain = honest[0].chain
    assert chain.head_number >= 7
    assert byz.byz_actions["double_vote"] >= 1
    assert sum(n.double_sign_events for n in honest) >= 1
    included = [
        n for n in range(1, chain.head_number + 1)
        if chain.header_by_number(n).slashes
    ]
    assert included, "no committed block carried the slash record"
    rec = SL.decode_records(
        chain.header_by_number(included[0]).slashes
    )[0]
    assert rec.evidence.offender == offender
    w = chain.state().validator(offender)
    assert w.status == 2
    assert stake0 - w.total_delegation() == SL.apply_slash(
        stake0
    ).total_slashed
    # reporter (an honest dev account) credited above its allocation
    assert chain.state().balance(rec.reporter) > 10**24
    # post-ban election excludes the slashed key; honest heads agree
    assert ext.pub.bytes not in chain.committee_for_epoch(2)
    common = min(n.chain.head_number for n in honest)
    assert len({
        n.chain.block_by_number(common).hash() for n in honest
    }) == 1


def test_equivocating_leader_absorbed_by_first_announce_wins():
    """Twin-second equivocation: honest validators vote the FIRST
    announce only, the round commits one block, no honest node forks."""
    nodes, _, _ = _localnet(4, byz_index=1, behaviors=("equivocate",))
    byz = nodes[1]
    assert byz.is_leader  # view 1 -> committee key 1
    _run_round(nodes)
    assert byz.byz_actions["equivocate"] == 1
    honest = [n for n in nodes if n is not byz]
    assert all(n.chain.head_number == 1 for n in honest)
    assert len({n.chain.block_by_number(1).hash()
                for n in honest}) == 1


def test_equivocating_twin_first_wedges_but_never_forks():
    """Twin-FIRST equivocation: the committee prepares the twin while
    the leader's collector only counts the real block — the round must
    WEDGE (no commit) rather than fork."""
    nodes, _, _ = _localnet(4, byz_index=1, behaviors=("equivocate",))
    byz = nodes[1]
    byz.byz_actions["equivocate"] = 1  # force the twin-first posture
    _run_round(nodes)
    honest = [n for n in nodes if n is not byz]
    assert all(n.chain.head_number == 0 for n in honest)  # wedged
    # every honest validator voted for exactly one proposal
    assert all(n._announce_voted is not None for n in honest)


def test_withholding_validator_follows_without_voting():
    nodes, _, _ = _localnet(4, byz_index=3, behaviors=("withhold",))
    byz = nodes[3]
    _run_round(nodes)
    # 3-of-4 keys still meet quorum; the withholder FOLLOWS the chain
    assert all(n.chain.head_number == 1 for n in nodes)
    assert byz.byz_actions["withhold"] >= 1
    # and its key is absent from the commit bitmap evidence: the round
    # committed with exactly the honest signers
    proof = nodes[0].chain.read_commit_sig(1)
    assert proof is not None


def test_invalid_proposals_rejected_by_every_validator():
    nodes, _, _ = _localnet(4, byz_index=1,
                            behaviors=("invalid_proposal",))
    byz = nodes[1]
    assert byz.is_leader
    byz.start_round_if_leader()
    _pump(nodes)
    assert byz.byz_actions["invalid_proposal"] == 1
    honest = [n for n in nodes if n is not byz]
    # nobody voted for the garbage: no head moved, no prepare cast
    assert all(n.chain.head_number == 0 for n in honest)
    assert all(n._announce_voted is None for n in honest)


# -- hostile-wire scoring ladder ---------------------------------------------


def test_hub_scores_throttles_then_mutes_spammer():
    from harmony_tpu.p2p.host import P2P_COUNTERS

    net = InProcessNetwork()
    evil = net.host("evil")
    good = net.host("good")
    victim = net.host("victim")
    victim.add_validator("t", lambda p, f: REJECT)
    victim.subscribe("t", lambda t, p, f: None)
    throttled0 = P2P_COUNTERS["throttled"]
    for i in range(100):
        evil.publish("t", b"junk-%d" % i)
        if "evil" in net.muted:
            break
    assert "evil" in net.muted
    assert net.invalid_total >= 20
    assert net.scores["evil"] <= net.MUTE_FLOOR
    assert P2P_COUNTERS["throttled"] > throttled0  # the middle tier
    # muted: nothing further routes, honest peers unaffected
    seen = []
    victim.add_validator("ok", lambda p, f: ACCEPT)
    victim.subscribe("ok", lambda t, p, f: seen.append((p, f)))
    evil.publish("ok", b"from-evil")
    good.publish("ok", b"from-good")
    assert seen == [(b"from-good", "good")]


def test_tcp_peer_throttled_then_dropped_for_spam():
    h1 = TCPHost("defender")
    h2 = TCPHost("spammer")
    try:
        h1.add_validator("x", lambda p, f: REJECT)
        h1.subscribe("x", lambda t, p, f: None)
        h2.connect(h1.port)
        assert h1.wait_for_peers(1) and h2.wait_for_peers(1)
        for i in range(80):
            h2.publish("x", b"junk-%d" % i)
            if h1.peer_count() == 0:
                break
            time.sleep(0.01)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and h1.peer_count():
            time.sleep(0.05)
        assert h1.peer_count() == 0, "spamming peer was not dropped"
    finally:
        h1.close(), h2.close()


def test_p2p_and_slash_metrics_exposed():
    from harmony_tpu.metrics import Registry

    text = Registry().expose()
    assert "harmony_p2p_invalid_messages_total" in text
    assert "harmony_p2p_peer_score" in text
    assert 'harmony_slash_events_total{stage="applied"}' in text
    assert "harmony_slash_amount_atto_total" in text


def test_wire_spray_variants_never_crash_honest_validators():
    """Every spray variant lands on a real node's gossip validators:
    all must be REJECTed (scored) without crashing the host."""
    nodes, _, (ext, net) = _localnet(2, byz_index=1,
                                     behaviors=("wire_spray",))
    byz = nodes[1]
    import random

    rng = random.Random(99)
    for _ in range(200):
        byz._spray_once(rng)
    assert byz.byz_actions["wire_spray"] > 0
    assert net.invalid_total > 0
    # the honest node's pump survives whatever was delivered pre-mute
    nodes[0].process_pending()
    assert nodes[0].chain.head_number == 0
