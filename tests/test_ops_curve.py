"""Batched Jacobian group-law tests vs the affine bigint reference."""

import random

import jax.numpy as jnp
import numpy as np

from harmony_tpu.ops import curve as CV
from harmony_tpu.ops import interop as I
from harmony_tpu.ref import curve as RC
from harmony_tpu.ref.params import R_ORDER

rng = random.Random(0xC4)

KS = [rng.randrange(1, R_ORDER) for _ in range(4)]
G1_REF = [RC.g1.mul(RC.G1_GEN, k) for k in KS]
G1_PTS = jnp.asarray(np.stack([I.g1_affine_to_jacobian_arr(p) for p in G1_REF]))


def test_g1_dbl():
    out = CV.dbl(G1_PTS, CV.FP_OPS)
    for i in range(4):
        assert I.arr_to_g1_affine(np.array(out[i])) == RC.g1.dbl(G1_REF[i])


def test_g1_add_including_special_cases():
    p0, p1 = G1_REF[0], G1_REF[1]
    cases = [
        (p0, p1),
        (p0, p0),  # doubling path
        (p0, RC.g1.neg(p0)),  # inverse -> infinity
        (None, p1),
        (p0, None),
        (None, None),
    ]
    a = jnp.asarray(np.stack([I.g1_affine_to_jacobian_arr(x) for x, _ in cases]))
    b = jnp.asarray(np.stack([I.g1_affine_to_jacobian_arr(y) for _, y in cases]))
    out = CV.add(a, b, CV.FP_OPS)
    for i, (x, y) in enumerate(cases):
        assert I.arr_to_g1_affine(np.array(out[i])) == RC.g1.add(x, y), i


def test_g2_dbl_add():
    ref2 = [RC.g2.mul(RC.G2_GEN, k) for k in KS[:2]]
    pts2 = jnp.asarray(np.stack([I.g2_affine_to_jacobian_arr(p) for p in ref2]))
    out = CV.dbl(pts2, CV.FP2_OPS)
    for i in range(2):
        assert I.arr_to_g2_affine(np.array(out[i])) == RC.g2.dbl(ref2[i])
    cases = [
        (ref2[0], ref2[1]),
        (ref2[0], ref2[0]),
        (ref2[0], RC.g2.neg(ref2[0])),
        (None, ref2[1]),
    ]
    a = jnp.asarray(np.stack([I.g2_affine_to_jacobian_arr(x) for x, _ in cases]))
    b = jnp.asarray(np.stack([I.g2_affine_to_jacobian_arr(y) for _, y in cases]))
    out = CV.add(a, b, CV.FP2_OPS)
    for i, (x, y) in enumerate(cases):
        assert I.arr_to_g2_affine(np.array(out[i])) == RC.g2.add(x, y), i


def test_scalar_mul_per_element():
    ks = [rng.randrange(1, 1 << 64) for _ in range(4)]
    bits = jnp.asarray(
        [[(k >> (63 - j)) & 1 for j in range(64)] for k in ks], dtype=jnp.int32
    )
    out = CV.scalar_mul(G1_PTS, bits, CV.FP_OPS)
    for i in range(4):
        assert I.arr_to_g1_affine(np.array(out[i])) == RC.g1.mul(
            G1_REF[i], ks[i]
        )


def test_masked_sum_matches_mask_aggregate():
    # the Mask.AggregatePublic behavior (reference: crypto/bls/mask.go)
    mask = [1, 0, 1, 1]
    expect = None
    for i, m in enumerate(mask):
        if m:
            expect = RC.g1.add(expect, G1_REF[i])
    out = CV.masked_sum(G1_PTS, jnp.asarray(mask), CV.FP_OPS)
    assert I.arr_to_g1_affine(np.array(out)) == expect
    # empty mask -> infinity
    out = CV.masked_sum(G1_PTS, jnp.asarray([0, 0, 0, 0]), CV.FP_OPS)
    assert I.arr_to_g1_affine(np.array(out)) is None


def test_masked_sum_duplicate_points():
    # duplicate keys exercise the doubling path inside the tree reduction
    dup = jnp.asarray(
        np.stack([I.g1_affine_to_jacobian_arr(G1_REF[0])] * 2)
    )
    out = CV.masked_sum(dup, jnp.asarray([1, 1]), CV.FP_OPS)
    assert I.arr_to_g1_affine(np.array(out)) == RC.g1.dbl(G1_REF[0])


def test_to_affine_roundtrip():
    ax, ay = CV.to_affine(G1_PTS, CV.FP_OPS)
    for i in range(4):
        assert (
            I.arr_to_fp(np.array(ax[i])),
            I.arr_to_fp(np.array(ay[i])),
        ) == G1_REF[i]
