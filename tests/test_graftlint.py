"""graftlint tier-1 gate + linter self-tests.

Pure-AST: none of these tests import jax or the linted modules, so the
whole file runs in a few seconds and belongs in tier-1.  Three layers:

1. fixture files under tests/fixtures/graftlint/ assert exact rule ids
   and line numbers per rule family (positive + suppressed cases);
2. baseline machinery (pinning, excess-is-new, fixed detection) on a
   dedicated pinned-cases fixture;
3. THE GATE: harmony_tpu/ linted against the committed baseline — any
   new finding fails tier-1 — plus the CLI exit-code contract.
"""

import json
import re
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.graftlint import (  # noqa: E402
    DEFAULT_BASELINE_PATH,
    REPO_ROOT,
    Baseline,
    lint_paths,
    lint_source,
    load_baseline,
)
from tools.graftlint.engine import compare  # noqa: E402

FIXTURES = REPO / "tests" / "fixtures" / "graftlint"
_EXPECT_RE = re.compile(r"#\s*expect:\s*(GL\d{2}(?:\s*,\s*GL\d{2})*)")


def _expected(path: Path) -> set:
    out = set()
    for lineno, line in enumerate(
            path.read_text(encoding="utf-8").splitlines(), start=1):
        m = _EXPECT_RE.search(line)
        if m:
            for rule in m.group(1).split(","):
                out.add((lineno, rule.strip()))
    return out


@pytest.mark.parametrize("name", [
    "gl01_cases.py", "gl02_cases.py", "gl03_cases.py", "gl04_cases.py",
    "gl05_cases.py", "gl06_cases.py", "gl07_cases.py", "gl08_cases.py",
])
def test_fixture_exact_lines(name):
    """Each rule family flags exactly the tagged lines — no more, no
    less — and inline suppressions (incl. wrong-rule ones) behave."""
    path = FIXTURES / name
    rel = path.relative_to(REPO_ROOT).as_posix()
    findings = lint_source(path.read_text(encoding="utf-8"), rel)
    actual = {(f.line, f.rule) for f in findings}
    expected = _expected(path)
    assert actual == expected, (
        f"{name}: flagged {sorted(actual - expected)} unexpectedly, "
        f"missed {sorted(expected - actual)}"
    )


def test_fixture_rules_scoped_inside_harmony_tpu():
    """The same weak-where source that fires in a limb module is out of
    scope elsewhere in harmony_tpu/ — scoping is path-based."""
    src = "import jax.numpy as jnp\n\ndef f(x):\n    return jnp.where(x > 0, 1, 0)\n"
    in_scope = lint_source(src, "harmony_tpu/ops/fp.py")
    out_of_scope = lint_source(src, "harmony_tpu/consensus/quorum.py")
    assert [(f.rule, f.line) for f in in_scope] == [("GL02", 4)]
    assert out_of_scope == []


PINNED_SRC = '''\
def racy_one(sig):
    try:
        return sig.check()
    except Exception:
        pass


def racy_two(sig):
    try:
        return sig.check()
    except Exception:
        pass
'''


def test_baseline_pins_and_flags_excess():
    """Pinned findings stay quiet; the same fingerprint appearing MORE
    often than pinned reports exactly the excess sites."""
    rel = "tests/fixtures/graftlint/pinned_virtual.py"
    findings = lint_source(PINNED_SRC, rel)
    assert [(f.rule, f.line) for f in findings] == [
        ("GL04", 4), ("GL04", 11),
    ]
    # distinct contexts -> distinct fingerprints: pin both, gate clean
    full = Baseline.from_findings(findings)
    new, pinned, fixed = compare(findings, full)
    assert new == [] and pinned == 2 and fixed == []

    # same fingerprint twice, only one pinned -> the excess is NEW and
    # it is the LATER line that is reported
    dup_src = PINNED_SRC.replace("racy_two", "racy_one")
    dup = lint_source(dup_src, rel)
    assert len({f.fingerprint for f in dup}) == 1
    half = Baseline({dup[0].fingerprint: 1})
    new, pinned, fixed = compare(dup, half)
    assert pinned == 1 and [f.line for f in new] == [11]

    # a fixed finding is reported so the pin can be shrunk
    new, pinned, fixed = compare([], full)
    assert new == [] and pinned == 0 and len(fixed) == 2


def test_repo_gate_clean_against_committed_baseline():
    """THE tier-1 gate: no new violations in harmony_tpu/."""
    result = lint_paths(["harmony_tpu"])
    assert not result.errors, result.errors
    baseline = load_baseline()
    new, _pinned, fixed = compare(result.findings, baseline)
    assert not new, (
        "new graftlint violations (fix them, or pin deliberate debt "
        "via `python -m tools.graftlint --write-baseline`):\n"
        + "\n".join(f.render() for f in new)
    )
    assert not fixed, (
        "baseline entries no longer fire — shrink the pin file with "
        "`python -m tools.graftlint --write-baseline`:\n"
        + "\n".join(fixed)
    )


def test_baseline_has_no_ops_gl01_gl02_pins():
    """The ops/ hot path must be FIXED, never pinned, for purity and
    dtype discipline (ISSUE 1 acceptance criterion)."""
    baseline = load_baseline()
    offenders = [
        fp for fp in baseline.counts
        if fp.startswith("harmony_tpu/ops/")
        and ("::GL01::" in fp or "::GL02::" in fp)
    ]
    assert offenders == []


def _run_cli(*args, cwd=REPO_ROOT):
    return subprocess.run(
        [sys.executable, "-m", "tools.graftlint", *args],
        cwd=cwd, capture_output=True, text=True, timeout=120,
    )


def test_cli_exit_code_contract(tmp_path):
    """0 clean, 1 new violations, 2 internal error — stable for hooks."""
    clean = tmp_path / "clean.py"
    clean.write_text("def f():\n    return 1\n", encoding="utf-8")
    dirty = tmp_path / "dirty.py"
    dirty.write_text(
        "def f(x):\n    try:\n        return x.check()\n"
        "    except:\n        pass\n",
        encoding="utf-8",
    )
    missing_baseline = tmp_path / "nothing.json"

    r = _run_cli(str(clean), "--baseline", str(missing_baseline))
    assert r.returncode == 0, r.stdout + r.stderr

    r = _run_cli(str(dirty), "--baseline", str(missing_baseline))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "GL04" in r.stdout

    r = _run_cli(str(dirty), "--rules", "GL99")
    assert r.returncode == 2, r.stdout + r.stderr

    # --write-baseline pins the debt; the re-run gates clean on it
    pin = tmp_path / "baseline.json"
    r = _run_cli(str(dirty), "--baseline", str(pin), "--write-baseline")
    assert r.returncode == 0, r.stdout + r.stderr
    data = json.loads(pin.read_text(encoding="utf-8"))
    assert sum(e["count"] for e in data["findings"]) == 1
    r = _run_cli(str(dirty), "--baseline", str(pin))
    assert r.returncode == 0, r.stdout + r.stderr

    # a narrowed run must not clobber the DEFAULT baseline's other pins
    committed = DEFAULT_BASELINE_PATH.read_bytes()
    r = _run_cli(str(dirty), "--write-baseline")
    assert r.returncode == 2, r.stdout + r.stderr
    assert "refusing" in r.stderr
    assert DEFAULT_BASELINE_PATH.read_bytes() == committed

    # a syntactically broken file gates like a violation, not a crash
    broken = tmp_path / "broken.py"
    broken.write_text("def f(:\n", encoding="utf-8")
    r = _run_cli(str(broken), "--baseline", str(missing_baseline))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "SyntaxError" in r.stderr

    # a typo'd path must fail loudly, not lint zero files and pass
    r = _run_cli(str(tmp_path / "no_such_dir"),
                 "--baseline", str(missing_baseline))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "not a .py file or directory" in r.stderr


def test_default_baseline_is_committed_and_loads():
    assert DEFAULT_BASELINE_PATH.exists()
    baseline = load_baseline()
    for fp, count in baseline.counts.items():
        assert count >= 1
        path = fp.split("::", 1)[0]
        assert (REPO_ROOT / path).exists(), f"stale baseline path {path}"


# -- interprocedural pass (GL05-GL07) ---------------------------------------


def test_cross_file_program_blocking_under_lock(tmp_path):
    """The call graph crosses FILE boundaries: a.py holds its lock
    while calling into b.py, whose helper sleeps — the finding lands in
    a.py at the call site.  Linting a.py ALONE sees no finding (the
    callee is outside the program), which is exactly the failure mode
    the whole-program pass exists to close."""
    (tmp_path / "b_helpers.py").write_text(
        "import time\n\n\ndef drain():\n    time.sleep(1)\n",
        encoding="utf-8",
    )
    (tmp_path / "a_caller.py").write_text(
        "import threading\n\nfrom b_helpers import drain\n\n"
        "_L = threading.Lock()\n\n\ndef tick():\n    with _L:\n"
        "        drain()\n",
        encoding="utf-8",
    )
    both = lint_paths([tmp_path])
    assert not both.errors
    gl06 = [f for f in both.findings if f.rule == "GL06"]
    assert [(Path(f.path).name, f.line) for f in gl06] == \
        [("a_caller.py", 10)]
    assert "time.sleep" in gl06[0].message
    alone = lint_paths([tmp_path / "a_caller.py"])
    assert [f for f in alone.findings if f.rule == "GL06"] == []


def test_lock_order_cycle_detected_across_classes(tmp_path):
    """Opposite nesting of the same two locks in two classes is a
    GL05 cycle; consistent nesting is only an (un-pinned) edge."""
    (tmp_path / "deadlockable.py").write_text(
        "import threading\n\n\n"
        "class Consensus:\n"
        "    def __init__(self):\n"
        "        self._vc_lock = threading.Lock()\n"
        "        self.net = Gossip()\n\n"
        "    def view_change(self):\n"
        "        with self._vc_lock:\n"
        "            self.net.broadcast_view()\n\n\n"
        "class Gossip:\n"
        "    def __init__(self):\n"
        "        self._mesh_lock = threading.Lock()\n"
        "        self.fbft = None\n\n"
        "    def broadcast_view(self):\n"
        "        with self._mesh_lock:\n"
        "            pass\n\n"
        "    def on_message(self):\n"
        "        with self._mesh_lock:\n"
        "            self.fbft.start_view_change()\n\n\n"
        "class FBFT:\n"
        "    def start_view_change(self):\n"
        "        with self.consensus._vc_lock:\n"
        "            pass\n",
        encoding="utf-8",
    )
    res = lint_paths([tmp_path / "deadlockable.py"])
    cycles = [f for f in res.findings
              if f.rule == "GL05" and "cycle" in f.message]
    assert len(cycles) == 2, [f.render() for f in res.findings]
    assert {f.context for f in cycles} == \
        {"Consensus.view_change", "Gossip.on_message"}


def test_sarif_output_validates_against_schema(tmp_path):
    """--sarif emits SARIF 2.1.0 that validates against the minimal
    schema (the subset GitHub/CI annotators require)."""
    jsonschema = pytest.importorskip("jsonschema")
    dirty = tmp_path / "dirty.py"
    dirty.write_text(
        "import threading\nimport time\n\n_L = threading.Lock()\n\n\n"
        "def f():\n    with _L:\n        time.sleep(1)\n",
        encoding="utf-8",
    )
    r = _run_cli(str(dirty), "--sarif",
                 "--baseline", str(tmp_path / "none.json"))
    assert r.returncode == 1, r.stdout + r.stderr
    doc = json.loads(r.stdout)

    schema = {
        "type": "object",
        "required": ["version", "runs"],
        "properties": {
            "version": {"const": "2.1.0"},
            "runs": {
                "type": "array", "minItems": 1,
                "items": {
                    "type": "object",
                    "required": ["tool", "results"],
                    "properties": {
                        "tool": {
                            "type": "object",
                            "required": ["driver"],
                            "properties": {"driver": {
                                "type": "object",
                                "required": ["name"],
                                "properties": {
                                    "name": {"type": "string"},
                                    "rules": {"type": "array"},
                                },
                            }},
                        },
                        "results": {
                            "type": "array",
                            "items": {
                                "type": "object",
                                "required": ["ruleId", "message",
                                             "locations"],
                                "properties": {
                                    "ruleId": {"type": "string"},
                                    "level": {"enum": [
                                        "none", "note", "warning",
                                        "error"]},
                                    "message": {
                                        "type": "object",
                                        "required": ["text"],
                                    },
                                    "locations": {
                                        "type": "array", "minItems": 1,
                                        "items": {
                                            "type": "object",
                                            "required": [
                                                "physicalLocation"],
                                            "properties": {
                                                "physicalLocation": {
                                                    "type": "object",
                                                    "required": [
                                                        "artifactLocation"],
                                                },
                                            },
                                        },
                                    },
                                },
                            },
                        },
                    },
                },
            },
        },
    }
    jsonschema.validate(doc, schema)
    results = doc["runs"][0]["results"]
    assert {r["ruleId"] for r in results} == {"GL06"}
    region = results[0]["locations"][0]["physicalLocation"]["region"]
    assert region["startLine"] == 9
    fps = results[0]["partialFingerprints"]
    assert "::GL06::" in fps["graftlintFingerprint/v1"]


_DOT_EDGE_RE = re.compile(r'^  "([^"]+)" -> "([^"]+)";$')


def test_dot_output_is_parseable_callgraph():
    r = _run_cli("tests/fixtures/graftlint/gl06_cases.py", "--dot")
    assert r.returncode == 0, r.stdout + r.stderr
    lines = r.stdout.splitlines()
    assert lines[0] == "digraph graftlint_callgraph {"
    assert lines[-1] == "}"
    edges = set()
    for line in lines[1:-1]:
        m = _DOT_EDGE_RE.match(line)
        assert m, f"unparseable DOT line: {line!r}"
        edges.add((m.group(1), m.group(2)))
    assert ("gl06_cases.py:sleepy_via_call",
            "gl06_cases.py:_nap") in edges


def test_whole_program_pass_is_fast():
    """Acceptance: the full-repo whole-program pass runs in < 15 s on
    CPU (measured ~4 s; the bound is generous for a loaded CI box)."""
    import time as _time

    t0 = _time.monotonic()
    result = lint_paths(["harmony_tpu"])
    dt = _time.monotonic() - t0
    assert not result.errors
    assert dt < 15.0, f"whole-program pass took {dt:.1f}s"


def test_interproc_fingerprints_are_line_free_and_stable():
    """GL05/GL06/GL07 fingerprints carry the lock pair / sync site,
    never line numbers or witness chains — pins must survive unrelated
    edits and witness rerouting."""
    result = lint_paths(["harmony_tpu"])
    inter = [f for f in result.findings
             if f.rule in ("GL05", "GL06", "GL07")]
    assert inter, "expected pinned interprocedural findings to exist"
    for f in inter:
        assert str(f.line) not in f.fingerprint.split("::", 2)[2], (
            "line leaked into fingerprint", f.fingerprint)
        if f.detail:
            assert f.detail not in f.fingerprint
