"""graftlint tier-1 gate + linter self-tests.

Pure-AST: none of these tests import jax or the linted modules, so the
whole file runs in a few seconds and belongs in tier-1.  Three layers:

1. fixture files under tests/fixtures/graftlint/ assert exact rule ids
   and line numbers per rule family (positive + suppressed cases);
2. baseline machinery (pinning, excess-is-new, fixed detection) on a
   dedicated pinned-cases fixture;
3. THE GATE: harmony_tpu/ linted against the committed baseline — any
   new finding fails tier-1 — plus the CLI exit-code contract.
"""

import json
import re
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.graftlint import (  # noqa: E402
    DEFAULT_BASELINE_PATH,
    REPO_ROOT,
    Baseline,
    lint_paths,
    lint_source,
    load_baseline,
)
from tools.graftlint.engine import compare  # noqa: E402

FIXTURES = REPO / "tests" / "fixtures" / "graftlint"
_EXPECT_RE = re.compile(r"#\s*expect:\s*(GL\d{2}(?:\s*,\s*GL\d{2})*)")


def _expected(path: Path) -> set:
    out = set()
    for lineno, line in enumerate(
            path.read_text(encoding="utf-8").splitlines(), start=1):
        m = _EXPECT_RE.search(line)
        if m:
            for rule in m.group(1).split(","):
                out.add((lineno, rule.strip()))
    return out


@pytest.mark.parametrize("name", [
    "gl01_cases.py", "gl02_cases.py", "gl03_cases.py", "gl04_cases.py",
    "gl05_cases.py", "gl06_cases.py", "gl07_cases.py", "gl08_cases.py",
    "gl09_cases.py", "gl10_cases.py", "gl11_cases.py",
    "gl12_cases.py", "gl13_cases.py", "gl14_cases.py",
    "gl15_cases.py", "gl16_cases.py", "gl17_cases.py",
])
def test_fixture_exact_lines(name):
    """Each rule family flags exactly the tagged lines — no more, no
    less — and inline suppressions (incl. wrong-rule ones) behave."""
    path = FIXTURES / name
    rel = path.relative_to(REPO_ROOT).as_posix()
    findings = lint_source(path.read_text(encoding="utf-8"), rel)
    actual = {(f.line, f.rule) for f in findings}
    expected = _expected(path)
    assert actual == expected, (
        f"{name}: flagged {sorted(actual - expected)} unexpectedly, "
        f"missed {sorted(expected - actual)}"
    )


def test_fixture_rules_scoped_inside_harmony_tpu():
    """The same weak-where source that fires in a limb module is out of
    scope elsewhere in harmony_tpu/ — scoping is path-based."""
    src = "import jax.numpy as jnp\n\ndef f(x):\n    return jnp.where(x > 0, 1, 0)\n"
    in_scope = lint_source(src, "harmony_tpu/ops/fp.py")
    out_of_scope = lint_source(src, "harmony_tpu/consensus/quorum.py")
    assert [(f.rule, f.line) for f in in_scope] == [("GL02", 4)]
    assert out_of_scope == []


PINNED_SRC = '''\
def racy_one(sig):
    try:
        return sig.check()
    except Exception:
        pass


def racy_two(sig):
    try:
        return sig.check()
    except Exception:
        pass
'''


def test_baseline_pins_and_flags_excess():
    """Pinned findings stay quiet; the same fingerprint appearing MORE
    often than pinned reports exactly the excess sites."""
    rel = "tests/fixtures/graftlint/pinned_virtual.py"
    findings = lint_source(PINNED_SRC, rel)
    assert [(f.rule, f.line) for f in findings] == [
        ("GL04", 4), ("GL04", 11),
    ]
    # distinct contexts -> distinct fingerprints: pin both, gate clean
    full = Baseline.from_findings(findings)
    new, pinned, fixed = compare(findings, full)
    assert new == [] and pinned == 2 and fixed == []

    # same fingerprint twice, only one pinned -> the excess is NEW and
    # it is the LATER line that is reported
    dup_src = PINNED_SRC.replace("racy_two", "racy_one")
    dup = lint_source(dup_src, rel)
    assert len({f.fingerprint for f in dup}) == 1
    half = Baseline({dup[0].fingerprint: 1})
    new, pinned, fixed = compare(dup, half)
    assert pinned == 1 and [f.line for f in new] == [11]

    # a fixed finding is reported so the pin can be shrunk
    new, pinned, fixed = compare([], full)
    assert new == [] and pinned == 0 and len(fixed) == 2


def test_repo_gate_clean_against_committed_baseline():
    """THE tier-1 gate: no new violations in harmony_tpu/.  Runs
    through the content-hash cache — check.sh's CLI stage warms it, so
    this second full-repo pass is ~10x cheaper on an unchanged tree
    (test_result_cache_is_content_correct proves cache == fresh)."""
    result = lint_paths(["harmony_tpu"], use_cache=True)
    assert not result.errors, result.errors
    baseline = load_baseline()
    new, _pinned, fixed = compare(result.findings, baseline)
    assert not new, (
        "new graftlint violations (fix them, or pin deliberate debt "
        "via `python -m tools.graftlint --write-baseline`):\n"
        + "\n".join(f.render() for f in new)
    )
    assert not fixed, (
        "baseline entries no longer fire — shrink the pin file with "
        "`python -m tools.graftlint --write-baseline`:\n"
        + "\n".join(fixed)
    )


def test_baseline_has_no_ops_gl01_gl02_pins():
    """The ops/ hot path must be FIXED, never pinned, for purity and
    dtype discipline (ISSUE 1 acceptance criterion)."""
    baseline = load_baseline()
    offenders = [
        fp for fp in baseline.counts
        if fp.startswith("harmony_tpu/ops/")
        and ("::GL01::" in fp or "::GL02::" in fp)
    ]
    assert offenders == []


def _run_cli(*args, cwd=REPO_ROOT):
    return subprocess.run(
        [sys.executable, "-m", "tools.graftlint", *args],
        cwd=cwd, capture_output=True, text=True, timeout=120,
    )


def test_cli_exit_code_contract(tmp_path):
    """0 clean, 1 new violations, 2 internal error — stable for hooks."""
    clean = tmp_path / "clean.py"
    clean.write_text("def f():\n    return 1\n", encoding="utf-8")
    dirty = tmp_path / "dirty.py"
    dirty.write_text(
        "def f(x):\n    try:\n        return x.check()\n"
        "    except:\n        pass\n",
        encoding="utf-8",
    )
    missing_baseline = tmp_path / "nothing.json"

    r = _run_cli(str(clean), "--baseline", str(missing_baseline))
    assert r.returncode == 0, r.stdout + r.stderr

    r = _run_cli(str(dirty), "--baseline", str(missing_baseline))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "GL04" in r.stdout

    r = _run_cli(str(dirty), "--rules", "GL99")
    assert r.returncode == 2, r.stdout + r.stderr

    # --write-baseline pins the debt; the re-run gates clean on it
    pin = tmp_path / "baseline.json"
    r = _run_cli(str(dirty), "--baseline", str(pin), "--write-baseline")
    assert r.returncode == 0, r.stdout + r.stderr
    data = json.loads(pin.read_text(encoding="utf-8"))
    assert sum(e["count"] for e in data["findings"]) == 1
    r = _run_cli(str(dirty), "--baseline", str(pin))
    assert r.returncode == 0, r.stdout + r.stderr

    # a narrowed run must not clobber the DEFAULT baseline's other pins
    committed = DEFAULT_BASELINE_PATH.read_bytes()
    r = _run_cli(str(dirty), "--write-baseline")
    assert r.returncode == 2, r.stdout + r.stderr
    assert "refusing" in r.stderr
    assert DEFAULT_BASELINE_PATH.read_bytes() == committed

    # a syntactically broken file gates like a violation, not a crash
    broken = tmp_path / "broken.py"
    broken.write_text("def f(:\n", encoding="utf-8")
    r = _run_cli(str(broken), "--baseline", str(missing_baseline))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "SyntaxError" in r.stderr

    # a typo'd path must fail loudly, not lint zero files and pass
    r = _run_cli(str(tmp_path / "no_such_dir"),
                 "--baseline", str(missing_baseline))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "not a .py file or directory" in r.stderr


def test_cli_changed_mode_contract():
    """--changed lints only the git-diff slice.  A bad ref must exit 2
    (fail loudly), never lint zero files and pass; a narrowed --changed
    run must refuse to clobber the default baseline; a valid ref runs
    the gate and reports the changed-slice summary (tree-state agnostic:
    either files changed vs HEAD, or nothing to lint)."""
    r = _run_cli("--changed=definitely-not-a-ref")
    assert r.returncode == 2, r.stdout + r.stderr
    assert "definitely-not-a-ref" in r.stderr

    committed = DEFAULT_BASELINE_PATH.read_bytes()
    r = _run_cli("--changed", "--write-baseline")
    assert r.returncode == 2, r.stdout + r.stderr
    assert "refusing" in r.stderr
    assert DEFAULT_BASELINE_PATH.read_bytes() == committed

    r = _run_cli("--changed=HEAD")
    assert r.returncode in (0, 1), r.stdout + r.stderr
    assert ("changed files vs HEAD" in r.stdout
            or "nothing to lint" in r.stdout), r.stdout


def test_default_baseline_is_committed_and_loads():
    assert DEFAULT_BASELINE_PATH.exists()
    baseline = load_baseline()
    for fp, count in baseline.counts.items():
        assert count >= 1
        path = fp.split("::", 1)[0]
        assert (REPO_ROOT / path).exists(), f"stale baseline path {path}"


# -- interprocedural pass (GL05-GL07) ---------------------------------------


def test_cross_file_program_blocking_under_lock(tmp_path):
    """The call graph crosses FILE boundaries: a.py holds its lock
    while calling into b.py, whose helper sleeps — the finding lands in
    a.py at the call site.  Linting a.py ALONE sees no finding (the
    callee is outside the program), which is exactly the failure mode
    the whole-program pass exists to close."""
    (tmp_path / "b_helpers.py").write_text(
        "import time\n\n\ndef drain():\n    time.sleep(1)\n",
        encoding="utf-8",
    )
    (tmp_path / "a_caller.py").write_text(
        "import threading\n\nfrom b_helpers import drain\n\n"
        "_L = threading.Lock()\n\n\ndef tick():\n    with _L:\n"
        "        drain()\n",
        encoding="utf-8",
    )
    both = lint_paths([tmp_path])
    assert not both.errors
    gl06 = [f for f in both.findings if f.rule == "GL06"]
    assert [(Path(f.path).name, f.line) for f in gl06] == \
        [("a_caller.py", 10)]
    assert "time.sleep" in gl06[0].message
    alone = lint_paths([tmp_path / "a_caller.py"])
    assert [f for f in alone.findings if f.rule == "GL06"] == []


def test_lock_order_cycle_detected_across_classes(tmp_path):
    """Opposite nesting of the same two locks in two classes is a
    GL05 cycle; consistent nesting is only an (un-pinned) edge."""
    (tmp_path / "deadlockable.py").write_text(
        "import threading\n\n\n"
        "class Consensus:\n"
        "    def __init__(self):\n"
        "        self._vc_lock = threading.Lock()\n"
        "        self.net = Gossip()\n\n"
        "    def view_change(self):\n"
        "        with self._vc_lock:\n"
        "            self.net.broadcast_view()\n\n\n"
        "class Gossip:\n"
        "    def __init__(self):\n"
        "        self._mesh_lock = threading.Lock()\n"
        "        self.fbft = None\n\n"
        "    def broadcast_view(self):\n"
        "        with self._mesh_lock:\n"
        "            pass\n\n"
        "    def on_message(self):\n"
        "        with self._mesh_lock:\n"
        "            self.fbft.start_view_change()\n\n\n"
        "class FBFT:\n"
        "    def start_view_change(self):\n"
        "        with self.consensus._vc_lock:\n"
        "            pass\n",
        encoding="utf-8",
    )
    res = lint_paths([tmp_path / "deadlockable.py"])
    cycles = [f for f in res.findings
              if f.rule == "GL05" and "cycle" in f.message]
    assert len(cycles) == 2, [f.render() for f in res.findings]
    assert {f.context for f in cycles} == \
        {"Consensus.view_change", "Gossip.on_message"}


def test_sarif_output_validates_against_schema(tmp_path):
    """--sarif emits SARIF 2.1.0 that validates against the minimal
    schema (the subset GitHub/CI annotators require)."""
    jsonschema = pytest.importorskip("jsonschema")
    dirty = tmp_path / "dirty.py"
    dirty.write_text(
        "import threading\nimport time\n\n_L = threading.Lock()\n\n\n"
        "def f():\n    with _L:\n        time.sleep(1)\n",
        encoding="utf-8",
    )
    r = _run_cli(str(dirty), "--sarif",
                 "--baseline", str(tmp_path / "none.json"))
    assert r.returncode == 1, r.stdout + r.stderr
    doc = json.loads(r.stdout)

    schema = {
        "type": "object",
        "required": ["version", "runs"],
        "properties": {
            "version": {"const": "2.1.0"},
            "runs": {
                "type": "array", "minItems": 1,
                "items": {
                    "type": "object",
                    "required": ["tool", "results"],
                    "properties": {
                        "tool": {
                            "type": "object",
                            "required": ["driver"],
                            "properties": {"driver": {
                                "type": "object",
                                "required": ["name"],
                                "properties": {
                                    "name": {"type": "string"},
                                    "rules": {"type": "array"},
                                },
                            }},
                        },
                        "results": {
                            "type": "array",
                            "items": {
                                "type": "object",
                                "required": ["ruleId", "message",
                                             "locations"],
                                "properties": {
                                    "ruleId": {"type": "string"},
                                    "level": {"enum": [
                                        "none", "note", "warning",
                                        "error"]},
                                    "message": {
                                        "type": "object",
                                        "required": ["text"],
                                    },
                                    "locations": {
                                        "type": "array", "minItems": 1,
                                        "items": {
                                            "type": "object",
                                            "required": [
                                                "physicalLocation"],
                                            "properties": {
                                                "physicalLocation": {
                                                    "type": "object",
                                                    "required": [
                                                        "artifactLocation"],
                                                },
                                            },
                                        },
                                    },
                                },
                            },
                        },
                    },
                },
            },
        },
    }
    jsonschema.validate(doc, schema)
    results = doc["runs"][0]["results"]
    assert {r["ruleId"] for r in results} == {"GL06"}
    region = results[0]["locations"][0]["physicalLocation"]["region"]
    assert region["startLine"] == 9
    fps = results[0]["partialFingerprints"]
    assert "::GL06::" in fps["graftlintFingerprint/v1"]


_DOT_EDGE_RE = re.compile(r'^  "([^"]+)" -> "([^"]+)";$')


def test_dot_output_is_parseable_callgraph():
    r = _run_cli("tests/fixtures/graftlint/gl06_cases.py", "--dot")
    assert r.returncode == 0, r.stdout + r.stderr
    lines = r.stdout.splitlines()
    assert lines[0] == "digraph graftlint_callgraph {"
    assert lines[-1] == "}"
    edges = set()
    for line in lines[1:-1]:
        m = _DOT_EDGE_RE.match(line)
        assert m, f"unparseable DOT line: {line!r}"
        edges.add((m.group(1), m.group(2)))
    assert ("gl06_cases.py:sleepy_via_call",
            "gl06_cases.py:_nap") in edges


def test_whole_program_pass_is_fast():
    """Acceptance: the full-repo whole-program pass runs in < 15 s on
    CPU (measured ~4 s; the bound is generous for a loaded CI box)."""
    import time as _time

    t0 = _time.monotonic()
    result = lint_paths(["harmony_tpu"])
    dt = _time.monotonic() - t0
    assert not result.errors
    assert dt < 15.0, f"whole-program pass took {dt:.1f}s"


# -- kernelcheck (GL09-GL11) ------------------------------------------------


def test_gl09_proves_cios_montmul_and_kernel_modules_clean():
    """ISSUE 10 acceptance: the existing CIOS montmul path — and every
    annotated kernel module — verifies with ZERO unpinned GL09/GL10/
    GL11 findings.  The analysis is non-vacuous (see the seeded-
    overflow and dtype tests below)."""
    result = lint_paths(["harmony_tpu"])
    assert not result.errors, result.errors
    kernel = [f for f in result.findings
              if f.rule in ("GL09", "GL10", "GL11")]
    assert kernel == [], "\n".join(f.render() for f in kernel)


def test_gl09_flags_seeded_karatsuba_overflow_at_exact_line():
    """The sum-of-limbs convolution whose accumulator provably exceeds
    int32 is flagged AT the einsum line, with the proven bound in the
    message; the carry-resolved twin of the same shape is clean."""
    src = (FIXTURES / "gl09_cases.py").read_text(encoding="utf-8")
    rel = "tests/fixtures/graftlint/gl09_cases.py"
    lines = src.splitlines()
    bad_line = next(i for i, ln in enumerate(lines, 1)
                    if "einsum" in ln and "expect: GL09" in ln)
    good_lines = [i for i, ln in enumerate(lines, 1)
                  if "einsum" in ln and "expect" not in ln]
    findings = [f for f in lint_source(src, rel) if f.rule == "GL09"]
    flagged = {f.line for f in findings}
    assert bad_line in flagged
    assert not (flagged & set(good_lines)), (flagged, good_lines)
    kara = next(f for f in findings if f.line == bad_line)
    assert "4829479200" in kara.message  # 12285^2 * 32, the proof
    assert "int" not in kara.fingerprint.split("::")[2]  # ctx is fn name


def test_gl09_bound_is_dtype_parameterized():
    """The same kernel source is provable under int32 lanes and a
    violation under int8 — the knob the MXU int8-plane path needs."""
    src_t = (
        "# graftlint: kernel-module dtype={dtype}\n"
        "import jax.numpy as jnp\n\n"
        "# graftlint: kernel bounds=(<2**4, <2**4) -> any; domain=any\n"
        "def mac(a, b):\n"
        "    return a * b\n"
    )  # 15 * 15 = 225: inside int32 lanes, outside int8's [-128, 127]
    rel = "tests/fixtures/graftlint/virtual_dtype.py"
    ok = lint_source(src_t.format(dtype="int32"), rel)
    assert [f for f in ok if f.rule == "GL09"] == []
    bad = lint_source(src_t.format(dtype="int8"), rel)
    gl09 = [f for f in bad if f.rule == "GL09"]
    assert [f.line for f in gl09] == [6]
    assert "[-128, 127]" in gl09[0].message


def test_gl09_scan_accumulator_bound_is_derived_not_assumed():
    """Tightening normalize's declared input below the derived scan
    bound (~1.078e9) must flag mont_mul's call into it — proof that
    the 32-step CIOS unroll computes a real accumulator bound."""
    import ast as _ast

    from tools.graftlint.engine import _interproc_findings, _suppressions

    fp_src = (REPO / "harmony_tpu/ops/fp.py").read_text(encoding="utf-8")
    assert "bounds=(<2**31) -> limb" in fp_src  # the committed contract
    tightened = fp_src.replace("bounds=(<2**31) -> limb",
                               "bounds=(<2**30) -> limb")
    sources, supps = {}, {}
    for rel, src in (
        ("harmony_tpu/ops/limbs.py",
         (REPO / "harmony_tpu/ops/limbs.py").read_text(encoding="utf-8")),
        ("harmony_tpu/ops/_constants.py",
         (REPO / "harmony_tpu/ops/_constants.py").read_text(
             encoding="utf-8")),
        ("harmony_tpu/ops/fp.py", tightened),
    ):
        sources[rel] = (src, _ast.parse(src))
        supps[rel] = _suppressions(src)
    gl09 = [f for f in _interproc_findings(sources, supps, {"GL09"})
            if "normalize" in f.message]
    assert gl09
    assert any("exceeds declared [0, 1073741823]" in f.message
               for f in gl09), [f.render() for f in gl09]


def test_gl10_typestate_catches_wrong_conversion_inline():
    """from_mont written as a no-op (missing mont_mul by 1) leaves the
    value in the mont domain — caught against the declared std."""
    src = (
        "# graftlint: kernel-module dtype=int32\n"
        "# graftlint: kernel bounds=(limb, limb) -> limb; domain=mul; trusted\n"
        "def mmul(a, b):\n"
        "    return a\n\n"
        "# graftlint: kernel bounds=(limb) -> limb; domain=(mont) -> std\n"
        "def from_mont_broken(a):\n"
        "    return a\n"
    )
    findings = lint_source(src, "tests/fixtures/graftlint/virtual_gl10.py")
    gl10 = [f for f in findings if f.rule == "GL10"]
    assert [f.line for f in gl10] == [7]
    assert "mont" in gl10[0].message and "std" in gl10[0].message


def test_kernel_contract_parse_error_is_a_finding_not_a_crash():
    src = (
        "# graftlint: kernel-module dtype=int32\n"
        "# graftlint: kernel bounds=(wibble) -> limb\n"
        "def f(a):\n"
        "    return a\n"
    )
    findings = lint_source(src, "tests/fixtures/graftlint/virtual_bad.py")
    assert any(f.rule == "GL09" and "unparseable" in f.message
               for f in findings)


def test_gl11_repo_kernels_have_twins_tests_and_guards():
    """The three device-dispatched kernels (verify / agg_verify /
    agg_verify_batch, found via jax.jit sites in device.py) pass all
    three GL11 obligations on the real tree; renaming a twin away
    surfaces exactly that kernel."""
    import ast as _ast

    from tools.graftlint.engine import (_interproc_findings,
                                        _suppressions)

    sources, supps = {}, {}
    for f in sorted((REPO / "harmony_tpu").rglob("*.py")):
        rel = f.relative_to(REPO_ROOT).as_posix()
        src = f.read_text(encoding="utf-8")
        sources[rel] = (src, _ast.parse(src))
        supps[rel] = _suppressions(src)
    assert _interproc_findings(sources, supps, {"GL11"}) == []

    src = sources["harmony_tpu/ops/twin.py"][0].replace(
        "def agg_verify(tbl, bits, h_arr, sig_arr):",
        "def agg_verify_gone(tbl, bits, h_arr, sig_arr):")
    sources["harmony_tpu/ops/twin.py"] = (src, _ast.parse(src))
    broken = _interproc_findings(sources, supps, {"GL11"})
    assert [(f.path, f.context) for f in broken] == \
        [("harmony_tpu/ops/bls.py", "agg_verify")]
    assert "no twin" in broken[0].message


# -- incremental result cache ------------------------------------------------


def test_result_cache_is_content_correct(tmp_path, monkeypatch):
    """Cold == warm == fresh; any byte change re-analyzes; a corrupt
    cache file degrades to a miss, never to wrong results."""
    from tools.graftlint import cache as CA

    monkeypatch.setenv("GRAFTLINT_CACHE", str(tmp_path / "cache.json"))
    target = tmp_path / "mod_under_lint.py"
    target.write_text(
        "def f(x):\n    try:\n        return x.check()\n"
        "    except Exception:\n        pass\n",
        encoding="utf-8",
    )

    def rows(result):
        return [(f.path, f.line, f.rule, f.message) for f in result.findings]

    CA.clear_memory()
    fresh = lint_paths([target])                      # never cached
    cold = lint_paths([target], use_cache=True)       # fills the cache
    CA.clear_memory()                                 # force the disk path
    warm = lint_paths([target], use_cache=True)
    assert rows(fresh) == rows(cold) == rows(warm)
    assert rows(fresh), "fixture must produce findings"

    # a one-byte change must invalidate: the GL04 finding disappears
    target.write_text("def f(x):\n    return x.check()\n",
                      encoding="utf-8")
    CA.clear_memory()
    changed = lint_paths([target], use_cache=True)
    assert rows(changed) == []

    # corrupt cache file: correct results, cache rewritten
    (tmp_path / "cache.json").write_text("{not json", encoding="utf-8")
    CA.clear_memory()
    after = lint_paths([target], use_cache=True)
    assert rows(after) == []

    # linter-source hash keys the entry: a different linter sha misses
    key_now = CA.linter_sha()
    assert isinstance(key_now, str) and len(key_now) == 64

    # GRAFTLINT_CACHE=0 disables persistence entirely
    monkeypatch.setenv("GRAFTLINT_CACHE", "0")
    assert CA.cache_path() is None


def test_cache_aux_regex_covers_module_anno_grammar():
    """The engine's cheap aux-input regex must see every tests= dir the
    kernelcheck annotation parser would hand GL11 — if the grammar
    drifts, the cache could serve stale GL11 results for a changed
    tests tree.  The regex may over-match (spurious invalidation is
    sound); it must never under-match."""
    from tools.graftlint.engine import _TESTS_OVERRIDE_RE
    from tools.graftlint.kernelcheck import collect_annotations

    variants = [
        "# graftlint: kernel-module dtype=int32; tests=tests/kernels\n",
        "# graftlint: kernel-module tests=alt_tests; twin=x.py\n",
        "#  graftlint:  kernel-module  twin=t.py ;  tests=deep/dir\n",
        "# graftlint: kernel-module tests=skip\n",
    ]
    for src in variants:
        anno, _ = collect_annotations(src)
        assert anno is not None
        want = anno.tests
        got = [m.group(1) for m in _TESTS_OVERRIDE_RE.finditer(src)]
        if want is not None:
            assert want in got, (src, want, got)


def test_cli_no_cache_flag(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text("def f():\n    return 1\n", encoding="utf-8")
    r = _run_cli(str(clean), "--no-cache",
                 "--baseline", str(tmp_path / "none.json"))
    assert r.returncode == 0, r.stdout + r.stderr


def test_sarif_driver_lists_kernel_rules(tmp_path):
    from tools.graftlint import RULES

    assert {"GL09", "GL10", "GL11"} <= set(RULES)
    dirty = tmp_path / "dirty.py"
    dirty.write_text(
        "# graftlint: kernel-module dtype=int8\n"
        "import jax.numpy as jnp\n\n"
        "# graftlint: kernel bounds=(<2**7, <2**7) -> any; domain=any\n"
        "def mac(a, b):\n"
        "    return a * b\n",
        encoding="utf-8",
    )
    r = _run_cli(str(dirty), "--sarif",
                 "--baseline", str(tmp_path / "none.json"))
    assert r.returncode == 1, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    run = doc["runs"][0]
    rule_ids = {x["id"] for x in run["tool"]["driver"]["rules"]}
    assert {"GL09", "GL10", "GL11"} <= rule_ids
    results = run["results"]
    assert {x["ruleId"] for x in results} == {"GL09"}
    assert results[0]["locations"][0]["physicalLocation"]["region"][
        "startLine"] == 6


def test_interproc_fingerprints_are_line_free_and_stable():
    """GL05/GL06/GL07 fingerprints carry the lock pair / sync site,
    never line numbers or witness chains — pins must survive unrelated
    edits and witness rerouting."""
    result = lint_paths(["harmony_tpu"])
    inter = [f for f in result.findings
             if f.rule in ("GL05", "GL06", "GL07")]
    assert inter, "expected pinned interprocedural findings to exist"
    for f in inter:
        assert str(f.line) not in f.fingerprint.split("::", 2)[2], (
            "line leaked into fingerprint", f.fingerprint)
        if f.detail:
            assert f.detail not in f.fingerprint
