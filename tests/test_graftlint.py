"""graftlint tier-1 gate + linter self-tests.

Pure-AST: none of these tests import jax or the linted modules, so the
whole file runs in a few seconds and belongs in tier-1.  Three layers:

1. fixture files under tests/fixtures/graftlint/ assert exact rule ids
   and line numbers per rule family (positive + suppressed cases);
2. baseline machinery (pinning, excess-is-new, fixed detection) on a
   dedicated pinned-cases fixture;
3. THE GATE: harmony_tpu/ linted against the committed baseline — any
   new finding fails tier-1 — plus the CLI exit-code contract.
"""

import json
import re
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.graftlint import (  # noqa: E402
    DEFAULT_BASELINE_PATH,
    REPO_ROOT,
    Baseline,
    lint_paths,
    lint_source,
    load_baseline,
)
from tools.graftlint.engine import compare  # noqa: E402

FIXTURES = REPO / "tests" / "fixtures" / "graftlint"
_EXPECT_RE = re.compile(r"#\s*expect:\s*(GL\d{2}(?:\s*,\s*GL\d{2})*)")


def _expected(path: Path) -> set:
    out = set()
    for lineno, line in enumerate(
            path.read_text(encoding="utf-8").splitlines(), start=1):
        m = _EXPECT_RE.search(line)
        if m:
            for rule in m.group(1).split(","):
                out.add((lineno, rule.strip()))
    return out


@pytest.mark.parametrize("name", [
    "gl01_cases.py", "gl02_cases.py", "gl03_cases.py", "gl04_cases.py",
])
def test_fixture_exact_lines(name):
    """Each rule family flags exactly the tagged lines — no more, no
    less — and inline suppressions (incl. wrong-rule ones) behave."""
    path = FIXTURES / name
    rel = path.relative_to(REPO_ROOT).as_posix()
    findings = lint_source(path.read_text(encoding="utf-8"), rel)
    actual = {(f.line, f.rule) for f in findings}
    expected = _expected(path)
    assert actual == expected, (
        f"{name}: flagged {sorted(actual - expected)} unexpectedly, "
        f"missed {sorted(expected - actual)}"
    )


def test_fixture_rules_scoped_inside_harmony_tpu():
    """The same weak-where source that fires in a limb module is out of
    scope elsewhere in harmony_tpu/ — scoping is path-based."""
    src = "import jax.numpy as jnp\n\ndef f(x):\n    return jnp.where(x > 0, 1, 0)\n"
    in_scope = lint_source(src, "harmony_tpu/ops/fp.py")
    out_of_scope = lint_source(src, "harmony_tpu/consensus/quorum.py")
    assert [(f.rule, f.line) for f in in_scope] == [("GL02", 4)]
    assert out_of_scope == []


PINNED_SRC = '''\
def racy_one(sig):
    try:
        return sig.check()
    except Exception:
        pass


def racy_two(sig):
    try:
        return sig.check()
    except Exception:
        pass
'''


def test_baseline_pins_and_flags_excess():
    """Pinned findings stay quiet; the same fingerprint appearing MORE
    often than pinned reports exactly the excess sites."""
    rel = "tests/fixtures/graftlint/pinned_virtual.py"
    findings = lint_source(PINNED_SRC, rel)
    assert [(f.rule, f.line) for f in findings] == [
        ("GL04", 4), ("GL04", 11),
    ]
    # distinct contexts -> distinct fingerprints: pin both, gate clean
    full = Baseline.from_findings(findings)
    new, pinned, fixed = compare(findings, full)
    assert new == [] and pinned == 2 and fixed == []

    # same fingerprint twice, only one pinned -> the excess is NEW and
    # it is the LATER line that is reported
    dup_src = PINNED_SRC.replace("racy_two", "racy_one")
    dup = lint_source(dup_src, rel)
    assert len({f.fingerprint for f in dup}) == 1
    half = Baseline({dup[0].fingerprint: 1})
    new, pinned, fixed = compare(dup, half)
    assert pinned == 1 and [f.line for f in new] == [11]

    # a fixed finding is reported so the pin can be shrunk
    new, pinned, fixed = compare([], full)
    assert new == [] and pinned == 0 and len(fixed) == 2


def test_repo_gate_clean_against_committed_baseline():
    """THE tier-1 gate: no new violations in harmony_tpu/."""
    result = lint_paths(["harmony_tpu"])
    assert not result.errors, result.errors
    baseline = load_baseline()
    new, _pinned, fixed = compare(result.findings, baseline)
    assert not new, (
        "new graftlint violations (fix them, or pin deliberate debt "
        "via `python -m tools.graftlint --write-baseline`):\n"
        + "\n".join(f.render() for f in new)
    )
    assert not fixed, (
        "baseline entries no longer fire — shrink the pin file with "
        "`python -m tools.graftlint --write-baseline`:\n"
        + "\n".join(fixed)
    )


def test_baseline_has_no_ops_gl01_gl02_pins():
    """The ops/ hot path must be FIXED, never pinned, for purity and
    dtype discipline (ISSUE 1 acceptance criterion)."""
    baseline = load_baseline()
    offenders = [
        fp for fp in baseline.counts
        if fp.startswith("harmony_tpu/ops/")
        and ("::GL01::" in fp or "::GL02::" in fp)
    ]
    assert offenders == []


def _run_cli(*args, cwd=REPO_ROOT):
    return subprocess.run(
        [sys.executable, "-m", "tools.graftlint", *args],
        cwd=cwd, capture_output=True, text=True, timeout=120,
    )


def test_cli_exit_code_contract(tmp_path):
    """0 clean, 1 new violations, 2 internal error — stable for hooks."""
    clean = tmp_path / "clean.py"
    clean.write_text("def f():\n    return 1\n", encoding="utf-8")
    dirty = tmp_path / "dirty.py"
    dirty.write_text(
        "def f(x):\n    try:\n        return x.check()\n"
        "    except:\n        pass\n",
        encoding="utf-8",
    )
    missing_baseline = tmp_path / "nothing.json"

    r = _run_cli(str(clean), "--baseline", str(missing_baseline))
    assert r.returncode == 0, r.stdout + r.stderr

    r = _run_cli(str(dirty), "--baseline", str(missing_baseline))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "GL04" in r.stdout

    r = _run_cli(str(dirty), "--rules", "GL99")
    assert r.returncode == 2, r.stdout + r.stderr

    # --write-baseline pins the debt; the re-run gates clean on it
    pin = tmp_path / "baseline.json"
    r = _run_cli(str(dirty), "--baseline", str(pin), "--write-baseline")
    assert r.returncode == 0, r.stdout + r.stderr
    data = json.loads(pin.read_text(encoding="utf-8"))
    assert sum(e["count"] for e in data["findings"]) == 1
    r = _run_cli(str(dirty), "--baseline", str(pin))
    assert r.returncode == 0, r.stdout + r.stderr

    # a narrowed run must not clobber the DEFAULT baseline's other pins
    committed = DEFAULT_BASELINE_PATH.read_bytes()
    r = _run_cli(str(dirty), "--write-baseline")
    assert r.returncode == 2, r.stdout + r.stderr
    assert "refusing" in r.stderr
    assert DEFAULT_BASELINE_PATH.read_bytes() == committed

    # a syntactically broken file gates like a violation, not a crash
    broken = tmp_path / "broken.py"
    broken.write_text("def f(:\n", encoding="utf-8")
    r = _run_cli(str(broken), "--baseline", str(missing_baseline))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "SyntaxError" in r.stderr

    # a typo'd path must fail loudly, not lint zero files and pass
    r = _run_cli(str(tmp_path / "no_such_dir"),
                 "--baseline", str(missing_baseline))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "not a .py file or directory" in r.stderr


def test_default_baseline_is_committed_and_loads():
    assert DEFAULT_BASELINE_PATH.exists()
    baseline = load_baseline()
    for fp, count in baseline.counts.items():
        assert count >= 1
        path = fp.split("::", 1)[0]
        assert (REPO_ROOT / path).exists(), f"stale baseline path {path}"
