"""Cross-link verification tests: multiple shards' proofs on the beacon.

Engine runs host-mode (device=False) here: this image's XLA persistent cache aborts deserializing the big pairing executables (see tests/conftest.py); the device path's correctness is covered by the ops parity suite and runs on real TPU via bench/__graft_entry__."""

import pytest

from harmony_tpu import bls as B
from harmony_tpu.chain.crosslink import (
    CrossLink,
    verify_crosslink,
    verify_crosslinks_batch,
)
from harmony_tpu.chain.engine import Engine, EpochContext
from harmony_tpu.consensus.mask import Mask
from harmony_tpu.consensus.signature import construct_commit_payload


@pytest.fixture(scope="module")
def shards():
    """Two shards with distinct 4-key committees."""
    committees = {}
    for shard in (0, 1):
        keys = [
            B.PrivateKey.generate(bytes([50 + 10 * shard + i]))
            for i in range(4)
        ]
        committees[shard] = keys
    return committees


def _make_link(committees, shard, block_num, signers):
    keys = committees[shard]
    block_hash = bytes([shard]) * 16 + block_num.to_bytes(16, "little")
    payload = construct_commit_payload(block_hash, block_num, block_num, True)
    agg = B.aggregate_sigs([keys[i].sign_hash(payload) for i in signers])
    mask = Mask([k.pub.point for k in keys])
    for i in signers:
        mask.set_bit(i, True)
    return CrossLink(
        shard_id=shard,
        block_num=block_num,
        view_id=block_num,
        epoch=1,
        block_hash=block_hash,
        signature=agg.bytes,
        bitmap=mask.mask_bytes(),
    )


@pytest.fixture(scope="module")
def engine(shards):
    def provider(shard_id, epoch):
        return EpochContext([k.pub.bytes for k in shards[shard_id]])

    return Engine(provider, device=False)


def test_single_crosslink(engine, shards):
    link = _make_link(shards, 0, 500, [0, 1, 2, 3])
    assert verify_crosslink(engine, link)
    # quorum failure: 2 of 4
    weak = _make_link(shards, 0, 501, [0, 1])
    assert not verify_crosslink(engine, weak)


def test_batch_across_shards(engine, shards):
    links = [
        _make_link(shards, 0, 600, [0, 1, 2]),
        _make_link(shards, 1, 600, [1, 2, 3]),
        _make_link(shards, 0, 601, [0, 1, 2, 3]),
    ]
    # tamper: shard-1 proof presented as shard-0's (wrong committee)
    stolen = CrossLink(
        shard_id=0,
        block_num=600,
        view_id=600,
        epoch=1,
        block_hash=links[1].block_hash,
        signature=links[1].signature,
        bitmap=links[1].bitmap,
    )
    results = verify_crosslinks_batch(engine, links + [stolen])
    assert results == [True, True, True, False]
