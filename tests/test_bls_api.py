"""Wrapper-API tests: PublicKey/PrivateKey/Signature + multibls."""

import pytest

from harmony_tpu import bls as B
from harmony_tpu import multibls as MB


@pytest.fixture(scope="module")
def keys():
    return [B.PrivateKey.generate(bytes([i])) for i in range(3)]


MSG = b"0123456789abcdef0123456789abcdef"


def test_wrapper_roundtrip(keys):
    k = keys[0]
    assert len(k.pub.bytes) == B.PUBKEY_BYTES
    assert B.PublicKey.from_bytes(k.pub.bytes) == k.pub
    assert B.PrivateKey.from_bytes(k.bytes).pub == k.pub
    sig = k.sign_hash(MSG)
    assert len(sig.bytes) == B.SIG_BYTES
    assert B.Signature.from_bytes(sig.bytes) == sig


def test_sign_verify_wrapper(keys):
    sig = keys[0].sign_hash(MSG)
    assert sig.verify(keys[0].pub, MSG)
    assert not sig.verify(keys[1].pub, MSG)


def test_pubkey_add_sub(keys):
    a, b = keys[0].pub, keys[1].pub
    assert a.add(b).sub(b) == a


def test_aggregate_and_verify(keys):
    sigs = [k.sign_hash(MSG) for k in keys]
    agg = B.aggregate_sigs(sigs)
    agg_pk = keys[0].pub.add(keys[1].pub).add(keys[2].pub)
    assert agg.verify(agg_pk, MSG)


def test_multibls_dedup_and_aggregate(keys):
    pks = MB.PrivateKeys.from_keys(keys + [keys[0]])  # duplicate dropped
    assert len(pks) == 3
    assert pks.public_keys().contains(keys[1].pub)
    agg = pks.sign_hash_aggregated(MSG)
    agg_pk = keys[0].pub.add(keys[1].pub).add(keys[2].pub)
    assert agg.verify(agg_pk, MSG)


def test_cached_deserialization(keys):
    data = keys[0].pub.bytes
    p1 = B.pubkey_from_bytes_cached(data)
    p2 = B.pubkey_from_bytes_cached(data)
    assert p1.point is p2.point  # LRU hit returns the same object
