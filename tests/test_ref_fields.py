"""Algebraic-identity tests for the bigint tower fields."""

import random

from harmony_tpu.ref import fields as F
from harmony_tpu.ref.params import P

rng = random.Random(0xB15)


def rand_fp():
    return rng.randrange(P)


def rand_fp2():
    return (rand_fp(), rand_fp())


def rand_fp6():
    return (rand_fp2(), rand_fp2(), rand_fp2())


def rand_fp12():
    return (rand_fp6(), rand_fp6())


def test_fp2_ring_axioms():
    for _ in range(20):
        a, b, c = rand_fp2(), rand_fp2(), rand_fp2()
        assert F.fp2_mul(a, b) == F.fp2_mul(b, a)
        assert F.fp2_mul(a, F.fp2_add(b, c)) == F.fp2_add(
            F.fp2_mul(a, b), F.fp2_mul(a, c)
        )
        assert F.fp2_mul(F.fp2_mul(a, b), c) == F.fp2_mul(a, F.fp2_mul(b, c))


def test_fp2_inverse_and_conj():
    for _ in range(20):
        a = rand_fp2()
        assert F.fp2_mul(a, F.fp2_inv(a)) == F.FP2_ONE
        # conj is the p-power Frobenius
        assert F.fp2_conj(a) == tuple_pow_p(a)


def tuple_pow_p(a):
    # a^p via binary pow in Fp2 (slow; only for this test)
    result = F.FP2_ONE
    base = a
    e = P
    while e:
        if e & 1:
            result = F.fp2_mul(result, base)
        base = F.fp2_mul(base, base)
        e >>= 1
    return result


def test_fp2_sqrt_roundtrip():
    found = 0
    for _ in range(20):
        a = rand_fp2()
        s = F.fp2_sqrt(a)
        if s is not None:
            assert F.fp2_sqr(s) == a
            found += 1
    assert found > 0  # ~half of elements are squares
    # squares always have roots
    for _ in range(10):
        a = rand_fp2()
        sq = F.fp2_sqr(a)
        s = F.fp2_sqrt(sq)
        assert s is not None and F.fp2_sqr(s) == sq


def test_fp6_inverse_and_v_reduction():
    for _ in range(10):
        a = rand_fp6()
        assert F.fp6_mul(a, F.fp6_inv(a)) == F.FP6_ONE
        # v^3 = xi: multiplying three times by v == multiplying by xi
        v3 = F.fp6_mul_v(F.fp6_mul_v(F.fp6_mul_v(a)))
        xi_a = tuple(F.fp2_mul_xi(c) for c in a)
        assert v3 == xi_a


def test_fp12_inverse_mul_pow():
    for _ in range(5):
        a, b = rand_fp12(), rand_fp12()
        assert F.fp12_mul(a, F.fp12_inv(a)) == F.FP12_ONE
        assert F.fp12_mul(a, b) == F.fp12_mul(b, a)
        assert F.fp12_pow(a, 5) == F.fp12_mul(
            F.fp12_mul(F.fp12_sqr(F.fp12_sqr(a)), a), F.FP12_ONE
        )


def test_fp12_conj_is_p6_frobenius():
    # w^2 = v, and conj negates w-coefficient: conj(a) == a^(p^6) — check via
    # the multiplicative property conj(ab) = conj(a) conj(b) and conj(w) = -w
    a, b = rand_fp12(), rand_fp12()
    assert F.fp12_conj(F.fp12_mul(a, b)) == F.fp12_mul(
        F.fp12_conj(a), F.fp12_conj(b)
    )
    assert F.fp12_conj(F.FP12_W) == F.fp12_sub(F.FP12_ZERO, F.FP12_W)
