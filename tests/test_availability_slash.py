"""Availability accounting + double-sign slashing tests."""

import pytest

from harmony_tpu import bls as B
from harmony_tpu.consensus.signature import construct_commit_payload
from harmony_tpu.numeric import Dec
from harmony_tpu.staking import availability as AV
from harmony_tpu.staking import slash as SL


def test_block_signers_split():
    keys = [bytes([i]) * 48 for i in range(10)]
    bitmap = bytes([0b00000111, 0b00000010])  # signers 0,1,2,9
    signed, missing = AV.block_signers(bitmap, keys)
    assert signed == [keys[0], keys[1], keys[2], keys[9]]
    assert len(missing) == 6
    with pytest.raises(ValueError):
        AV.block_signers(b"\x00", keys)


def test_increment_and_threshold():
    counters = {}
    members = ["a", "b", "c"]
    for _ in range(9):
        AV.increment_counts(counters, ["a", "b"], members)
    # a,b signed 9/9; c signed 0/9
    assert counters["a"].num_blocks_signed == 9
    assert counters["c"].num_blocks_to_sign == 9
    snap = AV.Counters()
    good = AV.compute_current_signing(snap, counters["a"])
    assert not good.is_below_threshold
    bad = AV.compute_current_signing(snap, counters["c"])
    assert bad.is_below_threshold
    # exactly 2/3 is BELOW threshold (LTE semantics, measure.go:178-181)
    c = AV.Counters(num_blocks_to_sign=9, num_blocks_signed=6)
    assert AV.compute_current_signing(snap, c).is_below_threshold
    c = AV.Counters(num_blocks_to_sign=9, num_blocks_signed=7)
    assert not AV.compute_current_signing(snap, c).is_below_threshold


def test_detect_double_sign():
    ballots = {b"key1": b"hashA"}
    assert SL.detect_double_sign(ballots, b"key1", b"hashB") == b"hashA"
    assert SL.detect_double_sign(ballots, b"key1", b"hashA") is None
    assert SL.detect_double_sign(ballots, b"key2", b"hashB") is None


@pytest.fixture(scope="module")
def signed_evidence():
    k = B.PrivateKey.generate(b"\x55")
    moment = SL.Moment(epoch=3, shard_id=0, height=100, view_id=7)
    h1, h2 = bytes([1]) * 32, bytes([2]) * 32
    votes = []
    for h in (h1, h2):
        payload = construct_commit_payload(h, moment.height, moment.view_id)
        votes.append(
            SL.Vote(
                signer_pubkeys=[k.pub.bytes],
                block_header_hash=h,
                signature=k.sign_hash(payload).bytes,
            )
        )
    record = SL.Record(
        evidence=SL.Evidence(
            moment=moment,
            first_vote=votes[0],
            second_vote=votes[1],
            offender=b"offender-addr",
        ),
        reporter=b"reporter-addr",
    )
    return record, k


def test_verify_valid_record(signed_evidence):
    record, k = signed_evidence
    SL.verify_record(record, [k.pub.bytes])  # no raise


def test_verify_rejects_bad_records(signed_evidence):
    record, k = signed_evidence
    committee = [k.pub.bytes]

    same = SL.Record(
        evidence=SL.Evidence(
            moment=record.evidence.moment,
            first_vote=record.evidence.first_vote,
            second_vote=record.evidence.first_vote,  # no conflict
            offender=record.evidence.offender,
        ),
        reporter=record.reporter,
    )
    with pytest.raises(SL.SlashVerifyError, match="conflict"):
        SL.verify_record(same, committee)

    self_report = SL.Record(
        evidence=record.evidence, reporter=record.evidence.offender
    )
    with pytest.raises(SL.SlashVerifyError, match="same"):
        SL.verify_record(self_report, committee)

    other = B.PrivateKey.generate(b"\x66")
    with pytest.raises(SL.SlashVerifyError, match="committee"):
        SL.verify_record(record, [other.pub.bytes])

    # tampered signature
    import dataclasses

    bad_vote = dataclasses.replace(
        record.evidence.second_vote,
        signature=record.evidence.first_vote.signature,
    )
    bad = SL.Record(
        evidence=dataclasses.replace(record.evidence, second_vote=bad_vote),
        reporter=record.reporter,
    )
    with pytest.raises(SL.SlashVerifyError, match="signature"):
        SL.verify_record(bad, committee)


def test_apply_slash_economics():
    app = SL.apply_slash(stake=1000)
    assert app.total_slashed == 20  # 2%
    assert app.total_beneficiary_reward == 10  # half to reporter
