"""ISSUE 4 observability tier: the span tracer, cross-boundary trace
propagation (consensus messages, sidecar frames, p2p streams, device
dispatch), the flight recorder, and the /debug/trace export.

Device kernels are the numpy/bigint twins (same trick as test_chaos:
real verify decisions, no XLA pairing compiles on the CPU image) and
``device.use_device(True)`` forces the device branches where a test
needs them — every span asserted here comes from the REAL dispatch
path, not a mock.
"""

import io
import json
import time

import numpy as np
import pytest

from harmony_tpu import bls as B
from harmony_tpu import device as DV
from harmony_tpu import faultinject as FI
from harmony_tpu import trace
from harmony_tpu.consensus.mask import Mask
from harmony_tpu.log import get_logger, init_logging
from harmony_tpu.ops import bls as OB
from harmony_tpu.ref import bls as RB
from harmony_tpu.ref.curve import g1


@pytest.fixture(autouse=True)
def _clean(tmp_path):
    """Every test starts disarmed and dumps into its own tmp dir."""
    trace.reset()
    FI.reset()
    trace.configure(dump_dir=str(tmp_path))
    yield
    trace.reset()
    FI.reset()
    DV.set_dispatch_deadline(None)


# -- tracer core -------------------------------------------------------------


def test_span_nesting_and_context():
    trace.configure(enabled=True)
    with trace.span("round", component="consensus") as root:
        assert trace.current_span() is root
        with trace.span("dispatch", component="device") as child:
            assert child.trace_id == root.trace_id
            assert child.parent_id == root.span_id
        assert trace.current_span() is root
    assert trace.current_span() is None
    spans = trace.spans(root.trace_id)
    assert {s.name for s in spans} == {"round", "dispatch"}
    assert all(s.dur_s is not None for s in spans)


def test_traceparent_roundtrip_and_garbage():
    trace.configure(enabled=True)
    with trace.span("r") as sp:
        tc = trace.traceparent()
        assert len(tc) == trace.TRACEPARENT_LEN
        assert trace.parse_traceparent(tc) == (sp.trace_id, sp.span_id)
    # malformed context never raises, never records
    for junk in (b"", b"junk", b"\xff" * 26, b"\x00" * 25):
        assert trace.parse_traceparent(junk) is None
        with trace.resume(junk, "x"):
            pass
    assert not trace.spans(trace_id="ffffffffffffffffffffffffffffffff")


def test_sampling_knob_deterministic():
    trace.configure(enabled=True, sample_rate=0.0)
    with trace.span("unsampled"):
        assert trace.traceparent() == b""
    assert trace.spans() == []
    trace.configure(sample_rate=1.0)
    with trace.span("sampled"):
        pass
    assert len(trace.spans()) == 1


def test_disabled_cost_is_a_comparison():
    """THE acceptance overhead bound: tracing disabled must add no
    measurable per-dispatch cost.  The disabled entry points return one
    shared no-op after a single flag check — asserted structurally
    (identity) and by a generous micro-benchmark bound (<20 us/call
    including the with-statement, ~50x the observed cost, so a loaded
    CI box never flakes this)."""
    assert not trace.enabled()
    assert trace.span("a") is trace.span("b")  # shared no-op singleton
    assert trace.traceparent() == b""
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        with trace.span("device.dispatch", component="device"):
            pass
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 20e-6, f"disabled span cost {per_call * 1e6:.2f}us"


# -- consensus message codec -------------------------------------------------


def test_fbft_message_carries_unsigned_trace_ctx():
    from harmony_tpu.consensus.messages import (
        FBFTMessage, MsgType, decode_message, encode_message,
    )

    m = FBFTMessage(MsgType.PREPARE, 1, 2, b"\x00" * 32,
                    [b"\x01" * 48], b"sig-bytes")
    legacy = encode_message(m)  # no trailer when no context
    assert decode_message(legacy).trace_ctx == b""
    m.trace_ctx = b"\x00" + b"\xab" * 16 + b"\xcd" * 8 + b"\x01"
    wired = decode_message(encode_message(m))
    assert wired.trace_ctx == m.trace_ctx
    assert wired.payload == m.payload
    # the context is transport metadata: same signable bytes, same key
    from harmony_tpu.consensus.messages import signable_bytes

    assert signable_bytes(wired) == signable_bytes(
        decode_message(legacy)
    )
    assert wired.key() == decode_message(legacy).key()
    # truncated trailer is malformed wire, not a crash
    with pytest.raises(ValueError):
        decode_message(encode_message(m)[:-3])


# -- log correlation ---------------------------------------------------------


def test_log_records_carry_trace_ids_and_feed_recorder():
    import sys

    trace.configure(enabled=True)
    buf = io.StringIO()
    init_logging(level="info", stream=buf)
    try:
        log = get_logger("test-trace")
        with trace.span("round", component="consensus") as sp:
            log.info("inside the round", block=7)
        log.info("outside any span")
    finally:
        init_logging(stream=sys.stderr)
    lines = [json.loads(ln) for ln in buf.getvalue().splitlines()]
    inside = next(ln for ln in lines if ln["msg"] == "inside the round")
    outside = next(ln for ln in lines if ln["msg"] == "outside any span")
    assert inside["trace_id"] == sp.trace_id
    assert inside["span_id"] == sp.span_id
    assert "trace_id" not in outside
    # the same record reached the flight recorder's event ring
    dump = trace.anomaly("unit_test", trace_id=sp.trace_id)
    payload = json.load(open(dump))
    assert any(r["msg"] == "inside the round" for r in payload["logs"])
    assert all(r.get("trace_id") == sp.trace_id for r in payload["logs"])


# -- device dispatch spans + metrics ----------------------------------------


N_KEYS = 4


def _twin_agg_verify(pk_affs, bitmap, h_aff, agg_sig_aff):
    from harmony_tpu.ops import interop as I

    tbl = np.asarray(pk_affs)
    agg = None
    for i, bit in enumerate(np.asarray(bitmap)):
        if bit:
            agg = g1.add(agg, (I.arr_to_fp(tbl[i][0]),
                               I.arr_to_fp(tbl[i][1])))
    if agg is None:
        return np.asarray(False)
    h = (I.arr_to_fp2(np.asarray(h_aff)[0]),
         I.arr_to_fp2(np.asarray(h_aff)[1]))
    s = (I.arr_to_fp2(np.asarray(agg_sig_aff)[0]),
         I.arr_to_fp2(np.asarray(agg_sig_aff)[1]))
    return np.asarray(RB.verify_hashed(agg, h, s))


def _twin_verify(pk_affs, h_affs, sig_affs):
    from harmony_tpu.ops import interop as I

    out = []
    for pk, h, s in zip(np.asarray(pk_affs), np.asarray(h_affs),
                        np.asarray(sig_affs)):
        out.append(RB.verify_hashed(
            (I.arr_to_fp(pk[0]), I.arr_to_fp(pk[1])),
            (I.arr_to_fp2(h[0]), I.arr_to_fp2(h[1])),
            (I.arr_to_fp2(s[0]), I.arr_to_fp2(s[1])),
        ))
    return np.asarray(out)


@pytest.fixture
def forced_device(monkeypatch):
    """Force the device path with cheap numpy/bigint twins standing in
    for the XLA kernels (the test_chaos recipe) and isolate the
    program-shape cache so hit/miss accounting starts fresh."""
    DV.use_device(True)
    monkeypatch.setattr(OB, "agg_verify", _twin_agg_verify)
    monkeypatch.setattr(OB, "verify", _twin_verify)
    monkeypatch.setattr(DV, "_SEEN_PROGRAMS", set())
    monkeypatch.setenv("HARMONY_KERNEL_TWIN", "1")
    monkeypatch.setattr(
        "harmony_tpu.ops.twin.agg_verify", _twin_agg_verify
    )
    monkeypatch.setattr("harmony_tpu.ops.twin.verify", _twin_verify)
    yield
    DV.use_device(None)


@pytest.fixture
def committee():
    keys = [B.PrivateKey.generate(bytes([60 + i])) for i in range(N_KEYS)]
    return keys, [k.pub.bytes for k in keys]


def test_device_dispatch_spans_and_new_metrics(forced_device, committee):
    from harmony_tpu.metrics import Registry

    keys, serialized = committee
    trace.configure(enabled=True)
    payload = b"observability-payload-32-bytes!!"
    sigs = [keys[i].sign_hash(payload) for i in range(3)]
    agg = B.aggregate_sigs(sigs)
    table = DV.get_committee_table(
        serialized, [k.pub.point for k in keys]
    )
    h2d0, d2h0 = DV.TRANSFER["h2d"], DV.TRANSFER["d2h"]
    hit0, miss0 = DV.JIT["hit"], DV.JIT["miss"]
    with trace.span("round", component="consensus") as root:
        for _ in range(3):
            assert DV.agg_verify_on_device(
                table, [1, 1, 1, 0], payload, agg.point
            )
    spans = [s for s in trace.spans(root.trace_id)
             if s.name == "device.dispatch"]
    assert len(spans) == 3
    assert all(s.parent_id == root.span_id for s in spans)
    # annotated with the program shape + jit-cache verdict
    caches = sorted(s.attrs["jit_cache"] for s in spans)
    assert caches == ["hit", "hit", "miss"]
    assert all(s.attrs["h2d_bytes"] > 0 for s in spans)
    # metrics: transfer bytes moved, exactly one compile, two reuses
    assert DV.TRANSFER["h2d"] > h2d0 and DV.TRANSFER["d2h"] > d2h0
    assert DV.JIT["miss"] == miss0 + 1 and DV.JIT["hit"] == hit0 + 2
    text = Registry().expose()
    assert "harmony_device_dispatch_seconds_count" in text
    assert 'harmony_device_transfer_bytes_total{direction="h2d"}' in text
    assert 'harmony_device_jit_programs_total{cache="miss"}' in text
    assert "harmony_device_jit_compile_seconds" in text


# -- sidecar propagation + reconnect ----------------------------------------


def test_sidecar_reconnect_resumes_trace(committee):
    """Satellite: kill the sidecar stream mid-round (faultinject) —
    the replayed connection resumes spans under the SAME trace_id,
    with no orphan spans, and the desync fires the flight recorder."""
    from harmony_tpu.sidecar import protocol as P
    from harmony_tpu.sidecar.client import SidecarClient
    from harmony_tpu.sidecar.server import SidecarServer

    keys, serialized = committee
    trace.configure(enabled=True)
    srv = SidecarServer().start()
    c = SidecarClient(srv.address)
    try:
        c.set_committee(3, 0, serialized)
        # the reader is parked in read_frame, so the armed fault fires
        # on its NEXT wakeup — right after the first in-round reply:
        # stream desync -> fail closed -> the next call redials,
        # REPLAYS the committee, and retries, all under the same trace
        FI.arm("sidecar.frame", exc=ValueError("injected garble"),
               times=1)
        payload = b"mid-round sidecar check payload!"
        mask = Mask([k.pub.point for k in keys])
        for i in range(3):
            mask.set_bit(i, True)
        agg = B.aggregate_sigs(
            [keys[i].sign_hash(payload) for i in range(3)]
        )
        with trace.span("round", component="consensus") as root:
            c.agg_verify(3, 0, payload, mask.mask_bytes(), agg.bytes)
            # the armed fault now kills the stream (desync, fail
            # closed) — wait for the drop, mid-round
            deadline = time.monotonic() + 5.0
            while c._sock is not None and time.monotonic() < deadline:
                time.sleep(0.01)
            assert c._sock is None, "injected desync did not drop conn"
            # second in-round call: redial + committee replay + retry
            c.agg_verify(3, 0, payload, mask.mask_bytes(), agg.bytes)
    finally:
        c.close()
        srv.stop()
    spans = trace.spans(root.trace_id)
    ids = {s.span_id for s in spans}
    # no orphans: every parent is in this trace (or the root itself)
    assert all(s.parent_id in ids for s in spans if s.parent_id)
    comps = {s.name for s in spans}
    assert "sidecar.call" in comps and "sidecar.serve" in comps
    # the replayed connection resumed under the round's trace: the
    # server saw BOTH the replayed SET_COMMITTEE and the retried
    # AGG_VERIFY inside trace root
    serve_types = sorted(
        s.attrs["msg_type"] for s in spans if s.name == "sidecar.serve"
    )
    assert P.MSG_SET_COMMITTEE in serve_types
    assert P.MSG_AGG_VERIFY in serve_types
    assert FI.hits("sidecar.frame") > 0
    # the desync anomaly produced a flight-recorder dump
    kinds = [json.load(open(p))["kind"] for p in trace.dumps()]
    assert kinds.count("sidecar_desync") == 1


# -- p2p stream propagation --------------------------------------------------


def test_p2p_stream_propagates_trace():
    from harmony_tpu.core.blockchain import Blockchain
    from harmony_tpu.core.genesis import dev_genesis
    from harmony_tpu.core.kv import MemKV
    from harmony_tpu.p2p.stream import SyncClient, SyncServer

    genesis, _, _ = dev_genesis()
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    srv = SyncServer(chain)
    trace.configure(enabled=True)
    try:
        cli = SyncClient(srv.port)
        with trace.span("round", component="consensus") as root:
            head, _ = cli.get_head()
            assert head == 0
        # untraced calls stay wire-compatible (no flag, no prefix)
        trace.configure(enabled=False)
        head, _ = cli.get_head()
        assert head == 0
        cli.close()
    finally:
        srv.close()
    trace.configure(enabled=True)
    spans = trace.spans(root.trace_id)
    names = {s.name for s in spans}
    assert "p2p.request" in names and "p2p.serve" in names
    req = next(s for s in spans if s.name == "p2p.request")
    serve = next(s for s in spans if s.name == "p2p.serve")
    assert serve.parent_id == req.span_id


# -- flight recorder: breaker open ------------------------------------------


def test_breaker_open_dumps_exactly_once(forced_device, committee,
                                         monkeypatch):
    """A breaker-open event triggers EXACTLY ONE flight-recorder dump,
    containing the offending round's spans and its correlated log
    lines; further rejected dispatches do not re-dump."""
    from harmony_tpu.resilience import CircuitBreaker

    keys, serialized = committee
    trace.configure(enabled=True)
    brk = CircuitBreaker("trace-test-device", failure_threshold=1,
                         reset_timeout_s=60.0)
    monkeypatch.setattr(DV, "BREAKER", brk)
    FI.arm("device.dispatch", exc=RuntimeError("injected wedge"))
    payload = b"breaker-open round payload bytes"
    sigs = [keys[i].sign_hash(payload) for i in range(3)]
    agg = B.aggregate_sigs(sigs)
    table = DV.get_committee_table(
        serialized, [k.pub.point for k in keys]
    )
    with trace.span("consensus.round", component="consensus",
                    block=9) as root:
        get_logger("consensus").info("round start", block=9)
        for _ in range(3):  # 1 failure trips it; 2 rejected fallbacks
            assert DV.agg_verify_on_device(
                table, [1, 1, 1, 0], payload, agg.point
            )
    dumps = [json.load(open(p)) for p in trace.dumps()]
    opens = [d for d in dumps if d["kind"] == "breaker_open"]
    assert len(opens) == 1, [d["kind"] for d in dumps]
    dump = opens[0]
    assert dump["trace_id"] == root.trace_id
    span_names = {s["name"] for s in dump["spans"]}
    assert "consensus.round" in span_names
    assert "device.dispatch" in span_names
    assert any(r["msg"] == "round start" for r in dump["logs"])
    assert all(r["trace_id"] == root.trace_id for r in dump["logs"])


# -- THE acceptance scenario: one round, one trace, four components ----------


CHAIN_ID = 2


def _traced_localnet(n_nodes, sidecar_address):
    """In-process localnet whose chains verify seals through an engine
    backed by the verification sidecar — the full deployment vertical:
    consensus gossip -> device-path quorum checks -> sidecar-verified
    insert."""
    from harmony_tpu.chain.engine import Engine, EpochContext
    from harmony_tpu.core.blockchain import Blockchain
    from harmony_tpu.core.genesis import dev_genesis
    from harmony_tpu.core.kv import MemKV
    from harmony_tpu.core.tx_pool import TxPool
    from harmony_tpu.multibls import PrivateKeys
    from harmony_tpu.node.node import Node
    from harmony_tpu.node.registry import Registry
    from harmony_tpu.p2p import InProcessNetwork
    from harmony_tpu.sidecar.client import SidecarClient

    genesis, ecdsa_keys, bls_keys = dev_genesis(n_keys=n_nodes)
    committee = [k.pub.bytes for k in bls_keys]
    net = InProcessNetwork()
    nodes, clients = [], []
    for i in range(n_nodes):
        client = SidecarClient(sidecar_address)
        clients.append(client)
        engine = Engine(
            lambda s, e, c=committee: EpochContext(c),
            device=False, backend=client,
        )
        chain = Blockchain(MemKV(), genesis, engine=engine,
                           blocks_per_epoch=16)
        pool = TxPool(CHAIN_ID, 0, chain.state)
        reg = Registry(
            blockchain=chain, txpool=pool, host=net.host(f"node{i}")
        )
        nodes.append(Node(reg, PrivateKeys.from_keys([bls_keys[i]])))
    return nodes, clients


def _pump(nodes, rounds=50):
    for _ in range(rounds):
        if not any(n.process_pending() for n in nodes):
            break


def test_localnet_round_yields_one_multicomponent_trace(forced_device):
    """A localnet FBFT round under the forced device path produces a
    SINGLE trace_id whose Chrome trace-event export contains nested
    spans from >= 4 components (consensus phase, device dispatch,
    sidecar call, block finalize), served as valid JSON over
    /debug/trace."""
    import http.client

    from harmony_tpu.metrics import MetricsServer, Registry
    from harmony_tpu.sidecar.server import SidecarServer

    trace.configure(enabled=True)
    sidecar = SidecarServer().start()
    nodes, clients = _traced_localnet(4, sidecar.address)
    try:
        leader = next(n for n in nodes if n.is_leader)
        leader.start_round_if_leader()
        _pump(nodes)
        assert all(n.chain.head_number == 1 for n in nodes)

        root_id = None
        rounds = [s for s in trace.spans()
                  if s.name == "consensus.round"]
        assert len(rounds) == 1  # ONE round root span
        root_id = rounds[0].trace_id
        spans = trace.spans(root_id)
        comps = {s.component for s in spans}
        assert {"consensus", "device", "sidecar", "chain"} <= comps, comps
        names = {s.name for s in spans}
        assert {"consensus.round", "consensus.phase.announce",
                "consensus.phase.prepare_quorum",
                "consensus.phase.commit_quorum", "device.dispatch",
                "sidecar.call", "sidecar.serve",
                "chain.finalize"} <= names, names
        # proper nesting, no orphans
        ids = {s.span_id for s in spans}
        assert all(s.parent_id in ids for s in spans if s.parent_id)
        # every consensus-path span shares THE round's trace id: the
        # device/sidecar/chain work of this round joined one trace
        strays = [
            s for s in trace.spans()
            if s.trace_id != root_id and s.component in
            ("consensus", "device", "chain")
        ]
        assert not strays, [(s.name, s.attrs) for s in strays]

        # the export is valid Chrome trace-event JSON over HTTP
        mreg = Registry()
        msrv = MetricsServer(mreg, port=0).start()
        try:
            conn = http.client.HTTPConnection(
                "127.0.0.1", msrv.port, timeout=10
            )
            conn.request("GET", f"/debug/trace?trace_id={root_id}")
            body = conn.getresponse().read()
            conn.close()
            doc = json.loads(body)
        finally:
            msrv.stop()
        events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert len(events) == len(spans)
        for e in events:
            assert {"name", "cat", "ts", "dur", "pid", "tid"} <= set(e)
        # every non-root event's parent exists in the export, and no
        # child STARTS before its parent (message-passing children may
        # legitimately OUTLIVE their parent span, so containment of
        # end times is not asserted)
        by_id = {e["args"]["span_id"]: e for e in events}
        for e in events:
            pid = e["args"].get("parent_id")
            if pid is None:
                continue
            assert pid in by_id
            assert by_id[pid]["ts"] <= e["ts"] + 1e-3
    finally:
        for c in clients:
            c.close()
        for n in nodes:
            n.stop()
        sidecar.stop()
