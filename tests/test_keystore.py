"""Encrypted keyfile tests."""

import json

import pytest

from harmony_tpu import keystore as KS
from harmony_tpu.bls import PrivateKey


def test_roundtrip(tmp_path):
    sk = PrivateKey.generate(b"\x11")
    path = tmp_path / "validator.key"
    KS.save_key(str(path), sk, "hunter2")
    loaded = KS.load_key(str(path), "hunter2")
    assert loaded.scalar == sk.scalar
    assert loaded.pub == sk.pub


def test_wrong_passphrase_rejected():
    sk = PrivateKey.generate(b"\x12")
    blob = KS.encrypt_key(sk, "correct")
    with pytest.raises(ValueError, match="wrong passphrase"):
        KS.decrypt_key(blob, "incorrect")


def test_tamper_detection():
    sk = PrivateKey.generate(b"\x13")
    blob = json.loads(KS.encrypt_key(sk, "pw"))
    ct = bytearray(bytes.fromhex(blob["ciphertext"]))
    ct[0] ^= 1
    blob["ciphertext"] = bytes(ct).hex()
    with pytest.raises(ValueError, match="wrong passphrase or corrupted"):
        KS.decrypt_key(json.dumps(blob).encode(), "pw")


def test_malformed_file():
    with pytest.raises(ValueError, match="malformed"):
        KS.decrypt_key(b"not json", "pw")
    with pytest.raises(ValueError, match="malformed"):
        KS.decrypt_key(b"{}", "pw")


def test_distinct_salts():
    sk = PrivateKey.generate(b"\x14")
    b1, b2 = KS.encrypt_key(sk, "pw"), KS.encrypt_key(sk, "pw")
    assert json.loads(b1)["salt"] != json.loads(b2)["salt"]
    assert json.loads(b1)["ciphertext"] != json.loads(b2)["ciphertext"]


def test_load_keys_multi(tmp_path):
    sks = [PrivateKey.generate(bytes([i])) for i in range(3)]
    pairs = []
    for i, sk in enumerate(sks):
        p = tmp_path / f"k{i}.key"
        KS.save_key(str(p), sk, f"pw{i}")
        pairs.append((str(p), f"pw{i}"))
    loaded = KS.load_keys(pairs)
    assert [k.pub for k in loaded] == [k.pub for k in sks]


def test_load_node_bls_keys_sources(tmp_path, monkeypatch):
    """The blsgen operational surface (reference: internal/blsgen
    config.go): passphrase from file, from env, a multikey directory,
    and KMS envelopes — all through one resolver."""
    from harmony_tpu import bls as B
    from harmony_tpu.blsgen_kms import LocalKMSProvider, save_kms_key
    from harmony_tpu.cli import load_node_bls_keys
    from harmony_tpu.keystore import save_key

    k1 = B.PrivateKey.generate(b"blsgen-one")
    k2 = B.PrivateKey.generate(b"blsgen-two")
    k3 = B.PrivateKey.generate(b"blsgen-three")
    k4 = B.PrivateKey.generate(b"blsgen-four")

    # passphrase file
    save_key(str(tmp_path / "a.key"), k1, "pw-one")
    (tmp_path / "a.pass").write_text("pw-one\n")
    # passphrase env
    save_key(str(tmp_path / "b.key"), k2, "pw-two")
    monkeypatch.setenv("B_PASS", "pw-two")
    # a multikey directory sharing one passphrase file
    d = tmp_path / "multikey"
    d.mkdir()
    save_key(str(d / "c.key"), k3, "pw-dir")
    (tmp_path / "dir.pass").write_text("pw-dir")
    # KMS envelope
    LocalKMSProvider.generate_master(str(tmp_path / "master"))
    provider = LocalKMSProvider(str(tmp_path / "master"))
    save_kms_key(str(tmp_path / "d.kms"), k4.bytes, provider)

    cfg = {
        "bls_keys": [
            {"path": str(tmp_path / "a.key"),
             "passphrase_file": str(tmp_path / "a.pass")},
            {"path": str(tmp_path / "b.key"), "passphrase_env": "B_PASS"},
            {"path": str(tmp_path / "d.kms"), "kms": True},
        ],
        "bls_dir": str(d),
        "bls_dir_passphrase_file": str(tmp_path / "dir.pass"),
        "kms_master_key": str(tmp_path / "master"),
    }
    keys = load_node_bls_keys(cfg)
    got = {k.pub.bytes for k in keys}
    assert got == {k1.pub.bytes, k2.pub.bytes, k3.pub.bytes, k4.pub.bytes}

    # unset env is a config error, not a hang
    import pytest as _pytest

    with _pytest.raises(ValueError):
        load_node_bls_keys({"bls_keys": [
            {"path": str(tmp_path / "b.key"), "passphrase_env": "NOPE"},
        ]})
    # no source + no tty: refuse rather than prompt into the void
    with _pytest.raises(ValueError):
        load_node_bls_keys({"bls_keys": [
            {"path": str(tmp_path / "b.key")},
        ]})
