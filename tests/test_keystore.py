"""Encrypted keyfile tests."""

import json

import pytest

from harmony_tpu import keystore as KS
from harmony_tpu.bls import PrivateKey


def test_roundtrip(tmp_path):
    sk = PrivateKey.generate(b"\x11")
    path = tmp_path / "validator.key"
    KS.save_key(str(path), sk, "hunter2")
    loaded = KS.load_key(str(path), "hunter2")
    assert loaded.scalar == sk.scalar
    assert loaded.pub == sk.pub


def test_wrong_passphrase_rejected():
    sk = PrivateKey.generate(b"\x12")
    blob = KS.encrypt_key(sk, "correct")
    with pytest.raises(ValueError, match="wrong passphrase"):
        KS.decrypt_key(blob, "incorrect")


def test_tamper_detection():
    sk = PrivateKey.generate(b"\x13")
    blob = json.loads(KS.encrypt_key(sk, "pw"))
    ct = bytearray(bytes.fromhex(blob["ciphertext"]))
    ct[0] ^= 1
    blob["ciphertext"] = bytes(ct).hex()
    with pytest.raises(ValueError, match="wrong passphrase or corrupted"):
        KS.decrypt_key(json.dumps(blob).encode(), "pw")


def test_malformed_file():
    with pytest.raises(ValueError, match="malformed"):
        KS.decrypt_key(b"not json", "pw")
    with pytest.raises(ValueError, match="malformed"):
        KS.decrypt_key(b"{}", "pw")


def test_distinct_salts():
    sk = PrivateKey.generate(b"\x14")
    b1, b2 = KS.encrypt_key(sk, "pw"), KS.encrypt_key(sk, "pw")
    assert json.loads(b1)["salt"] != json.loads(b2)["salt"]
    assert json.loads(b1)["ciphertext"] != json.loads(b2)["ciphertext"]


def test_load_keys_multi(tmp_path):
    sks = [PrivateKey.generate(bytes([i])) for i in range(3)]
    pairs = []
    for i, sk in enumerate(sks):
        p = tmp_path / f"k{i}.key"
        KS.save_key(str(p), sk, f"pw{i}")
        pairs.append((str(p), f"pw{i}"))
    loaded = KS.load_keys(pairs)
    assert [k.pub for k in loaded] == [k.pub for k in sks]
