"""RLP codec + Merkle-Patricia trie (reference: go-ethereum rlp/trie
packages; yellow-paper appendices B-D)."""

import pytest

from harmony_tpu import rlp
from harmony_tpu.core.trie import (
    EMPTY_ROOT,
    Trie,
    secure_trie_root,
    trie_root,
)
from harmony_tpu.ref.keccak import keccak256


def test_rlp_known_vectors():
    # yellow-paper / ethereum wiki canonical vectors
    assert rlp.encode(b"dog") == b"\x83dog"
    assert rlp.encode([b"cat", b"dog"]) == b"\xc8\x83cat\x83dog"
    assert rlp.encode(b"") == b"\x80"
    assert rlp.encode([]) == b"\xc0"
    assert rlp.encode(0) == b"\x80"
    assert rlp.encode(15) == b"\x0f"
    assert rlp.encode(1024) == b"\x82\x04\x00"
    # set theoretical representation of three
    assert rlp.encode([[], [[]], [[], [[]]]]) == bytes.fromhex(
        "c7c0c1c0c3c0c1c0"
    )
    lorem = b"Lorem ipsum dolor sit amet, consectetur adipisicing elit"
    assert rlp.encode(lorem) == b"\xb8\x38" + lorem


def test_rlp_roundtrip_and_strictness():
    for item in (b"", b"\x00", b"\x7f", b"\x80", b"x" * 55, b"y" * 56,
                 [b"a", [b"b", b"c"], b""], [b"z" * 100, [b"w"] * 20]):
        assert rlp.decode(rlp.encode(item)) == item
    with pytest.raises(rlp.RLPError):
        rlp.decode(b"\x81\x01")  # single byte <0x80 wrapped as string
    with pytest.raises(rlp.RLPError):
        rlp.decode(b"\xb8\x01x")  # long form for short length
    with pytest.raises(rlp.RLPError):
        rlp.decode(b"\x83do")  # truncated
    with pytest.raises(rlp.RLPError):
        rlp.decode(b"\x83dogX")  # trailing bytes


def test_trie_known_roots():
    # the canonical empty root
    assert EMPTY_ROOT == bytes.fromhex(
        "56e81f171bcc55a6ff8345e692c0f86e5b48e01b996cadc001622fb5e363b421"
    )
    # single entry trie vs hand-derived leaf encoding
    t = Trie()
    t.update(b"A", b"aaaa")
    # leaf: [HP([4,1], leaf), b"aaaa"] -> rlp -> keccak
    expect = keccak256(rlp.encode([b"\x20\x41", b"aaaa"]))
    assert t.root() == expect


def test_trie_go_ethereum_vector():
    """The classic go-ethereum TestInsert vector."""
    t = Trie()
    t.update(b"doe", b"reindeer")
    t.update(b"dog", b"puppy")
    t.update(b"dogglesworth", b"cat")
    assert t.root() == bytes.fromhex(
        "8aad789dff2f538bca5d8ea56e8abe10f4c7ba3a5dea95fea4cd6e7c3a1168d3"
    )
    t2 = Trie()
    t2.update(b"A", b"aaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaa")
    assert t2.root() == bytes.fromhex(
        "d23786fb4a010da3ce639d66d5e904a11dbc02746d1ce25029e53290cabf28ab"
    )


def test_trie_order_independence_and_delete():
    import random

    items = {
        bytes([i]) * (1 + i % 7): bytes([i ^ 0x5A]) * (1 + i % 11)
        for i in range(40)
    }
    base = trie_root(items)
    keys = list(items)
    random.Random(7).shuffle(keys)
    t = Trie()
    for k in keys:
        t.update(k, items[k])
    assert t.root() == base
    # deleting (empty value) = absent
    t.update(keys[0], b"")
    reduced = dict(items)
    del reduced[keys[0]]
    assert t.root() == trie_root(reduced)


def test_secure_trie_and_state_mpt_root():
    items = {b"\x01" * 20: b"acct1", b"\x02" * 20: b"acct2"}
    assert secure_trie_root(items) == trie_root(
        {keccak256(k): v for k, v in items.items()}
    )

    from harmony_tpu.core.state import StateDB

    s = StateDB()
    s.add_balance(b"\x0a" * 20, 1000)
    s.set_nonce(b"\x0a" * 20, 3)
    s.set_code(b"\x0b" * 20, b"\x60\x00")
    s.storage_set(b"\x0b" * 20, b"\x00" * 32, 42)
    r1 = s.mpt_root()
    assert len(r1) == 32 and r1 != EMPTY_ROOT
    # storage affects the root through the per-account trie
    s.storage_set(b"\x0b" * 20, b"\x00" * 32, 43)
    assert s.mpt_root() != r1
    # flat root and mpt root both see the same data
    s2 = s.copy()
    assert s2.mpt_root() == s.mpt_root()
    assert s2.root() == s.root()


def test_trie_proofs_inclusion_exclusion():
    """Trie.prove / verify_proof (reference: go-ethereum Trie.Prove +
    VerifyProof, the eth_getProof machinery)."""
    import os
    import random

    from harmony_tpu.core.trie import (
        EMPTY_ROOT, prove, trie_root, verify_proof,
    )

    rng = random.Random(7)
    items = {
        bytes([rng.randrange(256) for _ in range(32)]):
            bytes([rng.randrange(1, 256) for _ in range(rng.randrange(1, 40))])
        for _ in range(120)
    }
    root = trie_root(items)
    # every key proves its value
    for key in list(items)[:25]:
        proof = prove(items, key)
        assert verify_proof(root, key, proof) == items[key]
    # absent keys prove absence through the same machinery
    for _ in range(10):
        absent = bytes([rng.randrange(256) for _ in range(32)])
        if absent in items:
            continue
        proof = prove(items, absent)
        assert verify_proof(root, absent, proof) == b""
    # tampering any proof node breaks verification: the walk must
    # either raise (missing/renamed node) or prove absence — it must
    # NEVER return the original value
    key = next(iter(items))
    proof = prove(items, key)
    bad = [bytearray(n) for n in proof]
    bad[-1][0] ^= 0xFF
    try:
        got = verify_proof(root, key, [bytes(n) for n in bad])
    except ValueError:
        got = None
    assert got != items[key]
    # empty trie
    assert verify_proof(EMPTY_ROOT, b"\x01" * 32, []) == b""


def test_state_account_proof_verifies_against_mpt_root():
    """eth_getProof end to end at the state layer: account leaf +
    storage slots verify against mpt_root; absent accounts prove
    absent."""
    from harmony_tpu.core.state import StateDB
    from harmony_tpu.core.trie import verify_proof
    from harmony_tpu.ref.keccak import keccak256

    s = StateDB()
    a, b = b"\x0a" * 20, b"\x0b" * 20
    s.add_balance(a, 5_000)
    s.set_nonce(a, 9)
    s.add_balance(b, 1)
    slot = (7).to_bytes(32, "big")
    s.storage_set(b, slot, 424242)
    root = s.mpt_root()

    proot, leaf, acct_proof, _ = s.account_proof(a)
    assert proot == root
    assert leaf and verify_proof(root, keccak256(a), acct_proof) == leaf

    # storage proof checks against the account's own storage root
    from harmony_tpu import rlp

    _, leaf_b, proof_b, storage = s.account_proof(b, [slot])
    assert verify_proof(root, keccak256(b), proof_b) == leaf_b
    storage_root = rlp.decode(leaf_b)[2]
    sslot, sval, snodes = storage[0]
    assert sval == 424242
    assert verify_proof(
        storage_root, keccak256(slot), snodes
    ) == rlp.encode(rlp.int_to_bytes(424242))

    # an account this state never saw proves ABSENT against the root
    ghost = b"\xee" * 20
    _, leaf_g, proof_g, _ = s.account_proof(ghost)
    assert leaf_g == b""
    assert verify_proof(root, keccak256(ghost), proof_g) == b""
