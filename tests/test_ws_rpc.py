"""WebSocket JSON-RPC + eth_subscribe push (reference: rpc WS servers,
rpc/harmony/rpc.go startWS — VERDICT r2 missing #8's WS half)."""

import base64
import hashlib
import json
import socket
import time

from harmony_tpu.core.blockchain import Blockchain
from harmony_tpu.core.genesis import dev_genesis
from harmony_tpu.core.kv import MemKV
from harmony_tpu.hmy.facade import Harmony
from harmony_tpu.node.worker import Worker
from harmony_tpu.rpc.server import RPCServer
from harmony_tpu.rpc.ws import WSServer, read_frame, write_frame

CHAIN_ID = 2


def _ws_connect(port):
    sock = socket.create_connection(("127.0.0.1", port), timeout=10)
    key = base64.b64encode(b"0123456789abcdef").decode()
    sock.sendall(
        f"GET / HTTP/1.1\r\nHost: x\r\nUpgrade: websocket\r\n"
        f"Connection: Upgrade\r\nSec-WebSocket-Key: {key}\r\n"
        f"Sec-WebSocket-Version: 13\r\n\r\n".encode()
    )
    data = b""
    while b"\r\n\r\n" not in data:
        data += sock.recv(4096)
    assert b"101" in data.split(b"\r\n")[0]
    want = base64.b64encode(
        hashlib.sha1(
            key.encode() + b"258EAFA5-E914-47DA-95CA-C5AB0DC85B11"
        ).digest()
    )
    assert want in data
    return sock


def _rpc_ws(sock, method, params=None, req_id=1):
    write_frame(sock, json.dumps({
        "jsonrpc": "2.0", "id": req_id, "method": method,
        "params": params or [],
    }).encode())
    op, payload = read_frame(sock)
    return json.loads(payload)


def test_ws_dispatch_and_newheads_subscription():
    genesis, keys, _bls = dev_genesis()
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    hmy = Harmony(chain)
    rpc = RPCServer(hmy)
    ws = WSServer(rpc, poll_interval=0.05).start()
    try:
        sock = _ws_connect(ws.port)
        # plain request/response over WS shares the HTTP dispatch
        out = _rpc_ws(sock, "hmyv2_blockNumber")
        assert out["result"] == 0
        # subscribe to newHeads, then grow the chain
        out = _rpc_ws(sock, "eth_subscribe", ["newHeads"], req_id=2)
        sub_id = out["result"]
        worker = Worker(chain, None)
        block = worker.propose_block(view_id=1)
        chain.insert_chain([block], verify_seals=False)
        # the pusher must deliver a notification for block 1
        sock.settimeout(5)
        op, payload = read_frame(sock)
        note = json.loads(payload)
        assert note["method"] == "eth_subscription"
        assert note["params"]["subscription"] == sub_id
        assert note["params"]["result"]["number"] == "0x1"
        # unsubscribe stops the stream
        out = _rpc_ws(sock, "eth_unsubscribe", [sub_id], req_id=3)
        assert out["result"] is True
        sock.close()
    finally:
        ws.stop()


def test_ws_ping_pong_and_close():
    genesis, keys, _bls = dev_genesis()
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    ws = WSServer(RPCServer(Harmony(chain))).start()
    try:
        sock = _ws_connect(ws.port)
        write_frame(sock, b"hello", 0x9)  # ping
        op, payload = read_frame(sock)
        assert (op, payload) == (0xA, b"hello")
        write_frame(sock, b"", 0x8)  # close
        op, _ = read_frame(sock)
        assert op == 0x8
        sock.close()
    finally:
        ws.stop()


def test_ws_new_pending_transactions_subscription():
    """eth_subscribe("newPendingTransactions") pushes hashes of txs
    that arrive in the pool AFTER the subscription (geth semantics)."""
    from harmony_tpu.core import rawdb
    from harmony_tpu.core.tx_pool import TxPool
    from harmony_tpu.core.types import Transaction

    genesis, keys, _bls = dev_genesis()
    chain = Blockchain(MemKV(), genesis, blocks_per_epoch=16)
    pool = TxPool(CHAIN_ID, 0, chain.state)
    # pre-existing tx: must NOT be pushed
    pre = Transaction(
        nonce=0, gas_price=1, gas_limit=25_000, shard_id=0, to_shard=0,
        to=b"\x0e" * 20, value=1,
    ).sign(keys[0], CHAIN_ID)
    pool.add(pre)
    hmy = Harmony(chain, pool)
    rpc = RPCServer(hmy)
    ws = WSServer(rpc, poll_interval=0.05).start()
    try:
        sock = _ws_connect(ws.port)
        out = _rpc_ws(sock, "eth_subscribe", ["newPendingTransactions"])
        sub_id = out["result"]
        tx = Transaction(
            nonce=1, gas_price=1, gas_limit=25_000, shard_id=0,
            to_shard=0, to=b"\x0e" * 20, value=2,
        ).sign(keys[0], CHAIN_ID)
        hmy.send_raw_transaction(rawdb.encode_tx(tx, CHAIN_ID))
        sock.settimeout(5)
        op, payload = read_frame(sock)
        note = json.loads(payload)
        assert note["params"]["subscription"] == sub_id
        assert note["params"]["result"] == (
            "0x" + tx.hash(CHAIN_ID).hex()
        )
        sock.close()
    finally:
        ws.stop()
