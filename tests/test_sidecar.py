"""Sidecar tests: wire protocol, live server with Python client, and the
native C++ client library driven through ctypes."""

import ctypes
import os
import pathlib
import subprocess

import pytest

from harmony_tpu.consensus.mask import Mask
from harmony_tpu.ref import bls as RB
from harmony_tpu.sidecar import protocol as P
from harmony_tpu.sidecar.client import SidecarClient
from harmony_tpu.sidecar.server import SidecarServer

MSG = b"0123456789abcdef0123456789abcdef"


# --- protocol unit tests ---------------------------------------------------


def test_frame_roundtrip():
    f = P.pack_frame(P.MSG_PING, 7, b"abc")
    msg_type, req_id, body = P.unpack_frame(f[4:])
    assert (msg_type, req_id, body) == (P.MSG_PING, 7, b"abc")


def test_body_roundtrips():
    keys = [bytes([i]) * 48 for i in range(3)]
    assert P.parse_set_committee(P.build_set_committee(5, 2, keys)) == (
        5,
        2,
        keys,
    )
    body = P.build_agg_verify(1, 0, b"payload", b"\x07", bytes(96))
    assert P.parse_agg_verify(body) == (1, 0, b"payload", b"\x07", bytes(96))
    items = [(bytes(48), b"m1", bytes(96)), (bytes(48), b"m2", bytes(96))]
    assert P.parse_verify_batch(P.build_verify_batch(items)) == items


def test_frame_size_limit():
    with pytest.raises(ValueError):
        P.pack_frame(P.MSG_PING, 1, bytes(P.MAX_FRAME))


# --- live server -----------------------------------------------------------


@pytest.fixture(scope="module")
def committee():
    sks = [RB.keygen(bytes([i])) for i in range(4)]
    pks = [RB.pubkey(sk) for sk in sks]
    sigs = [RB.sign(sk, MSG) for sk in sks]
    return sks, pks, sigs


@pytest.fixture(scope="module")
def server():
    s = SidecarServer().start()
    yield s
    s.stop()


def test_ping_and_committee_upload(server, committee):
    _, pks, _ = committee
    c = SidecarClient(server.address)
    assert c.ping() == P.VERSION
    c.set_committee(3, 0, [RB.pubkey_to_bytes(p) for p in pks])
    c.close()


def test_agg_verify_over_socket(server, committee):
    _, pks, sigs = committee
    c = SidecarClient(server.address)
    c.set_committee(4, 1, [RB.pubkey_to_bytes(p) for p in pks])
    # 3-of-4 aggregate, bits 0, 2, 3
    agg = RB.aggregate_sigs([sigs[0], sigs[2], sigs[3]])
    mask = Mask(pks)
    for i in (0, 2, 3):
        mask.set_bit(i, True)
    ok = c.agg_verify(4, 1, MSG, mask.mask_bytes(), RB.sig_to_bytes(agg))
    assert ok
    # wrong bitmap (all four) must fail
    mask.set_bit(1, True)
    assert not c.agg_verify(4, 1, MSG, mask.mask_bytes(), RB.sig_to_bytes(agg))
    # unknown committee raises
    with pytest.raises(KeyError):
        c.agg_verify(99, 9, MSG, mask.mask_bytes(), RB.sig_to_bytes(agg))
    c.close()


def test_verify_batch_over_socket(server, committee):
    _, pks, sigs = committee
    c = SidecarClient(server.address)
    items = [
        (RB.pubkey_to_bytes(pks[i]), MSG, RB.sig_to_bytes(sigs[i]))
        for i in range(3)
    ]
    # corrupt the last one: wrong signer
    items.append(
        (RB.pubkey_to_bytes(pks[3]), MSG, RB.sig_to_bytes(sigs[0]))
    )
    assert c.verify_batch(items) == [True, True, True, False]
    c.close()


# --- native C++ client -----------------------------------------------------


@pytest.fixture(scope="module")
def native_lib():
    root = pathlib.Path(__file__).parent.parent
    so = root / "native" / "libharmony_sidecar.so"
    if not so.exists():
        subprocess.run(
            ["make", "-C", str(root / "native")], check=True,
            capture_output=True,
        )
    lib = ctypes.CDLL(str(so))
    lib.harmony_sidecar_connect_tcp.restype = ctypes.c_void_p
    lib.harmony_sidecar_connect_tcp.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.harmony_sidecar_close.argtypes = [ctypes.c_void_p]
    lib.harmony_sidecar_ping.argtypes = [ctypes.c_void_p]
    lib.harmony_sidecar_set_committee.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint32,
        ctypes.c_char_p, ctypes.c_uint32,
    ]
    lib.harmony_sidecar_agg_verify.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint32,
        ctypes.c_char_p, ctypes.c_uint16,
        ctypes.c_char_p, ctypes.c_uint16,
        ctypes.c_char_p,
    ]
    return lib


def test_native_client_end_to_end(server, committee, native_lib):
    _, pks, sigs = committee
    host, port = server.address
    h = native_lib.harmony_sidecar_connect_tcp(host.encode(), port)
    assert h, "native connect failed"
    try:
        assert native_lib.harmony_sidecar_ping(h) == P.VERSION
        keys = b"".join(RB.pubkey_to_bytes(p) for p in pks)
        assert (
            native_lib.harmony_sidecar_set_committee(h, 7, 0, keys, 4) == 0
        )
        agg = RB.aggregate_sigs(sigs)
        mask = Mask(pks)
        for i in range(4):
            mask.set_bit(i, True)
        bm = mask.mask_bytes()
        ok = native_lib.harmony_sidecar_agg_verify(
            h, 7, 0, MSG, len(MSG), bm, len(bm), RB.sig_to_bytes(agg)
        )
        assert ok == 1
        # flipped bit -> invalid
        bad = bytes([bm[0] ^ 0x02])
        ok = native_lib.harmony_sidecar_agg_verify(
            h, 7, 0, MSG, len(MSG), bad, len(bad), RB.sig_to_bytes(agg)
        )
        assert ok == 0
    finally:
        native_lib.harmony_sidecar_close(h)


# --- engine-through-sidecar (the wired backend, VERDICT r2 #7) -------------


def test_engine_routes_checks_through_sidecar(server):
    """Engine(backend=SidecarClient) must push the committee once and
    verify header seals entirely through the sidecar service."""
    from harmony_tpu import bls as B
    from harmony_tpu.chain.engine import Engine, EpochContext
    from harmony_tpu.chain.header import Header
    from harmony_tpu.consensus.signature import construct_commit_payload

    keys = [B.PrivateKey.generate(bytes([90 + i])) for i in range(4)]
    serialized = [k.pub.bytes for k in keys]
    client = SidecarClient(server.address)
    eng = Engine(lambda s, e: EpochContext(serialized), device=False,
                 backend=client)
    h = Header(shard_id=0, block_num=10, epoch=2, view_id=10)
    payload = construct_commit_payload(
        h.hash(), h.block_num, h.view_id, True
    )
    sigs = [keys[i].sign_hash(payload) for i in (0, 1, 2)]
    agg = B.aggregate_sigs(sigs)
    mask = Mask([k.pub.point for k in keys])
    for i in (0, 1, 2):
        mask.set_bit(i, True)
    assert eng.verify_header_signature(h, agg.bytes, mask.mask_bytes())
    assert eng._backend_committees == {(0, 2)}
    # cached second call: no wire round-trip needed (still True)
    assert eng.verify_header_signature(h, agg.bytes, mask.mask_bytes())
    # wrong bitmap (claims all 4 signed) fails THROUGH the sidecar
    mask.set_bit(3, True)
    assert not eng.verify_header_signature(
        h, agg.bytes, mask.mask_bytes()
    )
    client.close()
