"""Host-side consensus-layer tests: Dec, mask, payloads, votepower, quorum."""

import pytest

from harmony_tpu.consensus import quorum as Q
from harmony_tpu.consensus import signature as SIG
from harmony_tpu.consensus import votepower as VP
from harmony_tpu.consensus.mask import Mask
from harmony_tpu.numeric import Dec, new_dec, one_dec, zero_dec
from harmony_tpu.ref import bls as RB
from harmony_tpu.ref import curve as RC


# --- Dec -------------------------------------------------------------------


def test_dec_basics():
    a = Dec.from_str("1.5")
    b = Dec.from_str("2.5")
    assert a.add(b).equal(new_dec(4))
    assert b.sub(a).equal(one_dec())
    assert a.mul(b).equal(Dec.from_str("3.75"))
    assert new_dec(1).quo(new_dec(3)).raw == 333333333333333333
    assert new_dec(2).quo(new_dec(3)).raw == 666666666666666667


def test_dec_bankers_rounding():
    # 0.5 ulp cases round to even
    x = Dec(5)  # 5e-18
    tenth = Dec.from_str("0.1")
    # 5e-18 * 0.1 = 5e-19 -> half of an ulp -> rounds to 0 (even)
    assert x.mul(tenth).raw == 0
    y = Dec(15)
    # 1.5e-18 ulp product -> rounds to 2 (even)
    assert y.mul(tenth).raw == 2


def test_dec_negative_and_truncate():
    a = Dec.from_str("-1.7")
    assert a.truncate_int() == -1
    assert a.round_int() == -2
    assert a.neg().equal(Dec.from_str("1.7"))
    assert Dec.from_str("5.0").quo_truncate(new_dec(3)).raw == 1666666666666666666


# --- payloads --------------------------------------------------------------


def test_commit_payload_layout():
    h = bytes(range(32))
    p = SIG.construct_commit_payload(h, 0x1122334455667788, 0x99, True)
    assert p[:8] == bytes.fromhex("8877665544332211")  # LE block number
    assert p[8:40] == h
    assert p[40:48] == (0x99).to_bytes(8, "little")
    assert len(p) == 48
    p2 = SIG.construct_commit_payload(h, 1, 2, False)
    assert len(p2) == 40  # pre-staking: no view id
    with pytest.raises(ValueError):
        SIG.construct_commit_payload(b"short", 1, 2, True)


# --- mask ------------------------------------------------------------------


@pytest.fixture(scope="module")
def committee():
    sks = [RB.keygen(bytes([i])) for i in range(10)]
    return [RB.pubkey(sk) for sk in sks]


def test_mask_bit_semantics(committee):
    m = Mask(committee)
    assert m.bytes_len() == 2  # 10 keys -> 2 bytes
    m.set_bit(0, True)
    m.set_bit(7, True)
    m.set_bit(8, True)
    # little-endian: bit i -> byte i>>3, bit (i & 7)
    assert m.mask_bytes() == bytes([0b10000001, 0b00000001])
    assert m.count_enabled() == 3
    m.set_bit(7, False)
    assert m.mask_bytes() == bytes([0b00000001, 0b00000001])
    with pytest.raises(IndexError):
        m.set_bit(10, True)


def test_mask_set_mask_length_check(committee):
    m = Mask(committee)
    with pytest.raises(ValueError):
        m.set_mask(b"\x01")  # wrong length
    m.set_mask(bytes([0xFF, 0x03]))
    assert m.count_enabled() == 10


def test_mask_set_key_and_signers(committee):
    m = Mask(committee)
    m.set_key(RB.pubkey_to_bytes(committee[3]), True)
    assert m.index_enabled() == [3]
    assert m.get_signed_pubkeys() == [committee[3]]


def test_mask_aggregate_host_matches_reference(committee):
    m = Mask(committee)
    for i in (0, 2, 5, 9):
        m.set_bit(i, True)
    expect = None
    for i in (0, 2, 5, 9):
        expect = RC.g1.add(expect, committee[i])
    assert m.aggregate_public(device=False) == expect


# --- votepower -------------------------------------------------------------


def _slots():
    # 2 harmony slots + 3 stakers with stakes 100, 200, 700
    slots = [
        VP.Slot("hmy1", b"k0", None),
        VP.Slot("hmy2", b"k1", None),
        VP.Slot("s1", b"k2", new_dec(100)),
        VP.Slot("s2", b"k3", new_dec(200)),
        VP.Slot("s3", b"k4", new_dec(700)),
    ]
    return slots


def test_roster_sums_to_one():
    r = VP.compute_roster(
        _slots(), Dec.from_str("0.49"), Dec.from_str("0.51")
    )
    total = r.our_voting_power.add(r.their_voting_power)
    assert total.equal(one_dec())
    assert r.harmony_slot_count == 2
    # harmony nodes split 0.49 equally
    assert r.voters[b"k0"].overall_percent.equal(Dec.from_str("0.245"))
    # staker with 70% of stake gets 0.7 * 0.51 plus the rounding residue
    v = r.voters[b"k4"]
    assert v.overall_percent.sub(Dec.from_str("0.357")).raw in (0, 1, -1)


def test_roster_all_harmony_sums_to_one():
    # no external stakers: the residue lands on the last Harmony voter
    # and the invariant must still hold exactly
    slots = [VP.Slot(f"h{i}", bytes([i]), None) for i in range(3)]
    r = VP.compute_roster(slots, one_dec(), zero_dec())
    assert r.our_voting_power.add(r.their_voting_power).equal(one_dec())
    # the last slot absorbed the 1e-18 residue
    assert r.voters[bytes([2])].overall_percent.gt(
        r.voters[bytes([0])].overall_percent
    )


def test_roster_residue_to_last_staker():
    # 3 stakers with equal stake: 1/3 each cannot sum exactly; the residue
    # lands on the last one
    slots = [
        VP.Slot("a", b"a", new_dec(1)),
        VP.Slot("b", b"b", new_dec(1)),
        VP.Slot("c", b"c", new_dec(1)),
    ]
    r = VP.compute_roster(slots, zero_dec(), one_dec())
    assert r.our_voting_power.add(r.their_voting_power).equal(one_dec())
    assert r.voters[b"c"].overall_percent.gt(r.voters[b"a"].overall_percent)


# --- quorum ----------------------------------------------------------------


def test_uniform_quorum():
    keys = [bytes([i]) for i in range(10)]
    d = Q.Decider(Q.Policy.UNIFORM, keys)
    # threshold = 2*10//3 + 1 = 7
    for i in range(6):
        d.submit_vote(
            Q.Phase.PREPARE, Q.Ballot(keys[i], b"h", b"s", 1, 0)
        )
    assert not d.is_quorum_achieved(Q.Phase.PREPARE)
    d.submit_vote(Q.Phase.PREPARE, Q.Ballot(keys[6], b"h", b"s", 1, 0))
    assert d.is_quorum_achieved(Q.Phase.PREPARE)
    # duplicate ballots are rejected
    assert not d.submit_vote(
        Q.Phase.PREPARE, Q.Ballot(keys[6], b"h", b"s", 1, 0)
    )
    assert d.count(Q.Phase.PREPARE) == 7
    # mask-based check agrees with the ballot path at exact quorum
    assert not d.is_quorum_achieved_by_mask([1] * 6 + [0] * 4)
    assert d.is_quorum_achieved_by_mask([1] * 7 + [0] * 3)


def test_staked_quorum():
    slots = [
        VP.Slot("h", b"k0", None),
        VP.Slot("a", b"k1", new_dec(400)),
        VP.Slot("b", b"k2", new_dec(600)),
    ]
    roster = VP.compute_roster(
        slots, Dec.from_str("0.30"), Dec.from_str("0.70")
    )
    keys = [b"k0", b"k1", b"k2"]
    d = Q.Decider(Q.Policy.STAKED, keys, roster)
    # k2 alone: 0.6*0.7 = 0.42 < 2/3
    d.submit_vote(Q.Phase.COMMIT, Q.Ballot(b"k2", b"h", b"s", 1, 0))
    assert not d.is_quorum_achieved(Q.Phase.COMMIT)
    # + harmony 0.30 => 0.72 > 2/3
    d.submit_vote(Q.Phase.COMMIT, Q.Ballot(b"k0", b"h", b"s", 1, 0))
    assert d.is_quorum_achieved(Q.Phase.COMMIT)
    assert d.is_quorum_achieved_by_mask([1, 0, 1])
    assert d.is_quorum_achieved_by_mask([0, 1, 1])  # 0.28 + 0.42 = 0.70
    assert not d.is_quorum_achieved_by_mask([1, 1, 0])  # 0.30 + 0.28 = 0.58


def test_staked_quorum_exact_boundary():
    # power exactly 2/3 must NOT reach quorum (strictly greater)
    slots = [
        VP.Slot("a", b"a", new_dec(2)),
        VP.Slot("b", b"b", new_dec(1)),
    ]
    roster = VP.compute_roster(slots, zero_dec(), one_dec())
    d = Q.Decider(Q.Policy.STAKED, [b"a", b"b"], roster)
    d.submit_vote(Q.Phase.COMMIT, Q.Ballot(b"a", b"h", b"s", 1, 0))
    # a's power: 2/3 rounded = 0.666666666666666667 > 2/3's Dec value
    # (0.666666666666666667) -> equal, not greater
    assert not d.is_quorum_achieved(Q.Phase.COMMIT)
