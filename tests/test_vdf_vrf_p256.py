"""Wesolowski class-group VDF + P-256 ECVRF (VERDICT r2 missing #9).

References: harmony-one/vdf consumed at consensus/consensus_v2.go:955-
1034 (Wesolowski over class groups); crypto/vrf/p256/p256.go (CONIKS
VRF)."""

import pytest

from harmony_tpu import crypto_vrf_p256 as V
from harmony_tpu.vdf_wesolowski import (
    Form,
    WesolowskiVDF,
    create_discriminant,
    generator,
    identity,
    is_probable_prime,
)


def test_discriminant_is_negative_prime_7_mod_8():
    D = create_discriminant(b"seed", 256)
    assert D < 0 and abs(D).bit_length() == 256
    assert (-D) % 8 == 7 and D % 8 == 1
    assert is_probable_prime(-D)
    # deterministic in the seed
    assert D == create_discriminant(b"seed", 256)
    assert D != create_discriminant(b"seed2", 256)


def test_class_group_laws():
    D = create_discriminant(b"group", 256)
    g = generator(D)
    e = identity(D)
    assert g.discriminant == D
    assert g.compose(e) == g.reduced()
    g2, g3 = g.square(), g.square().compose(g)
    assert g.compose(g2) == g3                      # associativity shape
    assert g2.compose(g3) == g.pow(5)               # pow consistency
    assert g3.compose(g2) == g.pow(5)               # commutativity
    assert g.pow(5).discriminant == D               # closed
    assert g.pow(0) == e._normalized()


def test_form_serialization_roundtrip_and_rejection():
    D = create_discriminant(b"ser", 256)
    f = generator(D).pow(77)
    back = Form.deserialize(f.serialize(), D)
    assert back == f
    with pytest.raises(ValueError):
        # (a, b) pair off the discriminant lattice
        Form.deserialize(Form(3, 1, 1).serialize(), D)


def test_wesolowski_evaluate_verify_reject():
    # difficulty > challenge bit-length so pi is a non-trivial group
    # element (2^T / l > 1); tampering it must then break the check
    v = WesolowskiVDF(difficulty=160, discriminant_bits=256)
    out, proof = v.evaluate(b"epoch-randomness-seed")
    assert v.verify(b"epoch-randomness-seed", out, proof)
    # wrong seed, tampered output, tampered proof: all rejected
    assert not v.verify(b"wrong-seed", out, proof)
    bad = bytearray(out)
    bad[5] ^= 1
    assert not v.verify(b"epoch-randomness-seed", bytes(bad), proof)
    from harmony_tpu.vdf_wesolowski import WesolowskiProof, identity

    assert proof.pi != identity(proof.pi.discriminant)._normalized()
    fake = WesolowskiProof(proof.y, proof.pi.square())
    assert not v.verify(b"epoch-randomness-seed", out, fake)


def test_wesolowski_output_is_deterministic():
    v = WesolowskiVDF(difficulty=16, discriminant_bits=256)
    out1, _ = v.evaluate(b"x")
    out2, _ = v.evaluate(b"x")
    assert out1 == out2


# -- P-256 ECVRF -------------------------------------------------------------


def test_p256_vrf_roundtrip_and_determinism():
    sk = V.keygen(b"vrf-seed")
    pk = V.pubkey(sk)
    idx, proof = V.evaluate(sk, b"epoch-7-entropy", r=999)
    assert V.proof_to_hash(pk, b"epoch-7-entropy", proof) == idx
    idx2, proof2 = V.evaluate(sk, b"epoch-7-entropy", r=999)
    assert (idx2, proof2) == (idx, proof)
    # random-nonce proofs also verify (and give the same index: the
    # VRF point depends only on sk and m)
    idx3, proof3 = V.evaluate(sk, b"epoch-7-entropy")
    assert idx3 == idx
    assert V.proof_to_hash(pk, b"epoch-7-entropy", proof3) == idx


def test_p256_vrf_rejects_forgery():
    sk = V.keygen(b"a")
    pk = V.pubkey(sk)
    _, proof = V.evaluate(sk, b"msg")
    with pytest.raises(ValueError):
        V.proof_to_hash(pk, b"other-msg", proof)
    other_pk = V.pubkey(V.keygen(b"b"))
    with pytest.raises(ValueError):
        V.proof_to_hash(other_pk, b"msg", proof)
    bad = bytearray(proof)
    bad[70] ^= 1
    with pytest.raises(ValueError):
        V.proof_to_hash(pk, b"msg", bytes(bad))


def test_p256_pubkey_serialization():
    pk = V.pubkey(V.keygen(b"s"))
    assert V.deserialize_pubkey(V.serialize_pubkey(pk)) == pk
    with pytest.raises(ValueError):
        V.deserialize_pubkey(b"\x01" * 64)
