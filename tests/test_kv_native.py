"""Native C++ KV store: interop with the Python FileKV twin (same
on-disk format), tombstones, torn-tail recovery, compaction, and the
SHARED torn-batch/corruption replay suite (tests/kv_corruption.py —
the full parametrized matrix runs in tests/test_kv_corruption.py)."""

import os

import pytest

import kv_corruption as KC
from harmony_tpu.core.kv import FileKV, WriteBatch
from harmony_tpu.core.kv_native import NativeKV, available

pytestmark = pytest.mark.skipif(
    not available(), reason="native toolchain unavailable"
)


def test_native_basic_and_python_interop(tmp_path):
    path = str(tmp_path / "kv.db")
    db = NativeKV(path)
    db.put(b"a", b"1")
    db.put(b"b", b"22")
    db.put(b"a", b"333")  # overwrite
    db.delete(b"b")
    assert db.get(b"a") == b"333"
    assert db.get(b"b") is None
    assert db.has(b"a") and not db.has(b"b")
    assert len(db) == 1
    db.flush()
    db.close()

    # the Python twin opens the same file
    py = FileKV(path)
    assert py.get(b"a") == b"333" and not py.has(b"b")
    py.put(b"c", b"4444")
    py.flush()
    py.close()

    # and the native store reads Python's appends
    db = NativeKV(path)
    assert db.get(b"c") == b"4444" and db.get(b"a") == b"333"
    before = os.path.getsize(path)
    db.compact()
    assert os.path.getsize(path) < before
    assert db.get(b"a") == b"333" and db.get(b"c") == b"4444"
    db.close()


def test_native_torn_tail_recovery(tmp_path):
    path = str(tmp_path / "torn.db")
    db = NativeKV(path)
    db.put(b"k", b"v")
    db.flush()
    db.close()
    with open(path, "ab") as f:
        f.write(b"\x09\x00\x00\x00\x05")  # header fragment
    db = NativeKV(path)  # replay truncates the tear
    assert db.get(b"k") == b"v"
    db.put(b"k2", b"v2")
    assert db.get(b"k2") == b"v2"
    db.close()
    py = FileKV(path)
    assert py.get(b"k2") == b"v2"
    py.close()


def test_native_backs_a_blockchain(tmp_path):
    from harmony_tpu.core.blockchain import Blockchain
    from harmony_tpu.core.genesis import dev_genesis
    from harmony_tpu.node.worker import Worker

    genesis, keys, _ = dev_genesis()
    path = str(tmp_path / "chain.db")
    chain = Blockchain(NativeKV(path), genesis, blocks_per_epoch=16)
    block = Worker(chain, None).propose_block(view_id=1)
    assert chain.insert_chain([block], verify_seals=False) == 1
    chain.db.flush()
    chain.db.close()
    chain2 = Blockchain(NativeKV(path), genesis, blocks_per_epoch=16)
    assert chain2.head_number == 1
    assert chain2.current_header().hash() == block.hash()
    chain2.db.close()


def test_native_torn_value_recovery(tmp_path):
    """A record whose VALUE was cut by a crash must be dropped on
    replay (not read back as zeros) — and a corrupt huge klen must
    yield a clean open, not a process abort."""
    path = str(tmp_path / "tornval.db")
    db = NativeKV(path)
    db.put(b"good", b"value")
    db.flush()
    db.close()
    # append header+key claiming a 100-byte value, but write only 3
    with open(path, "ab") as f:
        f.write(b"\x04\x00\x00\x00" + b"\x64\x00\x00\x00" + b"torn" + b"abc")
    db = NativeKV(path)
    assert db.get(b"good") == b"value"
    assert db.get(b"torn") is None  # dropped, not zero-filled
    db.put(b"after", b"tear")
    db.flush()
    db.close()
    py = FileKV(path)
    assert py.get(b"after") == b"tear" and py.get(b"torn") is None
    py.close()

    # corrupt klen = 0xFFFFFFFE: open must succeed (truncating) or at
    # worst return a handle error — never abort the process.  (That
    # klen is now the batch BEGIN sentinel: an orphaned marker with no
    # COMMIT is exactly a torn batch and must be discarded.)
    path2 = str(tmp_path / "badklen.db")
    with open(path2, "wb") as f:
        f.write(b"\xfe\xff\xff\xff" + b"\x01\x00\x00\x00" + b"xx")
    db = NativeKV(path2)
    assert db.get(b"xx") is None
    db.close()


def test_native_batch_parity_with_filekv(tmp_path):
    """kv_write_batch: all-or-nothing on disk, marker grammar readable
    by the Python twin, torn native batches discarded by BOTH."""
    path = str(tmp_path / "batch.db")
    db = NativeKV(path)
    db.put(b"pre", b"existing")
    batch = WriteBatch()
    batch.put(b"b1", b"v1")
    batch.put(b"pre", b"overwritten")
    batch.delete(b"b1")
    db.write_batch(batch)
    assert db.get(b"b1") is None
    assert db.get(b"pre") == b"overwritten"
    db.flush()
    db.close()
    py = FileKV(path)
    assert py.get(b"b1") is None and py.get(b"pre") == b"overwritten"
    py.close()

    # a torn batch appended behind the native store's back: both
    # stores must discard it and keep the committed prefix
    with open(path, "ab") as f:
        f.write(KC.marker(0xFFFFFFFE, 2) + KC.rec(b"lost", b"L"))
    for factory in (NativeKV, FileKV):
        db = factory(path)
        assert db.get(b"lost") is None
        assert db.get(b"pre") == b"overwritten"
        db.close()


def test_native_runs_shared_corruption_cases(tmp_path):
    """The native store must reach the same verdict as FileKV on every
    shared corruption fixture (the parametrized matrix also runs in
    test_kv_corruption.py; this pins the suite to the native tier)."""
    for name, tail, expect in KC.CASES:
        KC.run_case(NativeKV, str(tmp_path / f"{name}.db"), tail, expect)
