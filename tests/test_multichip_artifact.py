"""CI diff of the fused multichip lowering artifact (VERDICT r3 #7).

Regenerates the StableHLO summary of ``sharded_agg_verify`` lowered for
an 8-virtual-device mesh and diffs it against the checked-in artifact —
a sharding or shape regression in parallel/mesh.py (or anywhere in the
ops tier the program includes) fails here WITHOUT executing the
program, which no box below a real 8-chip mesh can afford.  Lowering is
tracing + StableHLO emission only (no LLVM): ~2-3 min on the 1-core
box.  Set MULTICHIP_ARTIFACT=0 to skip locally.
"""

import os
import pathlib
import subprocess
import sys

import pytest

ROOT = pathlib.Path(__file__).parent.parent

if os.environ.get("MULTICHIP_ARTIFACT") == "0":
    pytest.skip("MULTICHIP_ARTIFACT=0", allow_module_level=True)


def test_fused_lowering_matches_checked_in_artifact():
    env = dict(os.environ)
    # a clean child: the conftest's CPU pinning must not leak, and the
    # script pins the platform itself
    env.pop("PYTEST_CURRENT_TEST", None)
    proc = subprocess.run(
        [sys.executable, "tools/lower_multichip.py", "--check"],
        cwd=ROOT,
        capture_output=True,
        text=True,
        timeout=1200,
    )
    assert proc.returncode == 0, (
        f"multichip lowering artifact drifted:\n{proc.stdout[-3000:]}"
        f"\n{proc.stderr[-500:]}"
    )
