"""GL11 fixture: twin/padding discipline for device-dispatched kernels.

This file declares ITSELF as its twin module (single-file mode: the
twin of ``verify_x`` is ``verify_x_twin``), skips the parity-test scan
(``tests=skip`` — the repo-level scan is exercised against the real
tree in tests/test_graftlint.py), and lists the dispatched kernels
explicitly.  The tagged lines are a kernel with no twin and a kernel
whose dataflow never reaches an infinity-sentinel guard.
"""
# graftlint: kernel-module dtype=int32; twin=tests/fixtures/graftlint/gl11_cases.py; tests=skip; dispatch=verify_ok, verify_no_twin, verify_no_guard

import jax.numpy as jnp


# graftlint: kernel padding-safe
def _finite_mask(pk):
    """Reviewed infinity-sentinel check: (0, 0) lanes are padding."""
    return ~jnp.all(pk == 0, axis=(-1, -2))


def verify_ok(pk, sig):
    """Twin present, guard reached: must stay quiet."""
    return jnp.where(_finite_mask(pk), sig[..., 0, 0],
                 jnp.zeros_like(sig[..., 0, 0]))


def verify_ok_twin(pk, sig):
    return [bool(p.any()) for p in pk]


def verify_no_twin(pk, sig):  # expect: GL11
    return jnp.where(_finite_mask(pk), sig[..., 0, 0],
                     jnp.zeros_like(sig[..., 0, 0]))


def verify_no_guard(pk, sig):  # expect: GL11
    return sig[..., 0, 0]


def verify_no_guard_twin(pk, sig):
    return [bool(p.any()) for p in pk]
