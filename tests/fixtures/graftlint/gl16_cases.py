"""GL16 fixtures: warmup-manifest coverage of derivable buckets.

Never imported or executed; tests/test_graftlint.py lints this file and
asserts that exactly the lines tagged ``# expect: GLxx`` are flagged.

Every site here IS derivable (annotated bucket-fn, pinned registry) —
the failure mode is narrower than GL15's: the derived program names
are not all present in the committed compile manifest
(tools/artifacts/aot/compile_manifest.json), so a warmed node would
still pay a first-use compile the first time the bucket is hit.  The
clean cases derive names the real manifest covers; coverage is checked
against that committed artifact, the same diff CI gates.
"""

from harmony_tpu import aot

BUCKETS = (8, 16)


# graftlint: bucket-fn registry=BUCKETS
def bucket(n):
    for b in BUCKETS:
        if n <= b:
            return b
    raise ValueError(n)


def _program_first_use(program):
    return False


def serve_covered(items):
    """agg_verify_b{8,16}: both names in the committed manifest."""
    width = bucket(len(items))
    program = f"agg_verify_b{width}"
    return aot.resolve(program)


def serve_uncovered_family(items):
    """A family the manifest has never heard of: every derived name
    is missing, the warmup can never precompile it."""
    width = bucket(len(items))
    program = f"quorum_probe_b{width}"  # expect: GL16
    return aot.resolve(program)


def serve_partially_covered(items):
    """verify_w8 is in the manifest but verify_w16 is not — partial
    coverage still leaves a first-use compile reachable."""
    width = bucket(len(items))
    program = f"verify_w{width}"  # expect: GL16
    return aot.resolve(program)


def first_use_gate(items):
    """Same coverage contract through the first-use counter sink."""
    program = f"replay_sweep_b{bucket(len(items))}"  # expect: GL16
    if _program_first_use(program):
        return None
    return program
