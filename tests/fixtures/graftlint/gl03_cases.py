"""GL03 fixtures: lock discipline — positive, suppressed, clean.

Never imported or executed; tests/test_graftlint.py lints this file and
asserts that exactly the lines tagged ``# expect: GLxx`` are flagged.
"""

import threading

COUNTS = {"hits": 0}
_CACHE: dict = {}
_LOCK = threading.Lock()
_G = 0


class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._items: list = []
        self.total = 0
        self.closed = False  # never written under the lock: unguarded

    def put(self, item):
        with self._lock:
            self._items.append(item)
            self.total += 1

    def racy_put(self, item):
        self._items.append(item)  # expect: GL03
        self.total += 1  # expect: GL03

    def racy_index(self, k, v):
        self._items[k] = v  # expect: GL03

    def reviewed_put(self, item):
        self._items.append(item)  # graftlint: disable=GL03

    def close(self):
        self.closed = True  # not lock-guarded anywhere: clean

    def start(self):
        threading.Thread(
            target=self._worker, daemon=True,  # graftlint: thread-role=transient
        ).start()

    def _worker(self):
        while not self.closed:
            if self._items:  # expect: GL03
                with self._lock:
                    self._items.pop()


def bump_locked():
    global _G
    with _LOCK:
        _G += 1


def bump_racy():
    global _G
    _G += 1  # expect: GL03


def count_hit():
    COUNTS["hits"] += 1  # expect: GL03


def cache_put(k, v):
    _CACHE[k] = v  # expect: GL03


def cache_evict(k):
    _CACHE.pop(k, None)  # expect: GL03


def cache_put_locked(k, v):
    with _LOCK:
        _CACHE[k] = v


def local_shadow(k, v):
    _CACHE = {}
    _CACHE[k] = v  # shadows the module container: clean
    return _CACHE


def outer_with_nested_global():
    def inner():
        global _G
        _G = 2  # expect: GL03

    _G = 3  # a LOCAL of outer (no global decl here): clean
    inner()
    return _G
