"""GL15 fixtures: bucket derivability at compile-program sites.

Never imported or executed; tests/test_graftlint.py lints this file and
asserts that exactly the lines tagged ``# expect: GLxx`` are flagged.

The positive cases re-create the PR-15 NEWVIEW wedge statically: a
program name whose width placeholder cannot be derived from a pinned
bucket registry (raw ``len()`` of runtime data, arithmetic, a helper
that never declared itself a bucket-fn) mints a fresh XLA program at
an unpredictable shape.  The negative cases run the SAME sink shapes
through an annotated bucket-fn — including the guarded-placeholder
refinement device.py's fused/eager split relies on — and stay clean
because every derived name is covered by the committed manifest.
"""

from harmony_tpu import aot

BUCKETS = (8, 16)


# graftlint: bucket-fn registry=BUCKETS
def bucket(n):
    for b in BUCKETS:
        if n <= b:
            return b
    raise ValueError(n)


def helper_without_annotation(n):
    for b in BUCKETS:
        if n <= b:
            return b
    raise ValueError(n)


def serve_pinned(items):
    """Registry-derived width: agg_verify_b{8,16}, manifest-covered."""
    width = bucket(len(items))
    program = f"agg_verify_b{width}"
    return aot.resolve(program)


def serve_raw_len(items):
    """The wedge itself: one program per observed committee size."""
    program = f"agg_verify_b{len(items)}"  # expect: GL15
    return aot.resolve(program)


def serve_arithmetic(items):
    width = bucket(len(items)) * 2
    program = f"agg_verify_b{width}"  # expect: GL15
    return aot.resolve(program)


def serve_undeclared_helper(items):
    """Same math as ``bucket`` but never annotated: the analysis must
    not trust an unpinned helper's return set."""
    program = f"agg_verify_b{helper_without_annotation(len(items))}"  # expect: GL15
    return aot.resolve(program)


def serve_refined(items, fused):
    """The device.py fused/eager split: the placeholder is a guarded
    IfExp and the sink only runs under the SAME guard, so the eager
    branch's raw ``len`` never reaches a compile."""
    width = bucket(len(items)) if fused else len(items)
    program = f"agg_verify_b{width}"
    if fused:
        warm = aot.resolve(program)
        if warm is not None:
            return warm
    return None


def serve_conjunct_refined(items, fused, twin):
    """Refinement through a conjunction: ``if fused and not twin``
    still proves the bare ``fused`` test of the placeholder."""
    width = bucket(len(items)) if fused else len(items)
    program = f"agg_verify_b{width}"
    if fused and not twin:
        return aot.resolve(program)
    return None
