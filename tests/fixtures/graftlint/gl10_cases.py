"""GL10 fixture: Montgomery-domain typestate.

Every field value carries an R-degree (x * R^d): std d=0, mont d=1,
the R^2 conversion constant d=2.  ``mmul`` is the degree primitive
(d_out = d_a + d_b - 1); add/select require matching degrees.  The
tagged lines are the four defect classes: a conversion that lands in
the wrong domain, arithmetic mixing domains, a raw ``*`` product of
domain values, and a degree that leaves {0, 1, 2}.
"""
# graftlint: kernel-module dtype=int32

import jax.numpy as jnp

LIMB_BITS = 12
LIMB_MASK = (1 << LIMB_BITS) - 1

ONE_M = jnp.asarray([1] * 32, dtype=jnp.int32)  # graftlint: kernel bounds=limb; domain=mont
R2C = jnp.asarray([2] * 32, dtype=jnp.int32)  # graftlint: kernel bounds=limb; domain=r2


# graftlint: kernel bounds=(limb, limb) -> limb; domain=mul; trusted
def mmul(a, b):
    """Montgomery-product stand-in (degree algebra primitive)."""
    return a


# graftlint: kernel bounds=(limb, limb) -> limb; domain=(same, same) -> same; trusted
def fadd(a, b):
    """Canonical modular addition stand-in."""
    return a


# graftlint: kernel bounds=(limb) -> limb; domain=(std) -> mont
def to_mont_ok(a):
    return mmul(a, R2C)  # 0 + 2 - 1 = mont: clean


# graftlint: kernel bounds=(limb) -> limb; domain=(std) -> mont
def to_mont_missing_r2(a):  # expect: GL10
    return mmul(a, ONE_M)  # 0 + 1 - 1 = std, contract says mont


# graftlint: kernel bounds=(limb, limb) -> limb; domain=(mont, std) -> mont
def mixed_add(am, bs):  # expect: GL10
    return fadd(am, bs)  # expect: GL10


# graftlint: kernel bounds=(limb, limb) -> any; domain=(mont, mont) -> any
def raw_product(a, b):
    return (a * b) & LIMB_MASK  # expect: GL10


# graftlint: kernel bounds=() -> any; domain=any
def r3_degree():
    return mmul(R2C, R2C)  # expect: GL10


# graftlint: kernel bounds=(any, limb, limb) -> any; domain=(any, mont, std) -> any
def select_mixed(m, x, y):
    return jnp.where(m[..., None], x, y)  # expect: GL10


# graftlint: kernel bounds=(limb, limb) -> any; domain=(mont, std) -> any
def mixed_add_reviewed(am, bs):
    return fadd(am, bs)  # graftlint: disable=GL10 boundary conversion audited
