"""GL14 fixtures: watchdog coverage — positive, compliant, exempt.

Never imported or executed; tests/test_graftlint.py lints this file and
asserts that exactly the lines tagged ``# expect: GLxx`` are flagged.

The positive cases re-create the PR-14 gap: a spawned long-lived loop
the watchdog cannot see — no declared role, an unknown role, a role
that never registers a Heartbeat, and one that registers but never
beats (permanently stale).  The compliant case registers AND beats;
``transient`` threads and bounded targets are exempt by policy.
"""

import threading

from harmony_tpu import health


class NoRole:
    """Long-lived loop, no thread-role annotation."""

    def start(self):
        threading.Thread(  # expect: GL14
            target=self._loop, daemon=True,
        ).start()

    def _loop(self):
        while True:
            step()


class BadRole:
    """Annotated, but the role is not in the registry."""

    def start(self):
        threading.Thread(  # expect: GL14
            # graftlint: thread-role=mystery.worker
            target=self._loop, daemon=True,
        ).start()

    def _loop(self):
        while True:
            step()


class NeverRegisters:
    """sidecar.reader demands a Heartbeat; nothing ever registers."""

    def start(self):
        threading.Thread(  # expect: GL14
            # graftlint: thread-role=sidecar.reader
            target=self._read_loop, daemon=True,
        ).start()

    def _read_loop(self):
        while True:
            pull_frame()


class RegistersButSilent:
    """Registered, but the loop never beats — permanently stale."""

    def start(self):
        t = threading.Thread(  # expect: GL14
            # graftlint: thread-role=governor.sampler
            target=self._loop, daemon=True,
        )
        t.start()
        self._hb = health.register("fixture.silent", thread=t)

    def _loop(self):
        while True:
            sample()


class Compliant:
    """Registers at the spawn site and beats in the loop: clean."""

    def start(self):
        t = threading.Thread(
            # graftlint: thread-role=netem.scheduler
            target=self._loop, daemon=True,
        )
        t.start()
        self._hb = health.register("fixture.good", thread=t)

    def _loop(self):
        while True:
            self._hb.beat()
            deliver()


class PerConn:
    """transient threads (bounded lifetime by contract) are exempt."""

    def spawn(self, q):
        threading.Thread(
            # graftlint: thread-role=transient — per-connection
            target=self._serve, args=(q,), daemon=True,
        ).start()

    def _serve(self, q):
        while True:
            item = q.get()
            if item is None:
                return


def fire_and_forget(fn):
    """Unresolvable target (a parameter): not statically analyzable."""
    threading.Thread(target=fn, daemon=True).start()
