"""GL08 fixture: unbounded blocking calls — socket connect/recv and
urlopen reachable without a timeout ever being set.
tests/test_graftlint.py asserts that exactly the lines tagged
``# expect: GLxx`` are flagged.

Covers: connect/recv on timeout-less sockets (local and self-attr,
with the timeout recognized ACROSS methods), bounded dials via
settimeout and create_connection(timeout=...), urlopen with/without a
timeout, the interprocedural case (a timeout-less socket passed into a
helper that recvs on it), a callee that bounds its own parameter, and
an inline suppression.
"""

import socket
import urllib.request


def dial_no_timeout(addr):
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.connect(addr)  # expect: GL08
    return s


def dial_with_timeout(addr):
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.settimeout(5)
    s.connect(addr)
    return s


def recv_after_bounded_dial(addr):
    s = socket.create_connection(addr, timeout=3)
    return s.recv(4)


def recv_after_unbounded_dial(addr):
    s = socket.create_connection(addr)  # expect: GL08
    return s.recv(4)  # expect: GL08


def fetch_no_timeout(url):
    return urllib.request.urlopen(url)  # expect: GL08


def fetch_with_timeout(url):
    return urllib.request.urlopen(url, timeout=5)


class Client:
    def __init__(self, addr):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.connect(addr)  # expect: GL08

    def read(self):
        return self._sock.recv(4)  # expect: GL08


class BoundedClient:
    """settimeout in __init__ bounds the recv in a SIBLING method —
    the class-wide view the whole-program pass provides."""

    def __init__(self, addr):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.settimeout(2)
        self._sock.connect(addr)

    def read(self):
        return self._sock.recv(4)


def _read_exact(sock, n):
    return sock.recv(n)


class Framed:
    def __init__(self):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)

    def read_frame(self):
        return _read_exact(self._sock, 4)  # expect: GL08


def bounded_param_flow(addr):
    s = socket.create_connection(addr, timeout=1)
    return _read_exact(s, 4)


def callee_sets_timeout(sock):
    sock.settimeout(1)
    return sock.recv(4)


def suppressed_dial(addr):
    s = socket.create_connection(addr)  # graftlint: disable=GL08 bounded by the caller's alarm
    return s.recv(4)  # expect: GL08


def fetch_explicit_none_timeout(url):
    return urllib.request.urlopen(url, timeout=None)  # expect: GL08
