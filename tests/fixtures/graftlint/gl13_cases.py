"""GL13 fixtures: wire-taint budgets — positive, sanitized, clean.

Never imported or executed; tests/test_graftlint.py lints this file and
asserts that exactly the lines tagged ``# expect: GLxx`` are flagged.

The positive cases re-create the PR-13 bug class: a length/count read
straight off an untrusted blob bounds a loop, sizes an allocation or
multiplies a payload with no dominating remaining-budget check — a
4-byte forged prefix buys four billion iterations.  The sanitized
cases show the two blessed idioms (explicit remaining-bytes guard,
``min``-clamp) staying quiet.
"""

import struct

MAX_ITEMS = 1024


def decode_unchecked_loop(buf: bytes):
    n = int.from_bytes(buf[:4], "little")
    out = []
    for _ in range(n):  # expect: GL13
        out.append(buf[:1])
    return out


def decode_unchecked_alloc(buf: bytes):
    n = int.from_bytes(buf[:4], "little")
    return bytearray(n)  # expect: GL13


def decode_unchecked_mult(buf: bytes):
    n = int.from_bytes(buf[:4], "little")
    return b"\x00" * n  # expect: GL13


def decode_struct_source(buf: bytes):
    (n,) = struct.unpack("<I", buf[:4])
    return bytearray(n)  # expect: GL13


def decode_guarded(buf: bytes):
    """The remaining-budget idiom: every element costs >= 1 byte, so a
    count that cannot fit in what's left is rejected before the loop."""
    n = int.from_bytes(buf[:4], "little")
    if n > len(buf) - 4:
        raise ValueError("implausible element count")
    return [buf[:1] for _ in range(n)]


def decode_clamped(buf: bytes):
    n = min(int.from_bytes(buf[:4], "little"), MAX_ITEMS)
    return [buf[:1] for _ in range(n)]


def decode_window(buf: bytes, count: int):
    """range(start, start + n) iterates n times — a clamped n keeps a
    tainted START from being a cost bound (it is a lookup key)."""
    start = int.from_bytes(buf[:4], "little")
    n = min(count, MAX_ITEMS)
    return [start + i for i in range(start, start + n)]
