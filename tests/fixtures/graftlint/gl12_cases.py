"""GL12 fixtures: thread-role dispatch discipline — positive, negative.

Never imported or executed; tests/test_graftlint.py lints this file and
asserts that exactly the lines tagged ``# expect: GLxx`` are flagged.

The positive cases re-create the PR-15 ``aggregate_public`` wedge shape:
a spawn site annotated with a latency-critical role whose loop reaches
a jax compile (and an unbounded wait) through a helper.  The negative
cases run the SAME shapes on a non-latency-critical role — compile and
blocking clauses are role-scoped; only the ops-excursion clause fires
for every role (twin mode keeps jax unloaded on all of them).
"""

import threading

import harmony_tpu.ops.curve as CV
import jax
from harmony_tpu import health


def _pump_compile_helper(xs):
    fn = jax.jit(lambda a: a)  # expect: GL12
    return fn(xs)


def _serving_compile_helper(xs):
    fn = jax.jit(lambda a: a)  # compile off the critical path: clean
    return fn(xs)


class Pump:
    """Latency-critical role: compile AND unbounded blocking flagged."""

    def __init__(self):
        self.closing = False
        self.ev = threading.Event()
        self._hb = None

    def start(self):
        t = threading.Thread(
            # graftlint: thread-role=consensus.pump
            target=self._pump_loop, daemon=True,
        )
        t.start()
        self._hb = health.register("fixture.pump", thread=t)

    def _pump_loop(self):
        while not self.closing:
            self._hb.beat()
            self._step()

    def _step(self):
        self.ev.wait()  # expect: GL12
        return _pump_compile_helper([1, 2, 3])


class Background:
    """serving role, same shape: compile/blocking clauses stay quiet,
    but the ops excursion fires on EVERY role."""

    def __init__(self):
        self.closing = False
        self.ev = threading.Event()

    def start(self):
        threading.Thread(
            # graftlint: thread-role=serving
            target=self._loop, daemon=True,
        ).start()

    def _loop(self):
        while not self.closing:
            self.ev.wait()  # serving may park unbounded: clean
            _serving_compile_helper([1])
            self._masked()

    def _masked(self, pks=None, bits=None):
        return CV.masked_sum(pks, bits, CV.FP_OPS)  # expect: GL12
