"""GL07 fixture: hot-path host syncs.  tests/test_graftlint.py
asserts that exactly the lines tagged ``# expect: GLxx`` are flagged.

Covers: a per-item device->host sync inside a loop, a loop calling a
helper that syncs internally, the clean dispatch-all-then-drain
pattern, host-side numpy in a loop (NOT a device sync), and an inline
suppression.
"""

import jax
import numpy as np


def _kernel(x):
    return x + 1


def per_item_sync(items):
    fn = jax.jit(_kernel)
    out = []
    for it in items:
        ok = fn(it)
        out.append(bool(np.asarray(ok)))  # expect: GL07
    return out


def _check_one(v):
    fn = jax.jit(_kernel)
    ok = fn(v)
    return bool(np.asarray(ok))


def loop_calls_syncer(items):
    fn = jax.jit(_kernel)
    first = fn(items[0])
    out = [bool(np.asarray(first))]
    for v in items[1:]:
        out.append(_check_one(v))  # expect: GL07
    return out


def clean_dispatch_then_drain(items):
    fn = jax.jit(_kernel)
    pending = []
    for it in items:
        pending.append(fn(it))
    stacked = np.asarray(pending)
    return [bool(x) for x in stacked]


def host_numpy_in_loop(rows):
    fn = jax.jit(_kernel)
    fn(rows[0])  # keep this function on the hot path
    out = []
    for r in rows:
        out.append(np.asarray(r))  # host data prep: not a device sync
    return out


def suppressed_per_item(items):
    fn = jax.jit(_kernel)
    out = []
    for it in items:
        ok = fn(it)
        out.append(bool(np.asarray(ok)))  # graftlint: disable=GL07 reviewed: tiny batches, latency beats batching
    return out
