"""GL04 fixtures: silent-failure hygiene — positive, suppressed, clean.

Never imported or executed; tests/test_graftlint.py lints this file and
asserts that exactly the lines tagged ``# expect: GLxx`` are flagged.
"""


def sign(payload):
    try:
        return payload.sign()
    except:  # expect: GL04
        return None


def verify(sig):
    try:
        return sig.check()
    except Exception:  # expect: GL04
        pass


def verify_base(sig):
    try:
        return sig.check()
    except BaseException:  # expect: GL04
        pass


def verify_logged(sig, log):
    try:
        return sig.check()
    except ValueError as e:
        log.warn("bad signature", error=str(e))
        return False


def tolerated(sig):
    try:
        return sig.check()
    except Exception:  # graftlint: disable=GL04
        pass


def counted(sig, stats):
    try:
        return sig.check()
    except Exception:
        stats["dropped"] += 1  # not silent: counted and surfaced
        return False
