"""GL05 fixture: lock-order analysis.  tests/test_graftlint.py
asserts that exactly the lines tagged ``# expect: GLxx`` are flagged.

Covers: a two-lock cycle (both edges flagged as cycles), an acyclic
nested pair (flagged as an undeclared edge), a non-reentrant
re-acquisition through a helper (self-deadlock), RLock re-acquisition
(exempt), a cross-class edge through a uniquely-named method, and an
inline suppression.
"""

import threading

_A = threading.Lock()
_B = threading.Lock()
_X = threading.Lock()
_Y = threading.Lock()
_C = threading.Lock()
_R = threading.RLock()


def cycle_ab():
    with _A:
        with _B:  # expect: GL05
            pass


def cycle_ba():
    with _B:
        with _A:  # expect: GL05
            pass


def acyclic_edge():
    with _X:
        with _Y:  # expect: GL05
            pass


def _takes_c():
    with _C:
        pass


def self_deadlock():
    with _C:
        _takes_c()  # expect: GL05


def _takes_r():
    with _R:
        pass


def reentrant_ok():
    with _R:
        _takes_r()  # RLock: same-thread re-acquisition is legal


class Inner:
    def __init__(self):
        self._guard = threading.Lock()

    def poke_inner_state(self):
        with self._guard:
            pass


class Outer:
    def __init__(self):
        self._lk = threading.Lock()
        self.inner = Inner()

    def touch(self):
        with self._lk:
            self.inner.poke_inner_state()  # expect: GL05


def suppressed_edge():
    with _X:
        with _C:  # graftlint: disable=GL05 reviewed: X before C everywhere
            pass


_P = threading.Lock()
_Q = threading.Lock()


def multi_item_pq():
    with _P, _Q:  # expect: GL05
        pass


def nested_qp():
    with _Q:
        with _P:  # expect: GL05
            pass
