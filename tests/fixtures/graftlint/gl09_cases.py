"""GL09 fixture: limb value-range abstract interpretation.

Each tagged line must be flagged exactly there; everything else must
stay quiet.  The centerpiece is the seeded Karatsuba-shaped
overflow: two-level 32->16->8 digit-sum splitting feeds sums-of-sums
(<= 3*4095 = 12285) into a limb convolution, whose 32-term accumulator
is provably 12285^2 * 32 = 4.83e9 > int32 — the exact silent-overflow
class the Karatsuba/MXU kernel optimizations can introduce.  The
guarded twin resolves carries back to ~12-bit digits first and must
NOT be flagged (4097^2 * 32 = 5.4e8 fits).
"""
# graftlint: kernel-module dtype=int32

import jax.numpy as jnp

LIMB_BITS = 12
LIMB_MASK = (1 << LIMB_BITS) - 1


def _resolve_once(s):
    """One carry round: lazy digits <= 2^14 back to <= 2^12 + 3."""
    q = s >> LIMB_BITS
    r = s & LIMB_MASK
    return r + jnp.concatenate(
        [jnp.zeros_like(q[..., :1]), q[..., :-1]], axis=-1
    )


# graftlint: kernel bounds=(limb, limb) -> any; domain=(std, std) -> any
def kara_convolution_unguarded(a, b):
    """Two-level Karatsuba split WITHOUT re-reducing the digit sums."""
    sa = (a + a) + a  # models (a_lo + a_hi) + carry-folded second split
    sb = (b + b) + b
    return jnp.einsum("...i,...i->...", sa, sb)  # expect: GL09


# graftlint: kernel bounds=(limb, limb) -> any; domain=(std, std) -> any
def kara_convolution_guarded(a, b):
    """Same shape, digit sums carry-resolved before the convolution —
    the accumulator provably fits int32; must NOT be flagged."""
    sa = _resolve_once((a + a) + a)
    sb = _resolve_once((b + b) + b)
    return jnp.einsum("...i,...i->...", sa, sb)


# graftlint: kernel bounds=(<2**16, <2**16) -> any; domain=any
def plane_recombine_unguarded(hi_plane, lo_plane):
    """int8-plane recombination done as a raw 16x16-bit product."""
    return hi_plane * lo_plane  # expect: GL09


# graftlint: kernel bounds=(<2**13) -> limb; domain=(same) -> same; trusted
def resolve13(s):
    """Stand-in for fp.resolve_carries: exact for inputs < 2^13."""
    return s & LIMB_MASK


# graftlint: kernel bounds=(limb, limb) -> limb; domain=(same, same) -> same
def triple_add_bad(a, b):
    return resolve13(a + b + b)  # expect: GL09


# graftlint: kernel bounds=(limb, limb) -> limb; domain=(same, same) -> same
def triple_add_reviewed(a, b):
    return resolve13(a + b + b)  # graftlint: disable=GL09 b is pre-halved upstream


# graftlint: kernel bounds=(limb, limb) -> limb; domain=(same, same) -> same
def double_add_ok(a, b):
    return resolve13(a + b)  # 8190 < 2^13: clean
