"""GL01 fixtures: jit purity — positive, suppressed, and clean cases.

Never imported or executed; tests/test_graftlint.py lints this file and
asserts that exactly the lines tagged ``# expect: GLxx`` are flagged.
"""

import functools
import random
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

STATE = {"calls": 0}


@jax.jit
def impure_time(x):
    t = time.time()  # expect: GL01
    return x + t


@functools.partial(jax.jit, static_argnames=("n",))
def impure_print(x, n):
    print("tracing", n)  # expect: GL01
    return x * n


@jax.jit
def impure_host_sync(x):
    y = np.asarray(x)  # expect: GL01
    return x + y.item()  # expect: GL01


@jax.jit
def impure_global(x):
    global STATE  # expect: GL01
    STATE = {"calls": 1}
    return x


@jax.jit
def impure_attr(obj, x):
    obj.cache = x  # expect: GL01
    return x


@jax.jit
def impure_random(x):
    return x + random.random()  # expect: GL01


@jax.jit
def suppressed_ok(x):
    print("reviewed: trace-time only")  # graftlint: disable=GL01
    return x


@jax.jit
def wrong_suppression(x):
    print("still flagged")  # graftlint: disable=GL02  # expect: GL01
    return x


def helper_step(carry, x):
    time.sleep(0)  # expect: GL01
    return carry + x, None


def uses_scan(xs):
    return jax.lax.scan(helper_step, 0, xs)


def kernel(in_ref, out_ref):
    out_ref[:, :] = in_ref[:, :] * 2  # ref store: the Pallas idiom, clean
    print("kernel side effect")  # expect: GL01


def call_kernel(x):
    return pl.pallas_call(kernel, out_shape=None)(x)


@jax.jit
def pure_fn(x):
    y = jnp.zeros_like(x)
    return x + y


def plain_function(x):
    # not traced: host-side impurity is fine
    print("host-side logging", time.time())
    return x
