"""GL17 fixtures: compile locality — every trace/lower/compile must
live in the sanctioned device layer or a declared warmup/diagnostic
phase.

Never imported or executed; tests/test_graftlint.py lints this file and
asserts that exactly the lines tagged ``# expect: GLxx`` are flagged.

The ``compile-zone`` marker below opts this (non-harmony_tpu) file
into the in-zone patterns — the same grammar a serving module outside
the package tree would use.  The positive cases are the ways a compile
has actually leaked onto a serving thread: a bare jit head, an
immediate first-trace, a jit-bound callable traced in place, explicit
``.lower(...)``/``.compile()`` chains.  The negative cases carry the
phase annotations (warmup / diagnostic) that sanction a compile off
the serving path, including through a nested def.
"""

# graftlint: compile-zone=serving

import jax


def bare_head(xs):
    fn = jax.jit(lambda a: a)  # expect: GL17
    return fn(xs)  # expect: GL17


def immediate_first_trace(xs):
    return jax.jit(lambda a: a + 1)(xs)  # expect: GL17


@jax.jit  # expect: GL17
def decorated(x):
    return x


def explicit_lower(fn, xs):
    lowered = fn.lower(xs)  # expect: GL17
    return lowered.compile()  # expect: GL17


def lower_compile_chain(fn):
    return fn.lower().compile()  # expect: GL17


# graftlint: compile-phase=warmup
def warmup_precompile(fn, spec):
    """Startup warmup: compiles are the POINT here — exempt."""
    lowered = fn.lower(spec)
    compiled = lowered.compile()
    jitted = jax.jit(lambda a: a)
    jitted(spec)
    return compiled


# graftlint: compile-phase=warmup
def warmup_with_nested(fn, specs):
    """The phase annotation reaches nested defs: closures spawned by
    a warmup routine are still warmup."""

    def one(spec):
        return fn.lower(spec).compile()

    return [one(s) for s in specs]


# graftlint: compile-phase=diagnostic
def cost_probe(fn, args):
    """prof.py's cost-analysis shape: a diagnostic compile, off the
    serving path by construction."""
    compiled = fn.lower(*args).compile()
    return compiled.cost_analysis()
