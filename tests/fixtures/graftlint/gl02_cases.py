"""GL02 fixtures: limb-dtype discipline — positive, suppressed, clean.

Never imported or executed; tests/test_graftlint.py lints this file and
asserts that exactly the lines tagged ``# expect: GLxx`` are flagged.
"""

import jax.numpy as jnp

GOOD_TABLE = jnp.asarray([1, 2, 3], dtype=jnp.int32)
BAD_TABLE = jnp.asarray([1, 2, 3])  # expect: GL02
BAD_ARRAY = jnp.array((4, 5))  # expect: GL02
BAD_COMP = jnp.asarray([i & 1 for i in range(8)])  # expect: GL02


def make_masks(converted_limbs):
    typed = jnp.zeros(32, dtype=jnp.int32)
    untyped = jnp.zeros(32)  # expect: GL02
    untyped_full = jnp.full(32, 7)  # expect: GL02
    from_var = jnp.asarray(converted_limbs)  # dtype unknowable: clean
    return typed, untyped, untyped_full, from_var


def weak_where(x):
    disciplined = jnp.where(x > 0, 1, 0).astype(x.dtype)
    weak = jnp.where(x > 0, 1, 0)  # expect: GL02
    reviewed = jnp.where(x > 0, 1, 0)  # graftlint: disable=GL02
    named_operands = jnp.where(x > 0, x, -x)
    return disciplined, weak, reviewed, named_operands


def float_leak(x):
    scale = 1.5  # expect: GL02
    return x * scale


def reason_suffix(x):
    # a justification after the rule id must still suppress
    return jnp.where(x > 0, 1, 0)  # graftlint: disable=GL02 weak-by-design
