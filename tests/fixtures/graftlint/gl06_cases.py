"""GL06 fixture: blocking work under a held lock.  tests/test_graftlint.py
asserts that exactly the lines tagged ``# expect: GLxx`` are flagged.

Covers: a direct sleep under a lock, a sleep reached through a helper
call, socket I/O under a lock, a Thread.join under a lock, the clean
patterns (blocking work outside the critical section), and an inline
suppression.
"""

import socket
import threading
import time

_L = threading.Lock()


def sleepy_direct():
    with _L:
        time.sleep(1)  # expect: GL06


def _nap():
    time.sleep(1)


def sleepy_via_call():
    with _L:
        _nap()  # expect: GL06


def recv_under_lock(sock):
    with _L:
        sock.recv(4)  # expect: GL06


def dial_under_lock(addr):
    with _L:
        return socket.create_connection(addr)  # expect: GL06, GL08


def join_under_lock():
    t = threading.Thread(target=_nap)
    t.start()
    with _L:
        t.join()  # expect: GL06


def clean_blocking_outside():
    _nap()
    with _L:
        marker = 1
    time.sleep(0)
    return marker


def clean_snapshot_then_send(sock):
    with _L:
        payload = b"x"
    sock.sendall(payload)


def suppressed_sleep():
    with _L:
        time.sleep(1)  # graftlint: disable=GL06 reviewed: bounded test-only wait
