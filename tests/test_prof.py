"""Kernel-stage profiler tier (harmony_tpu/prof.py, ISSUE 6).

Covers the four acceptance edges: stage spans nest under the PR-4
round trace, a compiled program's cost-analysis keys reach /metrics,
the disabled fast path stays micro-benchmark cheap, and the metrics
quantile helper the loadgen/bench report path leans on.
"""

import os
import pathlib
import sys
import time

import numpy as np
import pytest

from harmony_tpu import prof, trace
from harmony_tpu.metrics import Histogram, Registry

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent / "tools"))

from obs_smoke import validate_prometheus  # noqa: E402


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    # the first-dispatch assertions below need a fresh per-program
    # seen-set: earlier suite files (the chaostest scenarios) dispatch
    # the same bucket-8 programs and would otherwise mark them used
    from harmony_tpu import device as DV

    monkeypatch.setattr(DV, "_SEEN_PROGRAMS", set())
    prof.reset()
    trace.reset()
    yield
    prof.reset()
    trace.reset()


# -- stage spans -------------------------------------------------------------


def test_stage_spans_nest_under_the_round_trace():
    prof.configure(enabled=True)
    trace.configure(enabled=True)
    with trace.span("consensus.round", component="consensus") as root:
        with prof.stage("hash_to_g2"):
            pass
        with prof.stage("miller_loop", batch=8):
            pass
    spans = [s for s in trace.spans() if s.name == "prof.stage"]
    assert len(spans) == 2
    for s in spans:
        assert s.parent_id == root.span_id
        assert s.trace_id == root.trace_id
        assert s.component == "prof"
    assert {s.attrs["stage"] for s in spans} == {"hash_to_g2",
                                                "miller_loop"}


def test_stage_records_histogram_samples():
    prof.configure(enabled=True)
    with prof.stage("montmul"):
        time.sleep(0.002)
    summary = prof.stage_summary()["montmul"]
    assert summary["count"] == 1
    assert summary["sum_s"] >= 0.002


def test_stage_survives_exceptions():
    prof.configure(enabled=True)
    with pytest.raises(ValueError):
        with prof.stage("final_exp"):
            raise ValueError("stage body failed")
    assert prof.stage_summary()["final_exp"]["count"] == 1


def test_env_var_arms_the_profiler(monkeypatch):
    """HARMONY_TPU_PROF=1 is the documented operator path; prof.py
    applies it at import and arm_from_env() re-applies after reset."""
    monkeypatch.setenv("HARMONY_TPU_PROF", "1")
    assert not prof.enabled()
    assert prof.arm_from_env() is True
    assert prof.enabled()


def test_batch_dispatch_records_execute_histogram():
    """The replay-critical batch programs feed the execute histogram
    on their non-compiling dispatches (issue->drain latency)."""
    os.environ["HARMONY_KERNEL_TWIN"] = "1"
    try:
        from harmony_tpu import device as DV
        from harmony_tpu.metrics import Registry
        from harmony_tpu.ref import bls as RB
        from harmony_tpu.ref.curve import g2
        from harmony_tpu.ref.hash_to_curve import hash_to_g2

        prof.configure(enabled=True)
        DV.use_device(True)
        sks = [RB.keygen(bytes([31, i])) for i in range(4)]
        table = DV.CommitteeTable([RB.pubkey(sk) for sk in sks])
        h = hash_to_g2(b"batch-exec-histogram-check!!!!!!")
        agg = RB.aggregate_sigs([g2.mul(h, sk) for sk in sks])
        bits = [[1, 1, 1, 1]] * 2
        for _ in range(2):  # first pays "compile", second executes
            assert all(DV.agg_verify_batch_on_device(
                table, bits, [h] * 2, [agg] * 2
            ))
        text = Registry().expose()
        assert ('harmony_prof_execute_seconds_count'
                f'{{program="agg_verify_batch_b{table.size}x8"}} 1'
                in text)
    finally:
        from harmony_tpu import device as DV

        DV.use_device(None)
        os.environ.pop("HARMONY_KERNEL_TWIN", None)


def test_disabled_stage_cost_is_noise():
    """The profiler sits on the verify hot path; disabled it must cost
    one comparison.  10k disabled stages in well under a second is a
    ~50x margin over the measured cost on this box."""
    assert not prof.enabled()
    t0 = time.perf_counter()
    for _ in range(10_000):
        with prof.stage("montmul"):
            pass
    assert time.perf_counter() - t0 < 0.5
    assert prof.stage_summary() == {}  # nothing recorded while dark


# -- program registry / cost analysis ----------------------------------------


def _tiny_jitted():
    import jax

    return jax.jit(lambda x: (x @ x).sum()), np.ones((8, 8), np.float32)


def test_cost_analysis_keys_present_for_a_compiled_program():
    prof.configure(enabled=True)
    fn, x = _tiny_jitted()
    prof.on_first_dispatch("test_prog_w8", fn, (x,), 0.05)
    entry = prof.programs()["test_prog_w8"]
    assert entry["compile_s"] == 0.05
    # XLA's own analysis of the executable, not a model
    assert entry["flops"] > 0
    assert entry["bytes_accessed"] > 0
    assert "peak_memory_bytes" in entry


def test_program_families_reach_the_metrics_exposition():
    prof.configure(enabled=True)
    fn, x = _tiny_jitted()
    prof.on_first_dispatch("test_prog_w8", fn, (x,), 0.05)
    prof.observe_execute("test_prog_w8", 0.004)
    text = Registry().expose()
    assert 'harmony_prof_program_flops{program="test_prog_w8"}' in text
    assert ('harmony_prof_program_bytes_accessed{program="test_prog_w8"}'
            in text)
    assert ('harmony_prof_program_compile_seconds{program="test_prog_w8"}'
            in text)
    assert 'harmony_prof_execute_seconds' in text
    assert validate_prometheus(text) == []


def test_twin_callable_records_walltime_without_analysis():
    """Twin kernels are plain callables: the registry still carries the
    compile wall time, just no XLA analysis."""
    prof.configure(enabled=True)
    prof.on_first_dispatch("agg_verify_b8", lambda *a: True, (), 0.01)
    entry = prof.programs()["agg_verify_b8"]
    assert entry == {"compile_s": 0.01}


def test_device_dispatch_populates_the_registry():
    """The device.py wiring end to end: a twin-kernel dispatch lands
    its program shape in the prof registry and exposition."""
    os.environ["HARMONY_KERNEL_TWIN"] = "1"
    try:
        from harmony_tpu import device as DV
        from harmony_tpu.ref import bls as RB
        from harmony_tpu.ref.curve import g2
        from harmony_tpu.ref.hash_to_curve import hash_to_g2

        prof.configure(enabled=True)
        DV.use_device(True)
        sks = [RB.keygen(bytes([i + 1])) for i in range(4)]
        pks = [RB.pubkey(sk) for sk in sks]
        msg = b"prof-device-dispatch-check!!!!!!"
        h = hash_to_g2(msg)
        agg = RB.aggregate_sigs([g2.mul(h, sk) for sk in sks])
        table = DV.CommitteeTable(pks)
        assert DV.agg_verify_on_device(table, [1, 1, 1, 1], msg, agg)
        progs = prof.programs()
        assert f"agg_verify_b{table.size}" in progs
        assert prof.stage_summary()["hash_to_g2"]["count"] >= 1
    finally:
        from harmony_tpu import device as DV

        DV.use_device(None)
        os.environ.pop("HARMONY_KERNEL_TWIN", None)


# -- capture hook ------------------------------------------------------------


def test_profile_dir_capture_yields_nonempty_trace(tmp_path, monkeypatch):
    """HARMONY_TPU_PROFILE_DIR + one jitted call -> a loadable,
    non-empty profiler trace on CPU (the acceptance edge: the first
    device attempt must produce a trace, not a second run)."""
    d = str(tmp_path / "prof_trace")
    monkeypatch.setenv("HARMONY_TPU_PROFILE_DIR", d)
    prof.configure(enabled=True)
    fn, x = _tiny_jitted()
    import jax

    with prof.capture():
        jax.block_until_ready(fn(x))
    files = [p for p in pathlib.Path(d).rglob("*") if p.is_file()]
    assert files, "profiler capture produced no trace files"


def test_capture_without_dir_is_a_noop(monkeypatch):
    monkeypatch.delenv("HARMONY_TPU_PROFILE_DIR", raising=False)
    with prof.capture():
        pass  # nothing to assert: must simply not touch jax/raise


# -- the metrics quantile helper ---------------------------------------------


def test_histogram_quantile_interpolates():
    h = Histogram("t", "", buckets=(0.01, 0.1, 1.0))
    assert h.quantile(0.5) is None  # empty
    for v in (0.005, 0.05, 0.05, 0.5):
        h.observe(v)
    # rank 2 of 4 falls in the (0.01, 0.1] bucket
    assert 0.01 <= h.quantile(0.5) <= 0.1
    assert 0.1 <= h.quantile(0.99) <= 1.0
    s = h.summary()
    assert s["count"] == 4 and s["p50_s"] <= s["p99_s"]


def test_histogram_quantile_overflow_clamps_to_last_bound():
    h = Histogram("t", "", buckets=(0.01, 0.1))
    h.observe(5.0)  # lands in +Inf
    assert h.quantile(0.99) == 0.1
