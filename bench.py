"""Benchmark: BLS12-381 pairing throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline (BASELINE.md): >= 50_000 pairings/s sustained on 1x TPU v5e.

Measures the batched full pairing (Miller loop + final exponentiation)
at the largest batch that fits comfortably, steady-state (post-compile),
wall-clock per device-complete iteration.
"""

import json
import os
import sys
import time


def _arm_watchdog(seconds: int):
    """The axon TPU tunnel can wedge with jax.devices() hanging forever
    (observed in round 1); emit an honest zero-result instead of hanging
    the driver."""
    import threading

    def fire():
        print(
            json.dumps(
                {
                    "metric": "bls12_381_pairings_per_sec_per_chip",
                    "value": 0,
                    "unit": "pairings/s",
                    "vs_baseline": 0.0,
                    "error": f"timeout after {seconds}s (TPU tunnel wedged?)",
                }
            ),
            flush=True,
        )
        os._exit(2)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


def main():
    watchdog = _arm_watchdog(int(os.environ.get("BENCH_TIMEOUT", "3000")))
    import jax

    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    import jax.numpy as jnp
    import numpy as np

    from harmony_tpu.ops import interop as I
    from harmony_tpu.ops import pairing as OP
    from harmony_tpu.ref import bls as RB
    from harmony_tpu.ref.curve import g1, g2, G1_GEN, G2_GEN

    batch = int(os.environ.get("BENCH_BATCH", "256"))
    iters = int(os.environ.get("BENCH_ITERS", "3"))

    # distinct inputs (scalar multiples of the generators), tiled to batch
    base_p = [G1_GEN, g1.dbl(G1_GEN), g1.mul(G1_GEN, 5), g1.mul(G1_GEN, 7)]
    base_q = [G2_GEN, g2.dbl(G2_GEN), g2.mul(G2_GEN, 5), g2.mul(G2_GEN, 7)]
    p_arr = I.g1_batch_affine(base_p)
    q_arr = I.g2_batch_affine(base_q)
    reps = (batch + 3) // 4
    ps = jnp.asarray(np.tile(p_arr, (reps, 1, 1))[:batch])
    qs = jnp.asarray(np.tile(q_arr, (reps, 1, 1, 1))[:batch])

    fn = jax.jit(OP.pairing)
    out = fn(ps, qs)
    out.block_until_ready()  # compile + warm

    # correctness guard: bench numbers only count if results are right
    e1 = I.arr_to_fp12(np.array(out[0]))
    from harmony_tpu.ref import pairing as RP

    assert e1 == RP.pairing(G1_GEN, G2_GEN), "bench result wrong!"

    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn(ps, qs).block_until_ready()
        times.append(time.perf_counter() - t0)
    best = min(times)
    pairings_per_s = batch / best

    watchdog.cancel()
    print(
        json.dumps(
            {
                "metric": "bls12_381_pairings_per_sec_per_chip",
                "value": round(pairings_per_s, 1),
                "unit": "pairings/s",
                "vs_baseline": round(pairings_per_s / 50_000.0, 4),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
