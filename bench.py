"""Benchmark: BLS12-381 quorum-crypto throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
Primary metric (BASELINE.md): >= 50_000 pairings/s sustained on 1x TPU
v5e.  The same line carries the other BASELINE configs under "extra":
  - agg_verify_p50_ms_1k_keys  (config #2: 1000-key masked aggregate
    verify, < 2 ms p50 target)
  - replay_headers_per_sec     (config #5: batched header-seal verify,
    the block-replay throughput shape)

Robustness contract (VERDICT r2 #1 — two rounds of rc=1/timeouts):
this file must emit a parseable JSON line on EVERY exit path.  The
axon TPU tunnel has two observed failure modes on this image: a hang
inside backend init (r1) and a RuntimeError("Unable to initialize
backend 'axon'") (r2).  Both are survived by running the measurement
in a CHILD process: the parent arms a deadline, captures the child's
output, and on any failure retries on the forced-CPU backend so the
round still records a real measured number (clearly labeled) instead
of a traceback.
"""

import json
import os
import subprocess
import sys
import time

PRIMARY = "bls12_381_pairings_per_sec_per_chip"
TARGET_PAIRINGS_S = 50_000.0

# docs/PERF_MODEL.md §4: the as-written kernel's conservative projection
# band on one v5e chip — the modeled claim every measured number is
# ledgered against (tools/bench_ledger.py diffs measured-vs-modeled
# across BENCH rounds; tools/bench_device.py checks the band on device).
MODELED_BAND_PAIRINGS_S = (9_000.0, 21_000.0)


def _m(value, unit: str, source: str = "measured", **fields) -> dict:
    """One ledger-tagged metric: every number bench.py emits carries
    its unit and whether it was measured on this run or derived from
    the analytic model (ISSUE 6: no untagged metrics).  Extra fields
    record the measurement's parameters (n_keys, mode, ...) so the
    ledger can tell a redefinition from a regression."""
    out = {"value": value, "unit": unit, "source": source}
    out.update(fields)
    return out


def _modeled_band() -> dict:
    lo, hi = MODELED_BAND_PAIRINGS_S
    ref = "docs/PERF_MODEL.md §4"
    return {
        "modeled_pairings_per_sec_lo": _m(lo, "pairings/s", "modeled",
                                          ref=ref),
        "modeled_pairings_per_sec_hi": _m(hi, "pairings/s", "modeled",
                                          ref=ref),
    }


def pairing_fixture(batch: int):
    """(ps, qs) numpy affine tiles of ``batch`` G1/G2 pairs from 4
    distinct base points — THE kernel-bench input, shared with
    tools/bench_device.py so the bare-kernel and full-bench numbers
    measure identical work."""
    import numpy as np

    from harmony_tpu.ops import interop as I
    from harmony_tpu.ref.curve import G1_GEN, G2_GEN, g1, g2

    base_p = [G1_GEN, g1.dbl(G1_GEN), g1.mul(G1_GEN, 5),
              g1.mul(G1_GEN, 7)]
    base_q = [G2_GEN, g2.dbl(G2_GEN), g2.mul(G2_GEN, 5),
              g2.mul(G2_GEN, 7)]
    reps = (batch + 3) // 4
    ps = np.tile(I.g1_batch_affine(base_p), (reps, 1, 1))[:batch]
    qs = np.tile(I.g2_batch_affine(base_q), (reps, 1, 1, 1))[:batch]
    return ps, qs

# The axon PJRT plugin reaches the TPU through a loopback relay:
# jax.devices() goes via :8083 (stateless), sessions via :8082
# (/root/.axon_site/axon/register/pjrt.py:187-189).  A 2 s TCP probe of
# those ports classifies the tunnel BEFORE burning the child timeout:
# "refused" = relay process absent (r4 observation), "open" = at least
# listening, "timeout" = wedged transport.  See
# tools/diag/TUNNEL_POSTMORTEM_r4.md.
RELAY_PORTS = (8083, 8082)


def _probe_relay():
    import socket

    host = os.environ.get("PALLAS_AXON_POOL_IPS", "127.0.0.1").split(",")[0]
    out = {}
    for port in RELAY_PORTS:
        try:
            with socket.create_connection((host, port), timeout=2.0):
                out[str(port)] = "open"
        except ConnectionRefusedError:
            out[str(port)] = "refused"
        except OSError as e:
            out[str(port)] = f"error: {e.__class__.__name__}"
    return out


def _emit(obj):
    print(json.dumps(obj), flush=True)


def _error_to_file(err: str, name: str):
    """(one-line reason, file path) for a failure record: the JSON line
    carries a readable single line, the full traceback goes to a file
    next to this script — multi-KB tracebacks were drowning the bench
    record's ``extra`` (ISSUE 5 satellite)."""
    lines = [ln for ln in err.strip().splitlines() if ln.strip()]
    reason = (lines[-1] if lines else err)[:200]
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), f"bench_{name}.log"
    )
    try:
        with open(path, "w") as f:
            f.write(err)
    except OSError:
        path = None
    return reason, path


def _honest_zero(err: str, meta=None):
    _emit(
        {
            "metric": PRIMARY,
            "value": 0,
            "unit": "pairings/s",
            "vs_baseline": 0.0,
            "source": "measured",
            "error": err[-2000:],
            "extra": {},
            "meta": meta or {},
        }
    )


# ----------------------------------------------------------------------
# parent: orchestrate child measurement processes
# ----------------------------------------------------------------------


def _run_child(force_cpu: bool, timeout_s: float):
    """Run this file in --child mode; return (parsed_json | None, err)."""
    env = dict(os.environ)
    env["BENCH_CHILD"] = "1"
    # soft budget: the child stops measuring and emits its JSON before
    # the parent's hard kill would discard everything
    env["BENCH_CHILD_BUDGET"] = str(max(timeout_s - 30, 30))
    if force_cpu:
        env["BENCH_FORCE_CPU"] = "1"
        # XLA:CPU on this 1-core image: parallel LLVM codegen segfaults
        # intermittently; serialize it (see tests/conftest.py).
        flags = env.get("XLA_FLAGS", "")
        if "parallel_codegen" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_cpu_parallel_codegen_split_count=1"
            ).strip()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child"],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired as e:
        tail = ((e.stderr or b"").decode("utf-8", "replace")
                if isinstance(e.stderr, bytes) else (e.stderr or ""))
        return None, f"child timeout after {timeout_s:.0f}s; stderr tail: {tail[-500:]}"
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
            if isinstance(parsed, dict) and "metric" in parsed:
                return parsed, ""
        except (json.JSONDecodeError, ValueError):
            continue
    return None, (
        f"child rc={proc.returncode}; no JSON line; "
        f"stderr tail: {proc.stderr[-800:]}"
    )


def main():
    budget = float(os.environ.get("BENCH_TIMEOUT", "3000"))
    t0 = time.monotonic()
    relay = _probe_relay()
    # Attempt 1: default backend (TPU via the axon tunnel if alive).
    # When the relay ports refuse outright the plugin can only hang in
    # its connect-retry loop (make_c_api_client, no timeout), so spend
    # 120 s confirming instead of 60% of the budget; if anything
    # listens, give the device attempt the full share.
    relay_dead = all(v == "refused" for v in relay.values())
    tpu_timeout = 120.0 if relay_dead else budget * 0.6
    result, err1 = _run_child(force_cpu=False, timeout_s=tpu_timeout)
    if result is not None and not result.get("error"):
        result.setdefault("meta", {})["relay_tcp"] = relay
        _emit(result)
        return 0
    # Attempt 2: forced CPU — a real measured number beats a traceback.
    remaining = budget - (time.monotonic() - t0) - 10
    if remaining < 60:
        _honest_zero(
            f"tpu attempt failed ({err1}); no time left for cpu",
            meta={"relay_tcp": relay},
        )
        return 0
    result2, err2 = _run_child(force_cpu=True, timeout_s=remaining)
    if result2 is not None:
        meta = result2.setdefault("meta", {})
        reason, detail = _error_to_file(err1, "tpu_attempt_error")
        meta["tpu_attempt_error"] = reason
        if detail:
            meta["tpu_attempt_error_file"] = detail
        meta["relay_tcp"] = relay
        _emit(result2)
        return 0
    _honest_zero(f"tpu: {err1} || cpu: {err2}", meta={"relay_tcp": relay})
    return 0


# ----------------------------------------------------------------------
# child: the actual measurements
# ----------------------------------------------------------------------


def _child():
    child_budget = float(os.environ.get("BENCH_CHILD_BUDGET", "1e9"))
    deadline = time.monotonic() + child_budget
    if child_budget < 1e8:
        # If backend init hangs (axon connect-retry loop), dump the
        # stack shortly before the parent's hard kill so the hang
        # location lands in the recorded stderr tail.
        import faulthandler

        faulthandler.dump_traceback_later(
            max(child_budget + 15, 30), exit=False
        )
    import jax

    force_cpu = os.environ.get("BENCH_FORCE_CPU") == "1"
    if force_cpu:
        # the axon sitecustomize force-selects "axon,cpu" via
        # jax.config.update, overriding JAX_PLATFORMS — counter it
        # before any backend initializes
        jax.config.update("jax_platforms", "cpu")
    else:
        cache = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".jax_cache"
        )
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    import numpy as np
    import jax.numpy as jnp

    backend = jax.default_backend()
    on_tpu = backend not in ("cpu",)

    from harmony_tpu.ops import bls as OB
    from harmony_tpu.ops import curve as CV
    from harmony_tpu.ops import interop as I
    from harmony_tpu.ops import pairing as OP
    from harmony_tpu.ref import bls as RB
    from harmony_tpu.ref.curve import G1_GEN, G2_GEN, g1, g2
    from harmony_tpu.ref.hash_to_curve import hash_to_g2

    meta = {"backend": backend, "configs_failed": []}
    if not on_tpu:
        # XLA:CPU cannot build ANY pairing-shaped program inside the
        # budget on the 1-core fallback box (>20 min jit OR eager,
        # measured 2026-07-29) — measure the HOST path instead so the
        # round still records real numbers, clearly labeled: the native
        # C++ library (native/bls381.cpp) when it loads, the bigint twin
        # otherwise.
        from harmony_tpu.ref import native as NB

        meta["backend"] = (
            "cpu-native-bls381" if NB.available() else "cpu-bigint-reference"
        )
        return _child_cpu_bigint(meta, deadline)

    # ---- shared fixtures (small host-side setup) ----------------------
    msg = b"bench-agg-verify-block-payload!!"
    h_pt = hash_to_g2(msg)
    n_keys = int(os.environ.get("BENCH_KEYS", "1000"))
    sks = [RB.keygen(bytes([i % 251, i // 251])) for i in range(n_keys)]
    pks = [RB.pubkey(sk) for sk in sks]
    # sign via the precomputed message point: RB.sign would redo the
    # host hash-to-G2 n_keys times (fixture setup, not the measurement)
    sigs = [g2.mul(h_pt, sk) for sk in sks]

    extra = _modeled_band()
    # ---- config #2: 1000-key aggregate-verify p50 ---------------------
    # Committee table resident on device; per call: bitmap + 96B sig in,
    # bool out — the steady-state FBFT quorum check.
    try:
        from harmony_tpu import device as DV

        table = DV.CommitteeTable(pks)
        rng = np.random.default_rng(7)
        lat = []
        n_calls = int(os.environ.get("BENCH_AGG_CALLS", "12" if on_tpu else "4"))
        for i in range(n_calls):
            bits = np.ones(n_keys, dtype=np.int64)
            # drop a random ~one-sixth of signers (stays over 2/3 quorum)
            drop = rng.choice(n_keys, size=n_keys // 6, replace=False)
            bits[drop] = 0
            agg = RB.aggregate_sigs(
                [s for s, b in zip(sigs, bits) if b]
            )
            t1 = time.perf_counter()
            ok = DV.agg_verify_on_device(table, bits, msg, agg)
            dt = time.perf_counter() - t1
            if i > 0:  # first call pays compile
                lat.append(dt)
            assert ok, "agg_verify rejected a valid quorum!"
            if time.monotonic() > deadline:
                break
        if lat:
            extra["agg_verify_p50_ms_1k_keys"] = _m(
                round(sorted(lat)[len(lat) // 2] * 1e3, 3), "ms",
                n_keys=n_keys,
            )
    except Exception as e:  # noqa: BLE001 — report, don't crash the bench
        meta["configs_failed"].append(f"agg_verify: {e!r:.300}")

    # ---- config #5: replay throughput (batched seal verify) -----------
    try:
        from harmony_tpu import device as DV

        width = int(os.environ.get("BENCH_REPLAY_WIDTH", "64"))
        reps = int(os.environ.get("BENCH_REPLAY_REPS", "3" if on_tpu else "1"))
        small_keys = pks[:250]  # mainnet historic committee size
        small_sigs = sigs[:250]
        tbl = DV.CommitteeTable(small_keys)
        bits = np.ones(250, dtype=np.int64)
        agg = RB.aggregate_sigs(small_sigs)
        bl = [bits] * width
        hl = [h_pt] * width
        sl = [agg] * width
        DV.agg_verify_batch_on_device(tbl, bl, hl, sl)  # compile + warm
        best = None
        for _ in range(reps):
            t1 = time.perf_counter()
            res = DV.agg_verify_batch_on_device(tbl, bl, hl, sl)
            dt = time.perf_counter() - t1
            best = dt if best is None else min(best, dt)
            assert all(res), "replay batch rejected valid seals!"
            if time.monotonic() > deadline:
                break
        extra["replay_headers_per_sec"] = _m(
            round(width / best, 1), "headers/s",
            mode="device_batch_kernel", committee_keys=250, width=width,
        )
    except Exception as e:  # noqa: BLE001
        meta["configs_failed"].append(f"replay: {e!r:.300}")

    # ---- primary: raw pairing throughput ------------------------------
    batch = int(os.environ.get("BENCH_BATCH", "256" if on_tpu else "8"))
    iters = int(os.environ.get("BENCH_ITERS", "3" if on_tpu else "1"))
    ps_np, qs_np = pairing_fixture(batch)
    ps, qs = jnp.asarray(ps_np), jnp.asarray(qs_np)

    fn = jax.jit(OP.pairing)
    out = fn(ps, qs)
    out.block_until_ready()  # compile + warm

    # correctness guard: bench numbers only count if results are right
    e1 = I.arr_to_fp12(np.array(out[0]))
    from harmony_tpu.ref import pairing as RP

    assert e1 == RP.pairing(G1_GEN, G2_GEN), "bench result wrong!"

    # HARMONY_TPU_PROFILE_DIR: the FIRST device round must leave a
    # loadable profiler trace — no second run to re-instrument
    from harmony_tpu import prof

    times = []
    with prof.capture():
        for _ in range(iters):
            t1 = time.perf_counter()
            fn(ps, qs).block_until_ready()
            times.append(time.perf_counter() - t1)
    pairings_per_s = batch / min(times)
    if prof.capture_dir():
        meta["profile_dir"] = prof.capture_dir()

    # ---- Pallas-backend pairing (FP_BACKEND=pallas): the VMEM-resident
    # mont_mul (ops/fp_pallas.py) vs the scan path just measured.  The
    # HEADLINE number stays whichever is faster; both are recorded.
    try:
        from harmony_tpu.ops import fp as FPMOD

        FPMOD.set_backend("pallas")
        try:
            fnp = jax.jit(lambda p, q: OP.pairing(p, q))
            outp = fnp(ps, qs)
            jax.block_until_ready(outp)
            assert I.arr_to_fp12(np.array(outp[0])) == e1, (
                "pallas backend produced a different GT element!"
            )
            ptimes = []
            for _ in range(iters):
                t1 = time.perf_counter()
                fnp(ps, qs).block_until_ready()
                ptimes.append(time.perf_counter() - t1)
            extra["pairings_per_s_pallas"] = _m(
                round(batch / min(ptimes), 1), "pairings/s"
            )
            extra["pairings_per_s_scan"] = _m(
                round(pairings_per_s, 1), "pairings/s"
            )
            pairings_per_s = max(pairings_per_s, batch / min(ptimes))
        finally:
            FPMOD.set_backend("scan")
    except Exception as e:  # noqa: BLE001
        meta["configs_failed"].append(f"pallas_pairing: {e!r:.300}")

    _emit(
        {
            "metric": PRIMARY,
            "value": round(pairings_per_s, 1),
            "unit": "pairings/s",
            "vs_baseline": round(pairings_per_s / TARGET_PAIRINGS_S, 4),
            "source": "measured",
            "extra": extra,
            "meta": meta,
        }
    )
    return 0


def _replay_bench_e2e(deadline):
    """BASELINE config #5 measured END TO END (ISSUE 6): build a sealed
    chain, then drive it through the staged-sync downloader into a
    fresh replica — wire decode, the engine's verified-sig LRU, the
    verification scheduler's SYNC lane, seal verification and chain
    insert (execution included) all inside the timed window.  Replaces
    the 1/p50-of-one-agg-verify derivation (VERDICT Weak #2): that
    number modeled the kernel; this one measures the replay PIPELINE.
    Twin kernels route the device-path layers onto the host crypto
    exactly as a forced-device localnet does.

    The committee signs each block via the aggregate secret (Σ sk_i —
    the aggregate of all N signatures equals (Σ sk_i)·H(payload)), so
    fixture construction costs one G2 mul per block, not N."""
    import time as _t

    os.environ["HARMONY_KERNEL_TWIN"] = "1"
    from harmony_tpu import device as DV
    from harmony_tpu import sched as SC
    from harmony_tpu.chain.engine import Engine, EpochContext
    from harmony_tpu.consensus.mask import Mask
    from harmony_tpu.consensus.signature import construct_commit_payload
    from harmony_tpu.core import rawdb
    from harmony_tpu.core.blockchain import Blockchain
    from harmony_tpu.core.genesis import dev_genesis
    from harmony_tpu.core.kv import MemKV
    from harmony_tpu.core.types import Block
    from harmony_tpu.node.worker import Worker
    from harmony_tpu.ref import bls as RB
    from harmony_tpu.ref import native as NB
    from harmony_tpu.ref.curve import R_ORDER, g2
    from harmony_tpu.ref.hash_to_curve import hash_to_g2
    from harmony_tpu.sync.staged import Downloader

    n_headers = int(os.environ.get("BENCH_REPLAY_HEADERS", "2048"))
    committee_n = int(os.environ.get("BENCH_REPLAY_COMMITTEE", "64"))
    DV.use_device(True)
    SC.reset()
    try:
        genesis, _, bls_keys = dev_genesis(n_accounts=2,
                                           n_keys=committee_n)
        chain_id = genesis.config.chain_id
        sk_sum = sum(k.scalar for k in bls_keys) % R_ORDER
        g2mul = NB.g2_mul if NB.available() else g2.mul
        mask = Mask([k.pub.point for k in bls_keys])
        for i in range(committee_n):
            mask.set_bit(i, True)
        bitmap = mask.mask_bytes()

        # -- fixture: a sealed source chain, serialized as the sync
        # wire would carry it (build phase, untimed) ------------------
        src = Blockchain(MemKV(), genesis, blocks_per_epoch=1 << 30)
        worker = Worker(src)
        blobs, hashes = [], []
        prev = None
        # the replay pass costs about as much as the build (same
        # execution work + the seal checks); keep a symmetric reserve
        build_stop = _t.monotonic() + (deadline - _t.monotonic()) / 2.5
        for i in range(n_headers):
            block = worker.propose_block(view_id=i + 1, timestamp=i + 1)
            if prev is not None:
                block.header.last_commit_sig = prev[:96]
                block.header.last_commit_bitmap = prev[96:]
            payload = construct_commit_payload(
                block.header.hash(), block.header.block_num,
                block.header.view_id, True,
            )
            proof = RB.sig_to_bytes(
                g2mul(hash_to_g2(payload), sk_sum)
            ) + bitmap
            src.insert_chain([block], commit_sigs=[proof],
                             verify_seals=False)
            blobs.append((rawdb.encode_header(block.header),
                          rawdb.encode_body(block, chain_id), proof))
            hashes.append(block.hash())
            prev = proof
            if _t.monotonic() > build_stop:
                break

        class _Feed:
            """SyncClient twin serving the serialized chain — the
            decode cost the real sync stream pays, minus the socket."""

            def get_head(self, deadline=None):
                return len(blobs), hashes[-1]

            def get_block_hashes(self, start, count, deadline=None):
                return hashes[start - 1:start - 1 + count]

            def get_blocks_by_number(self, start, count, deadline=None):
                out = []
                for hdr, body, sig in blobs[start - 1:start - 1 + count]:
                    header = rawdb.decode_header(hdr)
                    txs, stxs, cxs, order = rawdb.decode_body(body)
                    out.append(
                        (Block(header, txs, stxs, cxs, order), sig)
                    )
                return out

        # -- the timed replay -----------------------------------------
        ctx = EpochContext(list(genesis.committee))
        replica = Blockchain(
            MemKV(), genesis,
            engine=Engine(lambda s, e: ctx, device=True),
            blocks_per_epoch=1 << 30,
        )
        t0 = _t.perf_counter()
        res = Downloader(replica, [_Feed()], verify_seals=True).sync_once()
        dt = _t.perf_counter() - t0
        if res.errors or res.inserted != len(blobs):
            raise RuntimeError(
                f"replay incomplete: {res.inserted}/{len(blobs)} "
                f"{res.errors[:2]}"
            )
        return _m(
            round(res.inserted / dt, 2), "headers/s",
            mode="staged_sync_e2e", headers=res.inserted,
            committee_keys=committee_n,
            path="decode+lru+sched+verify+insert",
        )
    finally:
        SC.reset()
        DV.use_device(None)
        os.environ.pop("HARMONY_KERNEL_TWIN", None)


def _child_cpu_bigint(meta, deadline):
    """Honest fallback numbers from the host crypto path: the driver's
    TPU tunnel has been dead in every prior round; a labeled host
    measurement beats a traceback and gives optimization work a floor
    to compare against.  Since round 5 the host path is the native C++
    library (native/bls381.cpp) when it loads — the role herumi's mcl
    plays under the reference — with the bigint twin as last resort."""
    import time as _t

    from harmony_tpu.ref import bls as RB
    from harmony_tpu.ref import native as NB
    from harmony_tpu.ref import pairing as RP
    from harmony_tpu.ref.curve import G1_GEN, G2_GEN, g1, g2
    from harmony_tpu.ref.hash_to_curve import hash_to_g2

    native = NB.available()
    extra = _modeled_band()

    msg = b"bench-agg-verify-block-payload!!"
    h_pt = hash_to_g2(msg)
    # config #2 at BOTH the historic committee size and the stated
    # 1000-key target, so rounds stay comparable to BASELINE.md even
    # when the device is absent (VERDICT r3 #9).
    n_max = 1000
    sks = [RB.keygen(bytes([i % 251, i // 251])) for i in range(n_max)]
    pks = [RB.pubkey(sk) for sk in sks]
    # precomputed-h signing; twin g2.mul costs ~112 ms each, so the
    # fixture must ride the native path when it is loaded
    _g2mul = NB.g2_mul if native else g2.mul
    sigs = [_g2mul(h_pt, sk) for sk in sks]

    for n_keys, label in ((250, "agg_verify_p50_ms_host"),):
        try:
            lat = []
            for _ in range(3):
                t1 = _t.perf_counter()
                agg_sig = RB.aggregate_sigs(sigs[:n_keys])
                agg_pk = RB.aggregate_pubkeys(pks[:n_keys])
                assert RB.verify_hashed(agg_pk, h_pt, agg_sig)
                lat.append(_t.perf_counter() - t1)
                if _t.monotonic() > deadline:
                    break
            p50 = sorted(lat)[len(lat) // 2]
            extra[label] = _m(round(p50 * 1e3, 1), "ms", n_keys=n_keys)
        except Exception as e:  # noqa: BLE001
            meta["configs_failed"].append(
                f"agg_verify_host_{n_keys}: {e!r:.300}"
            )

    # the TRUE replay number (decode + LRU + scheduler + verify +
    # insert through sync/staged.py) — the 1/p50 derivation this key
    # used to carry is retired; the ledger reads the mode change as a
    # redefinition, not a regression
    try:
        extra["replay_headers_per_sec_host"] = _replay_bench_e2e(deadline)
    except Exception as e:  # noqa: BLE001
        meta["configs_failed"].append(f"replay_e2e: {e!r:.300}")

    # config #2 at the 1000-key target, measured THROUGH the
    # verification scheduler under concurrent replay load (ISSUE 5):
    # twin kernels force the device-path layers onto this host crypto,
    # a background thread streams 8-wide replay batches down the sync
    # lane, and the recorded p50 is the CONSENSUS lane's — with the
    # batch fill ratio alongside, so the round captures the
    # continuous-batching behavior (fill/latency), not just kernel
    # speed.  The old inline-1k number measured the same pairing with
    # no queue in front of it.
    try:
        import threading as _th

        os.environ["HARMONY_KERNEL_TWIN"] = "1"
        from harmony_tpu import device as DV
        from harmony_tpu import sched as SC
        from harmony_tpu.sched.scheduler import FILL as _FILL

        DV.use_device(True)
        try:
            table_1k = DV.CommitteeTable(pks)
            table_replay = DV.CommitteeTable(pks[:250])
            agg_1k = RB.aggregate_sigs(sigs)
            agg_replay = RB.aggregate_sigs(sigs[:250])
            bits_1k, bits_replay = [1] * n_max, [1] * 250
            items0, slots0 = _FILL["items"], _FILL["slots"]
            stop = _th.Event()

            def replay_load():
                while not stop.is_set() and _t.monotonic() < deadline:
                    futs = [
                        SC.scheduler().submit_agg(
                            table_replay, bits_replay, h_pt, agg_replay,
                            lane=SC.Lane.SYNC,
                        )
                        for _ in range(8)
                    ]
                    for f in futs:
                        try:
                            f.result(120)
                        except Exception:  # noqa: BLE001 — bench load
                            return

            loader = _th.Thread(target=replay_load, daemon=True)
            loader.start()
            lat = []
            for _ in range(7):
                t1 = _t.perf_counter()
                ok = SC.agg_verify(table_1k, bits_1k, msg, agg_1k,
                                   lane=SC.Lane.CONSENSUS)
                lat.append(_t.perf_counter() - t1)
                assert ok, "scheduled 1k agg_verify rejected a quorum!"
                if _t.monotonic() > deadline:
                    break
            stop.set()
            loader.join(timeout=30)
            # mode stamped on the metric (the measurement changed in
            # r06: through the scheduler, twin kernels, under replay
            # load — trend diffs must read a redefinition, not a
            # host-crypto regression)
            extra["agg_verify_p50_ms_host_1k"] = _m(
                round(sorted(lat)[len(lat) // 2] * 1e3, 1), "ms",
                n_keys=n_max, mode="sched_mixed_lane_twin",
            )
            d_items = _FILL["items"] - items0
            d_slots = _FILL["slots"] - slots0
            if d_slots:
                extra["sched_batch_fill_ratio"] = _m(
                    round(d_items / d_slots, 3), "ratio"
                )
            extra["sched_items_dispatched"] = _m(d_items, "items")
        finally:
            SC.reset()
            DV.use_device(None)
            os.environ.pop("HARMONY_KERNEL_TWIN", None)
    except Exception as e:  # noqa: BLE001
        meta["configs_failed"].append(f"agg_verify_sched_1k: {e!r:.300}")

    # primary: raw host pairing throughput (full pairing incl. final exp)
    if native:
        pairs = [
            (NB.g1_mul(G1_GEN, 3 + i), NB.g2_mul(G2_GEN, 5 + i))
            for i in range(16)
        ]
        for p, q in pairs[:4]:  # warm the library/page cache
            NB.multi_pairing([(p, q)])
        n = 0
        t0 = _t.perf_counter()
        while _t.perf_counter() - t0 < 3.0 and _t.monotonic() < deadline:
            p, q = pairs[n % len(pairs)]
            NB.multi_pairing([(p, q)])
            n += 1
        rate = n / (_t.perf_counter() - t0)
        # the replay shape shares one final exponentiation across the
        # product — record that Miller-loop-bound rate too
        t0 = _t.perf_counter()
        reps = 0
        while _t.perf_counter() - t0 < 2.0 and _t.monotonic() < deadline:
            NB.multi_pairing(pairs)
            reps += 1
        extra["pairing_product_pairs_per_sec"] = _m(
            round(reps * len(pairs) / (_t.perf_counter() - t0), 1),
            "pairs/s",
        )
    else:
        n = 6
        pairs = [
            (g1.mul(G1_GEN, 3 + i), g2.mul(G2_GEN, 5 + i)) for i in range(n)
        ]
        t0 = _t.perf_counter()
        for p, q in pairs:
            RP.pairing(p, q)
        rate = n / (_t.perf_counter() - t0)
    _emit(
        {
            "metric": PRIMARY,
            "value": round(rate, 2),
            "unit": "pairings/s",
            "vs_baseline": round(rate / TARGET_PAIRINGS_S, 6),
            "source": "measured",
            "extra": extra,
            "meta": meta,
        }
    )
    return 0


if __name__ == "__main__":
    if "--child" in sys.argv or os.environ.get("BENCH_CHILD") == "1":
        sys.exit(_child())
    sys.exit(main())
