"""Storage crash-point sweep: kill a block commit at every point,
reopen, machine-check the consistency invariants.

The durability gate of ISSUE 12 (check.sh stage 8).  Three sweeps, all
over REAL chain workloads (dev-genesis chain, worker-proposed blocks,
commit proofs stored, ``require_commit_sigs=True`` on reopen):

1. **Fault-point sweep** — ``FileKV.write_batch`` fires the
   ``kv.commit`` faultinject point before the BEGIN marker, before
   every record, and before the COMMIT marker.  For every point k the
   sweep arms a one-shot crash at k, inserts the next block, lets the
   injected crash kill the write, abandons the store un-closed (writes
   are unbuffered — exactly a SIGKILL's disk state), reopens the
   chain, and asserts: head rolled back to the pre-insert block with
   header + state + commit sig all present, and re-inserting the same
   block succeeds with NO manual repair.

2. **Byte-truncation sweep** — the same insert's on-disk extent is cut
   at every byte offset (stride configurable) into a copy; reopening
   must yield the pre-insert head (torn batch discarded by replay) at
   every offset except the full length (committed batch visible), and
   the store must accept the re-insert.

3. **Native parity** — every truncated copy from (2) is also opened
   with the C++ store (same on-disk format); its recovered head and
   head-record presence must agree with FileKV's verdict.

Every reported number is ledger-tagged ``source: measured`` and named
``crash_*`` so ``tools/bench_ledger.py --check`` gates them across
BENCH rounds.

Usage:
    python tools/crash_sweep.py                      # full sweep
    python tools/crash_sweep.py --check              # CI gate (stage 8)
    python tools/crash_sweep.py --stride 7 --blocks 2
    python tools/crash_sweep.py --check --bench-out BENCH.json
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import shutil
import sys
import tempfile
import time

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _build_chain(path: str, blocks: int):
    """A durable chain with ``blocks`` committed blocks, each carrying
    a stored commit proof (the consensus shape recovery requires)."""
    from harmony_tpu.core.blockchain import Blockchain
    from harmony_tpu.core.genesis import dev_genesis
    from harmony_tpu.core.kv import FileKV

    genesis, _, _ = dev_genesis()
    chain = Blockchain(FileKV(path), genesis, blocks_per_epoch=64,
                       require_commit_sigs=True)
    _grow(chain, blocks)
    return chain, genesis


def _proof_for(chain, block) -> bytes:
    committee = chain.committee_for_epoch(
        chain.epoch_of(block.block_num)
    )
    nbytes = (len(committee) + 7) >> 3
    return b"\x01" * 96 + b"\xff" * nbytes


def _grow(chain, blocks: int):
    from harmony_tpu.node.worker import Worker

    worker = Worker(chain, None)
    for _ in range(blocks):
        block = worker.propose_block(view_id=chain.head_number + 1)
        n = chain.insert_chain(
            [block], commit_sigs=[_proof_for(chain, block)],
            verify_seals=False,
        )
        if n != 1:
            raise RuntimeError(f"insert failed at {block.block_num}")


def _next_block(chain):
    from harmony_tpu.node.worker import Worker

    block = Worker(chain, None).propose_block(
        view_id=chain.head_number + 1
    )
    return block, _proof_for(chain, block)


def _reopen(path: str, genesis):
    from harmony_tpu.core.blockchain import Blockchain
    from harmony_tpu.core.kv import FileKV

    return Blockchain(FileKV(path), genesis, blocks_per_epoch=64,
                      require_commit_sigs=True)


def _assert_consistent(chain, want_head: int, tag: str, failures: list):
    """The reopen invariant: head == want_head with header, state and
    commit sig all present and bound (Blockchain.__init__ already
    verified state-root binding; this re-checks the read surface)."""
    from harmony_tpu.core import rawdb

    ok = True
    if chain.head_number != want_head:
        failures.append(f"{tag}: head {chain.head_number} != {want_head}")
        ok = False
    header = chain.current_header()
    if header is None:
        failures.append(f"{tag}: no header at recovered head")
        return False
    if rawdb.read_state(chain.db, header.root) is None:
        failures.append(f"{tag}: no state at recovered head")
        ok = False
    if want_head > 0 and chain.read_commit_sig(chain.head_number) is None:
        failures.append(f"{tag}: no commit sig at recovered head")
        ok = False
    return ok


def sweep_fault_points(workdir: str, blocks: int, failures: list):
    """Sweep 1: enumerate every kv.commit crash point of one block
    insert; kill at each, reopen, verify, re-insert."""
    from harmony_tpu import faultinject as FI

    base = os.path.join(workdir, "base.kv")
    chain, genesis = _build_chain(base, blocks)
    chain.db.close()

    # count the points: a sentinel rule that never fires arms the
    # registry so fire() counts hits during a dry-run insert
    dry = os.path.join(workdir, "dry.kv")
    shutil.copyfile(base, dry)
    FI.reset()
    FI.arm("kv.commit", key="__count_only__", after=10**9)
    chain = _reopen(dry, genesis)
    block, proof = _next_block(chain)
    before = FI.hits("kv.commit")
    chain.insert_chain([block], commit_sigs=[proof], verify_seals=False)
    points = FI.hits("kv.commit") - before
    chain.db.close()
    FI.reset()
    if points < 3:
        failures.append(f"fault-point sweep: only {points} crash "
                        "points enumerated (instrumentation broken?)")
        return 0

    for k in range(points):
        path = os.path.join(workdir, f"fp{k}.kv")
        shutil.copyfile(base, path)
        chain = _reopen(path, genesis)
        block, proof = _next_block(chain)
        FI.reset()
        FI.arm("kv.commit", key=path, after=k, times=1)
        crashed = False
        try:
            chain.insert_chain([block], commit_sigs=[proof],
                               verify_seals=False)
        except FI.FaultInjected:
            crashed = True
        except Exception as e:  # noqa: BLE001 — a different error IS
            # a finding: the commit path must only die at the armed
            # point, never wedge some other way
            failures.append(f"fault point {k}: unexpected {e!r}")
        FI.reset()
        if not crashed:
            failures.append(f"fault point {k}: crash never fired "
                            f"({points} points enumerated)")
        # abandon WITHOUT close: unbuffered writes = SIGKILL disk state
        reopened = _reopen(path, genesis)
        if _assert_consistent(reopened, blocks, f"fault point {k}",
                              failures):
            # zero manual repair: the same block must insert cleanly
            try:
                n = reopened.insert_chain(
                    [block], commit_sigs=[proof], verify_seals=False
                )
                if n != 1 or reopened.head_number != blocks + 1:
                    failures.append(
                        f"fault point {k}: re-insert after recovery "
                        f"landed {n} blocks (head "
                        f"{reopened.head_number})"
                    )
            except Exception as e:  # noqa: BLE001
                failures.append(f"fault point {k}: re-insert raised "
                                f"{e!r}")
        reopened.db.close()
        os.unlink(path)
    return points


def sweep_truncation(workdir: str, blocks: int, stride: int,
                     failures: list, native: bool):
    """Sweeps 2+3: cut the last block's on-disk extent at every byte
    offset; FileKV reopen must discard the torn batch, and the native
    store must agree."""
    from harmony_tpu.core import rawdb

    base = os.path.join(workdir, "tbase.kv")
    chain, genesis = _build_chain(base, blocks)
    size_before = os.path.getsize(base)
    block, proof = _next_block(chain)
    chain.insert_chain([block], commit_sigs=[proof], verify_seals=False)
    chain.db.close()
    size_after = os.path.getsize(base)

    native_kv = None
    if native:
        from harmony_tpu.core.kv_native import NativeKV, available

        if available():
            native_kv = NativeKV

    offsets = list(range(size_before, size_after, stride))
    offsets.append(size_after)  # the fully-committed extent
    swept = 0
    for off in offsets:
        path = os.path.join(workdir, "cut.kv")
        with open(base, "rb") as src, open(path, "wb") as dst:
            dst.write(src.read(off))
        want = blocks + 1 if off == size_after else blocks
        reopened = _reopen(path, genesis)
        tag = f"truncate@{off}"
        if _assert_consistent(reopened, want, tag, failures):
            if want == blocks:
                try:
                    n = reopened.insert_chain(
                        [block], commit_sigs=[proof], verify_seals=False
                    )
                    if n != 1:
                        failures.append(f"{tag}: re-insert landed {n}")
                except Exception as e:  # noqa: BLE001
                    failures.append(f"{tag}: re-insert raised {e!r}")
        reopened.db.close()

        if native_kv is not None:
            # parity: the C++ replay must reach the same verdict on
            # the SAME torn file (cut again — FileKV healed/extended
            # the first copy while recovering)
            with open(base, "rb") as src, open(path, "wb") as dst:
                dst.write(src.read(off))
            ndb = native_kv(path)
            nhead = rawdb.read_head_number(ndb)
            if nhead != want:
                failures.append(
                    f"{tag}: native head {nhead} != FileKV {want}"
                )
            elif rawdb.read_header(ndb, nhead) is None:
                failures.append(f"{tag}: native lost head header")
            ndb.close()
        os.unlink(path)
        swept += 1
    return swept


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="exit 1 if any crash point fails its "
                         "recovery invariant")
    ap.add_argument("--blocks", type=int, default=3,
                    help="committed blocks before the victim insert")
    ap.add_argument("--stride", type=int, default=1,
                    help="byte stride of the truncation sweep (1 = "
                         "every offset)")
    ap.add_argument("--no-native", action="store_true",
                    help="skip the native-store parity sweep")
    ap.add_argument("--bench-out", default=None,
                    help="write a BENCH round file carrying the sweep "
                         "metrics (ledger schema)")
    ap.add_argument("--bench-round", type=int, default=7)
    args = ap.parse_args(argv)

    t0 = time.monotonic()
    failures: list = []
    workdir = tempfile.mkdtemp(prefix="harmony-crash-sweep-")
    try:
        fp = sweep_fault_points(workdir, args.blocks, failures)
        tr = sweep_truncation(workdir, args.blocks, args.stride,
                              failures, native=not args.no_native)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    dur = time.monotonic() - t0

    total = fp + tr
    ok = total - len({f.split(":")[0] for f in failures})
    for f in failures:
        print(f"crash_sweep: FAIL {f}", file=sys.stderr, flush=True)
    print(
        f"crash_sweep: {total} crash points swept "
        f"({fp} fault-injection, {tr} byte-truncation incl. native "
        f"parity), {len(failures)} failure(s), {dur:.1f}s",
        file=sys.stderr, flush=True,
    )

    def _m(value, unit, **fields):
        out = {"value": value, "unit": unit, "source": "measured"}
        out.update(fields)
        return out

    extra = {
        "crash_points_swept": _m(total, "points", fault_points=fp,
                                 truncation_points=tr,
                                 stride=args.stride),
        "crash_recoveries_ok": _m(ok, "points", total=total),
        "crash_sweep_run_s": _m(round(dur, 2), "s"),
    }
    doc = {
        "metric": "crash_recoveries_ok",
        "value": ok,
        "unit": "points",
        "source": "measured",
        "extra": extra,
        "meta": {"blocks": args.blocks, "stride": args.stride,
                 "failures": failures[:50]},
    }
    print(json.dumps(doc), flush=True)
    if args.bench_out:
        with open(args.bench_out, "w") as f:
            json.dump({
                "n": args.bench_round,
                "cmd": "python tools/crash_sweep.py",
                "parsed": doc,
            }, f, indent=2)
            f.write("\n")
    return 1 if (args.check and failures) else 0


if __name__ == "__main__":
    sys.exit(main())
