"""AOT-lower the FUSED multi-chip quorum check to StableHLO (VERDICT r3 #7).

The fused ``sharded_agg_verify`` program (parallel/mesh.py) cannot
EXECUTE on this box — no real mesh, and the 8-virtual-device CPU compile
of a pairing-shaped program exceeds any budget (docs/NOTES_r3.md).  But
LOWERING is tracing + StableHLO emission — no LLVM, seconds — and the
emitted module carries every sharding annotation and collective the
partitioner will act on.  Checking the text into the repo and diffing it
in CI (tests/test_multichip_artifact.py) makes shape/sharding
regressions in parallel/mesh.py or the ops tier fail CI without needing
an n-chip mesh.

Run:  python tools/lower_multichip.py [--check]
  writes (or with --check, diffs against)
  tools/artifacts/sharded_agg_verify_8dev.stablehlo.txt
"""

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_DEV = 8
ARTIFACT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "artifacts",
    f"sharded_agg_verify_{N_DEV}dev.stablehlo.summary.txt",
)


def _normalize(text: str) -> str:
    """Strip volatile location/name noise so the diff is semantic."""
    text = re.sub(r"loc\([^)]*\)", "loc(-)", text)
    text = re.sub(r'#loc\d+ = .*', "", text)
    return text


def _summarize(text: str) -> str:
    """The semantically load-bearing skeleton of the 270k-line module
    (the full text is ~22 MB — too big to vendor): the public function
    signatures with their sharding attributes, every collective op with
    its shapes and replica groups, and a digest of the whole normalized
    module.  Any change to shapes, shardings, collective layout, or any
    op in the program flips at least one of these lines."""
    import hashlib

    lines = text.splitlines()
    keep = []
    for ln in lines:
        s = ln.strip()
        if s.startswith("func.func"):
            keep.append(s)
        elif "mhlo.sharding" in s and "func.func" not in s:
            # per-arg sharding attr lines inside signatures
            keep.append(s[:400])
        elif ("all_gather" in s or "all_reduce" in s
              or "collective" in s or "all_to_all" in s
              or "psum" in s or "reduce_scatter" in s):
            keep.append(s[:400])
    digest = hashlib.sha256(text.encode()).hexdigest()
    head = [
        f"# fused sharded_agg_verify lowering summary ({N_DEV} virtual devices)",
        f"# full normalized module: {len(lines)} lines, sha256 {digest}",
        f"# regenerate: python tools/lower_multichip.py",
    ]
    return "\n".join(head + keep) + "\n"


def lower_text() -> str:
    os.environ.setdefault(
        "XLA_FLAGS",
        f"--xla_force_host_platform_device_count={N_DEV}",
    )
    if "device_count" not in os.environ["XLA_FLAGS"]:
        os.environ["XLA_FLAGS"] += (
            f" --xla_force_host_platform_device_count={N_DEV}"
        )
    import jax

    # counter the axon sitecustomize (forces "axon,cpu"); a wedged TPU
    # tunnel must not hang a lowering that never executes anything
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from harmony_tpu.ops import interop as I
    from harmony_tpu.parallel import mesh as M
    from harmony_tpu.ref import bls as RB
    from harmony_tpu.ref.curve import g2
    from harmony_tpu.ref.hash_to_curve import hash_to_g2

    mesh = M.make_mesh(jax.devices()[:N_DEV])
    fn = M.sharded_agg_verify(mesh)

    # tiny fixture: 2 keys per device, exactly dryrun_multichip's shapes
    n_keys = 2 * N_DEV
    msg = b"aot-lowering-fixture-blockhash32"
    h = hash_to_g2(msg)
    sks = [RB.keygen(bytes([70 + i])) for i in range(n_keys)]
    pk_jac = jnp.asarray(
        np.stack(
            [I.g1_affine_to_jacobian_arr(RB.pubkey(sk)) for sk in sks]
        )
    )
    bitmap = jnp.ones(n_keys, dtype=jnp.int32)
    h_aff = jnp.asarray(I.g2_affine_to_arr(h))
    sig_aff = jnp.asarray(
        I.g2_affine_to_arr(g2.mul(h, 12345))  # any valid G2 point
    )
    lowered = fn.lower(pk_jac, bitmap, h_aff, sig_aff)
    return _summarize(_normalize(lowered.as_text()))


def main() -> int:
    text = lower_text()
    if "--check" in sys.argv:
        with open(ARTIFACT) as fh:
            want = fh.read()
        if text != want:
            import difflib

            diff = "\n".join(
                list(
                    difflib.unified_diff(
                        want.splitlines(),
                        text.splitlines(),
                        "checked-in",
                        "regenerated",
                        lineterm="",
                    )
                )[:120]
            )
            print(
                "STALE ARTIFACT: the fused multichip lowering changed.\n"
                "If intended, regenerate: python tools/lower_multichip.py\n"
                + diff
            )
            return 1
        print("artifact up to date")
        return 0
    os.makedirs(os.path.dirname(ARTIFACT), exist_ok=True)
    stale = os.path.join(
        os.path.dirname(ARTIFACT),
        f"sharded_agg_verify_{N_DEV}dev.stablehlo.txt",
    )
    if os.path.exists(stale):  # pre-summary full dump; don't vendor 22 MB
        os.remove(stale)
    with open(ARTIFACT, "w") as fh:
        fh.write(text)
    n_collectives = text.count("all_gather") + text.count("all_reduce")
    print(
        f"wrote {ARTIFACT}: {len(text.splitlines())} lines, "
        f"{n_collectives} collective op lines"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
