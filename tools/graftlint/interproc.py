"""Whole-program analysis: call graph, per-function summaries, and the
interprocedural rule families GL05/GL06/GL07.

The per-file rules (rules.py) see one AST at a time; the failure modes
here span files: a lock acquired in consensus while p2p holds the
reverse pair, a socket recv four calls below a ``with self._lock:``, a
device->host sync buried in a helper that a hot-path loop calls per
item.  This module builds

1. a **Program**: every target file parsed once, plus per-module import
   and class indexes;
2. a **FuncInfo summary** per function: locks acquired (and what was
   held at the time), blocking operations, host syncs, call sites with
   the held-lock set and loop depth at each;
3. a **call graph** over conservative static resolution: bare names,
   ``self.method`` (through single-module inheritance), imported
   modules/functions, and a unique-method fallback for foreign
   attributes (``chain.insert_chain`` resolves because exactly one
   class in the program defines ``insert_chain``);
4. transitive closures (which locks / blocking ops a call can reach)
   feeding three rule families:

GL05 — lock-order: every edge "held L1 while acquiring L2" (directly
or through calls) goes into one digraph; a cycle is a potential
deadlock, a non-reentrant self-edge is a guaranteed one.

GL06 — blocking-under-lock: holding any Lock/RLock/Condition while
(transitively) reaching socket I/O, ``Thread.join``, ``time.sleep``,
or device work (a pairing program dispatch / device->host sync).

GL07 — hot-path host-sync: a device->host sync (``np.asarray``,
``bool()``/``float()``/``int()``, ``.item()``, ...) on a device value
inside a loop, or a per-item loop call into a function that syncs —
the pattern that serializes the TPU where the batched verification
pipeline needs it streaming.

Lock identity is static: ``path::NAME`` for module-global locks,
``path::Class.attr`` for instance locks (the class that assigns the
attribute, resolved through in-program bases).  Distinct instances of
one class share a static lock — the analysis is per lock *site*, which
is what an ordering discipline is about.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path

from .rules import dotted_name

# ---------------------------------------------------------------------------
# summaries

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)
_LOCK_CTORS = {"Lock": False, "RLock": True, "Condition": False,
               "Semaphore": False, "BoundedSemaphore": False}

# methods too common for the unique-method fallback to trust
_COMMON_METHODS = {
    "get", "put", "add", "pop", "set", "close", "items", "keys",
    "values", "append", "extend", "update", "remove", "discard",
    "clear", "encode", "decode", "read", "write", "send", "start",
    "stop", "run", "join", "wait", "hash", "copy", "insert", "index",
    "count", "sort", "split", "strip", "format", "flush", "seek",
    "tell", "name", "value", "state", "expose", "allow", "drop",
}

_SLEEP_HEADS = {"time.sleep"}
_SOCKET_HEADS = {"socket.create_connection"}
_SOCKET_METHODS = {"sendall", "recv", "recv_into", "accept", "connect",
                   "makefile"}
_SYNC_HEADS = {"jax.device_get", "jax.block_until_ready"}
_NP_SYNC = {"np.asarray", "numpy.asarray", "np.array", "numpy.array"}
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_CAST_SYNCS = {"bool", "float", "int"}

# modules whose functions ARE device programs (calling one dispatches
# device work; its result is a device value).  interop/ref are host-side
# converters and deliberately NOT here.
_DEVICE_MODULES = ("harmony_tpu/ops/bls.py", "harmony_tpu/ops/twin.py")
# device.py factories returning device-program callables
_DEVICE_FACTORIES = {"_get_verify_fn", "_get_agg_verify_fn",
                     "_get_agg_verify_batch_fn"}
_JIT_HEADS = {"jax.jit", "jit", "jax.pmap", "pjit"}


@dataclass(frozen=True)
class CallSite:
    ref: tuple  # ("name", n) | ("self", m) | ("mod", path, n) | ("attr", m)
    line: int
    col: int
    holds: tuple  # lock ids held lexically at the call
    in_loop: bool


@dataclass(frozen=True)
class Op:
    desc: str  # stable human id, e.g. "socket recv", "np.asarray(ok)"
    kind: str  # "sleep" | "join" | "socket" | "device" | "sync"
    line: int
    col: int
    holds: tuple
    in_loop: bool
    on_device_value: bool = False


@dataclass(frozen=True)
class Acquire:
    lock: str
    line: int
    col: int
    holds: tuple  # locks already held when this one is taken


@dataclass
class FuncInfo:
    fid: str
    relpath: str
    qualname: str
    cls: str | None
    calls: list = field(default_factory=list)      # [CallSite]
    acquires: list = field(default_factory=list)   # [Acquire]
    ops: list = field(default_factory=list)        # [Op]
    has_device_call: bool = False


@dataclass
class ModuleInfo:
    relpath: str
    tree: ast.Module
    source: str
    # import name -> target module relpath (in-program only)
    mod_imports: dict = field(default_factory=dict)
    # from-import: local name -> (module relpath, original name)
    name_imports: dict = field(default_factory=dict)
    # module-global lock name -> lock id
    locks: dict = field(default_factory=dict)
    # class name -> {"bases": [...], "methods": {name: fid},
    #                "lock_attrs": {attr: lock_id}}
    classes: dict = field(default_factory=dict)
    # top-level function name -> fid
    functions: dict = field(default_factory=dict)


class Program:
    """All target files parsed + indexed, the call graph, and the
    transitive closures the interprocedural rules consume."""

    def __init__(self):
        self.modules: dict[str, ModuleInfo] = {}
        self.funcs: dict[str, FuncInfo] = {}
        self.lock_reentrant: dict[str, bool] = {}
        # attr name -> [lock ids] across every class (foreign-object
        # resolution: unique attr names resolve, ambiguous ones don't)
        self._lock_attr_index: dict[str, list] = {}
        # method name -> [fids] across every class
        self._method_index: dict[str, list] = {}
        self.call_edges: dict[str, set] = {}  # fid -> {callee fid}
        self.trans_acquires: dict[str, dict] = {}  # fid -> {lock: via}
        self.trans_blocking: dict[str, dict] = {}  # fid -> {desc: via}
        self.trans_syncs: dict[str, dict] = {}     # fid -> {desc: via}

    # -- loading ------------------------------------------------------------

    def add_module(self, relpath: str, source: str, tree: ast.Module):
        mi = ModuleInfo(relpath, tree, source)
        self.modules[relpath] = mi
        self._index_defs(mi)

    def finalize(self):
        # imports resolve against the COMPLETE module set, so indexing
        # them must wait until every file is added
        for mi in self.modules.values():
            self._index_imports(mi)
        self._resolve_inherited_locks()
        for mi in self.modules.values():
            for fid in list(mi.functions.values()):
                self._summarize(mi, fid)
            for cls in mi.classes.values():
                for fid in cls["methods"].values():
                    self._summarize(mi, fid)
        self._build_edges()
        self.trans_acquires = self._closure(
            lambda f: {a.lock: "" for a in f.acquires})
        self.trans_blocking = self._closure(
            lambda f: {o.desc: "" for o in f.ops
                       if o.kind in ("sleep", "join", "socket", "device")})
        self.trans_syncs = self._closure(
            lambda f: {o.desc: "" for o in f.ops
                       if o.kind == "sync" and o.on_device_value})

    # -- indexing -----------------------------------------------------------

    def _module_path_of(self, relpath: str, module: str,
                        level: int) -> str | None:
        """Resolve an import to an in-program module relpath."""
        if level:
            base = Path(relpath).parent
            for _ in range(level - 1):
                base = base.parent
            parts = list(base.parts) + (module.split(".") if module else [])
        else:
            parts = module.split(".")
        cand = "/".join(parts) + ".py"
        if cand in self.modules:
            return cand
        cand = "/".join(parts) + "/__init__.py"
        if cand in self.modules:
            return cand
        if not level:
            # flat absolute import between files linted from one
            # directory (fixture programs outside the repo package)
            sib = (Path(relpath).parent / ("/".join(parts) + ".py"))
            sib = sib.as_posix()
            if sib in self.modules:
                return sib
        return None

    def _index_imports(self, mi: ModuleInfo):
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    target = self._module_path_of(mi.relpath, a.name, 0)
                    mi.mod_imports[a.asname or a.name.split(".")[0]] = (
                        target or a.name
                    )
            elif isinstance(node, ast.ImportFrom):
                modpath = self._module_path_of(
                    mi.relpath, node.module or "", node.level)
                for a in node.names:
                    local = a.asname or a.name
                    # ``from ..pkg import mod`` binds a MODULE: try the
                    # dotted submodule path before treating it as a name
                    sub = self._module_path_of(
                        mi.relpath,
                        ".".join(p for p in (node.module, a.name) if p),
                        node.level)
                    if sub is not None:
                        mi.mod_imports[local] = sub
                    elif modpath is not None:
                        mi.name_imports[local] = (modpath, a.name)

    def _index_defs(self, mi: ModuleInfo):
        for node in mi.tree.body:
            if isinstance(node, ast.Assign) and _lock_ctor_kind(node.value):
                reentrant = _lock_ctor_kind(node.value) == "RLock"
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        lid = f"{mi.relpath}::{tgt.id}"
                        mi.locks[tgt.id] = lid
                        self.lock_reentrant[lid] = reentrant
            elif isinstance(node, _FuncDef):
                fid = f"{mi.relpath}::{node.name}"
                mi.functions[node.name] = fid
                self.funcs[fid] = FuncInfo(fid, mi.relpath, node.name, None)
                self.funcs[fid].node = node
            elif isinstance(node, ast.ClassDef):
                self._index_class(mi, node)

    def _index_class(self, mi: ModuleInfo, node: ast.ClassDef):
        info = {"bases": [dotted_name(b) for b in node.bases],
                "methods": {}, "lock_attrs": {}}
        mi.classes[node.name] = info
        for item in node.body:
            if not isinstance(item, _FuncDef):
                continue
            fid = f"{mi.relpath}::{node.name}.{item.name}"
            info["methods"][item.name] = fid
            fi = FuncInfo(fid, mi.relpath, f"{node.name}.{item.name}",
                          node.name)
            fi.node = item
            self.funcs[fid] = fi
            self._method_index.setdefault(item.name, []).append(fid)
            for sub in ast.walk(item):
                if isinstance(sub, ast.Assign):
                    kind = _lock_ctor_kind(sub.value)
                    if not kind:
                        continue
                    for tgt in sub.targets:
                        attr = _self_attr(tgt)
                        if attr:
                            lid = f"{mi.relpath}::{node.name}.{attr}"
                            info["lock_attrs"][attr] = lid
                            self.lock_reentrant[lid] = kind == "RLock"
                            self._lock_attr_index.setdefault(
                                attr, []).append(lid)

    def _resolve_inherited_locks(self):
        """A subclass using ``self._lock`` assigned by an in-program
        base shares the base's lock id (Host/TCPHost)."""
        for mi in self.modules.values():
            for cname, info in mi.classes.items():
                for base in info["bases"]:
                    binfo = self._find_class(mi, base)
                    if binfo is None:
                        continue
                    for attr, lid in binfo["lock_attrs"].items():
                        info["lock_attrs"].setdefault(attr, lid)
                    for m, fid in binfo["methods"].items():
                        info["methods"].setdefault(m, fid)

    def _find_class(self, mi: ModuleInfo, name: str | None):
        if not name:
            return None
        name = name.split(".")[-1]
        if name in mi.classes:
            return mi.classes[name]
        for imp, (modpath, orig) in mi.name_imports.items():
            if imp == name and modpath in self.modules:
                return self.modules[modpath].classes.get(orig)
        for other in self.modules.values():
            if name in other.classes:
                return other.classes[name]
        return None

    # -- per-function summary ----------------------------------------------

    def _summarize(self, mi: ModuleInfo, fid: str):
        fi = self.funcs[fid]
        fn = fi.node
        cls = mi.classes.get(fi.cls) if fi.cls else None
        lock_attrs = cls["lock_attrs"] if cls else {}
        device_fns, device_vals, thread_names = _local_dataflow(
            fn, mi, self)

        def lock_of(expr: ast.AST) -> str | None:
            """Static lock id of a with-item / acquire target."""
            if isinstance(expr, ast.Name):
                return mi.locks.get(expr.id)
            attr = _self_attr(expr)
            if attr is not None:
                return lock_attrs.get(attr)
            # foreign object: obj.attr resolves iff the attr names a
            # lock in exactly one in-program class (chain._insert_lock)
            if isinstance(expr, ast.Attribute):
                cands = self._lock_attr_index.get(expr.attr, [])
                if len(cands) == 1:
                    return cands[0]
            return None

        def walk(node: ast.AST, holds: tuple, loop: int):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _FuncDef):
                    continue  # nested defs run in their own context
                if isinstance(child, ast.With):
                    # items acquire left-to-right: `with A, B:` is an
                    # A->B edge, so each item's Acquire must see the
                    # locks of the items before it, not just the outer
                    # holds
                    cur = holds
                    for item in child.items:
                        g = lock_of(item.context_expr)
                        if g is None:
                            continue
                        fi.acquires.append(Acquire(
                            g, child.lineno, child.col_offset, cur))
                        if g not in cur:
                            cur = cur + (g,)
                    walk(child, cur, loop)
                    continue
                in_loop = loop > 0
                if isinstance(child, ast.Call):
                    self._classify_call(
                        mi, fi, child, holds, in_loop or isinstance(
                            node, (ast.GeneratorExp, ast.ListComp,
                                   ast.SetComp, ast.DictComp)),
                        device_fns, device_vals, thread_names)
                next_loop = loop + (1 if isinstance(
                    child, (ast.For, ast.AsyncFor, ast.While,
                            ast.GeneratorExp, ast.ListComp, ast.SetComp,
                            ast.DictComp)) else 0)
                walk(child, holds, next_loop)

        walk(fn, (), 0)

    def _classify_call(self, mi, fi, node: ast.Call, holds, in_loop,
                       device_fns, device_vals, thread_names):
        head = dotted_name(node.func)
        line, col = node.lineno, node.col_offset

        def op(desc, kind, dev=False):
            fi.ops.append(Op(desc, kind, line, col, holds, in_loop, dev))

        arg_is_device = any(
            isinstance(a, ast.Name) and a.id in device_vals
            or _is_device_call(a, mi, self, device_fns)
            for a in node.args
        )
        # blocking / sync primitives
        if head in _SLEEP_HEADS:
            op("time.sleep", "sleep")
        elif head in _SOCKET_HEADS:
            op("socket connect", "socket")
        elif head in _SYNC_HEADS:
            op(head, "sync", dev=True)
        elif head in _NP_SYNC and arg_is_device:
            op(f"{head} on device value", "sync", dev=True)
        elif head in _CAST_SYNCS and arg_is_device:
            op(f"{head}() on device value", "sync", dev=True)
        elif isinstance(node.func, ast.Attribute):
            meth = node.func.attr
            base = node.func.value
            if meth in _SOCKET_METHODS:
                op(f"socket {meth}", "socket")
            elif meth == "join" and isinstance(base, ast.Name) \
                    and base.id in thread_names:
                op("Thread.join", "join")
            elif meth in _SYNC_METHODS and (
                    isinstance(base, ast.Name) and base.id in device_vals
                    or _is_device_call(base, mi, self, device_fns)):
                op(f".{meth}() on device value", "sync", dev=True)

        # device program dispatch (pairing work: seconds on CPU)
        if _is_device_call(node, mi, self, device_fns):
            fi.has_device_call = True
            op(f"device program {head or '<fn>'}()", "device", dev=True)

        # call-graph edge candidates
        ref = self._call_ref(mi, fi, node)
        if ref is not None:
            fi.calls.append(CallSite(ref, line, col, holds, in_loop))

    def _call_ref(self, mi, fi, node: ast.Call):
        f = node.func
        if isinstance(f, ast.Name):
            return ("name", f.id)
        if isinstance(f, ast.Attribute):
            if _self_attr(f) is not None:
                return ("self", f.attr)
            base = dotted_name(f.value)
            if base and base in mi.mod_imports:
                return ("mod", mi.mod_imports[base], f.attr)
            return ("attr", f.attr)
        return None

    # -- call graph ---------------------------------------------------------

    def resolve(self, fi: FuncInfo, ref: tuple) -> list:
        kind = ref[0]
        mi = self.modules[fi.relpath]
        if kind == "name":
            name = ref[1]
            if name in mi.functions:
                return [mi.functions[name]]
            if name in mi.name_imports:
                modpath, orig = mi.name_imports[name]
                target = self.modules.get(modpath)
                if target and orig in target.functions:
                    return [target.functions[orig]]
            return []
        if kind == "self":
            cls = mi.classes.get(fi.cls) if fi.cls else None
            if cls and ref[1] in cls["methods"]:
                return [cls["methods"][ref[1]]]
            return []
        if kind == "mod":
            target = self.modules.get(ref[1])
            if target:
                if ref[2] in target.functions:
                    return [target.functions[ref[2]]]
            return []
        if kind == "attr":
            meth = ref[1]
            if meth in _COMMON_METHODS or len(meth) <= 3:
                return []
            cands = self._method_index.get(meth, [])
            return cands if len(cands) == 1 else []
        return []

    def _build_edges(self):
        for fid, fi in self.funcs.items():
            out = self.call_edges.setdefault(fid, set())
            for cs in fi.calls:
                out.update(self.resolve(fi, cs.ref))

    def _closure(self, direct) -> dict:
        """fid -> {fact: via-chain}; facts flow callee -> caller.  The
        via-chain names one witness path to the fact.  Iteration is
        fully sorted so the chosen witness is deterministic run-to-run
        (witnesses are display-only, but nondeterministic output churns
        diffs and confuses users)."""
        facts = {fid: dict(direct(fi)) for fid, fi in self.funcs.items()}
        changed = True
        rounds = 0
        while changed and rounds < 50:
            changed = False
            rounds += 1
            for fid in sorted(self.call_edges):
                mine = facts[fid]
                for c in sorted(self.call_edges[fid]):
                    if c == fid:
                        continue
                    for fact, via in sorted(facts.get(c, {}).items()):
                        if fact not in mine:
                            mine[fact] = _short(c) + (
                                f" -> {via}" if via else "")
                            changed = True
        return facts


def _short(fid: str) -> str:
    path, qn = fid.split("::", 1)
    return f"{Path(path).name}:{qn}"


def short_lock(lid: str) -> str:
    path, name = lid.split("::", 1)
    return f"{Path(path).name}:{name}"


def _lock_ctor_kind(node: ast.AST) -> str | None:
    if not isinstance(node, ast.Call):
        return None
    d = dotted_name(node.func)
    if d and d.split(".")[-1] in _LOCK_CTORS:
        return d.split(".")[-1]
    return None


def _self_attr(node: ast.AST) -> str | None:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _is_device_head(head: str | None, mi: ModuleInfo,
                    prog: Program) -> bool:
    """Does this dotted call/attr head denote a device program?"""
    if not head:
        return False
    if head in _JIT_HEADS or head in _DEVICE_FACTORIES:
        return True
    if head == "aot.load" or head.endswith(".aot.load"):
        return True
    root = head.split(".")[0]
    target = mi.mod_imports.get(root)
    if target in _DEVICE_MODULES:
        return True
    if root in mi.name_imports:
        modpath, orig = mi.name_imports[root]
        if modpath in _DEVICE_MODULES:
            return True
        if modpath and modpath.endswith("device.py") \
                and orig in _DEVICE_FACTORIES:
            return True
    return False


def _is_device_call(node: ast.AST, mi: ModuleInfo, prog: Program,
                    device_fns: set) -> bool:
    """A Call that dispatches a device program."""
    if not isinstance(node, ast.Call):
        return False
    head = dotted_name(node.func)
    if head and head in device_fns:
        return True
    return _is_device_head(head, mi, prog)


def _local_dataflow(fn, mi: ModuleInfo, prog: Program):
    """(device_fns, device_vals, thread_names): names bound in this
    function to device callables, device values, and Thread objects."""
    device_fns: set[str] = set()
    device_vals: set[str] = set()
    threads: set[str] = set()

    def value_classes(expr) -> tuple[bool, bool, bool]:
        """(is_device_fn, is_device_val, is_thread) for an RHS."""
        if isinstance(expr, ast.IfExp):
            a = value_classes(expr.body)
            b = value_classes(expr.orelse)
            return tuple(x or y for x, y in zip(a, b))
        if isinstance(expr, ast.Call):
            head = dotted_name(expr.func)
            if head and head.split(".")[-1] == "Thread":
                return (False, False, True)
            if _is_device_head(head, mi, prog):
                # jit()/factory() returns a device callable; a device
                # module op call returns a device value
                root = head.split(".")[0] if head else ""
                factoryish = (head in _JIT_HEADS
                              or head in _DEVICE_FACTORIES
                              or (root in mi.name_imports
                                  and mi.name_imports[root][1]
                                  in _DEVICE_FACTORIES)
                              or (head or "").endswith("aot.load"))
                return (factoryish, not factoryish, False)
            if head and head in device_fns:
                return (False, True, False)
            return (False, False, False)
        head = dotted_name(expr) if isinstance(
            expr, (ast.Attribute, ast.Name)) else None
        if head and _is_device_head(head, mi, prog):
            return (True, False, False)  # fn = OB.agg_verify
        if isinstance(expr, ast.Name) and expr.id in device_vals:
            return (False, True, False)
        return (False, False, False)

    # two passes so `fn = ...; ok = fn(...)` resolves regardless of
    # statement order quirks
    for _ in range(2):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            is_fn, is_val, is_thr = value_classes(node.value)
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    if is_fn:
                        device_fns.add(tgt.id)
                    if is_val:
                        device_vals.add(tgt.id)
                    if is_thr:
                        threads.add(tgt.id)
    return device_fns, device_vals, threads


# ---------------------------------------------------------------------------
# GL05 — lock-order cycles


@dataclass(frozen=True)
class SiteFinding:
    """An interprocedural finding bound to a file.  ``detail`` carries
    the witness call chain — display-only, never fingerprinted."""
    relpath: str
    rule: str
    line: int
    col: int
    message: str
    context: str
    detail: str = ""


def gl05_findings(prog: Program) -> list[SiteFinding]:
    out = []
    edges: dict[tuple, tuple] = {}
    for fid, fi in prog.funcs.items():
        for a in fi.acquires:
            for held in a.holds:
                edges.setdefault((held, a.lock), (
                    fi.relpath, a.line, a.col, fi.qualname, ""))
        for cs in fi.calls:
            if not cs.holds:
                continue
            for callee in prog.resolve(fi, cs.ref):
                for lock, via in prog.trans_acquires.get(
                        callee, {}).items():
                    for held in cs.holds:
                        chain = _short(callee) + (
                            f" -> {via}" if via else "")
                        edges.setdefault((held, lock), (
                            fi.relpath, cs.line, cs.col, fi.qualname,
                            chain))

    for (a, b), (path, line, col, ctx, via) in sorted(edges.items()):
        if a == b and not prog.lock_reentrant.get(a, False):
            out.append(SiteFinding(
                path, "GL05", line, col,
                f"non-reentrant {short_lock(a)} re-acquired while "
                "held (self-deadlock)", ctx, via))

    adj: dict[str, set] = {}
    for (a, b) in edges:
        if a != b:
            adj.setdefault(a, set()).add(b)

    def reaches(src: str, dst: str) -> bool:
        seen, stack = set(), [src]
        while stack:
            cur = stack.pop()
            if cur == dst:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(adj.get(cur, ()))
        return False

    for (a, b), (path, line, col, ctx, via) in sorted(edges.items()):
        if a == b:
            continue
        if reaches(b, a):
            msg = (f"lock-order cycle: {short_lock(a)} -> "
                   f"{short_lock(b)} closes a reverse path "
                   "(potential deadlock)")
        else:
            # acyclic but UNDECLARED: every nested acquisition must be
            # reviewed once — the committed baseline is the declared
            # lock-order registry, and a cycle can only ever enter the
            # tree through a new edge, so new edges gate the PR
            msg = (f"lock-order edge {short_lock(a)} -> "
                   f"{short_lock(b)} (undeclared nested acquisition: "
                   "shrink the critical section, or pin after review)")
        out.append(SiteFinding(path, "GL05", line, col, msg, ctx, via))
    return out


# ---------------------------------------------------------------------------
# GL06 — blocking work under a lock


def gl06_findings(prog: Program) -> list[SiteFinding]:
    out = []
    for fid, fi in prog.funcs.items():
        for o in fi.ops:
            if o.kind in ("sleep", "join", "socket", "device") \
                    and o.holds:
                lock = short_lock(o.holds[-1])
                out.append(SiteFinding(
                    fi.relpath, "GL06", o.line, o.col,
                    f"{o.desc} while holding {lock}", fi.qualname))
        for cs in fi.calls:
            if not cs.holds:
                continue
            for callee in prog.resolve(fi, cs.ref):
                blocked = prog.trans_blocking.get(callee, {})
                if not blocked:
                    continue
                desc = sorted(blocked)[0]
                lock = short_lock(cs.holds[-1])
                # the witness callee goes in detail ONLY: fingerprints
                # must survive rerouting the same defect through a
                # different first-hop helper
                chain = _short(callee)
                if blocked[desc]:
                    chain += f" -> {blocked[desc]}"
                out.append(SiteFinding(
                    fi.relpath, "GL06", cs.line, cs.col,
                    f"call reaches {desc} while holding {lock}",
                    fi.qualname, chain))
    return out


# ---------------------------------------------------------------------------
# GL07 — hot-path host syncs


def _hot(fi: FuncInfo) -> bool:
    return (fi.relpath == "harmony_tpu/device.py"
            or fi.relpath.startswith("harmony_tpu/ops/")
            or fi.has_device_call)


def gl07_findings(prog: Program) -> list[SiteFinding]:
    out = []
    for fid, fi in prog.funcs.items():
        if not _hot(fi):
            continue
        for o in fi.ops:
            if o.kind == "sync" and o.on_device_value and o.in_loop:
                out.append(SiteFinding(
                    fi.relpath, "GL07", o.line, o.col,
                    f"per-item host sync {o.desc} inside a loop "
                    "(serializes the device pipeline; hoist it)",
                    fi.qualname))
        for cs in fi.calls:
            if not cs.in_loop:
                continue
            for callee in prog.resolve(fi, cs.ref):
                syncs = prog.trans_syncs.get(callee, {})
                if not syncs:
                    continue
                desc = sorted(syncs)[0]
                out.append(SiteFinding(
                    fi.relpath, "GL07", cs.line, cs.col,
                    f"loop calls {_short(callee)} which host-syncs "
                    f"({desc}); batch across iterations",
                    fi.qualname))
    return out


# ---------------------------------------------------------------------------
# GL08 — unbounded blocking calls (no timeout ever set)

_GL08_BLOCKING = {"connect", "recv", "recv_into"}
_GL08_URLOPEN = {"urlopen", "urllib.request.urlopen", "request.urlopen"}


def _is_none_const(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and node.value is None


def _gl08_sock_ctor(node: ast.AST) -> str | None:
    """'plain' for socket.socket(...), 'bounded'/'unbounded' for
    create_connection with/without a timeout, else None."""
    if not isinstance(node, ast.Call):
        return None
    head = dotted_name(node.func)
    if head in ("socket.socket", "socket"):
        return "plain"
    if head and head.split(".")[-1] == "create_connection":
        has_timeout = len(node.args) >= 2 or any(
            k.arg == "timeout" and not _is_none_const(k.value)
            for k in node.keywords
        )
        return "bounded" if has_timeout else "unbounded"
    return None


def _gl08_settimeout_target(node: ast.AST) -> ast.AST | None:
    """The receiver of a real ``settimeout`` call (None arg = blocking
    mode, which does NOT count as a timeout)."""
    if (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "settimeout"
            and node.args and not _is_none_const(node.args[0])):
        return node.func.value
    return None


def _gl08_class_attrs(cls_node: ast.ClassDef) -> tuple[set, set]:
    """(created socket attrs, timeout-bounded attrs) for ``self.X``
    sockets, scanned across EVERY method — a timeout set in __init__
    bounds the recv in a sibling method (that cross-method view is why
    this rule lives in the whole-program pass)."""
    created: set[str] = set()
    bounded: set[str] = set()
    for node in ast.walk(cls_node):
        if isinstance(node, ast.Assign):
            kind = _gl08_sock_ctor(node.value)
            if kind:
                for tgt in node.targets:
                    a = _self_attr(tgt)
                    if a:
                        created.add(a)
                        if kind == "bounded":
                            bounded.add(a)
        else:
            tgt = _gl08_settimeout_target(node)
            if tgt is not None:
                a = _self_attr(tgt)
                if a:
                    bounded.add(a)
    return created, bounded


def _gl08_local_sockets(fn: ast.AST) -> tuple[set, set]:
    """(created local socket names, timeout-bounded names) within one
    function body."""
    created: set[str] = set()
    bounded: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            kind = _gl08_sock_ctor(node.value)
            if kind:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        created.add(tgt.id)
                        if kind == "bounded":
                            bounded.add(tgt.id)
        else:
            tgt = _gl08_settimeout_target(node)
            if isinstance(tgt, ast.Name):
                bounded.add(tgt.id)
    return created, bounded


def _gl08_param_flow(prog: Program) -> tuple[dict, dict]:
    """(blocking params, params list) per fid.  A param index is
    *blocking* when the function recv/connects on it (without setting
    a timeout itself) or passes it positionally into a callee whose
    matching param is blocking — the transitive closure that makes
    ``read_frame(self._sock)`` light up at the call site."""
    params_of: dict[str, list] = {}
    blocking: dict[str, set] = {}
    edges: list[tuple] = []  # (caller fid, caller idx, callee fid, callee idx)
    for fid, fi in prog.funcs.items():
        fn = fi.node
        names = [a.arg for a in (fn.args.posonlyargs + fn.args.args)]
        params_of[fid] = names
        blocking[fid] = set()
    for fid in sorted(prog.funcs):
        fi = prog.funcs[fid]
        fn = fi.node
        names = params_of[fid]
        mi = prog.modules[fi.relpath]
        bounded: set[int] = set()
        for node in ast.walk(fn):
            tgt = _gl08_settimeout_target(node)
            if isinstance(tgt, ast.Name) and tgt.id in names:
                bounded.add(names.index(tgt.id))
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _GL08_BLOCKING
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in names):
                i = names.index(node.func.value.id)
                if i not in bounded:
                    blocking[fid].add(i)
            ref = prog._call_ref(mi, fi, node)
            if ref is None:
                continue
            callees = [c for c in prog.resolve(fi, ref)
                       if c in prog.funcs]
            for callee in callees:
                offset = 1 if prog.funcs[callee].cls else 0
                for ai, arg in enumerate(node.args):
                    if isinstance(arg, ast.Name) and arg.id in names:
                        i = names.index(arg.id)
                        if i not in bounded:
                            edges.append((fid, i, callee, ai + offset))
    changed = True
    rounds = 0
    while changed and rounds < 50:
        changed = False
        rounds += 1
        for caller, ci, callee, pi in edges:
            if pi in blocking.get(callee, ()) \
                    and ci not in blocking[caller]:
                blocking[caller].add(ci)
                changed = True
    return blocking, params_of


def gl08_findings(prog: Program) -> list[SiteFinding]:
    out = []
    param_blocking, params_of = _gl08_param_flow(prog)
    attr_info: dict[tuple, tuple] = {}
    for relpath, mi in prog.modules.items():
        for node in mi.tree.body:
            if isinstance(node, ast.ClassDef):
                attr_info[(relpath, node.name)] = _gl08_class_attrs(node)
    for fid in sorted(prog.funcs):
        fi = prog.funcs[fid]
        fn = fi.node
        mi = prog.modules[fi.relpath]
        created_a, bounded_a = attr_info.get(
            (fi.relpath, fi.cls), (set(), set()))
        created_l, bounded_l = _gl08_local_sockets(fn)

        def render_unbounded(expr: ast.AST) -> str | None:
            a = _self_attr(expr)
            if a is not None:
                if a in created_a and a not in bounded_a:
                    return f"self.{a}"
                return None
            if isinstance(expr, ast.Name):
                if expr.id in created_l and expr.id not in bounded_l:
                    return expr.id
            return None

        def emit(node, message):
            out.append(SiteFinding(
                fi.relpath, "GL08", node.lineno, node.col_offset,
                message, fi.qualname))

        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            head = dotted_name(node.func)
            if head in _GL08_URLOPEN:
                has_timeout = len(node.args) >= 3 or any(
                    k.arg == "timeout" and not _is_none_const(k.value)
                    for k in node.keywords)
                if not has_timeout:
                    emit(node, "urlopen without a timeout (hangs "
                               "forever on a stalled endpoint)")
                continue
            if _gl08_sock_ctor(node) == "unbounded":
                emit(node, "create_connection without a timeout "
                           "(blocking dial can hang forever)")
                continue
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _GL08_BLOCKING):
                name = render_unbounded(node.func.value)
                if name:
                    emit(node, f"socket {node.func.attr} on {name} "
                               "with no timeout ever set")
                continue
            ref = prog._call_ref(mi, fi, node)
            if ref is None:
                continue
            for callee in sorted(prog.resolve(fi, ref)):
                cfi = prog.funcs.get(callee)
                if cfi is None:
                    continue
                offset = 1 if cfi.cls else 0
                blocked = param_blocking.get(callee, set())
                for ai, arg in enumerate(node.args):
                    name = render_unbounded(arg)
                    if name and (ai + offset) in blocked:
                        emit(node, f"timeout-less socket {name} passed "
                                   f"into {_short(callee)} (reaches "
                                   "blocking socket I/O)")
    return out


# ---------------------------------------------------------------------------
# DOT dump


def to_dot(prog: Program) -> str:
    lines = ["digraph graftlint_callgraph {"]
    for fid in sorted(prog.call_edges):
        for callee in sorted(prog.call_edges[fid]):
            lines.append(f'  "{_short(fid)}" -> "{_short(callee)}";')
    lines.append("}")
    return "\n".join(lines) + "\n"


def analyze(sources: dict[str, tuple[str, ast.Module]]) -> Program:
    """Build + finalize a Program from {relpath: (source, tree)}."""
    prog = Program()
    for relpath, (source, tree) in sources.items():
        prog.add_module(relpath, source, tree)
    prog.finalize()
    return prog
