"""The per-file graftlint rule families (GL01-GL04), over the stdlib
AST.  The interprocedural families (GL05-GL07) live in interproc.py.

Each rule is a function ``(tree: ast.Module, relpath: str) -> list[RawFinding]``
— pure syntax, no imports of the linted code, so the linter runs in
milliseconds per file and can never be wedged by a broken module.

Rule ids are stable API: baselines and inline suppressions refer to
them.  Messages deliberately contain the offending *names* but never
line numbers, so a finding's fingerprint survives unrelated edits.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass


@dataclass(frozen=True)
class RawFinding:
    rule: str
    line: int
    col: int
    message: str
    context: str  # innermost enclosing function qualname, or "<module>"


# ---------------------------------------------------------------------------
# shared helpers


def dotted_name(node: ast.AST) -> str | None:
    """'jax.lax.scan' for nested Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _qualnames(tree: ast.Module) -> dict[int, str]:
    """Map id(def-node) -> dotted qualname for every function/class."""
    out: dict[int, str] = {}

    def walk(node: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                qn = f"{prefix}.{child.name}" if prefix else child.name
                out[id(child)] = qn
                walk(child, qn)
            else:
                walk(child, prefix)

    walk(tree, "")
    return out


def _enclosing_map(tree: ast.Module) -> dict[int, str]:
    """Map id(any node) -> qualname of innermost enclosing function."""
    qn = _qualnames(tree)
    out: dict[int, str] = {}

    def walk(node: ast.AST, ctx: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(child, qn[id(child)])
            else:
                out[id(child)] = ctx
                walk(child, ctx)

    walk(tree, "<module>")
    return out


_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)


# ---------------------------------------------------------------------------
# GL01 — jit purity

# decorators that make a function traced
_TRACE_DECOS = {
    "jax.jit", "jit", "jax.pmap", "pmap", "jax.shard_map", "shard_map",
    "pjit", "jax.experimental.pjit.pjit", "jax.vmap", "vmap",
}
_PARTIAL = {"functools.partial", "partial"}
# call heads whose function-valued args become traced
_TRACE_CALLERS = {
    "jax.jit", "jax.pmap", "jax.shard_map", "jax.vmap", "pjit",
    "pl.pallas_call", "pallas_call", "pltpu.pallas_call",
    "jax.lax.scan", "lax.scan", "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.fori_loop", "lax.fori_loop", "jax.lax.cond", "lax.cond",
    "jax.lax.switch", "lax.switch", "jax.lax.map", "lax.map",
    "jax.lax.associative_scan", "lax.associative_scan",
    "jax.checkpoint", "jax.remat",
}

_IMPURE_EXACT = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.sleep",
    "datetime.datetime.now", "datetime.datetime.utcnow", "datetime.now",
    "os.urandom", "uuid.uuid4", "open", "input",
}
_IMPURE_PREFIXES = (
    "random.", "np.random.", "numpy.random.", "secrets.",
)
_HOST_SYNC = {
    "np.asarray", "numpy.asarray", "np.array", "numpy.array",
    "jax.device_get", "jax.block_until_ready",
}
_HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}


def _is_trace_decorator(deco: ast.AST) -> bool:
    d = dotted_name(deco)
    if d in _TRACE_DECOS:
        return True
    if isinstance(deco, ast.Call):
        head = dotted_name(deco.func)
        if head in _TRACE_DECOS:
            return True  # e.g. @jax.jit(donate_argnums=0) style
        if head in _PARTIAL and deco.args:
            return dotted_name(deco.args[0]) in _TRACE_DECOS
    return False


def _collect_traced_defs(tree: ast.Module) -> list[ast.AST]:
    defs_by_name: dict[str, list[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, _FuncDef):
            defs_by_name.setdefault(node.name, []).append(node)

    traced: dict[int, ast.AST] = {}

    def mark(fn: ast.AST):
        if id(fn) in traced:
            return
        traced[id(fn)] = fn
        for inner in ast.walk(fn):
            if inner is not fn and isinstance(inner, _FuncDef):
                traced.setdefault(id(inner), inner)

    for node in ast.walk(tree):
        if isinstance(node, _FuncDef):
            if any(_is_trace_decorator(d) for d in node.decorator_list):
                mark(node)
        elif isinstance(node, ast.Call):
            if dotted_name(node.func) in _TRACE_CALLERS:
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        for fn in defs_by_name.get(arg.id, ()):
                            mark(fn)
    return list(traced.values())


def _local_bindings(fn: ast.AST) -> set[str]:
    """Names bound inside this function (params + stores), shallow —
    nested defs keep their own scope."""
    names: set[str] = set()
    args = fn.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs):
        names.add(a.arg)
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)

    def walk(node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FuncDef):
                names.add(child.name)
                continue
            if isinstance(child, ast.Name) and isinstance(
                    child.ctx, (ast.Store, ast.Del)):
                names.add(child.id)
            walk(child)

    walk(fn)
    return names


def check_gl01(tree: ast.Module, relpath: str) -> list[RawFinding]:
    enclosing = _enclosing_map(tree)
    findings: set[RawFinding] = set()

    def emit(node: ast.AST, message: str):
        findings.add(RawFinding(
            "GL01", node.lineno, node.col_offset, message,
            enclosing.get(id(node), "<module>"),
        ))

    for fn in _collect_traced_defs(tree):
        local = _local_bindings(fn)

        def walk(node: ast.AST):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _FuncDef) and child is not node:
                    continue  # nested defs are traced roots themselves
                check(child)
                walk(child)

        def check(node: ast.AST):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name == "print":
                    emit(node, "print() in traced function")
                elif name in _IMPURE_EXACT or (
                        name and name.startswith(_IMPURE_PREFIXES)):
                    emit(node, f"impure call {name}() in traced function")
                elif name in _HOST_SYNC:
                    emit(node, f"host sync {name}() in traced function")
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr in _HOST_SYNC_METHODS
                      and not node.args and not node.keywords):
                    emit(node, f".{node.func.attr}() host sync in "
                               "traced function")
            elif isinstance(node, ast.Global):
                emit(node, "global statement in traced function")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    for leaf in _store_leaves(tgt):
                        if isinstance(leaf, ast.Attribute):
                            d = dotted_name(leaf) or leaf.attr
                            emit(node, f"mutation of attribute {d} in "
                                       "traced function")
                        elif isinstance(leaf, ast.Subscript):
                            base = dotted_name(leaf.value)
                            if (isinstance(leaf.value, ast.Name)
                                    and leaf.value.id not in local):
                                emit(node, "subscript store to non-local "
                                           f"{base!r} in traced function")
                            elif isinstance(leaf.value, ast.Attribute):
                                emit(node, "subscript store to attribute "
                                           f"{base or '?'} in traced "
                                           "function")
            elif isinstance(node, ast.Delete):
                for tgt in node.targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                        emit(node, "del of shared state in traced function")

        walk(fn)
    return sorted(findings, key=lambda f: (f.line, f.col, f.message))


def _store_leaves(tgt: ast.AST):
    """Flatten tuple/list targets to the stored leaves."""
    if isinstance(tgt, (ast.Tuple, ast.List)):
        for elt in tgt.elts:
            yield from _store_leaves(elt)
    else:
        yield tgt


# ---------------------------------------------------------------------------
# GL02 — limb-dtype discipline

_JNP_ARRAY = {"jnp.array", "jnp.asarray", "jax.numpy.array",
              "jax.numpy.asarray"}
_JNP_FACTORY = {"jnp.zeros", "jnp.ones", "jnp.full", "jnp.empty",
                "jnp.arange", "jax.numpy.zeros", "jax.numpy.ones",
                "jax.numpy.full", "jax.numpy.empty", "jax.numpy.arange"}
_JNP_WHERE = {"jnp.where", "jax.numpy.where"}


def _is_literalish(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float, complex, bool))
    if isinstance(node, (ast.List, ast.Tuple)):
        return all(_is_literalish(e) for e in node.elts)
    if isinstance(node, (ast.ListComp, ast.GeneratorExp)):
        return True
    if isinstance(node, ast.UnaryOp):
        return _is_literalish(node.operand)
    if isinstance(node, ast.BinOp):
        return _is_literalish(node.left) and _is_literalish(node.right)
    return False


def check_gl02(tree: ast.Module, relpath: str) -> list[RawFinding]:
    enclosing = _enclosing_map(tree)
    findings: list[RawFinding] = []

    # calls immediately consumed by .astype(...) are dtype-disciplined
    astype_wrapped: set[int] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype"):
            astype_wrapped.add(id(node.func.value))

    def emit(node: ast.AST, message: str):
        findings.append(RawFinding(
            "GL02", node.lineno, node.col_offset, message,
            enclosing.get(id(node), "<module>"),
        ))

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            has_dtype = any(k.arg == "dtype" for k in node.keywords)
            if name in _JNP_ARRAY and not has_dtype and node.args:
                if _is_literalish(node.args[0]):
                    emit(node, f"untyped {name}() over Python literals "
                               "(weak dtype promotes in limb math)")
            elif name in _JNP_FACTORY and not has_dtype:
                emit(node, f"{name}() without explicit dtype "
                           "(defaults leak into limb math)")
            elif (name in _JNP_WHERE and len(node.args) == 3
                  and id(node) not in astype_wrapped):
                if any(isinstance(a, ast.Constant)
                       and isinstance(a.value, (int, float))
                       and not isinstance(a.value, bool)
                       for a in node.args[1:3]):
                    emit(node, "weak-typed numeric literal in jnp.where "
                               "(add .astype(...) or a typed constant)")
        elif (isinstance(node, ast.Constant)
              and isinstance(node.value, float)):
            emit(node, f"float literal {node.value!r} in integer limb "
                       "module")
    return sorted(findings, key=lambda f: (f.line, f.col, f.message))


# ---------------------------------------------------------------------------
# GL03 — lock discipline

_LOCK_FACTORIES = ("Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore")
_MUTATORS = {
    "append", "appendleft", "add", "remove", "discard", "pop", "popleft",
    "popitem", "clear", "update", "extend", "insert", "setdefault",
}
_CTOR_NAMES = {"__init__", "__new__", "__post_init__", "__init_subclass__"}


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = dotted_name(node.func)
    return bool(d) and d.split(".")[-1] in _LOCK_FACTORIES


def _self_attr(node: ast.AST) -> str | None:
    """'x' for ``self.x`` nodes, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def check_gl03(tree: ast.Module, relpath: str) -> list[RawFinding]:
    enclosing = _enclosing_map(tree)
    findings: list[RawFinding] = []

    def emit(node: ast.AST, message: str):
        findings.append(RawFinding(
            "GL03", node.lineno, node.col_offset, message,
            enclosing.get(id(node), "<module>"),
        ))

    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        _check_class(cls, emit)
    _check_module_globals(tree, emit)
    _check_module_containers(tree, emit)
    return sorted(findings, key=lambda f: (f.line, f.col, f.message))


def _with_lock_items(node: ast.With, lock_attrs: set[str]) -> bool:
    for item in node.items:
        a = _self_attr(item.context_expr)
        if a in lock_attrs:
            return True
    return False


def _check_class(cls: ast.ClassDef, emit):
    methods = [n for n in cls.body if isinstance(n, _FuncDef)]

    # 1. lock attributes: self.X = threading.Lock()/RLock()/Condition()
    lock_attrs: set[str] = set()
    for m in methods:
        for node in ast.walk(m):
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for tgt in node.targets:
                    a = _self_attr(tgt)
                    if a:
                        lock_attrs.add(a)
    if not lock_attrs:
        return

    # 2. guarded attrs: every self.Y *written or mutated* lexically
    #    under a ``with self.<lock>:`` anywhere in the class.  Reads
    #    under a lock are deliberately NOT enough to mark an attribute
    #    guarded — incidental reads inside a critical section (method
    #    calls, internally-synchronized members) would drown the signal.
    guarded: dict[str, str] = {}  # attr -> lock attr that guards it

    def note_guarded(child: ast.AST, lock: str):
        if isinstance(child, (ast.Assign, ast.AugAssign)):
            targets = (child.targets if isinstance(child, ast.Assign)
                       else [child.target])
            for tgt in targets:
                for leaf in _store_leaves(tgt):
                    a = _self_attr(leaf)
                    if a is None and isinstance(leaf, ast.Subscript):
                        a = _self_attr(leaf.value)
                    if a and a not in lock_attrs:
                        guarded.setdefault(a, lock)
        elif (isinstance(child, ast.Call)
              and isinstance(child.func, ast.Attribute)
              and child.func.attr in _MUTATORS):
            a = _self_attr(child.func.value)
            if a and a not in lock_attrs:
                guarded.setdefault(a, lock)

    def scan_guarded(node: ast.AST, lock: str | None):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.With):
                hit = None
                for item in child.items:
                    a = _self_attr(item.context_expr)
                    if a in lock_attrs:
                        hit = a
                scan_guarded(child, hit or lock)
                continue
            if lock is not None:
                note_guarded(child, lock)
            scan_guarded(child, lock)

    for m in methods:
        scan_guarded(m, None)
    if not guarded:
        return

    # 3. thread targets: methods handed to threading.Thread(target=...)
    thread_targets: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Call):
            d = dotted_name(node.func)
            if d and d.split(".")[-1] == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        a = _self_attr(kw.value)
                        if a:
                            thread_targets.add(a)

    # 4. flag unguarded writes (and reads inside thread targets)
    flagged: set[int] = set()

    def scan_unguarded(node: ast.AST, in_lock: bool, is_target: bool):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.With):
                scan_unguarded(
                    child,
                    in_lock or _with_lock_items(child, lock_attrs),
                    is_target,
                )
                continue
            if isinstance(child, _FuncDef):
                # nested def (e.g. a thread body defined inline): its
                # execution context is unknown — treat as outside lock
                scan_unguarded(child, False, is_target)
                continue
            if not in_lock:
                _flag_unguarded(child, guarded, is_target, emit, flagged)
            scan_unguarded(child, in_lock, is_target)

    for m in methods:
        if m.name in _CTOR_NAMES:
            continue
        scan_unguarded(m, False, m.name in thread_targets)


def _flag_unguarded(node: ast.AST, guarded: dict[str, str],
                    is_target: bool, emit, flagged: set[int]):
    if id(node) in flagged:
        return
    if isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for tgt in targets:
            for leaf in _store_leaves(tgt):
                a = _self_attr(leaf)
                if a in guarded:
                    flagged.add(id(node))
                    emit(node, f"write to self.{a} outside "
                               f"self.{guarded[a]} (lock-guarded "
                               "elsewhere)")
                elif (isinstance(leaf, ast.Subscript)):
                    a = _self_attr(leaf.value)
                    if a in guarded:
                        flagged.add(id(node))
                        emit(node, f"subscript store to self.{a}[...] "
                                   f"outside self.{guarded[a]} "
                                   "(lock-guarded elsewhere)")
    elif isinstance(node, ast.Call):
        if isinstance(node.func, ast.Attribute):
            a = _self_attr(node.func.value)
            if a in guarded and node.func.attr in _MUTATORS:
                flagged.add(id(node))
                emit(node, f"mutating call self.{a}.{node.func.attr}() "
                           f"outside self.{guarded[a]} (lock-guarded "
                           "elsewhere)")
    elif (is_target and isinstance(node, ast.Attribute)
          and isinstance(node.ctx, ast.Load)):
        a = _self_attr(node)
        if a in guarded:
            flagged.add(id(node))
            emit(node, f"read of lock-guarded self.{a} in thread target "
                       f"without self.{guarded[a]}")


def _check_module_globals(tree: ast.Module, emit):
    # module-level lock names: _LOCK = threading.Lock()
    locks: set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    locks.add(tgt.id)
    if not locks:
        return

    def with_has_lock(node: ast.With) -> str | None:
        for item in node.items:
            if (isinstance(item.context_expr, ast.Name)
                    and item.context_expr.id in locks):
                return item.context_expr.id
        return None

    # globals written under a module lock anywhere
    guarded: dict[str, str] = {}

    def scan(node: ast.AST, lock: str | None):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.With):
                scan(child, with_has_lock(child) or lock)
                continue
            if (lock is not None and isinstance(child, ast.Name)
                    and isinstance(child.ctx, ast.Store)):
                guarded.setdefault(child.id, lock)
            scan(child, lock)

    for node in tree.body:
        if isinstance(node, _FuncDef):
            scan(node, None)
    if not guarded:
        return

    # writes to guarded globals outside any with-lock, in functions that
    # DECLARE them global (module-level init assignments are fine).
    # Each function is visited standalone (ast.walk below), so nested
    # defs are skipped here — their `global` declarations must not leak
    # into the enclosing scope, where the same name may be a local.
    def scan_out(node: ast.AST, in_lock: bool, global_names: set[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.With):
                scan_out(child,
                         in_lock or with_has_lock(child) is not None,
                         global_names)
                continue
            if isinstance(child, _FuncDef):
                continue  # own scope; visited via ast.walk below
            if (not in_lock and isinstance(child, ast.Name)
                    and isinstance(child.ctx, ast.Store)
                    and child.id in guarded
                    and child.id in global_names):
                emit(child, f"write to module global {child.id} outside "
                            f"{guarded[child.id]} (lock-guarded "
                            "elsewhere)")
            scan_out(child, in_lock, global_names)

    def own_globals(fn: ast.AST) -> set[str]:
        """`global` names declared in this function body, nested defs
        excluded (they are their own scope)."""
        names: set[str] = set()

        def walk(node: ast.AST):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _FuncDef):
                    continue
                if isinstance(child, ast.Global):
                    names.update(child.names)
                walk(child)

        walk(fn)
        return names

    for node in ast.walk(tree):
        if isinstance(node, _FuncDef):
            scan_out(node, False, own_globals(node))


_CONTAINER_CTORS = {
    "dict", "list", "set", "OrderedDict", "collections.OrderedDict",
    "defaultdict", "collections.defaultdict", "deque",
    "collections.deque", "Counter", "collections.Counter",
}


def _imports_threading(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name.split(".")[0] == "threading"
                   for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.module.split(".")[0] == "threading":
                return True
    return False


def _check_module_containers(tree: ast.Module, emit):
    """Shared module-level dict/list/set mutated inside functions with
    no lock held at all — the ``COUNTERS[...] += 1`` class of race.
    Only fires in modules that use threading (otherwise there is no
    concurrency to race with)."""
    if not _imports_threading(tree):
        return

    locks: set[str] = set()
    containers: set[str] = set()
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        if _is_lock_ctor(value):
            locks.update(t.id for t in targets if isinstance(t, ast.Name))
        elif (isinstance(value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                                 ast.ListComp, ast.SetComp))
              or (isinstance(value, ast.Call)
                  and dotted_name(value.func) in _CONTAINER_CTORS)):
            containers.update(
                t.id for t in targets if isinstance(t, ast.Name)
            )
    if not containers:
        return

    def with_has_lock(node: ast.With) -> bool:
        return any(
            isinstance(i.context_expr, ast.Name)
            and i.context_expr.id in locks
            for i in node.items
        )

    def base_container(node: ast.AST, local: set[str]) -> str | None:
        if (isinstance(node, ast.Name) and node.id in containers
                and node.id not in local):
            return node.id
        return None

    def scan(node: ast.AST, in_lock: bool, local: set[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.With):
                scan(child, in_lock or with_has_lock(child), local)
                continue
            if isinstance(child, _FuncDef):
                continue  # visited on its own via ast.walk below
            if not in_lock:
                if isinstance(child, ast.AugAssign) and isinstance(
                        child.target, ast.Subscript):
                    name = base_container(child.target.value, local)
                    if name:
                        emit(child, "non-atomic augmented write to "
                                    f"shared module container {name}[...]"
                                    " without a lock")
                elif isinstance(child, ast.Assign):
                    for tgt in child.targets:
                        for leaf in _store_leaves(tgt):
                            if isinstance(leaf, ast.Subscript):
                                name = base_container(leaf.value, local)
                                if name:
                                    emit(child, "write to shared module "
                                                f"container {name}[...] "
                                                "without a lock")
                elif (isinstance(child, ast.Call)
                      and isinstance(child.func, ast.Attribute)
                      and child.func.attr in _MUTATORS):
                    name = base_container(child.func.value, local)
                    if name:
                        emit(child, "mutating call "
                                    f"{name}.{child.func.attr}() without "
                                    "a lock")
            scan(child, in_lock, local)

    for node in ast.walk(tree):
        if isinstance(node, _FuncDef):
            scan(node, False, _local_bindings(node))


# ---------------------------------------------------------------------------
# GL04 — silent-failure hygiene


def check_gl04(tree: ast.Module, relpath: str) -> list[RawFinding]:
    enclosing = _enclosing_map(tree)
    findings: list[RawFinding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            findings.append(RawFinding(
                "GL04", node.lineno, node.col_offset,
                "bare except: swallows everything incl. KeyboardInterrupt"
                " (use a typed except + log)",
                enclosing.get(id(node), "<module>"),
            ))
        elif (dotted_name(node.type) in ("Exception", "BaseException")
              and all(isinstance(s, ast.Pass) for s in node.body)):
            findings.append(RawFinding(
                "GL04", node.lineno, node.col_offset,
                f"except {dotted_name(node.type)}: pass silences failures"
                " (use a typed except + log)",
                enclosing.get(id(node), "<module>"),
            ))
    return sorted(findings, key=lambda f: (f.line, f.col, f.message))


ALL_RULES = {
    "GL01": check_gl01,
    "GL02": check_gl02,
    "GL03": check_gl03,
    "GL04": check_gl04,
}
