"""Thread-role & trust-boundary pass: GL12/GL13/GL14.

The three most expensive bug classes of the robustness arc were found
*dynamically* — chaos caught ``Mask.aggregate_public`` compiling XLA on
the consensus pump thread (a ~90 s wedge of every validator), the wire
fuzzer forced hand-hardening of every length-prefixed decoder after a
forged count turned into a 4-billion-iteration loop, and the watchdog
only protects threads that remembered to register a Heartbeat.  This
module turns each convention into a checked invariant:

GL12 — dispatch discipline over a **thread-role-annotated call graph**.
Spawn sites declare their thread's role with an inline annotation on
the ``threading.Thread(...)`` call::

    t = threading.Thread(  # graftlint: thread-role=consensus.pump
        target=loop, daemon=True)

From every annotated spawn the pass BFS-reaches over an *extended*
call graph (interproc.Program's edges plus nested ``def``s, which the
main graph deliberately skips) and flags, outside the sanctioned
dispatch layer (device.py / aot.py / ops/ / sched/ / parallel/):

- a jax compile/dispatch head (``jax.jit``, ``jnp.*``, a device-module
  op, an AOT load, a device.py factory) reachable on a
  **latency-critical** role — the exact aggregate_public wedge class.
  Work routed through ``device._guarded`` lives in nested ``dispatch()``
  closures that are *passed*, never called, so the guarded path is
  naturally invisible to the reachability — only inline device work
  lights up;
- an ``ops.*`` device excursion reachable on ANY role — under
  ``HARMONY_KERNEL_TWIN=1`` jax is UNLOADED by contract, so a thread
  touching the ops layer directly crashes exactly when the twin
  config is exercised;
- unbounded blocking (``.wait()`` / ``.join()`` with no timeout)
  reachable on a latency-critical role.

GL13 — wire-taint budgets.  Intra-procedural taint from trust-boundary
decode sources (``int.from_bytes``, ``struct.unpack*``, a Reader's
``.int_()``) to loop bounds (``range``), allocations (``bytes``/
``bytearray``), and size multiplications.  A taint is sanitized by a
*dominating* (earlier, same function) comparison naming it inside an
``if``/``assert`` test — the remaining-budget idiom every hardened
decoder uses — or by a clean rebind through ``min()`` / a Reader's
``.checked_count()``.  Scope (engine._rule_applies): the trust-boundary
modules only — consensus/messages.py, consensus/view_change.py,
p2p/stream.py, sidecar/protocol.py, staking/slash.py, core/rawdb.py,
core/types.py.

GL14 — watchdog coverage.  Every spawned **long-lived** loop (the
resolved thread target's own body contains a ``while``) must declare a
thread-role, and — where the role's policy demands it — register a
``health.Heartbeat`` (at the spawn site, anywhere in the spawning
class, or in the loop itself) and transitively reach ``beat()`` /
``idle()`` from its body.  ``transient`` declares a bounded lifetime
(scenario drivers, per-connection handlers that loop); ``serving``
and ``watchdog`` are heartbeat-exempt by policy (the serving plane is
covered by readiness probes; the watchdog cannot watch itself).

All findings are SiteFindings: witness call chains ride in ``detail``
(display-only), fingerprints stay line-free, and the baseline / inline
pins / SARIF / cache plumbing applies unchanged.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from .interproc import (
    _COMMON_METHODS,
    _FuncDef,
    Program,
    SiteFinding,
    _self_attr,
    _short,
)
from .rules import dotted_name

# -- role registry -----------------------------------------------------------

# role -> policy.  latency_critical: the thread sits on the consensus
# critical path and must never compile, dispatch or block unboundedly.
# heartbeat: the PR-14 watchdog contract applies (register + beat/idle).
ROLE_POLICY = {
    "consensus.pump":   {"latency_critical": True,  "heartbeat": True},
    "sched.flush":      {"latency_critical": True,  "heartbeat": True},
    "sidecar.reader":   {"latency_critical": False, "heartbeat": True},
    "governor.sampler": {"latency_critical": False, "heartbeat": True},
    "netem.scheduler":  {"latency_critical": False, "heartbeat": True},
    "obs.sink":         {"latency_critical": False, "heartbeat": True},
    "watchdog":         {"latency_critical": False, "heartbeat": False},
    # the union label for the general serving plane (rpc, metrics,
    # explorer, discovery, accept loops): long-lived but off the
    # consensus critical path; covered by /readyz, not per-thread beats
    "serving":          {"latency_critical": False, "heartbeat": False},
    # declared bounded lifetime: joined by a scenario / request scope
    "transient":        {"latency_critical": False, "heartbeat": False},
}

_ROLE_RE = re.compile(r"graftlint:\s*thread-role=([A-Za-z0-9_.\-]+)")

# the sanctioned device-dispatch layer: these files ARE the guarded
# path (plus the kernel programs themselves and the submission layer)
_SANCTIONED_FILES = {"harmony_tpu/device.py", "harmony_tpu/aot.py"}
_SANCTIONED_PREFIXES = (
    "harmony_tpu/ops/", "harmony_tpu/sched/", "harmony_tpu/parallel/",
)


def _sanctioned(relpath: str) -> bool:
    return (relpath in _SANCTIONED_FILES
            or relpath.startswith(_SANCTIONED_PREFIXES))


# -- extended function index (nested defs included) --------------------------


@dataclass
class XFunc:
    """One function *or nested def* with the facts GL12/GL14 consume."""
    fid: str
    relpath: str
    qualname: str
    cls: str | None
    node: ast.AST
    parent: "XFunc | None"
    nested: dict = field(default_factory=dict)   # name -> fid
    edges: set = field(default_factory=set)      # callee fids
    while_lines: list = field(default_factory=list)
    # (line, col, desc, clause) — clause "compile" | "ops"
    device_ops: list = field(default_factory=list)
    blocking: list = field(default_factory=list)  # (line, col, desc)
    beats: bool = False
    registers: bool = False
    spawns: list = field(default_factory=list)   # [ast.Call]


class _Index:
    """interproc.Program's call graph, extended with nested defs (the
    main graph skips them on purpose — its lock/holds semantics are
    lexical — but a thread *target* is usually a nested ``loop()``)."""

    def __init__(self, prog: Program):
        self.prog = prog
        self.funcs: dict[str, XFunc] = {}
        for relpath in sorted(prog.modules):
            mi = prog.modules[relpath]
            for node in mi.tree.body:
                if isinstance(node, _FuncDef):
                    self._add(mi, node, node.name, None, None)
                elif isinstance(node, ast.ClassDef):
                    for item in node.body:
                        if isinstance(item, _FuncDef):
                            self._add(mi, item,
                                      f"{node.name}.{item.name}",
                                      node.name, None)
        # a class "registers a heartbeat" when any of its methods does
        # (start() registers, _revive() respawns — same participant)
        self._class_registers: set = set()
        for xf in self.funcs.values():
            if xf.registers and xf.cls:
                self._class_registers.add((xf.relpath, xf.cls))

    def _add(self, mi, node, qual, cls, parent):
        fid = f"{mi.relpath}::{qual}"
        xf = XFunc(fid, mi.relpath, qual, cls, node, parent)
        self.funcs[fid] = xf
        if parent is not None:
            parent.nested[node.name] = fid
        for child in _own_nodes(node):
            if isinstance(child, ast.While):
                xf.while_lines.append(child.lineno)
            elif isinstance(child, ast.Call):
                self._classify(mi, xf, child)
        for d in _child_defs(node):
            self._add(mi, d, f"{qual}.<locals>.{d.name}", cls, xf)
        # edges resolve lazily (nested siblings must be indexed first)

    def finalize(self):
        for xf in self.funcs.values():
            mi = self.prog.modules[xf.relpath]
            for node in _own_nodes(xf.node):
                if isinstance(node, ast.Call):
                    xf.edges.update(self._resolve_call(mi, xf, node))

    # -- per-call classification -------------------------------------------

    def _classify(self, mi, xf: XFunc, node: ast.Call):
        head = dotted_name(node.func)
        if head and head.split(".")[-1] == "Thread":
            if any(k.arg == "target" for k in node.keywords):
                xf.spawns.append(node)
        if _is_health_register(head, mi):
            xf.registers = True
        if isinstance(node.func, ast.Attribute):
            if node.func.attr in ("beat", "idle"):
                xf.beats = True
            if node.func.attr in ("wait", "join") \
                    and not node.args and not node.keywords:
                xf.blocking.append((
                    node.lineno, node.col_offset,
                    f"unbounded .{node.func.attr}()"))
        clause = _device_clause(head, mi, self.prog)
        if clause:
            xf.device_ops.append((
                node.lineno, node.col_offset, head, clause))

    # -- resolution ----------------------------------------------------------

    def _resolve_call(self, mi, xf: XFunc, node: ast.Call) -> list:
        f = node.func
        if isinstance(f, ast.Name):
            return self._resolve_name(mi, xf, f.id)
        if isinstance(f, ast.Attribute):
            if _self_attr(f) is not None and xf.cls:
                cls = mi.classes.get(xf.cls)
                if cls and f.attr in cls["methods"]:
                    return [cls["methods"][f.attr]]
                return []
            base = dotted_name(f.value)
            if base and base in mi.mod_imports:
                tgt = self.prog.modules.get(mi.mod_imports[base])
                if tgt and f.attr in tgt.functions:
                    return [tgt.functions[f.attr]]
                return []
            meth = f.attr
            if meth in _COMMON_METHODS or len(meth) <= 3:
                return []
            cands = self.prog._method_index.get(meth, [])
            return list(cands) if len(cands) == 1 else []
        return []

    def _resolve_name(self, mi, xf: XFunc, name: str) -> list:
        p = xf
        while p is not None:  # lexical chain: own + enclosing nesteds
            if name in p.nested:
                return [p.nested[name]]
            p = p.parent
        if name in mi.functions:
            return [mi.functions[name]]
        if name in mi.name_imports:
            modpath, orig = mi.name_imports[name]
            tgt = self.prog.modules.get(modpath)
            if tgt and orig in tgt.functions:
                return [tgt.functions[orig]]
        return []

    def resolve_target(self, mi, xf: XFunc, expr) -> str | None:
        """The thread target's fid, or None (stdlib serve_forever,
        bound methods of foreign objects, lambdas: not analyzable)."""
        if isinstance(expr, ast.Name):
            got = self._resolve_name(mi, xf, expr.id)
            return got[0] if got else None
        if isinstance(expr, ast.Attribute) and _self_attr(expr) \
                is not None and xf.cls:
            cls = mi.classes.get(xf.cls)
            if cls and expr.attr in cls["methods"]:
                return cls["methods"][expr.attr]
        return None

    def reach(self, start: str) -> dict[str, str]:
        """fid -> witness chain ("" for the start) via BFS."""
        chains = {start: ""}
        queue = [start]
        while queue:
            cur = queue.pop(0)
            xf = self.funcs.get(cur)
            if xf is None:
                continue
            base = chains[cur]
            for nxt in sorted(xf.edges):
                if nxt in chains or nxt not in self.funcs:
                    continue
                chains[nxt] = (base + " -> " if base else "") \
                    + _short(nxt)
                queue.append(nxt)
        return chains

    def spawner_registers(self, xf: XFunc) -> bool:
        p = xf
        while p is not None:
            if p.registers:
                return True
            p = p.parent
        return (xf.relpath, xf.cls) in self._class_registers \
            if xf.cls else False


def _own_nodes(fn):
    """Every AST node of ``fn``'s body, nested defs excluded (they are
    their own XFuncs)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, _FuncDef) or isinstance(n, ast.ClassDef):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _child_defs(fn):
    """Defs nested directly under ``fn`` (inside ifs/trys included,
    inside deeper defs excluded)."""
    out = []
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, _FuncDef):
            out.append(n)
            continue
        if isinstance(n, ast.ClassDef):
            continue
        stack.extend(ast.iter_child_nodes(n))
    return sorted(out, key=lambda d: d.lineno)


def _is_health_register(head: str | None, mi) -> bool:
    if not head:
        return False
    parts = head.split(".")
    if parts[-1] != "register":
        return False
    if len(parts) == 1:
        tgt = mi.name_imports.get("register")
        return bool(tgt and str(tgt[0]).endswith("health.py"))
    root = parts[0]
    if root == "health":
        return True
    tgt = mi.mod_imports.get(root)
    return isinstance(tgt, str) and tgt.endswith("health.py")


def _device_clause(head: str | None, mi, prog) -> str | None:
    """"compile" for a jax compile/dispatch head, "ops" for a call into
    an ops device module (interop.py excluded: host-side converters)."""
    if not head:
        return None
    from .interproc import _is_device_head

    root = head.split(".")[0]
    if root in ("jnp",) or _is_device_head(head, mi, prog):
        return "compile"
    tgt = mi.mod_imports.get(root)
    if not isinstance(tgt, str) and root in mi.name_imports:
        tgt = mi.name_imports[root][0]
    if isinstance(tgt, str):
        # unresolved imports fall back to the dotted module NAME
        # (single-file lint can't see sibling files): normalize both
        norm = tgt if tgt.endswith(".py") \
            else tgt.replace(".", "/") + ".py"
        if norm.startswith("harmony_tpu/ops/") \
                and not norm.endswith("interop.py"):
            return "ops"
    return None


# -- roles at spawn sites ----------------------------------------------------


def _role_annotations(source: str) -> dict[int, str]:
    out = {}
    for lineno, line in enumerate(source.splitlines(), start=1):
        m = _ROLE_RE.search(line)
        if m:
            out[lineno] = m.group(1)
    return out


def _spawn_role(spawn: ast.Call, roles: dict[int, str]) -> str | None:
    end = getattr(spawn, "end_lineno", spawn.lineno) or spawn.lineno
    for ln in range(spawn.lineno, end + 1):
        if ln in roles:
            return roles[ln]
    return None


# -- GL12 + GL14 -------------------------------------------------------------


def _gl12_gl14(prog: Program) -> list[SiteFinding]:
    idx = _Index(prog)
    idx.finalize()
    out: list[SiteFinding] = []
    seen_gl12: set = set()

    spawners = sorted(
        idx.funcs.values(), key=lambda x: (x.relpath, x.qualname))
    for xf in spawners:
        if not xf.spawns:
            continue
        mi = prog.modules[xf.relpath]
        roles = _role_annotations(mi.source)
        for spawn in sorted(xf.spawns, key=lambda s: s.lineno):
            role = _spawn_role(spawn, roles)
            if role is not None and role not in ROLE_POLICY:
                out.append(SiteFinding(
                    xf.relpath, "GL14", spawn.lineno, spawn.col_offset,
                    f"unknown thread-role '{role}' (registry: "
                    + ", ".join(sorted(ROLE_POLICY)) + ")",
                    xf.qualname))
                continue
            target = next(k.value for k in spawn.keywords
                          if k.arg == "target")
            tfid = idx.resolve_target(mi, xf, target)
            tgt = idx.funcs.get(tfid) if tfid else None
            if tgt is None or not tgt.while_lines:
                continue  # bounded / not statically analyzable
            if role is None:
                out.append(SiteFinding(
                    xf.relpath, "GL14", spawn.lineno, spawn.col_offset,
                    "long-lived thread loop spawned without a declared "
                    "thread-role (annotate the Thread(...) call: "
                    "# graftlint: thread-role=<role>)",
                    xf.qualname,
                    f"target {_short(tfid)} loops at line "
                    f"{tgt.while_lines[0]}"))
                continue
            policy = ROLE_POLICY[role]
            chains = idx.reach(tfid)
            if policy["heartbeat"]:
                reg_ok = idx.spawner_registers(xf) or any(
                    idx.funcs[f].registers for f in chains)
                beat_ok = any(idx.funcs[f].beats for f in chains)
                if not reg_ok:
                    out.append(SiteFinding(
                        xf.relpath, "GL14", spawn.lineno,
                        spawn.col_offset,
                        f"{role} thread never registers a "
                        "health.Heartbeat (the watchdog cannot see it "
                        "wedge)", xf.qualname,
                        f"target {_short(tfid)}"))
                elif not beat_ok:
                    out.append(SiteFinding(
                        xf.relpath, "GL14", spawn.lineno,
                        spawn.col_offset,
                        f"{role} loop never reaches Heartbeat.beat()/"
                        "idle() (registered but silent = permanently "
                        "stale)", xf.qualname,
                        f"target {_short(tfid)}"))
            # GL12: role-cone dispatch discipline
            for fid in sorted(chains):
                rxf = idx.funcs[fid]
                if _sanctioned(rxf.relpath):
                    continue
                via = chains[fid]
                witness = _short(tfid) + (f" -> {via}" if via else "")
                for line, col, desc, clause in rxf.device_ops:
                    key = (rxf.relpath, line, col, clause)
                    if key in seen_gl12:
                        continue
                    if clause == "compile":
                        if not policy["latency_critical"]:
                            continue
                        msg = (f"jax compile/dispatch {desc} reachable "
                               f"on the {role} thread outside "
                               "device._guarded (the aggregate_public "
                               "wedge class: first-shape XLA compile "
                               "stalls the round)")
                    else:
                        msg = (f"ops device excursion {desc} reachable "
                               f"on the {role} thread (twin mode keeps "
                               "jax unloaded; route it through "
                               "device.py's guarded dispatch)")
                    seen_gl12.add(key)
                    out.append(SiteFinding(
                        rxf.relpath, "GL12", line, col, msg,
                        rxf.qualname, witness))
                if policy["latency_critical"]:
                    for line, col, desc in rxf.blocking:
                        key = (rxf.relpath, line, col, "block")
                        if key in seen_gl12:
                            continue
                        seen_gl12.add(key)
                        out.append(SiteFinding(
                            rxf.relpath, "GL12", line, col,
                            f"{desc} reachable on the {role} thread "
                            "(a latency-critical role may only block "
                            "with a timeout)", rxf.qualname, witness))
    return out


# -- GL13: wire-taint budgets ------------------------------------------------

_CLEAN_HEADS = {"min", "len", "_checked_count"}
_CLEAN_ATTRS = {"checked_count"}
_SOURCE_ATTRS = {"int_"}


def _is_source_call(node: ast.AST) -> str | None:
    if not isinstance(node, ast.Call):
        return None
    head = dotted_name(node.func)
    if head == "int.from_bytes":
        return "int.from_bytes"
    if head and head.split(".")[-1] in ("unpack", "unpack_from") \
            and head.split(".")[0] == "struct":
        return head
    if isinstance(node.func, ast.Attribute) \
            and node.func.attr in _SOURCE_ATTRS:
        return f".{node.func.attr}()"
    return None


def _is_clean_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    head = dotted_name(node.func)
    if head in _CLEAN_HEADS:
        return True
    return isinstance(node.func, ast.Attribute) \
        and node.func.attr in _CLEAN_ATTRS


def _expr_taint(expr, tainted: dict) -> str | None:
    """The source description when ``expr`` carries taint, else None.
    A clean call (min / checked_count / len) launders everything under
    it — that IS the sanctioner idiom.  A non-source helper call stops
    the descent too: ``lookup(db, n)`` with tainted ``n`` returns
    whatever the helper returns, not an attacker-sized integer, and
    a subscript is clamped by the sequence it indexes."""
    if _is_clean_call(expr):
        return None
    src = _is_source_call(expr)
    if src:
        return src
    if isinstance(expr, (ast.Call, ast.Subscript)):
        return None
    if isinstance(expr, ast.Name) and expr.id in tainted:
        return tainted[expr.id][1]
    for child in ast.iter_child_nodes(expr):
        got = _expr_taint(child, tainted)
        if got:
            return got
    return None


def _iter_stmts(body):
    for s in body:
        yield s
        for attr in ("body", "orelse", "finalbody"):
            sub = getattr(s, attr, None)
            if sub:
                yield from _iter_stmts(
                    [x for x in sub if not isinstance(x, _FuncDef)])
        for h in getattr(s, "handlers", []):
            yield from _iter_stmts(h.body)


def _gl13_function(fn, relpath: str, qualname: str) -> list[SiteFinding]:
    out: list[SiteFinding] = []
    tainted: dict[str, tuple[int, str]] = {}  # name -> (line, source)
    guards: dict[str, list[int]] = {}         # name -> [guard lines]

    def guarded(name: str, sink_line: int) -> bool:
        src_line = tainted[name][0]
        return any(src_line < g <= sink_line
                   for g in guards.get(name, ()))

    def names_in(expr):
        return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}

    def int_taint(expr) -> tuple[str | None, list]:
        """(direct source, tainted names) of an *integer-valued* size
        expression.  Only arithmetic is traversed: a tainted name
        buried inside a helper call (``self._take(ln)``) or a slice
        (``view[off:off+n]``) is length-clamped by that construct, not
        an n-sized cost."""
        if isinstance(expr, ast.Name):
            return None, ([expr.id] if expr.id in tainted else [])
        src = _is_source_call(expr)
        if src:
            return src, []
        if isinstance(expr, ast.BinOp):
            ls, ln_ = int_taint(expr.left)
            rs, rn = int_taint(expr.right)
            return ls or rs, ln_ + rn
        if isinstance(expr, ast.UnaryOp):
            return int_taint(expr.operand)
        if isinstance(expr, ast.IfExp):
            bs, bn = int_taint(expr.body)
            os_, on = int_taint(expr.orelse)
            return bs or os_, bn + on
        return None, []

    def check_bound(expr, line, col, what):
        """Flag ``expr`` used as ``what`` when tainted & unguarded."""
        direct, names = int_taint(expr)
        hot = [n for n in names if not guarded(n, line)]
        if direct:
            out.append(SiteFinding(
                relpath, "GL13", line, col,
                f"untrusted count from {direct} feeds {what} with no "
                "remaining-budget check (a forged prefix buys "
                "attacker-priced work)", qualname))
        elif hot:
            n = sorted(hot)[0]
            out.append(SiteFinding(
                relpath, "GL13", line, col,
                f"untrusted count feeds {what} with no dominating "
                "remaining-budget comparison (tainted from "
                f"{tainted[n][1]})", qualname,
                f"'{n}' tainted at line {tainted[n][0]}"))

    def range_bound(node: ast.Call):
        """The expression that sizes the iteration.  ``range(a, a+n)``
        iterates n times regardless of a — peel the shared base so a
        tainted *offset* with a clamped *count* stays clean."""
        if len(node.args) < 2:
            return node.args[0]
        bound = node.args[1]
        if isinstance(bound, ast.BinOp) and isinstance(bound.op, ast.Add):
            base = ast.dump(node.args[0])
            if ast.dump(bound.left) == base:
                return bound.right
            if ast.dump(bound.right) == base:
                return bound.left
        return bound

    def _is_sequence(expr) -> bool:
        return (isinstance(expr, ast.Constant)
                and isinstance(expr.value, (str, bytes))) \
            or isinstance(expr, (ast.List, ast.Tuple))

    def scan_sinks(stmt):
        """Sinks in this statement's OWN expressions (nested statement
        bodies are scanned at their own _iter_stmts visit, with the
        taint state of that point)."""
        exprs = []
        for _, value in ast.iter_fields(stmt):
            if isinstance(value, ast.expr):
                exprs.append(value)
            elif isinstance(value, list):
                exprs.extend(v for v in value
                             if isinstance(v, ast.expr))
        stack = [(e, False) for e in exprs]
        while stack:
            node, in_cmp = stack.pop()
            if isinstance(node, _FuncDef):
                continue
            if isinstance(node, ast.Compare):
                in_cmp = True
            if isinstance(node, ast.Call):
                head = dotted_name(node.func)
                if head == "range" and node.args:
                    check_bound(range_bound(node), node.lineno,
                                node.col_offset, "a range() bound")
                elif head in ("bytes", "bytearray") \
                        and len(node.args) == 1:
                    check_bound(node.args[0], node.lineno,
                                node.col_offset, "an allocation size")
            # sequence repeat: b"\x00" * n allocates n bytes outright
            # (plain integer arithmetic is cheap — it only becomes a
            # cost at the range/allocation it later feeds, where the
            # taint it carries is checked instead)
            if isinstance(node, ast.BinOp) \
                    and isinstance(node.op, ast.Mult) and not in_cmp:
                sides = ((node.left, node.right),
                         (node.right, node.left))
                for seq, cnt in sides:
                    if not _is_sequence(seq):
                        continue
                    direct, names = int_taint(cnt)
                    hot = [n for n in names
                           if not guarded(n, node.lineno)]
                    if direct or hot:
                        why = direct or tainted[sorted(hot)[0]][1]
                        out.append(SiteFinding(
                            relpath, "GL13", node.lineno,
                            node.col_offset,
                            "untrusted count sizes a sequence "
                            "repeat with no dominating remaining-"
                            f"budget comparison (tainted from {why})",
                            qualname))
                        break
            for child in ast.iter_child_nodes(node):
                stack.append((child, in_cmp))

    for stmt in _iter_stmts(
            [s for s in fn.body if not isinstance(s, _FuncDef)]):
        # guards first: `if n > budget: raise` guards the body it owns
        if isinstance(stmt, (ast.If, ast.Assert, ast.IfExp)):
            for cmp_node in ast.walk(stmt.test):
                if isinstance(cmp_node, ast.Compare):
                    for name in names_in(cmp_node):
                        guards.setdefault(name, []).append(
                            stmt.test.lineno)
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            if value is not None:
                src = _expr_taint(value, tainted)
                for tgt in targets:
                    if isinstance(tgt, ast.Name):
                        names = [tgt]
                    elif isinstance(tgt, (ast.Tuple, ast.List)):
                        names = [e for e in tgt.elts
                                 if isinstance(e, ast.Name)]
                    else:
                        names = []  # subscript/attr stores: no rebind
                    for nm in names:
                        if src:
                            tainted.setdefault(
                                nm.id, (stmt.lineno, src))
                        elif not isinstance(stmt, ast.AugAssign):
                            tainted.pop(nm.id, None)
        scan_sinks(stmt)
    return out


def gl13_findings(prog: Program) -> list[SiteFinding]:
    out = []
    for relpath in sorted(prog.modules):
        mi = prog.modules[relpath]

        def visit(node, qual_prefix=""):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _FuncDef):
                    qual = qual_prefix + child.name
                    out.extend(_gl13_function(child, relpath, qual))
                    visit(child, qual + ".<locals>.")
                elif isinstance(child, ast.ClassDef):
                    visit(child, qual_prefix + child.name + ".")
                else:
                    visit(child, qual_prefix)

        visit(mi.tree)
    return out


def threadrole_findings(prog: Program) -> list[SiteFinding]:
    return _gl12_gl14(prog) + gl13_findings(prog)
