"""graftlint: a JAX-aware static-analysis gate for harmony-tpu.

The hot path of this repo moves Harmony's BLS12-381 verification onto
JAX/XLA/Pallas, where three classic failure modes are invisible until
they corrupt a signature or deadlock consensus:

- Python side effects traced into ``@jax.jit`` (GL01): a ``time.time()``
  or attribute mutation inside a traced function runs ONCE at trace
  time and never again, silently freezing "dynamic" behavior into the
  compiled program.
- Weak-type promotion in limb arithmetic (GL02): an untyped literal or
  ``jnp.asarray`` inside the 12-bit-limb uint32 math can promote a
  whole accumulator chain to a different dtype and corrupt carries.
- Unguarded shared state across the node's threading call sites (GL03):
  state mutated under a lock in one method and written lock-free in
  another is a data race that only shows up under consensus load.
- Silent failure hygiene (GL04): a bare ``except:`` (or
  ``except Exception: pass``) in a consensus or crypto path turns a
  signature bug into an undiagnosable liveness stall.
- Kernel-domain safety (GL09-GL11, kernelcheck.py): a limb
  intermediate whose proven bound can leave int32 (GL09, interval
  abstract interpretation over the jnp dataflow), Montgomery-domain
  mixing or missing conversions (GL10, R-degree typestate), and
  device-dispatched kernels without a bigint twin, parity test or
  infinity-padding guard (GL11).

Usage (CLI)::

    python -m tools.graftlint [paths...]          # gate vs baseline
    python -m tools.graftlint --write-baseline    # regenerate pins
    python -m tools.graftlint --all               # ignore baseline

Inline suppression: append ``# graftlint: disable=GL01`` (comma-
separated rule ids, or ``all``) to the flagged line.

Exit codes: 0 clean, 1 new violations, 2 internal error.
"""

from .engine import (  # noqa: F401
    Finding,
    LintResult,
    Baseline,
    lint_paths,
    lint_source,
    load_baseline,
    write_baseline,
    DEFAULT_BASELINE_PATH,
    REPO_ROOT,
    RULES,
)

__version__ = "1.1"
