"""Content-hash-keyed result cache for the whole-program pass.

``tools/check.sh`` runs the full-repo lint twice (the CLI gate, then
the tier-1 ``tests/test_graftlint.py`` gate in a second process); each
pass costs ~4s of parsing + interprocedural closure + kernelcheck.
The findings are a pure function of (linted file contents, linter
source, rule subset), so the second run — and every run on an
unchanged tree — can be answered from a cache keyed on exactly that.

Soundness: the key is a sha256 over every linted file's content hash
PLUS a hash of the linter's own sources (``tools/graftlint/*.py``), so
editing any linted file, adding/removing a file, or changing any rule
invalidates the entry.  There is no per-file reuse of whole-program
results — GL05–GL11 facts flow across files, so a one-file change
re-analyzes the program (the cache's job is the unchanged-tree case;
changed files are re-read and re-hashed every run regardless).

Storage: one JSON file (default ``<repo>/.graftlint_cache.json``,
gitignored), at most ``_MAX_ENTRIES`` entries evicted FIFO, written
atomically via rename.  Every failure mode (corrupt JSON, unwritable
dir, permission) degrades to a cache miss — the cache can never make
the gate wrong or break it.  ``--no-cache`` or ``GRAFTLINT_CACHE=0``
bypasses it entirely.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from pathlib import Path

_MAX_ENTRIES = 8
_VERSION = 1

_MEM: dict[str, tuple] = {}  # in-process memo (same key space)
_linter_sha: str | None = None


def cache_path() -> Path | None:
    """Resolve the cache file; None when disabled via env."""
    from .engine import REPO_ROOT

    env = os.environ.get("GRAFTLINT_CACHE")
    if env is not None:
        if env.strip().lower() in ("0", "off", "no", ""):
            return None
        return Path(env)
    return REPO_ROOT / ".graftlint_cache.json"


def linter_sha() -> str:
    """Hash of the linter's own sources: rule changes invalidate."""
    global _linter_sha
    if _linter_sha is None:
        h = hashlib.sha256()
        here = Path(__file__).resolve().parent
        for f in sorted(here.glob("*.py")):
            h.update(f.name.encode())
            h.update(f.read_bytes())
        _linter_sha = h.hexdigest()
    return _linter_sha


def program_key(file_shas: list[tuple[str, str]],
                only_rules) -> str:
    """One key for a whole lint invocation."""
    h = hashlib.sha256()
    h.update(f"v{_VERSION}".encode())
    h.update(linter_sha().encode())
    h.update(repr(sorted(only_rules) if only_rules else None).encode())
    for rel, sha in sorted(file_shas):
        h.update(rel.encode())
        h.update(sha.encode())
    return h.hexdigest()


def file_sha(source: str) -> str:
    return hashlib.sha256(source.encode("utf-8")).hexdigest()


def get(key: str):
    """(findings_rows, errors) or None on miss."""
    if key in _MEM:
        return _MEM[key]
    path = cache_path()
    if path is None:
        return None
    try:
        data = json.loads(path.read_text(encoding="utf-8"))
        entry = data["entries"][key]
        out = (entry["findings"], entry["errors"])
    except (OSError, ValueError, KeyError, TypeError):
        return None
    _MEM[key] = out
    return out


def put(key: str, findings_rows: list, errors: list) -> None:
    _MEM[key] = (findings_rows, errors)
    path = cache_path()
    if path is None:
        return
    try:
        try:
            data = json.loads(path.read_text(encoding="utf-8"))
            if not isinstance(data.get("entries"), dict):
                raise ValueError("bad cache shape")
        except (OSError, ValueError):
            data = {"version": _VERSION, "entries": {}, "order": []}
        entries = data["entries"]
        order = [k for k in data.get("order", []) if k in entries]
        if key in entries:
            order = [k for k in order if k != key]
        entries[key] = {"findings": findings_rows, "errors": errors}
        order.append(key)
        while len(order) > _MAX_ENTRIES:
            entries.pop(order.pop(0), None)
        data["order"] = order
        fd, tmp = tempfile.mkstemp(
            dir=str(path.parent), prefix=path.name, suffix=".tmp")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as fh:
                json.dump(data, fh)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    except OSError:
        pass  # cache write failure must never fail the lint


def clear_memory() -> None:
    """Test hook: drop the in-process memo (disk cache untouched)."""
    _MEM.clear()
