"""graftlint engine: file walking, rule scoping, suppressions, baseline.

Scoping: each rule family applies to the slice of the tree where its
failure mode lives (see ``_rule_applies``).  Files OUTSIDE
``harmony_tpu/`` (fixtures, tools) get every rule — that is what the
linter's own test fixtures rely on.

Baseline: pre-existing findings are *pinned*, not hidden.  A finding's
fingerprint is ``path::rule::context::message`` — no line numbers, so
pins survive unrelated edits; the gate fails only when the count of a
fingerprint exceeds its pinned count (a NEW violation) and reports the
excess sites.
"""

from __future__ import annotations

import io
import json
import re
import tokenize
from collections import Counter
from dataclasses import dataclass, field
from pathlib import Path

from . import rules as R

REPO_ROOT = Path(__file__).resolve().parents[2]
DEFAULT_BASELINE_PATH = Path(__file__).resolve().parent / "baseline.json"

RULES = {
    "GL01": "jit purity: no side effects / host syncs in traced code",
    "GL02": "limb-dtype discipline: no weak-type promotion in limb math",
    "GL03": "lock discipline: no unguarded access to lock-guarded state",
    "GL04": "silent-failure hygiene: no blind excepts in crypto/consensus",
    "GL05": "lock order: no cycles in the whole-program lock graph",
    "GL06": "no blocking I/O / joins / device work under a held lock",
    "GL07": "hot path: no per-item device->host syncs in loops",
    "GL08": "bounded blocking: socket connect/recv and urlopen must "
            "have a timeout ever set",
    "GL09": "limb value-range: every kernel intermediate's proven "
            "bound must fit the module dtype's lanes",
    "GL10": "Montgomery-domain typestate: no mixing mont/std/R^2 "
            "values, declared domains hold",
    "GL11": "twin discipline: device-dispatched kernels need a twin, "
            "a parity test and a provable padding guard",
    "GL12": "dispatch discipline: no jax compile/dispatch, ops "
            "excursion or unbounded blocking reachable on a "
            "latency-critical thread role outside device._guarded",
    "GL13": "wire-taint budgets: untrusted decode counts must pass a "
            "dominating remaining-budget check before bounding a "
            "loop, allocation or size multiplication",
    "GL14": "watchdog coverage: every spawned long-lived loop "
            "declares a thread-role, registers a health.Heartbeat "
            "and beats it",
    "GL15": "bucket derivability: every serving-path compile program's "
            "shape placeholders derive from a pinned bucket registry "
            "through verified bucket-fns (the static NEWVIEW-wedge "
            "class)",
    "GL16": "manifest coverage: every derivable compile program is in "
            "the committed warmup manifest, and every committed name "
            "is still derivable (--emit-compile-manifest regenerates)",
    "GL17": "compile locality: no lower()/compile()/first-trace or "
            "bare compile head outside the device layer or an "
            "annotated warmup/diagnostic phase",
}
INTERPROC_RULES = {"GL05", "GL06", "GL07", "GL08"}
KERNEL_RULES = {"GL09", "GL10", "GL11"}
THREADROLE_RULES = {"GL12", "GL13", "GL14"}
COMPILESURFACE_RULES = {"GL15", "GL16", "GL17"}

# -- rule scoping over harmony_tpu/ -----------------------------------------

_GL02_FILES = {
    "harmony_tpu/ops/limbs.py",
    "harmony_tpu/ops/fp.py",
    "harmony_tpu/ops/fp_pallas.py",
    "harmony_tpu/ops/towers.py",
}
_GL03_PREFIXES = (
    "harmony_tpu/node/", "harmony_tpu/p2p/", "harmony_tpu/consensus/",
    "harmony_tpu/rpc/", "harmony_tpu/sync/",
)
_GL03_FILES = {"harmony_tpu/device.py", "harmony_tpu/metrics.py"}
_GL04_PREFIXES = (
    "harmony_tpu/consensus/", "harmony_tpu/node/", "harmony_tpu/chain/",
    "harmony_tpu/ops/", "harmony_tpu/ref/",
)
# GL13's trust boundary: the modules that decode wire/disk bytes an
# adversary (or a torn write) controls — see threadrole.py's docstring
_GL13_FILES = {
    "harmony_tpu/consensus/messages.py",
    "harmony_tpu/consensus/view_change.py",
    "harmony_tpu/p2p/stream.py",
    "harmony_tpu/sidecar/protocol.py",
    "harmony_tpu/staking/slash.py",
    "harmony_tpu/core/rawdb.py",
    "harmony_tpu/core/types.py",
}
_GL04_FILES = {
    "harmony_tpu/bls.py", "harmony_tpu/multibls.py",
    "harmony_tpu/crypto_bn256.py", "harmony_tpu/crypto_ecdsa.py",
    "harmony_tpu/crypto_vrf.py", "harmony_tpu/crypto_vrf_p256.py",
    "harmony_tpu/vdf.py", "harmony_tpu/vdf_wesolowski.py",
    "harmony_tpu/keystore.py", "harmony_tpu/blsgen_kms.py",
}


def _rule_applies(rule: str, relpath: str) -> bool:
    if not relpath.startswith("harmony_tpu/"):
        return True  # fixtures / external files: all rules
    if rule == "GL01":
        return True
    if rule == "GL02":
        return relpath in _GL02_FILES
    if rule == "GL03":
        return (relpath in _GL03_FILES
                or relpath.startswith(_GL03_PREFIXES))
    if rule == "GL04":
        return (relpath in _GL04_FILES
                or relpath.startswith(_GL04_PREFIXES))
    if rule in INTERPROC_RULES:
        # whole-program rules self-limit by semantics (locks held,
        # hot-path reachability) — every module participates
        return True
    if rule in KERNEL_RULES:
        # kernelcheck self-limits to modules carrying a
        # ``# graftlint: kernel-module`` contract
        return True
    if rule == "GL13":
        return relpath in _GL13_FILES
    if rule in THREADROLE_RULES:
        # GL12/GL14 self-limit to annotated spawn sites and role cones
        return True
    if rule in COMPILESURFACE_RULES:
        # compilesurface self-limits to program sites, bucket-fn
        # annotations and the sanctioned-device-layer boundary
        return True
    return False


# -- findings ----------------------------------------------------------------


@dataclass(frozen=True, order=True)
class Finding:
    path: str  # repo-relative posix path
    line: int
    col: int
    rule: str
    message: str
    context: str
    # free-form witness (e.g. a call chain) — rendered, NEVER part of
    # the fingerprint: witness paths reroute when unrelated helpers
    # change, and pins must survive that
    detail: str = ""

    @property
    def fingerprint(self) -> str:
        return f"{self.path}::{self.rule}::{self.context}::{self.message}"

    def render(self) -> str:
        out = (f"{self.path}:{self.line}:{self.col + 1}: "
               f"{self.rule} {self.message} [{self.context}]")
        if self.detail:
            out += f"\n    via {self.detail}"
        return out


@dataclass
class LintResult:
    findings: list[Finding] = field(default_factory=list)
    errors: list[str] = field(default_factory=list)  # unparseable files

    def by_rule(self) -> Counter:
        return Counter(f.rule for f in self.findings)


# -- suppressions ------------------------------------------------------------

_SUPPRESS_RE = re.compile(r"graftlint:\s*disable=([^#]*)")
_RULE_ID_RE = re.compile(r"\b(GL\d{2}|ALL)\b", re.IGNORECASE)


def _suppressions(source: str) -> dict[int, set[str]]:
    """line -> set of suppressed rule ids ('ALL' suppresses every rule).

    Ids are extracted as tokens so a trailing justification is fine:
    ``# graftlint: disable=GL03 caller holds the lock``."""
    out: dict[int, set[str]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if m:
                ids = {t.upper() for t in _RULE_ID_RE.findall(m.group(1))}
                if ids:
                    out.setdefault(tok.start[0], set()).update(ids)
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass  # the ast parse will report the real problem
    return out


def _suppressed(f: Finding, supp: dict[int, set[str]]) -> bool:
    ids = supp.get(f.line)
    return bool(ids) and (f.rule in ids or "ALL" in ids)


# -- linting -----------------------------------------------------------------


def _intra_findings(tree, relpath: str, supp: dict,
                    only_rules: set[str] | None) -> list[Finding]:
    findings: list[Finding] = []
    for rule, check in R.ALL_RULES.items():
        if only_rules is not None and rule not in only_rules:
            continue
        if not _rule_applies(rule, relpath):
            continue
        for raw in check(tree, relpath):
            f = Finding(relpath, raw.line, raw.col, raw.rule,
                        raw.message, raw.context)
            if not _suppressed(f, supp):
                findings.append(f)
    return findings


def _interproc_findings(sources: dict, supps: dict,
                        only_rules: set[str] | None,
                        program_out: list | None = None) -> list[Finding]:
    """Whole-program pass over {relpath: (source, tree)}."""
    from . import interproc as IP

    whole = (INTERPROC_RULES | KERNEL_RULES | THREADROLE_RULES
             | COMPILESURFACE_RULES)
    wanted = whole if only_rules is None else whole & only_rules
    if not wanted and program_out is None:
        return []
    prog = IP.analyze(sources)
    if program_out is not None:
        program_out.append(prog)
    raw: list = []
    if "GL05" in wanted:
        raw += IP.gl05_findings(prog)
    if "GL06" in wanted:
        raw += IP.gl06_findings(prog)
    if "GL07" in wanted:
        raw += IP.gl07_findings(prog)
    if "GL08" in wanted:
        raw += IP.gl08_findings(prog)
    if wanted & KERNEL_RULES:
        from . import kernelcheck as KC

        raw += [f for f in KC.kernel_findings(prog)
                if f.rule in wanted]
    if wanted & THREADROLE_RULES:
        from . import threadrole as TR

        raw += [f for f in TR.threadrole_findings(prog)
                if f.rule in wanted]
    if wanted & COMPILESURFACE_RULES:
        from . import compilesurface as CS

        raw += [f for f in CS.compilesurface_findings(prog)
                if f.rule in wanted]
    findings = []
    for sf in raw:
        if not _rule_applies(sf.rule, sf.relpath):
            continue
        f = Finding(sf.relpath, sf.line, sf.col, sf.rule,
                    sf.message, sf.context, sf.detail)
        if not _suppressed(f, supps.get(sf.relpath, {})):
            findings.append(f)
    return findings


def lint_source(source: str, relpath: str,
                only_rules: set[str] | None = None) -> list[Finding]:
    """Lint one file's source (the single-file program).  relpath must
    be repo-relative posix."""
    import ast

    tree = ast.parse(source, filename=relpath)
    supp = _suppressions(source)
    findings = _intra_findings(tree, relpath, supp, only_rules)
    findings += _interproc_findings(
        {relpath: (source, tree)}, {relpath: supp}, only_rules)
    return sorted(findings)


def _iter_py_files(paths: list[str | Path]) -> tuple[list[Path], list[str]]:
    """Resolve lint targets; unresolvable paths are returned as errors —
    a typo'd path in a CI hook must fail loudly, not lint zero files."""
    files: list[Path] = []
    bad: list[str] = []
    for p in paths:
        p = Path(p)
        if not p.is_absolute():
            p = REPO_ROOT / p
        if p.is_dir():
            files.extend(sorted(
                f for f in p.rglob("*.py") if "__pycache__" not in f.parts
            ))
        elif p.is_file() and p.suffix == ".py":
            files.append(p)
        else:
            bad.append(f"{p}: not a .py file or directory")
    return files, bad


# Cheap SUPERSET of kernelcheck.parse_module_anno's tests= clause:
# matches any graftlint comment line naming tests=<dir> (even inside a
# string literal).  Over-matching only adds aux hashes — a spurious
# cache invalidation, never a stale hit; under-matching would be a
# wrong-gate bug, so tests/test_graftlint.py pins the two parsers in
# sync (test_cache_aux_regex_covers_module_anno_grammar).
_TESTS_OVERRIDE_RE = re.compile(
    r"graftlint:[^\n]*\btests=([^\s;]+)")


def _aux_inputs_sha(texts: dict) -> list[tuple[str, str]]:
    """Non-linted inputs whole-program rules read from disk (GL11's
    parity-test scan of tests/*.py, plus any ``tests=`` override dir a
    kernel-module annotation names) — they must key the cache too.
    The committed baseline rides along for the same reason: a pin edit
    must never answer from a verdict cached against the old pins
    (inline ``# graftlint: disable=`` pins are already covered — they
    live in the linted files and therefore in the file shas).  The
    committed compile manifest is GL16's comparison target, so it keys
    the cache the same way."""
    from . import cache as CA

    out = []
    try:
        out.append((
            "aux:" + DEFAULT_BASELINE_PATH.as_posix(),
            CA.file_sha(
                DEFAULT_BASELINE_PATH.read_text(encoding="utf-8")),
        ))
    except OSError:
        pass  # no baseline yet: its absence is keyed by the empty list
    from . import compilesurface as CS

    try:
        out.append((
            "aux:" + CS.MANIFEST_PATH.as_posix(),
            CA.file_sha(CS.MANIFEST_PATH.read_text(encoding="utf-8")),
        ))
    except OSError:
        pass  # no manifest yet: GL16 reports the gap, the key is empty

    roots = {REPO_ROOT / "tests"}
    for src in texts.values():
        for m in _TESTS_OVERRIDE_RE.finditer(src):
            if m.group(1) != "skip":
                roots.add(REPO_ROOT / m.group(1))
    for root in sorted(roots, key=str):
        if not root.is_dir():
            continue
        for f in sorted(root.glob("*.py")):
            try:
                out.append(("aux:" + f.as_posix(),
                            CA.file_sha(f.read_text(encoding="utf-8"))))
            except OSError:
                continue
    return out


def lint_paths(paths: list[str | Path],
               only_rules: set[str] | None = None,
               program_out: list | None = None,
               use_cache: bool = False) -> LintResult:
    """Lint files/dirs.  The union of resolved files is ONE program:
    intra-file rules run per file, then the interprocedural pass (call
    graph, GL05-GL11) runs across all of them together.  Pass a list as
    ``program_out`` to receive the analyzed Program (for --dot).

    ``use_cache=True`` answers from the content-hash-keyed result cache
    (tools/graftlint/cache.py) when nothing — the linted files, the
    tests/ tree GL11 reads, or the linter itself — has changed."""
    import ast

    result = LintResult()
    files, bad = _iter_py_files(paths)
    result.errors.extend(bad)
    texts: dict = {}
    for f in files:
        try:
            rel = f.resolve().relative_to(REPO_ROOT).as_posix()
        except ValueError:
            rel = f.as_posix()
        try:
            texts[rel] = f.read_text(encoding="utf-8")
        except (UnicodeDecodeError, OSError) as e:
            result.errors.append(f"{rel}: {type(e).__name__}: {e}")

    key = None
    if use_cache and program_out is None and not result.errors:
        # any path/read error bypasses the cache: the key could not
        # represent the unreadable input
        from . import cache as CA

        shas = [(rel, CA.file_sha(src)) for rel, src in texts.items()]
        key = CA.program_key(shas + _aux_inputs_sha(texts), only_rules)
        hit = CA.get(key)
        if hit is not None:
            rows, errors = hit
            result.findings = [Finding(*row) for row in rows]
            result.errors.extend(errors)
            result.findings.sort()
            return result

    sources: dict = {}
    supps: dict = {}
    for rel, source in texts.items():
        try:
            tree = ast.parse(source, filename=rel)
        except SyntaxError as e:
            result.errors.append(f"{rel}: {type(e).__name__}: {e}")
            continue
        sources[rel] = (source, tree)
        supps[rel] = _suppressions(source)
        result.findings.extend(
            _intra_findings(tree, rel, supps[rel], only_rules))
    result.findings.extend(
        _interproc_findings(sources, supps, only_rules, program_out))
    result.findings.sort()

    if key is not None:
        from . import cache as CA

        CA.put(key,
               [[f.path, f.line, f.col, f.rule, f.message, f.context,
                 f.detail] for f in result.findings],
               list(result.errors))
    return result


# -- baseline ----------------------------------------------------------------


@dataclass
class Baseline:
    counts: dict[str, int] = field(default_factory=dict)

    @classmethod
    def from_findings(cls, findings: list[Finding]) -> "Baseline":
        return cls(dict(Counter(f.fingerprint for f in findings)))

    def by_rule(self) -> Counter:
        out: Counter = Counter()
        for fp, n in self.counts.items():
            out[fp.split("::")[1]] += n
        return out


def load_baseline(path: str | Path = DEFAULT_BASELINE_PATH) -> Baseline:
    path = Path(path)
    if not path.exists():
        return Baseline()
    data = json.loads(path.read_text(encoding="utf-8"))
    return Baseline({
        e["fingerprint"]: int(e["count"]) for e in data.get("findings", [])
    })


def write_baseline(baseline: Baseline,
                   path: str | Path = DEFAULT_BASELINE_PATH) -> None:
    data = {
        "version": 1,
        "tool": "graftlint",
        "note": ("pinned pre-existing findings; regenerate with "
                 "python -m tools.graftlint --write-baseline"),
        "findings": [
            {"fingerprint": fp, "count": n}
            for fp, n in sorted(baseline.counts.items())
        ],
    }
    Path(path).write_text(
        json.dumps(data, indent=1) + "\n", encoding="utf-8"
    )


def compare(findings: list[Finding],
            baseline: Baseline) -> tuple[list[Finding], int, list[str]]:
    """Gate findings against the baseline.

    Returns (new_findings, pinned_count, fixed_fingerprints): per
    fingerprint, the first ``pinned`` occurrences (by line) are covered
    by the baseline and any excess is NEW; baseline entries with no
    remaining occurrences are FIXED (candidates for --write-baseline).
    """
    by_fp: dict[str, list[Finding]] = {}
    for f in findings:
        by_fp.setdefault(f.fingerprint, []).append(f)

    new: list[Finding] = []
    pinned = 0
    for fp, fs in by_fp.items():
        allowed = baseline.counts.get(fp, 0)
        fs = sorted(fs)
        pinned += min(allowed, len(fs))
        new.extend(fs[allowed:])
    fixed = [
        fp for fp, n in baseline.counts.items()
        if len(by_fp.get(fp, ())) < n
    ]
    return sorted(new), pinned, sorted(fixed)
