"""CLI entry: ``python -m tools.graftlint [paths...]``.

Exit-code contract (stable — pre-commit hooks and CI key off it):
  0  clean: no findings beyond the committed baseline
  1  new violations (or parse failures in linted files)
  2  internal error in the linter itself
"""

from __future__ import annotations

import argparse
import sys
import time
import traceback
from pathlib import Path

from .engine import (
    DEFAULT_BASELINE_PATH,
    REPO_ROOT,
    RULES,
    Baseline,
    compare,
    lint_paths,
    load_baseline,
    write_baseline,
)


def _changed_scope(ref: str, scope: list) -> list[str] | None:
    """Repo-relative .py files changed vs ``ref`` (plus untracked),
    intersected with the requested lint paths.  None on git failure —
    a bad ref must fail loudly, not lint zero files and exit green."""
    import subprocess

    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", ref, "--"],
            capture_output=True, text=True, check=True,
            cwd=str(REPO_ROOT)).stdout.splitlines()
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            capture_output=True, text=True, check=True,
            cwd=str(REPO_ROOT)).stdout.splitlines()
    except (OSError, subprocess.CalledProcessError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        print(f"graftlint: --changed={ref}: {detail.strip()}",
              file=sys.stderr)
        return None
    prefixes = []
    for s in scope:
        p = Path(s)
        rel = p.as_posix() if not p.is_absolute() \
            else p.resolve().relative_to(REPO_ROOT).as_posix()
        prefixes.append(rel.rstrip("/"))
    out = []
    for f in sorted(set(diff) | set(untracked)):
        if not f.endswith(".py") or "__pycache__" in f:
            continue
        if not (REPO_ROOT / f).is_file():
            continue  # deleted vs ref: nothing to lint
        if any(f == s or f.startswith(s + "/") for s in prefixes):
            out.append(f)
    return out


def _parse_args(argv):
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="JAX-aware static-analysis gate for harmony-tpu "
                    "(rules: " + ", ".join(
                        f"{k} {v}" for k, v in RULES.items()) + ")",
    )
    ap.add_argument("paths", nargs="*", default=["harmony_tpu"],
                    help="files or directories to lint "
                         "(default: harmony_tpu)")
    ap.add_argument("--baseline", default=str(DEFAULT_BASELINE_PATH),
                    help="baseline JSON path")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from current findings "
                         "and exit 0")
    ap.add_argument("--all", action="store_true",
                    help="list every finding (pinned included), not just "
                         "new ones; exit code still gates on NEW only")
    ap.add_argument("--changed", nargs="?", const="HEAD", metavar="REF",
                    help="lint only files changed vs a git ref (plus "
                         "untracked files), intersected with the "
                         "requested paths — the warm-cache pre-commit "
                         "loop (default REF: HEAD).  Whole-program "
                         "rules see only the changed slice; CI still "
                         "gates the full scope")
    ap.add_argument("--rules",
                    help="comma-separated rule ids to run "
                         "(default: all)")
    ap.add_argument("--stats", action="store_true",
                    help="print per-rule finding counts")
    ap.add_argument("--sarif", action="store_true",
                    help="emit findings as SARIF 2.1.0 on stdout "
                         "(new-vs-baseline only; exit code unchanged)")
    ap.add_argument("--dot", action="store_true",
                    help="emit the whole-program call graph as DOT on "
                         "stdout and exit 0")
    ap.add_argument("--emit-compile-manifest", action="store_true",
                    help="emit the canonical warmup manifest "
                         "(compilesurface's derived program set) as "
                         "JSON on stdout and exit 0; CI diffs this "
                         "against tools/artifacts/aot/"
                         "compile_manifest.json")
    ap.add_argument("--no-cache", action="store_true",
                    help="bypass the content-hash result cache "
                         "(.graftlint_cache.json) and re-analyze")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress the summary line")
    return ap.parse_args(argv)


def _sarif(findings) -> dict:
    """Minimal SARIF 2.1.0 document (what CI annotators consume)."""
    return {
        "version": "2.1.0",
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri":
                    "https://invalid.example/harmony-tpu/graftlint",
                "rules": [
                    {"id": rid, "shortDescription": {"text": desc}}
                    for rid, desc in RULES.items()
                ],
            }},
            "results": [{
                "ruleId": f.rule,
                "level": "error",
                "message": {"text": f.message + (
                    f" (via {f.detail})" if f.detail else "")},
                "locations": [{
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.path},
                        "region": {"startLine": f.line,
                                   "startColumn": f.col + 1},
                    },
                }],
                "partialFingerprints": {
                    "graftlintFingerprint/v1": f.fingerprint,
                },
            } for f in findings],
        }],
    }


def main(argv=None) -> int:
    args = _parse_args(argv)
    only = None
    if args.rules:
        only = {r.strip().upper() for r in args.rules.split(",")}
        unknown = only - set(RULES)
        if unknown:
            print(f"graftlint: unknown rules {sorted(unknown)}",
                  file=sys.stderr)
            return 2

    lint_scope = list(args.paths)
    if args.changed is not None:
        changed = _changed_scope(args.changed, args.paths)
        if changed is None:
            return 2
        if not changed:
            if not args.quiet:
                print(f"graftlint: no changed .py files vs "
                      f"{args.changed} in scope — nothing to lint")
            return 0
        lint_scope = changed

    t0 = time.monotonic()
    program_out: list = [] if (args.dot or args.emit_compile_manifest) \
        else None
    result = lint_paths(lint_scope, only, program_out=program_out,
                        use_cache=not args.no_cache)

    if result.errors:
        for err in result.errors:
            print(f"graftlint: error: {err}", file=sys.stderr)
        return 1  # unlintable source/paths gate the tree like a violation

    if args.dot:
        from .interproc import to_dot

        sys.stdout.write(to_dot(program_out[0]))
        return 0

    if args.emit_compile_manifest:
        import json as _json

        from . import compilesurface as CS

        print(_json.dumps(CS.emit_manifest(program_out[0]), indent=1))
        return 0

    if args.write_baseline:
        # a narrowed run (path subset or --rules) sees only a slice of
        # the findings; writing it to the DEFAULT baseline would silently
        # drop every other pin and fail the next full gate
        narrowed = (only is not None or args.changed is not None
                    or list(args.paths) != ["harmony_tpu"])
        if narrowed and Path(args.baseline).resolve() == \
                DEFAULT_BASELINE_PATH.resolve():
            print("graftlint: refusing to overwrite the default baseline "
                  "from a narrowed run (path subset or --rules); lint the "
                  "full default scope, or pass an explicit --baseline "
                  "path", file=sys.stderr)
            return 2
        baseline = Baseline.from_findings(result.findings)
        write_baseline(baseline, args.baseline)
        if not args.quiet:
            per = dict(sorted(baseline.by_rule().items()))
            print(f"graftlint: baseline written to {args.baseline} "
                  f"({sum(baseline.counts.values())} findings: {per})")
        return 0

    baseline = load_baseline(args.baseline)
    new, pinned, fixed = compare(result.findings, baseline)

    if args.sarif:
        import json as _json

        shown = result.findings if args.all else new
        print(_json.dumps(_sarif(shown), indent=1))
        return 1 if new else 0

    shown = result.findings if args.all else new
    for f in shown:
        tag = "" if f in new else "  [pinned]"
        print(f.render() + tag)

    if args.stats:
        print("per-rule findings:", dict(sorted(
            result.by_rule().items())))
    if not args.quiet:
        dt = time.monotonic() - t0
        if args.changed is not None:
            # a changed-slice run can't see pins living in unchanged
            # files, so "fixed" would be noise here
            msg = (f"graftlint: {len(new)} new, {pinned} pinned "
                   f"({len(lint_scope)} changed files vs "
                   f"{args.changed}; {dt:.2f}s)")
        else:
            msg = (f"graftlint: {len(new)} new, {pinned} pinned, "
                   f"{len(fixed)} baseline entries now fixed "
                   f"({dt:.2f}s)")
            if fixed:
                msg += " — shrink the pin file with --write-baseline"
        print(msg)
    return 1 if new else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except SystemExit:
        raise
    except BaseException:
        traceback.print_exc()
        sys.exit(2)
